"""The committed compile budget: ``graftcheck-rt-budget.json``.

Per runtime probe (:mod:`trlx_tpu.analysis.rt.probes`) the budget commits the
expected *warmup* compile count — exact, because a silently-appearing extra
warmup compile is a new jit-cache family — and pins *steady-state* compiles to
**zero**. Steady state is not a committed number that can be regenerated
upward: ``compare`` treats any nonzero steady count as a violation even when
the committed file says otherwise, so the zero-recompile promise cannot be
waived by re-running ``--write-budget``.

Like ``graftcheck-ir-budget.json`` (and unlike the findings baseline),
deviations are always failures; the only path to new warmup numbers is
``python -m trlx_tpu.analysis.rt --write-budget`` plus a committed diff a
reviewer sees.
"""

import json
import os
from pathlib import Path
from typing import Any, Dict, List, Tuple

DEFAULT_BUDGET = "graftcheck-rt-budget.json"

SEED_ENV = "TRLX_RT_SEED_REGRESSION"


def load(path) -> Dict[str, Any]:
    p = Path(path)
    if not p.exists():
        return {}
    doc = json.loads(p.read_text())
    return {k: v for k, v in doc.items() if not k.startswith("_")}


def write(path, measurements: Dict[str, Dict[str, Any]]) -> int:
    """Write the committed budget. Refuses under a seeded regression — a
    budget regenerated while the seed is active would commit the defect."""
    if os.environ.get(SEED_ENV):
        raise RuntimeError(
            f"refusing --write-budget while {SEED_ENV}="
            f"{os.environ[SEED_ENV]!r} is set: the seeded defect would be "
            f"committed as the expected profile"
        )
    doc: Dict[str, Any] = {
        "_format": (
            "per-probe compile budget: warmup_compiles exact, steady_compiles "
            "pinned to zero regardless of this file's contents (see "
            "trlx_tpu/analysis/rt/budget.py)"
        ),
        "_regenerate": "python -m trlx_tpu.analysis.rt --write-budget",
    }
    for key in sorted(measurements):
        entry = dict(measurements[key])
        # never commit a nonzero steady count, even if measured: the written
        # file documents the contract, compare() enforces the measurement
        entry["steady_compiles"] = 0
        doc[key] = entry
    Path(path).write_text(json.dumps(doc, indent=1, sort_keys=False) + "\n")
    return len(measurements)


def compare(
    measurements: Dict[str, Dict[str, Any]], budget: Dict[str, Any]
) -> Tuple[List[str], List[str]]:
    """(violations, notes). Violations: nonzero steady-state compiles
    (always, budget notwithstanding), warmup drift from the committed exact
    count, probes with no committed entry. Only probes present in
    ``measurements`` are compared, so a ``--probe`` subset run never
    complains about probes it did not execute."""
    violations: List[str] = []
    notes: List[str] = []
    for key in sorted(measurements):
        got = measurements[key]
        steady = int(got.get("steady_compiles", 0))
        if steady != 0:
            violations.append(
                f"RT001 {key}: {steady} steady-state compile(s) — the "
                f"zero-recompile promise is broken (an unbucketed shape, "
                f"weak-type drift, or an unstable static reached this "
                f"entrypoint after warmup)"
            )
        want = budget.get(key)
        if want is None:
            violations.append(
                f"RT002 {key}: no committed budget entry — run "
                f"--write-budget and commit the result"
            )
            continue
        gw, ww = int(got.get("warmup_compiles", 0)), int(want.get("warmup_compiles", 0))
        if gw > ww:
            violations.append(
                f"RT002 {key}: warmup compiles {ww} -> {gw} — a new jit-cache "
                f"family appeared; if intended, regenerate the budget"
            )
        elif gw < ww:
            notes.append(
                f"RT002 {key}: warmup compiles improved {ww} -> {gw} "
                f"(regenerate to lock in)"
            )
    return violations, notes
