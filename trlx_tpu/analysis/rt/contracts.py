"""Declared shape contracts: the sanctioned bucketing ladders.

The whole fixed-shape promise rests on a handful of *quantizers* — functions
that collapse a data-dependent Python int (a ragged response length, a prompt
length, an admission-wave width) onto a small committed ladder of padded
shapes before it can reach a jitted call site. SH001 flags any shape that
reaches a jit boundary without passing through one of these; this registry is
how SH001 knows which functions count, instead of special-casing names inside
the rule.

Each :class:`ShapeContract` declares, for one jit-cache family:

- ``quantizers`` — the functions whose return value is a sanctioned shape
  (``pad_to_bucket``, ``quantize_stream_response``, ...). A value produced by
  any of these is bucketed by construction.
- ``guard`` — the runtime assertion that bounds the family
  (``check_stream_bucket_family``), if one exists. PR 13 introduced that
  guard ad hoc inside the trainer; registering it here makes it a declared
  contract the rt suite owns: the guard's ``limit`` default reads
  ``max_shapes`` from this registry, and the CompileWatcher probes use the
  same number as the warmup-compile ceiling.
- ``max_shapes`` — the committed jit-cache bound for the family.

This module is import-light on purpose: the trainer and serving engine import
it at module scope (to read ``max_shapes``), so it must not pull in jax or
any analysis machinery.
"""

from dataclasses import dataclass
from typing import Dict, FrozenSet, Optional, Tuple


@dataclass(frozen=True)
class ShapeContract:
    """One declared bucketing ladder and its jit-cache bound."""

    name: str
    #: dotted module owning the quantizer/guard implementations
    module: str
    #: function names whose return value is a sanctioned (bucketed) shape
    quantizers: Tuple[str, ...]
    #: committed max distinct shapes per family (the jit-cache bound)
    max_shapes: int
    #: runtime assertion bounding the family, when one exists
    guard: Optional[str] = None
    description: str = ""


#: name -> contract; populated below at import time. Adding a new bucketing
#: ladder anywhere in the tree means adding a declaration here — SH001 trusts
#: exactly this list.
CONTRACTS: Dict[str, ShapeContract] = {}


def register_shape_contract(contract: ShapeContract) -> ShapeContract:
    if contract.name in CONTRACTS:
        raise ValueError(f"duplicate shape contract {contract.name!r}")
    CONTRACTS[contract.name] = contract
    return contract


def get(name: str) -> ShapeContract:
    return CONTRACTS[name]


def quantizer_names() -> FrozenSet[str]:
    """Every registered quantizer function name (last dotted component) —
    the SH001 sanction list."""
    out = set()
    for c in CONTRACTS.values():
        out.update(c.quantizers)
    return frozenset(out)


def guard_names() -> FrozenSet[str]:
    out = set()
    for c in CONTRACTS.values():
        if c.guard:
            out.add(c.guard)
    return frozenset(out)


# -- the committed contracts --------------------------------------------------

#: PR 13's streamed-scoring ladder, promoted from an ad-hoc assertion inside
#: the trainer into a declared contract: ≤4 pow2 response-length shapes per
#: (batch, prompt-bucket) score-fn family. ``check_stream_bucket_family``
#: reads its default ``limit`` from here, and the ``stream_score_bucket``
#: CompileWatcher probe uses the same bound as its warmup ceiling.
register_shape_contract(ShapeContract(
    name="stream_score_ladder",
    module="trlx_tpu.trainer.ppo_trainer",
    quantizers=("quantize_stream_response", "overlap_r_buckets", "pad_to_bucket"),
    guard="check_stream_bucket_family",
    max_shapes=4,
    description=(
        "streamed scoring microbuckets: varied completion lengths quantize "
        "onto a <=4-entry pow2 ladder per (B, P) family"
    ),
))

#: The one-shot/serving prompt-length families: prompts pad onto the shared
#: pow2 bucket list before any prefill or generate compile.
register_shape_contract(ShapeContract(
    name="prompt_buckets",
    module="trlx_tpu.ops.generation",
    quantizers=("pad_to_bucket", "left_pad_batch"),
    max_shapes=8,
    description="prompt lengths pad onto the shared pow2 bucket ladder",
))

#: Serving-engine prefill waves: admission groups compile one wave program
#: per (pow2 prompt bucket, group width) pair.
register_shape_contract(ShapeContract(
    name="prefill_buckets",
    module="trlx_tpu.serving.engine",
    quantizers=("_pow2_at_least",),
    max_shapes=8,
    description="serving prefill waves bucket prompt lengths to pow2",
))
