"""Entry: force virtual CPU devices (the probes execute the same sharded
train-step artifacts graftcheck-ir lowers, so they need the same 8-device
virtual mesh), then run the rt gate. Same recipe as ``-m trlx_tpu.analysis.ir``
— the device count must be pinned before jax initializes a backend."""

import os
import sys

from trlx_tpu.analysis.ir.__main__ import _force_cpu

if __name__ == "__main__":
    _force_cpu(int(os.environ.get("TRLX_RT_DEVICES", os.environ.get("TRLX_IR_DEVICES", "8"))))
    from trlx_tpu.analysis.rt.cli import main

    sys.exit(main())
