"""graftcheck-rt command line.

Usage::

    python -m trlx_tpu.analysis.rt [PATH...] [options]

Two gates in one exit code:

1. **Static**: the SH001–SH004 rules over ``PATH...`` (default: the package
   tree), with the shared noqa/baseline machinery — delegated to the main
   graftcheck CLI with ``--select SH`` so semantics (stale filtering, subset
   runs, ``--jobs``) are identical to every other suite.
2. **Runtime**: the compile probes (:mod:`trlx_tpu.analysis.rt.probes`)
   against the committed ``graftcheck-rt-budget.json`` — warmup compiles
   exact, steady-state compiles must be zero.

Options:
    --baseline FILE      findings baseline (default: graftcheck-baseline.txt)
    --no-baseline        report every static finding as new
    --select R1,R2       restrict the static rules (default: the SH family)
    --jobs N             process-parallel static checking
    --budget FILE        compile budget (default: graftcheck-rt-budget.json)
    --write-budget       regenerate the budget from fresh probe runs, exit 0
                         (refused while TRLX_RT_SEED_REGRESSION is set)
    --probe N1,N2        run only the named probes (budget compare covers
                         exactly the probes that ran)
    --no-exec            static rules only (skip the probes)
    --exec-only          probes only (skip the static rules)

Exit status: 1 on any new static finding or budget violation, else 0 —
the contract ``scripts/ci.sh`` gates on. NOTE: the probes execute jitted
steps; run via ``python -m trlx_tpu.analysis.rt`` (which forces virtual CPU
devices before jax initializes) rather than importing this module into a
process already holding a backend.
"""

import argparse
import sys

from trlx_tpu.analysis.rt import budget as budget_mod

DEFAULT_SELECT = "SH"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m trlx_tpu.analysis.rt",
        description="graftcheck-rt: recompile & shape-stability analysis",
    )
    parser.add_argument("paths", nargs="*", default=["trlx_tpu"])
    parser.add_argument("--baseline", default=None)
    parser.add_argument("--no-baseline", action="store_true")
    parser.add_argument("--select", default=DEFAULT_SELECT)
    parser.add_argument("--jobs", type=int, default=1)
    parser.add_argument("--budget", default=budget_mod.DEFAULT_BUDGET)
    parser.add_argument("--write-budget", action="store_true")
    parser.add_argument("--probe", default=None, help="comma-separated probe names")
    parser.add_argument("--no-exec", action="store_true")
    parser.add_argument("--exec-only", action="store_true")
    args = parser.parse_args(argv)

    rc = 0
    if not args.exec_only:
        from trlx_tpu.analysis.cli import main as ast_main

        static_argv = list(args.paths) + ["--select", args.select, "--jobs", str(args.jobs)]
        if args.baseline:
            static_argv += ["--baseline", args.baseline]
        if args.no_baseline:
            static_argv += ["--no-baseline"]
        rc = max(rc, ast_main(static_argv))

    if args.no_exec:
        return rc

    from trlx_tpu.analysis.rt.probes import run_probes

    names = None
    if args.probe:
        names = [p.strip() for p in args.probe.split(",") if p.strip()]
    try:
        measurements, ledger = run_probes(names, verbose=True)
    except ValueError as e:
        print(f"graftcheck-rt: {e}", file=sys.stderr)
        return 2

    if args.write_budget:
        n = budget_mod.write(args.budget, measurements)
        print(f"graftcheck-rt: wrote {n} budget entries to {args.budget}")
        return rc

    committed = budget_mod.load(args.budget)
    violations, notes = budget_mod.compare(measurements, committed)
    for v in violations:
        print(v)
    for n in notes:
        print(f"note: {n}")
    warm = sum(m["warmup_compiles"] for m in measurements.values())
    steady = sum(m["steady_compiles"] for m in measurements.values())
    print(
        f"graftcheck-rt: {len(measurements)} entrypoint(s) probed, "
        f"{warm} warmup compile(s), {steady} steady-state compile(s), "
        f"{len(violations)} violation(s)"
    )
    return 1 if violations else rc


if __name__ == "__main__":
    sys.exit(main())
