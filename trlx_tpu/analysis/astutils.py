"""Shared AST reasoning for graftcheck rules: import aliases, dotted names,
parent maps, and — the piece every JX rule leans on — *traced-function
discovery*: which function bodies in a file execute under ``jax.jit``/``pjit``
tracing, whether via decorator, wrapper call, or same-file transitive call.

Everything here is per-file. Cross-module tracing (a trainer jitting a
function imported from ``ops/``) is out of scope by design: the importing
file sees the ``jax.jit(...)`` call but not the body, the defining file sees
the body but not the jit — each file is judged on what it can prove locally,
which keeps the rules precise instead of speculative.
"""

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set

#: jax.random functions that CONSUME a key: feeding the same key to two of
#: these yields correlated (usually identical) streams. ``fold_in`` is absent
#: on purpose — folding distinct data into one key is the idiomatic way to
#: derive many keys, not a reuse.
JAX_RANDOM_CONSUMERS = frozenset(
    {
        "ball", "bernoulli", "beta", "binomial", "categorical", "cauchy",
        "chisquare", "choice", "dirichlet", "double_sided_maxwell",
        "exponential", "f", "gamma", "generalized_normal", "geometric",
        "gumbel", "laplace", "loggamma", "logistic", "lognormal", "maxwell",
        "multivariate_normal", "normal", "orthogonal", "pareto", "permutation",
        "poisson", "rademacher", "randint", "rayleigh", "shuffle", "split",
        "t", "triangular", "truncated_normal", "uniform", "wald", "weibull_min",
    }
)

#: jax.random functions that PRODUCE a fresh key (assigning their result
#: re-arms the target name for another consumption).
JAX_RANDOM_PRODUCERS = frozenset({"PRNGKey", "key", "split", "fold_in", "clone", "wrap_key_data"})


@dataclass
class Aliases:
    """Names each interesting module/function is bound to in one file."""

    jax: Set[str] = field(default_factory=set)
    jax_random: Set[str] = field(default_factory=set)
    numpy: Set[str] = field(default_factory=set)
    time: Set[str] = field(default_factory=set)
    threading: Set[str] = field(default_factory=set)
    jit: Set[str] = field(default_factory=set)  # names bound to jit/pjit callables
    partial: Set[str] = field(default_factory=set)
    thread_class: Set[str] = field(default_factory=set)  # `from threading import Thread`
    lock_factories: Set[str] = field(default_factory=set)  # `from threading import Lock`
    event_class: Set[str] = field(default_factory=set)  # `from threading import Event`


_LOCK_FACTORY_NAMES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def collect_aliases(tree: ast.Module) -> Aliases:
    al = Aliases()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                bound = a.asname or a.name.split(".")[0]
                if a.name == "jax" or (a.asname is None and a.name.startswith("jax.")):
                    al.jax.add(bound)
                if a.name == "jax.random" and a.asname:
                    al.jax_random.add(bound)
                if a.name == "numpy":
                    al.numpy.add(bound)
                if a.name == "time":
                    al.time.add(bound)
                if a.name == "threading":
                    al.threading.add(bound)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                bound = a.asname or a.name
                if mod == "jax" and a.name == "random":
                    al.jax_random.add(bound)
                elif mod == "jax" and a.name in ("jit", "pjit"):
                    al.jit.add(bound)
                elif mod in ("jax.experimental.pjit", "jax.experimental") and a.name == "pjit":
                    al.jit.add(bound)
                elif mod == "functools" and a.name == "partial":
                    al.partial.add(bound)
                elif mod == "threading" and a.name == "Thread":
                    al.thread_class.add(bound)
                elif mod == "threading" and a.name in _LOCK_FACTORY_NAMES:
                    al.lock_factories.add(bound)
                elif mod == "threading" and a.name == "Event":
                    al.event_class.add(bound)
    return al


#: Call names (last dotted component) treated as *higher-order entry points*:
#: a function-valued argument handed to one of these runs — maybe later, maybe
#: on another thread — so for closure purposes the reference IS a call edge.
#: Covers the jax control-flow/transform surface (``lax.scan(body, ...)``
#: taints ``body``) and the runtime's thread/callback spawners
#: (``threading.Thread(target=self._loop)``, ``watchdog.escalate(name, cb)``).
HOF_NAMES = frozenset(
    {
        "scan", "cond", "while_loop", "switch", "fori_loop", "map",
        "associative_scan", "vmap", "pmap", "grad", "value_and_grad",
        "jit", "pjit", "remat", "checkpoint", "shard_map", "partial",
        "Thread", "Timer", "escalate",
    }
)


def callable_arg_refs(call: ast.Call) -> List[ast.AST]:
    """Function-valued references passed *into* a call: lambdas anywhere
    (they execute as part of the call), plus Name/Attribute args when the
    callee is a known higher-order entry point (:data:`HOF_NAMES`). Used by
    the traced-function closures and the call graph so ``lax.scan(body)``,
    ``Thread(target=self._x)`` and ``escalate(name, cb)`` count as calls."""
    fn = call.func
    last: Optional[str] = None
    if isinstance(fn, ast.Name):
        last = fn.id
    elif isinstance(fn, ast.Attribute):
        last = fn.attr
    out: List[ast.AST] = []
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(a, ast.Lambda):
            out.append(a)
        elif last in HOF_NAMES and isinstance(a, (ast.Name, ast.Attribute)):
            out.append(a)
    return out


def _closure_callees(call: ast.Call, defs_by_name: Dict[str, List[ast.AST]]) -> List[ast.AST]:
    """Same-file defs a call may reach: bare-name calls plus callable args to
    higher-order entry points (``body`` in ``lax.scan(body, ...)``, ``self._x``
    in ``Thread(target=self._x)`` — bound methods resolve by bare attr name)."""
    out: List[ast.AST] = []
    if isinstance(call.func, ast.Name):
        out.extend(defs_by_name.get(call.func.id, []))
    for ref in callable_arg_refs(call):
        if isinstance(ref, ast.Lambda):
            out.append(ref)
        elif isinstance(ref, ast.Name):
            out.extend(defs_by_name.get(ref.id, []))
        elif isinstance(ref, ast.Attribute) and isinstance(ref.value, ast.Name) and ref.value.id == "self":
            out.extend(defs_by_name.get(ref.attr, []))
    return out


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def build_parents(tree: ast.Module) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def jax_random_fn(call: ast.Call, al: Aliases) -> Optional[str]:
    """``'normal'`` for ``jax.random.normal(...)`` / ``jrandom.normal(...)``
    / ``random.normal(...)`` (when bound from jax), else None."""
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    base = dotted(fn.value)
    if base is None:
        return None
    if base in al.jax_random:
        return fn.attr
    root = base.split(".")[0]
    if root in al.jax and base == f"{root}.random":
        return fn.attr
    return None


def is_jit_ref(node: ast.AST, al: Aliases) -> bool:
    """True for an expression denoting the jit/pjit transform itself."""
    if isinstance(node, ast.Name):
        return node.id in al.jit
    d = dotted(node)
    if d is None:
        return False
    root = d.split(".")[0]
    if root in al.jax and d.split(".")[-1] in ("jit", "pjit"):
        return True
    return d in ("pjit.pjit",)


def _jit_call_target(call: ast.Call, al: Aliases) -> Optional[ast.AST]:
    """For ``jax.jit(f, ...)`` / ``pjit(f, ...)`` / ``partial(jax.jit, ...)(f)``,
    the wrapped function expression (Name or Lambda), else None."""
    if is_jit_ref(call.func, al) and call.args:
        return call.args[0]
    # partial(jax.jit, static_argnums=...)(f) — rare, handled for completeness
    if (
        isinstance(call.func, ast.Call)
        and isinstance(call.func.func, (ast.Name, ast.Attribute))
        and is_jit_ref(call.func.func, al)
        and call.args
    ):
        return call.args[0]
    return None


def _decorated_jit(fn: ast.AST, al: Aliases) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        if is_jit_ref(dec, al):
            return True
        if isinstance(dec, ast.Call):
            # @jax.jit(...) and @partial(jax.jit, ...) / @functools.partial(jit, ...)
            if is_jit_ref(dec.func, al):
                return True
            fname = dotted(dec.func)
            is_partial = (
                isinstance(dec.func, ast.Name) and dec.func.id in al.partial
            ) or (fname is not None and fname.endswith(".partial"))
            if is_partial and dec.args and is_jit_ref(dec.args[0], al):
                return True
    return False


def traced_functions(tree: ast.Module, al: Aliases) -> Set[ast.AST]:
    """FunctionDef/AsyncFunctionDef/Lambda nodes whose bodies run under trace:

    - decorated with ``@jit``/``@pjit``/``@partial(jit, ...)``;
    - wrapped anywhere in the file: ``jax.jit(step)``, ``jax.jit(lambda ...)``;
    - called (by bare name, same file) from an already-traced body, to a
      fixpoint — ``jax.jit(step)`` taints the helper ``body`` that ``step``
      calls, which is how "reachable inside jit" is approximated. The closure
      also follows callable *arguments* to higher-order entry points
      (``lax.scan(body, ...)`` taints ``body``), see :func:`callable_arg_refs`.
    """
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    traced: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and _decorated_jit(node, al):
            traced.add(node)
        elif isinstance(node, ast.Call):
            target = _jit_call_target(node, al)
            if isinstance(target, ast.Lambda):
                traced.add(target)
            elif isinstance(target, ast.Name):
                traced.update(defs_by_name.get(target.id, []))

    # transitive closure over same-file bare-name calls and HOF callable args
    changed = True
    while changed:
        changed = False
        for fn in list(traced):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    for callee in _closure_callees(node, defs_by_name):
                        if callee not in traced:
                            traced.add(callee)
                            changed = True
    return traced


def traced_roots(tree: ast.Module, al: Aliases) -> List[ast.AST]:
    """The traced set minus functions nested inside another traced function —
    walking each root's subtree visits every traced statement exactly once."""
    traced = traced_functions(tree, al)
    roots = []
    for fn in traced:
        nested = False
        for other in traced:
            if other is fn:
                continue
            for node in ast.walk(other):
                if node is fn:
                    nested = True
                    break
            if nested:
                break
        if not nested:
            roots.append(fn)
    return sorted(roots, key=lambda n: getattr(n, "lineno", 0))


def iter_functions(tree: ast.Module) -> Iterable[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            yield node
