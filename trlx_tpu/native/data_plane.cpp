// Host data plane: C++ implementations of the per-rollout host-side hot loops.
//
// The reference delegates all native-performance work to external libraries
// (SURVEY.md §2.4); its host-side Python loops (per-sample pad/collate in
// `ppo_collate_fn`, stop-sequence scanning in `decode`) run every rollout batch.
// This module provides those as a small C++ library driven via ctypes
// (pybind11 is not available in this image), with identical semantics to the
// numpy fallbacks in trlx_tpu.native.__init__.
//
// Build: `python -m trlx_tpu.native.build` (invokes g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstring>

extern "C" {

// Left- or right-pad a ragged batch of int32 rows into out[B, target_len]
// (pre-filled by caller is NOT required) and write the 0/1 mask.
// rows: concatenated row data; lengths[B]: row lengths; offsets[B]: row starts.
// pad_left != 0 -> left padding. Rows longer than target_len are truncated,
// keeping the tail when left-padding and the head when right-padding (matching
// ops/generation.left_pad_batch and pipeline/ppo_pipeline.ppo_collate_fn).
void pad_collate_i32(const int32_t* rows, const int64_t* offsets,
                     const int64_t* lengths, int64_t batch, int64_t target_len,
                     int32_t pad_value, int pad_left, int32_t* out,
                     int32_t* mask) {
  for (int64_t i = 0; i < batch; ++i) {
    int32_t* out_row = out + i * target_len;
    int32_t* mask_row = mask + i * target_len;
    for (int64_t j = 0; j < target_len; ++j) {
      out_row[j] = pad_value;
      mask_row[j] = 0;
    }
    int64_t len = lengths[i];
    const int32_t* src = rows + offsets[i];
    if (len > target_len) {
      if (pad_left) src += (len - target_len);  // keep tail
      len = target_len;
    }
    int64_t start = pad_left ? (target_len - len) : 0;
    std::memcpy(out_row + start, src, len * sizeof(int32_t));
    for (int64_t j = 0; j < len; ++j) mask_row[start + j] = 1;
  }
}

// Same for float32 payloads (logprobs/values/rewards right-padded with zeros).
void pad_collate_f32(const float* rows, const int64_t* offsets,
                     const int64_t* lengths, int64_t batch, int64_t target_len,
                     float pad_value, int pad_left, float* out) {
  for (int64_t i = 0; i < batch; ++i) {
    float* out_row = out + i * target_len;
    for (int64_t j = 0; j < target_len; ++j) out_row[j] = pad_value;
    int64_t len = lengths[i];
    const float* src = rows + offsets[i];
    if (len > target_len) {
      if (pad_left) src += (len - target_len);
      len = target_len;
    }
    int64_t start = pad_left ? (target_len - len) : 0;
    std::memcpy(out_row + start, src, len * sizeof(float));
  }
}

// For each row of seqs[B, T], find the first occurrence (start index) of any of
// the given stop token-sequences; writes T (no match) or the match start into
// out[B]. Stop sequences are concatenated in `stops` with lengths `stop_lens`.
void find_stop_positions(const int32_t* seqs, int64_t batch, int64_t seq_len,
                         const int32_t* stops, const int64_t* stop_offsets,
                         const int64_t* stop_lens, int64_t n_stops,
                         int64_t* out) {
  for (int64_t i = 0; i < batch; ++i) {
    const int32_t* row = seqs + i * seq_len;
    int64_t best = seq_len;
    for (int64_t s = 0; s < n_stops; ++s) {
      const int32_t* pat = stops + stop_offsets[s];
      int64_t m = stop_lens[s];
      if (m == 0 || m > seq_len) continue;
      for (int64_t j = 0; j + m <= seq_len && j < best; ++j) {
        if (std::memcmp(row + j, pat, m * sizeof(int32_t)) == 0) {
          if (j < best) best = j;
          break;
        }
      }
    }
    out[i] = best;
  }
}

}  // extern "C"
