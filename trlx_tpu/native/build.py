"""CLI: build the native host data plane (`python -m trlx_tpu.native.build`)."""

import sys

from trlx_tpu.native import build

if __name__ == "__main__":
    path = build(verbose=True)
    if path is None:
        print("native build FAILED (numpy fallbacks will be used)", file=sys.stderr)
        sys.exit(1)
    print(f"built {path}")
