"""Native host data plane: ctypes bindings for the C++ collate/scan kernels with
numpy fallbacks (identical semantics, property-tested against each other).

The library is built on first use (or via ``python -m trlx_tpu.native.build``); in
environments without a toolchain everything silently uses the numpy fallbacks.
"""

import ctypes
import os
import subprocess
import sys
from typing import List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "libdata_plane.so")
_lib = None
_tried = False


def build(verbose: bool = False) -> Optional[str]:
    """Compile data_plane.cpp -> libdata_plane.so. Returns the path or None."""
    src = os.path.join(_HERE, "data_plane.cpp")
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", _SO_PATH, src]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if res.returncode != 0:
            if verbose:
                print(res.stderr, file=sys.stderr)
            return None
        return _SO_PATH
    except (OSError, subprocess.TimeoutExpired):
        return None


def get_lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO_PATH):
        if build() is None:
            return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
        lib.pad_collate_i32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.pad_collate_f32.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_float, ctypes.c_int,
            ctypes.c_void_p,
        ]
        lib.find_stop_positions.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p,
        ]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def _ragged_concat_i32(rows: Sequence[np.ndarray]):
    lengths = np.asarray([len(r) for r in rows], np.int64)
    offsets = np.zeros(len(rows), np.int64)
    np.cumsum(lengths[:-1], out=offsets[1:])
    flat = np.concatenate([np.asarray(r) for r in rows]) if rows else np.zeros(0)
    return flat, offsets, lengths


def pad_collate_i32(
    rows: Sequence[np.ndarray], target_len: int, pad_value: int, pad_left: bool
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad ragged int32 rows to [B, target_len] + 0/1 mask. Native when available."""
    B = len(rows)
    lib = get_lib()
    if lib is not None:
        flat, offsets, lengths = _ragged_concat_i32([np.asarray(r, np.int32) for r in rows])
        flat = np.ascontiguousarray(flat, np.int32)
        out = np.empty((B, target_len), np.int32)
        mask = np.empty((B, target_len), np.int32)
        lib.pad_collate_i32(
            flat.ctypes.data, offsets.ctypes.data, lengths.ctypes.data,
            B, target_len, pad_value, int(pad_left), out.ctypes.data, mask.ctypes.data,
        )
        return out, mask
    # numpy fallback
    out = np.full((B, target_len), pad_value, np.int32)
    mask = np.zeros((B, target_len), np.int32)
    for i, r in enumerate(rows):
        r = np.asarray(r, np.int32)
        r = r[-target_len:] if pad_left else r[:target_len]
        if pad_left:
            out[i, target_len - len(r):] = r
            mask[i, target_len - len(r):] = 1
        else:
            out[i, : len(r)] = r
            mask[i, : len(r)] = 1
    return out, mask


def pad_collate_f32(
    rows: Sequence[np.ndarray], target_len: int, pad_value: float = 0.0, pad_left: bool = False
) -> np.ndarray:
    B = len(rows)
    lib = get_lib()
    if lib is not None:
        rows32 = [np.ascontiguousarray(r, np.float32) for r in rows]
        flat, offsets, lengths = _ragged_concat_i32(rows32)
        flat = np.ascontiguousarray(flat, np.float32)
        out = np.empty((B, target_len), np.float32)
        lib.pad_collate_f32(
            flat.ctypes.data, offsets.ctypes.data, lengths.ctypes.data,
            B, target_len, ctypes.c_float(pad_value), int(pad_left), out.ctypes.data,
        )
        return out
    out = np.full((B, target_len), pad_value, np.float32)
    for i, r in enumerate(rows):
        r = np.asarray(r, np.float32)
        r = r[-target_len:] if pad_left else r[:target_len]
        if pad_left:
            out[i, target_len - len(r):] = r
        else:
            out[i, : len(r)] = r
    return out


def find_stop_positions(seqs: np.ndarray, stop_token_seqs: Sequence[Sequence[int]]) -> np.ndarray:
    """First start index of any stop token-sequence per row; seq_len if none."""
    seqs = np.ascontiguousarray(seqs, np.int32)
    B, T = seqs.shape
    stops = [np.asarray(s, np.int32) for s in stop_token_seqs if len(s) > 0]
    if not stops:
        return np.full(B, T, np.int64)
    lib = get_lib()
    if lib is not None:
        flat, offsets, lengths = _ragged_concat_i32(stops)
        flat = np.ascontiguousarray(flat, np.int32)
        out = np.empty(B, np.int64)
        lib.find_stop_positions(
            seqs.ctypes.data, B, T, flat.ctypes.data, offsets.ctypes.data,
            lengths.ctypes.data, len(stops), out.ctypes.data,
        )
        return out
    out = np.full(B, T, np.int64)
    for i in range(B):
        row = seqs[i]
        for pat in stops:
            m = len(pat)
            for j in range(0, T - m + 1):
                if int(out[i]) <= j:
                    break
                if np.array_equal(row[j : j + m], pat):
                    out[i] = min(out[i], j)
                    break
    return out
