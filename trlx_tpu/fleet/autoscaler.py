"""FleetAutoscaler: gauge-driven replica scaling with hysteresis.

The router makes N replicas one serving surface; the autoscaler decides what
N should be. It is deliberately *gauge-driven*: its only inputs are the
per-replica gauges the engines already export
(``serving/replica/<seat>/pending_depth``, ``.../live_slots``) — the
same numbers an operator's dashboard shows — so a scaling decision is always
explainable from the observability surface, and the obs pipeline itself gets
exercised by the control loop (a replica whose gauges stop updating reads as
idle and is drained, which is the correct response to a zombie).

Scaling policy (docs/serving.md "Fleet serving"):

- **Up**: fleet pending depth per active slot above
  ``scale_up_pending_per_slot`` for ``breach_rounds`` consecutive
  observations → :meth:`FleetRouter.add_replica`. Pending-per-slot is the
  pressure signal the shedding watermarks key off, one level up: queue
  growth the existing replicas cannot absorb.
- **Down**: zero pending AND mean slot occupancy below
  ``scale_down_occupancy`` for ``breach_rounds`` consecutive observations →
  :meth:`FleetRouter.begin_decommission` of the least-loaded active replica
  (graceful: its queued + live work finishes where it was accepted).
- **Hysteresis**: both directions require ``breach_rounds`` consecutive
  breaches (one hot round never scales), and every action starts a
  ``cooldown_rounds`` refractory window in which no further action fires —
  oscillating load cannot flap the fleet (the no-flap test's contract).
- **Windowing**: each round's fleet aggregates are appended to a
  :class:`~trlx_tpu.obs.timeseries.SeriesStore` and the decision reads the
  newest ``window_rounds`` points with *conservative* reductions — min over
  the window for the scale-up pressure signal, max for the scale-down
  signals — so one spiky sample inside the window can neither trigger an
  expansion nor hide sustained idleness. ``window_rounds=1`` (the default)
  degenerates to the instantaneous reads and reproduces the pre-windowing
  behavior bit-for-bit.

``observe()`` is called once per fleet round, after
:meth:`FleetRouter.export_gauges`, on the driving thread.
"""

from typing import List, Optional, Tuple

from trlx_tpu.fleet.router import FleetRouter
from trlx_tpu.obs.timeseries import SeriesStore
from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)

#: series keys the autoscaler maintains, one point per observe() round
PRESSURE_KEY = "fleet/series/pending_per_slot"
PENDING_KEY = "fleet/series/pending_depth"
OCCUPANCY_KEY = "fleet/series/occupancy"


class FleetAutoscaler:
    def __init__(
        self,
        router: FleetRouter,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        scale_up_pending_per_slot: float = 1.0,
        scale_down_occupancy: float = 0.25,
        breach_rounds: int = 3,
        cooldown_rounds: int = 8,
        window_rounds: int = 1,
        series: Optional[SeriesStore] = None,
    ):
        if not (1 <= min_replicas <= max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        if breach_rounds < 1 or cooldown_rounds < 0:
            raise ValueError(
                f"breach_rounds must be >= 1 (got {breach_rounds}), "
                f"cooldown_rounds >= 0 (got {cooldown_rounds})"
            )
        if window_rounds < 1:
            raise ValueError(f"window_rounds must be >= 1, got {window_rounds}")
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_pending_per_slot = float(scale_up_pending_per_slot)
        self.scale_down_occupancy = float(scale_down_occupancy)
        self.breach_rounds = int(breach_rounds)
        self.cooldown_rounds = int(cooldown_rounds)
        self.window_rounds = int(window_rounds)
        # retention only needs to cover the decision window (plus slack for
        # post-hoc inspection); an external store may be shared with exporters
        self.series = (
            series
            if series is not None
            else SeriesStore(capacity=max(64, 4 * self.window_rounds))
        )
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0
        self._round = 0
        #: (round, action) history — ``fleet_autoscale_events`` in bench
        self.events: List[Tuple[int, str]] = []

    def observe(self) -> None:
        """One control-loop tick: read the per-replica gauges, update the
        breach streaks, maybe act. Single-driver (the fleet round loop)."""
        self._round += 1
        actives = self.router._active_handles()
        if not actives:
            return
        pending = 0.0
        live = 0.0
        slots = 0
        for h in actives:
            prefix = f"serving/replica/{h.seat}/"
            pending += gauges.get(prefix + "pending_depth")
            live += gauges.get(prefix + "live_slots")
            slots += h.supervisor.num_slots
        # per-round occupancy from the live_slots gauge, not the lifetime-mean
        # slot_occupancy: scale-down must see idleness now, not averaged
        # over the busy history
        self.series.append(PRESSURE_KEY, pending / max(1, slots))
        self.series.append(PENDING_KEY, pending)
        self.series.append(OCCUPANCY_KEY, live / max(1, slots))
        # conservative windowed reads: every point in the window must show
        # pressure before a round counts toward scale-up (min), and every
        # point must show idleness before one counts toward scale-down (max).
        # window_rounds=1 → these are exactly the instantaneous values.
        w = self.window_rounds
        pressure = self.series.reduce(PRESSURE_KEY, "min", w)
        pending = self.series.reduce(PENDING_KEY, "max", w)
        mean_occupancy = self.series.reduce(OCCUPANCY_KEY, "max", w)
        if self._cooldown > 0:
            self._cooldown -= 1
            # streaks reset during cooldown: the refractory window demands
            # fresh consecutive evidence before the next action
            self._up_streak = 0
            self._down_streak = 0
            return
        if pressure > self.scale_up_pending_per_slot:
            self._up_streak += 1
            self._down_streak = 0
        elif pending == 0.0 and mean_occupancy < self.scale_down_occupancy:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = 0
            self._down_streak = 0
        if self._up_streak >= self.breach_rounds and len(actives) < self.max_replicas:
            logger.info(
                f"fleet autoscale up: pending/slot {pressure:.2f} > "
                f"{self.scale_up_pending_per_slot} for {self._up_streak} rounds"
            )
            self.router.add_replica()
            self.router.ledger.note_scale_up()
            self.events.append((self._round, "up"))
            self._cooldown = self.cooldown_rounds
            self._up_streak = 0
        elif self._down_streak >= self.breach_rounds and len(actives) > self.min_replicas:
            victim = max(actives, key=lambda h: (-h.load, h.seat))
            logger.info(
                f"fleet autoscale drain: idle (occupancy {mean_occupancy:.2f} < "
                f"{self.scale_down_occupancy}) for {self._down_streak} rounds — "
                f"draining seat {victim.seat}"
            )
            self.router.begin_decommission(victim)
            self.events.append((self._round, "drain"))
            self._cooldown = self.cooldown_rounds
            self._down_streak = 0
