"""FleetLedger: fleet-wide SLO accounting across engine replicas.

Each replica already exports its own gauges under
``serving/replica/<seat>/``, but per-tenant SLO questions — "what p99 does
the pro class actually see?", "how many free-tier requests were shed?" —
are *fleet-level*: a tenant's traffic spreads over replicas, so per-replica
latency windows understate the tail and per-replica shed counts fragment
the story. The ledger is the single aggregation point: the router feeds it
every routing decision and every terminal request, and it reduces them to
the ``fleet/*`` gauge namespace (docs/observability.md):

- ``fleet/replicas``, ``fleet/pending_depth``, ``fleet/restarts`` — size
  and churn;
- ``fleet/affinity_hit_rate`` vs ``fleet/random_hit_rate`` — what the
  prefix-affinity router delivers vs what uniform-random routing would
  have (the soak's "affinity beats random" gate reads exactly these);
- ``fleet/sticky_hit_rate``, ``fleet/reroutes``, ``fleet/replica_kills``,
  ``fleet/autoscale/up``, ``fleet/autoscale/drain`` — routing and
  lifecycle churn;
- ``fleet/shed`` / ``fleet/expired`` / ``fleet/finished`` and per-class /
  per-tenant breakdowns ``fleet/class/<c>/*``, ``fleet/tenant/<t>/*``
  including nearest-rank p99 latency over a bounded window;
- ``fleet/alert/fast_burn`` / ``fleet/alert/slow_burn`` /
  ``fleet/alert/firing`` — SLO error-budget burn rates over a fast and a
  slow window of terminal outcomes (multi-window burn-rate alerting: the
  fast window catches an outage quickly, the slow window keeps a brief
  blip from paging). Burn rate is ``windowed_bad_fraction / error_budget``
  where the budget is ``1 - slo_target``; the alert fires only when BOTH
  windows exceed ``burn_threshold``.

Thread-safety: ``note_route`` runs on producer threads (inside the router's
``submit``), ``record`` on the driving thread — one lock covers all counters
and windows, held only for the bookkeeping itself.
"""

import threading
from collections import deque
from typing import Dict, Optional

from trlx_tpu.obs.timeseries import SeriesStore
from trlx_tpu.serving.scheduler import (
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_STOP,
    Request,
)
from trlx_tpu.utils.metrics import gauges, nearest_rank

#: finish reasons that count as a successful generation (latency sample)
_SUCCESS = (FINISH_EOS, FINISH_STOP, FINISH_LENGTH)
#: bounded latency window per class/tenant — gauges are operational, not
#: an unbounded history (matches the engine's per-tenant window size)
_WINDOW = 512

#: series key holding the per-terminal bad-outcome indicator (1.0 = SLO miss)
SLO_BAD_KEY = "fleet/slo/bad"


def _nearest_rank_p99(window) -> float:
    xs = sorted(window)
    return nearest_rank(xs, 0.99) if xs else 0.0


class FleetLedger:
    def __init__(
        self,
        slo_target: float = 0.99,
        fast_window: int = 32,
        slow_window: int = 256,
        burn_threshold: float = 2.0,
        series: Optional[SeriesStore] = None,
    ):
        if not 0.0 < slo_target < 1.0:
            raise ValueError(f"slo_target must be in (0, 1), got {slo_target}")
        if fast_window < 1 or slow_window < fast_window:
            raise ValueError(
                "need 1 <= fast_window <= slow_window, got "
                f"fast={fast_window} slow={slow_window}"
            )
        self.slo_target = float(slo_target)
        self.fast_window = int(fast_window)
        self.slow_window = int(slow_window)
        self.burn_threshold = float(burn_threshold)
        # series retention must cover the slow window or slow burn silently
        # degrades into a faster one
        self.series = (
            series if series is not None else SeriesStore(capacity=slow_window)
        )
        self._lock = threading.Lock()
        self._routed = 0
        self._affinity_hits = 0
        self._sticky_hits = 0
        self._random_hit_weight = 0.0
        self._reroutes = 0
        self._replica_kills = 0
        self._scale_ups = 0
        self._decommissions = 0
        self._finished = 0
        self._outcomes: Dict[str, int] = {}
        self._class_lat: Dict[int, deque] = {}
        self._tenant_lat: Dict[str, deque] = {}
        self._class_outcomes: Dict[int, Dict[str, int]] = {}
        self._tenant_outcomes: Dict[str, Dict[str, int]] = {}

    # ------------------------------------------------------------- recording

    def note_route(
        self, *, affinity_hit: bool, sticky_hit: bool, random_hit_weight: float
    ) -> None:
        """One routing decision: whether the chosen replica held a warm
        prefix, whether it matched the tenant's recent seats, and the
        probability a uniform-random choice would have hit a warm prefix
        (the baseline the affinity gate compares against)."""
        with self._lock:
            self._routed += 1
            self._affinity_hits += 1 if affinity_hit else 0
            self._sticky_hits += 1 if sticky_hit else 0
            self._random_hit_weight += float(random_hit_weight)

    def note_kill(self, rerouted: int) -> None:
        with self._lock:
            self._replica_kills += 1
            self._reroutes += int(rerouted)

    def note_scale_up(self) -> None:
        with self._lock:
            self._scale_ups += 1

    def note_decommission(self) -> None:
        with self._lock:
            self._decommissions += 1

    def record(self, req: Request) -> None:
        """One terminal request (called exactly once per uid — the router's
        delivered-set dedup is the caller's contract)."""
        with self._lock:
            self._finished += 1
            reason = req.finish_reason or "unknown"
            self._outcomes[reason] = self._outcomes.get(reason, 0) + 1
            c = self._class_outcomes.setdefault(req.slo_class, {})
            c[reason] = c.get(reason, 0) + 1
            t = self._tenant_outcomes.setdefault(req.tenant_id, {})
            t[reason] = t.get(reason, 0) + 1
            if reason in _SUCCESS and req.latency_s is not None:
                self._class_lat.setdefault(
                    req.slo_class, deque(maxlen=_WINDOW)
                ).append(req.latency_s)
                self._tenant_lat.setdefault(
                    req.tenant_id, deque(maxlen=_WINDOW)
                ).append(req.latency_s)
        # outside the ledger lock: the store has its own (lock order stays flat)
        self.series.append(SLO_BAD_KEY, 0.0 if reason in _SUCCESS else 1.0)  # graftcheck: noqa[CC001] — SeriesStore is internally locked; appending outside the ledger lock keeps the lock order flat

    # --------------------------------------------------------------- reading

    def summary(self) -> Dict[str, float]:
        with self._lock:
            routed = max(1, self._routed)
            return {
                "fleet_routed": float(self._routed),
                "fleet_affinity_hit_rate": self._affinity_hits / routed,
                "fleet_sticky_hit_rate": self._sticky_hits / routed,
                "fleet_random_hit_rate": self._random_hit_weight / routed,
                "fleet_reroutes": float(self._reroutes),
                "fleet_replica_kills": float(self._replica_kills),
                "fleet_scale_ups": float(self._scale_ups),
                "fleet_decommissions": float(self._decommissions),
                "fleet_finished": float(self._finished),
            }

    def p99_by_class(self) -> Dict[int, float]:
        with self._lock:
            return {c: _nearest_rank_p99(w) for c, w in self._class_lat.items()}

    def burn_rates(self) -> Dict[str, float]:
        """Fast/slow-window SLO burn rates from the terminal-outcome series.

        ``burn = windowed_bad_fraction / (1 - slo_target)`` — burn 1.0 means
        the error budget is being spent exactly at the sustainable rate;
        ``firing`` is 1.0 only when BOTH windows exceed ``burn_threshold``
        (the classic multi-window guard against paging on a blip)."""
        budget = 1.0 - self.slo_target
        fast = self.series.reduce(SLO_BAD_KEY, "mean", self.fast_window) / budget
        slow = self.series.reduce(SLO_BAD_KEY, "mean", self.slow_window) / budget
        firing = (
            fast > self.burn_threshold and slow > self.burn_threshold
        )
        return {
            "fast_burn": fast,
            "slow_burn": slow,
            "firing": 1.0 if firing else 0.0,
        }

    def export_gauges(
        self, *, replicas: int, pending_depth: int, restarts: int
    ) -> None:
        s = self.summary()
        gauges.set("fleet/replicas", float(replicas))
        gauges.set("fleet/pending_depth", float(pending_depth))
        gauges.set("fleet/restarts", float(restarts))
        gauges.set("fleet/routed", s["fleet_routed"])
        gauges.set("fleet/affinity_hit_rate", s["fleet_affinity_hit_rate"])
        gauges.set("fleet/sticky_hit_rate", s["fleet_sticky_hit_rate"])
        gauges.set("fleet/random_hit_rate", s["fleet_random_hit_rate"])
        gauges.set("fleet/reroutes", s["fleet_reroutes"])
        gauges.set("fleet/replica_kills", s["fleet_replica_kills"])
        gauges.set("fleet/autoscale/up", s["fleet_scale_ups"])
        gauges.set("fleet/autoscale/drain", s["fleet_decommissions"])
        gauges.set("fleet/finished", s["fleet_finished"])
        with self._lock:
            outcomes = dict(self._outcomes)
            class_lat = {c: list(w) for c, w in self._class_lat.items()}
            tenant_lat = {t: list(w) for t, w in self._tenant_lat.items()}
            class_out = {c: dict(o) for c, o in self._class_outcomes.items()}
            tenant_out = {t: dict(o) for t, o in self._tenant_outcomes.items()}
        for key in ("shed", "deadline", "preempted"):
            gauges.set(f"fleet/{key}", float(outcomes.get(key, 0)))
        burn = self.burn_rates()
        gauges.set("fleet/alert/fast_burn", burn["fast_burn"])
        gauges.set("fleet/alert/slow_burn", burn["slow_burn"])
        gauges.set("fleet/alert/firing", burn["firing"])
        for cls, window in class_lat.items():
            gauges.set(f"fleet/class/{cls}/p99_latency_s", _nearest_rank_p99(window))
        for tid, window in tenant_lat.items():
            gauges.set(f"fleet/tenant/{tid}/p99_latency_s", _nearest_rank_p99(window))
        for cls, counts in class_out.items():
            for key in ("shed", "deadline"):
                gauges.set(f"fleet/class/{cls}/{key}", float(counts.get(key, 0)))
        for tid, counts in tenant_out.items():
            for key in ("shed", "deadline"):
                gauges.set(f"fleet/tenant/{tid}/{key}", float(counts.get(key, 0)))

    def close(self) -> None:
        """Retire the fleet's observability surface (prefix-aware clear)."""
        gauges.clear(prefix="fleet/")
