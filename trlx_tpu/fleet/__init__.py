"""Serving fleet: one logical serving surface over N supervised engine
replicas (docs/serving.md "Fleet serving").

- :class:`~trlx_tpu.fleet.router.FleetRouter` — prefix-cache-aware +
  tenant-affinity routing, cross-replica re-route on replica death
  (exactly-once terminal accounting), graceful decommission;
- :class:`~trlx_tpu.fleet.autoscaler.FleetAutoscaler` — gauge-driven
  scale-up/scale-down with hysteresis;
- :class:`~trlx_tpu.fleet.ledger.FleetLedger` — fleet-wide per-tenant /
  per-class SLO accounting into the ``fleet/*`` gauge namespace;
- :func:`~trlx_tpu.fleet.scenario.run_fleet_scenario` — the deterministic
  fleet chaos harness (tests/test_serving_fleet.py, bench.py ``fleet`` leg).
"""

from trlx_tpu.fleet.autoscaler import FleetAutoscaler
from trlx_tpu.fleet.ledger import FleetLedger
from trlx_tpu.fleet.router import (
    ACTIVE,
    DEAD,
    DRAINING,
    UID_STRIDE,
    FleetRouter,
    ReplicaHandle,
    fleet_factory,
)
from trlx_tpu.fleet.scenario import FleetScenarioReport, run_fleet_scenario

__all__ = [
    "ACTIVE",
    "DEAD",
    "DRAINING",
    "UID_STRIDE",
    "FleetAutoscaler",
    "FleetLedger",
    "FleetRouter",
    "FleetScenarioReport",
    "ReplicaHandle",
    "fleet_factory",
    "run_fleet_scenario",
]
