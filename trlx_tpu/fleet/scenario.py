"""Fleet-level chaos scenario harness.

:func:`trlx_tpu.serving.scenario.run_scenario` proves the single-engine
composition (tenancy × resilience × chaos); this module lifts the same
deterministic drive to a fleet: N replicas behind the
:class:`~trlx_tpu.fleet.router.FleetRouter`, the gauge-driven
:class:`~trlx_tpu.fleet.autoscaler.FleetAutoscaler` in the loop, and the
fleet chaos sites (``fleet-route`` mis-routing, ``fleet-replica-kill`` hard
deaths with cross-replica re-route) armed alongside the per-engine ones.
The invariants checked are the single-engine ones, fleet-wide:

- **exactly-once accounting** — every accepted uid reaches exactly one
  terminal state, across replica kills, cross-replica re-routes, supervised
  restarts and autoscale drains;
- **quota isolation** — per-round, per-replica: no tenant's live block usage
  exceeds its quota on ANY replica (quotas bound each engine's pool);
- **SLO ordering** — per-class p99 is aggregated across replicas through
  the :class:`~trlx_tpu.fleet.ledger.FleetLedger`, and higher classes must
  still order below lower ones fleet-wide;
- **affinity beats random** — the router's warm-prefix hit rate must exceed
  what uniform-random replica choice would have scored on the same traffic
  (the seeded ``blind_router`` regression makes this gate fail, proving it
  bites).

The run finishes with an idle tail (``idle_tail_rounds``) so the
autoscaler's scale-down path triggers inside the scenario — the acceptance
soak requires at least one graceful drain mid-run, not just kills.
"""

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

from trlx_tpu.fleet.autoscaler import FleetAutoscaler
from trlx_tpu.fleet.router import FleetRouter
from trlx_tpu.resilience.chaos import chaos
from trlx_tpu.serving.engine import ServingEngine
from trlx_tpu.serving.policy import RequestTooLarge
from trlx_tpu.serving.scenario import (
    SUCCESS_REASONS,
    ScenarioReport,
    TenantTraffic,
    _build_arrivals,
    _nearest_rank_p99,
)
from trlx_tpu.serving.tenancy import TenantRegistry, jain_fairness
from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)


@dataclass
class FleetScenarioReport(ScenarioReport):
    """:class:`ScenarioReport` plus the fleet-level facts the soak asserts."""

    affinity_hit_rate: float = 0.0
    random_hit_rate: float = 0.0
    sticky_hit_rate: float = 0.0
    replica_kills: int = 0
    reroutes: int = 0
    autoscale_events: List[Tuple[int, str]] = field(default_factory=list)
    replicas_final: int = 0
    replicas_peak: int = 0


def _check_fleet_census(router: FleetRouter, registry: TenantRegistry) -> int:
    """Allocator invariants + per-tenant quota census on every live replica.
    Returns the number of quota violations found (the bar is zero)."""
    violations = 0
    for handle in router._live_handles():
        engine = handle.supervisor.engine
        engine.allocator.check_invariants()
        for tid, used in engine.allocator.owner_census().items():
            if tid is None:
                continue
            quota = registry.quota(tid)
            if quota and used > quota:
                violations += 1
                logger.warning(
                    f"replica seat {handle.seat}: tenant {tid!r} at {used} "
                    f"blocks exceeds quota {quota}"
                )
    return violations


def run_fleet_scenario(
    engine_factory: Callable[[int], ServingEngine],
    registry: TenantRegistry,
    traffic: Sequence[TenantTraffic],
    *,
    num_replicas: int = 3,
    chaos_spec: Optional[str] = None,
    dt_s: float = 0.05,
    max_rounds: int = 800,
    seed: int = 0,
    max_restarts: int = 8,
    wedge_timeout_s: float = 0.25,
    backoff_base_s: float = 0.01,
    diagnostics_dir: str = "diagnostics",
    prefix_weight: float = 1.0,
    tenant_weight: float = 0.25,
    load_weight: float = 2.0,
    autoscale: bool = True,
    min_replicas: int = 1,
    max_replicas: Optional[int] = None,
    scale_up_pending_per_slot: float = 1.0,
    scale_down_occupancy: float = 0.25,
    breach_rounds: int = 3,
    cooldown_rounds: int = 6,
    idle_tail_rounds: int = 24,
) -> FleetScenarioReport:
    """Drive one deterministic fleet chaos scenario to completion.

    ``engine_factory(seat)`` builds one replica's engine with the scenario's
    registry installed (``tenants=registry``); vary the sampling seed off
    ``seat`` for replica-independent streams. The harness re-seats every
    engine generation's scheduler clock on the shared virtual clock, so
    deadline arithmetic stays deterministic across replicas, restarts and
    re-routes."""
    report = FleetScenarioReport()
    t = [0.0]

    def clocked_factory(seat: int) -> ServingEngine:
        eng = engine_factory(seat)
        assert eng.tenants is registry, (
            "engine_factory must install the scenario's TenantRegistry"
        )
        eng.scheduler.clock = lambda: t[0]
        return eng

    router = FleetRouter(
        clocked_factory,
        num_replicas,
        prefix_weight=prefix_weight,
        tenant_weight=tenant_weight,
        load_weight=load_weight,
        max_restarts=max_restarts,
        backoff_base_s=backoff_base_s,
        wedge_timeout_s=wedge_timeout_s,
        diagnostics_dir=diagnostics_dir,
    )
    scaler = (
        FleetAutoscaler(
            router,
            min_replicas=min_replicas,
            max_replicas=(
                num_replicas + 1 if max_replicas is None else max_replicas
            ),
            scale_up_pending_per_slot=scale_up_pending_per_slot,
            scale_down_occupancy=scale_down_occupancy,
            breach_rounds=breach_rounds,
            cooldown_rounds=cooldown_rounds,
        )
        if autoscale else None
    )
    arrivals = _build_arrivals(traffic, seed)
    accepted: set = set()
    if chaos_spec:
        chaos.configure(chaos_spec)
    try:
        i = 0
        rnd = 0
        idle_tail = 0
        while True:
            while i < len(arrivals) and arrivals[i][0] <= rnd:
                _, tid, prompt, max_new = arrivals[i]
                i += 1
                report.submitted += 1
                try:
                    uid = router.submit(prompt, max_new, tenant_id=tid)
                    accepted.add(uid)
                except RequestTooLarge:
                    report.rejected += 1
            t[0] += dt_s
            router.step()
            router.export_gauges()
            if scaler is not None:
                scaler.observe()
            for uid, req in router.scheduler.pop_finished().items():
                assert uid not in report.terminal, (
                    f"uid {uid} reached a second terminal state "
                    f"({report.terminal[uid]} then {req.finish_reason})"
                )
                report.terminal[uid] = req.finish_reason
                report.requests[uid] = req
            report.quota_violations += _check_fleet_census(router, registry)
            report.replicas_peak = max(
                report.replicas_peak, router.num_replicas
            )
            rnd += 1
            done = i >= len(arrivals) and accepted <= set(report.terminal)
            if done:
                # idle tail: keep ticking the control loop so the autoscaler
                # can observe idleness and trigger its graceful drain while
                # the scenario is still watching invariants
                idle_tail += 1
                if idle_tail >= idle_tail_rounds:
                    break
            else:
                idle_tail = 0
            if rnd >= max_rounds:
                break
        if not (accepted <= set(report.terminal)):
            for uid, req in router.drain().items():
                if uid in accepted and uid not in report.terminal:
                    report.terminal[uid] = req.finish_reason
                    report.requests[uid] = req
    finally:
        if chaos_spec:
            chaos.configure(None)
    report.rounds = rnd
    missing = accepted - set(report.terminal)
    assert not missing, f"requests never reached a terminal state: {missing}"
    report.quota_violations += _check_fleet_census(router, registry)

    for uid in accepted:
        req = report.requests[uid]
        report.delivered_by_tenant[req.tenant_id] = (
            report.delivered_by_tenant.get(req.tenant_id, 0) + len(req.generated)
        )
        if report.terminal[uid] in SUCCESS_REASONS and req.latency_s is not None:
            report.latencies_by_class.setdefault(req.slo_class, []).append(
                req.latency_s
            )
        if report.terminal[uid] == "shed":
            report.shed_by_class[req.slo_class] = (
                report.shed_by_class.get(req.slo_class, 0) + 1
            )
    report.p99_by_class = {
        c: _nearest_rank_p99(xs) for c, xs in report.latencies_by_class.items()
    }
    report.fairness_jain = jain_fairness(list(report.delivered_by_tenant.values()))
    report.outcome_counts = router.scheduler.outcome_counts()
    router.export_gauges()
    s = router.summary()
    report.affinity_hit_rate = s["fleet_affinity_hit_rate"]
    report.random_hit_rate = s["fleet_random_hit_rate"]
    report.sticky_hit_rate = s["fleet_sticky_hit_rate"]
    report.replica_kills = int(s["fleet_replica_kills"])
    report.reroutes = int(s["fleet_reroutes"])
    report.restarts = int(gauges.get("fleet/restarts"))
    report.replicas_final = router.num_replicas
    if scaler is not None:
        report.autoscale_events = list(scaler.events)
    # final gauge snapshot BEFORE the prefix-aware clears retire the
    # namespaces (fleet/* and every serving/replica/<seat>/*)
    report.gauges = dict(gauges.snapshot(prefix="fleet/"))
    report.gauges.update(gauges.snapshot(prefix="serving/"))
    router.close()
    return report
