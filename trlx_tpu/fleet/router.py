"""FleetRouter: one logical serving surface over N supervised engine replicas.

Every serving feature so far (paged KV, spec decode, tenancy, resilience,
overlap) drives exactly one :class:`~trlx_tpu.serving.engine.ServingEngine`.
Podracer-style fleets get pod-scale throughput from many independent replicas
behind a dispatcher; this module is that dispatcher (docs/serving.md "Fleet
serving"):

- **Prefix-cache-aware + tenant-affinity routing.** A new request is scored
  against every active replica: warm prefix blocks for the prompt's
  token-chain hash (``allocator.cached_prefix_blocks`` — the replica that
  already holds the prefix prefills almost nothing), where the tenant's
  recent traffic landed (KV reuse and per-tenant batching compound on the
  same replica), minus current load (live slots + pending depth). Highest
  score wins; with the weights zeroed this degenerates to pure
  least-loaded. The seeded CI regression
  ``TRLX_FLEET_SEED_REGRESSION=blind_router`` forces exactly that
  degeneration in memory so ci.sh can prove the affinity-hit-rate test
  fails without real affinity.
- **Consistent re-route with exactly-once terminal accounting.** A replica
  that exhausts its supervised restart budget — or is hard-killed by the
  ``fleet-replica-kill`` chaos site — dies; its host-side request state
  (:meth:`InflightScheduler.export_state`) is adopted by the least-loaded
  survivor, exactly the supervisor's own replay seam but *across* replicas.
  Uids never collide (each replica's scheduler is seated in a disjoint uid
  block, :data:`UID_STRIDE` apart) and never change on re-route, so
  client-held uids stay valid and every uid still reaches exactly one
  terminal state.
- **Graceful decommission.** :meth:`begin_decommission` puts a replica in
  drain (reject new routes, let queued + live work finish); the fleet keeps
  stepping it until it empties, then retires it — the
  :class:`~trlx_tpu.fleet.autoscaler.FleetAutoscaler` scale-down path.

The router is a drop-in for the engine from
:class:`~trlx_tpu.serving.client.GenerationClient`'s point of view
(``submit``/``cancel``/``step``/``run``/``drain``/``scheduler``/``summary``/
``pad_token_id``); a fleet of one replica is byte-identical to the bare
engine (same uids, same rng fold sequence, same outputs — the parity test's
contract).

Thread-safety matches the engine's: ``submit``/``cancel`` may come from
producer threads; ``step``/``run``/``drain`` and the autoscaler's
add/decommission calls are single-driver. The router lock guards only the
routing tables (handle list, uid→replica map, affinity counters) and is
never held across an engine round or a replica build.
"""

import os
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

from trlx_tpu.fleet.ledger import _SUCCESS, FleetLedger
from trlx_tpu.obs.flight import flight
from trlx_tpu.resilience.chaos import chaos
from trlx_tpu.serving.engine import ServingEngine
from trlx_tpu.serving.scheduler import Request
from trlx_tpu.serving.supervisor import ServingRestartBudgetExceeded, ServingSupervisor
from trlx_tpu.serving.tenancy import DEFAULT_TENANT
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

#: uid block size per replica seat: each replica's scheduler counts uids from
#: ``seat * UID_STRIDE``, so uids from different replicas can never collide
#: (2^40 requests per replica before overlap — effectively never) and seat 0
#: counts from 0, which is what keeps a one-replica fleet uid-identical to
#: the bare engine.
UID_STRIDE = 1 << 40

# replica lifecycle states
ACTIVE = "active"       # routing candidate, stepped every round
DRAINING = "draining"   # no new routes; stepped until its work finishes
DEAD = "dead"           # killed or fully drained; never stepped again


class ReplicaHandle:
    """One replica: a seat number (monotonic, never reused — it namespaces
    the uid block and the ``serving/replica/<seat>/`` gauges), the
    supervisor wrapping its engine generations, and its lifecycle state."""

    def __init__(self, seat: int, supervisor: ServingSupervisor):
        self.seat = seat
        self.supervisor = supervisor
        self.state = ACTIVE
        # set when a kill exports this replica's state into a survivor:
        # adopt_state folds the outcome counters in there, so fleet-wide
        # counter sums must skip this handle or double-count
        self.counters_adopted = False

    @property
    def scheduler(self):
        return self.supervisor.scheduler

    @property
    def load(self) -> float:
        """Normalized queue pressure: (live slots + pending) per slot."""
        sched = self.supervisor.scheduler
        return (sched.live_slots + sched.pending_depth) / max(
            1, self.supervisor.num_slots
        )

    def __repr__(self):
        return f"ReplicaHandle(seat={self.seat}, state={self.state})"


class _FleetScheduler:
    """The ``engine.scheduler`` facade :class:`GenerationClient` drives:
    uid-keyed lookups forward to the owning replica's scheduler, finished
    collection sweeps every live replica with router-level exactly-once
    dedup. Read-only composition — all mutation goes through the router."""

    def __init__(self, router: "FleetRouter"):
        self._router = router

    @property
    def has_work(self) -> bool:
        return any(
            h.scheduler.has_work for h in self._router._live_handles()
        )

    @property
    def pending_depth(self) -> int:
        return sum(
            h.scheduler.pending_depth for h in self._router._live_handles()
        )

    def get_request(self, uid: int) -> Optional[Request]:
        h = self._router._handle_of(uid)
        return None if h is None else h.scheduler.get_request(uid)

    def pop_request(self, uid: int) -> Optional[Request]:
        return self._router._pop_request(uid)

    def pop_finished(self) -> Dict[int, Request]:
        return self._router._pop_finished()

    def outcome_counts(self) -> Dict[str, int]:
        totals: Dict[str, int] = {"shed": 0, "expired": 0, "preempted": 0}
        for h in self._router._all_handles():
            if h.counters_adopted:
                continue  # already folded into a survivor by adopt_state
            for key, n in h.scheduler.outcome_counts().items():
                totals[key] = totals.get(key, 0) + n
        return totals


class FleetRouter:
    """Prefix-affinity router + lifecycle manager over N supervised replicas
    (module docstring).

    :param engine_factory: ``engine_factory(seat) -> ServingEngine`` builds
        one replica's engine. The router re-namespaces whatever it returns
        (``gauge_prefix = serving/replica/<seat>/``, ``replica_id = seat``)
        and seats its uid counter at ``seat * UID_STRIDE`` — the factory
        varies per-replica inputs it cares about (e.g. the sampling seed)
        off the seat argument.
    :param num_replicas: replicas built up front (the autoscaler may add or
        drain more later, between ``min_replicas`` and its own cap).
    :param prefix_weight: score weight per warm prefix block the candidate
        already caches for the prompt.
    :param tenant_weight: score weight per recent request of the same tenant
        routed to the candidate (the stickiness term).
    :param load_weight: score penalty per unit of normalized load
        (live slots + pending, per slot) — the least-loaded fallback: with
        no warm prefix and no tenant history anywhere, the emptiest replica
        wins.
    :param tenant_window: how many recent routing decisions per tenant feed
        the stickiness term.
    """

    def __init__(
        self,
        engine_factory: Callable[[int], ServingEngine],
        num_replicas: int,
        *,
        prefix_weight: float = 1.0,
        tenant_weight: float = 0.25,
        load_weight: float = 2.0,
        tenant_window: int = 32,
        max_restarts: int = 3,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 10.0,
        wedge_timeout_s: Optional[float] = 60.0,
        diagnostics_dir: str = "diagnostics",
        learn_tenants: Optional[Sequence[str]] = None,
    ):
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        seed_reg = os.environ.get("TRLX_FLEET_SEED_REGRESSION", "")
        if seed_reg not in ("", "blind_router"):
            raise ValueError(
                f"TRLX_FLEET_SEED_REGRESSION={seed_reg!r}: only "
                f"'blind_router' is defined"
            )
        self._seed_regression = seed_reg
        self._factory = engine_factory
        # learn-eligibility tagging (docs/online.md): every successfully
        # finished request is stamped learn-eligible at sweep time; a
        # learn_tenants allow-list narrows harvesting to opted-in tenants
        self._learn_tenants = (
            None if learn_tenants is None else frozenset(map(str, learn_tenants))
        )
        self.prefix_weight = float(prefix_weight)
        self.tenant_weight = float(tenant_weight)
        self.load_weight = float(load_weight)
        self.tenant_window = int(tenant_window)
        self._sup_kwargs = dict(
            max_restarts=max_restarts,
            backoff_base_s=backoff_base_s,
            backoff_max_s=backoff_max_s,
            wedge_timeout_s=wedge_timeout_s,
            diagnostics_dir=diagnostics_dir,
        )
        self.ledger = FleetLedger()
        # routing tables: handle list + uid ownership + per-tenant recent
        # seats. submit() runs on producer threads while step() re-routes on
        # the driving thread — one lock covers them all, held only for table
        # reads/writes (never across an engine round or a replica build).
        self._lock = threading.Lock()
        self._handles: List[ReplicaHandle] = []
        self._retired: List[ReplicaHandle] = []
        self._uid_seat: Dict[int, ReplicaHandle] = {}
        self._tenant_recent: Dict[str, deque] = {}
        self._finished: Dict[int, Request] = {}
        self._delivered: set = set()
        self._next_seat = 0
        self._params = None
        self._params_set = False
        self.scheduler = _FleetScheduler(self)
        for _ in range(int(num_replicas)):
            self.add_replica()

    # -------------------------------------------------------------- lifecycle

    def _prep_engine(self, engine: ServingEngine, seat: int) -> ServingEngine:
        """Namespace a freshly built engine into its replica seat. Runs for
        the initial build AND every supervised restart (the supervisor's
        factory closes over this), so a successor engine keeps its seat's
        gauge prefix, replica id and uid block."""
        engine.gauge_prefix = f"serving/replica/{seat}/"
        engine.replica_id = seat
        engine.scheduler.seat_uid_base(seat * UID_STRIDE)
        with self._lock:
            params, params_set = self._params, self._params_set
        if params_set:
            engine.set_params(params)
        return engine

    def add_replica(self) -> ReplicaHandle:
        """Build and activate one replica at the next seat (autoscaler
        scale-up; also the constructor's initial build). Seats are never
        reused: a replica added after a drain gets a fresh uid block and a
        fresh gauge namespace."""
        with self._lock:
            seat = self._next_seat
            self._next_seat += 1
        sup = ServingSupervisor(
            lambda: self._prep_engine(self._factory(seat), seat),
            heartbeat=f"serving-engine-r{seat}",
            **self._sup_kwargs,
        )
        handle = ReplicaHandle(seat, sup)
        with self._lock:
            self._handles.append(handle)
        logger.info(f"fleet: replica seat {seat} active")
        return handle

    def begin_decommission(self, handle: ReplicaHandle) -> None:
        """Gracefully drain one replica (autoscaler scale-down): no new
        routes land on it, queued requests are NOT shed (they finish where
        they were accepted), and :meth:`step` retires it once its scheduler
        empties."""
        with self._lock:
            if handle.state != ACTIVE:
                return
            handle.state = DRAINING
        handle.supervisor.begin_drain(shed_pending=False)
        self.ledger.note_decommission()
        logger.info(f"fleet: replica seat {handle.seat} draining")

    def _retire(self, handle: ReplicaHandle) -> None:
        """Take a drained/dead replica out of the fleet: sweep its last
        finished requests, unregister its watchdog escalation, clear its
        gauge namespace."""
        self._sweep(handle)
        handle.supervisor.close()
        handle.supervisor.engine.close()  # prefix-aware gauge clear
        with self._lock:
            handle.state = DEAD
            if handle in self._handles:
                self._handles.remove(handle)
            self._retired.append(handle)

    def _kill_replica(self, handle: ReplicaHandle, reason: str) -> None:
        """Hard replica death (restart budget exhausted, or chaos
        ``fleet-replica-kill``): export the dead scheduler's host-side
        request state and re-route it onto the least-loaded surviving
        replica via the same adopt/replay seam a supervised restart uses.
        Uids are preserved (the survivor's counter is already seated past
        every adopted uid) — exactly-once terminal accounting holds. With
        no survivor the failure propagates: a dead fleet must fail closed,
        not strand accepted requests."""
        with self._lock:
            survivors = [
                h for h in self._handles if h is not handle and h.state == ACTIVE
            ]
        if not survivors:
            raise ServingRestartBudgetExceeded(
                f"fleet: replica seat {handle.seat} died with no surviving "
                f"active replica to adopt its requests ({reason})"
            )
        self._sweep(handle)  # finished-but-uncollected must not be replayed
        state = handle.supervisor.engine.scheduler.export_state()
        target = min(survivors, key=lambda h: (h.load, h.seat))
        logger.warning(
            f"fleet: replica seat {handle.seat} died ({reason}); re-routing "
            f"{len(state['replay'])} requests to seat {target.seat}"
        )
        if flight.enabled:
            # a replica kill is a re-route INSIDE the same flight: the uid's
            # journal keeps accumulating across seats, so the preempt_replay
            # phase absorbs the adoption tax instead of the flight forking
            t_kill = target.supervisor.engine.scheduler.clock()
            for req in state["replay"]:
                flight.record(
                    req.uid, "re_route", t=t_kill,
                    seat=target.seat, reason=reason,
                )
        target.supervisor.engine.adopt(state)
        handle.counters_adopted = True
        self._retire(handle)
        with self._lock:
            # ownership follows the requests: every uid the dead replica
            # held now answers on the survivor
            for uid, h in list(self._uid_seat.items()):
                if h is handle:
                    self._uid_seat[uid] = target
        self.ledger.note_kill(rerouted=len(state["replay"]))

    # ---------------------------------------------------------------- routing

    def _live_handles(self) -> List[ReplicaHandle]:
        with self._lock:
            return [h for h in self._handles if h.state != DEAD]

    def _active_handles(self) -> List[ReplicaHandle]:
        with self._lock:
            return [h for h in self._handles if h.state == ACTIVE]

    def _all_handles(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._handles) + list(self._retired)

    def _handle_of(self, uid: int) -> Optional[ReplicaHandle]:
        with self._lock:
            return self._uid_seat.get(uid)

    def replica_of(self, uid: int) -> Optional[int]:
        """Seat serving ``uid`` (error attribution for typed client errors)."""
        h = self._handle_of(uid)
        return None if h is None else h.seat

    def _score(
        self, handle: ReplicaHandle, prompt: Sequence[int], tenant_id: str
    ) -> float:
        warm = handle.supervisor.engine.allocator.cached_prefix_blocks(prompt)
        with self._lock:
            recent = self._tenant_recent.get(tenant_id)
            sticky = sum(1 for s in recent if s == handle.seat) if recent else 0
        if self._seed_regression == "blind_router":
            # seeded CI regression: pure least-loaded, affinity ignored —
            # the affinity-hit-rate gate must FAIL under this
            return -self.load_weight * handle.load
        return (
            self.prefix_weight * warm
            + self.tenant_weight * sticky
            - self.load_weight * handle.load
        )

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int,
        stop_sequences: Sequence[Sequence[int]] = (),
        deadline_s: Optional[float] = None,
        tenant_id: Optional[str] = None,
    ) -> int:
        """Route one request: score every active replica, submit to the
        best. The ``fleet-route`` chaos site deliberately mis-routes to the
        WORST-scoring replica — routing quality is a performance property,
        never a correctness one, and the soak proves mis-routed requests
        still finish exactly once."""
        candidates = self._active_handles()
        if not candidates:
            raise ServingRestartBudgetExceeded(
                "fleet: no active replica to route to"
            )
        tid = DEFAULT_TENANT if tenant_id is None else str(tenant_id)
        prompt = list(map(int, prompt))
        scored = sorted(
            ((self._score(h, prompt, tid), -h.load, -h.seat, h) for h in candidates),
            key=lambda s: (s[0], s[1], s[2]),
            reverse=True,
        )
        chosen = scored[-1][3] if chaos.should_fail("fleet-route") else scored[0][3]
        uid = chosen.supervisor.submit(
            prompt, max_new_tokens, stop_sequences=stop_sequences,
            deadline_s=deadline_s, tenant_id=tenant_id,
        )
        warm_chosen = chosen.supervisor.engine.allocator.cached_prefix_blocks(prompt)
        warm_anywhere = sum(
            1 for h in candidates
            if h.supervisor.engine.allocator.cached_prefix_blocks(prompt) > 0
        )
        with self._lock:
            self._uid_seat[uid] = chosen
            recent = self._tenant_recent.setdefault(
                tid, deque(maxlen=self.tenant_window)
            )
            sticky_hit = chosen.seat in recent
            recent.append(chosen.seat)
        self.ledger.note_route(
            affinity_hit=warm_chosen > 0,
            sticky_hit=sticky_hit,
            # what uniform-random routing would have hit: the fraction of
            # candidates holding any warm prefix for this prompt — the
            # baseline fleet_affinity_hit_rate must beat
            random_hit_weight=warm_anywhere / len(candidates),
        )
        return uid

    def cancel(self, uid: int) -> bool:
        h = self._handle_of(uid)
        return False if h is None else h.supervisor.cancel(uid)

    # ----------------------------------------------------------------- driver

    def _sweep(self, handle: ReplicaHandle) -> None:
        """Collect one replica's newly finished requests into the fleet
        buffer, exactly once per uid (a request re-routed after a kill can
        only ever surface from one scheduler, but the delivered set keeps
        that a checked invariant rather than an assumed one)."""
        finished = handle.scheduler.pop_finished()
        if not finished:
            return
        fresh: List[Request] = []
        with self._lock:
            for uid, req in finished.items():
                if uid in self._delivered:
                    continue
                self._delivered.add(uid)
                self._finished[uid] = req
                fresh.append(req)
        for req in fresh:
            # stamp learn-eligibility for the online collector (exactly once
            # per uid — this loop is already dedup-guarded above): successful
            # finishes from opted-in tenants may become GRPO training data
            req.learn_eligible = bool(
                req.finish_reason in _SUCCESS
                and req.generated
                and (
                    self._learn_tenants is None
                    or req.tenant_id in self._learn_tenants
                )
            )
            self.ledger.record(req)

    def _pop_finished(self) -> Dict[int, Request]:
        for h in self._live_handles():
            self._sweep(h)
        with self._lock:
            out, self._finished = self._finished, {}
        return out

    def _pop_request(self, uid: int) -> Optional[Request]:
        h = self._handle_of(uid)
        with self._lock:
            self._uid_seat.pop(uid, None)
        return None if h is None else h.scheduler.pop_request(uid)

    def step(self) -> List[Request]:
        """One fleet round: every live replica steps once (active AND
        draining — a draining replica still owes its queued work), dead ones
        are re-routed, fully drained ones retire. Returns the requests that
        reached a terminal state this round, fleet-wide."""
        if chaos.should_fail("fleet-replica-kill"):
            actives = self._active_handles()
            if len(actives) > 1:
                victim = max(actives, key=lambda h: (h.load, h.seat))
                self._kill_replica(victim, "chaos: fleet-replica-kill")
        finished: List[Request] = []
        for handle in self._live_handles():
            try:
                finished.extend(handle.supervisor.step())
            except ServingRestartBudgetExceeded as e:
                self._kill_replica(handle, f"restart budget exhausted: {e}")
                continue
            if handle.state == DRAINING and not handle.scheduler.has_work:
                self._retire(handle)
                logger.info(f"fleet: replica seat {handle.seat} drained and retired")
        return finished

    def run(self, uids: Optional[Sequence[int]] = None) -> Dict[int, Request]:
        """Drive fleet rounds until the given uids (or all work) complete —
        the fleet mirror of :meth:`ServingEngine.run`."""
        want = set(uids) if uids is not None else None
        done: Dict[int, Request] = dict(self._pop_finished())
        while True:
            if want is not None:
                if want <= set(done):
                    break
                if not self.scheduler.has_work:
                    raise RuntimeError(
                        f"fleet drained with requests unaccounted: {want - set(done)}"
                    )
            elif not self.scheduler.has_work:
                break
            self.step()
            done.update(self._pop_finished())
            self.export_gauges()
        return done

    def begin_drain(self, shed_pending: bool = True) -> None:
        for h in self._active_handles():
            with self._lock:
                h.state = DRAINING
            h.supervisor.begin_drain(shed_pending=shed_pending)

    def drain(self) -> Dict[int, Request]:
        """Fleet-wide graceful shutdown: drain every replica, step until all
        work is accounted, retire everything."""
        self.begin_drain()
        done: Dict[int, Request] = dict(self._pop_finished())
        while self.scheduler.has_work:
            self.step()
            done.update(self._pop_finished())
        for h in self._live_handles():
            self._retire(h)
        return done

    def close(self) -> None:
        """Retire every replica (watchdog escalations unregistered, per-
        replica gauge namespaces cleared) and the fleet's own ``fleet/*``
        gauges."""
        for h in self._live_handles():
            self._retire(h)
        self.ledger.close()

    # ------------------------------------------------- engine-compat surface

    @property
    def pad_token_id(self) -> int:
        return self._any_handle().supervisor.pad_token_id

    @property
    def tenants(self):
        return self._any_handle().supervisor.tenants

    @property
    def num_blocks(self) -> int:
        """Total KV pool across live replicas (capacity logging)."""
        return sum(h.supervisor.num_blocks for h in self._live_handles())

    @property
    def num_replicas(self) -> int:
        return len(self._active_handles())

    @property
    def serving_version(self) -> int:
        return self._any_handle().supervisor.serving_version

    def _any_handle(self) -> ReplicaHandle:
        with self._lock:
            handles = self._handles or self._retired
            if not handles:
                raise RuntimeError("fleet has no replicas")
            return handles[0]

    def set_params(self, params) -> None:
        """Swap the parameter snapshot on every live replica — remembered so
        replicas added later (autoscale-up, supervised restart) come up with
        the same weights."""
        with self._lock:
            self._params = params
            self._params_set = True
        for h in self._live_handles():
            h.supervisor.set_params(params)

    def note_overlap(self, decode_busy_s: float, overlapped_s: float) -> None:
        self._any_handle().supervisor.note_overlap(decode_busy_s, overlapped_s)

    def summary(self) -> Dict[str, float]:
        """Fleet-aggregate of the per-replica summaries: additive counters
        sum, rate-like keys average over live replicas, plus the fleet's own
        routing/lifecycle counters."""
        live = self._live_handles()
        sums: Dict[str, float] = {}
        for h in live:
            for key, v in h.supervisor.summary().items():
                sums[key] = sums.get(key, 0.0) + float(v)
        for key in (
            "accepted_tok_per_round", "spec_accept_rate", "overlap_fraction",
            "mean_slot_occupancy", "prefix_cache_hit_rate",
        ):
            if key in sums and live:
                sums[key] /= len(live)
        sums.update(self.ledger.summary())
        sums["replicas"] = float(len(self._active_handles()))
        return sums

    def export_gauges(self) -> None:
        """Per-replica gauges under each seat's own namespace, then the
        fleet-level ``fleet/*`` aggregation."""
        live = self._live_handles()
        for h in live:
            h.supervisor.export_gauges()
        restarts = sum(h.supervisor.restarts for h in live + self._all_retired())
        self.ledger.export_gauges(
            replicas=len(self._active_handles()),
            pending_depth=self.scheduler.pending_depth,
            restarts=restarts,
        )

    def _all_retired(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._retired)


def fleet_factory(
    engine_factory: Callable[[int], ServingEngine],
    fleet_config: Any,
    **supervisor_kwargs,
) -> FleetRouter:
    """Build a :class:`FleetRouter` from a ``train.serving_fleet`` config
    (:class:`~trlx_tpu.data.configs.ServingFleetConfig`) — the trainer's
    wiring seam, kept here so the config module never imports the fleet."""
    return FleetRouter(
        engine_factory,
        fleet_config.num_replicas,
        prefix_weight=fleet_config.prefix_weight,
        tenant_weight=fleet_config.tenant_weight,
        load_weight=fleet_config.load_weight,
        tenant_window=fleet_config.tenant_window,
        **supervisor_kwargs,
    )
