"""RL method definitions: each algorithm = a registered MethodConfig dataclass that
owns its (pure-JAX) loss function, mirroring the reference's design where the method
config carries the loss (`/root/reference/trlx/data/method_configs.py`,
`modeling_ppo.py:175`, `modeling_ilql.py:94`). Importing this package registers all
built-in methods."""

from trlx_tpu.methods.ppo import AdaptiveKLController, FixedKLController, PPOConfig
from trlx_tpu.methods.grpo import GRPOConfig
from trlx_tpu.methods.ilql import ILQLConfig
from trlx_tpu.methods.sft import SFTConfig
from trlx_tpu.methods.rft import RFTConfig

__all__ = [
    "PPOConfig",
    "GRPOConfig",
    "ILQLConfig",
    "SFTConfig",
    "RFTConfig",
    "AdaptiveKLController",
    "FixedKLController",
]
