"""RFT (rejection fine-tuning, ReST-style) method config (parity: ``RFTConfig``,
`/root/reference/trlx/trainer/accelerate_rft_trainer.py:18-44`): N generations per
prompt, scored by the reward function, filtered by a per-prompt percentile threshold
that rises over ``n_improve_steps``, deduplicated, then SFT on the survivors."""

from dataclasses import dataclass, field
from typing import Any, Dict

from trlx_tpu.data.method_configs import register_method
from trlx_tpu.methods.sft import SFTConfig


@register_method
@dataclass
class RFTConfig(SFTConfig):
    """:param n_generations_per_prompt: samples drawn per prompt each improve step.
    :param start_percentile / end_percentile: score-filter schedule bounds.
    :param n_improve_steps: how many filtering iterations per epoch.
    :param n_residual_prompts: prompts kept for logging unfiltered stats."""

    name: str = "RFTConfig"
    n_generations_per_prompt: int = 4
    start_percentile: float = 0.7
    end_percentile: float = 0.95
    n_improve_steps: int = 4
    n_residual_prompts: int = 0
    gen_kwargs: Dict[str, Any] = field(
        default_factory=lambda: dict(max_new_tokens=32, temperature=1.0, do_sample=True)
    )
