"""PPO method: hyperparameters, KL controllers, GAE, and the clipped surrogate loss.

Functional parity with the reference's ``PPOConfig``
(`/root/reference/trlx/models/modeling_ppo.py:32-238`): same hyperparameter surface,
same GAE math (`get_advantages_and_returns`, :136-173), same clipped policy+value loss
and stat names (:175-238), and the same Adaptive/Fixed KL controllers (:35-67). The
implementation is TPU-first: GAE is a reverse ``lax.scan`` (not a Python loop), all
ragged response lengths are handled with masks at fixed shapes, and whitening reduces
over the global sharded batch (XLA inserts the cross-device collectives).
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.analysis.ir.entrypoints import EntryArtifacts, register_entrypoint
from trlx_tpu.data.method_configs import MethodConfig, register_method
from trlx_tpu.utils.modeling import masked_mean, whiten


class AdaptiveKLController:
    """Adaptive KL coefficient per https://arxiv.org/abs/1909.08593 §2.2
    (parity: modeling_ppo.py:35-53)."""

    def __init__(self, init_kl_coef: float, target: float, horizon: int):
        self.value = init_kl_coef
        self.target = target
        self.horizon = horizon

    def update(self, current: float, n_steps: int):
        # host-side scalar math: no device op / sync per step
        proportional_error = min(0.2, max(-0.2, float(current) / self.target - 1))
        self.value *= 1 + proportional_error * n_steps / self.horizon


class FixedKLController:
    """Constant KL coefficient (parity: modeling_ppo.py:56-67)."""

    def __init__(self, kl_coef: float):
        self.value = kl_coef

    def update(self, current: float, n_steps: int):
        pass


def gae_advantages_and_returns(
    values: jnp.ndarray,
    rewards: jnp.ndarray,
    mask: jnp.ndarray,
    gamma: float,
    lam: float,
    use_whitening: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Generalized Advantage Estimation over the response window.

    Shapes: values/rewards/mask are [B, T] over response tokens (mask 1 where a real
    response token exists). Equivalent to the reference's reverse Python loop
    (modeling_ppo.py:136-173) but expressed as a reverse ``lax.scan`` so it compiles
    to one fused kernel. Positions past a sample's response end contribute nothing:
    bootstrap values and deltas are masked.
    """
    mask = mask.astype(values.dtype)
    values = values * mask
    rewards = rewards * mask
    next_values = jnp.concatenate([values[:, 1:], jnp.zeros_like(values[:, :1])], axis=1)
    next_mask = jnp.concatenate([mask[:, 1:], jnp.zeros_like(mask[:, :1])], axis=1)
    deltas = rewards + gamma * next_values * next_mask - values

    def step(carry, xs):
        delta_t, m_next = xs
        carry = delta_t + gamma * lam * m_next * carry
        return carry, carry

    # scan over time, reversed; carry shape [B]
    _, adv_rev = jax.lax.scan(
        step,
        jnp.zeros_like(deltas[:, 0]),
        (deltas.T[::-1], next_mask.T[::-1]),
    )
    advantages = adv_rev[::-1].T * mask
    returns = advantages + values
    if use_whitening:
        advantages = whiten(advantages, mask=mask) * mask
    return jax.lax.stop_gradient(advantages), jax.lax.stop_gradient(returns)


@register_method
@dataclass
class PPOConfig(MethodConfig):
    """PPO hyperparameters (parity: modeling_ppo.py:70-134; same field names).

    :param num_rollouts: rollouts collected per experience phase.
    :param chunk_size: prompts per generation batch during rollout.
    :param ppo_epochs: optimization epochs per experience batch.
    :param init_kl_coef / target / horizon: KL controller (adaptive if target set).
    :param gamma / lam: GAE discounting.
    :param cliprange / cliprange_value / vf_coef: clipped-loss coefficients.
    :param scale_reward: None | "ref" | "running" reward scaling.
    :param cliprange_reward: clip scores to ±value before KL assembly.
    :param gen_kwargs / gen_experience_kwargs: generation settings (eval / rollout).
    :param num_value_layers_unfrozen: depth of the separate value branch (0 = head only).
    """

    name: str = "PPOConfig"
    ppo_epochs: int = 4
    num_rollouts: int = 128
    chunk_size: int = 128
    init_kl_coef: float = 0.05
    target: Optional[float] = 6.0
    horizon: int = 10000
    gamma: float = 1.0
    lam: float = 0.95
    cliprange: float = 0.2
    cliprange_value: float = 0.2
    vf_coef: float = 1.0
    scale_reward: Optional[str] = "ignored"
    ref_mean: Optional[float] = None
    ref_std: Optional[float] = None
    cliprange_reward: float = 10.0
    gen_kwargs: Dict[str, Any] = field(default_factory=lambda: dict(max_new_tokens=16))
    gen_experience_kwargs: Optional[Dict[str, Any]] = None
    num_value_layers_unfrozen: int = 0
    # overlap reward_fn scoring of chunk i with generation of chunk i+1 during
    # make_experience (double-buffer; worthwhile when the reward model is served
    # remotely — the RPC round-trip hides behind device work). reward_fn then
    # runs on a worker thread, so it must be thread-safe.
    overlap_reward_scoring: bool = False
    # prompts per *generation* device batch during make_experience (defaults to
    # chunk_size). Decode is bandwidth-bound on the weights — every step streams
    # all parameters regardless of batch — so the decode batch wants to be as
    # wide as memory allows, independently of the reward/scoring chunk. The
    # batch-width effect is recorded per round by bench.py's
    # gpt2_rollout_new_tok_s (B=256) vs gpt2_rollout_new_tok_s_b32 keys
    # (BENCH_r0N.json / .bench_tpu_cache.json; docs/evidence.md).
    decode_batch_size: Optional[int] = None

    def kl_controller(self):
        if self.target is not None:
            return AdaptiveKLController(self.init_kl_coef, self.target, self.horizon)
        return FixedKLController(self.init_kl_coef)

    def get_advantages_and_returns(self, values, rewards, mask, use_whitening: bool = True):
        return gae_advantages_and_returns(values, rewards, mask, self.gamma, self.lam, use_whitening)

    def loss(
        self,
        logprobs: jnp.ndarray,
        values: jnp.ndarray,
        old_logprobs: jnp.ndarray,
        old_values: jnp.ndarray,
        advantages: jnp.ndarray,
        returns: jnp.ndarray,
        mask: jnp.ndarray,
        staleness: Optional[jnp.ndarray] = None,
        is_ratio_clip: Optional[float] = None,
    ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        """Clipped PPO policy + value loss with the reference's stats dict
        (modeling_ppo.py:175-238). All inputs are [B, T_resp]-shaped and masked.

        With ``staleness`` ([B] policy-version lag from the async rollout
        engine) and ``is_ratio_clip`` both set, the policy term of stale
        samples is reweighted by clipped per-token importance weights against
        the behavior-policy ``old_logprobs`` (docs/rollout.md). Weights are
        exactly 1.0 at staleness 0, keeping on-policy losses bitwise-identical
        to the vanilla path."""
        mask = mask.astype(values.dtype)
        # pin the float hyperparameters to concrete dtypes once (SH002): as
        # bare Python floats each use would trace as a weak_type scalar,
        # splitting the jit cache on weak_type and letting promotion drift on
        # bf16 operands
        cliprange = jnp.asarray(self.cliprange, logprobs.dtype)
        cliprange_value = jnp.asarray(self.cliprange_value, values.dtype)
        vf_coef = jnp.asarray(self.vf_coef, jnp.float32)
        # every loss accumulation pins dtype=float32: operands may be bf16 on
        # TPU, and a sequence-length sum in bf16 loses the low bits of exactly
        # the small per-token terms PPO clips on (JX007 discipline)
        n = jnp.maximum(mask.sum(dtype=jnp.float32), 1.0)

        values_clipped = jnp.clip(
            values, old_values - cliprange_value, old_values + cliprange_value
        )
        vf_loss1 = (values - returns) ** 2
        vf_loss2 = (values_clipped - returns) ** 2
        vf_loss = 0.5 * jnp.sum(jnp.maximum(vf_loss1, vf_loss2) * mask, dtype=jnp.float32) / n
        vf_clipfrac = jnp.sum((vf_loss2 > vf_loss1).astype(mask.dtype) * mask, dtype=jnp.float32) / n

        log_ratio = (logprobs - old_logprobs) * mask
        ratio = jnp.exp(log_ratio)
        # k3 estimator of approximate KL: mean(exp(-lr) - 1 + lr)
        approx_kl = jnp.sum((jnp.exp(-log_ratio) - 1.0 + log_ratio) * mask, dtype=jnp.float32) / n

        is_weights = None
        if staleness is not None and is_ratio_clip is not None:
            from trlx_tpu.rollout.staleness import staleness_importance_weights

            # reweight the surrogate's advantages (w > 0 commutes with the
            # clipped max below); stop-gradient inside keeps this a fixed
            # per-token correction, not a second policy-gradient path
            is_weights = staleness_importance_weights(log_ratio, staleness, is_ratio_clip)
            advantages = advantages * is_weights

        pg_loss1 = -advantages * ratio
        pg_loss2 = -advantages * jnp.clip(ratio, 1.0 - cliprange, 1.0 + cliprange)
        pg_loss = jnp.sum(jnp.maximum(pg_loss1, pg_loss2) * mask, dtype=jnp.float32) / n
        pg_clipfrac = jnp.sum((pg_loss2 > pg_loss1).astype(mask.dtype) * mask, dtype=jnp.float32) / n

        loss = pg_loss + vf_coef * vf_loss

        stats = dict(
            losses=dict(total_loss=loss, policy_loss=pg_loss, value_loss=vf_loss),
            values=dict(
                get_tensor_stats=dict(
                    mean=masked_mean(values, mask),
                    min=jnp.min(jnp.where(mask > 0, values, jnp.inf)),
                    max=jnp.max(jnp.where(mask > 0, values, -jnp.inf)),
                    std=jnp.sqrt(masked_mean((values - masked_mean(values, mask)) ** 2, mask)),
                ),
                values_error=jnp.sum(((values - returns) * mask) ** 2, dtype=jnp.float32) / n,
                clipfrac=vf_clipfrac,
            ),
            old_values=dict(mean=masked_mean(old_values, mask)),
            returns=dict(
                mean=masked_mean(returns, mask),
                std=jnp.sqrt(masked_mean((returns - masked_mean(returns, mask)) ** 2, mask)),
            ),
            policy=dict(approx_kl=approx_kl, clipfrac=pg_clipfrac),
            ratio=jnp.sum(ratio * mask, dtype=jnp.float32) / n,
            padding_percentage=1.0 - n / mask.size,
        )
        if is_weights is not None:
            stats["staleness"] = dict(
                mean=jnp.mean(staleness.astype(jnp.float32)),
                max=jnp.max(staleness),
                is_weight_mean=jnp.sum(is_weights * mask, dtype=jnp.float32) / n,
            )
        return loss, stats


# -- AOT audit surface (graftcheck-ir) ----------------------------------------


@register_entrypoint("ppo_train_step", specs=("small",))
def build_ppo_train_step(spec: str, mesh) -> EntryArtifacts:
    """The PPO learner step as graftcheck-ir audits it: the same
    loss/grad-accum-scan/optax-update construction as
    ``PPOTrainer._get_train_step`` + ``MeshRLTrainer.make_grad_accum_step``,
    over fully abstract sharded inputs (nothing materialized — the
    ``scripts/scale_proof.py`` blueprint at audit shapes).

    ``TRLX_IR_SEED_REGRESSION`` injects a deliberate defect (``f32_upcast``:
    an f32 logit matmul IR001 must flag; ``allgather``: a replication
    constraint whose all-gather must break the IR005 budget) so CI can prove
    the gate fails closed.
    """
    return _build_train_step(spec, mesh, PPOConfig())


def _build_train_step(spec: str, mesh, method) -> EntryArtifacts:
    """The shared audit-shape learner-step construction behind the
    ``ppo_train_step`` and ``grpo_train_step`` entrypoints — GRPO inherits
    PPO's step plumbing wholesale (methods/grpo.py), so the audit surface is
    one builder parameterized by the method, not two drifting copies."""
    import os

    import optax
    from jax.sharding import NamedSharding, PartitionSpec

    from trlx_tpu.data.ppo_types import PPORLBatch
    from trlx_tpu.models.policy import CausalLMWithValueHead
    from trlx_tpu.models.presets import PRESETS
    from trlx_tpu.parallel.mesh import BATCH_AXES
    from trlx_tpu.parallel.sharding import make_param_shardings, make_state_shardings
    from trlx_tpu.utils.modeling import logprobs_of_labels

    dims = {"small": dict(hidden=64, layers=2, heads=4, vocab=256, B=8, P=24, R=8)}[spec]
    model_config = PRESETS["gpt2"].replace(
        vocab_size=dims["vocab"], hidden_size=dims["hidden"],
        num_layers=dims["layers"], num_heads=dims["heads"],
        intermediate_size=4 * dims["hidden"], max_position_embeddings=64,
        param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
    )
    module = CausalLMWithValueHead(model_config)
    seed_regression = os.environ.get("TRLX_IR_SEED_REGRESSION", "")

    params_shape = jax.eval_shape(
        lambda: module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32), jnp.ones((1, 2), jnp.int32)
        )
    )["params"]
    abs_params = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        params_shape, make_param_shardings(params_shape, mesh),
    )
    tx = optax.adamw(1e-5)
    opt_shapes = jax.eval_shape(tx.init, abs_params)
    abs_opt = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        opt_shapes, make_state_shardings(opt_shapes, mesh),
    )

    B, P, R = dims["B"], dims["P"], dims["R"]
    bsh = NamedSharding(mesh, PartitionSpec(BATCH_AXES, None))

    def babs(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=bsh)

    abs_batch = PPORLBatch(
        query_tensors=babs((B, P), jnp.int32),
        response_tensors=babs((B, R), jnp.int32),
        logprobs=babs((B, R), jnp.float32),
        values=babs((B, R), jnp.float32),
        rewards=babs((B, R), jnp.float32),
        attention_mask=babs((B, P), jnp.int32),
        response_mask=babs((B, R), jnp.int32),
    )
    num_mb = 2

    def loss_fn(params, mb):
        seq = jnp.concatenate([mb.query_tensors, mb.response_tensors], axis=1)
        mask = jnp.concatenate([mb.attention_mask, mb.response_mask], axis=1)
        logits, values_pred, _, _ = module.apply({"params": params}, seq, mask)
        if seed_regression == "allgather":
            # audit seed: replicating the sharded logits forces an all-gather
            # the committed budget does not contain
            logits = jax.lax.with_sharding_constraint(
                logits, NamedSharding(mesh, PartitionSpec())
            )
        logprobs = logprobs_of_labels(logits[:, :-1], seq[:, 1:])
        start = mb.query_tensors.shape[1] - 1
        logprobs = logprobs[:, start:start + R]
        values_pred = values_pred[:, start:start + R].astype(jnp.float32)
        advantages, returns = method.get_advantages_and_returns(
            mb.values, mb.rewards, mb.response_mask
        )
        loss, _ = method.loss(
            logprobs, values_pred, mb.logprobs, mb.values, advantages, returns,
            mb.response_mask,
        )
        if seed_regression == "f32_upcast":
            # audit seed: a heavy f32 matmul inside the bf16-declared step
            logits32 = logits.astype(jnp.float32)
            probe = jnp.einsum("btv,bsv->ts", logits32, logits32)
            loss = loss + 0.0 * jnp.sum(probe, dtype=jnp.float32)
        return loss

    def train_step(params, opt_state, batch):
        mbs = jax.tree.map(
            lambda x: x.reshape((num_mb, x.shape[0] // num_mb) + x.shape[1:]), batch
        )

        def body(grads_acc, mb):
            grads = jax.grad(loss_fn)(params, mb)
            return jax.tree.map(jnp.add, grads_acc, grads), None

        grads, _ = jax.lax.scan(body, jax.tree.map(jnp.zeros_like, params), mbs)
        grads = jax.tree.map(lambda g: g / num_mb, grads)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt_state

    return EntryArtifacts(
        fn=train_step,
        args=(abs_params, abs_opt, abs_batch),
        donate_argnums=(0, 1),
        compute_dtype="bfloat16",
        # the value head's output Dense is deliberately f32 (MLPHead.fc_out):
        # 1 forward + 2 backward dots per step, and no more
        f32_allow=frozenset({"dot_general:3"}),
        meta=dict(batch=B, prompt=P, response=R, num_microbatches=num_mb),
    )


def _ppo_audit_loss_fn(module, method, mesh, R: int):
    """The audit-shape PPO loss shared by the overlap entrypoints: same
    construction as ``build_ppo_train_step``'s, minus the seeds (the overlap
    seed lives in ``parallel/fsdp.py``'s step builder, not the loss)."""
    from trlx_tpu.utils.modeling import logprobs_of_labels

    def loss_fn(params, mb):
        seq = jnp.concatenate([mb.query_tensors, mb.response_tensors], axis=1)
        mask = jnp.concatenate([mb.attention_mask, mb.response_mask], axis=1)
        logits, values_pred, _, _ = module.apply({"params": params}, seq, mask)
        logprobs = logprobs_of_labels(logits[:, :-1], seq[:, 1:])
        start = mb.query_tensors.shape[1] - 1
        logprobs = logprobs[:, start:start + R]
        values_pred = values_pred[:, start:start + R].astype(jnp.float32)
        advantages, returns = method.get_advantages_and_returns(
            mb.values, mb.rewards, mb.response_mask
        )
        loss, _ = method.loss(
            logprobs, values_pred, mb.logprobs, mb.values, advantages, returns,
            mb.response_mask,
        )
        return loss

    return loss_fn


@register_entrypoint(
    "ppo_train_step_overlap",
    specs=("small",),
    mesh={"data": 2, "fsdp": 2, "pipe": 1, "model": 1},
)
def build_ppo_train_step_overlap(spec: str, mesh) -> EntryArtifacts:
    """The overlapped-collective FSDP learner step (``train.learner_overlap``,
    ``parallel/fsdp.py``) as graftcheck-ir audits it: explicit shard_map
    collectives — per-leaf parameter all-gather in the forward, whose AD
    transpose reduce-scatters the gradient per-leaf during the backward —
    with a gradient-shard accumulation carry and a ZeRO-sharded optimizer
    update. The committed IR005 budget for this entry must show
    ``reduce-scatter:fsdp`` / ``all-gather:fsdp`` and NO ``all-reduce:fsdp``;
    ``TRLX_IR_SEED_REGRESSION=allreduce_under_fsdp`` (handled by the step
    builder) restores the full-gradient all-reduce so CI can prove the budget
    rejects it. Audits on a pure data/fsdp mesh — the overlap path's
    requirement (``fsdp.can_overlap``).
    """
    import optax
    from jax.sharding import NamedSharding, PartitionSpec

    from trlx_tpu.data.ppo_types import PPORLBatch
    from trlx_tpu.models.policy import CausalLMWithValueHead
    from trlx_tpu.models.presets import PRESETS
    from trlx_tpu.parallel import fsdp as fsdp_lib
    from trlx_tpu.parallel.mesh import BATCH_AXES

    dims = {"small": dict(hidden=64, layers=2, heads=4, vocab=256, B=8, P=24, R=8)}[spec]
    model_config = PRESETS["gpt2"].replace(
        vocab_size=dims["vocab"], hidden_size=dims["hidden"],
        num_layers=dims["layers"], num_heads=dims["heads"],
        intermediate_size=4 * dims["hidden"], max_position_embeddings=64,
        param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
    )
    module = CausalLMWithValueHead(model_config)
    method = PPOConfig()

    params_shape = jax.eval_shape(
        lambda: module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32), jnp.ones((1, 2), jnp.int32)
        )
    )["params"]
    tx = optax.adamw(1e-5)
    specs = fsdp_lib.make_overlap_specs(params_shape, tx, mesh)
    abs_params = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=NamedSharding(mesh, s)),
        params_shape, specs.param_specs,
    )
    abs_opt = fsdp_lib.global_state_struct(specs, mesh)

    B, P, R = dims["B"], dims["P"], dims["R"]
    bsh = NamedSharding(mesh, PartitionSpec(BATCH_AXES, None))

    def babs(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=bsh)

    abs_batch = PPORLBatch(
        query_tensors=babs((B, P), jnp.int32),
        response_tensors=babs((B, R), jnp.int32),
        logprobs=babs((B, R), jnp.float32),
        values=babs((B, R), jnp.float32),
        rewards=babs((B, R), jnp.float32),
        attention_mask=babs((B, P), jnp.int32),
        response_mask=babs((B, R), jnp.int32),
    )
    num_mb = 2
    loss_fn = _ppo_audit_loss_fn(module, method, mesh, R)
    step = fsdp_lib.make_overlapped_grad_accum_step(
        loss_fn, tx, specs, mesh, num_mb, has_aux=False, max_grad_norm=1.0,
    )

    def train_step(params, opt_state, batch):
        new_params, new_opt, _ = step(params, opt_state, batch)
        return new_params, new_opt

    return EntryArtifacts(
        fn=train_step,
        args=(abs_params, abs_opt, abs_batch),
        donate_argnums=(0, 1),
        compute_dtype="bfloat16",
        f32_allow=frozenset({"dot_general:3"}),
        meta=dict(
            batch=B, prompt=P, response=R, num_microbatches=num_mb,
            overlap=True, sharded_opt_state=True,
        ),
    )


@register_entrypoint(
    "ppo_train_step_unsharded_opt",
    specs=("small",),
    mesh={"data": 2, "fsdp": 2, "pipe": 1, "model": 1},
)
def build_ppo_train_step_unsharded_opt(spec: str, mesh) -> EntryArtifacts:
    """Memory comparator for the overlap entry (IR006): the plain GSPMD step
    with deliberately REPLICATED optimizer state, on the same pure data/fsdp
    mesh as ``ppo_train_step_overlap``. The committed budget pins both
    entries' ``memory_bytes``; the overlap entry (sharded state + shard-local
    update) must stay strictly below this one — asserted by
    ``tests/test_learner_overlap.py`` against the committed budget and
    re-checked on every regeneration.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    art = build_ppo_train_step(spec, mesh)
    repl = NamedSharding(mesh, PartitionSpec())
    abs_params, abs_opt, abs_batch = art.args
    abs_opt = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=repl), abs_opt
    )
    return EntryArtifacts(
        fn=art.fn,
        args=(abs_params, abs_opt, abs_batch),
        donate_argnums=art.donate_argnums,
        compute_dtype=art.compute_dtype,
        f32_allow=art.f32_allow,
        meta=dict(art.meta, unsharded_opt_state=True),
    )
