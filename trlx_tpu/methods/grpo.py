"""GRPO method: critic-free group-relative policy optimization.

GRPO (Group Relative Policy Optimization, Shao et al. 2024, DeepSeekMath)
replaces PPO's learned value baseline with a *group* baseline: for each
prompt, sample a group of G completions, score them, and normalize each
score against the group's own mean and standard deviation:

    A_i = (r_i - mean(r_group)) / (std(r_group) + eps)

No value head, no GAE bootstrap, no value loss — the surrogate is PPO's
clipped policy term driven by the group-relative advantage spread over
response tokens as discounted returns-to-go, plus the same per-token
KL-to-reference shaping the PPO path already assembles in
``_score_and_store``. Everything else — microbatching, the FSDP /
overlapped-collective step, stream-overlap rollout, staleness
importance-weighting — is inherited from :class:`PPOConfig` /
``PPOTrainer`` unchanged, which is the point: the fleet's served
completion groups (docs/online.md) are exactly GRPO's input shape.

Two exact properties the tests pin:

- a constant-reward group normalizes to *exactly* zero advantage (the
  centered residual is identically 0 before the std division), so a
  degenerate group is a no-op update, not a NaN;
- for identical inputs, ``GRPOConfig.loss`` equals the ``policy_loss``
  component of ``PPOConfig.loss`` — the shared-plumbing parity that keeps
  the two methods one codepath apart, not two implementations.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.analysis.ir.entrypoints import EntryArtifacts, register_entrypoint
from trlx_tpu.data.method_configs import register_method
from trlx_tpu.methods.ppo import PPOConfig, gae_advantages_and_returns
from trlx_tpu.utils.modeling import masked_mean

#: guard for the group-std division — centered residuals of a constant
#: group are exactly zero, so eps only sets the scale of near-ties
GROUP_EPS = 1e-6


@register_method
@dataclass
class GRPOConfig(PPOConfig):
    """GRPO hyperparameters: :class:`PPOConfig` minus the critic.

    :param group_size: completions sampled per prompt; scores normalize
        within each group. ``num_rollouts`` and ``chunk_size`` must both be
        multiples of ``group_size`` so groups never straddle a scoring
        chunk (the group baseline needs the whole group in one batch).
    :param whiten_advantages: re-whiten the per-token advantages over the
        global batch after the group normalization. Off by default — the
        group baseline *is* the normalization; batch whitening on top
        changes the estimator.

    Inherited value-function fields (``vf_coef``, ``cliprange_value``,
    ``num_value_layers_unfrozen``) are inert: the loss has no value term
    and the trainer trains no value branch.
    """

    name: str = "GRPOConfig"
    group_size: int = 4
    whiten_advantages: bool = False
    # groups need diverse completions — greedy decode makes every group
    # member identical and every advantage zero
    gen_kwargs: Dict[str, Any] = field(
        default_factory=lambda: dict(max_new_tokens=16, do_sample=True)
    )

    def __post_init__(self):
        if self.group_size < 2:
            raise ValueError(
                f"group_size must be >= 2 (a singleton group has zero "
                f"advantage by construction), got {self.group_size}"
            )
        if self.num_rollouts % self.group_size != 0:
            raise ValueError(
                f"num_rollouts ({self.num_rollouts}) must be a multiple of "
                f"group_size ({self.group_size})"
            )
        if self.chunk_size % self.group_size != 0:
            raise ValueError(
                f"chunk_size ({self.chunk_size}) must be a multiple of "
                f"group_size ({self.group_size}) — groups must not straddle "
                f"scoring chunks"
            )

    # ------------------------------------------------------------ group math

    def group_normalize(self, scores: np.ndarray) -> np.ndarray:
        """Host-side group-relative normalization of a flat score vector.

        ``scores`` is [B] with B a multiple of ``group_size`` and group
        members adjacent (the trainer's prompt repetition guarantees the
        layout). Returns [B] advantages. A constant group yields exact
        zeros: the centered residual is identically 0, so the eps-guarded
        std division never manufactures signal from a degenerate group.
        """
        scores = np.asarray(scores, dtype=np.float32)
        if scores.ndim != 1 or scores.size % self.group_size != 0:
            raise ValueError(
                f"scores must be flat with size a multiple of group_size="
                f"{self.group_size}, got shape {scores.shape}"
            )
        grouped = scores.reshape(-1, self.group_size)
        centered = grouped - grouped.mean(axis=1, keepdims=True)
        std = np.sqrt((centered**2).mean(axis=1, keepdims=True))
        return (centered / (std + GROUP_EPS)).reshape(-1)

    def get_advantages_and_returns(
        self, values, rewards, mask, use_whitening: bool = True
    ):
        """Critic-free advantages: discounted returns-to-go of the per-token
        rewards (group-normalized score at the last token + KL shaping),
        computed as GAE with a zero value baseline and ``lam=1`` — the exact
        degenerate case of the shared reverse-scan kernel. Returns zero
        "returns" (stop-gradded) so the inherited value-loss plumbing sees a
        fixed zero target it contributes nothing against (``vf_coef`` is
        unused in :meth:`loss` anyway)."""
        zeros = jnp.zeros_like(rewards)
        advantages, _ = gae_advantages_and_returns(
            zeros, rewards, mask, self.gamma, 1.0,
            use_whitening=use_whitening and self.whiten_advantages,
        )
        return advantages, jax.lax.stop_gradient(zeros)

    # ------------------------------------------------------------------ loss

    def loss(
        self,
        logprobs: jnp.ndarray,
        values: jnp.ndarray,
        old_logprobs: jnp.ndarray,
        old_values: jnp.ndarray,
        advantages: jnp.ndarray,
        returns: jnp.ndarray,
        mask: jnp.ndarray,
        staleness: Optional[jnp.ndarray] = None,
        is_ratio_clip: Optional[float] = None,
    ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        """PPO's clipped policy surrogate with NO value term.

        Same signature as :meth:`PPOConfig.loss` so the trainer's loss_fn is
        method-agnostic; ``values``/``old_values``/``returns`` are accepted
        and ignored. Stats mirror PPO's key layout (``losses/value_loss`` is
        a constant 0) plus a ``group`` block: the advantage spread actually
        driving the update and ``policy_delta`` = mean |ratio - 1|, the
        per-step policy movement the online loop exports as
        ``online/policy_delta``."""
        mask = mask.astype(logprobs.dtype)
        # pin the clip range once (SH002): a bare Python float would trace as
        # a weak_type scalar and split the jit cache on weak_type
        cliprange = jnp.asarray(self.cliprange, logprobs.dtype)
        # f32-pinned reductions throughout: operands may be bf16 on TPU and
        # sequence-length sums lose exactly the small clipped terms (JX007)
        n = jnp.maximum(mask.sum(dtype=jnp.float32), 1.0)

        log_ratio = (logprobs - old_logprobs) * mask
        ratio = jnp.exp(log_ratio)
        # k3 estimator of approximate KL: mean(exp(-lr) - 1 + lr)
        approx_kl = jnp.sum(
            (jnp.exp(-log_ratio) - 1.0 + log_ratio) * mask, dtype=jnp.float32
        ) / n

        is_weights = None
        if staleness is not None and is_ratio_clip is not None:
            from trlx_tpu.rollout.staleness import staleness_importance_weights

            is_weights = staleness_importance_weights(
                log_ratio, staleness, is_ratio_clip
            )
            advantages = advantages * is_weights

        pg_loss1 = -advantages * ratio
        pg_loss2 = -advantages * jnp.clip(
            ratio, 1.0 - cliprange, 1.0 + cliprange
        )
        pg_loss = jnp.sum(
            jnp.maximum(pg_loss1, pg_loss2) * mask, dtype=jnp.float32
        ) / n
        pg_clipfrac = jnp.sum(
            (pg_loss2 > pg_loss1).astype(mask.dtype) * mask, dtype=jnp.float32
        ) / n

        loss = pg_loss

        adv_mean = masked_mean(advantages, mask)
        adv_std = jnp.sqrt(masked_mean((advantages - adv_mean) ** 2, mask))
        policy_delta = jnp.sum(
            jnp.abs(ratio - 1.0) * mask, dtype=jnp.float32
        ) / n

        stats = dict(
            losses=dict(
                total_loss=loss,
                policy_loss=pg_loss,
                value_loss=jnp.zeros((), dtype=jnp.float32),
            ),
            policy=dict(approx_kl=approx_kl, clipfrac=pg_clipfrac),
            group=dict(
                adv_mean=adv_mean, adv_std=adv_std, policy_delta=policy_delta
            ),
            ratio=jnp.sum(ratio * mask, dtype=jnp.float32) / n,
            padding_percentage=1.0 - n / mask.size,
        )
        if is_weights is not None:
            stats["staleness"] = dict(
                mean=jnp.mean(staleness.astype(jnp.float32)),
                max=jnp.max(staleness),
                is_weight_mean=jnp.sum(is_weights * mask, dtype=jnp.float32) / n,
            )
        return loss, stats


# -- AOT audit surface (graftcheck-ir / graftcheck-rt) ------------------------


@register_entrypoint("grpo_train_step", specs=("small",))
def build_grpo_train_step(spec: str, mesh) -> EntryArtifacts:
    """The GRPO learner step at audit shapes: PPO's shared step construction
    (grad-accum scan + adamw update) with :class:`GRPOConfig`'s critic-free
    loss swapped in — one builder (``methods/ppo.py:_build_train_step``), two
    methods, which is the parity the GRPO tests pin. The rt compile-budget
    probe executes this same artifact to prove the step compiles once and
    never again in steady state."""
    from trlx_tpu.methods.ppo import _build_train_step

    return _build_train_step(spec, mesh, GRPOConfig())
