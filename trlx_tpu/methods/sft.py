"""SFT method config (parity: ``SFTConfig``,
`/root/reference/trlx/trainer/accelerate_sft_trainer.py:16-26`): plain masked
cross-entropy on (prompt, output) dialogues; ``gen_kwargs`` drive eval generation."""

from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.method_configs import MethodConfig, register_method


@register_method
@dataclass
class SFTConfig(MethodConfig):
    name: str = "SFTConfig"
    gen_kwargs: Dict[str, Any] = field(default_factory=lambda: dict(max_new_tokens=32))

    def loss(self, logits: jnp.ndarray, labels: jnp.ndarray, loss_mask: jnp.ndarray) -> Tuple[jnp.ndarray, Dict]:
        """Next-token CE: logits [B,T,V] vs labels [B,T], masked by ``loss_mask``
        (0 on prompt tokens when only outputs are supervised)."""
        shift_logits = logits[:, :-1]
        shift_labels = labels[:, 1:]
        shift_mask = loss_mask[:, 1:].astype(shift_logits.dtype)
        logprobs = jax.nn.log_softmax(shift_logits, axis=-1)
        nll = -jnp.take_along_axis(logprobs, shift_labels[..., None], axis=-1)[..., 0]
        n = jnp.maximum(shift_mask.sum(), 1.0)
        loss = jnp.sum(nll * shift_mask) / n
        return loss, dict(losses=dict(loss=loss))
