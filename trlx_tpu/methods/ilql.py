"""ILQL method: expectile V loss, double-Q TD loss, CQL regularizer, AWAC-weighted CE.

Functional parity with the reference's ``ILQLConfig.loss``
(`/root/reference/trlx/models/modeling_ilql.py:48-166`), including the index
conventions: heads are evaluated at state positions (``states_ixs``, one more than the
action count), Q values are gathered at the action token ids, targets use the minimum
over (target) Q heads, and every term is normalized by the count of non-terminal
transitions. Expressed as pure jnp on fixed shapes with masks.
"""

from dataclasses import dataclass, field
from functools import reduce
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.analysis.ir.entrypoints import EntryArtifacts, register_entrypoint
from trlx_tpu.data.method_configs import MethodConfig, register_method
from trlx_tpu.utils.modeling import masked_mean


def topk_mask(xs: jnp.ndarray, k: int) -> jnp.ndarray:
    """Set everything below the k-th largest value (last axis) to -inf
    (parity: modeling_ilql.py:29-33)."""
    if k >= xs.shape[-1]:
        return xs
    mintop = jax.lax.top_k(xs, k)[0][..., -1:]
    return jnp.where(xs < mintop, -jnp.inf, xs)


def batched_index_select(x: jnp.ndarray, idxs: jnp.ndarray) -> jnp.ndarray:
    """Gather vectors at ``idxs`` along axis 1: [B,T,H], [B,I] -> [B,I,H]
    (parity: modeling_ilql.py:36-45)."""
    return jnp.take_along_axis(x, idxs[..., None], axis=1)


@register_method
@dataclass
class ILQLConfig(MethodConfig):
    """ILQL hyperparameters (same names/semantics as the reference docstring):
    ``tau`` expectile, ``gamma`` discount, ``cql_scale``, ``awac_scale``, Polyak
    ``alpha``, AWAC/advantage ``beta``, ``steps_for_target_q_sync``, ``two_qs``,
    ``gen_kwargs`` (with ``beta`` consumed by advantage-shaped decoding)."""

    name: str = "ILQLConfig"
    tau: float = 0.7
    gamma: float = 0.99
    cql_scale: float = 0.1
    awac_scale: float = 1.0
    alpha: float = 0.001
    beta: float = 0.0
    steps_for_target_q_sync: int = 200
    two_qs: bool = True
    gen_kwargs: Dict[str, Any] = field(
        default_factory=lambda: dict(max_new_tokens=56, top_k=20, beta=4.0, temperature=1.0)
    )

    def loss(self, outputs, batch) -> Tuple[jnp.ndarray, Dict[str, Any]]:
        """``outputs = (logits_at_actions, (qs, target_qs, vs))``; ``batch`` is an
        :class:`trlx_tpu.data.ilql_types.ILQLBatch`.

        Shapes: qs/target_qs tuples of [B, A, V] at action states; vs [B, A+1, 1];
        ``batch.rewards`` [B, A]; ``batch.dones`` [B, A+1] (1 while non-terminal).
        ``logits_at_actions`` [B, A, V] are the policy logits at action positions.
        """
        logits, (qs, target_qs, vs) = outputs
        terminal_mask = batch.dones[:, :-1].astype(vs.dtype)
        # pin the float hyperparameters to concrete dtypes once (SH002): bare
        # Python floats would trace as weak_type scalars, splitting the jit
        # cache on weak_type and drifting promotion on bf16 operands
        gamma = jnp.asarray(self.gamma, vs.dtype)
        tau = jnp.asarray(self.tau, vs.dtype)
        beta = jnp.asarray(self.beta, vs.dtype)
        cql_scale = jnp.asarray(self.cql_scale, jnp.float32)
        awac_scale = jnp.asarray(self.awac_scale, jnp.float32)
        # loss sums pin dtype=float32: Q/V are f32 by head design but the CE
        # term multiplies in logits-derived terms that are bf16 on TPU
        # (JX007 discipline)
        n_nonterminal = jnp.maximum(terminal_mask.sum(dtype=jnp.float32), 1.0)

        # token ids actually taken at each action position (parity with the
        # reference's ILQLBatch-vs-seq2seq dispatch, modeling_ilql.py:99-103):
        # causal — input_ids shifted left, gathered at action indices;
        # seq2seq — decoder tokens after decoder_start
        if hasattr(batch, "decoder_input_ids"):
            actions = batch.decoder_input_ids[:, 1:]
        else:
            actions = jnp.take_along_axis(batch.input_ids[:, 1:], batch.actions_ixs, axis=1)
        bsize, nactions = actions.shape

        Q = [jnp.take_along_axis(q, actions[..., None], axis=-1)[..., 0] for q in qs]
        targetQs = [
            jax.lax.stop_gradient(jnp.take_along_axis(q, actions[..., None], axis=-1)[..., 0])
            for q in target_qs
        ]
        targetQ = reduce(jnp.minimum, targetQs)

        V = vs[:, :-1, 0]
        Vnext = vs[:, 1:, 0] * batch.dones[:, 1:].astype(vs.dtype)
        Q_ = batch.rewards + gamma * jax.lax.stop_gradient(Vnext)

        loss_q = sum(jnp.sum(((Qi - Q_) * terminal_mask) ** 2, dtype=jnp.float32) / n_nonterminal for Qi in Q)

        expectile_w = jnp.where(targetQ >= V, tau, 1.0 - tau)
        loss_v = jnp.sum(expectile_w * (targetQ - V) ** 2 * terminal_mask, dtype=jnp.float32) / n_nonterminal

        def cql_loss(q):
            logprobs = jax.nn.log_softmax(q, axis=-1)
            nll = -jnp.take_along_axis(logprobs, actions[..., None], axis=-1)[..., 0]
            return jnp.sum(nll * terminal_mask, dtype=jnp.float32) / n_nonterminal

        loss_cql = sum(cql_loss(q) for q in qs)

        ce = -jnp.take_along_axis(jax.nn.log_softmax(logits, axis=-1), actions[..., None], axis=-1)[..., 0]
        awac_weight = jax.lax.stop_gradient(jnp.exp(beta * (targetQ - V)))
        loss_awac = jnp.sum(ce * awac_weight * terminal_mask, dtype=jnp.float32) / n_nonterminal

        loss = loss_q + loss_v + cql_scale * loss_cql + awac_scale * loss_awac

        stats = dict(
            losses=dict(
                loss=loss, loss_q=loss_q, loss_v=loss_v, loss_cql=loss_cql, loss_awac=loss_awac
            ),
            values=dict(mean=masked_mean(V, terminal_mask)),
            qvalues={str(ix): dict(mean=masked_mean(Q[ix], terminal_mask)) for ix in range(len(Q))},
            awac_weight=dict(mean=masked_mean(awac_weight, terminal_mask)),
        )
        return loss, stats


# -- AOT audit surface (graftcheck-ir) ----------------------------------------


@register_entrypoint("ilql_train_step", specs=("small",))
def build_ilql_train_step(spec: str, mesh) -> EntryArtifacts:
    """The ILQL learner step as graftcheck-ir audits it: the same
    ``CausalLMWithILQLHeads`` forward + :meth:`ILQLConfig.loss` + optax update
    as ``ILQLTrainer._get_train_step``, over fully abstract sharded inputs."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec

    from trlx_tpu.data.ilql_types import ILQLBatch
    from trlx_tpu.models.policy import CausalLMWithILQLHeads
    from trlx_tpu.models.presets import PRESETS
    from trlx_tpu.parallel.mesh import BATCH_AXES
    from trlx_tpu.parallel.sharding import make_param_shardings, make_state_shardings

    dims = {"small": dict(hidden=64, layers=2, heads=4, vocab=256, B=8, T=24, A=7)}[spec]
    model_config = PRESETS["gpt2"].replace(
        vocab_size=dims["vocab"], hidden_size=dims["hidden"],
        num_layers=dims["layers"], num_heads=dims["heads"],
        intermediate_size=4 * dims["hidden"], max_position_embeddings=64,
        param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
    )
    module = CausalLMWithILQLHeads(model_config, two_qs=True)
    method = ILQLConfig()

    params_shape = jax.eval_shape(
        lambda: module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32), jnp.ones((1, 2), jnp.int32)
        )
    )["params"]
    abs_params = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        params_shape, make_param_shardings(params_shape, mesh),
    )
    tx = optax.adamw(1e-5)
    opt_shapes = jax.eval_shape(tx.init, abs_params)
    abs_opt = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        opt_shapes, make_state_shardings(opt_shapes, mesh),
    )

    B, T, A = dims["B"], dims["T"], dims["A"]
    bsh = NamedSharding(mesh, PartitionSpec(BATCH_AXES, None))

    def babs(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=bsh)

    abs_batch = ILQLBatch(
        input_ids=babs((B, T), jnp.int32),
        attention_mask=babs((B, T), jnp.int32),
        rewards=babs((B, A), jnp.float32),
        states_ixs=babs((B, A + 1), jnp.int32),
        actions_ixs=babs((B, A), jnp.int32),
        dones=babs((B, A + 1), jnp.int32),
    )

    def loss_fn(params, mb):
        logits, qs, target_qs, vs, _ = module.apply(
            {"params": params}, mb.input_ids, mb.attention_mask, None,
            mb.actions_ixs, mb.states_ixs,
        )
        action_logits = batched_index_select(logits, mb.actions_ixs)
        loss, _ = method.loss((action_logits, (qs, target_qs, vs)), mb)
        return loss

    def train_step(params, opt_state, batch):
        grads = jax.grad(loss_fn)(params, batch)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt_state

    return EntryArtifacts(
        fn=train_step,
        args=(abs_params, abs_opt, abs_batch),
        donate_argnums=(0, 1),
        compute_dtype="bfloat16",
        # q/target-q/v heads all end in a deliberately-f32 Dense
        # (MLPHead.fc_out): 11 f32 dots for two_qs=True, and no more
        f32_allow=frozenset({"dot_general:11"}),
        meta=dict(batch=B, seq=T, actions=A),
    )
