"""Architecture presets + HF-config conversion for the generic TransformerLM.

Replaces the reference's per-architecture model surgery (`hf_get_*` getters,
`/root/reference/trlx/utils/modeling.py:13-120`, and the per-arch hydra branches in
`modeling_ppo.py`): each supported family is a preset of TransformerConfig switches.
"""

from typing import Any, Dict, Optional

from trlx_tpu.models.transformer import TransformerConfig

# Tiny shape defaults used when no checkpoint is available (offline/random-init runs
# and tests); real dims come from HF configs via from_hf_config.
PRESETS: Dict[str, TransformerConfig] = {
    "gpt2": TransformerConfig(
        vocab_size=50257, hidden_size=768, num_layers=12, num_heads=12,
        max_position_embeddings=1024, pos_embedding="learned", norm="layernorm",
        activation="gelu_new", attn_bias=True, mlp_bias=True, tie_word_embeddings=True,
    ),
    "gptj": TransformerConfig(
        vocab_size=50400, hidden_size=4096, num_layers=28, num_heads=16,
        max_position_embeddings=2048, pos_embedding="rotary", rope_style="gptj",
        rotary_pct=64 / 256, norm="layernorm", activation="gelu_new",
        parallel_residual=True, shared_parallel_ln=True, attn_bias=False, mlp_bias=True,
        head_bias=True, tie_word_embeddings=False,
    ),
    "gpt_neox": TransformerConfig(
        vocab_size=50304, hidden_size=768, num_layers=12, num_heads=12,
        max_position_embeddings=2048, pos_embedding="rotary", rope_style="neox",
        rotary_pct=0.25, norm="layernorm", activation="gelu", parallel_residual=True,
        shared_parallel_ln=False, attn_bias=True, mlp_bias=True, tie_word_embeddings=False,
    ),
    "opt": TransformerConfig(
        vocab_size=50272, hidden_size=768, num_layers=12, num_heads=12,
        max_position_embeddings=2048, pos_embedding="learned", pos_offset=2,
        norm="layernorm", activation="relu", attn_bias=True, mlp_bias=True,
        tie_word_embeddings=True,
    ),
    "llama": TransformerConfig(
        vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
        intermediate_size=11008, max_position_embeddings=4096, pos_embedding="rotary",
        rope_style="neox", norm="rmsnorm", norm_eps=1e-6, activation="silu", glu=True,
        attn_bias=False, mlp_bias=False, tie_word_embeddings=False,
    ),
    # reference parity: BloomModelBranch (modeling_ppo.py:816) — ALiBi positions,
    # embedding LayerNorm, fused per-head qkv, tied embeddings
    "bloom": TransformerConfig(
        vocab_size=250880, hidden_size=1024, num_layers=24, num_heads=16,
        max_position_embeddings=2048, pos_embedding="alibi", norm="layernorm",
        activation="gelu_new", attn_bias=True, mlp_bias=True, embed_ln=True,
        tie_word_embeddings=True,
    ),
    # reference parity: GPTBigCodeModelBranch (modeling_ppo.py:1079) — multi-query
    # attention (1 kv head), learned positions, tanh-gelu
    "gpt_bigcode": TransformerConfig(
        vocab_size=49152, hidden_size=2048, num_layers=24, num_heads=16,
        num_kv_heads=1, max_position_embeddings=2048, pos_embedding="learned",
        norm="layernorm", activation="gelu_new", attn_bias=True, mlp_bias=True,
        tie_word_embeddings=True,
    ),
}


def get_preset(name: str, overrides: Optional[Dict[str, Any]] = None) -> TransformerConfig:
    """Resolve a family preset by name (exact or prefix: "gpt2-imdb" -> gpt2)."""
    key = name.lower()
    config = None
    if key in PRESETS:
        config = PRESETS[key]
    else:
        for family in ("gpt_bigcode", "gpt_neox", "gptj", "gpt2", "llama", "opt", "bloom"):
            if family.replace("_", "") in key.replace("_", "").replace("-", ""):
                config = PRESETS[family]
                break
        if config is None and ("pythia" in key or "neox" in key):
            config = PRESETS["gpt_neox"]
        if config is None and ("starcoder" in key or "santacoder" in key):
            config = PRESETS["gpt_bigcode"]
    if config is None:
        raise ValueError(f"Unknown architecture preset for {name!r}; known: {sorted(PRESETS)}")
    if overrides:
        config = config.replace(**overrides)
    return config


def from_hf_config(hf_config, overrides: Optional[Dict[str, Any]] = None) -> TransformerConfig:
    """Convert a ``transformers`` config object to TransformerConfig."""
    mt = hf_config.model_type
    if mt == "gpt2":
        config = PRESETS["gpt2"].replace(
            vocab_size=hf_config.vocab_size, hidden_size=hf_config.n_embd,
            num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
            intermediate_size=getattr(hf_config, "n_inner", None),
            max_position_embeddings=hf_config.n_positions,
            norm_eps=hf_config.layer_norm_epsilon,
        )
    elif mt == "gptj":
        config = PRESETS["gptj"].replace(
            vocab_size=hf_config.vocab_size, hidden_size=hf_config.n_embd,
            num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
            max_position_embeddings=hf_config.n_positions,
            rotary_pct=hf_config.rotary_dim / (hf_config.n_embd // hf_config.n_head),
            norm_eps=hf_config.layer_norm_epsilon,
        )
    elif mt == "gpt_neox":
        config = PRESETS["gpt_neox"].replace(
            vocab_size=hf_config.vocab_size, hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers, num_heads=hf_config.num_attention_heads,
            intermediate_size=hf_config.intermediate_size,
            max_position_embeddings=hf_config.max_position_embeddings,
            rotary_pct=hf_config.rotary_pct, norm_eps=hf_config.layer_norm_eps,
            parallel_residual=hf_config.use_parallel_residual,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        )
    elif mt == "opt":
        config = PRESETS["opt"].replace(
            vocab_size=hf_config.vocab_size, hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers, num_heads=hf_config.num_attention_heads,
            intermediate_size=hf_config.ffn_dim,
            max_position_embeddings=hf_config.max_position_embeddings,
            tie_word_embeddings=hf_config.tie_word_embeddings,
        )
    elif mt == "llama":
        config = PRESETS["llama"].replace(
            vocab_size=hf_config.vocab_size, hidden_size=hf_config.hidden_size,
            num_layers=hf_config.num_hidden_layers, num_heads=hf_config.num_attention_heads,
            num_kv_heads=getattr(hf_config, "num_key_value_heads", None),
            intermediate_size=hf_config.intermediate_size,
            max_position_embeddings=hf_config.max_position_embeddings,
            rope_theta=getattr(hf_config, "rope_theta", 10000.0),
            norm_eps=hf_config.rms_norm_eps,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        )
    elif mt == "bloom":
        config = PRESETS["bloom"].replace(
            vocab_size=hf_config.vocab_size, hidden_size=hf_config.hidden_size,
            num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
            norm_eps=hf_config.layer_norm_epsilon,
            tie_word_embeddings=getattr(hf_config, "tie_word_embeddings", True),
        )
    elif mt == "gpt_bigcode":
        config = PRESETS["gpt_bigcode"].replace(
            vocab_size=hf_config.vocab_size, hidden_size=hf_config.n_embd,
            num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
            num_kv_heads=1 if getattr(hf_config, "multi_query", True) else None,
            intermediate_size=getattr(hf_config, "n_inner", None),
            max_position_embeddings=hf_config.n_positions,
            norm_eps=hf_config.layer_norm_epsilon,
        )
    else:
        raise ValueError(f"Unsupported HF model_type {mt!r}")
    if overrides:
        config = config.replace(**overrides)
    return config
