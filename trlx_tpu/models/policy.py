"""Policy/value/ref model composition for PPO and ILQL.

Parity targets (all in `/root/reference/trlx/models/`):
- ``AutoModelForCausalLMWithValueHead`` (modeling_ppo.py:266-382): trunk + value head.
- ``AutoModelForCausalLMWithHydraValueHead`` (modeling_ppo.py:385-453): adds a frozen
  top-branch reference model run from the branch-point hidden state. In JAX this needs
  NO per-architecture branch classes: the frozen branch is the same ``TransformerLM``
  module applied with a *separate frozen param subtree* via ``method="forward_from"``.
- ``AutoModelForCausalLMWithILQLHeads`` (modeling_ilql.py:262-442): trunk + ILQL heads
  evaluated at gathered state/action positions.
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax.core import unfreeze

from trlx_tpu.methods.ilql import batched_index_select
from trlx_tpu.models.heads import ILQLHeads, ValueHead
from trlx_tpu.models.transformer import KVCache, TransformerConfig, TransformerLM


class CausalLMWithValueHead(nn.Module):
    """Trunk LM + scalar value head. ``branch_layer`` (when set in a call) returns the
    activation entering that layer, for the hydra reference branch.

    ``num_value_layers`` > 0 gives the value function its own trainable *branch* of
    top layers fed from the trunk activation ``num_value_layers`` from the top
    (parity: ``make_value_branch``, modeling_ppo.py:255-263)."""

    config: TransformerConfig
    num_value_layers: int = 0

    def setup(self):
        from trlx_tpu.models.transformer import Block, _norm_module

        if self.num_value_layers > self.config.num_layers:
            raise ValueError(
                f"num_value_layers_unfrozen={self.num_value_layers} exceeds "
                f"num_layers={self.config.num_layers}"
            )
        self.transformer = TransformerLM(self.config)
        self.v_head = ValueHead(self.config)
        if self.num_value_layers > 0:
            self.value_blocks = [Block(self.config) for _ in range(self.num_value_layers)]
            self.value_ln = _norm_module(self.config)

    def _value_branch(self, hidden, attention_mask, positions):
        from trlx_tpu.models.transformer import make_attn_bias

        B, T, _ = hidden.shape
        default_positions, mask_bias = make_attn_bias(self.config, attention_mask, B, T)
        if positions is None:
            positions = default_positions
        x = hidden
        for blk in self.value_blocks:
            x, _ = blk(x, mask_bias, positions, None, attention_mask)
        return self.v_head(self.value_ln(x))

    def __call__(
        self,
        input_ids: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray] = None,
        positions: Optional[jnp.ndarray] = None,
        cache: Optional[KVCache] = None,
        branch_layer: Optional[int] = None,
    ):
        if self.num_value_layers > 0:
            if cache is not None:
                # the trained value fn is value_ln(value_blocks(...)); v_head on the
                # trunk hidden would silently return meaningless numbers
                raise NotImplementedError(
                    "value-branch models do not support cached decode value reads; "
                    "use lm_only for generation"
                )
            value_start = self.config.num_layers - self.num_value_layers
            capture = sorted({value_start, *(() if branch_layer is None else (branch_layer,))})
            logits, hidden, captures, new_cache = self.transformer(
                input_ids, attention_mask, positions, cache, tuple(capture)
            )
            values = self._value_branch(captures[value_start], attention_mask, positions)
            branch_hidden = None if branch_layer is None else captures[branch_layer]
            return logits, values, branch_hidden, new_cache
        logits, hidden, branch_hidden, new_cache = self.transformer(
            input_ids, attention_mask, positions, cache, branch_layer
        )
        values = self.v_head(hidden)
        return logits, values, branch_hidden, new_cache

    def lm_only(
        self,
        input_ids: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray] = None,
        positions: Optional[jnp.ndarray] = None,
        cache: Optional[KVCache] = None,
    ):
        """Forward without the value head (generation decode steps)."""
        logits, _, _, new_cache = self.transformer(input_ids, attention_mask, positions, cache)
        return logits, new_cache

    def forward_branch(
        self,
        hidden: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray],
        positions: Optional[jnp.ndarray],
        start_layer: int,
    ):
        """Frozen-branch forward (hydra): run layers[start_layer:] + head from a
        cached activation. Call with the frozen param subtree."""
        return self.transformer.forward_from(hidden, attention_mask, positions, start_layer)

    def init_cache(self, batch_size: int, max_length: int) -> KVCache:
        return self.transformer_init_cache(batch_size, max_length)

    def transformer_init_cache(self, batch_size: int, max_length: int) -> KVCache:
        # plain helper (not a module method) — cache needs no params
        return TransformerLM(self.config).init_cache(batch_size, max_length)


class CausalLMWithILQLHeads(nn.Module):
    """Trunk LM + ILQL {V, Q, target-Q} heads (parity: modeling_ilql.py:262-442)."""

    config: TransformerConfig
    two_qs: bool = True

    def setup(self):
        self.transformer = TransformerLM(self.config)
        self.ilql_heads = ILQLHeads(self.config, two_qs=self.two_qs)

    def __call__(
        self,
        input_ids: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray] = None,
        positions: Optional[jnp.ndarray] = None,
        actions_ixs: Optional[jnp.ndarray] = None,
        states_ixs: Optional[jnp.ndarray] = None,
        cache: Optional[KVCache] = None,
    ):
        logits, hidden, _, new_cache = self.transformer(
            input_ids, attention_mask, positions, cache
        )
        if states_ixs is not None:
            states_hs = batched_index_select(hidden, states_ixs)
            actions_hs = batched_index_select(hidden, actions_ixs)
        else:
            states_hs = actions_hs = hidden
        qs, target_qs, vs = self.ilql_heads(states_hs, actions_hs)
        return logits, qs, target_qs, vs, new_cache

    def heads_only(self, hidden: jnp.ndarray):
        """Apply the ILQL heads to trunk hidden states [B, T, H] (used by the
        advantage-shaped decode, parity: modeling_ilql.py:325-412)."""
        return self.ilql_heads(hidden, hidden)


def init_value_branch_from_trunk(
    params: Dict[str, Any], config: TransformerConfig, num_value_layers: int
) -> Dict[str, Any]:
    """Copy the (pretrained) top-N trunk layers + final norm into the value-branch
    params (parity with the reference's ModelBranch deepcopy of pretrained blocks,
    modeling_ppo.py:523-533) so the value function starts from trunk features, not
    random init. Leaves are host copies to avoid any buffer aliasing with the
    (donated) trunk params."""
    import numpy as np

    copy_leaf = lambda x: np.array(jax.device_get(x))
    p = dict(params)
    start = config.num_layers - num_value_layers
    for i in range(num_value_layers):
        p[f"value_blocks_{i}"] = jax.tree.map(copy_leaf, params["transformer"][f"layers_{start + i}"])
    if config.final_norm and "ln_f" in params["transformer"]:
        p["value_ln"] = jax.tree.map(copy_leaf, params["transformer"]["ln_f"])
    return p


def branch_param_subtree(trunk_params: Dict[str, Any], start_layer: int, config: TransformerConfig) -> Dict[str, Any]:
    """Extract the frozen reference-branch params: top layers + final norm + output
    head (+ tied embedding). This is the JAX analogue of the reference's
    ``deepcopy`` of unfrozen blocks into ``frozen_head`` (modeling_ppo.py:385-410)."""
    t = unfreeze(trunk_params) if hasattr(trunk_params, "unfreeze") else dict(trunk_params)
    sub: Dict[str, Any] = {}
    for i in range(start_layer, config.num_layers):
        key = f"layers_{i}"
        if key in t:
            sub[key] = jax.tree.map(lambda x: x, t[key])
    if config.final_norm and "ln_f" in t:
        sub["ln_f"] = jax.tree.map(lambda x: x, t["ln_f"])
    if config.tie_word_embeddings:
        sub["embed_tokens"] = jax.tree.map(lambda x: x, t["embed_tokens"])
    elif "lm_head" in t:
        sub["lm_head"] = jax.tree.map(lambda x: x, t["lm_head"])
    return sub


def apply_hydra_branch(
    module: CausalLMWithValueHead,
    branch_params: Dict[str, Any],
    branch_hidden: jnp.ndarray,
    attention_mask: Optional[jnp.ndarray],
    start_layer: int,
    positions: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Reference logits from the frozen branch (parity: ``forward_hydra``)."""
    return module.apply(
        {"params": {"transformer": branch_params}},
        branch_hidden,
        attention_mask,
        positions,
        start_layer,
        method=module.forward_branch,
    )


def t5_branch_param_subtree(t5_params: Dict[str, Any], start_layer: int, config) -> Dict[str, Any]:
    """Frozen decoder-top branch params: decoder blocks [start_layer:], the final
    decoder LN, and the output head (tied embedding or lm_head). The analogue of
    :func:`branch_param_subtree` for the seq2seq hydra reference (reference
    ``T5Branch``, modeling_ppo.py:1483-1593) — ~num_layers_unfrozen decoder
    blocks of extra memory instead of a full frozen T5 copy."""
    t = dict(t5_params)
    sub: Dict[str, Any] = {}
    for i in range(start_layer, config.num_decoder_layers):
        key = f"decoder_blocks_{i}"
        if key in t:
            sub[key] = jax.tree.map(lambda x: x, t[key])
    sub["decoder_ln"] = jax.tree.map(lambda x: x, t["decoder_ln"])
    if config.tie_word_embeddings:
        sub["shared"] = jax.tree.map(lambda x: x, t["shared"])
    elif "lm_head" in t:
        sub["lm_head"] = jax.tree.map(lambda x: x, t["lm_head"])
    return sub


class Seq2SeqLMWithValueHead(nn.Module):
    """T5-style seq2seq LM + scalar value head over decoder hidden states
    (parity: ``AutoModelForSeq2SeqLMWithValueHead``, modeling_ppo.py:1242-1350)."""

    config: "object"  # trlx_tpu.models.t5.T5Config

    def setup(self):
        from trlx_tpu.models.t5 import T5LM
        from trlx_tpu.models.heads import MLPHead

        self.t5 = T5LM(self.config)
        self.v_head_mlp = MLPHead(_t5_head_cfg(self.config), out_dim=1)

    def __call__(self, input_ids, attention_mask, decoder_input_ids, decoder_attention_mask=None):
        logits, hidden, enc = self.t5(input_ids, attention_mask, decoder_input_ids, decoder_attention_mask)
        values = self.v_head_mlp(hidden)[..., 0]
        return logits, values, enc

    def forward_with_branch(
        self, input_ids, attention_mask, decoder_input_ids, decoder_attention_mask, branch_layer
    ):
        """(logits, values, enc, branch_hidden, position_bias) — the scoring
        forward used with the decoder-top hydra reference branch."""
        logits, hidden, enc, branch_hidden, position_bias = self.t5.forward_with_branch(
            input_ids, attention_mask, decoder_input_ids, decoder_attention_mask, branch_layer
        )
        values = self.v_head_mlp(hidden)[..., 0]
        return logits, values, enc, branch_hidden, position_bias

    def encode(self, input_ids, attention_mask):
        return self.t5.encode(input_ids, attention_mask)

    def precompute_cross_kv(self, enc_states):
        return self.t5.precompute_cross_kv(enc_states)

    def decode_step(self, decoder_input_ids, enc_states, encoder_attention_mask,
                    decoder_attention_mask, positions, cache, cross_kvs):
        logits, hidden, new_cache = self.t5.decode(
            decoder_input_ids, enc_states, encoder_attention_mask,
            decoder_attention_mask, positions, cache, cross_kvs,
        )
        return logits, hidden, new_cache


def _t5_head_cfg(t5_config):
    """Adapter so MLPHead (which reads hidden_size etc.) works on T5Config."""
    from trlx_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=t5_config.vocab_size, hidden_size=t5_config.d_model,
        param_dtype=t5_config.param_dtype, compute_dtype=t5_config.compute_dtype,
    )


class Seq2SeqLMWithILQLHeads(nn.Module):
    """T5 + ILQL {V, Q, target-Q} heads over decoder hidden states
    (parity: ``AutoModelForSeq2SeqLMWithILQLHeads``, modeling_ilql.py:481-666)."""

    config: "object"  # trlx_tpu.models.t5.T5Config
    two_qs: bool = True

    def setup(self):
        from trlx_tpu.models.t5 import T5LM

        self.t5 = T5LM(self.config)
        self.ilql_heads = ILQLHeads(_t5_head_cfg(self.config), two_qs=self.two_qs)

    def __call__(
        self,
        input_ids,
        attention_mask,
        decoder_input_ids,
        decoder_attention_mask=None,
        actions_ixs=None,
        states_ixs=None,
    ):
        logits, hidden, _ = self.t5(
            input_ids, attention_mask, decoder_input_ids, decoder_attention_mask
        )
        if states_ixs is not None:
            states_hs = batched_index_select(hidden, states_ixs)
            actions_hs = batched_index_select(hidden, actions_ixs)
        else:
            states_hs = actions_hs = hidden
        qs, target_qs, vs = self.ilql_heads(states_hs, actions_hs)
        return logits, qs, target_qs, vs

    def heads_only(self, hidden):
        return self.ilql_heads(hidden, hidden)

    def encode(self, input_ids, attention_mask):
        return self.t5.encode(input_ids, attention_mask)

    def precompute_cross_kv(self, enc_states):
        return self.t5.precompute_cross_kv(enc_states)

    def decode_step(self, decoder_input_ids, enc_states, encoder_attention_mask,
                    decoder_attention_mask, positions, cache, cross_kvs):
        return self.t5.decode(
            decoder_input_ids, enc_states, encoder_attention_mask,
            decoder_attention_mask, positions, cache, cross_kvs,
        )
