from trlx_tpu.models.transformer import TransformerConfig, TransformerLM
from trlx_tpu.models.policy import (
    CausalLMWithILQLHeads,
    CausalLMWithValueHead,
    apply_hydra_branch,
    branch_param_subtree,
)
from trlx_tpu.models.heads import ILQLHeads, ValueHead, sync_target_q_heads
from trlx_tpu.models.presets import PRESETS, from_hf_config, get_preset
