"""Value and ILQL head modules.

Parity targets: ``make_head`` / value head (`/root/reference/trlx/models/modeling_ppo.py:
245-263` — Linear(n, 2n) → ReLU → Linear(2n, out)), the multi-layer value *branch*
(``make_value_branch``, :255-263), and ``ILQLHeads`` (`modeling_ilql.py:169-227` —
V head + 1–2 Q heads + Polyak-synced target Q heads). Target-Q heads live in the same
param tree under ``target_q_heads`` and are excluded from the optimizer by a trainable
mask; the Polyak sync is a pure function over params (no ZeRO gather dance needed —
params are already global arrays under SPMD).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from trlx_tpu.models.transformer import TransformerConfig


class MLPHead(nn.Module):
    """Two-layer head: hidden -> 2*hidden -> ReLU -> out."""

    config: TransformerConfig
    out_dim: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = self.config
        x = x.astype(c.compute_dtype)
        h = nn.Dense(
            c.hidden_size * 2, dtype=c.compute_dtype, param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(c.initializer_range), name="fc_in",
        )(x)
        h = jax.nn.relu(h)
        return nn.Dense(
            self.out_dim, dtype=jnp.float32, param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(c.initializer_range), name="fc_out",
        )(h)


class ValueHead(nn.Module):
    """Scalar value head returning [B, T] float32 values."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, hidden: jnp.ndarray) -> jnp.ndarray:
        return MLPHead(self.config, out_dim=1, name="value_head")(hidden)[..., 0]


class QHead(nn.Module):
    """Q head over the full vocab: [B, T, H] -> [B, T, V]."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, hidden: jnp.ndarray) -> jnp.ndarray:
        return MLPHead(self.config, out_dim=self.config.vocab_size, name="q_head")(hidden)


class ILQLHeads(nn.Module):
    """V head + (1 or 2) Q heads + matching target Q heads.

    ``__call__(states_hs, actions_hs)`` -> (qs, target_qs, vs): qs/target_qs are
    tuples of [B, A, V] evaluated at action positions; vs is [B, S, 1] at state
    positions (parity: modeling_ilql.py:169-227).
    """

    config: TransformerConfig
    two_qs: bool = True

    def setup(self):
        n = 2 if self.two_qs else 1
        self.q_heads = [MLPHead(self.config, out_dim=self.config.vocab_size) for _ in range(n)]
        self.target_q_heads = [MLPHead(self.config, out_dim=self.config.vocab_size) for _ in range(n)]
        self.v_head = MLPHead(self.config, out_dim=1)

    def __call__(
        self, states_hs: jnp.ndarray, actions_hs: Optional[jnp.ndarray] = None
    ) -> Tuple[Tuple[jnp.ndarray, ...], Tuple[jnp.ndarray, ...], jnp.ndarray]:
        if actions_hs is None:
            actions_hs = states_hs
        qs = tuple(q(actions_hs) for q in self.q_heads)
        target_qs = tuple(
            jax.lax.stop_gradient(q(actions_hs)) for q in self.target_q_heads
        )
        vs = self.v_head(states_hs)
        return qs, target_qs, vs


def sync_target_q_heads(params: dict, alpha: float) -> dict:
    """Polyak-average q_heads into target_q_heads (parity: modeling_ilql.py:216-227):
    ``target = alpha * q + (1 - alpha) * target``. Pure function over the ILQL heads
    param subtree (expects keys ``q_heads_{i}`` / ``target_q_heads_{i}``)."""
    new = dict(params)
    for key in params:
        if key.startswith("q_heads_"):
            tkey = "target_" + key
            new[tkey] = jax.tree.map(
                lambda q, t: alpha * q + (1.0 - alpha) * t, params[key], params[tkey]
            )
    return new
