"""T5-style encoder-decoder LM in Flax (capability parity with the reference's
seq2seq path: `AutoModelForSeq2SeqLMWithValueHead`/`T5Branch`,
`/root/reference/trlx/models/modeling_ppo.py:1242-1593`, and the ILQL seq2seq heads,
`modeling_ilql.py:481-666`).

Architecture: T5 — RMS-style layernorm (no mean subtraction, no bias), relative
position bias in the first self-attention layer of each stack (shared by the rest),
ReLU or gated-GeLU FFN, no biases, tied embeddings with ``d_model**-0.5`` decoder
output scaling (HF `tie_word_embeddings`). Decoder supports a functional KV cache for
jitted incremental decoding; cross-attention K/V are precomputed once at prefill.
"""

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, Optional, Tuple


import jax
import jax.numpy as jnp
from flax import linen as nn


@dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6  # encoder layers
    num_decoder_layers: int = 6
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    feed_forward_proj: str = "relu"  # "relu" | "gated-gelu"
    tie_word_embeddings: bool = True
    initializer_factor: float = 1.0
    decoder_start_token_id: int = 0
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    # LoRA adapters (parity: reference peft support is architecture-agnostic,
    # modeling_base.py:162-240 — T5 must not be excluded). Target names are the
    # T5 projection modules: q/k/v/o (attention) and wi/wi_0/wi_1/wo (FFN) —
    # HF peft's default for T5 is ("q", "v").
    lora_r: int = 0
    lora_alpha: float = 16.0
    lora_targets: Tuple[str, ...] = ("q", "v")
    # int8 decoder self-attention KV cache (see TransformerConfig.kv_cache_quant)
    kv_cache_quant: bool = False

    @property
    def is_gated(self) -> bool:
        return self.feed_forward_proj.startswith("gated")

    def replace(self, **kw) -> "T5Config":
        return replace(self, **kw)


def from_hf_t5_config(hf_config, overrides: Optional[Dict[str, Any]] = None) -> T5Config:
    config = T5Config(
        vocab_size=hf_config.vocab_size, d_model=hf_config.d_model, d_kv=hf_config.d_kv,
        d_ff=hf_config.d_ff, num_layers=hf_config.num_layers,
        num_decoder_layers=hf_config.num_decoder_layers, num_heads=hf_config.num_heads,
        relative_attention_num_buckets=hf_config.relative_attention_num_buckets,
        relative_attention_max_distance=getattr(hf_config, "relative_attention_max_distance", 128),
        layer_norm_epsilon=hf_config.layer_norm_epsilon,
        feed_forward_proj="gated-gelu" if "gated" in hf_config.feed_forward_proj else "relu",
        tie_word_embeddings=hf_config.tie_word_embeddings,
        decoder_start_token_id=hf_config.decoder_start_token_id or 0,
    )
    if overrides:
        config = config.replace(**overrides)
    return config


def relative_position_bucket(relative_position, bidirectional: bool, num_buckets: int, max_distance: int):
    """T5 relative position bucketing (same math as HF)."""
    ret = 0
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5LayerNorm(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x):
        c = self.config
        scale = self.param("scale", nn.initializers.ones, (c.d_model,), c.param_dtype)
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        x = x * jax.lax.rsqrt(var + c.layer_norm_epsilon)
        return (x * scale).astype(c.compute_dtype)


class T5Attention(nn.Module):
    config: T5Config
    has_relative_bias: bool = False
    bidirectional: bool = True

    def setup(self):
        from trlx_tpu.models.transformer import LoraDense

        c = self.config
        inner = c.num_heads * c.d_kv
        # same param layout as nn.Dense; low-rank adapters engage per target name
        dense = lambda feats, name: LoraDense(
            feats, use_bias=False, dtype=c.compute_dtype, param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(c.initializer_factor * (c.d_model**-0.5)),
            r=c.lora_r if name in c.lora_targets else 0, alpha=c.lora_alpha,
        )
        self.q = dense(inner, "q")
        self.k = dense(inner, "k")
        self.v = dense(inner, "v")
        self.o = dense(c.d_model, "o")
        if self.has_relative_bias:
            self.relative_attention_bias = nn.Embed(
                c.relative_attention_num_buckets, c.num_heads,
                dtype=c.compute_dtype, param_dtype=c.param_dtype,
                embedding_init=nn.initializers.normal(c.initializer_factor * (c.d_model**-0.5)),
            )

    def compute_bias(self, q_pos: jnp.ndarray, k_pos: jnp.ndarray) -> jnp.ndarray:
        """[1, H, Tq, Tk] position bias."""
        c = self.config
        rel = k_pos[None, :] - q_pos[:, None]
        buckets = relative_position_bucket(
            rel, self.bidirectional, c.relative_attention_num_buckets,
            c.relative_attention_max_distance,
        )
        values = self.relative_attention_bias(buckets)  # [Tq, Tk, H]
        return values.transpose(2, 0, 1)[None]

    def __call__(
        self,
        x: jnp.ndarray,
        kv: Optional[jnp.ndarray] = None,
        mask_bias: Optional[jnp.ndarray] = None,
        position_bias: Optional[jnp.ndarray] = None,
        cache: Optional[Dict[str, jnp.ndarray]] = None,
        kv_static: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    ):
        """x [B,T,D]; kv = encoder states for cross-attn; cache = self-attn KV cache;
        kv_static = precomputed cross-attn (k, v). T5 does NOT scale scores by
        1/sqrt(d) (folded into init)."""
        c = self.config
        B, T, _ = x.shape
        q = self.q(x).reshape(B, T, c.num_heads, c.d_kv)
        # kh/vh [B, H, S, D] — the cache layout (contiguous per-(b,h) along S,
        # see TransformerLM.Attention: avoids a full-cache transposed copy per
        # decode step)
        if kv_static is not None:
            kh, vh = kv_static  # already [B, H, S, D] (cross_kv)
            new_cache = None
        else:
            src = x if kv is None else kv
            S = src.shape[1]
            k = self.k(src).reshape(B, S, c.num_heads, c.d_kv)
            v = self.v(src).reshape(B, S, c.num_heads, c.d_kv)
            kh = k.transpose(0, 2, 1, 3)
            vh = v.transpose(0, 2, 1, 3)
            if cache is not None:
                from trlx_tpu.models.transformer import read_kv_cache, write_kv_cache

                new_cache = write_kv_cache(cache, kh, vh, cache["index"])
                kh, vh = read_kv_cache(new_cache, c.compute_dtype)
            else:
                new_cache = None
        scores = jnp.einsum("bthd,bhsd->bhts", q, kh).astype(jnp.float32)
        if position_bias is not None:
            scores = scores + position_bias.astype(jnp.float32)
        if mask_bias is not None:
            scores = scores + mask_bias
        probs = jax.nn.softmax(scores, axis=-1).astype(c.compute_dtype)
        out = jnp.einsum("bhts,bhsd->bthd", probs, vh).reshape(B, T, c.num_heads * c.d_kv)
        return self.o(out), new_cache


class T5FFN(nn.Module):
    config: T5Config

    @nn.compact
    def __call__(self, x):
        from trlx_tpu.models.transformer import LoraDense

        c = self.config
        dense = lambda feats, name: LoraDense(
            feats, use_bias=False, dtype=c.compute_dtype, param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(c.initializer_factor * (c.d_model**-0.5)), name=name,
            r=c.lora_r if name in c.lora_targets else 0, alpha=c.lora_alpha,
        )
        if c.is_gated:
            h = jax.nn.gelu(dense(c.d_ff, "wi_0")(x), approximate=True) * dense(c.d_ff, "wi_1")(x)
        else:
            h = jax.nn.relu(dense(c.d_ff, "wi")(x))
        return dense(c.d_model, "wo")(h)


class T5EncoderBlock(nn.Module):
    config: T5Config
    has_relative_bias: bool = False

    def setup(self):
        self.ln_1 = T5LayerNorm(self.config)
        self.attn = T5Attention(self.config, self.has_relative_bias, bidirectional=True)
        self.ln_2 = T5LayerNorm(self.config)
        self.mlp = T5FFN(self.config)

    def __call__(self, x, mask_bias, position_bias):
        a, _ = self.attn(self.ln_1(x), None, mask_bias, position_bias)
        x = x + a
        x = x + self.mlp(self.ln_2(x))
        return x


class T5DecoderBlock(nn.Module):
    config: T5Config
    has_relative_bias: bool = False

    def setup(self):
        self.ln_1 = T5LayerNorm(self.config)
        self.self_attn = T5Attention(self.config, self.has_relative_bias, bidirectional=False)
        self.ln_cross = T5LayerNorm(self.config)
        self.cross_attn = T5Attention(self.config, False, bidirectional=True)
        self.ln_2 = T5LayerNorm(self.config)
        self.mlp = T5FFN(self.config)

    def __call__(self, x, self_mask_bias, position_bias, enc_states, cross_mask_bias, cache=None, cross_kv=None):
        a, new_cache = self.self_attn(self.ln_1(x), None, self_mask_bias, position_bias, cache)
        x = x + a
        kv_arg = None if cross_kv is not None else enc_states
        ca, _ = self.cross_attn(self.ln_cross(x), kv_arg, cross_mask_bias, None, None, cross_kv)
        x = x + ca
        x = x + self.mlp(self.ln_2(x))
        return x, new_cache

    def cross_kv(self, enc_states):
        """Precompute cross-attention K/V from encoder states (prefill).
        Returned in the [B, H, S, D] attention layout."""
        c = self.config
        B, S, _ = enc_states.shape
        k = self.cross_attn.k(enc_states).reshape(B, S, c.num_heads, c.d_kv)
        v = self.cross_attn.v(enc_states).reshape(B, S, c.num_heads, c.d_kv)
        return k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3)


class T5LM(nn.Module):
    """Encoder-decoder LM; methods: encode / decode / __call__ (full seq2seq fwd)."""

    config: T5Config

    def setup(self):
        c = self.config
        self.shared = nn.Embed(
            c.vocab_size, c.d_model, dtype=c.compute_dtype, param_dtype=c.param_dtype,
            embedding_init=nn.initializers.normal(c.initializer_factor),
        )
        self.encoder_blocks = [
            T5EncoderBlock(c, has_relative_bias=(i == 0)) for i in range(c.num_layers)
        ]
        self.encoder_ln = T5LayerNorm(c)
        self.decoder_blocks = [
            T5DecoderBlock(c, has_relative_bias=(i == 0)) for i in range(c.num_decoder_layers)
        ]
        self.decoder_ln = T5LayerNorm(c)
        if not c.tie_word_embeddings:
            self.lm_head = nn.Dense(
                c.vocab_size, use_bias=False, dtype=c.compute_dtype, param_dtype=c.param_dtype,
                kernel_init=nn.initializers.normal(c.initializer_factor),
            )

    def encode(self, input_ids: jnp.ndarray, attention_mask: Optional[jnp.ndarray] = None):
        B, S = input_ids.shape
        x = self.shared(input_ids)
        mask_bias = None
        if attention_mask is not None:
            mask_bias = jnp.where(attention_mask[:, None, None, :].astype(bool), 0.0, -1e9).astype(jnp.float32)
        pos = jnp.arange(S)
        position_bias = self.encoder_blocks[0].attn.compute_bias(pos, pos)
        for block in self.encoder_blocks:
            x = block(x, mask_bias, position_bias)
        return self.encoder_ln(x)

    def _decoder_stack(
        self, x, self_mask_bias, position_bias, enc_states, cross_mask_bias, cache, cross_kvs,
        branch_layer=None,
    ):
        new_caches = []
        branch_hidden = None
        for i, block in enumerate(self.decoder_blocks):
            if branch_layer is not None and i == branch_layer:
                branch_hidden = x
            layer_cache = None
            if cache is not None:
                layer_cache = {key: cache[key][i] for key in cache if key != "index"}
                layer_cache["index"] = cache["index"]
            ckv = None if cross_kvs is None else (cross_kvs[0][i], cross_kvs[1][i])
            x, new_lc = block(x, self_mask_bias, position_bias, enc_states, cross_mask_bias, layer_cache, ckv)
            if cache is not None:
                new_caches.append(new_lc)
        hidden = self.decoder_ln(x)
        new_cache = None
        if cache is not None:
            # per-layer list layout (see TransformerLM.init_cache): restacking
            # would copy the whole cache every decode step
            new_cache = {
                key: [lc[key] for lc in new_caches] for key in new_caches[0]
            }
            new_cache["index"] = cache["index"] + x.shape[1]
        return hidden, new_cache, branch_hidden

    def _head(self, hidden):
        c = self.config
        if c.tie_word_embeddings:
            hidden = hidden * (c.d_model**-0.5)
            return hidden @ self.shared.embedding.astype(c.compute_dtype).T
        return self.lm_head(hidden)

    def _self_bias_nocache(self, T, decoder_attention_mask):
        """Cache-free causal self-attention bias [*,1,T,T]."""
        causal = jnp.tril(jnp.ones((T, T), dtype=bool))[None, None]
        if decoder_attention_mask is not None:
            causal = jnp.logical_and(causal, decoder_attention_mask[:, None, None, :].astype(bool))
        return jnp.where(causal, 0.0, -1e9).astype(jnp.float32)

    def _cross_bias(self, encoder_attention_mask):
        if encoder_attention_mask is None:
            return None
        return jnp.where(
            encoder_attention_mask[:, None, None, :].astype(bool), 0.0, -1e9
        ).astype(jnp.float32)

    def decode(
        self,
        decoder_input_ids: jnp.ndarray,
        enc_states: jnp.ndarray,
        encoder_attention_mask: Optional[jnp.ndarray] = None,
        decoder_attention_mask: Optional[jnp.ndarray] = None,
        positions: Optional[jnp.ndarray] = None,
        cache: Optional[Dict[str, Any]] = None,
        cross_kvs: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    ):
        """Returns (logits, hidden, new_cache). With ``cache``, T may be 1 and
        ``positions`` gives absolute decoder positions for the relative bias."""
        B, T = decoder_input_ids.shape
        x = self.shared(decoder_input_ids)

        if cache is not None:
            S = cache["k"][0].shape[2]  # per-layer [B,H,S,D]
            idx = cache["index"]
            if positions is None:
                positions = idx + jnp.arange(T, dtype=jnp.int32)
            else:
                positions = positions.reshape(-1)[:T] if positions.ndim > 1 else positions
            kv_slot = jnp.arange(S)[None, None, None, :]
            q_slot = (idx + jnp.arange(T, dtype=jnp.int32))[None, None, :, None]
            causal = kv_slot <= q_slot
            if decoder_attention_mask is not None:
                causal = jnp.logical_and(causal, decoder_attention_mask[:, None, None, :].astype(bool))
            self_mask_bias = jnp.where(causal, 0.0, -1e9).astype(jnp.float32)
            k_pos = jnp.arange(S)
            position_bias = self.decoder_blocks[0].self_attn.compute_bias(positions, k_pos)
        else:
            self_mask_bias = self._self_bias_nocache(T, decoder_attention_mask)
            pos = jnp.arange(T)
            position_bias = self.decoder_blocks[0].self_attn.compute_bias(pos, pos)

        cross_mask_bias = self._cross_bias(encoder_attention_mask)

        hidden, new_cache, _ = self._decoder_stack(
            x, self_mask_bias, position_bias, enc_states, cross_mask_bias, cache, cross_kvs
        )
        return self._head(hidden), hidden, new_cache

    def __call__(
        self,
        input_ids: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray] = None,
        decoder_input_ids: Optional[jnp.ndarray] = None,
        decoder_attention_mask: Optional[jnp.ndarray] = None,
    ):
        """Full seq2seq forward: (logits, decoder_hidden, encoder_states)."""
        enc = self.encode(input_ids, attention_mask)
        logits, hidden, _ = self.decode(
            decoder_input_ids, enc, attention_mask, decoder_attention_mask
        )
        return logits, hidden, enc

    def forward_with_branch(
        self,
        input_ids: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray],
        decoder_input_ids: jnp.ndarray,
        decoder_attention_mask: Optional[jnp.ndarray],
        branch_layer: int,
    ):
        """Full forward that also captures the hydra branch point: returns
        (logits, decoder_hidden, encoder_states, branch_hidden, position_bias).
        ``branch_hidden`` is the input activation of decoder block
        ``branch_layer``; ``position_bias`` is the (frozen-by-construction)
        relative bias the branch re-uses."""
        enc = self.encode(input_ids, attention_mask)
        B, T = decoder_input_ids.shape
        x = self.shared(decoder_input_ids)
        self_mask_bias = self._self_bias_nocache(T, decoder_attention_mask)
        pos = jnp.arange(T)
        position_bias = self.decoder_blocks[0].self_attn.compute_bias(pos, pos)
        cross_mask_bias = self._cross_bias(attention_mask)
        hidden, _, branch_hidden = self._decoder_stack(
            x, self_mask_bias, position_bias, enc, cross_mask_bias, None, None,
            branch_layer=branch_layer,
        )
        return self._head(hidden), hidden, enc, branch_hidden, position_bias

    def forward_branch(
        self,
        branch_hidden: jnp.ndarray,
        enc_states: jnp.ndarray,
        encoder_attention_mask: Optional[jnp.ndarray],
        decoder_attention_mask: Optional[jnp.ndarray],
        position_bias: jnp.ndarray,
        start_layer: int,
    ):
        """Frozen decoder-top branch: run decoder blocks [start_layer:] + final LN
        + head from a captured branch activation (the reference's ``T5Branch``,
        modeling_ppo.py:1483-1593 — a decoder-top reference model instead of a
        full frozen T5 copy). Apply with the frozen param subtree from
        :func:`trlx_tpu.models.policy.t5_branch_param_subtree`; encoder states
        and position_bias come from the live model, whose encoder / bottom
        decoder blocks are frozen by the train mask, so they equal the reference
        model's."""
        B, T, _ = branch_hidden.shape
        self_mask_bias = self._self_bias_nocache(T, decoder_attention_mask)
        cross_mask_bias = self._cross_bias(encoder_attention_mask)
        x = branch_hidden
        for block in self.decoder_blocks[start_layer:]:
            x, _ = block(x, self_mask_bias, position_bias, enc_states, cross_mask_bias, None, None)
        return self._head(self.decoder_ln(x))

    def precompute_cross_kv(self, enc_states):
        # per-layer lists (not stacked arrays): slicing layer i from a stacked
        # [L, ...] array inside the decode loop copies the whole thing per step
        ks, vs = [], []
        for block in self.decoder_blocks:
            k, v = block.cross_kv(enc_states)
            ks.append(k)
            vs.append(v)
        return ks, vs

    def init_cache(self, batch_size: int, max_length: int, dtype=None) -> Dict[str, Any]:
        c = self.config
        dtype = dtype or c.compute_dtype
        # per-layer list layout: in-place single-token writes in the decode loop
        # (a stacked [L, ...] array forces full-cache slice/restack copies per step)
        from trlx_tpu.models.transformer import kv_cache_layout

        shape = (batch_size, c.num_heads, max_length, c.d_kv)
        per_layer = kv_cache_layout(shape, dtype, c.kv_cache_quant)
        out = {
            key: [jnp.zeros(shp, dt) for _ in range(c.num_decoder_layers)]
            for key, (shp, dt) in per_layer.items()
        }
        out["index"] = jnp.array(0, jnp.int32)
        return out
