"""HF checkpoint interop: load torch checkpoints into TransformerLM params and export
back (parity: ``PreTrainedModelWrapper.from_pretrained/save_pretrained`` incl. sharded
checkpoint merging, `/root/reference/trlx/models/modeling_base.py:44-374`).

Conversion is per model family (gpt2 / gptj / gpt_neox / opt / llama). All conversions
are bidirectional so ``save_pretrained_hf`` can export an HF-loadable directory, and a
roundtrip test validates both directions without network access by instantiating tiny
random HF torch models from config.

Offline behavior: when ``model_path`` is not a local directory with weights, we fall
back to a family preset with random init (tests/benchmarks in a zero-egress sandbox).
"""

import json
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from trlx_tpu.models.presets import from_hf_config, get_preset
from trlx_tpu.models.transformer import TransformerConfig, TransformerLM
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


# --------------------------------------------------------------------------- io


def load_torch_state_dict(model_dir: str) -> Dict[str, np.ndarray]:
    """Load (possibly sharded) torch weights from a local HF model dir into numpy."""
    out: Dict[str, np.ndarray] = {}

    def _load_safetensors(path):
        from safetensors import safe_open

        with safe_open(path, framework="np") as f:
            for k in f.keys():
                out[k] = f.get_tensor(k)

    def _load_bin(path):
        import torch

        sd = torch.load(path, map_location="cpu", weights_only=True)
        for k, v in sd.items():
            out[k] = v.float().numpy() if v.dtype in (torch.bfloat16, torch.float16) else v.numpy()

    for index_name, loader in (
        ("model.safetensors.index.json", _load_safetensors),
        ("pytorch_model.bin.index.json", _load_bin),
    ):
        index_path = os.path.join(model_dir, index_name)
        if os.path.exists(index_path):
            with open(index_path) as f:
                index = json.load(f)
            for shard in sorted(set(index["weight_map"].values())):
                loader(os.path.join(model_dir, shard))
            return out
    for name, loader in (("model.safetensors", _load_safetensors), ("pytorch_model.bin", _load_bin)):
        path = os.path.join(model_dir, name)
        if os.path.exists(path):
            loader(path)
            return out
    raise FileNotFoundError(f"No weights found in {model_dir}")


# ------------------------------------------------------------------ conversions

# Each family: (hf_to_params, params_to_hf). Params trees are plain nested dicts of
# numpy arrays with TransformerLM naming.


def _ln(sd, prefix):
    d = {"scale": sd[f"{prefix}.weight"]}
    if f"{prefix}.bias" in sd:
        d["bias"] = sd[f"{prefix}.bias"]
    return d


def _linear(sd, prefix, transpose=True):
    d = {"kernel": sd[f"{prefix}.weight"].T if transpose else sd[f"{prefix}.weight"]}
    if f"{prefix}.bias" in sd:
        d["bias"] = sd[f"{prefix}.bias"]
    return d


def _gpt2_to_params(sd: Dict[str, np.ndarray], c: TransformerConfig) -> Dict[str, Any]:
    p: Dict[str, Any] = {
        "embed_tokens": {"embedding": sd["transformer.wte.weight"]},
        "embed_positions": {"embedding": sd["transformer.wpe.weight"]},
        "ln_f": _ln(sd, "transformer.ln_f"),
    }
    H = c.hidden_size
    for i in range(c.num_layers):
        pre = f"transformer.h.{i}"
        # HF Conv1D stores [in, out] — no transpose
        ck = sd[f"{pre}.attn.c_attn.weight"]
        cb = sd[f"{pre}.attn.c_attn.bias"]
        p[f"layers_{i}"] = {
            "ln_1": _ln(sd, f"{pre}.ln_1"),
            "ln_2": _ln(sd, f"{pre}.ln_2"),
            "attn": {
                "q_proj": {"kernel": ck[:, :H], "bias": cb[:H]},
                "k_proj": {"kernel": ck[:, H : 2 * H], "bias": cb[H : 2 * H]},
                "v_proj": {"kernel": ck[:, 2 * H :], "bias": cb[2 * H :]},
                "o_proj": _linear(sd, f"{pre}.attn.c_proj", transpose=False),
            },
            "mlp": {
                "up_proj": _linear(sd, f"{pre}.mlp.c_fc", transpose=False),
                "down_proj": _linear(sd, f"{pre}.mlp.c_proj", transpose=False),
            },
        }
    return p


def _gpt2_from_params(p: Dict[str, Any], c: TransformerConfig) -> Dict[str, np.ndarray]:
    sd = {
        "transformer.wte.weight": p["embed_tokens"]["embedding"],
        "transformer.wpe.weight": p["embed_positions"]["embedding"],
        "transformer.ln_f.weight": p["ln_f"]["scale"],
        "transformer.ln_f.bias": p["ln_f"]["bias"],
    }
    for i in range(c.num_layers):
        L = p[f"layers_{i}"]
        pre = f"transformer.h.{i}"
        sd[f"{pre}.ln_1.weight"] = L["ln_1"]["scale"]
        sd[f"{pre}.ln_1.bias"] = L["ln_1"]["bias"]
        sd[f"{pre}.ln_2.weight"] = L["ln_2"]["scale"]
        sd[f"{pre}.ln_2.bias"] = L["ln_2"]["bias"]
        sd[f"{pre}.attn.c_attn.weight"] = np.concatenate(
            [L["attn"][k]["kernel"] for k in ("q_proj", "k_proj", "v_proj")], axis=1
        )
        sd[f"{pre}.attn.c_attn.bias"] = np.concatenate(
            [L["attn"][k]["bias"] for k in ("q_proj", "k_proj", "v_proj")]
        )
        sd[f"{pre}.attn.c_proj.weight"] = L["attn"]["o_proj"]["kernel"]
        sd[f"{pre}.attn.c_proj.bias"] = L["attn"]["o_proj"]["bias"]
        sd[f"{pre}.mlp.c_fc.weight"] = L["mlp"]["up_proj"]["kernel"]
        sd[f"{pre}.mlp.c_fc.bias"] = L["mlp"]["up_proj"]["bias"]
        sd[f"{pre}.mlp.c_proj.weight"] = L["mlp"]["down_proj"]["kernel"]
        sd[f"{pre}.mlp.c_proj.bias"] = L["mlp"]["down_proj"]["bias"]
    return sd


def _llama_to_params(sd, c):
    p = {
        "embed_tokens": {"embedding": sd["model.embed_tokens.weight"]},
        "ln_f": {"scale": sd["model.norm.weight"]},
    }
    if not c.tie_word_embeddings:
        p["lm_head"] = _linear(sd, "lm_head")
    for i in range(c.num_layers):
        pre = f"model.layers.{i}"
        p[f"layers_{i}"] = {
            "ln_1": {"scale": sd[f"{pre}.input_layernorm.weight"]},
            "ln_2": {"scale": sd[f"{pre}.post_attention_layernorm.weight"]},
            "attn": {
                "q_proj": _linear(sd, f"{pre}.self_attn.q_proj"),
                "k_proj": _linear(sd, f"{pre}.self_attn.k_proj"),
                "v_proj": _linear(sd, f"{pre}.self_attn.v_proj"),
                "o_proj": _linear(sd, f"{pre}.self_attn.o_proj"),
            },
            "mlp": {
                "gate_proj": _linear(sd, f"{pre}.mlp.gate_proj"),
                "up_proj": _linear(sd, f"{pre}.mlp.up_proj"),
                "down_proj": _linear(sd, f"{pre}.mlp.down_proj"),
            },
        }
    return p


def _llama_from_params(p, c):
    sd = {
        "model.embed_tokens.weight": p["embed_tokens"]["embedding"],
        "model.norm.weight": p["ln_f"]["scale"],
    }
    if "lm_head" in p:
        sd["lm_head.weight"] = p["lm_head"]["kernel"].T
    for i in range(c.num_layers):
        L = p[f"layers_{i}"]
        pre = f"model.layers.{i}"
        sd[f"{pre}.input_layernorm.weight"] = L["ln_1"]["scale"]
        sd[f"{pre}.post_attention_layernorm.weight"] = L["ln_2"]["scale"]
        for name in ("q_proj", "k_proj", "v_proj", "o_proj"):
            sd[f"{pre}.self_attn.{name}.weight"] = L["attn"][name]["kernel"].T
        for name in ("gate_proj", "up_proj", "down_proj"):
            sd[f"{pre}.mlp.{name}.weight"] = L["mlp"][name]["kernel"].T
    return sd


def _neox_to_params(sd, c):
    p = {
        "embed_tokens": {"embedding": sd["gpt_neox.embed_in.weight"]},
        "ln_f": _ln(sd, "gpt_neox.final_layer_norm"),
        "lm_head": _linear(sd, "embed_out"),
    }
    heads, hd, H = c.num_heads, c.dim_per_head, c.hidden_size
    for i in range(c.num_layers):
        pre = f"gpt_neox.layers.{i}"
        qkv_w = sd[f"{pre}.attention.query_key_value.weight"]  # [3H, H], per-head interleave
        qkv_b = sd[f"{pre}.attention.query_key_value.bias"]
        w = qkv_w.reshape(heads, 3, hd, H)
        b = qkv_b.reshape(heads, 3, hd)
        mk_w = lambda j: w[:, j].reshape(heads * hd, H).T  # -> [H, H] kernel
        mk_b = lambda j: b[:, j].reshape(heads * hd)
        p[f"layers_{i}"] = {
            "ln_1": _ln(sd, f"{pre}.input_layernorm"),
            "ln_2": _ln(sd, f"{pre}.post_attention_layernorm"),
            "attn": {
                "q_proj": {"kernel": mk_w(0), "bias": mk_b(0)},
                "k_proj": {"kernel": mk_w(1), "bias": mk_b(1)},
                "v_proj": {"kernel": mk_w(2), "bias": mk_b(2)},
                "o_proj": _linear(sd, f"{pre}.attention.dense"),
            },
            "mlp": {
                "up_proj": _linear(sd, f"{pre}.mlp.dense_h_to_4h"),
                "down_proj": _linear(sd, f"{pre}.mlp.dense_4h_to_h"),
            },
        }
    return p


def _neox_from_params(p, c):
    sd = {
        "gpt_neox.embed_in.weight": p["embed_tokens"]["embedding"],
        "gpt_neox.final_layer_norm.weight": p["ln_f"]["scale"],
        "gpt_neox.final_layer_norm.bias": p["ln_f"]["bias"],
        "embed_out.weight": p["lm_head"]["kernel"].T,
    }
    heads, hd, H = c.num_heads, c.dim_per_head, c.hidden_size
    for i in range(c.num_layers):
        L = p[f"layers_{i}"]
        pre = f"gpt_neox.layers.{i}"
        sd[f"{pre}.input_layernorm.weight"] = L["ln_1"]["scale"]
        sd[f"{pre}.input_layernorm.bias"] = L["ln_1"]["bias"]
        sd[f"{pre}.post_attention_layernorm.weight"] = L["ln_2"]["scale"]
        sd[f"{pre}.post_attention_layernorm.bias"] = L["ln_2"]["bias"]
        ws = [L["attn"][k]["kernel"].T.reshape(heads, hd, H) for k in ("q_proj", "k_proj", "v_proj")]
        bs = [L["attn"][k]["bias"].reshape(heads, hd) for k in ("q_proj", "k_proj", "v_proj")]
        sd[f"{pre}.attention.query_key_value.weight"] = np.stack(ws, axis=1).reshape(3 * H, H)
        sd[f"{pre}.attention.query_key_value.bias"] = np.stack(bs, axis=1).reshape(3 * H)
        sd[f"{pre}.attention.dense.weight"] = L["attn"]["o_proj"]["kernel"].T
        sd[f"{pre}.attention.dense.bias"] = L["attn"]["o_proj"]["bias"]
        sd[f"{pre}.mlp.dense_h_to_4h.weight"] = L["mlp"]["up_proj"]["kernel"].T
        sd[f"{pre}.mlp.dense_h_to_4h.bias"] = L["mlp"]["up_proj"]["bias"]
        sd[f"{pre}.mlp.dense_4h_to_h.weight"] = L["mlp"]["down_proj"]["kernel"].T
        sd[f"{pre}.mlp.dense_4h_to_h.bias"] = L["mlp"]["down_proj"]["bias"]
    return sd


def _gptj_to_params(sd, c):
    p = {
        "embed_tokens": {"embedding": sd["transformer.wte.weight"]},
        "ln_f": _ln(sd, "transformer.ln_f"),
        "lm_head": _linear(sd, "lm_head"),
    }
    for i in range(c.num_layers):
        pre = f"transformer.h.{i}"
        p[f"layers_{i}"] = {
            "ln_1": _ln(sd, f"{pre}.ln_1"),
            "attn": {
                "q_proj": _linear(sd, f"{pre}.attn.q_proj"),
                "k_proj": _linear(sd, f"{pre}.attn.k_proj"),
                "v_proj": _linear(sd, f"{pre}.attn.v_proj"),
                "o_proj": _linear(sd, f"{pre}.attn.out_proj"),
            },
            "mlp": {
                "up_proj": _linear(sd, f"{pre}.mlp.fc_in"),
                "down_proj": _linear(sd, f"{pre}.mlp.fc_out"),
            },
        }
    return p


def _gptj_from_params(p, c):
    sd = {
        "transformer.wte.weight": p["embed_tokens"]["embedding"],
        "transformer.ln_f.weight": p["ln_f"]["scale"],
        "transformer.ln_f.bias": p["ln_f"]["bias"],
        "lm_head.weight": p["lm_head"]["kernel"].T,
    }
    if "bias" in p["lm_head"]:
        sd["lm_head.bias"] = p["lm_head"]["bias"]
    for i in range(c.num_layers):
        L = p[f"layers_{i}"]
        pre = f"transformer.h.{i}"
        sd[f"{pre}.ln_1.weight"] = L["ln_1"]["scale"]
        sd[f"{pre}.ln_1.bias"] = L["ln_1"]["bias"]
        for ours, theirs in (("q_proj", "q_proj"), ("k_proj", "k_proj"), ("v_proj", "v_proj"), ("o_proj", "out_proj")):
            sd[f"{pre}.attn.{theirs}.weight"] = L["attn"][ours]["kernel"].T
        sd[f"{pre}.mlp.fc_in.weight"] = L["mlp"]["up_proj"]["kernel"].T
        sd[f"{pre}.mlp.fc_in.bias"] = L["mlp"]["up_proj"]["bias"]
        sd[f"{pre}.mlp.fc_out.weight"] = L["mlp"]["down_proj"]["kernel"].T
        sd[f"{pre}.mlp.fc_out.bias"] = L["mlp"]["down_proj"]["bias"]
    return sd


def _opt_to_params(sd, c):
    prefix = "model.decoder" if "model.decoder.embed_tokens.weight" in sd else "decoder"
    p = {
        "embed_tokens": {"embedding": sd[f"{prefix}.embed_tokens.weight"]},
        "embed_positions": {"embedding": sd[f"{prefix}.embed_positions.weight"]},
    }
    if f"{prefix}.final_layer_norm.weight" in sd:
        p["ln_f"] = _ln(sd, f"{prefix}.final_layer_norm")
    for i in range(c.num_layers):
        pre = f"{prefix}.layers.{i}"
        p[f"layers_{i}"] = {
            "ln_1": _ln(sd, f"{pre}.self_attn_layer_norm"),
            "ln_2": _ln(sd, f"{pre}.final_layer_norm"),
            "attn": {
                "q_proj": _linear(sd, f"{pre}.self_attn.q_proj"),
                "k_proj": _linear(sd, f"{pre}.self_attn.k_proj"),
                "v_proj": _linear(sd, f"{pre}.self_attn.v_proj"),
                "o_proj": _linear(sd, f"{pre}.self_attn.out_proj"),
            },
            "mlp": {
                "up_proj": _linear(sd, f"{pre}.fc1"),
                "down_proj": _linear(sd, f"{pre}.fc2"),
            },
        }
    return p


def _opt_from_params(p, c):
    prefix = "model.decoder"
    sd = {
        f"{prefix}.embed_tokens.weight": p["embed_tokens"]["embedding"],
        f"{prefix}.embed_positions.weight": p["embed_positions"]["embedding"],
    }
    if "ln_f" in p:
        sd[f"{prefix}.final_layer_norm.weight"] = p["ln_f"]["scale"]
        sd[f"{prefix}.final_layer_norm.bias"] = p["ln_f"]["bias"]
    for i in range(c.num_layers):
        L = p[f"layers_{i}"]
        pre = f"{prefix}.layers.{i}"
        sd[f"{pre}.self_attn_layer_norm.weight"] = L["ln_1"]["scale"]
        sd[f"{pre}.self_attn_layer_norm.bias"] = L["ln_1"]["bias"]
        sd[f"{pre}.final_layer_norm.weight"] = L["ln_2"]["scale"]
        sd[f"{pre}.final_layer_norm.bias"] = L["ln_2"]["bias"]
        for ours, theirs in (("q_proj", "q_proj"), ("k_proj", "k_proj"), ("v_proj", "v_proj"), ("o_proj", "out_proj")):
            sd[f"{pre}.self_attn.{theirs}.weight"] = L["attn"][ours]["kernel"].T
            sd[f"{pre}.self_attn.{theirs}.bias"] = L["attn"][ours]["bias"]
        sd[f"{pre}.fc1.weight"] = L["mlp"]["up_proj"]["kernel"].T
        sd[f"{pre}.fc1.bias"] = L["mlp"]["up_proj"]["bias"]
        sd[f"{pre}.fc2.weight"] = L["mlp"]["down_proj"]["kernel"].T
        sd[f"{pre}.fc2.bias"] = L["mlp"]["down_proj"]["bias"]
    return sd


def _bloom_to_params(sd, c):
    """Bloom: ALiBi, embedding LN, per-head-interleaved fused qkv (like neox)."""
    pre0 = "transformer." if "transformer.word_embeddings.weight" in sd else ""
    p = {
        "embed_tokens": {"embedding": sd[f"{pre0}word_embeddings.weight"]},
        "embed_layernorm": _ln(sd, f"{pre0}word_embeddings_layernorm"),
        "ln_f": _ln(sd, f"{pre0}ln_f"),
    }
    heads, hd, H = c.num_heads, c.dim_per_head, c.hidden_size
    for i in range(c.num_layers):
        pre = f"{pre0}h.{i}"
        qkv_w = sd[f"{pre}.self_attention.query_key_value.weight"]  # [3H, H]
        qkv_b = sd[f"{pre}.self_attention.query_key_value.bias"]
        w = qkv_w.reshape(heads, 3, hd, H)
        b = qkv_b.reshape(heads, 3, hd)
        mk_w = lambda j: w[:, j].reshape(heads * hd, H).T
        mk_b = lambda j: b[:, j].reshape(heads * hd)
        p[f"layers_{i}"] = {
            "ln_1": _ln(sd, f"{pre}.input_layernorm"),
            "ln_2": _ln(sd, f"{pre}.post_attention_layernorm"),
            "attn": {
                "q_proj": {"kernel": mk_w(0), "bias": mk_b(0)},
                "k_proj": {"kernel": mk_w(1), "bias": mk_b(1)},
                "v_proj": {"kernel": mk_w(2), "bias": mk_b(2)},
                "o_proj": _linear(sd, f"{pre}.self_attention.dense"),
            },
            "mlp": {
                "up_proj": _linear(sd, f"{pre}.mlp.dense_h_to_4h"),
                "down_proj": _linear(sd, f"{pre}.mlp.dense_4h_to_h"),
            },
        }
    return p


def _bloom_from_params(p, c):
    sd = {
        "transformer.word_embeddings.weight": p["embed_tokens"]["embedding"],
        "transformer.word_embeddings_layernorm.weight": p["embed_layernorm"]["scale"],
        "transformer.word_embeddings_layernorm.bias": p["embed_layernorm"]["bias"],
        "transformer.ln_f.weight": p["ln_f"]["scale"],
        "transformer.ln_f.bias": p["ln_f"]["bias"],
    }
    heads, hd, H = c.num_heads, c.dim_per_head, c.hidden_size
    for i in range(c.num_layers):
        L = p[f"layers_{i}"]
        pre = f"transformer.h.{i}"
        sd[f"{pre}.input_layernorm.weight"] = L["ln_1"]["scale"]
        sd[f"{pre}.input_layernorm.bias"] = L["ln_1"]["bias"]
        sd[f"{pre}.post_attention_layernorm.weight"] = L["ln_2"]["scale"]
        sd[f"{pre}.post_attention_layernorm.bias"] = L["ln_2"]["bias"]
        ws = [L["attn"][k]["kernel"].T.reshape(heads, hd, H) for k in ("q_proj", "k_proj", "v_proj")]
        bs = [L["attn"][k]["bias"].reshape(heads, hd) for k in ("q_proj", "k_proj", "v_proj")]
        sd[f"{pre}.self_attention.query_key_value.weight"] = np.stack(ws, axis=1).reshape(3 * H, H)
        sd[f"{pre}.self_attention.query_key_value.bias"] = np.stack(bs, axis=1).reshape(3 * H)
        sd[f"{pre}.self_attention.dense.weight"] = L["attn"]["o_proj"]["kernel"].T
        sd[f"{pre}.self_attention.dense.bias"] = L["attn"]["o_proj"]["bias"]
        sd[f"{pre}.mlp.dense_h_to_4h.weight"] = L["mlp"]["up_proj"]["kernel"].T
        sd[f"{pre}.mlp.dense_h_to_4h.bias"] = L["mlp"]["up_proj"]["bias"]
        sd[f"{pre}.mlp.dense_4h_to_h.weight"] = L["mlp"]["down_proj"]["kernel"].T
        sd[f"{pre}.mlp.dense_4h_to_h.bias"] = L["mlp"]["down_proj"]["bias"]
    return sd


def _bigcode_to_params(sd, c):
    """GPTBigCode: multi-query attention — c_attn packs [q(H) | k(hd) | v(hd)].

    Only the MQA layout is supported: with ``multi_query=False`` HF interleaves
    q/k/v per head instead, which this flat slicing would scramble."""
    if c.kv_heads != 1:
        raise ValueError(
            "gpt_bigcode converter supports multi_query=True checkpoints only "
            f"(got kv_heads={c.kv_heads}); the non-MQA c_attn layout is per-head "
            "interleaved and not implemented"
        )
    p = {
        "embed_tokens": {"embedding": sd["transformer.wte.weight"]},
        "embed_positions": {"embedding": sd["transformer.wpe.weight"]},
        "ln_f": _ln(sd, "transformer.ln_f"),
    }
    H = c.hidden_size
    kv_dim = c.kv_heads * c.dim_per_head
    for i in range(c.num_layers):
        pre = f"transformer.h.{i}"
        cw = sd[f"{pre}.attn.c_attn.weight"]  # [H + 2*kv_dim, H] (nn.Linear layout)
        cb = sd[f"{pre}.attn.c_attn.bias"]
        p[f"layers_{i}"] = {
            "ln_1": _ln(sd, f"{pre}.ln_1"),
            "ln_2": _ln(sd, f"{pre}.ln_2"),
            "attn": {
                "q_proj": {"kernel": cw[:H].T, "bias": cb[:H]},
                "k_proj": {"kernel": cw[H : H + kv_dim].T, "bias": cb[H : H + kv_dim]},
                "v_proj": {"kernel": cw[H + kv_dim :].T, "bias": cb[H + kv_dim :]},
                "o_proj": _linear(sd, f"{pre}.attn.c_proj"),
            },
            "mlp": {
                "up_proj": _linear(sd, f"{pre}.mlp.c_fc"),
                "down_proj": _linear(sd, f"{pre}.mlp.c_proj"),
            },
        }
    return p


def _bigcode_from_params(p, c):
    if c.kv_heads != 1:
        raise ValueError("gpt_bigcode export supports multi_query=True configs only")
    sd = {
        "transformer.wte.weight": p["embed_tokens"]["embedding"],
        "transformer.wpe.weight": p["embed_positions"]["embedding"],
        "transformer.ln_f.weight": p["ln_f"]["scale"],
        "transformer.ln_f.bias": p["ln_f"]["bias"],
    }
    for i in range(c.num_layers):
        L = p[f"layers_{i}"]
        pre = f"transformer.h.{i}"
        sd[f"{pre}.ln_1.weight"] = L["ln_1"]["scale"]
        sd[f"{pre}.ln_1.bias"] = L["ln_1"]["bias"]
        sd[f"{pre}.ln_2.weight"] = L["ln_2"]["scale"]
        sd[f"{pre}.ln_2.bias"] = L["ln_2"]["bias"]
        sd[f"{pre}.attn.c_attn.weight"] = np.concatenate(
            [L["attn"][k]["kernel"].T for k in ("q_proj", "k_proj", "v_proj")], axis=0
        )
        sd[f"{pre}.attn.c_attn.bias"] = np.concatenate(
            [L["attn"][k]["bias"] for k in ("q_proj", "k_proj", "v_proj")]
        )
        sd[f"{pre}.attn.c_proj.weight"] = L["attn"]["o_proj"]["kernel"].T
        sd[f"{pre}.attn.c_proj.bias"] = L["attn"]["o_proj"]["bias"]
        sd[f"{pre}.mlp.c_fc.weight"] = L["mlp"]["up_proj"]["kernel"].T
        sd[f"{pre}.mlp.c_fc.bias"] = L["mlp"]["up_proj"]["bias"]
        sd[f"{pre}.mlp.c_proj.weight"] = L["mlp"]["down_proj"]["kernel"].T
        sd[f"{pre}.mlp.c_proj.bias"] = L["mlp"]["down_proj"]["bias"]
    return sd


CONVERTERS = {
    "gpt2": (_gpt2_to_params, _gpt2_from_params),
    "llama": (_llama_to_params, _llama_from_params),
    "gpt_neox": (_neox_to_params, _neox_from_params),
    "gptj": (_gptj_to_params, _gptj_from_params),
    "opt": (_opt_to_params, _opt_from_params),
    "bloom": (_bloom_to_params, _bloom_from_params),
    "gpt_bigcode": (_bigcode_to_params, _bigcode_from_params),
}
# "t5" is registered below once its converters are defined (seq2seq section)


def hf_state_dict_to_params(model_type: str, sd: Dict[str, np.ndarray], config: TransformerConfig) -> Dict[str, Any]:
    if model_type not in CONVERTERS:
        raise ValueError(f"No converter for model_type {model_type!r}")
    p = CONVERTERS[model_type][0](sd, config)
    return jax.tree.map(lambda x: np.asarray(x, dtype=np.float32), p)


def params_to_hf_state_dict(
    model_type: str, params: Dict[str, Any], config: TransformerConfig
) -> Dict[str, np.ndarray]:
    if model_type not in CONVERTERS:
        raise ValueError(f"No converter for model_type {model_type!r}")
    params = jax.tree.map(lambda x: np.asarray(jax.device_get(x), dtype=np.float32), params)
    return CONVERTERS[model_type][1](params, config)


# ------------------------------------------------------------------- top level


def init_params(config: TransformerConfig, module=None, seed: int = 0) -> Dict[str, Any]:
    """Random-init trunk params (for offline runs and tests)."""
    module = module or TransformerLM(config)
    ids = jnp.zeros((1, 2), jnp.int32)
    return module.init(jax.random.PRNGKey(seed), ids, jnp.ones((1, 2), jnp.int32))["params"]


def _hf_load_retry_policy():
    """Retry policy for HF checkpoint reads: transient I/O faults (NFS blips,
    hub 5xx surfaced as OSError, injected chaos) are retried; a definitively
    missing file is an answer and fails immediately. Budget is overridable via
    TRLX_HF_LOAD_RETRIES for constrained CI."""
    from trlx_tpu.resilience.chaos import ChaosInjectedError
    from trlx_tpu.resilience.retry import RetryPolicy

    return RetryPolicy(
        max_retries=int(os.environ.get("TRLX_HF_LOAD_RETRIES", 2)),
        base_delay_s=float(os.environ.get("TRLX_HF_LOAD_RETRY_DELAY", 1.0)),
        max_delay_s=15.0,
        retry_on=(OSError, ChaosInjectedError),
        giveup_on=(FileNotFoundError, IsADirectoryError, NotADirectoryError),
    )


def _read_hf_checkpoint(model_path: str):
    """(AutoConfig, torch state dict) for a local HF dir, under the retry
    policy above, with the chaos ``hf-load`` fault site inside the retried
    body so injected faults exercise the same recovery path as real ones."""
    from trlx_tpu.resilience.chaos import chaos
    from trlx_tpu.resilience.retry import retry_call

    def read():
        chaos.fail_if_armed("hf-load", detail=model_path)
        import transformers

        hf_config = transformers.AutoConfig.from_pretrained(model_path)
        return hf_config, load_torch_state_dict(model_path)

    return retry_call(read, policy=_hf_load_retry_policy(), name=f"hf-load {model_path}")


def load_pretrained(
    model_path: str,
    overrides: Optional[Dict[str, Any]] = None,
    mesh=None,
) -> Tuple[TransformerConfig, Optional[Dict[str, Any]], str]:
    """Resolve (config, trunk params or None, model_type) for a model path.

    Local dir with config.json + weights → converted checkpoint. Otherwise a family
    preset with no params (caller random-inits) — the zero-egress fallback.
    With ``mesh``, a native pre-converted checkpoint restores directly into device
    shards (per-host partial reads); torch checkpoints always load host-side.
    """
    from trlx_tpu import checkpointing

    if checkpointing.is_native_checkpoint(model_path):
        # pre-converted chunked store: already in TransformerLM layout, restores
        # with per-host partial reads (see trlx_tpu/checkpointing.py)
        return checkpointing.restore_native(
            model_path, overrides, mesh=mesh, expect_seq2seq=False
        )
    config_path = os.path.join(model_path, "config.json")
    if os.path.isdir(model_path) and os.path.exists(config_path):
        hf_config, sd = _read_hf_checkpoint(model_path)
        config = from_hf_config(hf_config, overrides)
        params = hf_state_dict_to_params(hf_config.model_type, sd, config)
        return config, params, hf_config.model_type
    config = get_preset(model_path, overrides)
    model_type = _family_of(model_path)
    logger.warning(
        f"No local checkpoint at {model_path!r}; using random-init {model_type} preset "
        "(zero-egress environment)"
    )
    return config, None, model_type


def _family_of(name: str) -> str:
    key = name.lower().replace("-", "").replace("_", "")
    for family in ("gptbigcode", "gptneox", "gptj", "gpt2", "llama", "opt", "bloom"):
        if family in key:
            return {"gptneox": "gpt_neox", "gptbigcode": "gpt_bigcode"}.get(family, family)
    if "pythia" in key or "neox" in key:
        return "gpt_neox"
    if "starcoder" in key or "santacoder" in key:
        return "gpt_bigcode"
    return "gpt2"


def save_pretrained_hf(
    out_dir: str,
    model_type: str,
    params: Dict[str, Any],
    config: TransformerConfig,
    hf_config=None,
) -> None:
    """Export trunk params as an HF-format directory (safetensors + config.json),
    parity with the reference's ``save_pretrained`` hf_model export
    (accelerate_base_trainer.py:284-307)."""
    os.makedirs(out_dir, exist_ok=True)
    sd = params_to_hf_state_dict(model_type, params, config)
    from safetensors.numpy import save_file

    save_file({k: np.ascontiguousarray(v) for k, v in sd.items()}, os.path.join(out_dir, "model.safetensors"))
    if hf_config is None:
        hf_config = make_hf_config(model_type, config)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        f.write(hf_config.to_json_string())


def make_hf_config(model_type: str, c: TransformerConfig):
    import transformers

    if model_type == "gpt2":
        return transformers.GPT2Config(
            vocab_size=c.vocab_size, n_embd=c.hidden_size, n_layer=c.num_layers,
            n_head=c.num_heads, n_positions=c.max_position_embeddings,
            n_inner=c.ffn_dim, layer_norm_epsilon=c.norm_eps,
        )
    if model_type == "llama":
        return transformers.LlamaConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_hidden_layers=c.num_layers, num_attention_heads=c.num_heads,
            num_key_value_heads=c.kv_heads, intermediate_size=c.ffn_dim,
            max_position_embeddings=c.max_position_embeddings, rms_norm_eps=c.norm_eps,
            rope_theta=c.rope_theta, tie_word_embeddings=c.tie_word_embeddings,
        )
    if model_type == "gpt_neox":
        return transformers.GPTNeoXConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_hidden_layers=c.num_layers, num_attention_heads=c.num_heads,
            intermediate_size=c.ffn_dim, max_position_embeddings=c.max_position_embeddings,
            rotary_pct=c.rotary_pct, layer_norm_eps=c.norm_eps,
            use_parallel_residual=c.parallel_residual,
        )
    if model_type == "gptj":
        return transformers.GPTJConfig(
            vocab_size=c.vocab_size, n_embd=c.hidden_size, n_layer=c.num_layers,
            n_head=c.num_heads, n_positions=c.max_position_embeddings,
            rotary_dim=int(c.dim_per_head * c.rotary_pct), layer_norm_epsilon=c.norm_eps,
        )
    if model_type == "opt":
        return transformers.OPTConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size,
            num_hidden_layers=c.num_layers, num_attention_heads=c.num_heads,
            ffn_dim=c.ffn_dim, max_position_embeddings=c.max_position_embeddings,
            do_layer_norm_before=True,
        )
    if model_type == "bloom":
        return transformers.BloomConfig(
            vocab_size=c.vocab_size, hidden_size=c.hidden_size, n_layer=c.num_layers,
            n_head=c.num_heads, layer_norm_epsilon=c.norm_eps,
        )
    if model_type == "gpt_bigcode":
        return transformers.GPTBigCodeConfig(
            vocab_size=c.vocab_size, n_embd=c.hidden_size, n_layer=c.num_layers,
            n_head=c.num_heads, n_positions=c.max_position_embeddings,
            n_inner=c.ffn_dim, layer_norm_epsilon=c.norm_eps,
            multi_query=c.kv_heads == 1, activation_function="gelu_pytorch_tanh",
        )
    if model_type == "t5":
        return transformers.T5Config(
            vocab_size=c.vocab_size, d_model=c.d_model, d_kv=c.d_kv, d_ff=c.d_ff,
            num_layers=c.num_layers, num_decoder_layers=c.num_decoder_layers,
            num_heads=c.num_heads,
            relative_attention_num_buckets=c.relative_attention_num_buckets,
            relative_attention_max_distance=c.relative_attention_max_distance,
            layer_norm_epsilon=c.layer_norm_epsilon,
            feed_forward_proj="gated-gelu" if c.is_gated else "relu",
            tie_word_embeddings=c.tie_word_embeddings,
            decoder_start_token_id=c.decoder_start_token_id,
        )
    raise ValueError(f"No HF config factory for {model_type!r}")


# ------------------------------------------------------------------- T5 (seq2seq)


def _t5_attn_to_params(sd, pre, has_bias):
    p = {
        "q": {"kernel": sd[f"{pre}.q.weight"].T},
        "k": {"kernel": sd[f"{pre}.k.weight"].T},
        "v": {"kernel": sd[f"{pre}.v.weight"].T},
        "o": {"kernel": sd[f"{pre}.o.weight"].T},
    }
    if has_bias:
        p["relative_attention_bias"] = {"embedding": sd[f"{pre}.relative_attention_bias.weight"]}
    return p


def _t5_ffn_to_params(sd, pre, gated):
    if gated:
        return {
            "wi_0": {"kernel": sd[f"{pre}.wi_0.weight"].T},
            "wi_1": {"kernel": sd[f"{pre}.wi_1.weight"].T},
            "wo": {"kernel": sd[f"{pre}.wo.weight"].T},
        }
    return {"wi": {"kernel": sd[f"{pre}.wi.weight"].T}, "wo": {"kernel": sd[f"{pre}.wo.weight"].T}}


def t5_state_dict_to_params(sd: Dict[str, np.ndarray], config) -> Dict[str, Any]:
    """HF T5 state dict -> T5LM params (cites modeling_base.py:124 from_pretrained)."""
    gated = config.is_gated
    p: Dict[str, Any] = {
        "shared": {"embedding": sd["shared.weight"]},
        "encoder_ln": {"scale": sd["encoder.final_layer_norm.weight"]},
        "decoder_ln": {"scale": sd["decoder.final_layer_norm.weight"]},
    }
    if not config.tie_word_embeddings and "lm_head.weight" in sd:
        p["lm_head"] = {"kernel": sd["lm_head.weight"].T}
    for i in range(config.num_layers):
        pre = f"encoder.block.{i}"
        p[f"encoder_blocks_{i}"] = {
            "ln_1": {"scale": sd[f"{pre}.layer.0.layer_norm.weight"]},
            "attn": _t5_attn_to_params(sd, f"{pre}.layer.0.SelfAttention", i == 0),
            "ln_2": {"scale": sd[f"{pre}.layer.1.layer_norm.weight"]},
            "mlp": _t5_ffn_to_params(sd, f"{pre}.layer.1.DenseReluDense", gated),
        }
    for i in range(config.num_decoder_layers):
        pre = f"decoder.block.{i}"
        p[f"decoder_blocks_{i}"] = {
            "ln_1": {"scale": sd[f"{pre}.layer.0.layer_norm.weight"]},
            "self_attn": _t5_attn_to_params(sd, f"{pre}.layer.0.SelfAttention", i == 0),
            "ln_cross": {"scale": sd[f"{pre}.layer.1.layer_norm.weight"]},
            "cross_attn": _t5_attn_to_params(sd, f"{pre}.layer.1.EncDecAttention", False),
            "ln_2": {"scale": sd[f"{pre}.layer.2.layer_norm.weight"]},
            "mlp": _t5_ffn_to_params(sd, f"{pre}.layer.2.DenseReluDense", gated),
        }
    return jax.tree.map(lambda x: np.asarray(x, np.float32), p)


def _t5_attn_from_params(p, pre, sd):
    for k in ("q", "k", "v", "o"):
        sd[f"{pre}.{k}.weight"] = p[k]["kernel"].T
    if "relative_attention_bias" in p:
        sd[f"{pre}.relative_attention_bias.weight"] = p["relative_attention_bias"]["embedding"]


def _t5_ffn_from_params(p, pre, sd):
    for name in ("wi", "wi_0", "wi_1", "wo"):
        if name in p:
            sd[f"{pre}.{name}.weight"] = p[name]["kernel"].T


def _t5_from_params(p: Dict[str, Any], c) -> Dict[str, np.ndarray]:
    """T5LM params -> HF T5 state dict (reverse of :func:`t5_state_dict_to_params`)."""
    sd = {
        "shared.weight": p["shared"]["embedding"],
        "encoder.embed_tokens.weight": p["shared"]["embedding"],
        "decoder.embed_tokens.weight": p["shared"]["embedding"],
        "encoder.final_layer_norm.weight": p["encoder_ln"]["scale"],
        "decoder.final_layer_norm.weight": p["decoder_ln"]["scale"],
    }
    if "lm_head" in p:
        sd["lm_head.weight"] = p["lm_head"]["kernel"].T
    for i in range(c.num_layers):
        pre = f"encoder.block.{i}"
        L = p[f"encoder_blocks_{i}"]
        sd[f"{pre}.layer.0.layer_norm.weight"] = L["ln_1"]["scale"]
        _t5_attn_from_params(L["attn"], f"{pre}.layer.0.SelfAttention", sd)
        sd[f"{pre}.layer.1.layer_norm.weight"] = L["ln_2"]["scale"]
        _t5_ffn_from_params(L["mlp"], f"{pre}.layer.1.DenseReluDense", sd)
    for i in range(c.num_decoder_layers):
        pre = f"decoder.block.{i}"
        L = p[f"decoder_blocks_{i}"]
        sd[f"{pre}.layer.0.layer_norm.weight"] = L["ln_1"]["scale"]
        _t5_attn_from_params(L["self_attn"], f"{pre}.layer.0.SelfAttention", sd)
        sd[f"{pre}.layer.1.layer_norm.weight"] = L["ln_cross"]["scale"]
        _t5_attn_from_params(L["cross_attn"], f"{pre}.layer.1.EncDecAttention", sd)
        sd[f"{pre}.layer.2.layer_norm.weight"] = L["ln_2"]["scale"]
        _t5_ffn_from_params(L["mlp"], f"{pre}.layer.2.DenseReluDense", sd)
    return sd


CONVERTERS["t5"] = (t5_state_dict_to_params, _t5_from_params)


def load_pretrained_seq2seq(
    model_path: str, overrides: Optional[Dict[str, Any]] = None, mesh=None
):
    """Resolve (T5Config, params or None) for a seq2seq model path."""
    from trlx_tpu import checkpointing
    from trlx_tpu.models.t5 import T5Config, from_hf_t5_config

    if checkpointing.is_native_checkpoint(model_path):
        config, params, _ = checkpointing.restore_native(
            model_path, overrides, mesh=mesh, expect_seq2seq=True
        )
        return config, params
    config_path = os.path.join(model_path, "config.json")
    if os.path.isdir(model_path) and os.path.exists(config_path):
        hf_config, sd = _read_hf_checkpoint(model_path)
        config = from_hf_t5_config(hf_config, overrides)
        return config, t5_state_dict_to_params(sd, config)
    config = T5Config()
    if overrides:
        config = config.replace(**overrides)
    logger.warning(
        f"No local checkpoint at {model_path!r}; using random-init T5 config (zero-egress)"
    )
    return config, None


def merge_loaded_params(init_tree: Dict[str, Any], loaded_tree: Dict[str, Any]) -> Dict[str, Any]:
    """Overlay checkpoint leaves onto an init tree, keeping init-only params (LoRA
    adapters, new heads) — the JAX analogue of HF's lenient state-dict load."""
    if not isinstance(init_tree, dict):
        return loaded_tree if loaded_tree is not None else init_tree
    out = {}
    for k, v in init_tree.items():
        if isinstance(loaded_tree, dict) and k in loaded_tree:
            out[k] = merge_loaded_params(v, loaded_tree[k])
        else:
            out[k] = v
    # keep any loaded-only keys too (e.g. optional biases)
    if isinstance(loaded_tree, dict):
        for k, v in loaded_tree.items():
            if k not in out:
                out[k] = v
    return out


# leaf param names that belong to peft adapters (LoRA / prefix / prompt)
ADAPTER_PARAM_NAMES = ("lora_a", "lora_b", "prefix_k", "prefix_v", "prompt_embeddings")


def extract_adapter_params(tree: Any) -> Optional[Dict[str, Any]]:
    """The adapter-only subtree of a params tree (None if no adapters).

    Parity: the reference saves peft adapters + heads only instead of the full
    model (modeling_base.py:347-353)."""
    if not isinstance(tree, dict):
        return None
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        if k in ADAPTER_PARAM_NAMES:
            out[k] = v
        elif isinstance(v, dict):
            sub = extract_adapter_params(v)
            if sub:
                out[k] = sub
    return out or None


def save_adapters(path: str, params: Dict[str, Any]) -> bool:
    """Write adapters.msgpack next to the export; returns False if no adapters."""
    from flax.serialization import to_bytes

    adapters = extract_adapter_params(params)
    if not adapters:
        return False
    with open(os.path.join(path, "adapters.msgpack"), "wb") as f:
        f.write(to_bytes(adapters))
    return True


def load_adapters(path: str, params: Dict[str, Any]) -> Dict[str, Any]:
    """Overlay adapters.msgpack leaves onto ``params`` (shapes must match)."""
    from flax.serialization import from_bytes

    with open(os.path.join(path, "adapters.msgpack"), "rb") as f:
        template = extract_adapter_params(params)
        adapters = from_bytes(template, f.read())
    return merge_loaded_params(params, adapters)


def peft_overrides(peft_config: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Map a reference-style peft config dict to TransformerConfig overrides
    (parity: modeling_base.py:162-240 — LORA, PREFIX_TUNING, PROMPT_TUNING)."""
    if not peft_config:
        return {}
    ptype = str(peft_config.get("peft_type", "LORA")).upper()
    if ptype == "LORA":
        out = {"lora_r": int(peft_config.get("r", 8)),
               "lora_alpha": float(peft_config.get("lora_alpha", peft_config.get("alpha", 16)))}
        targets = peft_config.get("target_modules")
        if targets:
            out["lora_targets"] = tuple(targets)
        return out
    if ptype in ("PREFIX_TUNING", "PREFIX"):
        return {"peft_type": "prefix",
                "num_virtual_tokens": int(peft_config.get("num_virtual_tokens", 8))}
    if ptype in ("PROMPT_TUNING", "PROMPT"):
        return {"peft_type": "prompt",
                "num_virtual_tokens": int(peft_config.get("num_virtual_tokens", 8))}
    raise ValueError(f"Unsupported peft_type {ptype!r} (LORA / PREFIX_TUNING / PROMPT_TUNING)")


T5_LORA_TARGETS = ("q", "k", "v", "o", "wi", "wi_0", "wi_1", "wo")


def t5_peft_overrides(peft_config: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """Seq2seq variant of :func:`peft_overrides`: LoRA only, with T5 target-name
    validation — a causal-style target list (q_proj/v_proj) would otherwise
    silently build zero adapters and freeze the whole trunk."""
    peft = peft_overrides(peft_config)
    if not peft:
        return {}
    if "lora_r" not in peft:
        raise NotImplementedError(
            "seq2seq (T5) peft supports LORA adapters; prefix/prompt tuning "
            "is causal-only (T5Config has no virtual-token path)"
        )
    peft.setdefault("lora_targets", ("q", "v"))
    unknown = set(peft["lora_targets"]) - set(T5_LORA_TARGETS)
    if unknown:
        raise ValueError(
            f"peft target_modules {sorted(unknown)} match no T5 module; "
            f"valid T5 LoRA targets: {sorted(T5_LORA_TARGETS)}"
        )
    return peft
