"""Generic causal decoder LM in Flax covering the reference's model families.

The reference wraps HF torch models and re-implements a *frozen branch forward* per
architecture (`/root/reference/trlx/models/modeling_ppo.py:502-1637`: GPT/OPT/Bloom/
Llama/GPTBigCode branches). Here a single configurable module covers gpt2, gpt-neox/
pythia, gpt-j, opt, and llama: positional scheme (learned/rotary, neox- or gptj-style),
norm type (LN/RMS), activation (gelu/gelu_new/relu/silu), GLU mlp, parallel residual,
biases, GQA, and tied embeddings are all config switches. The same block stack is
reusable as the hydra frozen branch by calling ``forward_from`` on the top-N layers
with a separate (frozen) param subtree — no per-architecture branch code.

TPU-first details: all matmuls run in ``compute_dtype`` (bf16) against fp32 master
params; attention uses an additive mask built from fixed shapes (no dynamic shapes);
the KV cache is an explicit functional pytree updated with ``dynamic_update_slice`` so
generation jits to a single XLA while-loop; activations can be sequence-sharded via
``with_sharding_constraint`` hooks (Megatron-SP analogue).
"""

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from flax import linen as nn

from trlx_tpu.parallel.mesh import BATCH_AXES, MODEL_AXIS, PIPE_AXIS
from trlx_tpu.parallel.sharding import (
    ambient_mesh,
    batch_divisible,
    constrain_gathered,
    constrain_seq,
)

# {"k": ..., "v": ..., "index": i32[]} where k/v are a list of L arrays, each
# [B,Hkv,S,D] (default: per-layer carries -> in-place decode writes), or one
# stacked [L,B,Hkv,S,D] array when config.stacked (nn.scan layout)
KVCache = Dict[str, Any]


def _concrete_zero(x) -> bool:
    """True iff ``x`` is a concrete (non-traced) scalar equal to 0."""
    try:
        return int(x) == 0
    except Exception:  # jax TracerError and friends
        return False


@dataclass(frozen=True)
class TransformerConfig:
    """Architecture hyperparameters; presets for each family in
    :mod:`trlx_tpu.models.presets`."""

    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # GQA; None = num_heads
    head_dim: Optional[int] = None  # None = hidden_size // num_heads
    intermediate_size: Optional[int] = None  # None = 4*hidden
    max_position_embeddings: int = 1024

    pos_embedding: str = "learned"  # "learned" | "rotary" | "alibi" | "none"
    rope_style: str = "neox"  # "neox" (rotate-half) | "gptj" (interleaved)
    rotary_pct: float = 1.0
    rope_theta: float = 10000.0
    pos_offset: int = 0  # OPT uses a +2 offset into its learned table

    norm: str = "layernorm"  # "layernorm" | "rmsnorm"
    norm_eps: float = 1e-5
    activation: str = "gelu_new"  # "gelu_new" | "gelu" | "relu" | "silu"
    glu: bool = False  # SwiGLU-style gated mlp (llama)
    parallel_residual: bool = False  # gptj / neox style
    shared_parallel_ln: bool = False  # gptj: one LN feeds both attn and mlp
    attn_bias: bool = True
    mlp_bias: bool = True
    embed_ln: bool = False  # LayerNorm on embeddings (bloom word_embeddings_layernorm)
    head_bias: bool = False  # gptj's lm_head carries a bias
    tie_word_embeddings: bool = True
    final_norm: bool = True

    initializer_range: float = 0.02
    # Scale the residual-out projections (o_proj/down_proj) by 1/sqrt(2*L):
    # each residual stream sums 2L projection outputs, so flat-std init grows
    # the stream variance linearly with depth — the depth-48 first-step loss
    # spikes PARITY_r4 recorded (3.3 -> 7-13 under clip+warmup) while depth-24
    # trained cleanly. HF GPT-2 applies exactly this scaling in _init_weights
    # ("Scale initializations of select weights... by 1/sqrt(2*n_layer)"), and
    # the reference inherits it through from_pretrained/from_config
    # (/root/reference/trlx/models/modeling_base.py:124-161); random-init runs
    # here need it explicitly. Off reproduces the flat 0.02 behavior.
    depth_scaled_init: bool = True
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat: str = "none"  # "none" | "full" | "per_layer" | "nothing_saveable" | "dots_saveable"
    attention_impl: str = "xla"  # "xla" | "flash" (Pallas) | "ring" (sequence-parallel)
    # int8 KV cache (per-row symmetric quantization over the head dim): at wide
    # decode batches the KV cache dominates decode HBM traffic, so halving its
    # footprint raises the decode bandwidth roofline ~2x (the reference has no
    # analogue; its CUDA decode reads fp16 KV). Scales stored f32 per (b,h,slot).
    kv_cache_quant: bool = False
    # Paged-KV decode (serving engine): implementation for the block-table
    # gather attention — "auto" | "pallas" | "xla" (see ops/paged_attention.py;
    # auto = fused kernel on a single-device TPU, XLA gather elsewhere).
    paged_attention_impl: str = "auto"
    # Pipeline parallelism (the reference's Apex pipeline engine analogue,
    # modeling_nemo_ppo.py:713-731). > 1 stores block params STACKED ([L, ...]
    # under "layers_scan", sharded over the mesh "pipe" axis) and runs cache-free
    # forwards as a GPipe microbatch schedule over ppermute; cached decode runs a
    # sequential layer scan (layer shards streamed — the NeMo analogue toggles PP
    # scheduling off for inference too, modeling_nemo_ppo.py:838-870).
    pipeline_stages: int = 1
    pipeline_microbatches: int = 4
    # Stacked-layer layout WITHOUT pipelining: params [L, ...] under
    # "layers_scan", forwards run lax.scan over layers. Compile time becomes
    # O(1) in depth (an unrolled 32-layer llama body is traced/compiled 32x;
    # the scanned body once) at the cost of per-layer freeze paths and hydra
    # branches (same restrictions as pipeline_stages > 1).
    scan_layers: bool = False

    @property
    def stacked(self) -> bool:
        """Whether block params use the stacked [num_layers, ...] layout."""
        return self.pipeline_stages > 1 or self.scan_layers
    # Megatron-SP analogue: shard the residual stream's sequence dim over the
    # `model` axis between blocks (reference sequence_parallel cfg,
    # modeling_nemo_ppo.py:160-164). Applied on cache-free forwards.
    sequence_sharding: bool = False

    # Native peft equivalents (reference uses the peft library —
    # modeling_base.py:162-240). LoRA: r=0 disables. peft_type "prefix" adds
    # per-layer learned K/V prefixes; "prompt" prepends learned virtual-token
    # embeddings. A module built with peft_type="none"/lora_r=0 simply ignores
    # adapter params present in the tree — that IS the disable_adapter path.
    lora_r: int = 0
    lora_alpha: float = 16.0
    lora_targets: Tuple[str, ...] = ("q_proj", "v_proj")
    peft_type: str = "none"  # "none" | "prefix" | "prompt" (lora via lora_r)
    num_virtual_tokens: int = 0

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def dim_per_head(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def ffn_dim(self) -> int:
        return self.intermediate_size or 4 * self.hidden_size

    def residual_init_std(self) -> float:
        """Init std for projections writing into the residual stream
        (o_proj/down_proj): ``initializer_range / sqrt(2*num_layers)`` under
        ``depth_scaled_init`` (see the field's comment), flat otherwise."""
        if self.depth_scaled_init:
            return self.initializer_range / math.sqrt(2 * self.num_layers)
        return self.initializer_range

    def replace(self, **kw) -> "TransformerConfig":
        return replace(self, **kw)


def remat_policy(name: str):
    """Rematerialization policy by config name (shared by the listed-layer stack
    and the pipelined stage scan). ``per_layer`` = save only the block-boundary
    residuals (an ``nn.remat`` with no policy), the scale-appropriate middle
    ground between ``nothing_saveable`` (recompute everything, xl-class) and
    ``dots_saveable`` (keep matmul outputs, small models) — guidance per model
    scale in docs/parallelism.md "Learner overlap & FSDP"."""
    return {
        "full": None,
        "per_layer": None,
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
    }[name]


def _act(name: str):
    return {
        "gelu_new": lambda x: jax.nn.gelu(x, approximate=True),
        "gelu": lambda x: jax.nn.gelu(x, approximate=False),
        "relu": jax.nn.relu,
        "silu": jax.nn.silu,
    }[name]


def _norm_module(config: TransformerConfig, name: Optional[str] = None):
    kw = dict(epsilon=config.norm_eps, dtype=config.compute_dtype, param_dtype=config.param_dtype)
    if name is not None:
        kw["name"] = name
    if config.norm == "rmsnorm":
        return nn.RMSNorm(**kw)
    return nn.LayerNorm(**kw)


def make_causal_bias(attention_mask: Optional[jnp.ndarray], B: int, T: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(positions, additive causal+padding mask bias) for a cache-free forward."""
    if attention_mask is not None:
        positions = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0, None).astype(jnp.int32)
    else:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    causal = jnp.tril(jnp.ones((T, T), dtype=bool))[None, None, :, :]
    if attention_mask is not None:
        causal = jnp.logical_and(causal, attention_mask[:, None, None, :].astype(bool))
    return positions, jnp.where(causal, 0.0, -1e9).astype(jnp.float32)


def make_attn_bias(
    config: TransformerConfig, attention_mask: Optional[jnp.ndarray], B: int, T: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(positions, additive mask bias incl. ALiBi when configured) for a
    cache-free forward. Use this, not make_causal_bias, wherever the config is
    at hand — it folds the positional bias in so new call sites cannot miss it."""
    positions, bias = make_causal_bias(attention_mask, B, T)
    if config.pos_embedding == "alibi":
        bias = bias + alibi_bias(config, attention_mask, B, T)
    return positions, bias


def alibi_slopes(num_heads: int) -> jnp.ndarray:
    """Per-head ALiBi slopes (same algorithm as HF ``build_alibi_tensor``)."""
    closest = 2 ** math.floor(math.log2(num_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = base ** np.arange(1, closest + 1)
    if closest != num_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        num_rem = min(closest, num_heads - closest)
        extra = extra_base ** np.arange(1, 1 + 2 * num_rem, 2)
        slopes = np.concatenate([slopes, extra])
    return jnp.asarray(slopes, jnp.float32)


def alibi_bias(config: TransformerConfig, attention_mask: Optional[jnp.ndarray], B: int, S: int) -> jnp.ndarray:
    """[B, H, 1, S] additive ALiBi bias over key slots.

    Matches HF Bloom: bias = slope * key_position, where key position counts
    valid tokens (softmax-shift-invariant vs the relative form, since the
    -slope*q_pos term is constant per query row)."""
    if attention_mask is not None:
        m = attention_mask.astype(jnp.float32)
        key_pos = (jnp.clip(jnp.cumsum(m, axis=1) - 1, 0, None) * m)
    else:
        key_pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.float32)[None, :], (B, S))
    slopes = alibi_slopes(config.num_heads)
    return slopes[None, :, None, None] * key_pos[:, None, None, :]


def make_rotary(config: TransformerConfig, positions: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables [B, T, rot_dim/2] for the given positions."""
    rot_dim = int(config.dim_per_head * config.rotary_pct)
    rot_dim -= rot_dim % 2
    inv_freq = 1.0 / (config.rope_theta ** (jnp.arange(0, rot_dim, 2, dtype=jnp.float32) / rot_dim))
    freqs = positions[..., None].astype(jnp.float32) * inv_freq  # [B,T,rot/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rotary(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray, style: str) -> jnp.ndarray:
    """Rotate queries/keys. x: [B, T, H, D]; cos/sin [B, T, rot/2]."""
    rot_dim = cos.shape[-1] * 2
    x_rot, x_pass = x[..., :rot_dim], x[..., rot_dim:]
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    if style == "gptj":
        # interleaved pairs (x0,x1),(x2,x3),...
        x1 = x_rot[..., 0::2]
        x2 = x_rot[..., 1::2]
        r1 = x1 * cos - x2 * sin
        r2 = x2 * cos + x1 * sin
        rotated = jnp.stack([r1, r2], axis=-1).reshape(x_rot.shape)
    else:
        # neox rotate-half: first half paired with second half
        half = rot_dim // 2
        x1 = x_rot[..., :half]
        x2 = x_rot[..., half:]
        r1 = x1 * cos - x2 * sin
        r2 = x2 * cos + x1 * sin
        rotated = jnp.concatenate([r1, r2], axis=-1)
    return jnp.concatenate([rotated, x_pass], axis=-1).astype(x.dtype)


class LoraDense(nn.Module):
    """Dense with the same param layout as nn.Dense (``kernel``/``bias``) plus
    optional low-rank adapters ``lora_a``/``lora_b`` (y += x A B * alpha/r).
    ``lora_a`` is normal-initialized, ``lora_b`` zeros, so the adapter starts as a
    no-op — the LoRA convention."""

    features: int
    use_bias: bool
    dtype: Any
    param_dtype: Any
    kernel_init: Any
    r: int = 0
    alpha: float = 16.0

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel = self.param("kernel", self.kernel_init, (in_features, self.features), self.param_dtype)
        y = x.astype(self.dtype) @ kernel.astype(self.dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,), self.param_dtype)
            y = y + bias.astype(self.dtype)
        if self.r > 0:
            a = self.param(
                "lora_a", nn.initializers.normal(1.0 / self.r), (in_features, self.r), self.param_dtype
            )
            b = self.param("lora_b", nn.initializers.zeros, (self.r, self.features), self.param_dtype)
            y = y + (x.astype(self.dtype) @ a.astype(self.dtype)) @ b.astype(self.dtype) * (
                self.alpha / self.r
            )
        return y


def merge_lora_params(params: Dict[str, Any], config: "TransformerConfig") -> Dict[str, Any]:
    """Fold adapters into base kernels (W += A B * alpha/r) and drop lora leaves —
    used when exporting to HF format (parity: peft ``merge_and_unload``)."""
    import numpy as np

    def walk(tree):
        if not isinstance(tree, dict):
            return tree
        out = {}
        if "kernel" in tree and "lora_a" in tree:
            scale = config.lora_alpha / config.lora_r
            out["kernel"] = np.asarray(tree["kernel"]) + np.asarray(tree["lora_a"]) @ np.asarray(
                tree["lora_b"]
            ) * scale
            for k, v in tree.items():
                if k not in ("kernel", "lora_a", "lora_b"):
                    out[k] = walk(v)
            return out
        return {k: walk(v) for k, v in tree.items()}

    return walk(params)


def quantize_kv_rows(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-row int8 quantization over the trailing (head) dim:
    x [..., D] -> (int8 values [..., D], f32 scales [..., 1])."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, scale


def write_kv_cache(cache: Dict[str, jnp.ndarray], kT: jnp.ndarray, vT: jnp.ndarray, idx):
    """Append [B,H,T,D] rows at slot ``idx``; quantizes when the cache carries
    scale planes (kv_cache_quant layout). Shared by the causal and T5 decoders —
    the quant scheme must stay identical between them."""
    at = (0, 0, idx, 0)
    if "k_scale" in cache:
        kq, ks = quantize_kv_rows(kT)
        vq, vs = quantize_kv_rows(vT)
        return {
            "k": jax.lax.dynamic_update_slice(cache["k"], kq, at),
            "v": jax.lax.dynamic_update_slice(cache["v"], vq, at),
            "k_scale": jax.lax.dynamic_update_slice(cache["k_scale"], ks, at),
            "v_scale": jax.lax.dynamic_update_slice(cache["v_scale"], vs, at),
        }
    return {
        "k": jax.lax.dynamic_update_slice(cache["k"], kT.astype(cache["k"].dtype), at),
        "v": jax.lax.dynamic_update_slice(cache["v"], vT.astype(cache["v"].dtype), at),
    }


def read_kv_cache(cache: Dict[str, jnp.ndarray], compute_dtype):
    """(kh, vh) to attend over; int8 caches dequantize on read — XLA fuses the
    convert+scale into the score einsum's operand stream, so HBM moves int8."""
    if "k_scale" in cache:
        # multiply int8 values by the f32 scale at full precision, THEN cast:
        # casting the scale to bf16 first would truncate it to 8 mantissa bits
        # and stack avoidable error on top of the int8 quantization
        return (
            (cache["k"].astype(jnp.float32) * cache["k_scale"]).astype(compute_dtype),
            (cache["v"].astype(jnp.float32) * cache["v_scale"]).astype(compute_dtype),
        )
    return cache["k"], cache["v"]


def kv_cache_layout(shape: Tuple[int, ...], dtype, quant: bool) -> Dict[str, Tuple]:
    """Per-layer cache buffers as {key: (shape, dtype)} — int8 values + one f32
    scale per row when ``quant``."""
    if quant:
        return {
            "k": (shape, jnp.int8), "v": (shape, jnp.int8),
            "k_scale": (shape[:-1] + (1,), jnp.float32),
            "v_scale": (shape[:-1] + (1,), jnp.float32),
        }
    return {"k": (shape, dtype), "v": (shape, dtype)}


class Attention(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,
        mask_bias: jnp.ndarray,
        positions: jnp.ndarray,
        cache: Optional[Dict[str, jnp.ndarray]] = None,
        kv_valid: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, Optional[Dict[str, jnp.ndarray]]]:
        """x: [B,T,Hid]; mask_bias additive [B,1,T,S]; cache holds this layer's k/v
        [B,Hkv,S,D] plus the global write index. ``kv_valid`` [B,T] enables the
        Pallas flash path on any multi-token forward — cache-free (training /
        scoring) or generation prefill (cache written from slot 0, attention over
        the prefix k/v only); single-token decode steps use XLA over the cache."""
        c = self.config
        B, T, _ = x.shape
        dense = lambda feats, name, bias, std=c.initializer_range: LoraDense(
            feats, use_bias=bias, dtype=c.compute_dtype, param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(std), name=name,
            r=c.lora_r if name in c.lora_targets else 0, alpha=c.lora_alpha,
        )
        res_std = c.residual_init_std()
        q = dense(c.num_heads * c.dim_per_head, "q_proj", c.attn_bias)(x)
        k = dense(c.kv_heads * c.dim_per_head, "k_proj", c.attn_bias)(x)
        v = dense(c.kv_heads * c.dim_per_head, "v_proj", c.attn_bias)(x)
        q = q.reshape(B, T, c.num_heads, c.dim_per_head)
        k = k.reshape(B, T, c.kv_heads, c.dim_per_head)
        v = v.reshape(B, T, c.kv_heads, c.dim_per_head)

        if c.pos_embedding == "rotary":
            cos, sin = make_rotary(c, positions)
            q = apply_rotary(q, cos, sin, c.rope_style)
            k = apply_rotary(k, cos, sin, c.rope_style)

        if cache is not None and "block_tables" in cache:
            # Paged step (serving engine) against the block-pool cache. T == 1
            # is the steady-state decode: the new row lands at position
            # context_lens (its block is always exclusively owned — the
            # allocator never leaves a live write frontier inside a shared
            # prefix block), then attention runs over context_lens+1 tokens
            # gathered through the block table. T > 1 is the speculative-
            # verify / chunked-prefill append: token j lands at context_lens+j
            # and query j attends causally over context_lens+j+1 tokens.
            # Causality is structural — only written slots are valid — so no
            # mask_bias is consumed; alibi (a position-dependent score bias)
            # and prefix tuning (scale-less prepended rows) don't fit that
            # contract and the serving engine refuses such configs.
            if c.pos_embedding == "alibi" or c.peft_type == "prefix":
                raise ValueError(
                    "paged decode does not support alibi or prefix tuning"
                )
            from trlx_tpu.ops.paged_attention import (
                paged_decode_attention, paged_verify_attention,
                write_paged_kv, write_paged_kv_multi,
            )

            if T == 1:
                new_cache = write_paged_kv(cache, k[:, 0], v[:, 0])
                out = paged_decode_attention(
                    q[:, 0], new_cache["k"], new_cache["v"],
                    cache["block_tables"], cache["context_lens"] + 1,
                    k_scale=new_cache.get("k_scale"), v_scale=new_cache.get("v_scale"),
                    scale=1.0 / math.sqrt(c.dim_per_head),
                    impl=c.paged_attention_impl,
                )
            else:
                new_cache = write_paged_kv_multi(cache, k, v)
                out = paged_verify_attention(
                    q, new_cache["k"], new_cache["v"],
                    cache["block_tables"], cache["context_lens"],
                    k_scale=new_cache.get("k_scale"), v_scale=new_cache.get("v_scale"),
                    scale=1.0 / math.sqrt(c.dim_per_head),
                    impl=c.paged_attention_impl,
                )
            out = out.reshape(B, T, c.num_heads * c.dim_per_head).astype(c.compute_dtype)
            out = dense(c.hidden_size, "o_proj", c.attn_bias, res_std)(out)
            return out, new_cache

        if cache is not None:
            idx = cache["index"]
            # cache layout [B, Hkv, S, D]: per-(b,h) keys are contiguous along S,
            # so the decode matvec streams them sequentially. The former
            # [B, S, Hkv, D] layout made XLA materialize a transposed copy of
            # every layer's cache every decode step (profiled on one v5e chip:
            # ~60us copy + ~60us strided reduce per layer per step).
            new_cache = write_kv_cache(
                cache, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3), idx
            )
            ck, cv = new_cache["k"], new_cache["v"]
        else:
            new_cache = None

        # The flash path serves every multi-token forward: training loss, the
        # logprob/value scoring passes, AND generation prefill. For prefill
        # (cache present, T > 1, writes starting at slot 0) attention over the
        # just-computed prefix k/v is exactly attention over the cache, since all
        # cache slots >= T are still empty; k/v are written to the cache above
        # regardless. The slot-0 requirement is enforced structurally: the cache
        # index must be a concrete 0 at trace time (true for generate()'s prefill,
        # never true inside the decode while_loop or for chunked appends, which
        # fall back to attending over the full cache via XLA).
        # With a cache present, a non-None kv_valid IS the prefill-from-zero
        # marker: TransformerLM only passes it when the cache index was a
        # concrete 0 at trace time (checked there, outside the remat wrapper —
        # in here cache["index"] may be a remat tracer even at prefill).
        use_flash = (
            c.attention_impl == "flash"
            and kv_valid is not None
            and T > 1
            and c.pos_embedding != "alibi"  # kernel takes no additive bias
            and c.peft_type != "prefix"  # prefix keys break the kernel's causal index math
        )
        # Mosaic kernels cannot be auto-partitioned by XLA SPMD: on a
        # multi-device mesh the flash call must be placed explicitly (batch and
        # head axes are embarrassingly parallel) via shard_map, and a shape
        # that cannot divide those axes falls back to the einsum paths below.
        flash_mesh = None
        if use_flash:
            flash_mesh = ambient_mesh()
            if flash_mesh is not None:
                n_batch = int(np.prod([flash_mesh.shape.get(a, 1) for a in BATCH_AXES]))
                n_model = flash_mesh.shape.get(MODEL_AXIS, 1)
                if flash_mesh.size == 1:
                    # single device: plain call. (Any larger mesh must go via
                    # the shard_map wrapper even when batch/model axes are
                    # trivial — e.g. a pipe-only mesh still has an auto axis
                    # the Mosaic kernel cannot sit under.)
                    flash_mesh = None
                elif B % n_batch or c.num_heads % n_model or c.kv_heads % n_model:
                    use_flash = False  # kernel cannot place; XLA attention below
        # kh/vh [B, Hkv, S, D]: the layout attention consumes (and the cache layout)
        k_row_scale = v_row_scale = None
        if cache is not None and not use_flash:
            # attend over the cache (decode step / XLA prefill)
            if "k_scale" in new_cache and c.peft_type != "prefix":
                # int8 cache: bare dtype convert only — the per-row scales fold
                # into the scores (k) and the softmax weights (v) below, which
                # is algebraically identical to dequantizing the operands but
                # leaves the big K/V streams a pure int8->bf16 cast XLA fuses
                # into the dot (the dequant multiply on the operand blocked
                # that fusion: int8 decode measured only 1.16x plain bf16 at
                # B=256 despite moving half the bytes). int8 values are exact
                # in bf16, and the scale multiply happens in f32 on the small
                # score/prob tensors — strictly less rounding than the old
                # per-element dequant-to-bf16. (Prefix tuning prepends
                # scale-less rows, so it keeps the dequant-on-read path.)
                kh = new_cache["k"].astype(c.compute_dtype)
                vh = new_cache["v"].astype(c.compute_dtype)
                k_row_scale = new_cache["k_scale"]  # [B, Hkv, S, 1] f32
                v_row_scale = new_cache["v_scale"]
            else:
                kh, vh = read_kv_cache(new_cache, c.compute_dtype)
        else:
            kh = k.transpose(0, 2, 1, 3)
            vh = v.transpose(0, 2, 1, 3)

        # prefix tuning: learned per-layer K/V prepended to whatever we attend
        # over (never cached — they are static), visible to every query (zero
        # bias). No positions are consumed and no rotary is applied to them
        # (parity: peft PREFIX_TUNING past_key_values, modeling_base.py:162-240).
        if c.peft_type == "prefix" and c.num_virtual_tokens > 0:
            nv = c.num_virtual_tokens
            pk = self.param(
                "prefix_k", nn.initializers.normal(c.initializer_range),
                (nv, c.kv_heads, c.dim_per_head), c.param_dtype,
            )
            pv = self.param(
                "prefix_v", nn.initializers.normal(c.initializer_range),
                (nv, c.kv_heads, c.dim_per_head), c.param_dtype,
            )
            shape = (B, c.kv_heads, nv, c.dim_per_head)
            kh = jnp.concatenate(
                [jnp.broadcast_to(pk.astype(kh.dtype).transpose(1, 0, 2)[None], shape), kh], axis=2
            )
            vh = jnp.concatenate(
                [jnp.broadcast_to(pv.astype(vh.dtype).transpose(1, 0, 2)[None], shape), vh], axis=2
            )
            mask_bias = jnp.concatenate(
                [jnp.zeros(mask_bias.shape[:-1] + (nv,), mask_bias.dtype), mask_bias], axis=-1
            )

        scale = 1.0 / math.sqrt(c.dim_per_head)

        # Single-token decode stays on the XLA einsum path BY MEASUREMENT: a
        # fused Pallas decode kernel (grid (B,Hkv) or (B,) + in-kernel head
        # loop) ran 1.3x slower per layer than XLA's multiply-reduce fusions on
        # one v5e chip (441us vs 337us per 12-layer step, B=32 S=256) — decode
        # attention is a batched matvec, too fine-grained for TPU pallas grids,
        # and XLA's VPU reduce already streams the cache near bandwidth.

        if (
            c.attention_impl == "ring"
            and cache is None
            and kv_valid is not None
            and c.pos_embedding != "alibi"
            and c.peft_type != "prefix"
        ):
            from trlx_tpu.ops.ring_attention import ring_attention

            mesh = ambient_mesh()
            n = mesh.shape.get(MODEL_AXIS, 1) if mesh is not None else 1
            if mesh is not None and n > 1 and T % n == 0 and batch_divisible(mesh, B):
                # grouped K/V ride the ring at native head count (no repeat)
                out = ring_attention(
                    q.transpose(0, 2, 1, 3), kh, vh,
                    mesh, axis_name=MODEL_AXIS, causal=True, scale=scale,
                    kv_valid=kv_valid, batch_axes=BATCH_AXES,
                ).transpose(0, 2, 1, 3).astype(c.compute_dtype)
                out = out.reshape(B, T, c.num_heads * c.dim_per_head)
                out = dense(c.hidden_size, "o_proj", c.attn_bias, res_std)(out)
                return out, new_cache
            # fall through to XLA when the mesh/shape can't ring

        if use_flash:
            # the kernel maps query head h -> kv head h // rep natively: grouped
            # K/V are never materialized at full head count
            from trlx_tpu.ops.attention import flash_attention, flash_attention_sharded

            # interpret (XLA-emulated) mode iff the COMPILE TARGET is CPU. The
            # ambient mesh's devices name the target; default_backend alone is
            # wrong under deviceless TPU AOT compilation (scripts/scale_proof.py
            # runs with a CPU host backend but lowers for a TPU topology, where
            # interpret mode would re-materialize the score matrices the kernel
            # exists to avoid).
            target = (
                flash_mesh.devices.flat[0].platform
                if flash_mesh is not None
                else jax.default_backend()
            )
            if flash_mesh is not None:
                out = flash_attention_sharded(
                    q.transpose(0, 2, 1, 3), kh, vh, kv_valid, True, scale, 128, 128,
                    target == "cpu", flash_mesh, BATCH_AXES, MODEL_AXIS,
                )
            else:
                out = flash_attention(
                    q.transpose(0, 2, 1, 3), kh, vh,
                    kv_valid, True, scale, 128, 128, target == "cpu",
                )
            out = out.transpose(0, 2, 1, 3).astype(c.compute_dtype)
        elif c.kv_heads != c.num_heads:
            # grouped-query einsum: batch scores over kv heads with the group as
            # a free axis — the old jnp.repeat path copied the whole K/V cache to
            # full head count every decode step, multiplying HBM traffic by
            # num_heads/kv_heads on exactly the GQA models it targets
            rep = c.num_heads // c.kv_heads
            qg = q.reshape(B, T, c.kv_heads, rep, c.dim_per_head)
            scores = jnp.einsum("btkrd,bksd->bkrts", qg, kh).astype(jnp.float32) * scale
            if k_row_scale is not None:
                scores = scores * k_row_scale[..., 0][:, :, None, None, :]
            bias = (
                mask_bias[:, :, None]
                if mask_bias.shape[1] == 1
                else mask_bias.reshape(B, c.kv_heads, rep, *mask_bias.shape[2:])
            )
            probs = jax.nn.softmax(scores + bias, axis=-1)
            if v_row_scale is not None:
                probs = probs * v_row_scale[..., 0][:, :, None, None, :]
            probs = probs.astype(c.compute_dtype)
            # btkrd order flattens to head h = k*rep + r, matching the q reshape
            out = jnp.einsum("bkrts,bksd->btkrd", probs, vh)
        else:
            # [B,H,T,S]
            scores = jnp.einsum("bthd,bhsd->bhts", q, kh).astype(jnp.float32) * scale
            if k_row_scale is not None:
                scores = scores * k_row_scale[..., 0][:, :, None, :]
            scores = scores + mask_bias
            probs = jax.nn.softmax(scores, axis=-1)
            if v_row_scale is not None:
                probs = probs * v_row_scale[..., 0][:, :, None, :]
            probs = probs.astype(c.compute_dtype)
            out = jnp.einsum("bhts,bhsd->bthd", probs, vh)
        out = out.reshape(B, T, c.num_heads * c.dim_per_head)
        out = dense(c.hidden_size, "o_proj", c.attn_bias, res_std)(out)
        return out, new_cache


class MLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = self.config
        dense = lambda feats, name, std=c.initializer_range: LoraDense(
            feats, use_bias=c.mlp_bias, dtype=c.compute_dtype, param_dtype=c.param_dtype,
            kernel_init=nn.initializers.normal(std), name=name,
            r=c.lora_r if name in c.lora_targets else 0, alpha=c.lora_alpha,
        )
        act = _act(c.activation)
        if c.glu:
            h = act(dense(c.ffn_dim, "gate_proj")(x)) * dense(c.ffn_dim, "up_proj")(x)
        else:
            h = act(dense(c.ffn_dim, "up_proj")(x))
        return dense(c.hidden_size, "down_proj", c.residual_init_std())(h)


class Block(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, mask_bias, positions, cache=None, kv_valid=None):
        c = self.config
        if c.parallel_residual:
            h1 = _norm_module(c, "ln_1")(x)
            h2 = h1 if c.shared_parallel_ln else _norm_module(c, "ln_2")(x)
            attn_out, new_cache = Attention(c, name="attn")(h1, mask_bias, positions, cache, kv_valid)
            mlp_out = MLP(c, name="mlp")(h2)
            out = x + attn_out + mlp_out
            if c.sequence_sharding and cache is None:
                out = constrain_seq(out)
            return out, new_cache
        attn_out, new_cache = Attention(c, name="attn")(
            _norm_module(c, "ln_1")(x), mask_bias, positions, cache, kv_valid
        )
        x = x + attn_out
        x = x + MLP(c, name="mlp")(_norm_module(c, "ln_2")(x))
        # per-layer Megatron-SP residual constraint lives HERE (not in the caller's
        # layer loop) so every path — listed loop, nn.scan stack, value branch,
        # forward_from — gets it identically
        if c.sequence_sharding and cache is None:
            x = constrain_seq(x)
        return x, new_cache


class TransformerLM(nn.Module):
    """Decoder-only LM. ``__call__`` returns (logits, final_hidden, branch_hidden,
    cache); ``forward_from`` re-runs the top layers from a branch activation (hydra)."""

    config: TransformerConfig

    def setup(self):
        c = self.config
        self.embed_tokens = nn.Embed(
            c.vocab_size, c.hidden_size, dtype=c.compute_dtype, param_dtype=c.param_dtype,
            embedding_init=nn.initializers.normal(c.initializer_range),
        )
        if c.embed_ln:
            self.embed_layernorm = _norm_module(c)
        if c.peft_type == "prompt" and c.num_virtual_tokens > 0:
            # prompt tuning: learned virtual-token embeddings prepended to the
            # input (parity: peft PROMPT_TUNING, modeling_base.py:162-240)
            self.prompt_embeddings = self.param(
                "prompt_embeddings", nn.initializers.normal(c.initializer_range),
                (c.num_virtual_tokens, c.hidden_size), c.param_dtype,
            )
        if c.pos_embedding == "learned":
            self.embed_positions = nn.Embed(
                c.max_position_embeddings + c.pos_offset, c.hidden_size,
                dtype=c.compute_dtype, param_dtype=c.param_dtype,
                embedding_init=nn.initializers.normal(c.initializer_range),
            )
        block = Block
        if c.remat != "none":
            block = nn.remat(Block, policy=remat_policy(c.remat))
        if c.stacked:
            if c.pipeline_stages > 1:
                if c.num_layers % c.pipeline_stages != 0:
                    raise ValueError(
                        f"num_layers={c.num_layers} not divisible by "
                        f"pipeline_stages={c.pipeline_stages}"
                    )
                if c.attention_impl == "ring":
                    raise ValueError(
                        "pipeline_stages > 1 cannot nest ring attention's shard_map; "
                        "use attention_impl='xla' or 'flash'"
                    )
                if c.sequence_sharding:
                    raise ValueError(
                        "pipeline_stages > 1 does not apply sequence-sharding "
                        "constraints inside the pipelined stack; set "
                        "sequence_sharding=False (the trainer does this automatically "
                        "when mesh.pipe > 1)"
                    )
            # stacked layout: one scanned Block whose params carry a leading
            # [num_layers] dim (sharded over "pipe" by the partition rules)
            self.layers_scan = nn.scan(
                block,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=(nn.broadcast, nn.broadcast, 0, nn.broadcast),
                out_axes=0,
                length=c.num_layers,
            )(c, name="layers_scan")
            self.layers = ()
        else:
            self.layers = [block(c) for _ in range(c.num_layers)]
        if c.final_norm:
            self.ln_f = _norm_module(c)
        if not c.tie_word_embeddings:
            self.lm_head = nn.Dense(
                c.vocab_size, use_bias=c.head_bias, dtype=c.compute_dtype, param_dtype=c.param_dtype,
                kernel_init=nn.initializers.normal(c.initializer_range),
            )

    def _final(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(logits, post-norm hidden)."""
        if self.config.final_norm:
            x = self.ln_f(x)
        if self.config.tie_word_embeddings:
            emb = self.embed_tokens.embedding.astype(self.config.compute_dtype)
            logits = x @ emb.T
        else:
            logits = self.lm_head(x)
        return logits, x

    def embed(self, input_ids: jnp.ndarray, positions: jnp.ndarray) -> jnp.ndarray:
        x = self.embed_tokens(input_ids)
        if self.config.pos_embedding == "learned":
            x = x + self.embed_positions(positions + self.config.pos_offset)
        if self.config.embed_ln:
            x = self.embed_layernorm(x)
        return x

    def __call__(
        self,
        input_ids: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray] = None,
        positions: Optional[jnp.ndarray] = None,
        cache: Optional[KVCache] = None,
        branch_layer: Optional[int] = None,
    ):
        """input_ids [B,T]; attention_mask [B,T] (1=real token). With ``cache``,
        T may be 1 (decode step) and the mask must cover the cache length [B,S].
        Returns (logits [B,T,V], hidden [B,T,Hid] post-norm, branch_hidden or None,
        new cache or None). ``branch_layer`` = index of the first *unfrozen* layer;
        its input activation is returned for the hydra reference branch."""
        c = self.config
        B, T = input_ids.shape
        nv = c.num_virtual_tokens if c.peft_type == "prompt" else 0
        # prompt tuning prepends nv virtual rows internally; the external
        # contract (T-length outputs, T/S-length masks) is preserved by
        # extending masks here and slicing logits/hidden before returning.
        # Virtual rows occupy slots/positions 0..nv-1; real positions shift +nv.
        nv_rows = 0  # virtual rows present in this forward's activations
        if cache is not None:
            ck = cache["k"]
            # list layout: per-layer [B,H,S,D]; stacked layout: [L,B,H,S,D]
            S = ck[0].shape[2] if isinstance(ck, (list, tuple)) else ck.shape[3]
            idx = cache["index"]
            # a concrete-zero index marks prefill-from-zero (any T, including 1);
            # a traced index is a decode step inside the generation while_loop
            prompt_prefill = nv > 0 and _concrete_zero(idx)
            if nv > 0 and not (prompt_prefill or T == 1):
                raise ValueError(
                    "prompt-tuning cached forwards support only prefill-from-zero "
                    "or single-token decode steps"
                )
            ext_mask = attention_mask
            if nv and attention_mask is not None:
                ext_mask = jnp.concatenate(
                    [jnp.ones((B, nv), attention_mask.dtype), attention_mask], axis=1
                )
            if positions is None:
                # auto-derived decode positions come from the cache index, which
                # already counts the nv virtual slots — shift only at prefill
                base = idx + jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
                int_positions = base + nv if prompt_prefill else base
            else:
                int_positions = positions + nv if nv else positions
            nv_rows = nv if prompt_prefill else 0
            T_eff = T + nv_rows
            # Causal structure over cache *slots*: slots are written in temporal
            # order, so slot index ordering == temporal ordering even with left
            # padding (where position values repeat under the pad mask).
            kv_slot = jnp.arange(S)[None, None, None, :]
            q_slot = (idx + jnp.arange(T_eff, dtype=jnp.int32))[None, None, :, None]
            causal = kv_slot <= q_slot
            if ext_mask is not None:
                causal = jnp.logical_and(causal, ext_mask[:, None, None, :].astype(bool))
            mask_bias = jnp.where(causal, 0.0, -1e9).astype(jnp.float32)
            if c.pos_embedding == "alibi":
                mask_bias = mask_bias + alibi_bias(c, ext_mask, B, S)
            x = self.embed(input_ids, int_positions)
            layer_positions = int_positions
            if nv_rows:
                virt_pos = jnp.broadcast_to(jnp.arange(nv, dtype=jnp.int32)[None, :], (B, nv))
                layer_positions = jnp.concatenate([virt_pos, int_positions], axis=1)
                pe = jnp.broadcast_to(
                    self.prompt_embeddings.astype(x.dtype)[None], (B, nv, c.hidden_size)
                )
                x = jnp.concatenate([pe, x], axis=1)
            if T_eff > 1 and ext_mask is not None and _concrete_zero(idx):
                # generation prefill: the cache is written from slot 0, so the
                # flash path may attend over the prefix k/v alone. The
                # concrete-zero check must happen HERE, outside the remat
                # wrapper around the blocks: nn.remat turns every cache leaf —
                # including a Python-int index — into a tracer, so a check
                # inside Attention can never see the concrete 0 and would
                # silently disable flash prefill whenever remat is on.
                kv_valid = ext_mask[:, :T_eff]
            else:
                kv_valid = None
        else:
            mask_in = attention_mask
            if nv:
                nv_rows = nv
                if mask_in is None:
                    mask_in = jnp.ones((B, T), jnp.int32)
                ext_mask = jnp.concatenate([jnp.ones((B, nv), mask_in.dtype), mask_in], axis=1)
                default_positions, mask_bias = make_attn_bias(c, ext_mask, B, T + nv)
                int_positions = default_positions[:, nv:] if positions is None else positions + nv
                layer_positions = jnp.concatenate([default_positions[:, :nv], int_positions], axis=1)
                pe = jnp.broadcast_to(
                    self.prompt_embeddings.astype(c.compute_dtype)[None], (B, nv, c.hidden_size)
                )
                x = jnp.concatenate([pe, self.embed(input_ids, int_positions)], axis=1)
                kv_valid = ext_mask
            else:
                default_positions, mask_bias = make_attn_bias(c, attention_mask, B, T)
                if positions is None:
                    positions = default_positions
                x = self.embed(input_ids, positions)
                layer_positions = positions
                kv_valid = attention_mask
        # branch_layer: int -> return that single activation; tuple -> dict of them
        capture_set = ()
        if branch_layer is not None:
            capture_set = branch_layer if isinstance(branch_layer, tuple) else (branch_layer,)
        seq_shard = c.sequence_sharding and cache is None
        if seq_shard:
            x = constrain_seq(x)
        captures = {}
        if c.stacked:
            if capture_set:
                raise NotImplementedError(
                    "stacked/pipelined models do not support hydra branch capture "
                    "(per-layer activations are internal to the stage scan); use a "
                    "separate reference model (num_layers_unfrozen=-1) and "
                    "num_value_layers_unfrozen=0"
                )
            x, stacked_kv = self._apply_stacked(x, mask_bias, layer_positions, cache, kv_valid)
        else:
            new_layer_caches = []
            for i, layer in enumerate(self.layers):
                if i in capture_set:
                    captures[i] = x
                layer_cache = None
                if cache is not None:
                    layer_cache = {
                        key: cache[key][i] for key in cache if key != "index"
                    }
                    layer_cache["index"] = cache["index"]
                x, new_lc = layer(x, mask_bias, layer_positions, layer_cache, kv_valid)
                if cache is not None:
                    new_layer_caches.append(new_lc)
            stacked_kv = None
            if cache is not None:
                # keep the per-layer list layout (no jnp.stack: restacking would
                # copy the full cache every decode step)
                stacked_kv = {
                    key: [lc[key] for lc in new_layer_caches]
                    for key in new_layer_caches[0]
                }
        if seq_shard:
            # gather the sequence dim before heads (Megatron's
            # gather_from_sequence_parallel_region analogue)
            x = constrain_gathered(x)
        logits, hidden = self._final(x)
        if nv_rows:  # drop virtual rows: external output shape is [B, T, ...]
            logits = logits[:, nv_rows:]
            hidden = hidden[:, nv_rows:]
        new_cache = None
        if cache is not None:
            if c.stacked:
                # re-pin the written cache's layout: the decode while_loop's
                # carry sharding follows the BODY output, and unpinned it
                # reverts to GSPMD's choice (replicated over pipe — see
                # _constrain_cache_leaf)
                stacked_kv = {
                    k: self._constrain_cache_leaf(v, stacked=True)
                    for k, v in stacked_kv.items()
                }
            new_cache = {**stacked_kv, "index": cache["index"] + T + nv_rows}
        if branch_layer is not None and not isinstance(branch_layer, tuple):
            branch_out = captures.get(branch_layer)
        else:
            branch_out = captures if isinstance(branch_layer, tuple) else None
        return logits, hidden, branch_out, new_cache

    def _apply_stacked(self, x, mask_bias, positions, cache, kv_valid):
        """Run the stacked block stack (``pipeline_stages > 1`` or ``scan_layers`` layout).

        Cached decode → sequential ``nn.scan`` over the stacked params (each
        layer's shard is streamed to where it's needed; the NeMo reference
        likewise drops pipeline scheduling for inference,
        modeling_nemo_ppo.py:838-870). Cache-free forwards → the GPipe
        microbatch schedule over the mesh's ``pipe`` axis when one is active.
        Returns (x, stacked_kv or None)."""
        c = self.config
        if cache is not None:
            scan_cache = {key: cache[key] for key in cache if key != "index"}
            scan_cache["index"] = jnp.broadcast_to(cache["index"], (c.num_layers,))
            x, ys = self.layers_scan(x, mask_bias, positions, scan_cache, kv_valid)
            return x, ys
        if not self.is_initializing():
            mesh = ambient_mesh()
            if mesh is not None and mesh.shape.get(PIPE_AXIS, 1) > 1:
                from trlx_tpu.parallel.pipeline import pipeline_apply

                stack = self.variables["params"]["layers_scan"]
                x = pipeline_apply(c, stack, x, mask_bias, positions, kv_valid, mesh)
                return x, None
        x, _ = self.layers_scan(x, mask_bias, positions, None, kv_valid)
        return x, None

    def forward_from(
        self,
        hidden: jnp.ndarray,
        attention_mask: Optional[jnp.ndarray],
        positions: Optional[jnp.ndarray],
        start_layer: int,
    ):
        """Run layers[start_layer:] + final norm + lm head from a branch activation.
        This is the hydra frozen-branch forward (reference ``forward_hydra``,
        modeling_ppo.py:410-453) — called with the frozen param subtree via
        ``apply({"params": frozen}, ..., method="forward_from")``."""
        if self.config.stacked:
            raise NotImplementedError(
                "hydra branch forwards need per-layer params; stacked models "
                "use a separate reference model (num_layers_unfrozen=-1)"
            )
        B, T, _ = hidden.shape
        default_positions, mask_bias = make_attn_bias(self.config, attention_mask, B, T)
        if positions is None:
            positions = default_positions
        x = hidden
        for layer in self.layers[start_layer:]:
            x, _ = layer(x, mask_bias, positions, None, attention_mask)
        logits, _ = self._final(x)
        return logits

    def _constrain_cache_leaf(self, x: jnp.ndarray, stacked: bool) -> jnp.ndarray:
        """Pin the KV-cache layout over the mesh. Stacked decode ([L, B, H, ...]
        leaves) runs a sequential layer scan on EVERY device, so the layer dim
        must stay local — decode under pipeline layouts is pure data
        parallelism over `pipe`: batch shards over (pipe, data, fsdp), kv heads
        over `model`. Left to GSPMD propagation the cache came back REPLICATED
        over pipe (17.5G/device at 7B decode batch 128), and sharding the LAYER
        dim over pipe instead makes the scan all-gather the whole cache (both
        measured by the v5e compiler, scripts/scale_proof.py). No-op outside a
        mesh context; non-divisible dims are dropped."""
        mesh = ambient_mesh()
        if mesh is None:
            return x
        from trlx_tpu.parallel.sharding import _clip_spec
        from jax.sharding import NamedSharding, PartitionSpec

        batch_entry = ((PIPE_AXIS,) + BATCH_AXES) if stacked else BATCH_AXES
        entries = ([None] if stacked else []) + [batch_entry, MODEL_AXIS]
        entries += [None] * (x.ndim - len(entries))
        spec = _clip_spec(PartitionSpec(*entries), x.shape, mesh)
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    def init_cache(self, batch_size: int, max_length: int, dtype=None) -> KVCache:
        c = self.config
        dtype = dtype or c.compute_dtype
        if c.peft_type == "prompt":
            max_length += c.num_virtual_tokens  # virtual rows live in the cache too
        shape = (batch_size, c.kv_heads, max_length, c.dim_per_head)
        per_layer = kv_cache_layout(shape, dtype, c.kv_cache_quant)
        if c.stacked:
            # nn.scan layout needs one [L, ...] array per k/v
            out = {
                key: self._constrain_cache_leaf(
                    jnp.zeros((c.num_layers,) + shp, dt), stacked=True
                )
                for key, (shp, dt) in per_layer.items()
            }
            out["index"] = jnp.array(0, jnp.int32)
            return out
        # Per-layer list layout: the decode while_loop then carries each layer's
        # buffer as its own carry leaf, so the per-step dynamic_update_slice is a
        # true in-place single-token write. A single stacked [L, ...] array forces
        # XLA to slice out every layer and re-stack the WHOLE cache each step —
        # profiled at 3.6ms of a 4.65ms gpt2-124M decode step on one v5e chip
        # (~15x the HBM bound for this model).
        out = {
            key: [jnp.zeros(shp, dt) for _ in range(c.num_layers)]
            for key, (shp, dt) in per_layer.items()
        }
        out["index"] = jnp.array(0, jnp.int32)
        return out

    def init_paged_cache(
        self, num_blocks: int, block_size: int, max_blocks_per_seq: int,
        batch_size: int, dtype=None,
    ) -> KVCache:
        """Block-pool cache for the serving engine (see ops/paged_attention.py):
        per-layer k/v pools ``[num_blocks, block_size, Hkv, D]`` (int8 + f32
        row scales under ``kv_cache_quant``) plus shared ``block_tables``
        ``[B, max_blocks_per_seq]`` and ``context_lens`` ``[B]``. Block 0 is
        the allocator's reserved null block; fresh tables point at it."""
        from trlx_tpu.ops.paged_attention import paged_pool_layout

        c = self.config
        layout = paged_pool_layout(
            num_blocks, block_size, c.kv_heads, c.dim_per_head,
            dtype or c.compute_dtype, c.kv_cache_quant,
        )
        if c.stacked:
            # nn.scan layout: one stacked [L, ...] pool per k/v leaf, walked by
            # paged_verify's layer scan (paged_decode keeps the per-layer list
            # restriction — see its docstring)
            out = {
                key: jnp.zeros((c.num_layers,) + shp, dt)
                for key, (shp, dt) in layout.items()
            }
        else:
            out = {
                key: [jnp.zeros(shp, dt) for _ in range(c.num_layers)]
                for key, (shp, dt) in layout.items()
            }
        out["block_tables"] = jnp.zeros((batch_size, max_blocks_per_seq), jnp.int32)
        out["context_lens"] = jnp.zeros((batch_size,), jnp.int32)
        return out

    def paged_decode(self, input_ids: jnp.ndarray, cache: KVCache):
        """One decode step against the paged block-pool cache: ``input_ids``
        [B, 1], ``cache`` from :meth:`init_paged_cache` (pools possibly
        populated by the serving engine's prefill scatter). Returns
        (logits [B, 1, V], hidden [B, 1, Hid], new cache with
        ``context_lens`` advanced by 1). Idle slots (context_lens == 0 with a
        null block table row) still produce finite output — the engine
        discards it."""
        c = self.config
        if c.stacked:
            raise NotImplementedError("paged decode: per-layer list layout only")
        if c.peft_type in ("prompt", "prefix"):
            raise NotImplementedError("paged decode does not support peft prompt/prefix")
        B, T = input_ids.shape
        if T != 1:
            raise ValueError(
                "paged_decode is a single-token step; use paged_verify for "
                "multi-token appends"
            )
        lens = cache["context_lens"]
        positions = lens[:, None].astype(jnp.int32)  # incoming token's position
        x = self.embed(input_ids, positions)
        pool_keys = [k for k in cache if k not in ("block_tables", "context_lens")]
        new_layer_caches = []
        for i, layer in enumerate(self.layers):
            layer_cache = {key: cache[key][i] for key in pool_keys}
            layer_cache["block_tables"] = cache["block_tables"]
            layer_cache["context_lens"] = lens
            x, new_lc = layer(x, None, positions, layer_cache, None)
            new_layer_caches.append(new_lc)
        logits, hidden = self._final(x)
        new_cache = {
            key: [lc[key] for lc in new_layer_caches] for key in pool_keys
        }
        new_cache["block_tables"] = cache["block_tables"]
        new_cache["context_lens"] = lens + 1
        return logits, hidden, new_cache

    def paged_verify(self, input_ids: jnp.ndarray, cache: KVCache):
        """Multi-token paged step (speculative verify / chunked prefill):
        ``input_ids`` [B, Q]; token j is written through the block table at
        position ``context_lens + j`` and attends causally over every earlier
        position plus itself. Returns (logits [B, Q, V], hidden [B, Q, Hid],
        new cache with ``context_lens`` UNCHANGED) — the caller decides how
        far the frontier actually advances (speculative accept count, chunk
        length); KV rows written past the accepted frontier stay invisible to
        the attention mask and are rewritten before they can ever become
        valid, which is what makes rollback free. Supports both the per-layer
        list layout and the stacked ``scan_layers`` layout (pools ``[L, ...]``,
        walked by the layer scan with the table/lens broadcast across L)."""
        c = self.config
        if c.peft_type in ("prompt", "prefix"):
            raise NotImplementedError("paged verify does not support peft prompt/prefix")
        B, Q = input_ids.shape
        lens = cache["context_lens"]
        positions = lens[:, None].astype(jnp.int32) + jnp.arange(Q, dtype=jnp.int32)[None, :]
        x = self.embed(input_ids, positions)
        pool_keys = [k for k in cache if k not in ("block_tables", "context_lens")]
        if c.stacked:
            scan_cache = {key: cache[key] for key in pool_keys}
            scan_cache["block_tables"] = jnp.broadcast_to(
                cache["block_tables"], (c.num_layers,) + cache["block_tables"].shape
            )
            scan_cache["context_lens"] = jnp.broadcast_to(
                lens, (c.num_layers,) + lens.shape
            )
            x, ys = self.layers_scan(x, None, positions, scan_cache, None)
            new_cache = {key: ys[key] for key in pool_keys}
        else:
            new_layer_caches = []
            for i, layer in enumerate(self.layers):
                layer_cache = {key: cache[key][i] for key in pool_keys}
                layer_cache["block_tables"] = cache["block_tables"]
                layer_cache["context_lens"] = lens
                x, new_lc = layer(x, None, positions, layer_cache, None)
                new_layer_caches.append(new_lc)
            new_cache = {
                key: [lc[key] for lc in new_layer_caches] for key in pool_keys
            }
        new_cache["block_tables"] = cache["block_tables"]
        new_cache["context_lens"] = lens
        logits, hidden = self._final(x)
        return logits, hidden, new_cache
