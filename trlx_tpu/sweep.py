"""Hyperparameter sweep CLI (capability parity with `/root/reference/trlx/sweep.py:17-348`).

The reference drives Ray Tune (random/grid search + ASHA-style schedulers over
dotted ``train.*``/``method.*`` params) and writes a W&B report. Ray is not part
of this image's baked dependencies, so the executor here is a local process
runner over the same sweep-config format with the same capabilities:

- random | grid trial generation over dotted parameter paths;
- ``--max-concurrent N`` parallel trial subprocesses;
- an asynchronous successive-halving (ASHA) scheduler: trials report
  intermediate metrics (``SWEEP_METRIC`` lines emitted by the trainers at each
  eval) and under-performers are stopped early via a stop FILE the trainer
  polls — never a signal, because killing a jax process mid-TPU-claim can
  wedge the chip tunnel;
- a jsonl results summary plus a markdown report of all trials
  (the local stand-in for the reference's W&B report, sweep.py:267-348).

Sweep config YAML format (same shape as the reference's):

    tune_config:
      mode: "max"
      metric: "reward/mean"
      search_alg: "random"      # or "grid"
      num_samples: 8
      scheduler: "asha"         # optional; "none" default
      grace_steps: 100          # first ASHA rung (in trainer steps)
      reduction_factor: 3       # eta
    method.init_kl_coef:
      strategy: "loguniform"
      values: [0.0001, 0.1]
    train.seed:
      strategy: "choice"
      values: [1000, 1001, 1002]

Usage: ``python -m trlx_tpu.sweep --config sweep.yml script.py``
"""

import argparse
import itertools
import json
import math
import os
import queue
import random
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import yaml


def generate_trials(sweep_config: Dict[str, Any], seed: int = 0) -> List[Dict[str, Any]]:
    tune = sweep_config.get("tune_config", {})
    params = {k: v for k, v in sweep_config.items() if k != "tune_config"}
    rng = random.Random(seed)

    def sample(spec):
        strategy = spec["strategy"]
        values = spec["values"]
        if strategy == "choice":
            return rng.choice(values)
        if strategy == "uniform":
            return rng.uniform(values[0], values[1])
        if strategy == "loguniform":
            import math

            return math.exp(rng.uniform(math.log(values[0]), math.log(values[1])))
        if strategy == "int":
            return rng.randint(values[0], values[1])
        raise ValueError(f"Unknown strategy {strategy}")

    search = tune.get("search_alg", "random")
    if search == "grid":
        keys = list(params)
        grids = [params[k]["values"] for k in keys]
        return [dict(zip(keys, combo)) for combo in itertools.product(*grids)]
    num_samples = int(tune.get("num_samples", 4))
    return [{k: sample(v) for k, v in params.items()} for _ in range(num_samples)]


class _Trial:
    def __init__(self, idx: int, hparams: Dict[str, Any], stop_path: str):
        self.idx = idx
        self.hparams = hparams
        self.stop_path = stop_path
        self.proc: Optional[subprocess.Popen] = None
        self.t0 = 0.0
        self.final_metrics: Optional[Dict[str, Any]] = None
        self.history: List[Dict[str, Any]] = []  # SWEEP_METRIC records
        self.reported_rungs: set = set()
        self.early_stopped = False
        self.returncode: Optional[int] = None
        self.seconds: Optional[float] = None
        self.stderr_path = stop_path + ".stderr"
        self.stderr_file = None


class AshaScheduler:
    """Asynchronous successive halving: when a trial reports a metric at rung
    budget grace*eta^k, it is stopped unless it ranks in the top 1/eta of the
    values seen so far at that rung (parity with Ray Tune's ASHAScheduler used
    by the reference, sweep.py:300-320)."""

    def __init__(self, metric: str, mode: str, grace_steps: int, eta: int, max_rungs: int = 10):
        self.metric = metric
        self.sign = 1.0 if mode == "max" else -1.0
        self.eta = max(2, int(eta))
        self.rungs = [grace_steps * self.eta ** k for k in range(max_rungs)]
        self.rung_scores: Dict[int, List[float]] = {r: [] for r in self.rungs}

    def on_metric(self, trial: _Trial, step: int, metrics: Dict[str, Any]) -> bool:
        """Record a report; returns True if the trial should be stopped.

        Like Ray's ASHA, a report is credited to at most ONE rung per event —
        the smallest uncredited rung whose budget has been reached — so a late
        report cannot seed several early rungs with an extra-training value."""
        value = metrics.get(self.metric)
        if value is None:
            return False
        for rung in self.rungs:
            if rung in trial.reported_rungs:
                continue
            if step < rung:
                break
            trial.reported_rungs.add(rung)
            scores = self.rung_scores[rung]
            scores.append(self.sign * float(value))
            if len(scores) >= self.eta:
                top_k = max(1, math.ceil(len(scores) / self.eta))
                cutoff = sorted(scores, reverse=True)[top_k - 1]
                if self.sign * float(value) < cutoff:
                    return True
            break
        return False


def _reader(trial: _Trial, events: "queue.Queue"):
    """Stream a trial's stdout, forwarding metric lines as events. The exit
    event is guaranteed even if reading raises (e.g. a decode error from
    non-UTF-8 trial output) — otherwise run_trials would wait forever."""
    try:
        for line in trial.proc.stdout:
            line = line.strip()
            if line.startswith("SWEEP_METRIC "):
                try:
                    events.put(("metric", trial, json.loads(line[len("SWEEP_METRIC "):])))
                except json.JSONDecodeError:
                    pass
            elif line.startswith("SWEEP_RESULT "):
                try:
                    trial.final_metrics = json.loads(line[len("SWEEP_RESULT "):])
                except json.JSONDecodeError:
                    pass
    finally:
        trial.proc.wait()
        events.put(("exit", trial, None))


def run_trials(
    script: str,
    trials: List[Dict[str, Any]],
    out_path: str,
    metric: str,
    mode: str,
    max_concurrent: int = 1,
    scheduler: Optional[AshaScheduler] = None,
    report_path: Optional[str] = None,
    extra_env: Optional[Dict[str, str]] = None,
):
    records: List[_Trial] = [
        _Trial(i, hp, out_path + f".stop{i}") for i, hp in enumerate(trials)
    ]
    pending = list(records)
    running: Dict[int, _Trial] = {}
    events: "queue.Queue" = queue.Queue()

    def launch(trial: _Trial):
        print(f"[sweep] trial {trial.idx + 1}/{len(trials)}: {trial.hparams}", flush=True)
        env = dict(os.environ, TRLX_SWEEP="1", TRLX_SWEEP_STOP_FILE=trial.stop_path)
        env.update(extra_env or {})
        if os.path.exists(trial.stop_path):
            os.remove(trial.stop_path)
        trial.t0 = time.time()
        trial.stderr_file = open(trial.stderr_path, "w")
        trial.proc = subprocess.Popen(
            [sys.executable, script, json.dumps(trial.hparams)],
            stdout=subprocess.PIPE, stderr=trial.stderr_file, text=True, env=env,
        )
        running[trial.idx] = trial
        threading.Thread(target=_reader, args=(trial, events), daemon=True).start()

    while pending and len(running) < max_concurrent:
        launch(pending.pop(0))

    try:
        _event_loop(
            script, trials, out_path, metric, mode, records, pending, running,
            events, scheduler, launch,
        )
    finally:
        # on any abort (Ctrl-C, scheduler error): ask surviving trials to stop
        # via their stop files — the only sanctioned way to end a jax trial
        # (signals can wedge a TPU chip claim) — and give them a grace period
        for trial in list(running.values()):
            try:
                with open(trial.stop_path, "w") as f:
                    f.write("sweep-aborted")
            except OSError:
                pass
        for trial in list(running.values()):
            try:
                trial.proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                print(f"[sweep] trial {trial.idx} still running after abort request", flush=True)

    _write_results(records, out_path)
    scored = [t for t in records if (t.final_metrics or {}).get(metric) is not None]
    best = None
    if scored:
        best = (max if mode == "max" else min)(scored, key=lambda t: t.final_metrics[metric])
        print(
            f"[sweep] best trial: {best.idx} {metric}={best.final_metrics[metric]} {best.hparams}"
        )
    if report_path:
        _write_report(report_path, records, metric, mode, best)
    return [_record_dict(t) for t in records]


def _event_loop(script, trials, out_path, metric, mode, records, pending, running, events, scheduler, launch):
    while running:
        kind, trial, payload = events.get()
        if kind == "metric":
            trial.history.append(payload)
            if (
                scheduler is not None
                and not trial.early_stopped  # ignore post-stop reports
                and scheduler.on_metric(trial, int(payload.get("step", 0)), payload)
            ):
                # ask the trainer to stop at its next eval; never signal the process
                with open(trial.stop_path, "w") as f:
                    f.write("asha-stop")
                trial.early_stopped = True
                print(f"[sweep] ASHA stopping trial {trial.idx} at step {payload.get('step')}", flush=True)
        elif kind == "exit":
            trial.returncode = trial.proc.returncode
            trial.seconds = round(time.time() - trial.t0, 1)
            running.pop(trial.idx, None)
            if trial.stderr_file is not None:
                trial.stderr_file.close()
            cleanup = [trial.stop_path]
            if trial.returncode == 0:
                cleanup.append(trial.stderr_path)  # kept only for failure triage
            for path in cleanup:
                if os.path.exists(path):
                    os.remove(path)
            print(
                f"[sweep] trial {trial.idx} finished rc={trial.returncode} "
                f"({trial.seconds}s{', early-stopped' if trial.early_stopped else ''})",
                flush=True,
            )
            _write_results(records, out_path)
            if pending:
                launch(pending.pop(0))


def _record_dict(t: _Trial) -> Dict[str, Any]:
    rec = {
        "trial": t.idx,
        "hparams": t.hparams,
        "returncode": t.returncode,
        "early_stopped": t.early_stopped,
        "num_reports": len(t.history),
        "seconds": t.seconds,
    }
    if t.final_metrics is not None:
        rec["metrics"] = t.final_metrics
    if t.returncode not in (0, None) and os.path.exists(t.stderr_path):
        with open(t.stderr_path, "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() - 2000))
            rec["stderr_tail"] = f.read().decode(errors="replace")
    return rec


def _write_results(records: List[_Trial], out_path: str):
    with open(out_path, "w") as f:
        for t in records:
            f.write(json.dumps(_record_dict(t)) + "\n")


def _write_report(path: str, records: List[_Trial], metric: str, mode: str, best: Optional[_Trial]):
    """Markdown trial report — local counterpart of the reference's W&B report
    (sweep.py:267-348)."""
    keys = sorted({k for t in records for k in t.hparams})
    lines = ["# Sweep report", ""]
    if best is not None:
        lines += [f"**Best trial**: #{best.idx} with {metric} = {best.final_metrics[metric]} ({mode})", ""]
    lines += ["| trial | " + " | ".join(keys) + f" | {metric} | reports | status |",
              "|" + "---|" * (len(keys) + 4)]
    for t in records:
        val = (t.final_metrics or {}).get(metric, "—")
        status = "early-stopped" if t.early_stopped else ("failed" if t.returncode else "done")
        lines.append(
            f"| {t.idx} | "
            + " | ".join(str(t.hparams.get(k, "")) for k in keys)
            + f" | {val} | {len(t.history)} | {status} |"
        )
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def main():
    parser = argparse.ArgumentParser(description="trlx_tpu hyperparameter sweep")
    parser.add_argument("script", help="training script accepting a JSON hparams argv[1]")
    parser.add_argument("--config", required=True, help="sweep config yaml")
    parser.add_argument("--output", default="sweep_results.jsonl")
    parser.add_argument("--report", default=None, help="markdown report path")
    parser.add_argument("--max-concurrent", type=int, default=None,
                        help="parallel trial processes (default: tune_config or 1; "
                        "keep 1 on a single TPU chip — only one process may hold it)")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()

    with open(args.config) as f:
        sweep_config = yaml.safe_load(f)
    tune = sweep_config.get("tune_config", {})
    trials = generate_trials(sweep_config, args.seed)
    metric = tune.get("metric", "reward/mean")
    mode = tune.get("mode", "max")
    scheduler = None
    if str(tune.get("scheduler", "none")).lower() == "asha":
        scheduler = AshaScheduler(
            metric, mode,
            grace_steps=int(tune.get("grace_steps", 100)),
            eta=int(tune.get("reduction_factor", 3)),
        )
    max_concurrent = args.max_concurrent or int(tune.get("max_concurrent", 1))
    run_trials(
        args.script, trials, args.output, metric, mode,
        max_concurrent=max_concurrent, scheduler=scheduler,
        report_path=args.report or os.path.splitext(args.output)[0] + ".md",
    )


if __name__ == "__main__":
    main()
