"""Hyperparameter sweep CLI (capability parity with `/root/reference/trlx/sweep.py:17-348`).

The reference drives Ray Tune (random/grid search over dotted ``train.*``/``method.*``
params) and writes a W&B report. Ray is not part of this image's baked dependencies,
so the executor here is a local sequential/process runner over the same sweep-config
format (random | grid over dotted parameter paths); results land in a jsonl summary
(plus wandb when available). A Ray backend can be slotted in by replacing
``run_trials`` — the trial generation/reporting layer is executor-agnostic.

Sweep config YAML format (same shape as the reference's):

    tune_config:
      mode: "max"
      metric: "reward/mean"
      search_alg: "random"      # or "grid"
      num_samples: 8
    method.init_kl_coef:
      strategy: "loguniform"
      values: [0.0001, 0.1]
    train.seed:
      strategy: "choice"
      values: [1000, 1001, 1002]

Usage: ``python -m trlx_tpu.sweep --config sweep.yml script.py``
"""

import argparse
import importlib.util
import itertools
import json
import os
import random
import subprocess
import sys
import time
from typing import Any, Dict, List

import yaml


def generate_trials(sweep_config: Dict[str, Any], seed: int = 0) -> List[Dict[str, Any]]:
    tune = sweep_config.get("tune_config", {})
    params = {k: v for k, v in sweep_config.items() if k != "tune_config"}
    rng = random.Random(seed)

    def sample(spec):
        strategy = spec["strategy"]
        values = spec["values"]
        if strategy == "choice":
            return rng.choice(values)
        if strategy == "uniform":
            return rng.uniform(values[0], values[1])
        if strategy == "loguniform":
            import math

            return math.exp(rng.uniform(math.log(values[0]), math.log(values[1])))
        if strategy == "int":
            return rng.randint(values[0], values[1])
        raise ValueError(f"Unknown strategy {strategy}")

    search = tune.get("search_alg", "random")
    if search == "grid":
        keys = list(params)
        grids = [params[k]["values"] for k in keys]
        return [dict(zip(keys, combo)) for combo in itertools.product(*grids)]
    num_samples = int(tune.get("num_samples", 4))
    return [{k: sample(v) for k, v in params.items()} for _ in range(num_samples)]


def run_trials(script: str, trials: List[Dict[str, Any]], out_path: str, metric: str, mode: str):
    results = []
    for i, hparams in enumerate(trials):
        print(f"[sweep] trial {i + 1}/{len(trials)}: {hparams}", flush=True)
        t0 = time.time()
        env = dict(os.environ, TRLX_SWEEP="1")
        proc = subprocess.run(
            [sys.executable, script, json.dumps(hparams)],
            capture_output=True, text=True, env=env,
        )
        record = {
            "trial": i,
            "hparams": hparams,
            "returncode": proc.returncode,
            "seconds": round(time.time() - t0, 1),
        }
        # scripts print 'SWEEP_RESULT {json}' on their last line to report metrics
        for line in reversed(proc.stdout.splitlines()):
            if line.startswith("SWEEP_RESULT "):
                record["metrics"] = json.loads(line[len("SWEEP_RESULT "):])
                break
        if proc.returncode != 0:
            record["stderr_tail"] = proc.stderr[-2000:]
        results.append(record)
        with open(out_path, "w") as f:
            for r in results:
                f.write(json.dumps(r) + "\n")

    scored = [r for r in results if r.get("metrics", {}).get(metric) is not None]
    if scored:
        best = (max if mode == "max" else min)(scored, key=lambda r: r["metrics"][metric])
        print(f"[sweep] best trial: {best['trial']} {metric}={best['metrics'][metric]} {best['hparams']}")
    return results


def main():
    parser = argparse.ArgumentParser(description="trlx_tpu hyperparameter sweep")
    parser.add_argument("script", help="training script accepting a JSON hparams argv[1]")
    parser.add_argument("--config", required=True, help="sweep config yaml")
    parser.add_argument("--output", default="sweep_results.jsonl")
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()

    with open(args.config) as f:
        sweep_config = yaml.safe_load(f)
    tune = sweep_config.get("tune_config", {})
    trials = generate_trials(sweep_config, args.seed)
    run_trials(args.script, trials, args.output, tune.get("metric", "reward/mean"), tune.get("mode", "max"))


if __name__ == "__main__":
    main()
