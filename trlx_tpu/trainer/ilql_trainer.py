"""ILQL trainer (parity: `/root/reference/trlx/trainer/accelerate_ilql_trainer.py`):
offline experience building (returns standardization, last-action reward, action/state
index bookkeeping), the ILQL loss driver, periodic Polyak target-Q sync, and the
advantage-shaped generation used at evaluation.
"""

from typing import Dict

import numpy as np

import jax
import jax.numpy as jnp

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.ilql_types import ILQLBatch
from trlx_tpu.methods.ilql import ILQLConfig, batched_index_select
from trlx_tpu.models.hf_loading import load_pretrained
from trlx_tpu.models.heads import sync_target_q_heads as _sync_heads
from trlx_tpu.models.policy import CausalLMWithILQLHeads
from trlx_tpu.models.transformer import TransformerLM
from trlx_tpu.ops.generation import pad_to_bucket
from trlx_tpu.parallel import mesh as mesh_lib
from trlx_tpu.parallel.sharding import make_param_shardings
from trlx_tpu.pipeline.offline_pipeline import ILQLRolloutStorage, tokenize_dialogue
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.mesh_trainer import MeshRLTrainer
from trlx_tpu.utils import logging
from trlx_tpu.utils.modeling import flatten_dict

logger = logging.get_logger(__name__)

BUCKETS = [2 ** i for i in range(2, 14)]


def make_experience(samples, rewards, tokenizer=None, max_length: int = 2048,
                    verbose: bool = True) -> ILQLRolloutStorage:
    """Tokenize dialogues and compute ILQL index bookkeeping (parity:
    accelerate_ilql_trainer.py:30-100): per-sample ``actions_ixs`` = positions whose
    *next* token is an output token; ``states_ixs`` = actions + terminal; rewards are
    standardized returns placed on the last action."""
    if verbose:
        logger.info("Collecting rollouts")
    if tokenizer is not None:
        samples = [tokenize_dialogue(s, tokenizer, max_length) for s in samples]

    all_input_ids, all_actions_ixs, all_states_ixs, all_dones = [], [], [], []
    for sample in samples:
        length = 0
        input_ids = np.asarray([t for msg in sample for t in msg.tokens], np.int32)
        all_input_ids.append(input_ids)
        actions_ixs = []
        for dm in sample:
            if dm.is_output:
                actions_ixs.append(np.arange(length - 1, length + len(dm.tokens) - 1))
            length += len(dm.tokens)
        states_ixs = np.concatenate([*actions_ixs, [length - 1]])
        all_dones.append(np.asarray([1] * (len(states_ixs) - 1) + [0], np.int32))
        all_actions_ixs.append(np.concatenate(actions_ixs).astype(np.int32))
        all_states_ixs.append(states_ixs.astype(np.int32))

    returns = np.asarray(rewards, np.float64)
    returns = returns - returns.mean()
    std = returns.std()
    if not np.isnan(std) and std > 0:
        returns = returns / (std + np.finfo(returns.dtype).eps)
    rewards_per_token = [np.zeros(len(x), np.float32) for x in all_actions_ixs]
    for rs, ret in zip(rewards_per_token, returns):
        rs[-1] = ret

    attention_mask = [np.ones(len(x), np.int32) for x in all_input_ids]
    return ILQLRolloutStorage(
        all_input_ids, attention_mask, rewards_per_token, all_states_ixs, all_actions_ixs, all_dones
    )


@register_trainer
class ILQLTrainer(MeshRLTrainer):
    def __init__(self, config: TRLConfig, logit_mask=None, **kwargs):
        super().__init__(config, **kwargs)
        if not isinstance(config.method, ILQLConfig):
            raise ValueError("ILQLTrainer requires method=ILQLConfig")
        self.method: ILQLConfig = config.method
        # `beta` shapes decode logits; it is not a generation-engine kwarg. A
        # list (reference ilql_hh gen_kwargs beta=[1, 4]) stays in generate_kwargs
        # so evaluate() sweeps it; pop_gen_processor_kwargs routes the per-call
        # value to the logits processor. Default/rollout beta = first entry.
        beta = self.generate_kwargs.get("beta", 1.0)
        if isinstance(beta, (list, tuple)):
            # normalize to list: evaluate()'s sweep detection matches lists only
            self.generate_kwargs["beta"] = list(beta)
            self.ilql_beta = float(beta[0])
        else:
            self.ilql_beta = float(self.generate_kwargs.pop("beta", 1.0))
        # optional [V, V] next-token transition mask (parity: reference trainers'
        # logit_mask kwarg used by randomwalks; masks invalid successor tokens)
        self.logit_mask = None if logit_mask is None else np.asarray(logit_mask, bool)
        self._train_steps = {}
        self._sync_fn = None

    def setup_model(self):
        self.is_seq2seq = self.config.model.model_arch_type == "seq2seq"
        # validates mesh.pipe combinations (incl. rejecting seq2seq) regardless
        # of which arch branch runs below
        pp_overrides = self.pipeline_overrides()
        overrides = dict(self.config.model.model_overrides or {})
        overrides.setdefault("param_dtype", self.param_dtype)
        overrides.setdefault("compute_dtype", self.compute_dtype)
        if self.is_seq2seq:
            self._setup_seq2seq_model(overrides)
            return
        overrides.setdefault("remat", self.config.mesh.remat)
        overrides.setdefault("sequence_sharding", self.config.mesh.sequence_shard)
        from trlx_tpu.models.hf_loading import merge_loaded_params, peft_overrides

        overrides.update(peft_overrides(self.config.model.peft_config))
        overrides.update(pp_overrides)
        self.model_config, trunk_params, self.model_type = load_pretrained(
            self.config.model.model_path, overrides, mesh=self.restore_mesh(overrides)
        )
        trunk_params = self.maybe_stack_loaded(trunk_params, self.model_config.num_layers)
        self.module = CausalLMWithILQLHeads(self.model_config, two_qs=self.config.method.two_qs)
        self.trunk_module = TransformerLM(self.model_config)

        params = self.module.init(
            jax.random.PRNGKey(self.config.train.seed),
            jnp.zeros((1, 2), jnp.int32),
            jnp.ones((1, 2), jnp.int32),
        )["params"]
        if trunk_params is not None:
            params = dict(params)
            params["transformer"] = merge_loaded_params(params["transformer"], trunk_params)
        # start target heads equal to online heads (parity: ILQLHeads init sync)
        params["ilql_heads"] = _sync_heads(dict(params["ilql_heads"]), alpha=1.0)
        shardings = make_param_shardings(params, self.mesh)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x, self.param_dtype), s), params, shardings
        )

    def _setup_seq2seq_model(self, overrides):
        from trlx_tpu.models.hf_loading import (
            load_pretrained_seq2seq,
            merge_loaded_params,
            t5_peft_overrides,
        )
        from trlx_tpu.models.policy import Seq2SeqLMWithILQLHeads

        overrides = {**(overrides or {}), **t5_peft_overrides(self.config.model.peft_config)}
        self.model_config, t5_params = load_pretrained_seq2seq(
            self.config.model.model_path, overrides, mesh=self.mesh
        )
        self.model_type = "t5"
        self.decoder_start_token_id = self.model_config.decoder_start_token_id
        self.module = Seq2SeqLMWithILQLHeads(self.model_config, two_qs=self.config.method.two_qs)
        params = self.module.init(
            jax.random.PRNGKey(self.config.train.seed),
            jnp.ones((1, 4), jnp.int32), jnp.ones((1, 4), jnp.int32),
            jnp.zeros((1, 3), jnp.int32),
        )["params"]
        if t5_params is not None:
            params = dict(params)
            params["t5"] = merge_loaded_params(params["t5"], t5_params)
        params["ilql_heads"] = _sync_heads(dict(params["ilql_heads"]), alpha=1.0)
        shardings = make_param_shardings(params, self.mesh)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x, self.param_dtype), s), params, shardings
        )

    def seq2seq_gen_fns(self):
        module = self.module

        return {
            "encode": lambda params, ids, mask: module.apply(
                {"params": params}, ids, mask, method=module.encode
            ),
            "cross_kv": lambda params, enc: module.apply(
                {"params": params}, enc, method=module.precompute_cross_kv
            ),
            "decode": lambda params, tok, enc, enc_mask, dec_mask, pos, cache, ckv: module.apply(
                {"params": params}, tok, enc, enc_mask, dec_mask, pos, cache, ckv,
                method=module.decode_step,
            ),
            "init_cache": lambda params, b, n: self._t5().init_cache(b, n),
        }

    def _t5(self):
        from trlx_tpu.models.t5 import T5LM

        return T5LM(self.model_config)

    def trainable_path_predicate(self, path: str) -> bool:
        if "target_q_heads" in path:
            return False  # target heads update only via Polyak sync
        return super().trainable_path_predicate(path)

    # ------------------------------------------------------------- generation

    def gen_step_fn(self):
        trunk = self.trunk_module

        def step(params, ids, mask, positions, cache):
            logits, hidden, _, cache = trunk.apply(
                {"params": params["transformer"]}, ids, mask, positions, cache
            )
            return logits, hidden, cache

        return step, lambda b, s: trunk.init_cache(b, s)

    def pop_gen_processor_kwargs(self, gen_kwargs):
        if "beta" in gen_kwargs:
            val = gen_kwargs.pop("beta")
            # un-swept list (e.g. rollout path): use its first entry
            beta = float(val[0]) if isinstance(val, (list, tuple)) else float(val)
            return {"beta": beta}
        return {}

    def gen_logits_processor(self, beta=None):
        """Perturb decode logits by beta*(minQ - V) from the target heads
        (parity: modeling_ilql.py:325-412)."""
        module = self.module
        beta = self.ilql_beta if beta is None else beta
        logit_mask = None if self.logit_mask is None else jnp.asarray(self.logit_mask)

        def processor(params, hidden, logits, prev_tok):
            qs, target_qs, vs = module.apply(
                {"params": {"ilql_heads": params["ilql_heads"]}},
                hidden[:, None, :],
                method=module.heads_only,
            )
            q = target_qs[0]
            for tq in target_qs[1:]:
                q = jnp.minimum(q, tq)
            adv = q[:, 0, :] - vs[:, 0, :]
            shaped = logits + beta * adv
            if logit_mask is not None:
                # parity: reference masks logits by the previous token's allowed
                # successors (modeling_ilql.py generate: logits[~mask[last]] = -inf)
                allowed = logit_mask[prev_tok]  # [B, V] bool
                shaped = jnp.where(allowed, shaped, -1e10)
            return shaped

        return processor

    # ------------------------------------------------------------- experience

    def make_experience(self, samples, rewards, max_length: int = 2048):
        if getattr(self, "is_seq2seq", False):
            self.store = make_experience_seq2seq(samples, rewards, self.tokenizer, max_length)
        else:
            self.store = make_experience(samples, rewards, self.tokenizer, max_length)

    # ------------------------------------------------------------- train loop

    def prepare_learning(self):
        bs = self.config.train.batch_size
        self.num_mb = max(1, bs // (self.config.train.minibatch_size or bs))

    def create_train_dataloader(self):
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed
        )

    def _get_train_step(self, B: int, T: int, A: int):
        key = (B, T, A)
        if key in self._train_steps:
            return self._train_steps[key]
        module, method = self.module, self.method

        def loss_fn(params, mb: ILQLBatch):
            logits, qs, target_qs, vs, _ = module.apply(
                {"params": params}, mb.input_ids, mb.attention_mask, None,
                mb.actions_ixs, mb.states_ixs,
            )
            action_logits = batched_index_select(logits, mb.actions_ixs)
            loss, stats = method.loss((action_logits, (qs, target_qs, vs)), mb)
            return loss, flatten_dict(stats)

        self._train_steps[key] = self.make_grad_accum_step(loss_fn, self.num_mb)
        return self._train_steps[key]

    def _get_train_step_s2s(self, B: int, T: int, D: int):
        key = ("s2s", B, T, D)
        if key in self._train_steps:
            return self._train_steps[key]
        module, method = self.module, self.method

        def loss_fn(params, mb):
            logits, qs, target_qs, vs = module.apply(
                {"params": params}, mb.input_ids, mb.attention_mask,
                mb.decoder_input_ids, None, mb.actions_ixs, mb.states_ixs,
            )
            action_logits = batched_index_select(logits, mb.actions_ixs)
            loss, stats = method.loss((action_logits, (qs, target_qs, vs)), mb)
            return loss, flatten_dict(stats)

        self._train_steps[key] = self.make_grad_accum_step(loss_fn, self.num_mb)
        return self._train_steps[key]

    def train_step(self, batch: ILQLBatch) -> Dict[str, float]:
        if getattr(self, "is_seq2seq", False):
            return self._train_step_s2s(batch)
        B, T = batch.input_ids.shape
        A = batch.actions_ixs.shape[1]
        Tb, Ab = pad_to_bucket(T, BUCKETS), pad_to_bucket(A, BUCKETS)
        pad2 = lambda x, n, v=0: np.pad(np.asarray(x), ((0, 0), (0, n - x.shape[1])), constant_values=v)
        padded = ILQLBatch(
            input_ids=pad2(batch.input_ids, Tb, self.tokenizer.pad_token_id),
            attention_mask=pad2(batch.attention_mask, Tb),
            rewards=pad2(batch.rewards, Ab, 0.0),
            states_ixs=pad2(batch.states_ixs, Ab + 1),
            actions_ixs=pad2(batch.actions_ixs, Ab),
            dones=pad2(batch.dones, Ab + 1),
        )
        dbatch = mesh_lib.put_batch(self.mesh, padded)
        step = self._get_train_step(B, Tb, Ab)
        with self.mesh:
            self.params, self.opt_state, stats = step(self.params, self.opt_state, dbatch)
        return {k: float(v) for k, v in jax.device_get(stats).items()}

    def _train_step_s2s(self, batch) -> Dict[str, float]:
        from trlx_tpu.data.ilql_types import ILQLSeq2SeqBatch

        B, T = batch.input_ids.shape
        D = batch.decoder_input_ids.shape[1]
        A = batch.actions_ixs.shape[1]
        Tb = pad_to_bucket(T, BUCKETS)
        # the loss takes actions = decoder_input_ids[:, 1:], so D must equal A+1
        Ab = pad_to_bucket(max(A, D - 1), BUCKETS)
        Db = Ab + 1
        pad2 = lambda x, n, v=0: np.pad(np.asarray(x), ((0, 0), (0, n - x.shape[1])), constant_values=v)
        padded = ILQLSeq2SeqBatch(
            input_ids=pad2(batch.input_ids, Tb, self.tokenizer.pad_token_id),
            attention_mask=pad2(batch.attention_mask, Tb),
            decoder_input_ids=pad2(batch.decoder_input_ids, Db, self.tokenizer.pad_token_id),
            rewards=pad2(batch.rewards, Ab, 0.0),
            states_ixs=pad2(batch.states_ixs, Ab + 1),
            actions_ixs=pad2(batch.actions_ixs, Ab),
            dones=pad2(batch.dones, Ab + 1),
        )
        dbatch = mesh_lib.put_batch(self.mesh, padded)
        step = self._get_train_step_s2s(B, Tb, Db)
        with self.mesh:
            self.params, self.opt_state, stats = step(self.params, self.opt_state, dbatch)
        return {k: float(v) for k, v in jax.device_get(stats).items()}

    def post_backward_callback(self):
        """Polyak-sync target Q heads every ``steps_for_target_q_sync`` steps
        (parity: accelerate_ilql_trainer.py:138-140)."""
        if self.iter_count % self.method.steps_for_target_q_sync == 0:
            if self._sync_fn is None:
                alpha = self.method.alpha

                def sync(params):
                    new = dict(params)
                    new["ilql_heads"] = _sync_heads(dict(params["ilql_heads"]), alpha)
                    return new

                self._sync_fn = jax.jit(sync, donate_argnums=0)
            with self.mesh:
                self.params = self._sync_fn(self.params)


def make_experience_seq2seq(samples, rewards, tokenizer=None, max_length: int = 2048, verbose: bool = True):
    """Seq2seq ILQL experience (parity: accelerate_ilql_trainer.py:178-243):
    encoder input = prompt tokens, decoder = output tokens; actions over the decoder
    sequence; standardized returns on the last action."""
    from trlx_tpu.pipeline.offline_pipeline import ILQLSeq2SeqRolloutStorage

    if verbose:
        logger.info("Collecting rollouts (seq2seq)")
    if tokenizer is not None:
        samples = [tokenize_dialogue(s, tokenizer, max_length) for s in samples]

    all_input_ids, all_output_ids, all_actions_ixs, all_states_ixs, all_dones = [], [], [], [], []
    for sample in samples:
        prompt_msgs = [m for m in sample if not m.is_output]
        output_msgs = [m for m in sample if m.is_output]
        all_input_ids.append(
            np.asarray([t for m in prompt_msgs for t in m.tokens], np.int32)
        )
        out = np.asarray([t for m in output_msgs for t in m.tokens], np.int32)
        all_output_ids.append(out)
        length = len(out)
        actions_ixs = np.arange(0, max(length - 1, 1))
        states_ixs = np.concatenate([actions_ixs, [max(length - 1, 1)]])
        all_dones.append(np.asarray([1] * (len(states_ixs) - 1) + [0], np.int32))
        all_actions_ixs.append(actions_ixs.astype(np.int32))
        all_states_ixs.append(states_ixs.astype(np.int32))

    returns = np.asarray(rewards, np.float64)
    returns = returns - returns.mean()
    std = returns.std()
    if not np.isnan(std) and std > 0:
        returns = returns / (std + np.finfo(returns.dtype).eps)
    rewards_per_token = [np.zeros(len(x), np.float32) for x in all_actions_ixs]
    for rs, ret in zip(rewards_per_token, returns):
        rs[-1] = ret

    attention_mask = [np.ones(len(x), np.int32) for x in all_input_ids]
    return ILQLSeq2SeqRolloutStorage(
        all_input_ids, attention_mask, all_output_ids, rewards_per_token,
        all_states_ixs, all_actions_ixs, all_dones,
    )
