"""MeshRLTrainer — the single shared trainer engine over a TPU mesh.

This is the TPU-native replacement for BOTH reference backends (SURVEY.md §7): the
Accelerate engine (`/root/reference/trlx/trainer/accelerate_base_trainer.py:40-682`)
and the NeMo/Megatron one. One SPMD program over a ``data × fsdp × model`` mesh covers
DP / ZeRO / TP / SP via PartitionSpecs, so there is exactly one code path.

Responsibilities (reference line refs in method docstrings): model+optimizer setup
with layer freezing, jitted gradient-accumulation train step, the jitted generation
engine with shape bucketing, stop-sequence decode, distributed evaluate, the main
``learn()`` loop with periodic eval/checkpoint/save-best, checkpoint save/load, and
tracker logging with the reference's stat names.
"""

import json
import os
from abc import abstractmethod
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.obs import Observability, batch_token_count
from trlx_tpu.ops.generation import generate as generate_op
from trlx_tpu.ops.generation import generate_seq2seq, left_pad_batch, pad_to_bucket
from trlx_tpu.parallel import mesh as mesh_lib
from trlx_tpu.pipeline.tokenization import load_tokenizer
from trlx_tpu.resilience import Resilience, chaos_poison_batch, find_latest_committed
from trlx_tpu.trainer import BaseRLTrainer, register_trainer
from trlx_tpu.utils import (
    Clock,
    filter_non_scalars,
    get_git_tag,
    get_optimizer_class,
    get_scheduler_class,
    set_seed,
    significant,
)
from trlx_tpu.utils import logging
from trlx_tpu.utils.compilation_cache import configure_compilation_cache
from trlx_tpu.utils.trackers import make_tracker

logger = logging.get_logger(__name__)


def pack_scores(scores) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Fixed-shape encoding of reward_fn output for cross-host broadcast:
    (header [dense, width], padded [B, width] f32, lens [B] i32). Handles both
    per-sample scalars and dense per-token reward arrays (ragged, padded)."""
    dense = len(scores) > 0 and np.ndim(scores[0]) > 0
    if dense:
        lens = np.asarray([len(s) for s in scores], np.int32)
        width = max(1, int(lens.max()))
        padded = np.zeros((len(scores), width), np.float32)
        for i, s in enumerate(scores):
            padded[i, : len(s)] = np.asarray(s, np.float32)
    else:
        lens = np.zeros((len(scores),), np.int32)
        width = 1
        padded = np.asarray(jax.device_get(list(scores)), np.float32).reshape(-1, 1)
    return np.asarray([int(dense), width], np.int32), padded, lens


def unpack_scores(dense: bool, padded: np.ndarray, lens: np.ndarray):
    """Inverse of :func:`pack_scores`."""
    if dense:
        return [padded[i, : lens[i]] for i in range(padded.shape[0])]
    return padded[:, 0].tolist()


@register_trainer
class MeshRLTrainer(BaseRLTrainer):
    """Shared engine; algorithm trainers subclass and provide
    ``setup_model / create_train_dataloader / train_step / prepare_learning``."""

    def __init__(self, config: TRLConfig, **kwargs):
        super().__init__(config, **kwargs)
        # distributed init MUST precede any backend-initializing jax call
        # (PRNGKey creation below queries devices)
        mesh_lib.initialize_distributed()
        # persistent XLA compile cache: 20-40s first-compiles restore in ms on
        # subsequent runs with identical shapes. MUST come before the process's
        # first compile — jax latches cache-enablement at that point, and even
        # the PRNGKey below compiles a module
        configure_compilation_cache(config=config)
        self.np_rng = set_seed(config.train.seed)
        # identical on EVERY process: rng is a replicated jit input to generate,
        # and jax requires replicated inputs to be equal across hosts
        self.rng = jax.random.PRNGKey(config.train.seed)
        self.mesh = mesh_lib.mesh_from_config(config.mesh)
        self.tokenizer = load_tokenizer(config.tokenizer)

        self.compute_dtype = jnp.dtype(config.mesh.compute_dtype)
        self.param_dtype = jnp.dtype(config.mesh.param_dtype)

        self.setup_model()
        self.setup_optimizer()

        self.iter_count = 0
        self.nth_evaluation = 0
        self.best_reward = -float("inf")
        self.clock = Clock()
        self.generate_kwargs = dict(getattr(config.method, "gen_kwargs", {}) or {})
        self.generate_experience_kwargs = getattr(config.method, "gen_experience_kwargs", None)
        self._compiled_generate = {}
        self._rollout_params = None  # cached low-precision copy (rollout_param_dtype)
        self._cast_rollout_params = None  # its jitted cast fn (built once)

        run_name = config.train.run_name
        if run_name is None:
            tag, branch = get_git_tag()
            config.train.run_name = run_name = (
                f"{config.model.model_path.split('/')[-1]}"
                f"/{jax.device_count()}chips:{branch}"
            ).replace("/", "_")
        self.tracker = make_tracker(config.train, config.to_dict())
        # observability layer (span tracing / MFU / memory gauges / watchdog);
        # a disabled config makes every obs call a near-no-op
        obs_logging_dir = config.train.logging_dir or os.path.join(
            config.train.checkpoint_dir, "logs"
        )
        self.obs = Observability(config.train.observability, obs_logging_dir)
        # resilience subsystem (async atomic checkpointing / preemption
        # handling / auto-resume / reward retries); with the default disabled
        # config every hook is a no-op and reward_fn is wrapped only with the
        # free chaos check
        self.resilience = Resilience(
            config.train.resilience, multiprocess=jax.process_count() > 1
        )
        self.reward_fn = self.resilience.wrap_reward_fn(self.reward_fn)
        # self-healing health guard (skip -> rollback -> halt escalation
        # ladder; docs/resilience.md). None when disabled — the compiled train
        # step and the learn loop are then byte-identical to an unconfigured
        # run. Must exist before any train step is built: make_grad_accum_step
        # compiles the on-device skip guard only when a guard is present.
        self.health = None
        sh_config = config.train.self_healing
        if sh_config.enabled:
            from trlx_tpu.resilience.health import TrainingHealthGuard

            self.health = TrainingHealthGuard(
                sh_config,
                diagnostics_dir=sh_config.diagnostics_dir
                or os.path.join(config.train.checkpoint_dir, "diagnostics"),
            )
        self.self_healing_summary = None

    # ------------------------------------------------------------- model setup

    @abstractmethod
    def setup_model(self):
        """Set self.module, self.params (sharded train_state pytree incl. heads),
        self.model_config, self.model_type."""
        ...

    def pipeline_overrides(self) -> Dict[str, Any]:
        """Model overrides enabling pipeline parallelism when ``mesh.pipe > 1``
        (stacked layer layout + GPipe schedule, trlx_tpu/parallel/pipeline.py).
        Validates the config combinations PP cannot serve: stacked layers have no
        per-layer param paths, so partial layer freezing and the hydra/value
        branches (which capture a mid-stack activation) are unavailable — PPO
        falls back to the full reference copy it already uses at
        ``num_layers_unfrozen=-1`` (the NeMo PP reference does the same,
        modeling_nemo_ppo.py:228-244)."""
        mc = self.config.mesh
        if mc.pipe <= 1:
            return {}
        if self.config.model.model_arch_type == "seq2seq":
            raise ValueError("pipeline parallelism (mesh.pipe > 1) is causal-LM only")
        if self.config.model.num_layers_unfrozen >= 0:
            raise ValueError(
                "mesh.pipe > 1 requires num_layers_unfrozen=-1: pipelined models "
                "keep block params stacked and cannot freeze or branch at a layer "
                "boundary (PPO then uses a full reference copy automatically)"
            )
        if getattr(self.config.method, "num_value_layers_unfrozen", 0):
            raise ValueError("mesh.pipe > 1 requires num_value_layers_unfrozen=0")
        overrides: Dict[str, Any] = {
            "pipeline_stages": mc.pipe,
            "pipeline_microbatches": mc.pipeline_microbatches,
        }
        if mc.sequence_shard:
            logger.warning(
                "mesh.sequence_shard is disabled under pipeline parallelism: "
                "the pipelined stack applies no sequence-sharding constraints"
            )
            overrides["sequence_sharding"] = False
        return overrides

    def restore_mesh(self, overrides: Dict[str, Any]):
        """Mesh to hand ``load_pretrained`` for direct-to-device sharded restore
        of native checkpoints — or None when the model will use the stacked
        layout, whose host-side [L, ...] restack (``maybe_stack_loaded``) needs
        host arrays (np.asarray on non-addressable shards would throw on pods)."""
        if overrides.get("scan_layers") or overrides.get("pipeline_stages", 1) > 1:
            return None
        return self.mesh

    def maybe_stack_loaded(self, trunk_params, num_layers: int, stacked: Optional[bool] = None):
        """Convert HF-loaded per-layer params to the stacked layout when the
        built model uses it (``mesh.pipe > 1`` or ``scan_layers``)."""
        if stacked is None:
            stacked = getattr(self.model_config, "stacked", False)
        if stacked and trunk_params is not None:
            from trlx_tpu.parallel.pipeline import stack_layer_params

            return stack_layer_params(trunk_params, num_layers)
        return trunk_params

    def trainable_path_predicate(self, path: str) -> bool:
        """Which params receive gradients (parity: ``freeze_bottom_causal_layers``,
        reference utils/modeling.py:22-45): with num_layers_unfrozen = N > 0, only
        the top N transformer layers and all heads train; -1 trains everything."""
        if self.config.model.peft_config:
            # peft mode: only adapters (LoRA / prefix K-V / prompt embeddings)
            # and heads receive gradients
            if any(a in path for a in ("lora_", "prefix_k", "prefix_v", "prompt_embeddings")):
                return True
            return "transformer" not in path and "t5" not in path
        n_unfrozen = self.config.model.num_layers_unfrozen
        if n_unfrozen < 0:
            return True
        if "transformer" not in path:
            return True  # heads always train
        if "layers_scan" in path:
            # stacked blocks have no per-layer paths; partial freezing cannot be
            # honored. Reachable only when pipeline_stages was forced through
            # model_overrides (mesh.pipe > 1 validates this earlier).
            raise ValueError(
                "num_layers_unfrozen >= 0 cannot be applied to a stacked "
                "(pipeline_stages > 1) model; set num_layers_unfrozen=-1"
            )
        if "layers_" in path:
            layer = int(path.split("layers_")[1].split("/")[0])
            return layer >= self.model_config.num_layers - n_unfrozen
        # embeddings / final norm / lm_head of the trunk
        return False

    def _trainable_labels(self, params) -> Any:
        def build(tree, prefix=""):
            if isinstance(tree, dict):
                return {k: build(v, f"{prefix}/{k}" if prefix else str(k)) for k, v in tree.items()}
            return "train" if self.trainable_path_predicate(prefix) else "freeze"

        return build(params)

    def _learner_overlap_active(self) -> bool:
        """Whether the overlapped-collective FSDP step (``train.learner_overlap``,
        ``trlx_tpu/parallel/fsdp.py``) replaces the GSPMD grad-accum step.

        Config-level gate (``self.health`` does not exist yet during
        ``setup_optimizer``): requires a pure data/fsdp mesh — the shard_map
        body computes the full model locally, so TP (``model > 1``) and PP
        (``pipe > 1``) fall back — and no self-healing guard (the on-device
        skip guard is built into the GSPMD step only). Falls back with a
        warning, never raises: off-path runs stay byte-identical.
        """
        cfg = getattr(self.config.train, "learner_overlap", None)
        if cfg is None or not cfg.enabled:
            return False
        from trlx_tpu.parallel.fsdp import can_overlap

        if not can_overlap(self.mesh):
            logger.warning(
                "train.learner_overlap requires a pure data/fsdp mesh "
                f"(model=1, pipe=1), got {dict(self.mesh.shape)}: falling back "
                "to the GSPMD train step"
            )
            return False
        if self.config.train.self_healing.enabled:
            logger.warning(
                "train.learner_overlap is incompatible with the self-healing "
                "health guard (on-device skip lives in the GSPMD step): "
                "falling back to the GSPMD train step"
            )
            return False
        return True

    def setup_optimizer(self):
        """optax optimizer + schedule from the registries (parity:
        accelerate_base_trainer.py:173-201), masked by the freeze predicate, with
        optimizer state sharded like the params (ZeRO analogue)."""
        opt_config = self.config.optimizer
        kwargs = dict(opt_config.kwargs)
        lr = kwargs.pop("lr", 1e-5)
        sched_kwargs = dict(self.config.scheduler.kwargs)
        sched_lr = sched_kwargs.pop("learning_rate", lr)
        self.lr_schedule = get_scheduler_class(self.config.scheduler.name)(
            learning_rate=sched_lr, **sched_kwargs
        )
        max_grad_norm = kwargs.pop("max_grad_norm", None)
        overlap = self._learner_overlap_active()
        opt_name = opt_config.name
        if overlap and self.config.train.learner_overlap.int8_opt_state:
            # ZeRO + int8: blockwise-quantized Adam moments over each device's
            # LOCAL shard (ops/quantized_adam.py) — the block layout must be
            # shard-local, so this option only exists under the overlap step
            if str(opt_name).lower() in ("adam", "adamw", "adamw_8bit_bnb"):
                opt_name = "adamw_8bit_bnb"
            else:
                logger.warning(
                    f"learner_overlap.int8_opt_state ignored: optimizer "
                    f"{opt_name!r} is not adam-family"
                )
        tx = get_optimizer_class(opt_name)(learning_rate=self.lr_schedule, **kwargs)
        # Under the overlapped step, global-norm clipping cannot be an optax
        # link: the transform would see only this device's gradient SHARD.
        # The step computes the shard-aware global norm itself.
        self._overlap_max_grad_norm = max_grad_norm if overlap else None
        if max_grad_norm and not overlap:
            tx = optax.chain(optax.clip_by_global_norm(max_grad_norm), tx)
        labels = self._trainable_labels(self.params)
        self.tx = optax.multi_transform({"train": tx, "freeze": optax.set_to_zero()}, labels)
        if overlap:
            # ZeRO-sharded init: tx.init runs INSIDE shard_map on each
            # device's parameter shard, so the moments are born shard-local —
            # required for the int8 option (quantization blocks must tile the
            # local shard) and never materializes full-size state anywhere
            from trlx_tpu.parallel import fsdp as fsdp_lib

            self._overlap_specs = fsdp_lib.make_overlap_specs(
                self.params, self.tx, self.mesh
            )
            init = fsdp_lib.make_sharded_opt_init(self.tx, self._overlap_specs, self.mesh)
            with self.mesh:
                self.opt_state = init(self.params)
            return
        # Explicit state shardings: moment leaves take their param's layout by
        # key path, scalars replicate. Leaving this to GSPMD propagation
        # REPLICATES the moments (zeros_like outputs carry no input-derived
        # sharding) — for a full-finetune 7B that is 54G of Adam state per
        # device, measured by the v5e compiler (scripts/scale_proof.py). The
        # explicit specs also fix the old scalar-on-device-0 restore hazard.
        from trlx_tpu.parallel.sharding import make_state_shardings

        state_shardings = make_state_shardings(
            jax.eval_shape(self.tx.init, self.params), self.mesh
        )
        with self.mesh:
            self.opt_state = jax.jit(self.tx.init, out_shardings=state_shardings)(self.params)

    # -------------------------------------------------------------- train step

    def make_grad_accum_step(self, loss_fn: Callable, num_mb: int, donate: bool = True):
        """Build the jitted optimizer step: scan over ``num_mb`` microbatches
        accumulating grads (replaces torch grad-accum no_sync windows,
        accelerate_base_trainer.py:502-516), then one optax update.

        ``loss_fn(params, microbatch) -> (loss, stats_dict)``.

        With the self-healing health guard active (``train.self_healing``),
        the step takes one extra *traced* scalar — the grad-norm cap — and
        discards the computed update on device when the loss or global grad
        norm is non-finite or the norm exceeds the cap: the input buffers are
        donated, so by the time the host could inspect the stats the old
        params are already gone — the skip decision has to live inside the
        XLA program (the ``optax.apply_if_finite`` pattern). The cap is a
        traced argument precisely so the guard's rolling threshold never
        triggers a retrace. Without a guard the exact original program is
        compiled — off-config runs stay bit-identical.

        With ``train.learner_overlap`` active the step is instead built by
        :func:`trlx_tpu.parallel.fsdp.make_overlapped_grad_accum_step` —
        explicit shard_map collectives (per-leaf allgather forward,
        reduce-scatter backward), a gradient-SHARD accumulation carry, and a
        shard-local optimizer update over the ZeRO state from
        ``setup_optimizer``. The overlap-off program below is untouched.
        """
        if self._learner_overlap_active():
            from trlx_tpu.parallel import fsdp as fsdp_lib

            lov = self.config.train.learner_overlap
            logger.info(
                "learner_overlap: overlapped FSDP step active "
                f"(fsdp={self.mesh.shape['fsdp']}, num_microbatches={num_mb}, "
                f"int8_opt_state={lov.int8_opt_state}, remat={lov.remat}, "
                f"flash_bwd={lov.flash_bwd}, max_grad_norm={self._overlap_max_grad_norm})"
            )
            return fsdp_lib.make_overlapped_grad_accum_step(
                loss_fn,
                self.tx,
                self._overlap_specs,
                self.mesh,
                num_mb,
                max_grad_norm=self._overlap_max_grad_norm,
                lr_schedule=self.lr_schedule,
                donate=donate,
            )

        def compute_update(params, opt_state, batch):
            mbs = jax.tree.map(lambda x: x.reshape((num_mb, x.shape[0] // num_mb) + x.shape[1:]), batch)

            def body(grads_acc, mb):
                (loss, stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, mb)
                grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
                return grads_acc, (loss, stats)

            zero_grads = jax.tree.map(jnp.zeros_like, params)
            grads, (losses, stats) = jax.lax.scan(body, zero_grads, mbs)
            grads = jax.tree.map(lambda g: g / num_mb, grads)
            updates, new_opt_state = self.tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            mean_stats = jax.tree.map(lambda x: jnp.mean(x, axis=0), stats)
            mean_stats["learning_rate_group_0"] = self.lr_schedule(
                _opt_step_count(opt_state)
            )
            return new_params, new_opt_state, mean_stats, losses, grads

        def step(params, opt_state, batch):
            new_params, new_opt_state, mean_stats, _, _ = compute_update(params, opt_state, batch)
            return new_params, new_opt_state, mean_stats

        guard = self.health
        if guard is None:
            return jax.jit(step, donate_argnums=(0, 1) if donate else ())

        def guarded_step(params, opt_state, batch, grad_norm_cap):
            new_params, new_opt_state, mean_stats, losses, grads = compute_update(
                params, opt_state, batch
            )
            grad_norm = optax.global_norm(grads)
            loss_mean = jnp.mean(losses)
            ok = (
                jnp.isfinite(loss_mean)
                & jnp.isfinite(grad_norm)
                & (grad_norm <= grad_norm_cap)
            )
            keep = lambda new, old: jnp.where(ok, new, old)  # noqa: E731
            new_params = jax.tree.map(keep, new_params, params)
            new_opt_state = jax.tree.map(keep, new_opt_state, opt_state)
            mean_stats["health/grad_norm"] = grad_norm
            mean_stats["health/update_applied"] = ok.astype(jnp.float32)
            return new_params, new_opt_state, mean_stats

        jitted = jax.jit(guarded_step, donate_argnums=(0, 1) if donate else ())

        def run(params, opt_state, batch):
            return jitted(params, opt_state, batch, jnp.float32(guard.grad_norm_cap()))

        return run

    # -------------------------------------------------------------- generation

    @abstractmethod
    def gen_step_fn(self):
        """Return step_fn(params, ids, mask, positions, cache)->(logits,hidden,cache)
        and init_cache_fn(batch, total_len) for the generation engine."""
        ...

    def gen_logits_processor(self, **kwargs):
        """Optional decode-time logits processor (ILQL advantage shaping)."""
        return None

    def pop_gen_processor_kwargs(self, gen_kwargs: Dict[str, Any]) -> Dict[str, Any]:
        """Remove and return gen_kwargs consumed by the logits processor rather
        than the generation engine (e.g. ILQL's ``beta``); they become part of
        the compile key so eval sweeps over them recompile per value."""
        return {}

    def generation_params(self):
        """Params used by generate(): the masters, or (train.rollout_param_dtype)
        a cached low-precision copy — decode streams every weight per token, so
        f32 masters double rollout HBM traffic. The copy is invalidated after
        each optimizer step and re-cast lazily (one cast per experience phase)."""
        dtype_name = self.config.train.rollout_param_dtype
        if dtype_name is None:
            return self.params
        if self._rollout_params is None:
            if self._cast_rollout_params is None:
                dtype = jnp.dtype(dtype_name)

                def cast(x):
                    return x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x

                # built once: a fresh jit wrapper per re-cast would re-trace the
                # full param tree every optimizer step
                self._cast_rollout_params = jax.jit(lambda p: jax.tree.map(cast, p))
            with self.mesh:
                self._rollout_params = self._cast_rollout_params(self.params)
        return self._rollout_params

    def generate(
        self,
        prompts_ids: List[np.ndarray],
        eval_mode: bool = False,
        params: Optional[Any] = None,
        **kwargs,
    ):
        """Generate continuations for a list of ragged prompt id arrays.

        Host side: bucket-pad prompts (left) to limit recompiles; device side: one
        compiled generate per (B, P, gen-kwargs) key. Parity:
        accelerate_base_trainer.py:256-283 (generate vs generate_eval kwargs).

        ``params`` overrides the sampling parameters (the async rollout engine
        passes a published snapshot so the producer keeps a stable behavior
        policy while the live ``self.params`` are being donated/updated);
        default is :meth:`generation_params` (the masters or their cached
        low-precision rollout copy).
        """
        gen_kwargs = dict(self.generate_kwargs)
        if not eval_mode and self.generate_experience_kwargs:
            gen_kwargs = dict(self.generate_experience_kwargs)
        gen_kwargs.update(kwargs)
        gen_kwargs.setdefault("eos_token_id", self.tokenizer.eos_token_id)
        gen_kwargs.setdefault("pad_token_id", self.tokenizer.pad_token_id)
        max_new = int(gen_kwargs.pop("max_new_tokens", 16))
        proc_kwargs = self.pop_gen_processor_kwargs(gen_kwargs)

        max_len = max(len(p) for p in prompts_ids)
        buckets = [2 ** i for i in range(3, 14)]
        P = pad_to_bucket(max_len, buckets)
        ids, mask = left_pad_batch(prompts_ids, gen_kwargs["pad_token_id"], P)

        is_seq2seq = getattr(self, "is_seq2seq", False)
        key = (
            ids.shape, max_new, is_seq2seq,
            tuple(sorted(gen_kwargs.items())), tuple(sorted(proc_kwargs.items())),
        )
        if key not in self._compiled_generate:
            if is_seq2seq:
                fns = self.seq2seq_gen_fns()
                fn = partial(
                    generate_seq2seq,
                    fns["encode"], fns["cross_kv"], fns["decode"], fns["init_cache"],
                    max_new_tokens=max_new,
                    decoder_start_token_id=self.decoder_start_token_id,
                    logits_processor=self.gen_logits_processor(**proc_kwargs),
                    **gen_kwargs,
                )
                # outputs replicated: every host must address the full result
                # (host-side decode/reward runs identically on all processes)
                self._compiled_generate[key] = jax.jit(
                    lambda params, i, m, r: fn(params=params, input_ids=i, attention_mask=m, rng=r),
                    out_shardings=mesh_lib.replicated(self.mesh),
                )
            else:
                step_fn, init_cache_fn = self.gen_step_fn()
                fn = partial(
                    generate_op,
                    step_fn,
                    init_cache_fn=init_cache_fn,
                    max_new_tokens=max_new,
                    logits_processor=self.gen_logits_processor(**proc_kwargs),
                    **gen_kwargs,
                )
                self._compiled_generate[key] = jax.jit(
                    lambda params, i, m, r: fn(params, input_ids=i, attention_mask=m, rng=r),
                    out_shardings=mesh_lib.replicated(self.mesh),
                )
        self.rng, sub = jax.random.split(self.rng)
        batch = mesh_lib.put_batch(self.mesh, {"ids": ids, "mask": mask})
        gen_params = params if params is not None else self.generation_params()
        # the span covers dispatch + the device_get sync: decode is async until
        # the host fetch, so timing only the dispatch would undercount wildly
        with self.obs.span("generate"):
            with self.mesh:
                out = self._compiled_generate[key](
                    gen_params, batch["ids"], batch["mask"], sub
                )
            sequences = np.asarray(jax.device_get(out["sequences"]))
            response_mask = np.asarray(jax.device_get(out["response_mask"]))
        # seq2seq sequences are [decoder_start] + response: pad_len for decode() is 1
        return sequences, response_mask, 1 if is_seq2seq else P

    def decode(
        self,
        prompts: List[np.ndarray],
        samples: np.ndarray,
        prompt_pad_len: int,
        append_eos: bool = False,
        response_masks: Optional[np.ndarray] = None,
    ) -> Tuple[List[str], List[str], List[str], List[np.ndarray]]:
        """Decode generated sequences into (str_samples, str_prompts, str_outputs,
        trimmed_output_ids), trimming at the first stop sequence and (optionally)
        re-appending eos (parity: accelerate_base_trainer.py:203-255).

        Trimming is token-level on the rollout hot path: response lengths come from
        the generation ``response_mask`` and stop sequences are found by token-
        subsequence scan (native ``find_stop_positions``), so output ids are sliced
        from the sampled tokens without re-tokenization. A string-level check
        remains only as a net for stop sequences that cross token boundaries."""
        from trlx_tpu.native import find_stop_positions

        B = len(prompts)
        resp_all = np.ascontiguousarray(samples[:, prompt_pad_len:], np.int32)
        eos = self.tokenizer.eos_token_id
        pad = self.tokenizer.pad_token_id
        if response_masks is not None:
            lens = np.asarray(response_masks).sum(axis=1).astype(np.int64)
        else:
            valid = resp_all != pad
            lens = np.where(
                valid.any(axis=1), resp_all.shape[1] - np.argmax(valid[:, ::-1], axis=1), 0
            ).astype(np.int64)
        # response_mask counts the eos token itself; output ids exclude it
        if eos is not None and B > 0:
            last = resp_all[np.arange(B), np.maximum(lens - 1, 0)]
            lens = lens - ((lens > 0) & (last == eos)).astype(np.int64)
        token_stopped = np.zeros(B, bool)
        if self.stop_sequences:
            if not hasattr(self, "_stop_token_ids"):
                self._stop_token_ids = [
                    self.tokenizer(s, add_special_tokens=False).input_ids
                    for s in self.stop_sequences
                ]
            stop_pos = find_stop_positions(resp_all, self._stop_token_ids)
            token_stopped = stop_pos < lens
            lens = np.minimum(lens, stop_pos)

        str_samples, str_prompts, str_outputs, out_ids = [], [], [], []
        for i, prompt in enumerate(prompts):
            str_prompt = self.tokenizer.decode(prompt, skip_special_tokens=True)
            resp = resp_all[i, : lens[i]]
            if token_stopped[i]:
                # parity with the reference's str_output[:ix].rstrip(): drop the
                # whitespace run preceding the stop sequence (token-level)
                while len(resp) and self.tokenizer.decode(resp[-1:]).strip() == "":
                    resp = resp[:-1]
            str_output = self.tokenizer.decode(resp, skip_special_tokens=True)
            if token_stopped[i]:
                str_output = str_output.rstrip()
            for stop in self.stop_sequences:
                stop_ix = str_output.find(stop)
                if stop_ix >= 0:  # crossed a token boundary; rare slow path
                    str_output = str_output[:stop_ix].rstrip()
                    resp = np.asarray(
                        self.tokenizer(str_output, add_special_tokens=False).input_ids, np.int32
                    )
            trimmed = list(resp)
            if append_eos and eos is not None:
                trimmed.append(eos)
            if len(trimmed) == 0:  # never emit empty responses (breaks PPO shapes)
                trimmed = [eos or 0]
            str_samples.append(str_prompt + str_output)
            str_prompts.append(str_prompt)
            str_outputs.append(str_output)
            out_ids.append(np.asarray(trimmed, np.int32))
        return str_samples, str_prompts, str_outputs, out_ids

    # -------------------------------------------------------------- evaluation

    @property
    def reward_on_process_zero(self) -> bool:
        """Resolved ``train.reward_on_process_zero``: None (default) means auto —
        on exactly when this is a multi-process run (a served reward model must
        not be hit once per host, and a nondeterministic server would silently
        desync the hosts' rollouts — VERDICT r2 weak #5 / r3 weak #3)."""
        flag = self.config.train.reward_on_process_zero
        if flag is None:
            return jax.process_count() > 1
        return bool(flag)

    def call_reward_fn(self, **kwargs):
        """Invoke reward_fn; with :attr:`reward_on_process_zero` only process 0
        calls it and the scores are broadcast to every host.

        Every process must enter this function at the same point in the program
        (the broadcasts are collectives)."""
        if not self.reward_on_process_zero or jax.process_count() == 1:
            return self.reward_fn(**kwargs)
        scores = self.reward_fn(**kwargs) if jax.process_index() == 0 else None
        return self.broadcast_scores(scores, len(kwargs["samples"]))

    def broadcast_scores(self, scores, batch_size: int):
        """Broadcast process-0 scores to every host. MAIN THREAD ONLY: the
        broadcasts are collectives and must execute in identical program order
        on every process — the overlap rollout path keeps reward_fn on a worker
        thread but drains its futures through here on the main thread."""
        from jax.experimental import multihost_utils

        if jax.process_index() == 0:
            header, padded, lens = pack_scores(scores)
        else:
            header = np.zeros((2,), np.int32)
        header = np.asarray(multihost_utils.broadcast_one_to_all(header))
        dense, width = bool(header[0]), int(header[1])
        if jax.process_index() != 0:
            padded = np.zeros((batch_size, width), np.float32)
            lens = np.zeros((batch_size,), np.int32)
        padded = np.asarray(multihost_utils.broadcast_one_to_all(padded))
        lens = np.asarray(multihost_utils.broadcast_one_to_all(lens))
        return unpack_scores(dense, padded, lens)

    def evaluate(self) -> Dict[str, Any]:
        """Generate on eval prompts, score with reward_fn/metric_fn, log a sample
        table (parity: accelerate_base_trainer.py:339-500, incl. gen-kwarg sweeps
        via list-valued gen_kwargs)."""
        logger.info("Evaluating model")
        stats: Dict[str, Any] = {}
        sweep_keys = [k for k, v in self.generate_kwargs.items() if isinstance(v, list)]
        sweeps = [{}]
        if sweep_keys:
            sweeps = []
            base = {k: v for k, v in self.generate_kwargs.items() if k not in sweep_keys}
            from itertools import product

            for combo in product(*[self.generate_kwargs[k] for k in sweep_keys]):
                sweeps.append({**base, **dict(zip(sweep_keys, combo))})

        for sweep_kwargs in sweeps:
            suffix = "".join(f"@{k}={v}" for k, v in sweep_kwargs.items() if k in sweep_keys)
            # decode per batch with that batch's own prompt pad length: batches may
            # bucket to different prompt lengths, so a shared pad_len would slice
            # later batches' responses at the wrong offset
            str_samples, str_prompts, str_outputs, meta = [], [], [], {}
            for batch in self.eval_pipeline.create_loader(self.config.train.batch_size):
                prompts = batch["input_ids"]
                samples, resp_mask, pad_len = self.generate(prompts, eval_mode=True, **sweep_kwargs)
                s, p, o, _ = self.decode(prompts, samples, pad_len, response_masks=resp_mask)
                str_samples.extend(s)
                str_prompts.extend(p)
                str_outputs.extend(o)
                for k, v in batch.items():
                    if k != "input_ids":
                        meta.setdefault(k, []).extend(v)

            columns = ["prompt", "output"]
            columns_data = [str_prompts, str_outputs]
            if self.reward_fn is not None:
                rewards = self.call_reward_fn(
                    samples=str_samples, prompts=str_prompts, outputs=str_outputs,
                    tokenizer=self.tokenizer, **meta,
                )
                rewards = [float(np.sum(r)) if np.ndim(r) > 0 else float(r) for r in rewards]
                columns.append("reward")
                columns_data.append(rewards)
                stats[f"reward/mean{suffix}"] = float(np.mean(rewards))
                stats[f"reward/std{suffix}"] = float(np.std(rewards))
            if self.metric_fn is not None:
                metrics = self.metric_fn(
                    samples=str_samples, prompts=str_prompts, outputs=str_outputs, **meta
                )
                for k, xs in metrics.items():
                    stats[f"metrics/{k}{suffix}"] = float(np.mean(xs))
                    if np.ndim(xs) > 0 and len(xs) == len(str_samples):
                        columns.append(k)
                        columns_data.append(list(map(float, xs)))
            rows = list(zip(*columns_data))
            if jax.process_index() == 0:
                self.tracker.log_table(f"samples{suffix}", columns, [list(r) for r in rows], self.iter_count)
                for row in rows[:4]:
                    logger.info(" | ".join(str(c)[:72] for c in row))
        self.nth_evaluation += 1
        return stats

    # -------------------------------------------------------------- main loop

    @abstractmethod
    def create_train_dataloader(self):
        ...

    @abstractmethod
    def train_step(self, batch) -> Dict[str, float]:
        """One optimizer step on a host batch; returns flat stats."""
        ...

    # ----------------------------------------------------- staged learn batches
    # The microbatch-interleaved learn seam for stream-overlapped PPO
    # (docs/serving.md "Stream-overlapped PPO"): during the streaming window
    # the experience producer collates upcoming first-epoch learner batches
    # and ``device_put``s them while decode still owns the wall-clock, then
    # the train loop consumes the pre-staged device copies instead of
    # re-transferring. Purely a transfer optimization — the staged host batch
    # must match the loader's batch exactly or the whole stage is discarded,
    # so the optimizer sees identical data either way.

    def _clear_staged_learn(self) -> None:
        self._staged_learn: List[Tuple[Any, Any]] = []

    def _stage_learn_batch(self, host_batch, device_batch) -> None:
        """Record a (host, device) learn-batch pair staged ahead of the loop."""
        if not hasattr(self, "_staged_learn"):
            self._clear_staged_learn()
        self._staged_learn.append((host_batch, device_batch))

    @staticmethod
    def _host_batches_equal(a, b) -> bool:
        flat_a, tree_a = jax.tree.flatten(a)
        flat_b, tree_b = jax.tree.flatten(b)
        if tree_a != tree_b:
            return False
        return all(np.array_equal(x, y) for x, y in zip(flat_a, flat_b))

    def _pop_staged_learn(self, batch):
        """Device copy staged for ``batch``, or None to fall back to a fresh
        transfer. Staged batches are predictions of the loader's output in
        order; the first mismatch (quarantine drop, truncation, reshuffle)
        invalidates the remainder — correctness never depends on staging."""
        staged = getattr(self, "_staged_learn", None)
        if not staged:
            return None
        host, dev = staged[0]
        if self._host_batches_equal(host, batch):
            staged.pop(0)
            return dev
        self._clear_staged_learn()
        return None

    def prepare_learning(self):
        pass

    def post_epoch_callback(self, epoch: int):
        pass

    def post_backward_callback(self):
        pass

    def on_learn_end(self):
        """Teardown hook guaranteed to run when :meth:`learn` exits (normal
        return, early stop, or exception) — PPO uses it to drain and join the
        async rollout producer so no thread outlives training."""
        pass

    def learn(self):
        """Main training loop (parity: accelerate_base_trainer.py:518-652)."""
        try:
            return self._learn_loop()
        finally:
            if self.health is not None:
                # the run summary half of "visible in gauges and the run
                # summary" — stashed on the trainer so callers/tests see it
                self.self_healing_summary = self.health.report()
                logger.info(f"self-healing summary: {self.self_healing_summary}")
            self.on_learn_end()
            # after the engine drain: the writer flush below may be the
            # emergency checkpoint, and the producer must not race it
            self.resilience.close()
            # after on_learn_end: producer teardown spans still get recorded
            self.obs.close()

    def _maybe_resume(self, train_config):
        """Restore from an explicit resume path (missing → hard error, never a
        silent fresh start) or, under resilience auto-resume, from the newest
        *committed* checkpoint in checkpoint_dir. Runs BEFORE prepare_learning
        so the first rollouts already use the restored params, RNG streams,
        and prompt-stream position."""
        path = train_config.resume_from_checkpoint
        if path:
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"train.resume_from_checkpoint={path!r} does not exist; "
                    "refusing to silently train from scratch"
                )
            self.load(path)
            return
        if self.resilience.auto_resume:
            latest = find_latest_committed(train_config.checkpoint_dir)
            if latest is not None:
                logger.info(f"Auto-resume: restoring newest committed checkpoint {latest}")
                self.load(latest)

    def _learn_loop(self):
        train_config = self.config.train
        self.iter_count = 0
        self._maybe_resume(train_config)
        self.prepare_learning()
        self.obs.configure_model(self.params, getattr(self, "model_config", None))
        self.obs.beat("learner")

        with self.obs.span("evaluate"):
            results = self.evaluate() if getattr(self, "eval_pipeline", None) else {}
        self.tracker.log(results, self.iter_count)
        if self.iter_count >= train_config.total_steps:
            # resumed at (or past) the end of training: nothing left to do
            self._report_sweep_result(results)
            return results

        profiling = False
        try:
            for epoch in range(train_config.epochs):
                for batch in self.create_train_dataloader():
                    if train_config.profile_dir:
                        if self.iter_count == train_config.profile_start_step and not profiling:
                            jax.profiler.start_trace(train_config.profile_dir)
                            profiling = True
                        elif self.iter_count >= train_config.profile_end_step and profiling:
                            jax.profiler.stop_trace()
                            profiling = False
                    # chaos site "nan-loss": poison the batch to non-finite
                    # (free when unarmed) — the health guard must catch it
                    batch = chaos_poison_batch(batch)
                    self.clock.tick()  # reset: measure train_step alone
                    # drop the rollout param copy BEFORE the step: fwd+bwd+update is
                    # the peak-memory window and the copy is stale after it anyway
                    self._rollout_params = None
                    with self.obs.span("learn"):
                        stats = self.train_step(batch)
                    stats["time/forward_backward"] = self.clock.tick()
                    self.iter_count += 1
                    self.obs.beat("learner")
                    self.post_backward_callback()

                    if self.health is not None:
                        action = self.health.observe(stats, self.iter_count)
                        if action == "rollback":
                            # may raise TrainingHealthError when the budget is
                            # exhausted (fail closed, diagnostics bundle path
                            # in the message)
                            self._handle_health_rollback()
                            # the rest of this epoch's batches came from the
                            # anomalous policy — re-collect experience instead
                            # (post_epoch_callback refills the store)
                            break

                    if self.resilience.should_stop(self.iter_count):
                        return self._preempt_exit(stats)

                    if (
                        train_config.checkpoint_interval
                        and self.iter_count % train_config.checkpoint_interval == 0
                    ):
                        with self.obs.span("checkpoint"):
                            self._save_checkpoint(
                                os.path.join(train_config.checkpoint_dir, self._checkpoint_name())
                            )
                            self.save_pretrained(os.path.join(train_config.checkpoint_dir, "hf_model"))

                    if (
                        train_config.eval_interval
                        and self.iter_count % train_config.eval_interval == 0
                    ) or self.iter_count >= train_config.total_steps:
                        with self.obs.span("evaluate"):
                            results = self.evaluate() if getattr(self, "eval_pipeline", None) else {}
                        self.obs.beat("learner")  # a long eval is not a stall
                        stats.update(results)
                        if train_config.save_best and "reward/mean" in results:
                            # under SPMD every process computes the same global reward,
                            # replacing the reference's MAX all-reduce guard (:616-638)
                            if results["reward/mean"] > self.best_reward:
                                self.best_reward = results["reward/mean"]
                                self._save_checkpoint(
                                    os.path.join(train_config.checkpoint_dir, "best_checkpoint")
                                )
                        if self._sweep_tick(results):
                            # ASHA early stop: exit cleanly (no signals — killing a
                            # jax process mid-TPU-claim can wedge the chip tunnel)
                            logger.info("Sweep scheduler requested early stop")
                            self._report_sweep_result(results)
                            return results

                    if self.obs.enabled:
                        tokens, samples, seq_len = batch_token_count(batch)
                        stats.update(self.obs.step_stats(tokens, samples, seq_len))
                    stats = {k: significant(v) if isinstance(v, float) else v for k, v in stats.items()}
                    self.tracker.log(stats, self.iter_count)
                    if self.iter_count % 10 == 0 or self.iter_count == 1:
                        brief = {k: v for k, v in stats.items() if "loss" in k or "reward" in k}
                        logger.info(f"step {self.iter_count}/{train_config.total_steps} {brief}")

                    if self.iter_count >= train_config.total_steps:
                        # padded like the interval checkpoints, so the dir's
                        # lexicographic order is chronological (resume relies on it)
                        self._save_checkpoint(
                            os.path.join(train_config.checkpoint_dir, self._checkpoint_name())
                        )
                        self._report_sweep_result(results)
                        return results
                self.post_epoch_callback(epoch)
        finally:
            # the profiler window must close however the loop exits (total_steps
            # return, sweep early stop, or an exception mid-window) — otherwise
            # jax.profiler.stop_trace() is never called and the trace is lost
            if profiling:
                jax.profiler.stop_trace()
        self._report_sweep_result(results)
        return results

    def _sweep_tick(self, results) -> bool:
        """Under a sweep: report intermediate metrics (consumed by the ASHA
        scheduler in trlx_tpu/sweep.py) and poll the stop file. Returns True if
        the scheduler asked this trial to stop."""
        if not os.environ.get("TRLX_SWEEP"):
            return False
        if jax.process_index() == 0:
            print(
                "SWEEP_METRIC "
                + json.dumps({"step": self.iter_count, **filter_non_scalars(results or {})}),
                flush=True,
            )
        # The stop decision must be COLLECTIVE: rank 0 reads the file and the
        # result is broadcast, so every rank returns from learn() together (a
        # per-rank filesystem poll could race the file's creation and leave the
        # mesh with a missing participant)
        stop_file = os.environ.get("TRLX_SWEEP_STOP_FILE")
        stop = bool(stop_file and os.path.exists(stop_file)) if jax.process_index() == 0 else False
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            stop = bool(multihost_utils.broadcast_one_to_all(jnp.asarray(stop)))
        return stop

    def _report_sweep_result(self, results):
        """Final-metrics line consumed by the sweep runner (trlx_tpu/sweep.py)."""
        if os.environ.get("TRLX_SWEEP") and jax.process_index() == 0:
            print("SWEEP_RESULT " + json.dumps(filter_non_scalars(results or {})), flush=True)

    # ------------------------------------------------------------- checkpoints

    def _checkpoint_name(self, it: Optional[int] = None) -> str:
        """``checkpoint_<step>`` zero-padded to total_steps' width, so the
        checkpoint dir's lexicographic order equals chronological order (the
        resume scan additionally parses legacy unpadded names numerically)."""
        it = self.iter_count if it is None else it
        return f"checkpoint_{it:0{len(str(self.config.train.total_steps))}d}"

    def _state_dict(self) -> Dict[str, Any]:
        """Full JSON-serializable trainer state for ``state.json``: counters,
        both RNG streams (jax sampling key + host numpy generator), and
        algorithm extras (PPO's prompt-stream position) — everything needed to
        continue the exact sample sequence after a restart."""
        from trlx_tpu.resilience.resume import pack_np_rng, pack_rng_key

        return {
            "iter_count": self.iter_count,
            "best_reward": self.best_reward,
            "nth_evaluation": self.nth_evaluation,
            "rng_key": pack_rng_key(self.rng),
            "np_rng_state": pack_np_rng(self.np_rng),
            **self._extra_state(),
        }

    def _extra_state(self) -> Dict[str, Any]:
        """Algorithm-specific additions to state.json (override in subclasses)."""
        return {}

    def _restore_extra_state(self, state: Dict[str, Any]):
        """Inverse of :meth:`_extra_state` (state.json dict, already loaded)."""
        pass

    def _save_checkpoint(self, directory: str, block: bool = False):
        """Route one checkpoint through the resilience async writer when
        available (host snapshot now, serialize + atomic commit on the writer
        thread; only waits if a *prior* write is still in flight) or the
        synchronous :meth:`save` otherwise. ``block=True`` is the emergency-
        checkpoint path: the commit must land inside the grace window."""
        writer = self.resilience.writer
        if writer is None:
            self.save(directory)
            return
        # host snapshot before returning to the loop: the next train step
        # donates the device buffers, so the writer must never touch them
        trees = {"params": jax.device_get(self.params)}
        if self.config.train.save_optimizer:
            trees["opt_state"] = jax.device_get(self.opt_state)
        writer.save(os.path.abspath(directory), trees, self._state_dict(), block=block)

    def _preempt_exit(self, results):
        """Preemption path: blocking emergency checkpoint inside the grace
        window, then a clean return (``learn()``'s finally drains the rollout
        engine, flushes the writer, and closes the trackers)."""
        handler = self.resilience.preemption
        grace = handler.grace_remaining_s
        logger.warning(
            f"Preempted ({handler.reason}); writing emergency checkpoint at "
            f"step {self.iter_count} ({grace:.0f}s of grace remaining)"
        )
        path = os.path.join(self.config.train.checkpoint_dir, self._checkpoint_name())
        with self.obs.span("checkpoint"):
            self._save_checkpoint(path, block=True)
        remaining = handler.grace_remaining_s
        if remaining is not None and remaining < 0:
            logger.warning(
                f"Emergency checkpoint exceeded the grace window by {-remaining:.0f}s "
                "— raise resilience.grace_period_s or shrink checkpoint_interval"
            )
        self._report_sweep_result(results)
        return results

    def _handle_health_rollback(self):
        """Escalation-ladder step 2/3: the health guard saw ``rollback_after``
        consecutive anomalies. Restore the newest committed checkpoint if the
        rollback budget allows, else halt (raises :class:`TrainingHealthError`
        with a diagnostics bundle path — fail closed, never spin forever)."""
        if not self.health.rollback_budget_left():
            self.health.halt(
                self.iter_count,
                f"rollback budget exhausted ({self.health.config.max_rollbacks}) "
                f"with anomalies still occurring",
            )
        restored = self._health_rollback()
        self.health.on_rollback(self.iter_count, restored)

    def _health_rollback(self) -> bool:
        """Restore the newest committed checkpoint (exact-resume semantics:
        iter_count, RNG streams, prompt-stream position). Returns False when
        no committed checkpoint exists yet — the guard still burns a unit of
        rollback budget so a run that anomalizes before its first checkpoint
        cannot loop forever."""
        target = None
        writer = self.resilience.writer
        if writer is not None:
            # an in-flight async commit may be the freshest good state; wait
            # for it (this also re-raises any writer error now, not later)
            writer.wait()
            target = writer.last_committed
        if target is None:
            target = find_latest_committed(self.config.train.checkpoint_dir)
        if target is None:
            logger.warning(
                f"health rollback requested but no committed checkpoint exists "
                f"in {self.config.train.checkpoint_dir} — continuing with "
                f"current (possibly damaged) state"
            )
            return False
        self.load(target)
        self._post_rollback_restore()
        return True

    def _post_rollback_restore(self):
        """Re-anchor run state that :meth:`load` cannot rebuild by itself
        after a *mid-run* restore (vs. startup resume). Subclasses override:
        PPO rebuilds its prompt stream and republishes the restored params to
        the async producer."""
        pass

    def save(self, directory: str):
        """Sharded checkpoint (params, opt_state, state.json) via orbax (parity:
        accelerator.save_state, accelerate_base_trainer.py:309-317). state.json
        is written atomically (tmp file + rename) and the ``_COMMITTED``
        sentinel lands last, marking the directory complete — :meth:`load`
        warns when it is missing and auto-resume skips such torn dirs."""
        import orbax.checkpoint as ocp

        from trlx_tpu.resilience.checkpoint import STATE_FILE, mark_committed, write_json_atomic

        path = os.path.abspath(directory)
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.join(path, "params"), self.params, force=True)
        if self.config.train.save_optimizer:
            ckptr.save(os.path.join(path, "opt_state"), self.opt_state, force=True)
        ckptr.wait_until_finished()
        if jax.process_index() == 0:
            write_json_atomic(os.path.join(path, STATE_FILE), self._state_dict())
            mark_committed(path)
        logger.info(f"Saved checkpoint to {path}")

    def load(self, directory: str):
        """Restore a checkpoint saved by :meth:`save` (parity:
        accelerate_base_trainer.py:318-333)."""
        import orbax.checkpoint as ocp

        from trlx_tpu.resilience.checkpoint import is_committed

        path = os.path.abspath(directory)
        if not is_committed(path):
            logger.warning(
                f"Checkpoint {path} has no _COMMITTED sentinel — it may be torn "
                "(interrupted write) or predate atomic saves; restoring anyway "
                "since it was requested explicitly"
            )
        ckptr = ocp.StandardCheckpointer()

        def restore_like(sub, template):
            """Restore + re-place every leaf on its template sharding: orbax can
            hand back single-device arrays for scalar leaves (observed: a resumed
            adam `count` landed on device 0 while params spanned the mesh, and
            the next train_step died with 'incompatible devices')."""
            restored = ckptr.restore(sub, template)
            return jax.tree.map(
                lambda r, t: (
                    jax.device_put(r, t.sharding)
                    if isinstance(t, jax.Array) and r.sharding != t.sharding
                    else r
                ),
                restored, template,
            )

        self.params = restore_like(os.path.join(path, "params"), self.params)
        self._rollout_params = None
        opt_path = os.path.join(path, "opt_state")
        if os.path.exists(opt_path) and self.config.train.save_optimizer:
            self.opt_state = restore_like(opt_path, self.opt_state)
        state_path = os.path.join(path, "state.json")
        if os.path.exists(state_path):
            from trlx_tpu.resilience.resume import restore_np_rng, unpack_rng_key

            with open(state_path) as f:
                state = json.load(f)
            self.iter_count = state.get("iter_count", 0)
            self.best_reward = state.get("best_reward", -float("inf"))
            self.nth_evaluation = state.get("nth_evaluation", self.nth_evaluation)
            if state.get("rng_key") is not None:
                self.rng = unpack_rng_key(state["rng_key"], self.rng)
            if state.get("np_rng_state") is not None:
                restore_np_rng(self.np_rng, state["np_rng_state"])
            self._restore_extra_state(state)
        logger.info(f"Restored checkpoint from {path} (iter {self.iter_count})")

    def save_pretrained(self, directory: str):
        """Export the trunk in HF format + heads as msgpack (parity:
        accelerate_base_trainer.py:284-307; heads-only extras mirror the peft
        state-dict surgery in modeling_base.py:347-353)."""
        from flax.serialization import to_bytes

        from trlx_tpu.models.hf_loading import save_pretrained_hf

        params = jax.device_get(self.params)
        trunk_key = "transformer" if "transformer" in params else ("t5" if "t5" in params else None)
        trunk = params[trunk_key] if trunk_key else params
        if isinstance(trunk, dict) and "layers_scan" in trunk:
            # HF layout is per-layer: unstack the pipeline layout before export
            from trlx_tpu.parallel.pipeline import unstack_layer_params

            trunk = unstack_layer_params(trunk, self.model_config.num_layers)
        if getattr(self.model_config, "lora_r", 0):
            from trlx_tpu.models.transformer import merge_lora_params

            trunk = merge_lora_params(trunk, self.model_config)
        os.makedirs(directory, exist_ok=True)
        if jax.process_index() == 0:
            try:
                save_pretrained_hf(directory, self.model_type, trunk, self.model_config)
            except Exception as e:
                logger.warning(f"HF export unavailable ({e}); saving native params only")
            heads = {k: v for k, v in params.items() if k != trunk_key}
            if heads:
                with open(os.path.join(directory, "heads.msgpack"), "wb") as f:
                    f.write(to_bytes(heads))
            if self.config.model.peft_config:
                from trlx_tpu.models.hf_loading import save_adapters

                save_adapters(directory, params)


def _opt_step_count(opt_state) -> jnp.ndarray:
    """Best-effort extraction of the optax step count for LR logging."""
    leaves = jax.tree.leaves(opt_state)
    for leaf in leaves:
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.integer) and leaf.ndim == 0:
            return leaf
    return jnp.array(0)
