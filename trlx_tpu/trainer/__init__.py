"""Trainer registry + abstract base (parity:
`/root/reference/trlx/trainer/__init__.py:9-64`). Importing this package registers
the built-in trainers."""

from abc import abstractmethod
from typing import Any, Callable, Dict, Iterable, List, Optional

from trlx_tpu.data.configs import TRLConfig

from trlx_tpu.utils.registry import make_registry

_TRAINERS: Dict[str, type] = {}

#: Decorator registering a trainer class by (lowercased) name.
register_trainer = make_registry(_TRAINERS)


class BaseRLTrainer:
    """Abstract trainer protocol: {learn, push_to_store, add pipelines}."""

    def __init__(
        self,
        config: TRLConfig,
        reward_fn: Optional[Callable] = None,
        metric_fn: Optional[Callable] = None,
        stop_sequences: Optional[List[str]] = None,
        **kwargs,
    ):
        self.config = config
        self.reward_fn = reward_fn
        self.metric_fn = metric_fn
        self.stop_sequences = stop_sequences or []

    def push_to_store(self, data):
        self.store.push(data)

    def add_prompt_pipeline(self, pipeline):
        """Attach the rollout prompt pipeline (PPO)."""
        raise NotImplementedError

    def add_eval_pipeline(self, eval_pipeline):
        self.eval_pipeline = eval_pipeline

    @abstractmethod
    def learn(self):
        """Run the training loop."""
        ...


from trlx_tpu.trainer.mesh_trainer import MeshRLTrainer  # noqa: E402,F401
from trlx_tpu.trainer.ppo_trainer import PPOTrainer  # noqa: E402,F401
from trlx_tpu.trainer.grpo_trainer import GRPOTrainer  # noqa: E402,F401
from trlx_tpu.trainer.ilql_trainer import ILQLTrainer  # noqa: E402,F401
from trlx_tpu.trainer.sft_trainer import SFTTrainer  # noqa: E402,F401
from trlx_tpu.trainer.rft_trainer import RFTTrainer  # noqa: E402,F401
