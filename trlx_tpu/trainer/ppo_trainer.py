"""PPO trainer (parity: `/root/reference/trlx/trainer/accelerate_ppo_trainer.py:35-553`):
rollout store management, hydra-vs-full reference model, KL controllers, the
``make_experience`` pipeline (generate → reward → logprob/value/ref passes → KL
penalty → rollout store), and the PPO loss driver.

TPU-first shape: rollout generation and the scoring forwards are jitted fixed-shape
SPMD programs; the reference's rank-0 ``broadcast``/``scatter`` of reward scores
(:325-338) disappears because reward_fn runs on the single controller and scores are
placed onto the mesh with the batch.
"""

import os
import time
from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from trlx_tpu.analysis.rt import contracts as rt_contracts
from trlx_tpu.analysis.rt import seeds as rt_seeds
from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.ppo_types import PPORLBatch, PPORLElement
from trlx_tpu.methods.ppo import PPOConfig
from trlx_tpu.models.hf_loading import load_pretrained
from trlx_tpu.models.policy import (
    CausalLMWithValueHead,
    branch_param_subtree,
)
from trlx_tpu.models.transformer import TransformerLM
from trlx_tpu.obs import span
from trlx_tpu.obs.flight import flight
from trlx_tpu.parallel import mesh as mesh_lib
from trlx_tpu.parallel.sharding import make_param_shardings
from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage
from trlx_tpu.resilience.quarantine import chaos_corrupt_elements
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.mesh_trainer import MeshRLTrainer
from trlx_tpu.utils import infinite_loader, logging
from trlx_tpu.utils.metrics import gauges
from trlx_tpu.utils.modeling import RunningMoments, flatten_dict, logprobs_of_labels

logger = logging.get_logger(__name__)

#: Max distinct response-length buckets the streaming path may compile per
#: (B, P) score-fn family — the recompile bound docs/serving.md documents.
#: Sourced from the declared ``stream_score_ladder`` shape contract
#: (trlx_tpu/analysis/rt/contracts.py) so the runtime guard, the SH001
#: sanction list, and the CompileWatcher probe all share one number.
_STREAM_MAX_R_BUCKETS = rt_contracts.get("stream_score_ladder").max_shapes

#: the shared pow2 padding ladder (8 .. 8192) every bucketing path draws from
_POW2_BUCKETS = [2 ** i for i in range(3, 14)]


def overlap_r_buckets(max_new: int) -> List[int]:
    """The quantized response-length ladder for streaming microbuckets:
    ≤ :data:`_STREAM_MAX_R_BUCKETS` pow2 shapes covering up to
    ``max_new + 1`` (decode may re-append eos)."""
    from trlx_tpu.ops.generation import pad_to_bucket

    top = max(1, max_new + 1)
    # ceil(top / d) for d in 8,4,2,1 — dedup after pow2 padding keeps the
    # ladder at <= 4 entries with the full shape always present
    return sorted({pad_to_bucket(max(1, -(-top // d)), _POW2_BUCKETS) for d in (8, 4, 2, 1)})


def quantize_stream_response(r: int, ladder: List[int]) -> int:
    """Snap a raw completion length onto the streaming ladder — the ONLY
    sanctioned path from a data-dependent ``len()`` to the jitted score fn's
    R dimension (declared in the ``stream_score_ladder`` shape contract).

    ``TRLX_RT_SEED_REGRESSION=shape_churn`` makes this return the raw length
    — the unbucketed-shape defect the compile gate must catch (ci.sh proves
    the gate fails closed; see trlx_tpu/analysis/rt/seeds.py)."""
    from trlx_tpu.ops.generation import pad_to_bucket

    if rt_seeds.shape_churn():
        return r
    for cand in ladder:
        if r <= cand:
            return cand
    return pad_to_bucket(r, _POW2_BUCKETS)  # defensive; the ladder covers max_new+1


def check_stream_bucket_family(families, B: int, P: int, R: int, limit: int = _STREAM_MAX_R_BUCKETS):
    """Record R under the (B, P) family and assert the family stays bounded.

    Varied completion lengths must quantize onto a fixed small ladder of
    padded shapes (``_overlap_r_buckets``); a shape escaping the ladder means
    unbounded jit recompiles, which this turns into a loud failure instead of
    a silent compile storm."""
    fam = families.setdefault((B, P), set())
    fam.add(R)
    if len(fam) > limit:
        raise AssertionError(
            f"streaming score-fn bucket family (B={B}, P={P}) grew to "
            f"{sorted(fam)}; the response-length quantizer must keep "
            f"<= {limit} shapes per family"
        )


@register_trainer
class PPOTrainer(MeshRLTrainer):
    def __init__(self, config: TRLConfig, **kwargs):
        super().__init__(config, **kwargs)
        if not isinstance(config.method, PPOConfig):
            raise ValueError("PPOTrainer requires method=PPOConfig")
        self.method: PPOConfig = config.method

        self.store = PPORolloutStorage(self.tokenizer.pad_token_id)
        self.kl_ctl = self.method.kl_controller()
        self.running_moments = RunningMoments()
        self.mean_kl = 0.0
        self.rollout_stats: Dict[str, float] = {}
        self._score_fns = {}
        # (B, P) -> set of R shapes compiled through the streaming path; the
        # quantizer in _overlap_r_buckets must keep each family bounded
        self._score_fn_families = {}
        self._train_steps = {}

        # async rollout engine state (trlx_tpu/rollout; resolved in
        # prepare_learning — None means the synchronous path). Under
        # train.self_healing this is a ProducerSupervisor wrapping engine
        # generations; it exposes the same surface
        self._engine = None
        self._async_cfg = None
        self._policy_version = 0

        # generation-island runtime (trlx_tpu/serving/island; resolved in
        # _start_async_engine when train.islands is enabled). None keeps the
        # trainer byte-identical to the monolithic publish path.
        self._island = None

        # prompt-stream position (trlx_tpu/resilience): draws from the
        # infinite prompt iterator, checkpointed and replayed on resume so a
        # restarted run continues the exact prompt sequence
        self._prompt_batches_drawn = 0
        self._resume_prompt_batches = 0
        self._prompt_pipeline = None

        # continuous-batching serving engine (trlx_tpu/serving; resolved in
        # prepare_learning). None = the one-shot generate path. When set,
        # _generate_chunks routes generation through the GenerationClient;
        # decode/reward/scoring/quarantine downstream are identical.
        self._serving_client = None
        self._serving_engine = None
        self._serving_autoscaler = None
        self._serving_max_new = 0
        self._serving_min_new = 0
        self._serving_param_ref = None

        # experience quarantine (trlx_tpu/resilience/quarantine): screens
        # every assembled PPORLElement when self-healing is on; None = the
        # historical trust-everything behavior
        self._quarantine = None
        sh_config = config.train.self_healing
        if sh_config.enabled:
            from trlx_tpu.resilience.quarantine import ExperienceQuarantine

            self._quarantine = ExperienceQuarantine(
                sh_config.quarantine_dir
                or os.path.join(config.train.checkpoint_dir, "quarantine")
            )

        if config.train.rollout_logging_dir is not None:
            self.log_rollouts = True
            self.setup_rollout_logging(config)
        else:
            self.log_rollouts = False

    # ------------------------------------------------------------------ model

    def setup_model(self):
        """Build policy+value model; reference model is either the hydra frozen
        top-branch (num_layers_unfrozen > 0) or a full frozen param copy
        (parity: get_arch + ref_model setup, accelerate_ppo_trainer.py:65-108).
        ``model_arch_type == "seq2seq"`` selects the T5 path (parity:
        modeling_ppo.py:1242-1350)."""
        self.is_seq2seq = self.config.model.model_arch_type == "seq2seq"
        # validates mesh.pipe combinations (incl. rejecting seq2seq) regardless
        # of which arch branch runs below
        pp_overrides = self.pipeline_overrides()
        overrides = dict(self.config.model.model_overrides or {})
        overrides.setdefault("param_dtype", self.param_dtype)
        overrides.setdefault("compute_dtype", self.compute_dtype)
        if self.is_seq2seq:
            self._setup_seq2seq_model(overrides)
            return
        # per-scale remat override for the overlapped learner (docs/parallelism.md
        # "Learner overlap & FSDP"): learner_overlap.remat, when set, beats
        # mesh.remat but still yields to explicit model_overrides
        lov = getattr(self.config.train, "learner_overlap", None)
        if lov is not None and lov.enabled and lov.remat is not None:
            overrides.setdefault("remat", lov.remat)
        if lov is not None and lov.enabled and lov.flash_bwd is not None:
            # captured at trace time, so set before the step is first jitted
            from trlx_tpu.ops.attention import set_flash_backward

            set_flash_backward(lov.flash_bwd)
        overrides.setdefault("remat", self.config.mesh.remat)
        overrides.setdefault("sequence_sharding", self.config.mesh.sequence_shard)
        from trlx_tpu.models.hf_loading import merge_loaded_params, peft_overrides

        overrides.update(peft_overrides(self.config.model.peft_config))
        overrides.update(pp_overrides)
        self.model_config, trunk_params, self.model_type = load_pretrained(
            self.config.model.model_path, overrides, mesh=self.restore_mesh(overrides)
        )
        trunk_params = self.maybe_stack_loaded(trunk_params, self.model_config.num_layers)
        self.module = CausalLMWithValueHead(
            self.model_config,
            num_value_layers=getattr(self.config.method, "num_value_layers_unfrozen", 0),
        )
        self.trunk_module = TransformerLM(self.model_config)

        params = self.module.init(
            jax.random.PRNGKey(self.config.train.seed),
            jnp.zeros((1, 2), jnp.int32),
            jnp.ones((1, 2), jnp.int32),
        )["params"]
        if trunk_params is not None:
            params = dict(params)
            params["transformer"] = merge_loaded_params(params["transformer"], trunk_params)
        n_value_layers = getattr(self.config.method, "num_value_layers_unfrozen", 0)
        if n_value_layers > 0:
            from trlx_tpu.models.policy import init_value_branch_from_trunk

            params = init_value_branch_from_trunk(params, self.model_config, n_value_layers)

        shardings = make_param_shardings(params, self.mesh)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x, self.param_dtype), s), params, shardings
        )

        # The reference copies must NOT alias self.params: the train step donates
        # its input buffers (real buffer reuse on TPU), so aliased frozen params
        # would be deleted after the first optimizer step.
        def device_copy(tree):
            with self.mesh:
                return jax.jit(lambda t: jax.tree.map(lambda x: x.copy(), t))(tree)

        n_unfrozen = self.config.model.num_layers_unfrozen
        if n_unfrozen > self.model_config.num_layers:
            raise ValueError(
                f"num_layers_unfrozen={n_unfrozen} exceeds num_layers={self.model_config.num_layers}"
            )
        self.peft_base_ref = bool(self.config.model.peft_config)
        if self.config.model.offload_ref and (self.peft_base_ref or n_unfrozen > 0):
            # only the FULL-copy reference lives in HBM at size worth offloading;
            # hydra/peft refs are already the cheap option — say so instead of
            # silently ignoring the flag
            logger.warning(
                "offload_ref ignored: the reference is a hydra branch / disabled-"
                "adapter view (num_layers_unfrozen > 0 or peft), not a full copy"
            )
        if self.peft_base_ref:
            # peft mode: the trunk is frozen and only adapters train, so the KL
            # reference is the SAME params applied through a module with the
            # adapters structurally disabled (flax ignores the extra adapter
            # entries) — the reference's disable_adapter() forward_hydra path
            # (modeling_ppo.py:410-453) with zero extra memory.
            self.base_trunk_module = TransformerLM(
                self.model_config.replace(lora_r=0, peft_type="none", num_virtual_tokens=0)
            )
            self.branch_start = None
            self.frozen_branch_params = None
            self.ref_params = None
        elif n_unfrozen > 0:
            self.branch_start = self.model_config.num_layers - n_unfrozen
            branch = branch_param_subtree(self.params["transformer"], self.branch_start, self.model_config)
            self.frozen_branch_params = device_copy(branch)
            self.ref_params = None
        else:
            self.branch_start = None
            self.frozen_branch_params = None
            if self.config.model.offload_ref:
                self._setup_ref_offload(self.params["transformer"], shardings["transformer"])
                self.ref_params = None
            else:
                self.ref_params = device_copy(self.params["transformer"])

    def _setup_seq2seq_model(self, overrides):
        from trlx_tpu.models.hf_loading import load_pretrained_seq2seq, t5_peft_overrides
        from trlx_tpu.models.policy import Seq2SeqLMWithValueHead

        peft = t5_peft_overrides(self.config.model.peft_config)
        if peft:
            overrides = {**(overrides or {}), **peft}

        self.model_config, t5_params = load_pretrained_seq2seq(
            self.config.model.model_path, overrides, mesh=self.mesh
        )
        self.model_type = "t5"
        self.peft_base_ref = bool(peft)
        self.decoder_start_token_id = self.model_config.decoder_start_token_id
        self.module = Seq2SeqLMWithValueHead(self.model_config)
        params = self.module.init(
            jax.random.PRNGKey(self.config.train.seed),
            jnp.ones((1, 4), jnp.int32), jnp.ones((1, 4), jnp.int32),
            jnp.zeros((1, 2), jnp.int32),
        )["params"]
        if t5_params is not None:
            params = dict(params)
            params["t5"] = t5_params
        shardings = make_param_shardings(params, self.mesh)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x, self.param_dtype), s), params, shardings
        )

        # seq2seq reference model: with num_layers_unfrozen > 0, a frozen copy of
        # just the top-N decoder blocks (+ final LN + head) — the reference's
        # T5Branch shape (modeling_ppo.py:1483-1593); otherwise a full frozen copy
        def device_copy(tree):
            with self.mesh:
                return jax.jit(lambda t: jax.tree.map(lambda x: x.copy(), t))(tree)

        n_unfrozen = self.config.model.num_layers_unfrozen
        if n_unfrozen > self.model_config.num_decoder_layers:
            raise ValueError(
                f"num_layers_unfrozen={n_unfrozen} exceeds "
                f"num_decoder_layers={self.model_config.num_decoder_layers}"
            )
        if self.config.model.offload_ref and (
            self.peft_base_ref or 0 < n_unfrozen < self.model_config.num_decoder_layers
        ):
            logger.warning(
                "offload_ref ignored: the seq2seq reference is a decoder branch /"
                " disabled-adapter view, not a full copy"
            )
        if self.peft_base_ref:
            # adapters-only training: the KL reference is the SAME t5 params
            # applied through a module with LoRA structurally disabled (mirrors
            # the causal peft path / reference disable_adapter() forward_hydra)
            from trlx_tpu.models.t5 import T5LM

            self.base_t5_module = T5LM(self.model_config.replace(lora_r=0))
            self.branch_start = None
            self.frozen_branch_params = None
            self.ref_params = None
        elif 0 < n_unfrozen < self.model_config.num_decoder_layers:
            from trlx_tpu.models.policy import t5_branch_param_subtree

            self.branch_start = self.model_config.num_decoder_layers - n_unfrozen
            branch = t5_branch_param_subtree(self.params["t5"], self.branch_start, self.model_config)
            self.frozen_branch_params = device_copy(branch)
            self.ref_params = None
        else:
            # n_unfrozen in (-1, 0, num_decoder_layers): full frozen copy. The
            # all-layers-unfrozen case cannot use the branch — the branch reuses
            # the live model's decoder-block-0 relative bias, which would then
            # be training.
            self.branch_start = None
            self.frozen_branch_params = None
            if self.config.model.offload_ref:
                self._setup_ref_offload(self.params["t5"], shardings["t5"])
                self.ref_params = None
            else:
                self.ref_params = device_copy(self.params["t5"])

    def _setup_ref_offload(self, tree, shardings):
        """Keep the full frozen KL-reference in HOST memory (ModelConfig.offload_ref):
        pinned-host placement where the backend supports memory kinds (TPU), host
        numpy otherwise (single-host only — multi-host uses the pinned path). The
        ref streams onto the device per rollout-scoring phase and is dropped for
        the update phase, where HBM actually peaks — the reference's NeMo
        CPU-pinned policy/ref swap (modeling_nemo_ppo.py:228-312)."""
        self._ref_shardings = shardings
        self._ref_dev = None
        try:
            host_sh = jax.tree.map(lambda s: s.with_memory_kind("pinned_host"), shardings)
            self._ref_host = jax.device_put(tree, host_sh)
            jax.block_until_ready(self._ref_host)
            self._ref_host_kind = "pinned_host"
        except Exception as e:
            # the numpy fallback gathers the whole tree to one host, which is
            # only correct (and only possible — np.asarray of a non-addressable
            # sharded jax.Array raises) in a single-process run; on multi-host
            # a pinned_host failure is a real configuration error, not
            # something to paper over (ADVICE r4)
            if jax.process_count() > 1:
                raise
            logger.info(f"offload_ref: pinned_host placement unavailable ({type(e).__name__}: {e}); "
                        "falling back to host numpy copies")
            self._ref_host = jax.tree.map(lambda x: np.asarray(x), tree)
            self._ref_host_kind = "numpy"
        logger.info(f"offload_ref: frozen reference held in {self._ref_host_kind} host memory")

    def _ref_scoring_params(self):
        """Device view of the ref params for the scoring forward; materialized
        once per rollout phase (released by :meth:`_release_ref`)."""
        if getattr(self, "_ref_host", None) is None:
            return self.ref_params
        if self._ref_dev is None:
            with self.mesh:
                self._ref_dev = jax.device_put(self._ref_host, self._ref_shardings)
        return self._ref_dev

    def _pin_ref(self):
        """Pin the device ref view for a whole streaming window: materialize it
        once up front and make :meth:`_release_ref` a no-op until
        :meth:`_unpin_ref`. Without the pin, any release inside the window
        would force per-bucket host→device re-uploads of the full reference
        tree — exactly the transfer the streaming path exists to hide."""
        self._ref_pinned = True
        if getattr(self, "_ref_host", None) is not None:
            self._ref_scoring_params()

    def _unpin_ref(self):
        """End of the streaming window (stream drain): allow release again."""
        self._ref_pinned = False

    def _release_ref(self):
        """Free the device ref copy after make_experience (no-op unless
        offloaded; deferred while a streaming window holds the pin)."""
        if getattr(self, "_ref_pinned", False):
            return
        self._ref_dev = None

    def trainable_path_predicate(self, path: str) -> bool:
        if getattr(self, "is_seq2seq", False):
            if self.config.model.peft_config:
                # adapters + heads only — the generic predicate already treats
                # the t5 trunk like the causal transformer trunk
                return super().trainable_path_predicate(path)
            n_unfrozen = self.config.model.num_layers_unfrozen
            if n_unfrozen < 0 or "t5" not in path:
                return True
            # freeze encoder + bottom decoder blocks; top-N decoder blocks + heads train
            if "decoder_blocks_" in path:
                layer = int(path.split("decoder_blocks_")[1].split("/")[0])
                return layer >= self.model_config.num_decoder_layers - n_unfrozen
            return "decoder_ln" in path
        return super().trainable_path_predicate(path)

    # ------------------------------------------------------------- generation

    def seq2seq_gen_fns(self):
        module = self.module

        return {
            "encode": lambda params, ids, mask: module.apply(
                {"params": params}, ids, mask, method=module.encode
            ),
            "cross_kv": lambda params, enc: module.apply(
                {"params": params}, enc, method=module.precompute_cross_kv
            ),
            "decode": lambda params, tok, enc, enc_mask, dec_mask, pos, cache, ckv: module.apply(
                {"params": params}, tok, enc, enc_mask, dec_mask, pos, cache, ckv,
                method=module.decode_step,
            ),
            "init_cache": lambda params, b, n: self._t5_module().init_cache(b, n),
        }

    def _t5_module(self):
        from trlx_tpu.models.t5 import T5LM

        return T5LM(self.model_config)

    def gen_step_fn(self):
        trunk = self.trunk_module

        def step(params, ids, mask, positions, cache):
            logits, hidden, _, cache = trunk.apply(
                {"params": params["transformer"]}, ids, mask, positions, cache
            )
            return logits, hidden, cache

        init_cache = lambda b, s: trunk.init_cache(b, s)
        return step, init_cache

    # ------------------------------------------------------------- experience

    def add_prompt_pipeline(self, pipeline):
        """Attach the prompt pipeline for rollouts (parity: :245-249). The loader
        batches ``decode_batch_size`` prompts (generation is bandwidth-bound and
        wants the widest batch that fits); reward/scoring still run per
        ``chunk_size`` sub-chunk."""
        batch = self.method.decode_batch_size or self.method.chunk_size
        # kept so a health-guard rollback can rebuild the stream from scratch
        # and replay draws to the restored position (an iterator can't rewind)
        self._prompt_pipeline = pipeline
        loader = pipeline.create_loader(batch, shuffle=True, seed=self.config.train.seed)
        stream = infinite_loader(loader)
        lookahead = self.config.train.async_rollouts.length_bucket_lookahead
        if lookahead > 1:
            from trlx_tpu.rollout.engine import length_bucketed

            stream = length_bucketed(stream, lookahead)
        self.prompt_iterator = stream

    def setup_rollout_logging(self, config):
        import os
        import uuid

        self.run_id = f"run-{uuid.uuid4()}"
        self.rollout_logging_dir = os.path.join(config.train.rollout_logging_dir, self.run_id)
        # the base dir may not exist yet and a crashed run may have left the
        # run dir behind: both are fine, never assert/mkdir-race here
        os.makedirs(self.rollout_logging_dir, exist_ok=True)
        with open(os.path.join(self.rollout_logging_dir, "config.json"), "w") as f:
            import json

            f.write(json.dumps(config.to_dict(), indent=2))

    def _get_score_fn(self, B: int, P: int, R: int, bounded_family: bool = False):
        """Jitted scoring pass: policy logprobs+values and reference logprobs over
        the response window (parity: :414-446). One compile per (B, P, R).

        ``bounded_family`` marks a streaming-microbucket caller: R is then
        asserted to stay within the ≤4-shape quantized ladder per (B, P)
        family, so varied completion lengths cannot trigger unbounded
        recompiles."""
        if bounded_family:
            check_stream_bucket_family(self._score_fn_families, B, P, R)
        key = (B, P, R)
        if key in self._score_fns:
            return self._score_fns[key]

        if self.is_seq2seq:
            module, t5 = self.module, self._t5_module()
            start_tok = self.decoder_start_token_id
            branch_start = self.branch_start
            peft_base_ref = self.peft_base_ref
            base_t5 = getattr(self, "base_t5_module", None)

            def score_s2s(params, ref_params, frozen_branch, q_ids, q_mask, r_ids, r_mask):
                Bs = q_ids.shape[0]
                dec_in = jnp.concatenate(
                    [jnp.full((Bs, 1), start_tok, jnp.int32), r_ids[:, :-1]], axis=1
                )
                dec_mask = jnp.concatenate(
                    [jnp.ones((Bs, 1), jnp.int32), r_mask[:, :-1]], axis=1
                )
                if peft_base_ref:
                    # same (frozen) t5 params, adapters structurally disabled
                    logits, values, _ = module.apply(
                        {"params": params}, q_ids, q_mask, dec_in, dec_mask
                    )
                    ref_logits, _, _ = base_t5.apply(
                        {"params": params["t5"]}, q_ids, q_mask, dec_in, dec_mask
                    )
                elif branch_start is not None:
                    logits, values, enc, branch_hidden, pos_bias = module.apply(
                        {"params": params}, q_ids, q_mask, dec_in, dec_mask, branch_start,
                        method=module.forward_with_branch,
                    )
                    ref_logits = t5.apply(
                        {"params": frozen_branch}, branch_hidden, enc, q_mask, dec_mask,
                        pos_bias, branch_start, method=t5.forward_branch,
                    )
                else:
                    logits, values, _ = module.apply({"params": params}, q_ids, q_mask, dec_in, dec_mask)
                    ref_logits, _, _ = t5.apply({"params": ref_params}, q_ids, q_mask, dec_in, dec_mask)
                logprobs = logprobs_of_labels(logits, r_ids)
                ref_logprobs = logprobs_of_labels(ref_logits, r_ids)
                return logprobs, values.astype(jnp.float32), ref_logprobs

            self._score_fns[key] = jax.jit(
                score_s2s, out_shardings=mesh_lib.replicated(self.mesh)
            )
            return self._score_fns[key]

        module, trunk = self.module, self.trunk_module
        branch_start = self.branch_start
        peft_base_ref = self.peft_base_ref
        base_trunk = getattr(self, "base_trunk_module", None)

        def score(params, ref_params, frozen_branch, seq, mask):
            logits, values, branch_hidden, _ = module.apply(
                {"params": params}, seq, mask, branch_layer=branch_start
            )
            logprobs = logprobs_of_labels(logits[:, :-1], seq[:, 1:])
            if peft_base_ref:
                # same (frozen) trunk params, adapters structurally disabled
                ref_logits, _, _, _ = base_trunk.apply(
                    {"params": params["transformer"]}, seq, mask
                )
            elif branch_start is not None:
                ref_logits = module.apply(
                    {"params": {"transformer": frozen_branch}},
                    branch_hidden, mask, None, branch_start,
                    method=module.forward_branch,
                )
            else:
                ref_logits, _, _, _ = trunk.apply({"params": ref_params}, seq, mask)
            ref_logprobs = logprobs_of_labels(ref_logits[:, :-1], seq[:, 1:])
            start = P - 1
            return (
                logprobs[:, start : start + R],
                values[:, start : start + R].astype(jnp.float32),
                ref_logprobs[:, start : start + R],
            )

        self._score_fns[key] = jax.jit(
            score, out_shardings=mesh_lib.replicated(self.mesh)
        )
        return self._score_fns[key]

    # ------------------------------------------------------------- serving

    def _resolve_serving(self):
        """Build the continuous-batching GenerationClient when
        ``train.serving.enabled`` and the run shape supports it; otherwise
        log why and keep the one-shot generate path (``_serving_client``
        stays None). Called once from prepare_learning."""
        cfg = self.config.train.serving
        if not cfg.enabled or self._serving_client is not None:
            return

        def fallback(reason):
            logger.warning(f"train.serving disabled for this run: {reason}")

        if self.is_seq2seq:
            return fallback("seq2seq generation is not paged")
        if self.model_config.stacked:
            return fallback("stacked/pipelined layouts keep the contiguous cache")
        if self.model_config.peft_type in ("prompt", "prefix"):
            return fallback("prompt/prefix peft puts virtual rows in the cache")
        if self.mesh is not None and self.mesh.size > 1:
            return fallback("multi-device mesh (the paged step is single-device)")
        if self.gen_logits_processor() is not None:
            return fallback("decode-time logits processor in use")

        from trlx_tpu.models.transformer import TransformerLM
        from trlx_tpu.serving import (
            GenerationClient,
            ServingEngine,
            ServingResiliencePolicy,
            ServingSupervisor,
        )

        gen_kwargs = dict(self.generate_experience_kwargs or self.generate_kwargs)
        gen_kwargs.setdefault("eos_token_id", self.tokenizer.eos_token_id)
        gen_kwargs.setdefault("pad_token_id", self.tokenizer.pad_token_id)
        self._serving_max_new = int(gen_kwargs.pop("max_new_tokens", 16))
        self._serving_min_new = int(gen_kwargs.pop("min_new_tokens", 0))
        eos = gen_kwargs.pop("eos_token_id")
        pad = gen_kwargs.pop("pad_token_id")
        sample_keys = ("temperature", "top_k", "top_p", "do_sample", "top_k_impl")
        unknown = set(gen_kwargs) - set(sample_keys)
        if unknown:
            return fallback(f"unsupported gen_kwargs for the serving engine: {sorted(unknown)}")

        trunk_config = self.model_config.replace(
            kv_cache_quant=(
                self.model_config.kv_cache_quant
                if cfg.kv_cache_quant is None else bool(cfg.kv_cache_quant)
            ),
            paged_attention_impl=cfg.attention_impl,
        )
        num_slots = cfg.num_slots or (
            self.method.decode_batch_size or self.method.chunk_size
        )
        # prompts are admitted unpadded, so capacity only needs the real
        # prompt lengths (<= seq_length) plus the decode budget
        max_seq_len = self.config.train.seq_length + self._serving_max_new
        svr = self.config.train.serving_resilience
        policy = None
        if svr.enabled:
            policy = ServingResiliencePolicy(
                request_ttl_s=svr.request_ttl_s,
                max_pending_age_s=svr.max_pending_age_s,
                max_pending=svr.max_pending,
                high_watermark=svr.high_watermark,
                low_watermark=svr.low_watermark,
                preemption=svr.preemption,
            )
        svt = self.config.train.serving_tenancy
        # one registry across engine generations: tenant contracts (and the
        # aging policy) survive supervised restarts by construction
        tenants = svt.build_registry() if svt.enabled else None

        def build_engine(replica_seat=0):
            # each fleet seat samples from its own rng stream (seed offset by
            # the seat); seat 0 keeps the single-engine seed so a one-replica
            # fleet is byte-identical to the bare engine
            return ServingEngine(
                TransformerLM(trunk_config),
                None,  # snapshot installed per rollout phase in _serving_generate
                num_slots=num_slots,
                max_seq_len=max_seq_len,
                block_size=cfg.block_size,
                num_blocks=cfg.num_blocks,
                eos_token_id=eos,
                pad_token_id=pad,
                gen_kwargs=gen_kwargs,
                min_new_tokens=self._serving_min_new,
                prefix_caching=cfg.prefix_caching,
                seed=self.config.train.seed + 17 + replica_seat,
                policy=policy,
                spec_k=cfg.spec_k,
                spec_ngram=cfg.spec_ngram,
                prefill_chunk=cfg.prefill_chunk,
                tenants=tenants,
            )

        svf = self.config.train.serving_fleet
        if svf.enabled:
            # fleet mode: N supervised replicas behind the prefix-affinity
            # router (docs/serving.md "Fleet serving"); replicas are always
            # supervisor-wrapped — re-route on replica death rides the
            # supervisor's export/adopt replay seam
            from trlx_tpu.fleet import FleetAutoscaler, fleet_factory

            diag = svr.diagnostics_dir or os.path.join(
                self.config.train.checkpoint_dir, "diagnostics"
            )
            self._serving_engine = fleet_factory(
                build_engine,
                svf,
                max_restarts=svr.max_restarts,
                backoff_base_s=svr.restart_backoff_base_s,
                backoff_max_s=svr.restart_backoff_max_s,
                wedge_timeout_s=svr.wedge_timeout_s,
                diagnostics_dir=diag,
            )
            if svf.autoscale:
                self._serving_autoscaler = FleetAutoscaler(
                    self._serving_engine,
                    min_replicas=svf.min_replicas,
                    max_replicas=svf.max_replicas,
                    scale_up_pending_per_slot=svf.scale_up_pending_per_slot,
                    scale_down_occupancy=svf.scale_down_occupancy,
                    breach_rounds=svf.breach_rounds,
                    cooldown_rounds=svf.cooldown_rounds,
                )
        elif svr.enabled:
            # supervised: crashes/wedges rebuild the engine (same factory
            # args) and replay every accepted request — docs/serving.md
            diag = svr.diagnostics_dir or os.path.join(
                self.config.train.checkpoint_dir, "diagnostics"
            )
            self._serving_engine = ServingSupervisor(
                build_engine,
                max_restarts=svr.max_restarts,
                backoff_base_s=svr.restart_backoff_base_s,
                backoff_max_s=svr.restart_backoff_max_s,
                wedge_timeout_s=svr.wedge_timeout_s,
                diagnostics_dir=diag,
            )
        else:
            self._serving_engine = build_engine()
        self._serving_client = GenerationClient(self._serving_engine)
        logger.info(
            f"serving engine enabled: slots={num_slots}, "
            f"block_size={cfg.block_size}, blocks={self._serving_engine.num_blocks}, "
            f"int8_kv={trunk_config.kv_cache_quant}, impl={cfg.attention_impl}, "
            f"resilience={'on' if svr.enabled else 'off'}, "
            f"tenancy={'on' if svt.enabled else 'off'}, "
            f"fleet={svf.num_replicas if svf.enabled else 'off'}"
        )

    def _serving_generate(self, prompts, params=None):
        """Continuous-batched replacement for ``self.generate`` in the rollout
        producer: same ``(sequences, response_mask, pad_len)`` contract. The
        engine flushes its prefix cache whenever the parameter snapshot
        object changes (each publish / rollout-copy recast is a new tree)."""
        gen_params = params if params is not None else self.generation_params()
        tparams = gen_params["transformer"]
        if self._island is None and tparams is not self._serving_param_ref:
            # islands mode skips this install: the engine self-swaps to the
            # newest committed broadcast at its own round boundaries, and the
            # producer's snapshot stays the behavior-scoring policy (the
            # ≤1-version drift is absorbed by the clipped-IS correction)
            self._serving_engine.set_params(tparams)
            self._serving_param_ref = tparams
        with self.obs.span("generate"):
            out = self._serving_client.generate_batch(prompts, self._serving_max_new)
        if self._serving_autoscaler is not None:
            self._serving_autoscaler.observe()
        return out

    # --------------------------------------------------- stream-overlapped PPO

    def _overlap_r_buckets(self) -> List[int]:
        """The quantized response-length ladder for this run's ``max_new``
        (module-level :func:`overlap_r_buckets` carries the construction)."""
        return overlap_r_buckets(self._serving_max_new)

    def _make_experience_streamed(
        self, num_rollouts, iter_count, ppo_rl_elements, accumulated_kl, all_scores_log
    ):
        """Streaming experience pipeline (``train.serving.stream_overlap``;
        docs/serving.md "Stream-overlapped PPO").

        As each sequence finishes in the engine, its reward_fn call is
        dispatched from a bounded worker pool; completed-and-scored sequences
        are batched — in engine completion order, which is deterministic under
        greedy decode — into fixed-shape microbuckets for the jitted score fn;
        and first-epoch learner microbatches are collated and ``device_put``
        while the tail of the batch is still decoding. The scoring dispatch is
        double-buffered: bucket k's results are harvested only when bucket
        k+1 is about to dispatch (or at drain), so the next bucket's
        host→device transfer overlaps the in-flight device compute.

        Rollout contents (query/response tensors, store order) are identical
        to the serial serving path; score normalization runs per microbucket
        instead of per chunk, so running-moment grouping legitimately differs.
        ``TRLX_OVERLAP_SEED_REGRESSION=serialize`` forces serial in-memory
        consumption (block on every reward before the next decode round) —
        the seeded regression the overlap-fraction CI gate must catch."""
        import copy
        import random as pyrandom
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        from trlx_tpu.obs.overlap import OverlapWindow
        from trlx_tpu.ops.generation import left_pad_batch, pad_to_bucket
        from trlx_tpu.pipeline.ppo_pipeline import ppo_collate_fn
        from trlx_tpu.resilience.chaos import chaos
        from trlx_tpu.rollout.reorder import ReorderBuffer

        cfg = self.config.train.serving
        serialize = os.environ.get("TRLX_OVERLAP_SEED_REGRESSION", "") == "serialize"
        mb = int(cfg.overlap_microbucket or self.method.chunk_size)
        pad_id = self.tokenizer.pad_token_id
        pow2 = _POW2_BUCKETS
        r_ladder = self._overlap_r_buckets()
        # the reward worker threads must not share the main thread's HF fast
        # tokenizer (not re-entrant — same reasoning as overlap_reward_scoring)
        if not hasattr(self, "_reward_tokenizer"):
            self._reward_tokenizer = copy.deepcopy(self.tokenizer)

        window = OverlapWindow()
        reorder = ReorderBuffer()
        # one clock for flight events: the engine scheduler's (so the reward /
        # store_wait tail lines up with the engine-side phase decomposition)
        flight_clock = self._serving_client.engine.scheduler.clock
        pending = deque()  # (gidx, future, prompt, out_ids, uid) in completion order
        ready = deque()  # reward resolved, waiting for a full microbucket
        inflight = [None]  # one dispatched-but-unharvested scoring bucket
        dropped = [False]  # quarantine broke the 1:1 index map → stop staging
        cur = {"P": 0}  # current prompt batch's shared prompt bucket
        stage = {"perm": None, "next": 0}

        def stream_reward(kw):
            # chaos site "producer-wedge" in the streamed path: this reward
            # RPC stalls briefly (a stuck scorer the bounded pool rides out —
            # exactly-once accounting must hold regardless)
            if chaos.should_fail("producer-wedge"):
                logger.warning("chaos: streamed reward wedged at site 'producer-wedge'")
                time.sleep(0.2)
            t0 = time.perf_counter()
            with span("reward"):
                out = self.reward_fn(**kw)
            window.note_work(t0, time.perf_counter())
            return out

        def r_bucket(r):
            return quantize_stream_response(r, r_ladder)

        def dispatch(items):
            # harvest bucket k-1 first: its device compute had a full bucket's
            # worth of decode/reward time to finish, so the get is cheap, and
            # the put_batch below then overlaps whatever is still in flight
            harvest()
            t0 = time.perf_counter()
            n_real = len(items)
            raw = [it[3] for it in items]
            dense = np.ndim(raw[0]) > 0
            if dense:
                dense_scores = [np.asarray(s, np.float32) for s in raw]
                scores = np.asarray([s.sum() for s in dense_scores], np.float32)
            else:
                dense_scores = None
                scores = np.asarray(jax.device_get(raw), np.float32).reshape(-1)
            all_scores_log.extend(scores.tolist())
            # normalization runs per microbucket in completion order — the
            # documented stats difference vs the serial per-chunk grouping
            self.running_moments.update(scores)
            if self.method.cliprange_reward:
                scores = np.clip(
                    scores, -self.method.cliprange_reward, self.method.cliprange_reward
                )
            if self.method.scale_reward == "running":
                scores = scores / max(self.running_moments.std, 1e-8)
            elif self.method.scale_reward == "ref":
                scores = scores / max(self.method.ref_std or 1.0, 1e-8)

            padded = list(items) + [items[-1]] * (mb - n_real)
            prompts_b = [it[1] for it in padded]
            outs_b = [it[2] for it in padded]
            R = r_bucket(max(len(o) for o in outs_b))
            q_ids, q_mask = left_pad_batch(prompts_b, pad_id, cur["P"])
            r_ids = np.full((mb, R), pad_id, np.int32)
            r_mask = np.zeros((mb, R), np.int32)
            for j, o in enumerate(outs_b):
                r_ids[j, : len(o)] = o
                r_mask[j, : len(o)] = 1
            score_fn = self._get_score_fn(mb, cur["P"], R, bounded_family=True)
            # unlike the serial span, no device_get here: the forward is left
            # in flight (async dispatch) and harvested at the next bucket
            # boundary — that asynchrony IS the decode/score overlap
            with span("score"):
                seq = np.concatenate([q_ids, r_ids], axis=1)
                smask = np.concatenate([q_mask, r_mask], axis=1)
                dbatch = mesh_lib.put_batch(self.mesh, {"seq": seq, "mask": smask})
                with self.mesh:
                    logprobs, values, ref_logprobs = score_fn(
                        self.params, self._ref_scoring_params(), self.frozen_branch_params,
                        dbatch["seq"], dbatch["mask"],
                    )
            window.note_work(t0, time.perf_counter())
            inflight[0] = (items, scores, dense_scores, r_mask, logprobs, values, ref_logprobs)
            if serialize:
                harvest()

        def harvest():
            if inflight[0] is None:
                return
            items, scores, dense_scores, rm_b, lp, v, rlp = inflight[0]
            inflight[0] = None
            t0 = time.perf_counter()
            n_real = len(items)
            lp = np.asarray(jax.device_get(lp))[:n_real]
            v = np.asarray(jax.device_get(v))[:n_real]
            rlp = np.asarray(jax.device_get(rlp))[:n_real]
            rm = rm_b[:n_real]
            # per-token KL penalty & reward assembly — the same k3 math as
            # _score_and_store, per microbucket
            log_ratio = (lp - rlp) * rm
            kl_per_token = np.exp(log_ratio) - 1.0 - log_ratio
            accumulated_kl.append(kl_per_token.sum(axis=1).mean())
            kl_coef = self.kl_ctl.value
            t_store = flight_clock() if flight.enabled else 0.0
            new_elements = []
            for j in range(n_real):
                _, prompt, out, _, uid = items[j]
                if flight.enabled:
                    # the scored element lands in the rollout store here — the
                    # flight's store_wait tail closes
                    flight.record(uid, "store", t=t_store)
                l = int(rm[j].sum())
                rewards = -kl_coef * log_ratio[j, :l]
                if dense_scores is not None:
                    ds = dense_scores[j]
                    rewards[: min(l, len(ds))] += ds[: min(l, len(ds))]
                else:
                    rewards[l - 1] += scores[j]
                new_elements.append(
                    PPORLElement(
                        query_tensor=np.asarray(prompt, np.int32),
                        response_tensor=np.asarray(out, np.int32),
                        logprobs=lp[j, :l],
                        values=v[j, :l],
                        rewards=rewards.astype(np.float32),
                    )
                )
            # same trust boundary as _score_and_store; chaos replaces by
            # position, so new_elements[j] still corresponds to items[j]
            new_elements = chaos_corrupt_elements(new_elements)
            kept = new_elements
            if self._quarantine is not None:
                kept = self._quarantine.filter(
                    new_elements, context=f"iter={self.iter_count}"
                )
            kept_ids = {id(e) for e in kept}
            for j, elem in enumerate(new_elements):
                gidx = items[j][0]
                if id(elem) in kept_ids:
                    reorder.add(gidx, elem)
                else:
                    dropped[0] = True
                    reorder.add(gidx, None)  # tombstone: never stall the cursor
            ppo_rl_elements.extend(reorder.pop_ready())
            maybe_stage_learn()
            window.note_work(t0, time.perf_counter())

        def maybe_stage_learn():
            if not cfg.overlap_learn_stage or dropped[0]:
                return
            bs = self.config.train.batch_size
            if stage["perm"] is None:
                # replicate NumpyLoader's first-epoch permutation for the
                # loader create_train_dataloader will build over the store
                # (seed + iter_count, epoch 0); a mismatch at consume time is
                # detected by content and falls back to a fresh transfer
                idxs = list(range(num_rollouts))
                pyrandom.Random(self.config.train.seed + iter_count).shuffle(idxs)
                stage["perm"] = idxs
            avail = min(len(ppo_rl_elements), num_rollouts)
            while True:
                start = stage["next"] * bs
                if start + bs > num_rollouts:
                    break
                chunk = stage["perm"][start : start + bs]
                if any(ix >= avail for ix in chunk):
                    break
                t0 = time.perf_counter()
                with span("learn_stage"):
                    host = ppo_collate_fn(pad_id, [ppo_rl_elements[ix] for ix in chunk])
                    dev = mesh_lib.put_batch(self.mesh, host)
                self._stage_learn_batch(host, dev)
                window.note_work(t0, time.perf_counter())
                stage["next"] += 1

        def pump(block=False):
            # move FIFO-completed rewards to ready: bucket composition follows
            # engine completion order (deterministic), never worker timing
            while pending:
                gidx, fut, prompt, out, uid = pending[0]
                if not (block or fut.done()):
                    break
                pending.popleft()
                result = fut.result()[0]
                if flight.enabled:
                    flight.record(uid, "reward_done", t=flight_clock())
                ready.append((gidx, prompt, out, result, uid))
            while len(ready) >= mb:
                dispatch([ready.popleft() for _ in range(mb)])

        gen_params = self.generation_params()
        tparams = gen_params["transformer"]
        if tparams is not self._serving_param_ref:
            self._serving_engine.set_params(tparams)
            self._serving_param_ref = tparams

        self._pin_ref()
        self._clear_staged_learn()
        generated = 0
        try:
            with ThreadPoolExecutor(
                max_workers=max(1, int(cfg.overlap_reward_workers)),
                thread_name_prefix="overlap-reward",
            ) as pool:
                while generated < num_rollouts:
                    batch = next(self.prompt_iterator)
                    self._prompt_batches_drawn += 1
                    prompts = batch["input_ids"]
                    metadata = {k: v for k, v in batch.items() if k != "input_ids"}
                    base = generated
                    generated += len(prompts)
                    cur["P"] = pad_to_bucket(
                        max((len(p) for p in prompts), default=1), pow2
                    )

                    def on_finish(i, req, _base=base, _prompts=prompts, _meta=metadata):
                        gidx = _base + i
                        prompt = np.asarray(_prompts[i], np.int32)
                        gen = np.asarray(req.generated, np.int32)
                        row = np.concatenate([prompt, gen])[None, :]
                        rmask = np.ones((1, len(gen)), np.int32)
                        str_samples, str_prompts, str_outputs, out_ids = self.decode(
                            [prompt], row, len(prompt), append_eos=True,
                            response_masks=rmask,
                        )
                        kw = dict(
                            samples=str_samples, prompts=str_prompts,
                            outputs=str_outputs, tokenizer=self._reward_tokenizer,
                            **{k: [v[i]] for k, v in _meta.items()},
                        )
                        fut = pool.submit(stream_reward, kw)
                        if flight.enabled:
                            flight.record(
                                req.uid, "reward_dispatch", t=flight_clock()
                            )
                        pending.append((gidx, fut, prompt, out_ids[0], req.uid))
                        if serialize:
                            fut.result()  # seeded regression: serial consumption
                        pump()

                    with self.obs.span("decode"):
                        self._serving_client.stream_batch(
                            prompts, self._serving_max_new, on_finish,
                            on_step=window.note_decode,
                        )
                    # drain before the next batch can change the prompt bucket
                    pump(block=True)
                    if ready:
                        dispatch([ready.popleft() for _ in range(len(ready))])
                    harvest()
        finally:
            self._unpin_ref()
        eng = self._serving_engine
        eng.note_overlap(window.decode_busy_s, window.overlapped_s)
        eng.export_gauges()
        if self._serving_autoscaler is not None:
            self._serving_autoscaler.observe()

    # ------------------------------------------------------------- experience

    def _generate_chunks(self, tokenizer, params=None):
        """One device generation at decode_batch_size, split into chunk_size
        sub-chunks for reward_fn / the scoring forward. ``params`` overrides
        the sampling params (async producer passes a published snapshot)."""
        batch = next(self.prompt_iterator)
        self._prompt_batches_drawn += 1
        prompts = batch["input_ids"]
        metadata = {k: v for k, v in batch.items() if k != "input_ids"}
        if self._serving_client is not None:
            samples, resp_mask, pad_len = self._serving_generate(prompts, params=params)
        else:
            samples, resp_mask, pad_len = self.generate(prompts, eval_mode=False, params=params)
        str_samples, str_prompts, str_outputs, out_ids = self.decode(
            prompts, samples, pad_len, append_eos=True, response_masks=resp_mask
        )
        cs = self.method.chunk_size
        subs = []
        for i in range(0, len(prompts), cs):
            sl = slice(i, i + cs)
            reward_kwargs = dict(
                samples=str_samples[sl], prompts=str_prompts[sl],
                outputs=str_outputs[sl], tokenizer=tokenizer,
                **{k: v[sl] for k, v in metadata.items()},
            )
            subs.append(((prompts[sl], out_ids[sl]), reward_kwargs))
        return subs

    def make_experience(self, num_rollouts: int = 1024, iter_count: int = 0):
        """Roll out prompts → generations → rewards → KL-penalized per-token reward
        assembly → rollout store (parity: :251-524; see SURVEY.md §3.2).

        With ``method.overlap_reward_scoring``, reward_fn for chunk i runs on a
        worker thread while chunk i+1 generates on the device — double-buffering
        that hides a served reward model's RPC round-trip (the reference runs its
        Triton reward scoring serially on rank 0, :303-317)."""
        logger.info(f"Collecting {num_rollouts} rollouts")
        ppo_rl_elements: List[PPORLElement] = []
        accumulated_kl = []
        all_scores_log = []
        self.clock.tick()

        overlap = self.method.overlap_reward_scoring
        stream = (
            self._serving_client is not None
            and self.config.train.serving.stream_overlap
            and jax.process_count() == 1
        )
        if self.config.train.serving.stream_overlap and self._serving_client is not None and not stream:
            logger.warning(
                "serving.stream_overlap is single-process only: "
                "running the serial serving consumption path"
            )
        if stream:
            # stream-overlapped PPO: reward/score/learn-stage while the tail
            # of the batch is still decoding (docs/serving.md)
            self._make_experience_streamed(
                num_rollouts, iter_count, ppo_rl_elements, accumulated_kl, all_scores_log
            )
        elif overlap:
            import copy
            from collections import deque
            from concurrent.futures import ThreadPoolExecutor

            # Multihost + reward_on_process_zero composes with overlap (VERDICT
            # r3 weak #4): only process 0's reward_fn runs on the worker thread
            # (pure RPC/python, no collectives); the broadcast — a collective —
            # happens at future-drain time on the MAIN thread, which reaches
            # each drain in the same program order on every host.
            broadcasting = self.reward_on_process_zero and jax.process_count() > 1
            score_locally = not broadcasting or jax.process_index() == 0
            if broadcasting:
                logger.info(
                    "overlap_reward_scoring active with reward_on_process_zero: "
                    "process-0 worker-thread scoring + main-thread broadcast"
                )

            # reward_fn runs on a worker thread while the main thread keeps using
            # self.tokenizer in decode(); HF fast tokenizers are not re-entrant
            # ("Already borrowed"), so the worker gets its own copy
            if not hasattr(self, "_reward_tokenizer"):
                self._reward_tokenizer = copy.deepcopy(self.tokenizer)
            generated = 0  # count at generation time: len(ppo_rl_elements) lags
            with ThreadPoolExecutor(max_workers=1) as pool:
                pending = deque()
                while generated < num_rollouts or pending:
                    if generated < num_rollouts:
                        new = [
                            (chunk, pool.submit(self._spanned_reward_fn, **kw) if score_locally else None)
                            for chunk, kw in self._generate_chunks(self._reward_tokenizer)
                        ]
                        generated += sum(len(chunk[0]) for chunk, _ in new)
                    else:
                        new = []
                    # drain the previous generation's scores while this one's
                    # reward futures run behind the next device generation
                    while pending:
                        pchunk, pfut = pending.popleft()
                        scores = pfut.result() if pfut is not None else None
                        if broadcasting:
                            scores = self.broadcast_scores(scores, len(pchunk[0]))
                        self._score_and_store(
                            pchunk, scores, ppo_rl_elements, accumulated_kl, all_scores_log
                        )
                    pending.extend(new)
        else:
            while len(ppo_rl_elements) < num_rollouts:
                for chunk, reward_kwargs in self._generate_chunks(self.tokenizer):
                    with span("reward"):
                        scores = self.call_reward_fn(**reward_kwargs)
                    self._score_and_store(chunk, scores, ppo_rl_elements, accumulated_kl, all_scores_log)

        self.mean_kl = float(np.mean(accumulated_kl))
        rollout_time = self.clock.tick()
        self.rollout_stats = {
            "rollout_scores/mean": float(np.mean(all_scores_log)),
            "rollout_scores/std": float(np.std(all_scores_log)),
            "rollout_scores/running_mean": float(self.running_moments.mean),
            "rollout_scores/running_std": float(self.running_moments.std),
            "policy/sqrt_kl": float(np.sqrt(max(self.mean_kl, 0.0))),
            "kl_ctl_value": float(self.kl_ctl.value),
            "time/rollout_time": rollout_time,
        }
        if self.log_rollouts:
            self.store.export_history(location=self.rollout_logging_dir, tokenizer=self.tokenizer)
        self.push_to_store(ppo_rl_elements[:num_rollouts])
        # offloaded ref: drop the device copy before the update phase (where
        # grads + optimizer state peak HBM); no-op otherwise
        self._release_ref()

    def _spanned_reward_fn(self, **kwargs):
        """reward_fn under a ``reward`` span (overlap path runs it on a worker
        thread — the span keeps the RPC round-trip visible on that thread's
        timeline)."""
        with span("reward"):
            return self.reward_fn(**kwargs)

    def _score_and_store(
        self, chunk, scores, ppo_rl_elements, accumulated_kl, all_scores_log, params=None
    ):
        """Normalize scores, run the jitted logprob/value/ref scoring forward, and
        assemble KL-penalized PPORLElements (parity: :364-502).

        ``params`` overrides the policy used for the behavior logprob/value
        scoring pass — the async producer passes the same published snapshot it
        sampled with, so stored logprobs are the true behavior policy's even
        while the learner mutates ``self.params``."""
        policy_params = self.params if params is None else params
        prompts, out_ids = chunk
        dense = np.ndim(scores[0]) > 0
        if dense:
            dense_scores = [np.asarray(s, np.float32) for s in scores]
            scores = np.asarray([s.sum() for s in dense_scores], np.float32)
        else:
            dense_scores = None
            scores = np.asarray(jax.device_get(scores), np.float32).reshape(-1)

        all_scores_log.extend(scores.tolist())
        # clip + normalize scores (parity: :364-381)
        scores_mean, scores_std = self.running_moments.update(scores)
        if self.method.cliprange_reward:
            scores = np.clip(scores, -self.method.cliprange_reward, self.method.cliprange_reward)
        if self.method.scale_reward == "running":
            scores = scores / max(self.running_moments.std, 1e-8)
        elif self.method.scale_reward == "ref":
            scores = scores / max(self.method.ref_std or 1.0, 1e-8)

        # fixed-shape scoring forward
        P = max(len(p) for p in prompts)
        R = max(len(o) for o in out_ids)
        from trlx_tpu.ops.generation import left_pad_batch, pad_to_bucket

        P = pad_to_bucket(P, [2 ** i for i in range(3, 14)])
        R = pad_to_bucket(R, [2 ** i for i in range(3, 14)])
        q_ids, q_mask = left_pad_batch(prompts, self.tokenizer.pad_token_id, P)
        r_ids = np.full((len(out_ids), R), self.tokenizer.pad_token_id, np.int32)
        r_mask = np.zeros((len(out_ids), R), np.int32)
        for i, o in enumerate(out_ids):
            r_ids[i, : len(o)] = o
            r_mask[i, : len(o)] = 1
        score_fn = self._get_score_fn(q_ids.shape[0], P, R)
        # the span includes the device_get: the scoring forward is async until
        # the host fetch (same reasoning as the generate span)
        with span("score"):
            if self.is_seq2seq:
                dbatch = mesh_lib.put_batch(
                    self.mesh, {"q": q_ids, "qm": q_mask, "r": r_ids, "rm": r_mask}
                )
                with self.mesh:
                    logprobs, values, ref_logprobs = score_fn(
                        policy_params, self._ref_scoring_params(), self.frozen_branch_params,
                        dbatch["q"], dbatch["qm"], dbatch["r"], dbatch["rm"],
                    )
            else:
                seq = np.concatenate([q_ids, r_ids], axis=1)
                mask = np.concatenate([q_mask, r_mask], axis=1)
                dbatch = mesh_lib.put_batch(self.mesh, {"seq": seq, "mask": mask})
                with self.mesh:
                    logprobs, values, ref_logprobs = score_fn(
                        policy_params, self._ref_scoring_params(), self.frozen_branch_params,
                        dbatch["seq"], dbatch["mask"],
                    )
            logprobs = np.asarray(jax.device_get(logprobs))
            values = np.asarray(jax.device_get(values))
            ref_logprobs = np.asarray(jax.device_get(ref_logprobs))

        # per-token KL penalty & reward assembly (parity: :457-492)
        log_ratio = (logprobs - ref_logprobs) * r_mask
        kl_per_token = np.exp(log_ratio) - 1.0 - log_ratio  # k3 estimator (:461)
        # controller sees the per-SEQUENCE kl sum (reference :460 kl.sum(1).mean());
        # the shipped AdaptiveKL targets (e.g. 6.0) are calibrated to that scale
        mean_kl = kl_per_token.sum(axis=1).mean()
        accumulated_kl.append(mean_kl)

        kl_coef = self.kl_ctl.value
        new_elements = []
        for i in range(len(prompts)):
            l = int(r_mask[i].sum())
            rewards = -kl_coef * log_ratio[i, :l]
            if dense:
                ds = dense_scores[i]
                rewards[: min(l, len(ds))] += ds[: min(l, len(ds))]
            else:
                rewards[l - 1] += scores[i]
            new_elements.append(
                PPORLElement(
                    query_tensor=np.asarray(prompts[i], np.int32),
                    response_tensor=r_ids[i, :l],
                    logprobs=logprobs[i, :l],
                    values=values[i, :l],
                    rewards=rewards.astype(np.float32),
                )
            )
        # experience crosses a trust boundary here: this is the single choke
        # point both the sync path (make_experience) and the async producer
        # assemble elements through, so the quarantine screen covers both.
        # chaos site "bad-element" fabricates an offender first (free unarmed)
        new_elements = chaos_corrupt_elements(new_elements)
        if self._quarantine is not None:
            new_elements = self._quarantine.filter(
                new_elements, context=f"iter={self.iter_count}"
            )
        ppo_rl_elements.extend(new_elements)


    # ---------------------------------------------------------- async rollouts

    def _resolve_async_config(self):
        """The effective ``train.async_rollouts`` block, or None for the
        synchronous path. ``max_staleness=0`` means fully on-policy — exactly
        the synchronous semantics, so we run that code path rather than an
        async engine that must block on every publish."""
        cfg = getattr(self.config.train, "async_rollouts", None)
        if cfg is None or not cfg.enabled:
            return None
        if cfg.max_staleness <= 0:
            logger.warning(
                "async_rollouts.max_staleness=0 requests fully on-policy data: "
                "running the synchronous rollout path"
            )
            return None
        if jax.process_count() > 1:
            logger.warning(
                "async_rollouts is single-process only (cross-host reward "
                "broadcast ordering is undefined off the main thread): "
                "running the synchronous rollout path"
            )
            return None
        return cfg

    def _start_async_engine(self):
        from trlx_tpu.rollout import (
            AsyncRolloutEngine,
            ExperienceQueue,
            ParameterPublisher,
            StalenessAccountant,
        )

        cfg = self._async_cfg

        def device_copy(tree):
            # donate-free snapshot: the train step donates self.params' buffers,
            # so the producer must read an independent copy (same pattern as the
            # frozen KL reference in setup_model)
            with self.mesh:
                return jax.jit(lambda t: jax.tree.map(lambda x: x.copy(), t))(tree)

        icfg = getattr(self.config.train, "islands", None)
        if icfg is not None and icfg.enabled and self._serving_engine is None:
            logger.warning(
                "train.islands requires train.serving (the generation island "
                "IS the continuous-batching engine): running the monolithic "
                "publish path"
            )
            icfg = None
        if icfg is not None and icfg.enabled:
            from trlx_tpu.parallel.mesh import carve_islands
            from trlx_tpu.rollout import ChunkedParameterPublisher
            from trlx_tpu.serving import GenerationIsland

            placement = carve_islands(icfg.gen_devices)
            # published trees are full trainer params (transformer + heads);
            # the serving engine runs only the transformer trunk
            self._island = GenerationIsland(
                self._serving_engine, param_selector=lambda tree: tree["transformer"]
            )
            publisher = ChunkedParameterPublisher(
                copy_fn=device_copy,
                chunk_layers=icfg.chunk_layers,
                chunk_pause_s=icfg.chunk_pause_s,
                round_gate=self._island.round_gate,
            )
            self._island.bind_publisher(publisher)
            logger.info(
                f"generation island carved: gen={len(placement.gen)} device(s), "
                f"learn={len(placement.learn)} device(s), "
                f"shared={placement.shared}, chunk_layers={icfg.chunk_layers}"
            )
        else:
            publisher = ParameterPublisher(copy_fn=device_copy)
        self._policy_version = publisher.publish(self.params)
        capacity = cfg.queue_capacity or 4 * self.method.num_rollouts
        queue = ExperienceQueue(capacity, cfg.high_watermark, cfg.low_watermark)
        accountant = StalenessAccountant(cfg.max_staleness)
        sh_config = self.config.train.self_healing
        supervised = sh_config.enabled

        def make_engine():
            # generations share queue/publisher/accountant; under supervision
            # a dead generation must not close the queue its successor feeds
            return AsyncRolloutEngine(
                self._produce_rollout_chunk,
                publisher,
                queue,
                accountant,
                close_queue_on_death=not supervised,
            )

        if supervised:
            from trlx_tpu.rollout import ProducerSupervisor

            self._engine = ProducerSupervisor(
                make_engine,
                max_restarts=sh_config.max_producer_restarts,
                backoff_base_s=sh_config.restart_backoff_base_s,
                backoff_max_s=sh_config.restart_backoff_max_s,
                wedge_timeout_s=sh_config.wedge_timeout_s,
                diagnostics_dir=sh_config.diagnostics_dir
                or os.path.join(self.config.train.checkpoint_dir, "diagnostics"),
            )
        else:
            self._engine = make_engine()
        self._engine.start()
        if self._island is not None:
            # windows open after the seed publish, so the first broadcast's
            # compile/copy cost never pollutes the idle-bubble fractions
            self._island.open_window()
        logger.info(
            f"async rollout engine started{' (supervised)' if supervised else ''}: "
            f"queue_capacity={capacity} "
            f"(high={queue.high_watermark}, low={queue.low_watermark}), "
            f"max_staleness={cfg.max_staleness}, "
            f"publish_interval={cfg.publish_interval}"
        )

    def _produce_rollout_chunk(self, params, version):
        """PRODUCER THREAD: one decode-batch of generate → reward → score, with
        the published snapshot as both sampling and behavior-scoring policy.
        Runs concurrently with the learner's train steps; shares no mutable
        state with them except the float stats below (atomic swaps under the
        GIL) — evaluate(), which does share the tokenizer/RNG/generation
        caches, pauses the engine around itself."""
        elements: List[PPORLElement] = []
        kls: List[float] = []
        scores_log: List[float] = []
        t0 = time.monotonic()
        for chunk, reward_kwargs in self._generate_chunks(self.tokenizer, params=params):
            with span("reward"):
                scores = self.reward_fn(**reward_kwargs)
            self._score_and_store(chunk, scores, elements, kls, scores_log, params=params)
        if kls:
            self.mean_kl = float(np.mean(kls))
        self.rollout_stats = {
            "rollout_scores/mean": float(np.mean(scores_log)),
            "rollout_scores/std": float(np.std(scores_log)),
            "rollout_scores/running_mean": float(self.running_moments.mean),
            "rollout_scores/running_std": float(self.running_moments.std),
            "policy/sqrt_kl": float(np.sqrt(max(self.mean_kl, 0.0))),
            "kl_ctl_value": float(self.kl_ctl.value),
            "time/rollout_chunk_time": time.monotonic() - t0,
            "rollout/producer_version": float(version),
        }
        if self._island is not None and self._serving_client is not None:
            # behavior policy as actually served (the island may have swapped
            # mid-batch; drift vs. `version` is what clipped-IS absorbs)
            self.rollout_stats["rollout/served_version"] = float(
                self._serving_client.policy_version
            )
        return elements

    def _refill_store_async(self):
        """Pull ``num_rollouts`` staleness-admitted elements from the engine
        into the rollout store (the async analogue of make_experience)."""
        n = self.method.num_rollouts
        t0 = time.monotonic()
        with span("queue_wait"):
            elements = self._engine.collect(
                n, self._policy_version, timeout=self._async_cfg.collect_timeout_s
            )
        gauges.set("rollout/collect_wait_s", time.monotonic() - t0)
        if self.log_rollouts:
            self.store.export_history(location=self.rollout_logging_dir, tokenizer=self.tokenizer)
        self.push_to_store(elements[:n])

    # ------------------------------------------------------------- train loop

    def _extra_state(self):
        return {"prompt_batches_drawn": self._prompt_batches_drawn}

    def _restore_extra_state(self, state):
        self._resume_prompt_batches = int(state.get("prompt_batches_drawn", 0))

    def _fast_forward_prompt_stream(self):
        """Replay the restored number of prompt-batch draws. Exact replay (not
        modulo the loader length) because ``NumpyLoader`` reshuffles per epoch
        from ``seed + epoch`` — position N is only reproducible by drawing N
        times from the same freshly-built iterator."""
        n = self._resume_prompt_batches
        self._resume_prompt_batches = 0
        if n <= 0 or getattr(self, "prompt_iterator", None) is None:
            return
        for _ in range(n):
            next(self.prompt_iterator)
        self._prompt_batches_drawn = n
        logger.info(f"Auto-resume: fast-forwarded the prompt stream by {n} batches")

    def prepare_learning(self):
        bs = self.config.train.batch_size
        self.num_mb = max(1, bs // (self.config.train.minibatch_size or bs))
        self._fast_forward_prompt_stream()
        self._resolve_serving()
        self._async_cfg = self._resolve_async_config()
        icfg = getattr(self.config.train, "islands", None)
        if icfg is not None and icfg.enabled and self._async_cfg is None:
            logger.warning(
                "train.islands requires train.async_rollouts (the bounded "
                "experience queue is the island seam): islands disabled"
            )
        if self._async_cfg is not None:
            self._start_async_engine()
            self._refill_store_async()
        else:
            self.make_experience(self.method.num_rollouts, self.iter_count)

    def create_train_dataloader(self):
        """ppo_epochs passes over the current rollout store per outer epoch."""
        loader = self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed + self.iter_count
        )
        for _ in range(self.method.ppo_epochs):
            yield from loader

    def _get_train_step(self, B: int, P: int, R: int):
        key = (B, P, R)
        if key in self._train_steps:
            return self._train_steps[key]
        module, method = self.module, self.method

        # staleness-aware IS correction (async engine only): the mode is fixed
        # for the trainer's lifetime, so it needs no compile-key entry. With it
        # OFF this traces the identical program as before — the bitwise-equal
        # guarantee of the synchronous / max_staleness=0 path.
        use_is = self._engine is not None and bool(self._async_cfg.staleness_correction)
        is_clip = float(self._async_cfg.is_ratio_clip) if use_is else None

        def loss_extra(mb: PPORLBatch):
            if use_is and mb.staleness is not None:
                return dict(staleness=mb.staleness, is_ratio_clip=is_clip)
            return {}

        if self.is_seq2seq:
            start_tok = self.decoder_start_token_id

            def loss_fn_s2s(params, mb: PPORLBatch):
                Bs = mb.response_tensors.shape[0]
                dec_in = jnp.concatenate(
                    [jnp.full((Bs, 1), start_tok, jnp.int32), mb.response_tensors[:, :-1]], axis=1
                )
                dec_mask = jnp.concatenate(
                    [jnp.ones((Bs, 1), jnp.int32), mb.response_mask[:, :-1]], axis=1
                )
                logits, values_pred, _ = module.apply(
                    {"params": params}, mb.query_tensors, mb.attention_mask, dec_in, dec_mask
                )
                logprobs = logprobs_of_labels(logits, mb.response_tensors)
                values_pred = values_pred.astype(jnp.float32)
                advantages, returns = method.get_advantages_and_returns(
                    mb.values, mb.rewards, mb.response_mask
                )
                loss, stats = method.loss(
                    logprobs, values_pred, mb.logprobs, mb.values, advantages, returns,
                    mb.response_mask, **loss_extra(mb),
                )
                return loss, flatten_dict(stats)

            self._train_steps[key] = self.make_grad_accum_step(loss_fn_s2s, self.num_mb)
            return self._train_steps[key]

        def loss_fn(params, mb: PPORLBatch):
            seq = jnp.concatenate([mb.query_tensors, mb.response_tensors], axis=1)
            mask = jnp.concatenate([mb.attention_mask, mb.response_mask], axis=1)
            logits, values_pred, _, _ = module.apply({"params": params}, seq, mask)
            logprobs = logprobs_of_labels(logits[:, :-1], seq[:, 1:])
            start = mb.query_tensors.shape[1] - 1
            Rr = mb.response_tensors.shape[1]
            logprobs = logprobs[:, start : start + Rr]
            values_pred = values_pred[:, start : start + Rr].astype(jnp.float32)
            advantages, returns = method.get_advantages_and_returns(
                mb.values, mb.rewards, mb.response_mask
            )
            loss, stats = method.loss(
                logprobs, values_pred, mb.logprobs, mb.values, advantages, returns,
                mb.response_mask, **loss_extra(mb),
            )
            return loss, flatten_dict(stats)

        self._train_steps[key] = self.make_grad_accum_step(loss_fn, self.num_mb)
        return self._train_steps[key]

    def train_step(self, batch: PPORLBatch) -> Dict[str, float]:
        if self._engine is not None:
            # staleness is learner-relative and must be stamped NOW (the
            # learner kept publishing while this collated batch waited), not
            # at collate time
            stale = np.maximum(
                0, self._policy_version - np.asarray(batch.policy_version, np.int64)
            ).astype(np.int32)
            gauges.set("rollout/batch_staleness_mean", float(stale.mean()))
            gauges.set("rollout/batch_staleness_max", float(stale.max()))
            if self._async_cfg.staleness_correction:
                batch = batch.replace(staleness=stale)
        # stream-overlap learn seam: consume the device copy staged during the
        # decode window when it matches this batch exactly; fresh transfer
        # otherwise (identical data either way)
        dbatch = self._pop_staged_learn(batch)
        if dbatch is None:
            dbatch = mesh_lib.put_batch(self.mesh, batch)
        step = self._get_train_step(
            batch.query_tensors.shape[0], batch.query_tensors.shape[1], batch.response_tensors.shape[1]
        )
        t_learn0 = time.monotonic()
        with self.mesh:
            self.params, self.opt_state, stats = step(self.params, self.opt_state, dbatch)
        out = {k: float(v) for k, v in jax.device_get(stats).items()}
        if self._island is not None:
            # device_get above synced the step; the interval is real compute
            self._island.note_learn(t_learn0, time.monotonic())
            self._island.export_gauges()
        out.update(self.rollout_stats)
        if self._engine is not None:
            out.update(gauges.snapshot("rollout/"))
        if self._serving_client is not None:
            out.update(gauges.snapshot("serving/"))
            out.update(gauges.snapshot("fleet/"))
        return out

    def post_backward_callback(self):
        """KL controller update per optimizer step (parity: :227-231); under the
        async engine, also publish a fresh parameter snapshot so the producer's
        next chunk samples from the newest policy."""
        self.kl_ctl.update(self.mean_kl, n_steps=self.config.train.batch_size)
        if self._engine is not None and (
            self.iter_count % max(1, self._async_cfg.publish_interval) == 0
        ):
            t_pub0 = time.monotonic()
            self._policy_version = self._engine.publisher.publish(self.params)
            gauges.set("rollout/learner_version", float(self._policy_version))
            if self._island is not None:
                # the broadcast runs on the learner island's thread — it is
                # learner busy time, even though the chunks hide under decode
                self._island.note_learn(t_pub0, time.monotonic())

    def post_epoch_callback(self, epoch: int):
        """Discard stale rollouts and collect fresh experience (parity: :219-225).
        Async: the producer has been filling the queue during the optimizer
        epochs, so this usually just drains already-generated experience."""
        self.store.clear_history()
        if self._engine is not None:
            self._refill_store_async()
        else:
            self.make_experience(self.method.num_rollouts, self.iter_count)

    def _post_rollback_restore(self):
        """Mid-run health rollback: re-anchor the PPO-specific run state that
        :meth:`load` alone cannot rebuild. The prompt iterator cannot rewind,
        so it is rebuilt from the retained pipeline and the restored draw
        count is replayed (the same exact-resume mechanics as a process
        restart); the async producer is resynced by publishing the restored
        params so its next chunk samples from the good policy, not the
        anomalous one; experience already collected from the bad policy is
        dropped (post_epoch_callback refills the store after the epoch
        breaks)."""
        def reanchor():
            if self._prompt_pipeline is not None:
                self.add_prompt_pipeline(self._prompt_pipeline)
                self._prompt_batches_drawn = 0
                self._fast_forward_prompt_stream()
            if self._engine is not None:
                self._policy_version = self._engine.publisher.publish(self.params)
                gauges.set("rollout/learner_version", float(self._policy_version))

        if self._engine is not None and self._engine.running:
            # the producer draws from prompt_iterator between produce
            # iterations — swap it only while production is paused
            with self._engine.paused():
                reanchor()
        else:
            reanchor()
        self.store.clear_history()

    def evaluate(self):
        """Eval shares the tokenizer, RNG, and compiled-generate caches with the
        rollout producer: pause the engine for the duration."""
        if self._engine is not None and self._engine.running:
            with self._engine.paused():
                return super().evaluate()
        return super().evaluate()

    def on_learn_end(self):
        """Drain and join the rollout producer (no dangling threads, whatever
        path exited learn()). Producer errors found here are logged, not
        raised: this runs in learn()'s finally and must not mask the original
        exception; a producer death during training already surfaces through
        collect()."""
        engine, self._engine = self._engine, None
        island, self._island = self._island, None
        if engine is None:
            return
        try:
            stats = engine.stop(timeout=self._async_cfg.drain_timeout_s)
            logger.info(f"async rollout engine stopped: {stats}")
        except Exception as e:
            logger.warning(f"async rollout engine teardown: {type(e).__name__}: {e}")
        finally:
            if island is not None:
                # final numbers before the prefix-aware gauge clear
                logger.info(f"generation island closed: {island.summary()}")
                island.close()
