"""GRPO trainer: group-relative PPO without a critic, online-fed or self-fed.

One subclass away from :class:`~trlx_tpu.trainer.ppo_trainer.PPOTrainer` —
deliberately. GRPO changes three things and inherits everything else
(microbatching, the FSDP / overlapped-collective step, stream-overlap
rollout, checkpointing, chaos/quarantine screens):

1. **Group generation** — each drawn prompt is repeated ``group_size``
   times adjacently in the decode batch, so every scoring chunk holds
   whole groups (``chunk_size % group_size == 0`` is enforced by the
   method config). Batch shapes are unchanged: a decode batch of B prompts
   becomes B/G unique prompts × G repeats, never B×G sequences.
2. **Group scoring** — scalar rewards are normalized against their own
   group's mean/std (``GRPOConfig.group_normalize``) before the inherited
   ``_score_and_store`` assembles KL-penalized per-token rewards; the
   critic-free ``GRPOConfig.get_advantages_and_returns`` then turns them
   into returns-to-go advantages inside the jitted loss.
3. **Online experience** — with ``train.online.enabled`` the experience
   phase first drains labeled groups from an
   :class:`~trlx_tpu.online.buffer.OnlineExperienceBuffer` (fleet-harvested
   by a :class:`~trlx_tpu.online.collector.PreferenceCollector`), scoring
   the stored completions through the same forward pass as self-generated
   rollouts; self-generation tops up any shortfall. Staleness admission
   and version stamping ride the existing accountant (docs/online.md).

The behavior logprobs of online groups are recomputed under the *current*
policy at consumption time (the same scoring forward self-generated
rollouts use), so the PPO ratio starts at 1 and the group advantage drives
the first step — the standard "recompute-behavior" online simplification;
version lag is still bounded by the buffer's staleness admission.

Gauges: ``online/group_adv_std`` (mean within-group std of normalized
advantages; 0 = degenerate groups, ~1 = healthy spread),
``online/raw_score_std``, ``online/policy_delta`` (mean |ratio-1| from the
loss), plus the buffer/collector families.
"""

from typing import Dict, List

import numpy as np

import jax

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.methods.grpo import GRPOConfig
from trlx_tpu.obs import span
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.ppo_trainer import PPOTrainer
from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)


@register_trainer
class GRPOTrainer(PPOTrainer):
    def __init__(self, config: TRLConfig, **kwargs):
        super().__init__(config, **kwargs)
        if not isinstance(config.method, GRPOConfig):
            raise ValueError("GRPOTrainer requires method=GRPOConfig")
        self.method: GRPOConfig = config.method
        g = self.method.group_size
        dbs = self.method.decode_batch_size
        if dbs is not None and dbs % g != 0:
            raise ValueError(
                f"decode_batch_size ({dbs}) must be a multiple of "
                f"group_size ({g}) — groups must not straddle decode batches"
            )
        gen = self.method.gen_experience_kwargs or self.method.gen_kwargs
        if not gen.get("do_sample", False):
            logger.warning(
                "GRPO with greedy decoding: all group members will be "
                "identical and every group advantage zero — set "
                "do_sample=True in gen_kwargs"
            )

        # online experience plumbing (train.online; docs/online.md). The
        # buffer is built here so collectors can attach before learning
        # starts; attach_online swaps in an externally-fed buffer (the
        # fleet's collector owns it in the serving process).
        online = getattr(config.train, "online", None)
        self._online_cfg = online if (online is not None and online.enabled) else None
        self._online_buffer = None
        if self._online_cfg is not None:
            from trlx_tpu.online.buffer import OnlineExperienceBuffer

            if self._online_cfg.group_size != g:
                raise ValueError(
                    f"train.online.group_size ({self._online_cfg.group_size}) "
                    f"must match method.group_size ({g})"
                )
            self._online_buffer = OnlineExperienceBuffer(
                capacity=self._online_cfg.buffer_capacity,
                max_staleness=self._online_cfg.max_staleness,
            )

    # ----------------------------------------------------------- online feed

    @property
    def online_buffer(self):
        return self._online_buffer

    def attach_online(self, buffer) -> None:
        """Install an externally-fed experience buffer (the collector's).
        Requires ``train.online.enabled`` — with it off the trainer must be
        bit-for-bit the self-generating GRPO path."""
        if self._online_cfg is None:
            raise ValueError(
                "attach_online requires train.online.enabled=True"
            )
        self._online_buffer = buffer

    # ------------------------------------------------------ group generation

    def add_prompt_pipeline(self, pipeline):
        """Attach the prompt pipeline, regrouped: each decode batch keeps its
        size but holds B/G unique prompts repeated G times adjacently —
        scoring chunks then always contain whole groups."""
        super().add_prompt_pipeline(pipeline)
        g = self.method.group_size
        base = self.prompt_iterator

        def grouped(stream):
            for batch in stream:
                n = len(batch["input_ids"])
                keep = max(1, n // g)
                yield {
                    k: [v[i] for i in range(keep) for _ in range(g)]
                    for k, v in batch.items()
                }

        self.prompt_iterator = grouped(base)

    # --------------------------------------------------------- group scoring

    def _score_and_store(
        self, chunk, scores, ppo_rl_elements, accumulated_kl, all_scores_log, params=None
    ):
        """Group-normalize scalar scores, then defer to the inherited
        assembly. Dense (per-token) rewards collapse to their sum first —
        the group baseline is defined over sequence-level scores."""
        if np.ndim(scores[0]) > 0:
            scores = np.asarray(
                [np.asarray(s, np.float32).sum() for s in scores], np.float32
            )
        else:
            scores = np.asarray(jax.device_get(scores), np.float32).reshape(-1)
        g = self.method.group_size
        grouped = scores.reshape(-1, g)
        gauges.set("online/raw_score_std", float(grouped.std(axis=1).mean()))
        normalized = self.method.group_normalize(scores)
        gauges.set(
            "online/group_adv_std",
            float(normalized.reshape(-1, g).std(axis=1).mean()),
        )
        super()._score_and_store(
            chunk, normalized, ppo_rl_elements, accumulated_kl, all_scores_log,
            params=params,
        )

    # ------------------------------------------------------ online experience

    def make_experience(self, num_rollouts: int = 1024, iter_count: int = 0):
        """Experience phase: drain harvested groups first (online), then top
        up by self-generation. With online off (or an empty buffer) this IS
        the inherited path — the off switch stays bit-for-bit pre-online."""
        buffer = self._online_buffer
        if buffer is None or len(buffer) == 0:
            return super().make_experience(num_rollouts, iter_count)

        from trlx_tpu.data.ppo_types import PPORLElement

        g = self.method.group_size
        elements: List[PPORLElement] = []
        accumulated_kl: List[float] = []
        all_scores_log: List[float] = []
        self.clock.tick()
        groups = buffer.drain(
            max(1, num_rollouts // g), learner_version=self._policy_version
        )
        logger.info(
            f"Consuming {len(groups)} harvested groups "
            f"({len(groups) * g}/{num_rollouts} rollouts) from the online buffer"
        )
        for group in groups:
            if any(len(c) == 0 for c in group.completions):
                continue  # an empty completion has no last token to score
            chunk = (
                [list(group.prompt)] * group.group_size,
                [list(c) for c in group.completions],
            )
            n0 = len(elements)
            # one group per scoring call keeps the version stamp exact even
            # when the quarantine screen drops elements mid-chunk
            self._score_and_store(
                chunk, group.scores, elements, accumulated_kl, all_scores_log
            )
            for e in elements[n0:]:
                e.policy_version = group.policy_version
        gauges.set("online/groups_consumed", float(len(groups)))

        # top up the shortfall by self-generation (traffic ran short)
        if len(elements) < num_rollouts and self.reward_fn is None:
            logger.warning(
                f"online buffer supplied {len(elements)}/{num_rollouts} "
                f"rollouts and no reward_fn is attached to top up: training "
                f"on the short batch"
            )
        elif len(elements) < num_rollouts:
            while len(elements) < num_rollouts:
                for chunk, reward_kwargs in self._generate_chunks(self.tokenizer):
                    with span("reward"):
                        scores = self.call_reward_fn(**reward_kwargs)
                    self._score_and_store(
                        chunk, scores, elements, accumulated_kl, all_scores_log
                    )

        self.mean_kl = float(np.mean(accumulated_kl)) if accumulated_kl else 0.0
        rollout_time = self.clock.tick()
        self.rollout_stats = {
            "rollout_scores/mean": float(np.mean(all_scores_log)) if all_scores_log else 0.0,
            "rollout_scores/std": float(np.std(all_scores_log)) if all_scores_log else 0.0,
            "rollout_scores/running_mean": float(self.running_moments.mean),
            "rollout_scores/running_std": float(self.running_moments.std),
            "policy/sqrt_kl": float(np.sqrt(max(self.mean_kl, 0.0))),
            "kl_ctl_value": float(self.kl_ctl.value),
            "time/rollout_time": rollout_time,
        }
        if self.log_rollouts:
            self.store.export_history(
                location=self.rollout_logging_dir, tokenizer=self.tokenizer
            )
        self.push_to_store(elements[:num_rollouts])
        self._release_ref()

    # ------------------------------------------------------------- reporting

    def train_step(self, batch) -> Dict[str, float]:
        out = super().train_step(batch)
        if "group/policy_delta" in out:
            gauges.set("online/policy_delta", out["group/policy_delta"])
        if self._online_buffer is not None:
            out.update(gauges.snapshot("online/"))
        return out
