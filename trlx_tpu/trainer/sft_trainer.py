"""SFT trainer (parity: `/root/reference/trlx/trainer/accelerate_sft_trainer.py:29-97`):
supervised fine-tuning on strings or (prompt, output) dialogues with prompt-masked CE.
"""

from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.methods.sft import SFTConfig
from trlx_tpu.models.hf_loading import load_pretrained
from trlx_tpu.models.transformer import TransformerLM
from trlx_tpu.ops.generation import pad_to_bucket
from trlx_tpu.parallel import mesh as mesh_lib
from trlx_tpu.parallel.sharding import make_param_shardings
from trlx_tpu.pipeline.offline_pipeline import DialogStore, tokenize_dialogue
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.mesh_trainer import MeshRLTrainer
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

BUCKETS = [2 ** i for i in range(3, 14)]


def _resolve_pad_id(tokenizer):
    """pad_token_id with an eos fallback (causal-style tokenizers reused for T5
    experiments often carry pad_token_id=None); None only if both are unset."""
    pad = tokenizer.pad_token_id
    if pad is None:
        pad = getattr(tokenizer, "eos_token_id", None)
    return pad


class Seq2SeqSFTStore:
    """(encoder prompt ids, decoder target ids) pairs; right-padded at collate.
    The reference has no seq2seq SFT at all — its SFT trainer is causal-only —
    but the T5 PPO recipe needs a supervised warm-start stage, so this closes
    the gap the same way DialogStore does for causal dialogues."""

    IGNORE_INDEX = DialogStore.IGNORE_INDEX

    def __init__(self, pairs, tokenizer):
        self.pairs = pairs  # list of (enc_ids, dec_ids) int arrays
        self.tokenizer = tokenizer
        # resolve the pad id up front: causal-style tokenizers reused for T5
        # experiments often have pad_token_id=None, which would otherwise
        # surface as an opaque np.full TypeError at collate time
        self.pad_id = _resolve_pad_id(tokenizer)
        if self.pad_id is None:
            raise ValueError(
                "Seq2SeqSFTStore requires a tokenizer with pad_token_id (or "
                "eos_token_id as a fallback); both are None on "
                f"{type(tokenizer).__name__}"
            )

    def __len__(self):
        return len(self.pairs)

    def __getitem__(self, ix):
        return self.pairs[ix]

    def create_loader(self, batch_size: int, shuffle: bool = True, drop_last: bool = True,
                      seed: int = 0):
        from trlx_tpu.pipeline.offline_pipeline import NumpyLoader

        pad = self.pad_id

        def collate(items):
            enc_w = max(len(e) for e, _ in items)
            dec_w = max(len(d) for _, d in items)
            B = len(items)
            out = {
                "input_ids": np.full((B, enc_w), pad, np.int32),
                "attention_mask": np.zeros((B, enc_w), np.int32),
                "labels": np.full((B, dec_w), self.IGNORE_INDEX, np.int32),
            }
            for i, (e, d) in enumerate(items):
                out["input_ids"][i, : len(e)] = e
                out["attention_mask"][i, : len(e)] = 1
                out["labels"][i, : len(d)] = d
            return out

        return NumpyLoader(self, batch_size, collate, shuffle=shuffle,
                           drop_last=drop_last, seed=seed)


@register_trainer
class SFTTrainer(MeshRLTrainer):
    def __init__(self, config: TRLConfig, **kwargs):
        super().__init__(config, **kwargs)
        self.method: SFTConfig = config.method
        self._train_steps = {}

    def setup_model(self):
        self.is_seq2seq = self.config.model.model_arch_type == "seq2seq"
        if self.is_seq2seq:
            return self._setup_seq2seq_model()
        overrides = dict(self.config.model.model_overrides or {})
        overrides.setdefault("param_dtype", self.param_dtype)
        overrides.setdefault("compute_dtype", self.compute_dtype)
        overrides.setdefault("remat", self.config.mesh.remat)
        overrides.setdefault("sequence_sharding", self.config.mesh.sequence_shard)
        from trlx_tpu.models.hf_loading import init_params, merge_loaded_params, peft_overrides

        overrides.update(peft_overrides(self.config.model.peft_config))
        overrides.update(self.pipeline_overrides())
        self.model_config, trunk_params, self.model_type = load_pretrained(
            self.config.model.model_path, overrides, mesh=self.restore_mesh(overrides)
        )
        trunk_params = self.maybe_stack_loaded(trunk_params, self.model_config.num_layers)
        self.trunk_module = TransformerLM(self.model_config)
        init_tree = init_params(self.model_config, self.trunk_module, self.config.train.seed)
        if trunk_params is not None:
            init_tree = merge_loaded_params(init_tree, trunk_params)
        params = {"transformer": init_tree}
        shardings = make_param_shardings(params, self.mesh)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x, self.param_dtype), s), params, shardings
        )

    def _setup_seq2seq_model(self):
        from trlx_tpu.models.hf_loading import (
            load_pretrained_seq2seq,
            merge_loaded_params,
            t5_peft_overrides,
        )
        from trlx_tpu.models.t5 import T5LM

        self.pipeline_overrides()  # validates mesh.pipe (raises: PP is causal-only)
        overrides = dict(self.config.model.model_overrides or {})
        overrides.setdefault("param_dtype", self.param_dtype)
        overrides.setdefault("compute_dtype", self.compute_dtype)
        overrides.update(t5_peft_overrides(self.config.model.peft_config))
        self.model_config, t5_params = load_pretrained_seq2seq(
            self.config.model.model_path, overrides, mesh=self.mesh
        )
        self.model_type = "t5"
        self.decoder_start_token_id = self.model_config.decoder_start_token_id
        self.module = T5LM(self.model_config)
        params_t5 = self.module.init(
            jax.random.PRNGKey(self.config.train.seed),
            jnp.ones((1, 4), jnp.int32), jnp.ones((1, 4), jnp.int32),
            jnp.zeros((1, 2), jnp.int32),
        )["params"]
        if t5_params is not None:
            params_t5 = merge_loaded_params(params_t5, t5_params)
        params = {"t5": params_t5}
        shardings = make_param_shardings(params, self.mesh)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x, self.param_dtype), s), params, shardings
        )

    def seq2seq_gen_fns(self):
        module = self.module

        return {
            "encode": lambda params, ids, mask: module.apply(
                {"params": params["t5"]}, ids, mask, method=module.encode
            ),
            "cross_kv": lambda params, enc: module.apply(
                {"params": params["t5"]}, enc, method=module.precompute_cross_kv
            ),
            "decode": lambda params, tok, enc, enc_mask, dec_mask, pos, cache, ckv: module.apply(
                {"params": params["t5"]}, tok, enc, enc_mask, dec_mask, pos, cache, ckv,
                method=module.decode,
            ),
            "init_cache": lambda params, b, n: self.module.init_cache(b, n),
        }

    def gen_step_fn(self):
        trunk = self.trunk_module

        def step(params, ids, mask, positions, cache):
            logits, hidden, _, cache = trunk.apply(
                {"params": params["transformer"]}, ids, mask, positions, cache
            )
            return logits, hidden, cache

        return step, lambda b, s: trunk.init_cache(b, s)

    def make_experience(self, samples: List, seq_length: int):
        """Tokenize dialogues into the DialogStore (parity: sft_trainer :60-70);
        seq2seq: (prompt segments..., final output) -> encoder/decoder pair."""
        dialogs = [tokenize_dialogue(s, self.tokenizer, seq_length) for s in samples]
        if self.is_seq2seq:
            pairs = []
            for msgs in dialogs:
                enc = [t for m in msgs if not m.is_output for t in m.tokens]
                dec = [t for m in msgs if m.is_output for t in m.tokens]
                if not enc or not dec:
                    continue  # degenerate after truncation
                pairs.append((np.asarray(enc, np.int32), np.asarray(dec, np.int32)))
            self.store = Seq2SeqSFTStore(pairs, self.tokenizer)
            return
        self.store = DialogStore(dialogs, self.tokenizer)

    def create_train_dataloader(self):
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed
        )

    def prepare_learning(self):
        bs = self.config.train.batch_size
        self.num_mb = max(1, bs // (self.config.train.minibatch_size or bs))

    def _get_s2s_train_step(self, B: int, Te: int, Td: int):
        key = ("s2s", B, Te, Td)
        if key in self._train_steps:
            return self._train_steps[key]
        module = self.module
        start_id = self.decoder_start_token_id
        ignore = Seq2SeqSFTStore.IGNORE_INDEX

        def loss_fn(params, mb):
            labels = mb["labels"]
            valid = (labels != ignore).astype(jnp.int32)
            safe = jnp.where(valid.astype(bool), labels, 0)
            # teacher forcing: decoder reads [start, y_0..y_{T-2}], predicts y_t
            dec_in = jnp.concatenate(
                [jnp.full((labels.shape[0], 1), start_id, jnp.int32), safe[:, :-1]], axis=1
            )
            dec_mask = jnp.concatenate(
                [jnp.ones((labels.shape[0], 1), jnp.int32), valid[:, :-1]], axis=1
            )
            logits, _, _ = module.apply(
                {"params": params["t5"]}, mb["input_ids"], mb["attention_mask"],
                dec_in, dec_mask,
            )
            logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logprobs, safe[..., None], axis=-1)[..., 0]
            mask = valid.astype(jnp.float32)
            loss = jnp.sum(nll * mask) / jnp.maximum(mask.sum(), 1.0)
            from trlx_tpu.utils.modeling import flatten_dict

            return loss, flatten_dict(dict(losses=dict(loss=loss)))

        self._train_steps[key] = self.make_grad_accum_step(loss_fn, self.num_mb)
        return self._train_steps[key]

    def _get_train_step(self, B: int, T: int):
        key = (B, T)
        if key in self._train_steps:
            return self._train_steps[key]
        trunk, method = self.trunk_module, self.method

        def loss_fn(params, mb):
            logits, _, _, _ = trunk.apply(
                {"params": params["transformer"]}, mb["input_ids"], mb["attention_mask"]
            )
            loss_mask = (mb["labels"] != DialogStore.IGNORE_INDEX).astype(jnp.float32)
            labels = jnp.where(mb["labels"] == DialogStore.IGNORE_INDEX, 0, mb["labels"])
            loss, stats = method.loss(logits, labels, loss_mask * mb["attention_mask"])
            from trlx_tpu.utils.modeling import flatten_dict

            return loss, flatten_dict(stats)

        self._train_steps[key] = self.make_grad_accum_step(loss_fn, self.num_mb)
        return self._train_steps[key]

    def train_step(self, batch) -> Dict[str, float]:
        if self.is_seq2seq:
            return self._train_step_s2s(batch)
        B, T = batch["input_ids"].shape
        Tb = pad_to_bucket(T, BUCKETS)
        # pad rows to a num_mb multiple (fully-masked rows contribute zero loss)
        Bp = ((B + self.num_mb - 1) // self.num_mb) * self.num_mb
        pad = ((0, Bp - B), (0, Tb - T))
        padded = {
            "input_ids": np.pad(batch["input_ids"], pad, constant_values=self.tokenizer.pad_token_id),
            "attention_mask": np.pad(batch["attention_mask"], pad),
            "labels": np.pad(batch["labels"], pad, constant_values=DialogStore.IGNORE_INDEX),
        }
        B = Bp
        dbatch = mesh_lib.put_batch(self.mesh, padded)
        step = self._get_train_step(B, Tb)
        with self.mesh:
            self.params, self.opt_state, stats = step(self.params, self.opt_state, dbatch)
        return {k: float(v) for k, v in jax.device_get(stats).items()}

    def _train_step_s2s(self, batch) -> Dict[str, float]:
        B, Te = batch["input_ids"].shape
        Td = batch["labels"].shape[1]
        Teb, Tdb = pad_to_bucket(Te, BUCKETS), pad_to_bucket(Td, BUCKETS)
        Bp = ((B + self.num_mb - 1) // self.num_mb) * self.num_mb
        padded = {
            "input_ids": np.pad(
                batch["input_ids"], ((0, Bp - B), (0, Teb - Te)),
                constant_values=_resolve_pad_id(self.tokenizer),
            ),
            "attention_mask": np.pad(batch["attention_mask"], ((0, Bp - B), (0, Teb - Te))),
            "labels": np.pad(
                batch["labels"], ((0, Bp - B), (0, Tdb - Td)),
                constant_values=Seq2SeqSFTStore.IGNORE_INDEX,
            ),
        }
        dbatch = mesh_lib.put_batch(self.mesh, padded)
        step = self._get_s2s_train_step(Bp, Teb, Tdb)
        with self.mesh:
            self.params, self.opt_state, stats = step(self.params, self.opt_state, dbatch)
        return {k: float(v) for k, v in jax.device_get(stats).items()}
