"""SFT trainer (parity: `/root/reference/trlx/trainer/accelerate_sft_trainer.py:29-97`):
supervised fine-tuning on strings or (prompt, output) dialogues with prompt-masked CE.
"""

from typing import Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.methods.sft import SFTConfig
from trlx_tpu.models.hf_loading import load_pretrained
from trlx_tpu.models.transformer import TransformerLM
from trlx_tpu.ops.generation import pad_to_bucket
from trlx_tpu.parallel import mesh as mesh_lib
from trlx_tpu.parallel.sharding import make_param_shardings
from trlx_tpu.pipeline.offline_pipeline import DialogStore, tokenize_dialogue
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.mesh_trainer import MeshRLTrainer
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

BUCKETS = [2 ** i for i in range(3, 14)]


@register_trainer
class SFTTrainer(MeshRLTrainer):
    def __init__(self, config: TRLConfig, **kwargs):
        super().__init__(config, **kwargs)
        self.method: SFTConfig = config.method
        self._train_steps = {}

    def setup_model(self):
        overrides = dict(self.config.model.model_overrides or {})
        overrides.setdefault("param_dtype", self.param_dtype)
        overrides.setdefault("compute_dtype", self.compute_dtype)
        overrides.setdefault("remat", self.config.mesh.remat)
        overrides.setdefault("sequence_sharding", self.config.mesh.sequence_shard)
        from trlx_tpu.models.hf_loading import init_params, merge_loaded_params, peft_overrides

        overrides.update(peft_overrides(self.config.model.peft_config))
        overrides.update(self.pipeline_overrides())
        self.model_config, trunk_params, self.model_type = load_pretrained(
            self.config.model.model_path, overrides, mesh=self.restore_mesh(overrides)
        )
        trunk_params = self.maybe_stack_loaded(trunk_params, self.model_config.num_layers)
        self.trunk_module = TransformerLM(self.model_config)
        init_tree = init_params(self.model_config, self.trunk_module, self.config.train.seed)
        if trunk_params is not None:
            init_tree = merge_loaded_params(init_tree, trunk_params)
        params = {"transformer": init_tree}
        shardings = make_param_shardings(params, self.mesh)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(jnp.asarray(x, self.param_dtype), s), params, shardings
        )

    def gen_step_fn(self):
        trunk = self.trunk_module

        def step(params, ids, mask, positions, cache):
            logits, hidden, _, cache = trunk.apply(
                {"params": params["transformer"]}, ids, mask, positions, cache
            )
            return logits, hidden, cache

        return step, lambda b, s: trunk.init_cache(b, s)

    def make_experience(self, samples: List, seq_length: int):
        """Tokenize dialogues into the DialogStore (parity: sft_trainer :60-70)."""
        dialogs = [tokenize_dialogue(s, self.tokenizer, seq_length) for s in samples]
        self.store = DialogStore(dialogs, self.tokenizer)

    def create_train_dataloader(self):
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed
        )

    def prepare_learning(self):
        bs = self.config.train.batch_size
        self.num_mb = max(1, bs // (self.config.train.minibatch_size or bs))

    def _get_train_step(self, B: int, T: int):
        key = (B, T)
        if key in self._train_steps:
            return self._train_steps[key]
        trunk, method = self.trunk_module, self.method

        def loss_fn(params, mb):
            logits, _, _, _ = trunk.apply(
                {"params": params["transformer"]}, mb["input_ids"], mb["attention_mask"]
            )
            loss_mask = (mb["labels"] != DialogStore.IGNORE_INDEX).astype(jnp.float32)
            labels = jnp.where(mb["labels"] == DialogStore.IGNORE_INDEX, 0, mb["labels"])
            loss, stats = method.loss(logits, labels, loss_mask * mb["attention_mask"])
            from trlx_tpu.utils.modeling import flatten_dict

            return loss, flatten_dict(stats)

        self._train_steps[key] = self.make_grad_accum_step(loss_fn, self.num_mb)
        return self._train_steps[key]

    def train_step(self, batch) -> Dict[str, float]:
        B, T = batch["input_ids"].shape
        Tb = pad_to_bucket(T, BUCKETS)
        # pad rows to a num_mb multiple (fully-masked rows contribute zero loss)
        Bp = ((B + self.num_mb - 1) // self.num_mb) * self.num_mb
        pad = ((0, Bp - B), (0, Tb - T))
        padded = {
            "input_ids": np.pad(batch["input_ids"], pad, constant_values=self.tokenizer.pad_token_id),
            "attention_mask": np.pad(batch["attention_mask"], pad),
            "labels": np.pad(batch["labels"], pad, constant_values=DialogStore.IGNORE_INDEX),
        }
        B = Bp
        dbatch = mesh_lib.put_batch(self.mesh, padded)
        step = self._get_train_step(B, Tb)
        with self.mesh:
            self.params, self.opt_state, stats = step(self.params, self.opt_state, dbatch)
        return {k: float(v) for k, v in jax.device_get(stats).items()}
