"""RFT trainer (parity: `/root/reference/trlx/trainer/accelerate_rft_trainer.py:45-197`):
every ``n_improve_steps`` epochs, sample N generations per prompt, score them with the
reward function, keep generations above a rising per-prompt percentile threshold,
deduplicate, and supervised-train on the survivors (full CE over prompt+output, like
the reference's ``labels = input_ids``).
"""

from collections import defaultdict

import numpy as np

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.methods.rft import RFTConfig
from trlx_tpu.pipeline.offline_pipeline import DialogMessage, DialogStore, tokenize_dialogue
from trlx_tpu.trainer import register_trainer
from trlx_tpu.trainer.sft_trainer import SFTTrainer
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


@register_trainer
class RFTTrainer(SFTTrainer):
    def __init__(self, config: TRLConfig, **kwargs):
        super().__init__(config, **kwargs)
        self.method: RFTConfig = config.method
        self.generate_experience_kwargs = None

    def add_prompt_pipeline(self, pipeline):
        self.prompt_loader = pipeline.create_loader(self.config.train.batch_size)

    def prepare_learning(self):
        super().prepare_learning()
        self.epoch_count = 0
        self.generations_per_prompt = defaultdict(list)
        self.store = None
        self.make_experience()

    def post_epoch_callback(self, epoch: int):
        self.make_experience()
        self.epoch_count += 1

    def create_train_dataloader(self):
        if self.store is None or len(self.store.history) == 0:
            return iter(())
        return self.store.create_loader(
            self.config.train.batch_size, shuffle=True, seed=self.config.train.seed + self.epoch_count
        )

    def make_experience(self):
        method = self.method
        if self.epoch_count % method.n_improve_steps == 0:
            generations = []
            for batch in self.prompt_loader:
                prompts = batch["input_ids"]
                for _ in range(method.n_generations_per_prompt):
                    samples, resp_mask, pad_len = self.generate(prompts, eval_mode=True)
                    _, str_prompts, str_outputs, _ = self.decode(
                        prompts, samples, pad_len, append_eos=True, response_masks=resp_mask
                    )
                    generations.extend(
                        {"prompt": p, "output": o} for p, o in zip(str_prompts, str_outputs)
                    )
            scores = self.reward_fn(
                samples=[x["prompt"] + x["output"] for x in generations],
                prompts=[x["prompt"] for x in generations],
                outputs=[x["output"] for x in generations],
                tokenizer=self.tokenizer,
            )
            for g, s in zip(generations, scores):
                self.generations_per_prompt[g["prompt"]].append(
                    {"output": g["output"], "score": float(s)}
                )

        per_prompt_scores = [
            [x["score"] for x in self.generations_per_prompt[p]] for p in self.generations_per_prompt
        ]
        percentile_delta = (method.end_percentile - method.start_percentile) / method.n_improve_steps
        percentile = method.start_percentile + percentile_delta * (
            self.epoch_count % method.n_improve_steps
        )
        thresholds = np.array([np.quantile(np.array(s), percentile) for s in per_prompt_scores])
        # quantized-reward corner case: exclude min values, never exclude max values
        thresholds = np.clip(thresholds, thresholds.min() + 1e-3, thresholds.max() - 1e-3)

        samples_selected = []
        for prompt, threshold in zip(self.generations_per_prompt, thresholds):
            for x in self.generations_per_prompt[prompt]:
                if x["score"] >= threshold:
                    samples_selected.append((prompt, x["output"]))
        samples_selected = sorted(set(samples_selected))

        stats = {
            "rft/scores_mean": float(np.mean(np.hstack(per_prompt_scores))),
            "rft/len_samples_selected": len(samples_selected),
            "rft/percentile": percentile,
        }
        self.tracker.log(stats, self.iter_count)
        logger.info(f"RFT improve step: {stats}")

        if samples_selected:
            dialogs = [
                tokenize_dialogue([p, o], self.tokenizer, self.config.train.seq_length)
                for p, o in samples_selected
            ]
            # full-CE supervision (reference uses labels = input_ids)
            dialogs = [[DialogMessage(True, m.tokens) for m in d] for d in dialogs]
            self.store = DialogStore(dialogs, self.tokenizer)
