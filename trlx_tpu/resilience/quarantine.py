"""Experience quarantine: divert invalid rollout elements instead of training on them.

The PPO learner trusts its experience buffer completely — a single element
with NaN logprobs turns the importance ratio, hence the loss, hence (through
donated buffers) the *parameters* non-finite, and the run is dead long before
anyone reads a metric. Rollout elements cross a trust boundary (reward
servers, decode numerics, staleness bookkeeping), so they get validated at
the single choke point where both the synchronous and the async producer
paths assemble them (``PPOTrainer._score_and_store``).

:class:`ExperienceQuarantine` screens each element for

- non-finite ``logprobs`` / ``values`` / ``rewards``,
- an empty response,

and diverts offenders to a JSONL sidecar (one record per element: reason,
policy version, and the full arrays as lists) for postmortem — the learner
only ever sees clean experience, and nothing is silently discarded. Counts
land in the ``resilience/quarantined`` gauge, which rides the per-step stats
and the end-of-run self-healing summary.

Thread-safety: the async producer thread and the learner (sync path) may both
score; a lock serializes sidecar appends. Chaos site ``bad-element``
(:func:`chaos_corrupt_elements`) fabricates offenders to prove the screen
holds end-to-end.
"""

import json
import os
import threading
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from trlx_tpu.resilience.chaos import chaos
from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)

QUARANTINE_FILE = "quarantine.jsonl"


def validate_element(element) -> Optional[str]:
    """Reason this element must not reach the learner, or ``None`` if clean."""
    response = np.asarray(element.response_tensor)
    if response.size == 0:
        return "empty response"
    for field in ("logprobs", "values", "rewards"):
        arr = np.asarray(getattr(element, field))
        if arr.size and not np.all(np.isfinite(arr.astype(np.float64))):
            return f"non-finite {field}"
    return None


def chaos_corrupt_elements(elements: List[Any]) -> List[Any]:
    """Chaos site ``bad-element``: replace the first element's logprobs with
    NaNs — the signature of a poisoned scoring pass. Free when unarmed."""
    if not elements or not chaos.should_fail("bad-element"):
        return elements
    logger.warning("chaos: corrupting one rollout element at site 'bad-element'")
    first = elements[0]
    bad = np.full_like(np.asarray(first.logprobs, dtype=np.float32), np.nan)
    return [first.replace(logprobs=bad)] + list(elements[1:])


class ExperienceQuarantine:
    """Validate rollout elements; sidecar the bad ones (module docstring)."""

    def __init__(self, directory: str):
        self.directory = directory
        self.path = os.path.join(directory, QUARANTINE_FILE)
        self._lock = threading.Lock()
        self._count = 0

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def filter(self, elements: List[Any], context: str = "") -> List[Any]:
        """Split ``elements`` into clean (returned) and quarantined (written
        to the sidecar). Never raises on I/O: losing the sidecar must not
        lose the protection."""
        clean, bad = [], []
        for element in elements:
            reason = validate_element(element)
            if reason is None:
                clean.append(element)
            else:
                bad.append((reason, element))
        if bad:
            self._record(bad, context)
        return clean

    def _record(self, bad: List[Tuple[str, Any]], context: str):
        records = [
            {
                "time": time.time(),
                "context": context,
                "reason": reason,
                "policy_version": int(np.asarray(e.policy_version)),
                "query_tokens": np.asarray(e.query_tensor).tolist(),
                "response_tokens": np.asarray(e.response_tensor).tolist(),
                "logprobs": np.asarray(e.logprobs, dtype=np.float64).tolist(),
                "values": np.asarray(e.values, dtype=np.float64).tolist(),
                "rewards": np.asarray(e.rewards, dtype=np.float64).tolist(),
            }
            for reason, e in bad
        ]
        with self._lock:
            self._count += len(bad)
            count = self._count
            try:
                os.makedirs(self.directory, exist_ok=True)
                with open(self.path, "a") as f:
                    for record in records:
                        f.write(json.dumps(record) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            except OSError as e:
                logger.error(f"failed to append quarantine sidecar {self.path}: {e}")
        gauges.set("resilience/quarantined", float(count))
        reasons = ", ".join(sorted({r for r, _ in bad}))
        logger.warning(
            f"quarantined {len(bad)} rollout element(s) ({reasons}) -> {self.path}"
        )
