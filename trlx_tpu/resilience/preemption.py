"""Graceful preemption: turn SIGTERM into a checkpoint, not a lost run.

TPU preemptions (maintenance events, spot reclaims) follow a fixed script:
the job receives SIGTERM, gets a grace window (typically 30s–5min depending
on provisioning), then SIGKILL. Untrapped, that loses everything since the
last periodic checkpoint. :class:`PreemptionHandler` makes the window count:

- ``install()`` traps SIGTERM/SIGINT **on the main thread** (Python delivers
  signals there; installing from a worker raises ``ValueError``, so we check
  first and no-op with a warning — e.g. under pytest-xdist workers).
- The handler body only sets a flag and records the deadline — everything
  else (emergency checkpoint, rollout drain) runs in the trainer loop when it
  polls :meth:`should_stop`, because signal-handler context cannot safely run
  collective device operations.
- After the first signal the previous handler is **reinstated**: a second
  SIGTERM/SIGINT terminates immediately. This is deliberate — the operator's
  ctrl-C-twice escape hatch, and the SIGKILL-after-SIGTERM contract needs no
  special case (SIGKILL is untrappable anyway).
- :meth:`simulate` arms the same flag without any OS signal, which is how
  chaos's ``preempt-step:N`` site and the tests drive the full
  emergency-checkpoint path deterministically in-process.
"""

import signal
import threading
import time
from typing import Optional, Tuple

from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)


class PreemptionHandler:
    def __init__(
        self,
        grace_period_s: float = 30.0,
        signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT),
    ):
        self.grace_period_s = float(grace_period_s)
        self.signals = tuple(signals)
        self._flag = threading.Event()
        self._deadline: Optional[float] = None
        self._reason: Optional[str] = None
        self._prev_handlers = {}
        self._installed = False

    # -------------------------------------------------------------- lifecycle

    def install(self) -> bool:
        """Trap the signals; returns False (with a warning) off the main thread."""
        if self._installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "PreemptionHandler.install() called off the main thread; "
                "signal handling disabled (simulated preemption still works)"
            )
            return False
        for sig in self.signals:
            self._prev_handlers[sig] = signal.signal(sig, self._on_signal)
        self._installed = True
        return True

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # off main thread / handler gone
                pass
        self._prev_handlers = {}
        self._installed = False

    def _on_signal(self, signum, frame) -> None:
        # keep this body minimal: flag + deadline + reinstate previous handler
        # (second signal = immediate termination, the operator escape hatch)
        self._arm(f"signal {signal.Signals(signum).name}")
        self.uninstall()

    # ------------------------------------------------------------------ state

    def _arm(self, reason: str) -> None:
        if self._flag.is_set():
            return
        self._reason = reason
        self._deadline = time.monotonic() + self.grace_period_s
        self._flag.set()
        gauges.inc("resilience/preemptions")
        logger.warning(
            f"PREEMPTION: {reason}; grace window {self.grace_period_s:.0f}s — "
            "will checkpoint and exit at the next step boundary"
        )

    def simulate(self, reason: str = "simulated") -> None:
        """Arm the preemption flag without an OS signal (chaos / tests)."""
        self._arm(reason)

    @property
    def preempted(self) -> bool:
        return self._flag.is_set()

    @property
    def reason(self) -> Optional[str]:
        return self._reason

    @property
    def grace_remaining_s(self) -> Optional[float]:
        """Seconds left in the grace window; None if not preempted."""
        if self._deadline is None:
            return None
        return self._deadline - time.monotonic()
