"""Resilience subsystem: survive preemptions and transient faults
(docs/resilience.md).

Five primitives, each usable standalone, plus the :class:`Resilience` facade
the trainer drives from ``TRLConfig.train.resilience``:

- :mod:`trlx_tpu.resilience.checkpoint` — atomic commit protocol
  (tmp-dir → rename → ``_COMMITTED`` sentinel), retention GC, and the
  background :class:`AsyncCheckpointWriter` that takes checkpointing off the
  learner's critical path.
- :mod:`trlx_tpu.resilience.preemption` — SIGTERM/SIGINT grace-window
  handler: flag now, emergency-checkpoint at the next step boundary.
- :mod:`trlx_tpu.resilience.resume` — newest-committed-checkpoint discovery
  (numeric step order, torn dirs skipped) and RNG state packing.
- :mod:`trlx_tpu.resilience.retry` — backoff + jitter + deadline for flaky
  host-side calls (reward RPCs, HF hub loads).
- :mod:`trlx_tpu.resilience.chaos` — ``TRLX_CHAOS`` fault injection that
  proves all of the above in tests.
"""

from trlx_tpu.resilience.chaos import ChaosInjectedError, ChaosMonkey, chaos
from trlx_tpu.resilience.checkpoint import (
    COMMITTED_SENTINEL,
    AsyncCheckpointWriter,
    gc_checkpoints,
    is_committed,
    mark_committed,
    write_checkpoint,
    write_json_atomic,
)
from trlx_tpu.resilience.preemption import PreemptionHandler
from trlx_tpu.resilience.resume import (
    CHECKPOINT_PREFIX,
    checkpoint_step,
    find_latest_committed,
    list_checkpoints,
)
from trlx_tpu.resilience.retry import (
    RetryDeadlineExceeded,
    RetryPolicy,
    retry_call,
    with_retries,
)
from trlx_tpu.resilience.runtime import PROTECTED_CHECKPOINTS, Resilience

__all__ = [
    "AsyncCheckpointWriter",
    "CHECKPOINT_PREFIX",
    "COMMITTED_SENTINEL",
    "ChaosInjectedError",
    "ChaosMonkey",
    "PROTECTED_CHECKPOINTS",
    "PreemptionHandler",
    "Resilience",
    "RetryDeadlineExceeded",
    "RetryPolicy",
    "chaos",
    "checkpoint_step",
    "find_latest_committed",
    "gc_checkpoints",
    "is_committed",
    "list_checkpoints",
    "mark_committed",
    "retry_call",
    "with_retries",
    "write_checkpoint",
    "write_json_atomic",
]
