"""Resilience subsystem: survive preemptions and transient faults
(docs/resilience.md).

Five primitives, each usable standalone, plus the :class:`Resilience` facade
the trainer drives from ``TRLConfig.train.resilience``:

- :mod:`trlx_tpu.resilience.checkpoint` — atomic commit protocol
  (tmp-dir → rename → ``_COMMITTED`` sentinel), retention GC, and the
  background :class:`AsyncCheckpointWriter` that takes checkpointing off the
  learner's critical path.
- :mod:`trlx_tpu.resilience.preemption` — SIGTERM/SIGINT grace-window
  handler: flag now, emergency-checkpoint at the next step boundary.
- :mod:`trlx_tpu.resilience.resume` — newest-committed-checkpoint discovery
  (numeric step order, torn dirs skipped) and RNG state packing.
- :mod:`trlx_tpu.resilience.retry` — backoff + jitter + deadline for flaky
  host-side calls (reward RPCs, HF hub loads).
- :mod:`trlx_tpu.resilience.chaos` — ``TRLX_CHAOS`` fault injection that
  proves all of the above in tests.
- :mod:`trlx_tpu.resilience.health` — :class:`TrainingHealthGuard` escalation
  ladder (skip anomalous updates on device → roll back to the last committed
  checkpoint → halt with a diagnostics bundle) behind
  ``TRLConfig.train.self_healing``.
- :mod:`trlx_tpu.resilience.quarantine` — :class:`ExperienceQuarantine`
  screening rollout elements for non-finite numerics / empty responses and
  diverting offenders to a JSONL sidecar.
"""

from trlx_tpu.resilience.chaos import ChaosInjectedError, ChaosMonkey, chaos
from trlx_tpu.resilience.checkpoint import (
    COMMITTED_SENTINEL,
    AsyncCheckpointWriter,
    gc_checkpoints,
    is_committed,
    mark_committed,
    write_checkpoint,
    write_json_atomic,
)
from trlx_tpu.resilience.health import (
    TrainingHealthError,
    TrainingHealthGuard,
    chaos_poison_batch,
    write_diagnostics_bundle,
)
from trlx_tpu.resilience.preemption import PreemptionHandler
from trlx_tpu.resilience.quarantine import (
    ExperienceQuarantine,
    chaos_corrupt_elements,
    validate_element,
)
from trlx_tpu.resilience.resume import (
    CHECKPOINT_PREFIX,
    checkpoint_step,
    find_latest_committed,
    list_checkpoints,
)
from trlx_tpu.resilience.retry import (
    RetryDeadlineExceeded,
    RetryPolicy,
    retry_call,
    with_retries,
)
from trlx_tpu.resilience.runtime import PROTECTED_CHECKPOINTS, Resilience

__all__ = [
    "AsyncCheckpointWriter",
    "CHECKPOINT_PREFIX",
    "COMMITTED_SENTINEL",
    "ChaosInjectedError",
    "ChaosMonkey",
    "ExperienceQuarantine",
    "PROTECTED_CHECKPOINTS",
    "PreemptionHandler",
    "Resilience",
    "RetryDeadlineExceeded",
    "RetryPolicy",
    "TrainingHealthError",
    "TrainingHealthGuard",
    "chaos",
    "chaos_corrupt_elements",
    "chaos_poison_batch",
    "checkpoint_step",
    "find_latest_committed",
    "gc_checkpoints",
    "is_committed",
    "list_checkpoints",
    "mark_committed",
    "retry_call",
    "validate_element",
    "with_retries",
    "write_checkpoint",
    "write_diagnostics_bundle",
    "write_json_atomic",
]
