"""Atomic, asynchronous checkpointing.

**The atomicity protocol.** A checkpoint directory is *committed* iff it
contains the ``_COMMITTED`` sentinel file. Writers build the full payload in a
sibling ``<name>.tmp`` directory, ``os.replace`` it to its final name, then
write the sentinel and fsync the parent directory. A crash or preemption at
any point therefore leaves one of exactly three states — nothing, an orphaned
``.tmp`` dir, or a final-named dir without the sentinel — all of which
:func:`trlx_tpu.resilience.resume.find_latest_committed` recognizes as torn
and skips. A sentinel is never present over partial bytes.

**The async writer.** ``orbax``'s ``save()`` dispatches device→host transfers
asynchronously, but the existing trainer immediately calls
``wait_until_finished()``, stalling the learn loop for the full serialize+
write. :class:`AsyncCheckpointWriter` instead snapshots the (already
host-side) trees handed to it and runs serialize→fsync→rename→sentinel on a
background thread; the learner only blocks when a *prior* write is still in
flight (one write in flight at a time keeps peak host memory to one snapshot
and makes commit order equal request order). The writer thread beats the
stall watchdog while committing so a long write is distinguishable from a
hang, and errors are re-raised on the learner thread at the next
``save()``/``wait()`` — a failing disk must not be silent.

Single-process only: on multi-host, orbax saves are collective operations
that every process must enter, which a per-host background thread cannot
order safely. The ``Resilience`` runtime falls back to the synchronous path
there.
"""

import json
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

from trlx_tpu.obs import span, watchdog
from trlx_tpu.resilience.chaos import chaos
from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)

COMMITTED_SENTINEL = "_COMMITTED"
TMP_SUFFIX = ".tmp"
STATE_FILE = "state.json"
WRITER_HEARTBEAT = "checkpoint-writer"


def _fsync_dir(path: str) -> None:
    """fsync a directory so renames/creates inside it survive power loss;
    best-effort on filesystems that reject directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def is_committed(path: str) -> bool:
    """True iff ``path`` is a checkpoint directory with the commit sentinel."""
    return os.path.isdir(path) and os.path.exists(os.path.join(path, COMMITTED_SENTINEL))


def mark_committed(path: str) -> None:
    """Write the commit sentinel (the LAST step of any checkpoint write)."""
    sentinel = os.path.join(path, COMMITTED_SENTINEL)
    with open(sentinel, "w") as f:
        f.write(f"committed {time.time():.3f}\n")
        f.flush()
        os.fsync(f.fileno())
    _fsync_dir(path)


def write_json_atomic(path: str, obj: Any) -> None:
    """Write JSON via tmp-file + fsync + rename: readers see old or new, never torn."""
    tmp = path + TMP_SUFFIX
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def write_checkpoint(path: str, trees: Dict[str, Any], state: Dict[str, Any]) -> str:
    """Commit ``trees`` (name -> host pytree, saved via orbax) and ``state``
    (JSON) to ``path`` under the atomicity protocol in the module docstring.
    Runs on the caller's thread; the async writer calls this from its worker."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    tmp = path + TMP_SUFFIX
    if os.path.exists(tmp):  # leftover from a previous crash mid-write
        shutil.rmtree(tmp)
    os.makedirs(tmp, exist_ok=True)
    try:
        chaos.fail_if_armed("checkpoint", detail=path)
        ckptr = ocp.StandardCheckpointer()
        for name, tree in trees.items():
            ckptr.save(os.path.join(tmp, name), tree, force=True)
        ckptr.wait_until_finished()
        write_json_atomic(os.path.join(tmp, STATE_FILE), state)
    except BaseException:
        # the sentinel was never written and the final name never created:
        # a failed write leaves no dir a resume scan could mistake for real
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if os.path.exists(path):  # re-saving the same step (e.g. best_checkpoint)
        shutil.rmtree(path)
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
    mark_committed(path)
    return path


def gc_checkpoints(
    checkpoint_dir: str,
    keep_last: int,
    protected: Optional[List[str]] = None,
    prefix: str = "checkpoint_",
) -> List[str]:
    """Delete all but the newest ``keep_last`` step checkpoints under
    ``checkpoint_dir``. Only committed, ``prefix``-named dirs are candidates:
    ``.tmp`` leftovers, uncommitted dirs, and ``protected`` names
    (``best_checkpoint``, ``hf_model``) are never touched — an uncommitted dir
    may be a write in flight. Returns the deleted paths."""
    from trlx_tpu.resilience.resume import checkpoint_step

    protected = set(protected or [])
    if keep_last <= 0 or not os.path.isdir(checkpoint_dir):
        return []
    candidates = []
    for name in os.listdir(checkpoint_dir):
        if not name.startswith(prefix) or name.endswith(TMP_SUFFIX) or name in protected:
            continue
        path = os.path.join(checkpoint_dir, name)
        step = checkpoint_step(name, prefix)
        if step is None or not is_committed(path):
            continue
        candidates.append((step, path))
    candidates.sort()
    deleted = []
    for _, path in candidates[:-keep_last]:
        shutil.rmtree(path, ignore_errors=True)
        deleted.append(path)
        logger.info(f"Retention: deleted old checkpoint {path}")
    return deleted


class AsyncCheckpointWriter:
    """One-in-flight background checkpoint committer (see module docstring)."""

    def __init__(self, keep_last: int = 0, protected: Optional[List[str]] = None):
        self.keep_last = keep_last
        self.protected = list(protected or [])
        # guards the writer handle and its results: the commit thread writes
        # _error/_last_committed while the learner thread polls in_flight/
        # last_committed between saves
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._last_committed: Optional[str] = None

    @property
    def in_flight(self) -> bool:
        with self._lock:
            thread = self._thread
        return thread is not None and thread.is_alive()

    @property
    def last_committed(self) -> Optional[str]:
        with self._lock:
            return self._last_committed

    def save(
        self,
        path: str,
        trees: Dict[str, Any],
        state: Dict[str, Any],
        block: bool = False,
    ) -> None:
        """Queue one commit. Blocks only while a *prior* write is in flight
        (or entirely, with ``block=True`` — the emergency-checkpoint path).
        ``trees`` must already be host-side (``jax.device_get``) so the commit
        never touches live device buffers the train step may donate."""
        self.wait()  # also re-raises a previous write's error on this thread

        def commit():
            t0 = time.monotonic()
            try:
                watchdog.beat(WRITER_HEARTBEAT)
                with span("checkpoint_commit"):
                    write_checkpoint(path, trees, state)
                if self.keep_last:
                    gc_checkpoints(os.path.dirname(path), self.keep_last, self.protected)
                with self._lock:
                    self._last_committed = os.path.abspath(path)
                gauges.inc("resilience/ckpt_committed")
                gauges.set("resilience/ckpt_commit_s", time.monotonic() - t0)
                logger.info(
                    f"Committed checkpoint {path} in {time.monotonic() - t0:.2f}s"
                )
            except BaseException as e:
                with self._lock:
                    self._error = e
                logger.error(f"Checkpoint commit to {path} FAILED: {e}")
            finally:
                gauges.set("resilience/ckpt_inflight", 0.0)
                # no false posthumous stall report from an idle writer
                watchdog.unregister(WRITER_HEARTBEAT)

        gauges.set("resilience/ckpt_inflight", 1.0)
        thread = threading.Thread(target=commit, name="ckpt-writer", daemon=True)
        with self._lock:
            self._thread = thread
        thread.start()
        if block:
            self.wait()

    def wait(self, timeout: Optional[float] = None) -> None:
        """Join the in-flight write (if any); re-raise its error here."""
        with self._lock:
            thread = self._thread
        if thread is not None:
            # join OUTSIDE the lock: a commit in flight holds the disk for the
            # full serialize+fsync and in_flight/last_committed must stay live
            thread.join(timeout)
            if thread.is_alive():
                raise TimeoutError(f"checkpoint write still in flight after {timeout}s")
        with self._lock:
            if self._thread is thread:  # re-check: a newer save() may have swapped
                self._thread = None
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError("async checkpoint write failed") from err

    def close(self) -> None:
        """Flush the in-flight write; errors are logged, not raised (teardown)."""
        try:
            self.wait()
        except Exception as e:
            logger.error(f"async checkpoint writer: error during close: {e}")
