"""Auto-resume: find the newest *committed* checkpoint, restore full state.

A preempted-and-restarted job must continue with zero operator flags. The
contract has two halves:

1. **Selection** (:func:`find_latest_committed`): scan ``checkpoint_dir`` for
   ``checkpoint_<step>`` directories, order by **numeric** step (robust to
   legacy unpadded names, where lexicographic order put ``checkpoint_9`` after
   ``checkpoint_10``), and return the newest one carrying the ``_COMMITTED``
   sentinel. Torn directories — a rename that landed but whose sentinel write
   didn't, or an interrupted legacy synchronous save — are skipped with a
   warning, falling back to the next-newest committed one. ``best_checkpoint``
   is deliberately *not* a resume candidate: it is reward-ordered, not
   time-ordered.

2. **State** (the trainer's ``_state_dict``/``load``): beyond params and
   opt_state, a faithful resume restores ``iter_count``, ``best_reward``
   (else the first post-resume eval re-saves a worse "best"), the eval
   counter, both RNG streams (the jax sampling key and the host numpy
   generator), and the dataloader position (PPO's prompt-stream draw count —
   replayed exactly, because ``NumpyLoader`` reshuffles per epoch so position
   N is only reproducible by drawing N times from the same seed).

RNG packing: jax 0.4.x `PRNGKey`s are uint32[2] arrays; typed keys
(`jax.random.key`) are unwrapped via ``key_data``. Numpy state is the
``bit_generator.state`` dict, which is JSON-clean for PCG64.
"""

import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax

from trlx_tpu.resilience.checkpoint import COMMITTED_SENTINEL, TMP_SUFFIX, is_committed
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

CHECKPOINT_PREFIX = "checkpoint_"


def checkpoint_step(name: str, prefix: str = CHECKPOINT_PREFIX) -> Optional[int]:
    """Numeric step from a ``checkpoint_<step>`` dir name; None if not one."""
    if not name.startswith(prefix) or name.endswith(TMP_SUFFIX):
        return None
    suffix = name[len(prefix):]
    if not suffix.isdigit():
        return None
    return int(suffix)


def list_checkpoints(checkpoint_dir: str) -> List[Tuple[int, str]]:
    """All step-checkpoint dirs under ``checkpoint_dir`` as (step, path),
    sorted by step ascending — committed or not."""
    if not os.path.isdir(checkpoint_dir):
        return []
    out = []
    for name in sorted(os.listdir(checkpoint_dir)):
        step = checkpoint_step(name)
        path = os.path.join(checkpoint_dir, name)
        if step is not None and os.path.isdir(path):
            out.append((step, path))
    out.sort(key=lambda sp: sp[0])
    return out


def find_latest_committed(checkpoint_dir: str) -> Optional[str]:
    """Newest committed step checkpoint, skipping torn dirs (see module doc)."""
    for step, path in reversed(list_checkpoints(checkpoint_dir)):
        if is_committed(path):
            return path
        logger.warning(
            f"Auto-resume: skipping {path} — no {COMMITTED_SENTINEL} sentinel "
            "(torn or in-flight write)"
        )
    return None


# ------------------------------------------------------------------ RNG state


def pack_rng_key(key) -> List[int]:
    """jax PRNG key -> JSON-clean list of uint32 words."""
    data = jax.random.key_data(key) if jnp_is_typed_key(key) else key
    return [int(x) for x in np.asarray(jax.device_get(data)).ravel()]


def unpack_rng_key(words: List[int], like) -> Any:
    """Inverse of :func:`pack_rng_key`, shaped/typed like the current key."""
    if jnp_is_typed_key(like):
        impl = jax.random.key_impl(like)
        return jax.random.wrap_key_data(
            np.asarray(words, np.uint32).reshape(jax.random.key_data(like).shape),
            impl=impl,
        )
    arr = np.asarray(words, dtype=np.asarray(jax.device_get(like)).dtype)
    return arr.reshape(np.asarray(jax.device_get(like)).shape)


def jnp_is_typed_key(key) -> bool:
    """True for new-style typed PRNG keys (jax.random.key), False for the
    legacy uint32[2] arrays this codebase uses (jax.random.PRNGKey)."""
    try:
        import jax.dtypes

        return jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key)
    except (AttributeError, TypeError):
        return False


def pack_np_rng(np_rng: np.random.Generator) -> Dict[str, Any]:
    """numpy Generator -> its JSON-serializable bit_generator state dict."""
    return np_rng.bit_generator.state


def restore_np_rng(np_rng: np.random.Generator, state: Dict[str, Any]) -> None:
    np_rng.bit_generator.state = state
