"""Env-driven fault injection for resilience testing.

A resilience subsystem that is only exercised by real outages is untested
code. :class:`ChaosMonkey` lets tests (and brave operators) inject the exact
faults the subsystem claims to survive — without touching application code
paths beyond a one-line ``chaos.fail_if_armed("site")`` at each seam.

Faults are armed through the ``TRLX_CHAOS`` environment variable (or
programmatically via :meth:`ChaosMonkey.configure`), as a comma-separated list
of ``site:arg`` tokens:

- ``reward:N`` — the next ``N`` reward-fn calls raise
  :class:`ChaosInjectedError` (exercises the retry wrapper);
- ``rollout-producer:N`` — the async rollout producer thread dies ``N`` times
  (exercises queue close-on-death and error propagation);
- ``hf-load:N`` — the next ``N`` HF checkpoint loads fail (exercises the
  hub-loading retry policy);
- ``checkpoint:N`` — the next ``N`` checkpoint payload writes fail *before*
  the commit rename (exercises torn-checkpoint detection: the ``.tmp`` dir is
  left behind, no ``_COMMITTED`` sentinel ever appears);
- ``preempt-step:N`` — a simulated preemption "signal" is reported once the
  trainer reaches optimizer step ``N`` (exercises the emergency-checkpoint +
  auto-resume path end-to-end, no real SIGTERM required);
- ``producer-wedge:N`` — the async rollout producer *wedges* ``N`` times: it
  stops beating the watchdog and blocks silently instead of raising
  (exercises the watchdog-escalation → supervisor-restart path — the failure
  mode of a hung reward RPC, which no exception-based site can model);
- ``nan-loss:N`` — the next ``N`` train batches are poisoned to NaN before
  the optimizer step (exercises the TrainingHealthGuard skip/rollback
  ladder);
- ``bad-element:N`` — one element in each of the next ``N`` scored rollout
  chunks gets non-finite logprobs (exercises the experience quarantine);
- ``serving-prefill:N`` — the next ``N`` serving admission waves raise before
  their prefill runs (exercises supervised restart + replay of placed
  requests);
- ``serving-decode:N`` — the next ``N`` serving decode rounds raise before
  the device step (exercises restart + replay of live sequences);
- ``serving-alloc:N`` — the next ``N`` live-sequence KV-block extensions are
  reported as allocation failures (exercises KV-pressure preemption);
- ``serving-wedge:N`` — the serving engine's step loop wedges ``N`` times: it
  stops beating the watchdog and blocks until aborted (exercises the
  watchdog-escalation / wedge-timer → supervised-restart path);
- ``broadcast-chunk:N`` — the next ``N`` chunked-broadcast layer installs
  raise mid-broadcast (exercises the torn-version guarantee: the committed
  snapshot must stay the previous version, the burned version number must
  stay monotonic, and a re-publish must recover);
- ``fleet-route:N`` — the next ``N`` fleet routing decisions deliberately
  pick the WORST-scoring replica instead of the best (exercises the
  guarantee that routing quality is performance-only: mis-routed requests
  still finish exactly once, only affinity hit rates suffer);
- ``fleet-replica-kill:N`` — the fleet router hard-kills its busiest live
  replica ``N`` times (exercises cross-replica re-route: the dead replica's
  host-side request state is adopted by a survivor and every uid still
  reaches exactly one terminal state).

Count-based sites are *budgets*: each injected fault decrements the budget, so
``reward:2`` means exactly two failures then clean behavior — which is exactly
the shape of a transient outage.

The process-global handle is ``trlx_tpu.resilience.chaos.chaos``. It reads the
env var at each :meth:`reload_from_env`; the ``Resilience`` runtime calls that
at trainer init, so subprocess-spawned trainers pick up the spec without any
plumbing. With no spec armed, every check is a dict lookup that misses —
effectively free.
"""

import os
import threading
from typing import Dict, Optional

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

ENV_VAR = "TRLX_CHAOS"

# count-budget sites; "preempt-step" is threshold-based and handled separately
_COUNT_SITES = (
    "reward",
    "rollout-producer",
    "hf-load",
    "checkpoint",
    "producer-wedge",
    "nan-loss",
    "bad-element",
    "serving-prefill",
    "serving-decode",
    "serving-alloc",
    "serving-wedge",
    "broadcast-chunk",
    "fleet-route",
    "fleet-replica-kill",
)


class ChaosInjectedError(RuntimeError):
    """A fault deliberately injected by :class:`ChaosMonkey`."""


class ChaosMonkey:
    def __init__(self, spec: Optional[str] = None):
        self._lock = threading.Lock()
        self._budgets: Dict[str, int] = {}
        self._preempt_step: Optional[int] = None
        self._preempt_fired = False
        self._injected: Dict[str, int] = {}
        if spec:
            self.configure(spec)

    def configure(self, spec: Optional[str]) -> None:
        """Arm faults from a spec string (see module docstring); ``None``/"" disarms."""
        with self._lock:
            self._budgets = {}
            self._preempt_step = None
            self._preempt_fired = False
            self._injected = {}
            if not spec:
                return
            for token in spec.split(","):
                token = token.strip()
                if not token:
                    continue
                site, _, arg = token.partition(":")
                site = site.strip()
                try:
                    count = int(arg.strip()) if arg.strip() else 1
                except ValueError:
                    raise ValueError(f"chaos spec token {token!r}: argument must be an integer")
                if site == "preempt-step":
                    self._preempt_step = count
                elif site in _COUNT_SITES:
                    self._budgets[site] = self._budgets.get(site, 0) + count
                else:
                    raise ValueError(
                        f"chaos spec token {token!r}: unknown site "
                        f"(expected one of {_COUNT_SITES + ('preempt-step',)})"
                    )
            logger.warning(f"chaos armed: budgets={self._budgets} preempt_step={self._preempt_step}")

    def reload_from_env(self) -> None:
        self.configure(os.environ.get(ENV_VAR))

    @property
    def armed(self) -> bool:
        with self._lock:
            return bool(self._budgets) or self._preempt_step is not None

    def should_fail(self, site: str) -> bool:
        """Consume one unit of ``site``'s fault budget; True if a fault fires."""
        with self._lock:
            remaining = self._budgets.get(site, 0)
            if remaining <= 0:
                return False
            self._budgets[site] = remaining - 1
            self._injected[site] = self._injected.get(site, 0) + 1
            return True

    def fail_if_armed(self, site: str, detail: str = "") -> None:
        """Raise :class:`ChaosInjectedError` if ``site`` has budget left."""
        if self.should_fail(site):
            suffix = f" ({detail})" if detail else ""
            raise ChaosInjectedError(f"chaos: injected failure at site {site!r}{suffix}")

    def preempt_due(self, step: int) -> bool:
        """True exactly once, when ``step`` first reaches the armed threshold."""
        with self._lock:
            if self._preempt_step is None or self._preempt_fired:
                return False
            if step >= self._preempt_step:
                self._preempt_fired = True
                self._injected["preempt-step"] = 1
                return True
            return False

    def stats(self) -> Dict[str, int]:
        """Faults injected so far, by site (for tests and logs)."""
        with self._lock:
            return dict(self._injected)


# Process-global handle; tests reset it via chaos.configure(None).
chaos = ChaosMonkey()
