"""``Resilience`` — the facade ``MeshRLTrainer`` drives from
``TRLConfig.train.resilience``.

One object owns the subsystem's moving parts and their lifecycle:

- the :class:`~trlx_tpu.resilience.checkpoint.AsyncCheckpointWriter`
  (``None`` when async checkpointing is off or the run is multi-host — orbax
  saves are collective there and a per-host background thread cannot order
  them safely, so we warn and fall back to the synchronous path);
- the :class:`~trlx_tpu.resilience.preemption.PreemptionHandler`, installed
  at construction (main thread) when ``preemption_handling`` is on;
- the reward-fn wrapper: chaos's ``reward`` site is checked on *every* call
  (so tests can prove an unprotected run dies), and the retry policy is
  layered outside it when ``retry_rewards`` is on — an injected fault is
  retried exactly like a real transient one;
- chaos itself: :meth:`ChaosMonkey.reload_from_env` runs at construction, so
  a subprocess-spawned trainer picks up ``TRLX_CHAOS`` with no plumbing.

A disabled config (`enabled: false`, the default) constructs a facade whose
every hook is a cheap no-op and whose reward wrapper returns the function
unchanged — the trainer code can call it unconditionally.
"""

from typing import Callable, Optional

from trlx_tpu.resilience.chaos import chaos
from trlx_tpu.resilience.checkpoint import AsyncCheckpointWriter
from trlx_tpu.resilience.preemption import PreemptionHandler
from trlx_tpu.resilience.retry import RetryPolicy, with_retries
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

#: directory names the retention policy must never delete
PROTECTED_CHECKPOINTS = ("best_checkpoint", "hf_model")


class Resilience:
    def __init__(self, config, multiprocess: bool = False):
        self.config = config
        self.enabled = bool(getattr(config, "enabled", False))
        self.writer: Optional[AsyncCheckpointWriter] = None
        self.preemption: Optional[PreemptionHandler] = None
        self.retry_policy: Optional[RetryPolicy] = None
        chaos.reload_from_env()
        if not self.enabled:
            return
        if config.async_checkpointing:
            if multiprocess:
                logger.warning(
                    "resilience.async_checkpointing is single-process only "
                    "(orbax multi-host saves are collective); falling back to "
                    "synchronous atomic saves"
                )
            else:
                self.writer = AsyncCheckpointWriter(
                    keep_last=config.keep_last, protected=list(PROTECTED_CHECKPOINTS)
                )
        if config.preemption_handling:
            self.preemption = PreemptionHandler(grace_period_s=config.grace_period_s)
            self.preemption.install()
        if config.retry_rewards:
            self.retry_policy = RetryPolicy(
                max_retries=config.retry_max_retries,
                base_delay_s=config.retry_base_delay_s,
                max_delay_s=config.retry_max_delay_s,
                deadline_s=config.retry_deadline_s,
            )

    # ------------------------------------------------------------ reward calls

    def wrap_reward_fn(self, reward_fn: Optional[Callable]) -> Optional[Callable]:
        """Chaos-instrument (always) and retry-protect (when enabled) a
        reward_fn. Covers every call path — sync PPO scoring, the overlap
        thread, the async rollout producer, and evals — because they all go
        through ``trainer.reward_fn``."""
        if reward_fn is None:
            return None

        def chaos_checked(*args, **kwargs):
            chaos.fail_if_armed("reward")
            return reward_fn(*args, **kwargs)

        chaos_checked.__name__ = getattr(reward_fn, "__name__", "reward_fn")
        chaos_checked.__wrapped__ = reward_fn
        if self.retry_policy is None:
            return chaos_checked
        return with_retries(chaos_checked, policy=self.retry_policy, name="reward_fn")

    # -------------------------------------------------------------- preemption

    def should_stop(self, step: int) -> bool:
        """Poll once per optimizer step. Converts an armed chaos
        ``preempt-step`` into a simulated preemption, then reports whether the
        trainer must emergency-checkpoint and exit."""
        if self.preemption is None:
            return False
        if chaos.preempt_due(step):
            self.preemption.simulate(f"chaos preempt-step at step {step}")
        return self.preemption.preempted

    @property
    def auto_resume(self) -> bool:
        return self.enabled and bool(self.config.auto_resume)

    # --------------------------------------------------------------- lifecycle

    def close(self) -> None:
        """Flush the writer and release the signal handlers. Idempotent."""
        if self.writer is not None:
            self.writer.close()
        if self.preemption is not None:
            self.preemption.uninstall()
