"""Retry with exponential backoff, jitter, and a wall-clock deadline.

The hot failure mode on a production RLHF run is not the jitted SPMD program —
it is the *host-side* calls around it: a served reward model's RPC flaking, an
HF checkpoint read off congested NFS, a tracker backend hiccuping. Today any
one of those kills the whole run and throws away everything since the last
checkpoint. :func:`retry_call` wraps exactly those call sites:

- exponential backoff (``base_delay_s * 2^(attempt-1)``, capped at
  ``max_delay_s``) with symmetric jitter so a fleet of preempted-and-restarted
  jobs does not hammer a recovering reward endpoint in lockstep;
- a **deadline**: total wall time across attempts is bounded, so a
  hard-down endpoint surfaces as a clear :class:`RetryDeadlineExceeded`
  instead of an unbounded stall (the watchdog would page on the stall, but a
  typed error is a diagnosis, not a symptom);
- ``giveup_on`` exceptions are never retried (a ``FileNotFoundError`` is an
  answer, not a transient fault);
- every retry increments the ``resilience/retries`` gauge so the tracker
  backends see flakiness *before* it becomes an outage.

``sleep`` / ``clock`` / ``rng`` are injectable for deterministic tests.
"""

import random as _random
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)


class RetryDeadlineExceeded(TimeoutError):
    """The retry loop ran out of wall-clock budget (``RetryPolicy.deadline_s``)."""


@dataclass
class RetryPolicy:
    """How to retry one class of flaky call.

    :param max_retries: retries *after* the first attempt (total attempts =
        ``max_retries + 1``).
    :param base_delay_s: backoff before the first retry; doubles per retry.
    :param max_delay_s: cap on any single backoff sleep.
    :param jitter: symmetric jitter fraction — each delay is scaled by a
        uniform factor in ``[1 - jitter, 1 + jitter]``.
    :param deadline_s: total wall-clock budget across all attempts (sleeps
        included); ``None`` means attempts alone bound the loop.
    :param retry_on: exception types that are retried.
    :param giveup_on: exception types never retried, even when they match
        ``retry_on`` (checked first).
    """

    max_retries: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    jitter: float = 0.5
    deadline_s: Optional[float] = None
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    giveup_on: Tuple[Type[BaseException], ...] = ()

    def delay(self, attempt: int, rng=_random) -> float:
        """Backoff before retry ``attempt`` (1-based), jittered and capped."""
        d = min(self.base_delay_s * (2.0 ** (attempt - 1)), self.max_delay_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, d)


def retry_call(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    name: Optional[str] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng=_random,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)`` under ``policy`` (see module docstring)."""
    policy = policy or RetryPolicy()
    name = name or getattr(fn, "__name__", "call")
    start = clock()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except policy.giveup_on:
            raise
        except policy.retry_on as e:
            attempt += 1
            if attempt > policy.max_retries:
                logger.error(
                    f"{name}: failed after {attempt} attempts "
                    f"({type(e).__name__}: {e}); giving up"
                )
                raise
            delay = policy.delay(attempt, rng=rng)
            elapsed = clock() - start
            if policy.deadline_s is not None and elapsed + delay > policy.deadline_s:
                gauges.inc("resilience/retry_deadline_exceeded")
                raise RetryDeadlineExceeded(
                    f"{name}: retry deadline {policy.deadline_s}s would be "
                    f"exceeded after {attempt} attempts ({elapsed:.1f}s elapsed)"
                ) from e
            gauges.inc("resilience/retries")
            logger.warning(
                f"{name}: attempt {attempt}/{policy.max_retries + 1} failed "
                f"({type(e).__name__}: {e}); retrying in {delay:.2f}s"
            )
            sleep(delay)


def with_retries(
    fn: Callable, policy: Optional[RetryPolicy] = None, name: Optional[str] = None
) -> Callable:
    """Return ``fn`` wrapped in :func:`retry_call` (keeps the signature)."""

    def wrapped(*args, **kwargs):
        return retry_call(fn, *args, policy=policy, name=name, **kwargs)

    wrapped.__name__ = getattr(fn, "__name__", "wrapped")
    wrapped.__wrapped__ = fn
    return wrapped
