"""Training health guard: skip → roll back → halt instead of training on garbage.

A NaN loss does not crash a JAX training loop — it *converges* it, to a
parameter tree of NaNs that every subsequent step happily "optimizes". The
reference framework has no defense: one non-finite gradient (a bad reward, a
poisoned rollout batch, a numerics edge at scale) silently destroys hours of
TPU time, and the failure is only discovered when eval rewards flatline.

:class:`TrainingHealthGuard` closes that hole with an escalation ladder,
wired into ``MeshRLTrainer._learn_loop`` behind ``train.self_healing``:

1. **Skip** — the compiled train step (see
   ``MeshRLTrainer.make_grad_accum_step``) checks, on device, that the mean
   loss and global gradient norm are finite and that the norm is under
   ``grad_norm_spike_factor`` x the rolling median; if not, the already-
   computed parameter/optimizer update is discarded with a ``jnp.where``
   (the buffers are donated, so the decision *must* live inside the XLA
   program — by the time stats reach the host, the old params are gone).
   The step reports ``health/update_applied`` so the host sees what happened.
2. **Roll back** — ``rollback_after`` *consecutive* anomalies (skips, or KL
   spikes vs the rolling window) mean the run is poisoned beyond one bad
   batch: the trainer restores the last committed checkpoint (exact-resume
   replay from the resilience subsystem, including the PPO prompt-stream
   position) and re-collects experience. Bounded by ``max_rollbacks``.
3. **Halt** — an exhausted rollback budget raises
   :class:`TrainingHealthError` whose message carries the path of a
   diagnostics bundle (recent gauges, anomaly history, span trace, thread
   stacks) written by :func:`write_diagnostics_bundle`. Failing closed with
   a postmortem beats retrying forever.

The guard is pure host-side bookkeeping (deques + counters); its only
device-visible effect is the scalar ``grad_norm_cap`` argument threaded into
the jitted step — passed as a traced value so threshold updates never
retrace. Chaos site ``nan-loss`` (:func:`chaos_poison_batch`) poisons real
batches to exercise the whole ladder end-to-end.
"""

import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

from trlx_tpu.data.configs import SelfHealingConfig
from trlx_tpu.obs import format_all_stacks, tracer
from trlx_tpu.resilience.chaos import chaos
from trlx_tpu.resilience.checkpoint import write_json_atomic
from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)


class TrainingHealthError(RuntimeError):
    """Raised when the health guard halts the run (budget exhausted); the
    message contains the diagnostics bundle path."""


def write_diagnostics_bundle(
    directory: str,
    kind: str,
    anomalies: Optional[List[Dict[str, Any]]] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Write a postmortem bundle and return its path.

    The bundle is a directory ``<directory>/<kind>-<unix_ts>/`` holding
    ``bundle.json`` (gauge snapshot, anomaly history, chaos stats, extras),
    ``stacks.txt`` (every Python thread's stack — a wedged producer shows up
    here), and ``trace.json`` (Chrome span trace, when tracing is active).
    Best-effort: a failure to write diagnostics must never mask the failure
    being diagnosed, so errors degrade to a log line.
    """
    bundle_dir = os.path.join(directory, f"{kind}-{int(time.time() * 1000)}")
    try:
        os.makedirs(bundle_dir, exist_ok=True)
        payload = {
            "kind": kind,
            "written_at": time.time(),
            "gauges": gauges.snapshot(),
            "chaos_injected": chaos.stats(),
            "anomalies": list(anomalies or []),
        }
        if extra:
            payload.update(extra)
        write_json_atomic(os.path.join(bundle_dir, "bundle.json"), payload)
        with open(os.path.join(bundle_dir, "stacks.txt"), "w") as f:
            f.write(format_all_stacks())
        try:
            tracer.write_trace(os.path.join(bundle_dir, "trace.json"))
        except Exception:
            pass  # tracing disabled or empty — the JSON + stacks still land
        logger.warning(f"diagnostics bundle written: {bundle_dir}")
    except OSError as e:
        logger.error(f"failed to write diagnostics bundle at {bundle_dir}: {e}")
    return bundle_dir


def chaos_poison_batch(batch):
    """Chaos site ``nan-loss``: multiply every floating leaf of ``batch`` by
    NaN so the next loss/gradient is non-finite — the exact signature of a
    numerics blowup, injected at the last host-side seam before the compiled
    step. Free when unarmed (one dict lookup)."""
    if not chaos.should_fail("nan-loss"):
        return batch
    import jax
    import numpy as np

    logger.warning("chaos: poisoning train batch to NaN at site 'nan-loss'")

    def poison(x):
        arr = np.asarray(x)
        if np.issubdtype(arr.dtype, np.floating):
            return arr * arr.dtype.type(np.nan)
        return x

    return jax.tree.map(poison, batch)


class TrainingHealthGuard:
    """Escalation-ladder bookkeeping for :class:`MeshRLTrainer` (module docs).

    Single-threaded by design: every method is called from the learner
    thread, between steps. The guard never touches device memory.
    """

    def __init__(self, config: SelfHealingConfig, diagnostics_dir: str):
        self.config = config
        self.diagnostics_dir = diagnostics_dir
        self._grad_norms: deque = deque(maxlen=max(1, config.anomaly_window))
        self._kls: deque = deque(maxlen=max(1, config.anomaly_window))
        self.anomalies: List[Dict[str, Any]] = []
        self.consecutive_anomalies = 0
        self.skipped_updates = 0
        self.rollbacks = 0

    # ------------------------------------------------------------- thresholds

    #: Below this, the window median is not a usable baseline and the cap
    #: stays disarmed. A warm-started policy sits at its KL reference
    #: (sqrt_kl ~ 0), so a ratio spike test against that median would flag
    #: every healthy step once the policy starts moving; likewise a ~zero
    #: grad-norm median means the run is converged or frozen, and "10x of
    #: nothing" is still nothing. Non-finite values are caught by the
    #: device-side isfinite check regardless of the cap.
    _MIN_BASELINE = 1e-6

    @staticmethod
    def _median(window: deque) -> float:
        ordered = sorted(window)
        return float(ordered[len(ordered) // 2])

    def _cap(self, window: deque, factor: float) -> float:
        if len(window) < max(1, self.config.min_window):
            return float("inf")
        median = self._median(window)
        if median <= self._MIN_BASELINE:
            return float("inf")
        return factor * median

    def grad_norm_cap(self) -> float:
        """Device-enforced grad-norm ceiling for the *next* step: inf until
        the rolling window holds ``min_window`` healthy samples with a
        meaningfully nonzero median, then ``grad_norm_spike_factor`` x the
        window median."""
        return self._cap(self._grad_norms, self.config.grad_norm_spike_factor)

    def _kl_cap(self) -> float:
        return self._cap(self._kls, self.config.kl_spike_factor)

    # -------------------------------------------------------------- the ladder

    def observe(self, stats: Dict[str, Any], step: int) -> str:
        """Classify one completed step: ``"ok"``, ``"anomaly"`` (the on-device
        guard already skipped the update, or a host-visible KL spike), or
        ``"rollback"`` (``rollback_after`` consecutive anomalies — the caller
        decides restore-vs-halt against the budget)."""
        reasons = []
        applied = stats.get("health/update_applied")
        if applied is not None and float(applied) < 0.5:
            reasons.append("update skipped on device (non-finite loss/grads or grad-norm spike)")
        kl = stats.get("policy/sqrt_kl")
        kl_cap = self._kl_cap()
        if kl is not None and float(kl) > kl_cap:
            reasons.append(f"KL spike: sqrt_kl {float(kl):.4g} > {kl_cap:.4g}")

        if not reasons:
            # only healthy samples feed the baselines — an accepted spike
            # would inflate the median and blind the detector to the next one
            gn = stats.get("health/grad_norm")
            if gn is not None and float(gn) == float(gn):  # finite-ish (not NaN)
                self._grad_norms.append(float(gn))
            if kl is not None and float(kl) == float(kl):
                self._kls.append(float(kl))
            self.consecutive_anomalies = 0
            return "ok"

        self.consecutive_anomalies += 1
        if applied is not None and float(applied) < 0.5:
            self.skipped_updates += 1
            gauges.set("resilience/skipped_updates", float(self.skipped_updates))
        self.anomalies.append(
            {
                "step": step,
                "reasons": reasons,
                "grad_norm": _maybe_float(stats.get("health/grad_norm")),
                "loss": _maybe_float(stats.get("loss")),
                "sqrt_kl": _maybe_float(kl),
                "consecutive": self.consecutive_anomalies,
            }
        )
        del self.anomalies[:-256]  # bounded history; newest kept for the bundle
        gauges.set("resilience/anomalies", float(len(self.anomalies)))
        logger.warning(
            f"health anomaly at step {step} "
            f"({self.consecutive_anomalies} consecutive): {'; '.join(reasons)}"
        )
        if self.consecutive_anomalies >= max(1, self.config.rollback_after):
            return "rollback"
        return "anomaly"

    def rollback_budget_left(self) -> bool:
        return self.rollbacks < self.config.max_rollbacks

    def on_rollback(self, step: int, restored: bool):
        """Account one consumed rollback (whether or not a checkpoint existed
        to restore — a budget that only counts successes never exhausts)."""
        self.rollbacks += 1
        self.consecutive_anomalies = 0
        gauges.set("resilience/rollbacks", float(self.rollbacks))
        logger.warning(
            f"health rollback #{self.rollbacks}/{self.config.max_rollbacks} at step {step} "
            f"({'restored last committed checkpoint' if restored else 'no committed checkpoint to restore'})"
        )

    def halt(self, step: int, reason: str) -> None:
        """Fail closed: write the diagnostics bundle and raise with its path."""
        bundle = write_diagnostics_bundle(
            self.diagnostics_dir,
            kind="health-halt",
            anomalies=self.anomalies,
            extra={"halt_step": step, "halt_reason": reason, "rollbacks": self.rollbacks},
        )
        raise TrainingHealthError(
            f"training halted at step {step}: {reason}; diagnostics bundle: {bundle}"
        )

    def report(self) -> Dict[str, Any]:
        """End-of-run self-healing summary (also mirrored in gauges)."""
        return {
            "producer_restarts": int(gauges.get("resilience/restarts") or 0),
            "skipped_updates": self.skipped_updates,
            "rollbacks": self.rollbacks,
            "anomalies": len(self.anomalies),
            "quarantined": int(gauges.get("resilience/quarantined") or 0),
        }


def _maybe_float(x) -> Optional[float]:
    try:
        return float(x)
    except (TypeError, ValueError):
        return None
