"""Prompt and offline (ILQL/SFT) pipelines.

Parity: `/root/reference/trlx/pipeline/offline_pipeline.py` — ``PromptPipeline``
(:118-188, incl. per-prompt metadata dicts forwarded to reward_fn),
``tokenize_dialogue`` (:38-87, truncation-side aware interleaved dialogue
tokenization), ``DialogStore`` (:90-115), ``ILQLRolloutStorage`` (:202-237) and the
seq2seq variant (:252-289). Collation is numpy; trainers place batches on the mesh.
"""

from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Tuple, Union

import numpy as np

from trlx_tpu.data.ilql_types import ILQLBatch, ILQLElement, ILQLSeq2SeqBatch, ILQLSeq2SeqElement
from trlx_tpu.pipeline import (
    BasePipeline,
    BaseRolloutStore,
    NumpyLoader,
    register_datapipeline,
)


@dataclass
class DialogMessage:
    """One dialogue phrase: output (model) or prompt (user) tokens."""

    is_output: bool
    tokens: Tuple[int, ...]


def tokenize_dialogue(dialogue, tokenizer, max_length: int = 2048) -> List[DialogMessage]:
    """Tokenize an interleaved (prompt_1, output_1, prompt_2, ...) dialogue with
    truncation-side handling (semantics match reference offline_pipeline.py:38-87)."""
    if isinstance(dialogue, str):
        bos_token = getattr(tokenizer, "bos_token", None) or tokenizer.eos_token
        dialogue = [bos_token, dialogue]
    else:
        dialogue = list(dialogue)
        if len(dialogue) % 2 != 0:
            raise ValueError("Dialogue must have an even number of phrases, alternating prompt and output")

    if not dialogue[-1].endswith(tokenizer.eos_token):
        dialogue[-1] = dialogue[-1] + tokenizer.eos_token

    tokenized = [
        DialogMessage(is_output=i % 2 == 1, tokens=tuple(tokenizer(dialogue[i], add_special_tokens=False).input_ids))
        for i in range(len(dialogue))
    ]

    # flip so truncation always removes from the far end of the chosen side
    if tokenizer.truncation_side == "left":
        tokenized = [DialogMessage(m.is_output, m.tokens[::-1]) for m in tokenized[::-1]]

    lengths = [len(t.tokens) for t in tokenized]
    cumsum_lengths = [sum(lengths[:i]) for i in range(len(lengths))]
    truncated = [
        DialogMessage(t.is_output, t.tokens[: max(max_length - cl, 0)])
        for t, cl in zip(tokenized, cumsum_lengths)
    ]

    if tokenizer.truncation_side == "left":
        truncated = [DialogMessage(m.is_output, m.tokens[::-1]) for m in truncated[::-1]]

    out = [t for t in truncated if len(t.tokens) > 0]

    if out and out[0].is_output:
        # leading prompt was fully truncated: re-insert a bos, trimming one token
        # if the dialogue already saturates max_length
        if sum(len(m.tokens) for m in out) == max_length:
            if tokenizer.truncation_side == "left":
                out[0] = DialogMessage(out[0].is_output, out[0].tokens[1:])
            else:
                out[-1] = DialogMessage(out[-1].is_output, out[-1].tokens[:-1])
        bos = getattr(tokenizer, "bos_token_id", None)
        if bos is None:
            bos = tokenizer.eos_token_id
        out.insert(0, DialogMessage(False, (bos,)))
    return out


@register_datapipeline
class PromptPipeline(BasePipeline):
    """Tokenizes and stores prompts; prompts may be dicts carrying extra metadata keys
    which are forwarded to reward/metric functions (parity :118-188)."""

    def __init__(self, prompts: List[Union[str, Dict[str, Any]]], max_prompt_length: int,
                 tokenizer, add_special_tokens: bool = False):
        super().__init__()
        self.tokenizer = tokenizer

        if prompts and isinstance(prompts[0], dict):
            metadata = [dict(p) for p in prompts]
            prompts = [m.pop("prompt") for m in metadata]
        else:
            metadata = [{}] * len(prompts)

        self.prompts = []
        for prompt, meta in zip(prompts, metadata):
            ids = tokenizer(prompt, add_special_tokens=add_special_tokens).input_ids
            if tokenizer.truncation_side == "left":
                ids = ids[-max_prompt_length:]
            else:
                ids = ids[:max_prompt_length]
            self.prompts.append({"input_ids": ids, **meta})

    def __getitem__(self, ix: int):
        return self.prompts[ix]

    def __len__(self) -> int:
        return len(self.prompts)

    def create_loader(self, batch_size: int, shuffle: bool = False, drop_last: bool = False,
                      seed: int = 0) -> NumpyLoader:
        def collate(xs: List[dict]) -> Dict[str, Any]:
            out: Dict[str, Any] = {
                "input_ids": [np.asarray(x["input_ids"], np.int32) for x in xs]
            }
            for key in xs[0]:
                if key != "input_ids":
                    out[key] = [x[key] for x in xs]
            return out

        return NumpyLoader(self, batch_size, collate, shuffle=shuffle, drop_last=drop_last, seed=seed)


class DialogStore(BaseRolloutStore):
    """SFT store of tokenized dialogues with -100-masked prompt labels (parity :90-115)."""

    IGNORE_INDEX = -100

    def __init__(self, dialogs: List[List[DialogMessage]], tokenizer):
        super().__init__()
        self.tokenizer = tokenizer
        self.history = []
        for d in dialogs:
            ids = [t for m in d for t in m.tokens]
            labels = [t if m.is_output else self.IGNORE_INDEX for m in d for t in m.tokens]
            self.history.append(
                dict(
                    input_ids=np.asarray(ids, np.int32),
                    attention_mask=np.ones(len(ids), np.int32),
                    labels=np.asarray(labels, np.int32),
                )
            )

    def __getitem__(self, ix: int):
        return self.history[ix]

    def create_loader(self, batch_size: int, shuffle: bool = False, seed: int = 0) -> NumpyLoader:
        pad = self.tokenizer.pad_token_id

        def collate(xs):
            return dict(
                input_ids=_rpad_stack([x["input_ids"] for x in xs], pad),
                attention_mask=_rpad_stack([x["attention_mask"] for x in xs], 0),
                labels=_rpad_stack([x["labels"] for x in xs], self.IGNORE_INDEX),
            )

        return NumpyLoader(self.history, batch_size, collate, shuffle=shuffle, seed=seed)


def _rpad_stack(rows: List[np.ndarray], value) -> np.ndarray:
    T = max(len(r) for r in rows)
    out = np.full((len(rows), T), value, dtype=np.asarray(rows[0]).dtype)
    for i, r in enumerate(rows):
        out[i, : len(r)] = r
    return out


def ilql_collate_fn(elems: Iterable[ILQLElement]) -> ILQLBatch:
    elems = list(elems)
    return ILQLBatch(
        _rpad_stack([x.input_ids for x in elems], 0),
        _rpad_stack([x.attention_mask for x in elems], 0),
        _rpad_stack([x.rewards for x in elems], 0.0),
        _rpad_stack([x.states_ixs for x in elems], 0),
        _rpad_stack([x.actions_ixs for x in elems], 0),
        _rpad_stack([x.dones for x in elems], 0),
    )


class ILQLRolloutStorage(BaseRolloutStore):
    """Offline ILQL storage (parity :202-237)."""

    def __init__(self, input_ids, attention_mask, rewards, states_ixs, actions_ixs, dones):
        super().__init__()
        self.input_ids = input_ids
        self.attention_mask = attention_mask
        self.rewards = rewards
        self.states_ixs = states_ixs
        self.actions_ixs = actions_ixs
        self.dones = dones

    def __getitem__(self, ix: int) -> ILQLElement:
        return ILQLElement(
            self.input_ids[ix], self.attention_mask[ix], self.rewards[ix],
            self.states_ixs[ix], self.actions_ixs[ix], self.dones[ix],
        )

    def __len__(self) -> int:
        return len(self.input_ids)

    def create_loader(self, batch_size: int, shuffle: bool = True, drop_last: bool = True,
                      seed: int = 0) -> NumpyLoader:
        return NumpyLoader(self, batch_size, ilql_collate_fn, shuffle=shuffle, drop_last=drop_last, seed=seed)


def ilql_seq2seq_collate_fn(elems) -> ILQLSeq2SeqBatch:
    elems = list(elems)
    return ILQLSeq2SeqBatch(
        _rpad_stack([x.input_ids for x in elems], 0),
        _rpad_stack([x.attention_mask for x in elems], 0),
        _rpad_stack([x.decoder_input_ids for x in elems], 0),
        _rpad_stack([x.rewards for x in elems], 0.0),
        _rpad_stack([x.states_ixs for x in elems], 0),
        _rpad_stack([x.actions_ixs for x in elems], 0),
        _rpad_stack([x.dones for x in elems], 0),
    )


class ILQLSeq2SeqRolloutStorage(BaseRolloutStore):
    """Seq2seq ILQL storage (parity :252-289)."""

    def __init__(self, input_ids, attention_mask, decoder_input_ids, rewards, states_ixs, actions_ixs, dones):
        super().__init__()
        self.input_ids = input_ids
        self.attention_mask = attention_mask
        self.decoder_input_ids = decoder_input_ids
        self.rewards = rewards
        self.states_ixs = states_ixs
        self.actions_ixs = actions_ixs
        self.dones = dones

    def __getitem__(self, ix: int) -> ILQLSeq2SeqElement:
        return ILQLSeq2SeqElement(
            self.input_ids[ix], self.attention_mask[ix], self.decoder_input_ids[ix],
            self.rewards[ix], self.states_ixs[ix], self.actions_ixs[ix], self.dones[ix],
        )

    def __len__(self) -> int:
        return len(self.input_ids)

    def create_loader(self, batch_size: int, shuffle: bool = True, drop_last: bool = True,
                      seed: int = 0) -> NumpyLoader:
        return NumpyLoader(self, batch_size, ilql_seq2seq_collate_fn, shuffle=shuffle, drop_last=drop_last, seed=seed)
