"""Tokenizer layer: a uniform duck-type over HF tokenizers plus builtin offline
tokenizers (the zero-egress sandbox has no HF vocab files; the reference's CI-grade
benchmark task `examples/randomwalks` likewise builds its own toy vocab —
`/root/reference/examples/randomwalks/randomwalks.py:29`).

``tokenizer_path`` resolution:
- ``"char://<alphabet>"``  → :class:`CharTokenizer` over the given alphabet
- ``"bytes"``              → :class:`ByteTokenizer` (vocab 256 + specials)
- ``"bpe://<file>"``       → :class:`trlx_tpu.pipeline.bpe.BPETokenizer` (saved
  from-scratch byte-level BPE trained on a task corpus)
- anything else            → ``transformers.AutoTokenizer`` (local files / cache)
"""

from typing import Iterable, List, Union

from trlx_tpu.data.configs import TokenizerConfig
from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


class CharTokenizer:
    """Character-level tokenizer with pad/bos/eos specials. Interface mirrors the
    subset of the HF tokenizer API the trainers use."""

    def __init__(self, alphabet: str, padding_side="left", truncation_side="right"):
        self.alphabet = alphabet
        self.pad_token_id = 0
        self.bos_token_id = 1
        self.eos_token_id = 2
        self._offset = 3
        self._char_to_id = {ch: i + self._offset for i, ch in enumerate(alphabet)}
        self._id_to_char = {i + self._offset: ch for i, ch in enumerate(alphabet)}
        self.pad_token = "<pad>"
        self.bos_token = "<bos>"
        self.eos_token = "<eos>"
        self.padding_side = padding_side
        self.truncation_side = truncation_side
        self.vocab_size = self._offset + len(alphabet)
        self.name_or_path = f"char://{alphabet}"

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        ids = []
        rest = text
        # greedy-match specials so decode(encode(x)) roundtrips
        while rest:
            matched = False
            for tok, tid in (
                (self.pad_token, self.pad_token_id),
                (self.bos_token, self.bos_token_id),
                (self.eos_token, self.eos_token_id),
            ):
                if rest.startswith(tok):
                    ids.append(tid)
                    rest = rest[len(tok):]
                    matched = True
                    break
            if matched:
                continue
            ch = rest[0]
            if ch in self._char_to_id:
                ids.append(self._char_to_id[ch])
            rest = rest[1:]
        return ids

    def __call__(self, text: Union[str, List[str]], add_special_tokens: bool = False, **_):
        if isinstance(text, str):
            return _Enc(self.encode(text, add_special_tokens))
        return _BatchEnc([self.encode(t, add_special_tokens) for t in text])

    def decode(self, ids: Iterable[int], skip_special_tokens: bool = True) -> str:
        out = []
        for i in map(int, ids):
            if i >= self._offset:
                # ids beyond the alphabet (a model with a larger vocab than
                # this tokenizer) are dropped rather than crashing the decode
                ch = self._id_to_char.get(i)
                if ch is not None:
                    out.append(ch)
            elif not skip_special_tokens:
                out.append({0: self.pad_token, 1: self.bos_token, 2: self.eos_token}[i])
        return "".join(out)

    def batch_decode(self, batch, skip_special_tokens: bool = True) -> List[str]:
        return [self.decode(ids, skip_special_tokens) for ids in batch]


class ByteTokenizer(CharTokenizer):
    """UTF-8 byte-level tokenizer (vocab = 3 specials + 256 bytes)."""

    def __init__(self, padding_side="left", truncation_side="right"):
        super().__init__("", padding_side, truncation_side)
        self.vocab_size = self._offset + 256
        self.name_or_path = "bytes"

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        return [b + self._offset for b in text.encode("utf-8")]

    def decode(self, ids: Iterable[int], skip_special_tokens: bool = True) -> str:
        specials = {0: self.pad_token, 1: self.bos_token, 2: self.eos_token}
        out = []
        byte_run: list = []

        def flush():
            if byte_run:
                out.append(bytes(byte_run).decode("utf-8", errors="ignore"))
                byte_run.clear()

        for i in map(int, ids):
            if self._offset <= i < self._offset + 256:
                byte_run.append(i - self._offset)
            elif i < self._offset:
                flush()
                if not skip_special_tokens:
                    out.append(specials[i])
            # ids beyond the byte range (e.g. a model with a larger vocab than
            # this tokenizer) are dropped rather than crashing the decode
        flush()
        return "".join(out)


class _Enc:
    def __init__(self, input_ids):
        self.input_ids = input_ids


class _BatchEnc:
    def __init__(self, input_ids):
        self.input_ids = input_ids


def load_tokenizer(config: TokenizerConfig):
    """Resolve a tokenizer from a :class:`TokenizerConfig`."""
    path = config.tokenizer_path
    if path.startswith("char://"):
        tok = CharTokenizer(path[len("char://"):], config.padding_side, config.truncation_side)
        return tok
    if path == "bytes":
        return ByteTokenizer(config.padding_side, config.truncation_side)
    if path.startswith("bpe://"):
        from trlx_tpu.pipeline.bpe import BPETokenizer

        return BPETokenizer.load(
            path[len("bpe://"):], config.padding_side, config.truncation_side
        )
    import transformers

    tok = transformers.AutoTokenizer.from_pretrained(path, **config.tokenizer_extra_kwargs)
    tok.padding_side = config.padding_side
    tok.truncation_side = config.truncation_side
    if tok.pad_token is None:
        # parity: reference sets pad = eos ("<|endoftext|>") in its trainers
        tok.pad_token = tok.eos_token
    return tok
