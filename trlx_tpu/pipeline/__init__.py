"""Pipeline layer: prompt/rollout dataset abstractions, a torch-free loader, the
gradient-accumulation minibatch slicer, and the pipeline registry.

Parity: `/root/reference/trlx/pipeline/__init__.py:14-177` (``BasePipeline``,
``BaseRolloutStore``, ``register_datapipeline``, ``MiniBatchIterator``). The torch
``DataLoader`` is replaced by :class:`NumpyLoader` — rollout data lives in host numpy
and is placed onto the device mesh by the trainer (``parallel.mesh.put_batch``), so no
framework tensor layer is needed in between.
"""

import random
from abc import abstractmethod
from dataclasses import is_dataclass
from typing import Any, Callable, Dict, Iterable, List

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

from trlx_tpu.utils.registry import make_registry

# name (lowercased) -> pipeline class
_DATAPIPELINES: Dict[str, type] = {}

#: Decorator registering a pipeline class by (lowercased) name.
register_datapipeline = make_registry(_DATAPIPELINES)


class NumpyLoader:
    """Minimal re-iterable loader: dataset (sequence) → collated batches.

    ``drop_last`` mirrors the reference's distributed drop_last; under the
    single-controller SPMD runtime uneven final batches are simply dropped when
    requested by trainers that need static shapes.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Callable[[List[Any]], Any],
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._epoch = 0
        self.seed = seed

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        idxs = list(range(len(self.dataset)))
        if self.shuffle:
            rng = random.Random(self.seed + self._epoch)
            rng.shuffle(idxs)
        self._epoch += 1
        for start in range(0, len(idxs), self.batch_size):
            chunk = idxs[start : start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield self.collate_fn([self.dataset[i] for i in chunk])


class BasePipeline:
    """Abstract prompt dataset (parity: pipeline/__init__.py:41-70)."""

    def __init__(self, path: str = "dataset"):
        self.path = path

    @abstractmethod
    def __getitem__(self, index: int):
        ...

    @abstractmethod
    def __len__(self) -> int:
        ...

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False) -> NumpyLoader:
        ...


class BaseRolloutStore:
    """Abstract rollout/experience store (parity: pipeline/__init__.py:73-102)."""

    def __init__(self, capacity: int = -1):
        self.history: Iterable[Any] = None
        self.capacity = capacity

    @abstractmethod
    def push(self, exps: Iterable[Any]):
        ...

    @abstractmethod
    def __getitem__(self, index: int):
        ...

    def __len__(self) -> int:
        return len(self.history)

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False) -> NumpyLoader:
        ...


class MiniBatchIterator:
    """Slice loader batches into gradient-accumulation microbatches
    (parity: pipeline/__init__.py:105-177 incl. the warning semantics)."""

    def __init__(self, data_loader, mb_size: int, num_mb: int):
        self.data_loader = data_loader
        self.data_loader_iter = iter(data_loader)
        self.mb_size = mb_size
        self.num_mb = num_mb

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self.data_loader_iter)
        if batch is None:
            logger.warning("Not enough samples to saturate the minibatch size.")
            raise StopIteration

        minibatches = []
        for mbi in range(self.num_mb):
            batch_dict = batch.__dict__ if is_dataclass(batch) else dict(batch)
            sliced_data = {}
            empty = False
            for key, value in batch_dict.items():
                sliced = value[mbi * self.mb_size : (mbi + 1) * self.mb_size]
                if self.num_mb > 1 and len(sliced) == 0:
                    logger.warning("MiniBatchIterator generated an empty minibatch.")
                    empty = True
                    break
                if self.num_mb > 1 and len(sliced) < self.mb_size:
                    logger.warning("MiniBatchIterator generated a minibatch smaller than mb_size.")
                sliced_data[key] = sliced
            if empty or not sliced_data:
                break
            if is_dataclass(batch):
                minibatches.append(batch.__class__(**sliced_data))
            else:
                minibatches.append(sliced_data)

        if not minibatches:
            raise StopIteration
        return minibatches


from trlx_tpu.pipeline.offline_pipeline import (  # noqa: E402,F401
    DialogMessage,
    DialogStore,
    ILQLRolloutStorage,
    ILQLSeq2SeqRolloutStorage,
    PromptPipeline,
    tokenize_dialogue,
)
from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage  # noqa: E402,F401
