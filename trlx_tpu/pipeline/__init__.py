"""Pipeline layer: prompt/rollout dataset abstractions, a torch-free loader, and
the pipeline registry.

Parity: `/root/reference/trlx/pipeline/__init__.py:14-177` (``BasePipeline``,
``BaseRolloutStore``, ``register_datapipeline``). The reference's host-side
``MiniBatchIterator`` has no counterpart here by design: gradient-accumulation
microbatching happens inside the jitted train step as a ``lax.scan``
(``MeshRLTrainer.make_grad_accum_step``), which keeps the full batch on device
and the microbatch loop compiled. The torch
``DataLoader`` is replaced by :class:`NumpyLoader` — rollout data lives in host numpy
and is placed onto the device mesh by the trainer (``parallel.mesh.put_batch``), so no
framework tensor layer is needed in between.
"""

import random
from abc import abstractmethod
from typing import Any, Callable, Dict, Iterable, List

from trlx_tpu.utils.registry import make_registry

# name (lowercased) -> pipeline class
_DATAPIPELINES: Dict[str, type] = {}

#: Decorator registering a pipeline class by (lowercased) name.
register_datapipeline = make_registry(_DATAPIPELINES)


class NumpyLoader:
    """Minimal re-iterable loader: dataset (sequence) → collated batches.

    ``drop_last`` mirrors the reference's distributed drop_last; under the
    single-controller SPMD runtime uneven final batches are simply dropped when
    requested by trainers that need static shapes.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Callable[[List[Any]], Any],
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._epoch = 0
        self.seed = seed

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        idxs = list(range(len(self.dataset)))
        if self.shuffle:
            rng = random.Random(self.seed + self._epoch)
            rng.shuffle(idxs)
        self._epoch += 1
        for start in range(0, len(idxs), self.batch_size):
            chunk = idxs[start : start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield self.collate_fn([self.dataset[i] for i in chunk])


class BasePipeline:
    """Abstract prompt dataset (parity: pipeline/__init__.py:41-70)."""

    def __init__(self, path: str = "dataset"):
        self.path = path

    @abstractmethod
    def __getitem__(self, index: int):
        ...

    @abstractmethod
    def __len__(self) -> int:
        ...

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False) -> NumpyLoader:
        ...


class BaseRolloutStore:
    """Abstract rollout/experience store (parity: pipeline/__init__.py:73-102)."""

    def __init__(self, capacity: int = -1):
        self.history: Iterable[Any] = None
        self.capacity = capacity

    @abstractmethod
    def push(self, exps: Iterable[Any]):
        ...

    @abstractmethod
    def __getitem__(self, index: int):
        ...

    def __len__(self) -> int:
        return len(self.history)

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False) -> NumpyLoader:
        ...


from trlx_tpu.pipeline.offline_pipeline import (  # noqa: E402,F401
    DialogMessage,
    DialogStore,
    ILQLRolloutStorage,
    ILQLSeq2SeqRolloutStorage,
    PromptPipeline,
    tokenize_dialogue,
)
from trlx_tpu.pipeline.ppo_pipeline import PPORolloutStorage  # noqa: E402,F401
