"""From-scratch byte-level BPE: trainable tokenizer for the zero-egress sandbox.

The reference's recipes ride HF's pretrained BPE vocabularies (gpt2 / gpt-j
tokenizers); with zero egress those vocab files don't exist here, and the
char/byte fallbacks the examples used instead change the task's fidelity —
VERDICT r4 flagged the hh chain's char-level policy as its weakest link. This
module closes that gap the way GPT-2's own tokenizer was built: byte-level BPE
(Sennrich-style merges over UTF-8 bytes, words pre-split on whitespace with
the leading-space convention) TRAINED on the task corpus, saved as JSON, and
loaded via the ``bpe://<path>`` tokenizer scheme
(:func:`trlx_tpu.pipeline.tokenization.load_tokenizer`).

Id layout matches the other local tokenizers: 0/1/2 = pad/bos/eos, 3..258 the
256 byte symbols, 259+ the learned merges — so any saved model keeps decoding
even under a tokenizer with fewer merges.
"""

import json
import os
from collections import Counter
from typing import Dict, Iterable, List, Sequence, Tuple, Union

_OFFSET = 3  # pad/bos/eos
_NUM_BYTES = 256


def _pre_split(text: str) -> List[str]:
    """GPT-2-style pre-tokenization, simplified: words keep their leading
    space so merges never cross word boundaries."""
    words: List[str] = []
    cur = ""
    for ch in text:
        if ch == " " and cur:
            words.append(cur)
            cur = " "
        else:
            cur += ch
    if cur:
        words.append(cur)
    return words


def train_bpe(texts: Sequence[str], vocab_size: int = 1024) -> List[Tuple[int, int]]:
    """Learn BPE merges over the corpus; returns the ordered merge list.

    Standard word-frequency training: each distinct word is a byte-symbol
    sequence weighted by its corpus count; every round merges the most
    frequent adjacent pair into a new symbol until ``vocab_size`` is reached.
    """
    n_merges = max(0, vocab_size - _OFFSET - _NUM_BYTES)
    word_freq = Counter()
    for t in texts:
        word_freq.update(_pre_split(t))
    # each word as a tuple of symbol ids (bytes offset to final id space)
    words: List[List[int]] = []
    freqs: List[int] = []
    for w, f in word_freq.items():
        words.append([b + _OFFSET for b in w.encode("utf-8")])
        freqs.append(f)

    merges: List[Tuple[int, int]] = []
    next_id = _OFFSET + _NUM_BYTES
    for _ in range(n_merges):
        pair_counts: Counter = Counter()
        for seq, f in zip(words, freqs):
            for a, b in zip(seq, seq[1:]):
                pair_counts[(a, b)] += f
        if not pair_counts:
            break
        (a, b), count = pair_counts.most_common(1)[0]
        if count < 2:
            break
        merges.append((a, b))
        for i, seq in enumerate(words):
            if len(seq) < 2:
                continue
            out = []
            j = 0
            while j < len(seq):
                if j + 1 < len(seq) and seq[j] == a and seq[j + 1] == b:
                    out.append(next_id)
                    j += 2
                else:
                    out.append(seq[j])
                    j += 1
            words[i] = out
        next_id += 1
    return merges


class BPETokenizer:
    """Byte-level BPE with the local-tokenizer interface the trainers use."""

    def __init__(self, merges: Sequence[Tuple[int, int]],
                 padding_side: str = "left", truncation_side: str = "right",
                 name: str = "bpe"):
        self.pad_token_id, self.bos_token_id, self.eos_token_id = 0, 1, 2
        self.pad_token, self.bos_token, self.eos_token = "<pad>", "<bos>", "<eos>"
        self.padding_side = padding_side
        self.truncation_side = truncation_side
        self.merges = [tuple(m) for m in merges]
        self.ranks: Dict[Tuple[int, int], int] = {m: r for r, m in enumerate(self.merges)}
        self.merged_id: Dict[Tuple[int, int], int] = {
            m: _OFFSET + _NUM_BYTES + r for r, m in enumerate(self.merges)
        }
        # token id -> byte string, for decode
        self._bytes: Dict[int, bytes] = {_OFFSET + i: bytes([i]) for i in range(_NUM_BYTES)}
        for (a, b), tid in self.merged_id.items():
            self._bytes[tid] = self._bytes[a] + self._bytes[b]
        self.vocab_size = _OFFSET + _NUM_BYTES + len(self.merges)
        self.name_or_path = name
        self._word_cache: Dict[str, List[int]] = {}

    # ------------------------------------------------------------- encoding
    def _encode_word(self, word: str) -> List[int]:
        cached = self._word_cache.get(word)
        if cached is not None:
            return cached
        seq = [b + _OFFSET for b in word.encode("utf-8")]
        while len(seq) > 1:
            best_rank, best_i = None, -1
            for i, pair in enumerate(zip(seq, seq[1:])):
                r = self.ranks.get(pair)
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_i = r, i
            if best_rank is None:
                break
            pair = (seq[best_i], seq[best_i + 1])
            seq = seq[:best_i] + [self.merged_id[pair]] + seq[best_i + 2:]
        if len(self._word_cache) < 65536:
            self._word_cache[word] = seq
        return seq

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        ids: List[int] = []
        for w in _pre_split(text):
            ids.extend(self._encode_word(w))
        return ids

    def __call__(self, text: Union[str, List[str]], add_special_tokens: bool = False, **_):
        from trlx_tpu.pipeline.tokenization import _BatchEnc, _Enc

        if isinstance(text, str):
            return _Enc(self.encode(text, add_special_tokens))
        return _BatchEnc([self.encode(t, add_special_tokens) for t in text])

    # ------------------------------------------------------------- decoding
    def decode(self, ids: Iterable[int], skip_special_tokens: bool = True) -> str:
        specials = {0: self.pad_token, 1: self.bos_token, 2: self.eos_token}
        out: List[str] = []
        run = b""
        for i in map(int, ids):
            bs = self._bytes.get(i)
            if bs is not None:
                run += bs
            elif i < _OFFSET:
                if run:
                    out.append(run.decode("utf-8", errors="ignore"))
                    run = b""
                if not skip_special_tokens:
                    out.append(specials[i])
            # unknown ids (model vocab larger than tokenizer) are dropped
        if run:
            out.append(run.decode("utf-8", errors="ignore"))
        return "".join(out)

    def batch_decode(self, batch, skip_special_tokens: bool = True) -> List[str]:
        return [self.decode(ids, skip_special_tokens) for ids in batch]

    # ----------------------------------------------------------- persistence
    def save(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump({"merges": self.merges, "vocab_size": self.vocab_size}, f)
        return path

    @classmethod
    def load(cls, path: str, padding_side: str = "left", truncation_side: str = "right"):
        with open(path) as f:
            data = json.load(f)
        return cls(data["merges"], padding_side, truncation_side, name=f"bpe://{path}")


def train_and_save(texts: Sequence[str], vocab_size: int, path: str) -> BPETokenizer:
    tok = BPETokenizer(train_bpe(texts, vocab_size))
    tok.save(path)
    return BPETokenizer.load(path)
