"""PPO rollout storage (parity: `/root/reference/trlx/pipeline/ppo_pipeline.py:14-104`):
replay buffer of :class:`PPORLElement`, left-pad-query / right-pad-response collate
into :class:`PPORLBatch`, and JSON export for algorithm distillation."""

import json
import os
import threading
import time
from typing import Iterable, List

import numpy as np

from trlx_tpu.data.ppo_types import PPORLBatch, PPORLElement
from trlx_tpu.pipeline import BaseRolloutStore, NumpyLoader


def ppo_collate_fn(pad_token_id: int, elems: List[PPORLElement]) -> PPORLBatch:
    """Left-pad queries / right-pad responses+payloads (parity: ppo_pipeline.py:23-35);
    the padding loops run in the C++ data plane when available."""
    from trlx_tpu.native import pad_collate_f32, pad_collate_i32

    P = max(len(e.query_tensor) for e in elems)
    R = max(len(e.response_tensor) for e in elems)

    queries, q_mask = pad_collate_i32(
        [e.query_tensor for e in elems], P, pad_token_id, pad_left=True
    )
    responses, r_mask = pad_collate_i32(
        [e.response_tensor for e in elems], R, pad_token_id, pad_left=False
    )
    logprobs = pad_collate_f32([e.logprobs for e in elems], R)
    values = pad_collate_f32([e.values for e in elems], R)
    rewards = pad_collate_f32([e.rewards for e in elems], R)
    versions = np.asarray(
        [int(getattr(e, "policy_version", 0) or 0) for e in elems], np.int32
    )

    return PPORLBatch(
        queries, responses, logprobs, values, rewards, q_mask, r_mask,
        policy_version=versions,
    )


class PPORolloutStorage(BaseRolloutStore):
    """Rollout storage for PPO experience.

    Mutations are lock-guarded: with the async rollout engine the producer
    thread and the learner can touch the store concurrently (push vs
    clear_history/iteration), and ``history`` swaps must be atomic against a
    mid-``export_history`` snapshot."""

    def __init__(self, pad_token_id: int):
        super().__init__()
        self.pad_token_id = pad_token_id
        self.history: List[PPORLElement] = []
        self._lock = threading.RLock()

    def push(self, exps: Iterable[PPORLElement]):
        exps = list(exps)
        with self._lock:
            self.history = self.history + exps

    def clear_history(self):
        with self._lock:
            self.history = []

    def export_history(self, location: str, only_text: bool = False, tokenizer=None):
        """Append rollouts as JSON for algorithm distillation
        (parity: ppo_pipeline.py:71-89)."""
        assert os.path.exists(location)
        fpath = os.path.join(location, f"epoch-{str(time.time())}.json")

        def exp_to_dict(exp: PPORLElement):
            d = {
                "query_tensor": np.asarray(exp.query_tensor).tolist(),
                "response_tensor": np.asarray(exp.response_tensor).tolist(),
                "logprobs": np.asarray(exp.logprobs).tolist(),
                "values": np.asarray(exp.values).tolist(),
                "rewards": np.asarray(exp.rewards).tolist(),
            }
            if tokenizer is not None:
                d["query_text"] = tokenizer.decode(exp.query_tensor)
                d["response_text"] = tokenizer.decode(exp.response_tensor)
                if only_text:
                    d = {"query_text": d["query_text"], "response_text": d["response_text"]}
            return d

        with self._lock:
            history = self.history
        data = [exp_to_dict(exp) for exp in history]
        with open(fpath, "w") as f:
            json.dump(data, f)

    def __getitem__(self, index: int) -> PPORLElement:
        with self._lock:
            return self.history[index]

    def __len__(self) -> int:
        with self._lock:
            return len(self.history)

    def create_loader(self, batch_size: int, shuffle: bool = False, drop_last: bool = True,
                      seed: int = 0) -> NumpyLoader:
        return NumpyLoader(
            self, batch_size, lambda elems: ppo_collate_fn(self.pad_token_id, elems),
            shuffle=shuffle, drop_last=drop_last, seed=seed,
        )
