"""8-bit optimizer states: AdamW with blockwise-quantized moments.

The reference exposes bitsandbytes 8-bit optimizers (CUDA kernels,
`/root/reference/trlx/utils/__init__.py:104-123`); this is the TPU-native
counterpart as a pure optax ``GradientTransformation``. Both Adam moments are
stored int8 with one f32 scale per block (bnb-style blockwise dynamic
quantization, linear codebook): first moment signed (symmetric around 0),
second moment non-negative. State memory per parameter drops from 8 bytes
(2 x f32) to ~2.008 bytes (2 x int8 + 2 x f32/block). Dequantize → Adam math
in f32 → requantize happens inside the fused update, so XLA keeps the
transient f32 moments out of long-lived HBM.
"""

from typing import Callable, Union

import jax
import jax.numpy as jnp
import optax

BLOCK = 256


def _blocked(x: jnp.ndarray) -> jnp.ndarray:
    """Flatten to [n_blocks, BLOCK] (zero-padded)."""
    flat = x.reshape(-1)
    pad = -flat.size % BLOCK
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, BLOCK)


def _unblocked(xb: jnp.ndarray, shape) -> jnp.ndarray:
    n = 1
    for d in shape:
        n *= d
    return xb.reshape(-1)[:n].reshape(shape)


def _quant_signed(x: jnp.ndarray):
    xb = _blocked(x.astype(jnp.float32))
    scale = jnp.max(jnp.abs(xb), axis=1)
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(xb / safe[:, None] * 127.0), -127, 127).astype(jnp.int8)
    return q, scale


def _dequant_signed(q: jnp.ndarray, scale: jnp.ndarray, shape) -> jnp.ndarray:
    safe = jnp.where(scale == 0.0, 1.0, scale)
    return _unblocked(q.astype(jnp.float32) * (safe[:, None] / 127.0), shape)


def _quant_pos(x: jnp.ndarray):
    """Log-space blockwise quantization for the (non-negative) second moment.

    Linear codes starve small v entries sharing a block with large ones (their
    codes collapse to 0, so 1/sqrt(v) explodes); log-space codes give bounded
    MULTIPLICATIVE error instead — the role bnb's dynamic codebook plays. Code
    0 is reserved for exact zero; codes 1..255 span [log vmin, log vmax] of the
    block. Per-block side info: (log_min, log_range) as a [nb, 2] f32 array."""
    xb = _blocked(x.astype(jnp.float32))
    pos = xb > 0.0
    logs = jnp.log(jnp.where(pos, xb, 1.0))
    lmin = jnp.min(jnp.where(pos, logs, jnp.inf), axis=1)
    lmax = jnp.max(jnp.where(pos, logs, -jnp.inf), axis=1)
    has_pos = jnp.isfinite(lmin)
    lmin = jnp.where(has_pos, lmin, 0.0)
    lrange = jnp.where(has_pos, jnp.maximum(lmax - lmin, 1e-12), 1.0)
    q = 1 + jnp.round((logs - lmin[:, None]) / lrange[:, None] * 254.0)
    q = jnp.where(pos, jnp.clip(q, 1, 255), 0).astype(jnp.uint8)
    return q, jnp.stack([lmin, lrange], axis=1)


def _dequant_pos(q: jnp.ndarray, scale: jnp.ndarray, shape) -> jnp.ndarray:
    lmin, lrange = scale[:, 0], scale[:, 1]
    vals = jnp.exp(lmin[:, None] + (q.astype(jnp.float32) - 1.0) / 254.0 * lrange[:, None])
    return _unblocked(jnp.where(q == 0, 0.0, vals), shape)


def adamw_8bit(
    learning_rate: Union[float, Callable],
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """AdamW with int8 blockwise-quantized moment states."""

    def init(params):
        def init_leaf(p):
            nb = -(-p.size // BLOCK)
            return {
                "m_q": jnp.zeros((nb, BLOCK), jnp.int8),
                "m_scale": jnp.zeros((nb,), jnp.float32),
                "v_q": jnp.zeros((nb, BLOCK), jnp.uint8),
                "v_scale": jnp.zeros((nb, 2), jnp.float32),
            }

        return {
            "count": jnp.zeros((), jnp.int32),
            "moments": jax.tree.map(init_leaf, params),
        }

    def update(grads, state, params=None):
        if weight_decay and params is None:
            raise ValueError("adamw_8bit with weight_decay requires params")
        count = state["count"] + 1
        lr = learning_rate(state["count"]) if callable(learning_rate) else learning_rate
        bc1 = 1.0 - b1 ** count.astype(jnp.float32)
        bc2 = 1.0 - b2 ** count.astype(jnp.float32)

        def upd(g, s, p):
            orig_dtype = g.dtype
            g = g.astype(jnp.float32)
            m = b1 * _dequant_signed(s["m_q"], s["m_scale"], g.shape) + (1 - b1) * g
            v = b2 * _dequant_pos(s["v_q"], s["v_scale"], g.shape) + (1 - b2) * g * g
            step = m / bc1 / (jnp.sqrt(v / bc2) + eps)
            if weight_decay:
                step = step + weight_decay * p.astype(jnp.float32)
            m_q, m_scale = _quant_signed(m)
            v_q, v_scale = _quant_pos(v)
            new_s = {"m_q": m_q, "m_scale": m_scale, "v_q": v_q, "v_scale": v_scale}
            return (-lr * step).astype(orig_dtype), new_s

        params_like = params if params is not None else grads
        flat = jax.tree.map(upd, grads, state["moments"], params_like)
        # pairs only: optax.masked (multi_transform freeze groups) injects
        # MaskedNode — an EMPTY NamedTuple, i.e. an empty tuple — for frozen
        # leaves; unpacking it as a (update, state) pair raises IndexError
        is_pair = lambda x: isinstance(x, tuple) and len(x) == 2
        updates = jax.tree.map(lambda x: x[0], flat, is_leaf=is_pair)
        moments = jax.tree.map(lambda x: x[1], flat, is_leaf=is_pair)
        return updates, {"count": count, "moments": moments}

    return optax.GradientTransformation(init, update)


def adam_8bit(learning_rate, b1=0.9, b2=0.999, eps=1e-8) -> optax.GradientTransformation:
    return adamw_8bit(learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=0.0)
