"""Jitted KV-cache autoregressive generation under SPMD.

This replaces the reference's reliance on HF ``generate`` / NeMo ``text_generation``
(SURVEY.md §2.4.8 — "the rollout hot loop"): prefill builds the cache in one forward,
then a ``lax.while_loop`` decodes one token per step with early exit when every
sequence has finished (under SPMD the ``finished`` reduction is global, giving the
pod-wide eos short-circuit the reference gets from ``synced_gpus``). All shapes are
static: prompts are left-padded to a bucketed length, the cache is preallocated at
``prompt_len + max_new_tokens``, and the sequence buffer is donated across steps.

ILQL's advantage-shaped decoding (reference ``modeling_ilql.py:325-412``) plugs in as
a ``logits_processor(params, hidden, logits, prev_token) -> logits`` hook evaluated on the decode
hidden state each step.
"""

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from trlx_tpu.analysis.ir.entrypoints import EntryArtifacts, register_entrypoint
from trlx_tpu.ops.sampling import sample_token

# step_fn(params, ids[B,T], mask[B,S], positions[B,T], cache) -> (logits[B,T,V],
# hidden[B,T,H], cache). `hidden` feeds the ILQL logit processor; pass None-free.
StepFn = Callable[..., Tuple[jnp.ndarray, jnp.ndarray, Any]]


def pad_to_bucket(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= length (limits recompilation across prompt lengths;
    parity concern: reference pads to multiples of 8, SURVEY.md §7 hard-part 3)."""
    for b in sorted(buckets):
        if b >= length:
            return b
    return int(np.ceil(length / 64) * 64)


def left_pad_batch(
    ids_list: List[np.ndarray], pad_token_id: int, target_len: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side: left-pad a ragged list of prompt id arrays to [B, target_len]
    (C++ data plane when available, numpy otherwise)."""
    from trlx_tpu.native import pad_collate_i32

    return pad_collate_i32(ids_list, target_len, pad_token_id, pad_left=True)


def generate(
    step_fn: StepFn,
    params: Any,
    init_cache_fn: Callable[[int, int], Any],
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
    rng: jax.Array,
    max_new_tokens: int,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    do_sample: bool = True,
    top_k_impl: str = "approx",
    min_new_tokens: int = 0,
    logits_processor: Optional[Callable[[Any, jnp.ndarray, jnp.ndarray], jnp.ndarray]] = None,
) -> Dict[str, jnp.ndarray]:
    """Generate continuations for left-padded prompts.

    Returns dict with ``sequences`` [B, P+N] (prompt + generation, ``pad_token_id``
    after eos) and ``response_mask`` [B, N] (1 on generated tokens up to & incl. eos).
    Fully traceable: wrap in jit with static max_new_tokens via the trainer.
    """
    B, P = input_ids.shape
    N = int(max_new_tokens)
    total = P + N
    prompt_lens = attention_mask.sum(axis=1).astype(jnp.int32)

    cache = init_cache_fn(B, total)
    # pytree structure is static under trace — `in` probes dict keys, never
    # array values
    if isinstance(cache, dict) and "index" in cache:  # graftcheck: noqa[JX004]
        # static Python 0: marks prefill-from-zero at TRACE time, so the model's
        # prefill-only paths (flash kernel, prompt-tuning prepend) engage even
        # when this whole function is wrapped in an outer jit (where a
        # jnp.array(0) constant would already be a tracer)
        cache = {**cache, "index": 0}
    # mask over all cache slots; generated slots get enabled as they are written
    full_mask = jnp.concatenate([attention_mask.astype(jnp.int32), jnp.zeros((B, N), jnp.int32)], axis=1)

    positions = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0, None).astype(jnp.int32)
    logits, hidden, cache = step_fn(params, input_ids, full_mask, positions, cache)
    last_logits = logits[:, -1, :]
    if logits_processor is not None:
        last_logits = logits_processor(params, hidden[:, -1, :], last_logits, input_ids[:, -1])

    seqs = jnp.concatenate([input_ids, jnp.full((B, N), pad_token_id, jnp.int32)], axis=1)

    def sample_step(rng, step, logits, finished):
        rng, sub = jax.random.split(rng)
        if eos_token_id is not None and min_new_tokens > 0:
            logits = jnp.where(
                (step < min_new_tokens) & (jnp.arange(logits.shape[-1]) == eos_token_id)[None, :],
                -1e9,
                logits,
            )
        tok = sample_token(sub, logits, temperature, top_k, top_p, do_sample, top_k_impl)
        tok = jnp.where(finished, pad_token_id, tok)
        return rng, tok

    rng, tok = sample_step(rng, jnp.array(0), last_logits, jnp.zeros((B,), bool))
    finished = jnp.zeros((B,), bool)
    if eos_token_id is not None:
        finished = tok == eos_token_id

    def write(state_seqs, state_mask, tok, step):
        new_seqs = jax.lax.dynamic_update_slice(state_seqs, tok[:, None], (0, P + step))
        new_mask = jax.lax.dynamic_update_slice(
            state_mask, jnp.ones((B, 1), jnp.int32), (0, P + step)
        )
        return new_seqs, new_mask

    seqs, full_mask = write(seqs, full_mask, tok, 0)

    def cond(state):
        step, _, _, finished, _, _, _ = state
        return jnp.logical_and(step < N, jnp.logical_not(jnp.all(finished)))

    def body(state):
        step, seqs, full_mask, finished, cache, rng, tok = state
        # `tok` was sampled at iteration step-1 and sits at sequence slot P+step-1,
        # i.e. per-sample position prompt_len + step - 1
        pos = (prompt_lens + step - 1)[:, None]
        logits, hidden, cache = step_fn(params, tok[:, None], full_mask, pos, cache)
        step_logits = logits[:, -1, :]
        if logits_processor is not None:
            step_logits = logits_processor(params, hidden[:, -1, :], step_logits, tok)
        rng, new_tok = sample_step(rng, step, step_logits, finished)
        new_finished = finished
        if eos_token_id is not None:
            new_finished = jnp.logical_or(finished, new_tok == eos_token_id)
        seqs, full_mask = write(seqs, full_mask, new_tok, step)
        return step + 1, seqs, full_mask, new_finished, cache, rng, new_tok

    state = (jnp.array(1, jnp.int32), seqs, full_mask, finished, cache, rng, tok)
    step, seqs, full_mask, finished, cache, rng, tok = jax.lax.while_loop(cond, body, state)

    response_mask = full_mask[:, P:]
    # zero out mask past each sample's eos is already handled: finished samples write
    # pad tokens but their mask slots were set; rebuild mask from tokens instead:
    if eos_token_id is not None:
        resp = seqs[:, P:]
        is_eos = resp == eos_token_id
        after_eos = jnp.cumsum(jnp.pad(is_eos[:, :-1], ((0, 0), (1, 0))), axis=1) > 0
        response_mask = response_mask * (1 - after_eos.astype(jnp.int32))
        # never count trailing never-written slots (loop exited early)
        written = jnp.arange(N)[None, :] < step
        response_mask = response_mask * written.astype(jnp.int32)
        seqs = jnp.concatenate(
            [seqs[:, :P], jnp.where(response_mask > 0, resp, pad_token_id)], axis=1
        )
    return {"sequences": seqs, "response_mask": response_mask}


def generate_seq2seq(
    encode_fn,
    cross_kv_fn,
    decode_fn,
    init_cache_fn,
    params: Any,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
    rng: jax.Array,
    max_new_tokens: int,
    decoder_start_token_id: int = 0,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    do_sample: bool = True,
    top_k_impl: str = "approx",
    min_new_tokens: int = 0,
    logits_processor=None,
) -> Dict[str, jnp.ndarray]:
    """Seq2seq generation: encode once, precompute cross-attention K/V, then a
    ``lax.while_loop`` decoder with a preallocated self-attention cache (replaces the
    reference's HF seq2seq ``generate``; cf. modeling_ppo.py:1242-1350 usage).

    ``decode_fn(params, tok[B,1], enc, enc_mask, dec_mask, positions, cache,
    cross_kvs) -> (logits, hidden, cache)``. Returns ``sequences`` [B, 1+N] (leading
    decoder_start token) and ``response_mask`` [B, N].
    """
    B = input_ids.shape[0]
    N = int(max_new_tokens)

    enc = encode_fn(params, input_ids, attention_mask)
    cross_kvs = cross_kv_fn(params, enc)
    cache = init_cache_fn(params, B, N + 1)

    seqs = jnp.full((B, N + 1), pad_token_id, jnp.int32)
    seqs = seqs.at[:, 0].set(decoder_start_token_id)
    dec_mask = jnp.zeros((B, N + 1), jnp.int32).at[:, 0].set(1)

    def sample_step(rng, step, logits, finished):
        rng, sub = jax.random.split(rng)
        if eos_token_id is not None and min_new_tokens > 0:
            logits = jnp.where(
                (step < min_new_tokens)
                & (jnp.arange(logits.shape[-1]) == eos_token_id)[None, :],
                -1e9,
                logits,
            )
        tok = sample_token(sub, logits, temperature, top_k, top_p, do_sample, top_k_impl)
        return rng, jnp.where(finished, pad_token_id, tok)

    def cond(state):
        step, _, _, finished, _, _, _ = state
        return jnp.logical_and(step < N, jnp.logical_not(jnp.all(finished)))

    def body(state):
        step, seqs, dec_mask, finished, cache, rng, tok = state
        logits, hidden, cache = decode_fn(
            params, tok[:, None], enc, attention_mask, dec_mask, None, cache, cross_kvs
        )
        step_logits = logits[:, -1, :]
        if logits_processor is not None:
            step_logits = logits_processor(params, hidden[:, -1, :], step_logits, tok)
        rng, new_tok = sample_step(rng, step, step_logits, finished)
        new_finished = finished
        if eos_token_id is not None:
            new_finished = jnp.logical_or(finished, new_tok == eos_token_id)
        seqs = jax.lax.dynamic_update_slice(seqs, new_tok[:, None], (0, step + 1))
        dec_mask = jax.lax.dynamic_update_slice(
            dec_mask, jnp.ones((B, 1), jnp.int32), (0, step + 1)
        )
        return step + 1, seqs, dec_mask, new_finished, cache, rng, new_tok

    tok0 = jnp.full((B,), decoder_start_token_id, jnp.int32)
    state = (
        jnp.array(0, jnp.int32), seqs, dec_mask, jnp.zeros((B,), bool), cache, rng, tok0
    )
    step, seqs, dec_mask, finished, cache, rng, tok = jax.lax.while_loop(cond, body, state)

    response_mask = dec_mask[:, 1:]
    if eos_token_id is not None:
        resp = seqs[:, 1:]
        is_eos = resp == eos_token_id
        after_eos = jnp.cumsum(jnp.pad(is_eos[:, :-1], ((0, 0), (1, 0))), axis=1) > 0
        response_mask = response_mask * (1 - after_eos.astype(jnp.int32))
        written = jnp.arange(N)[None, :] < step
        response_mask = response_mask * written.astype(jnp.int32)
        seqs = jnp.concatenate(
            [seqs[:, :1], jnp.where(response_mask > 0, resp, pad_token_id)], axis=1
        )
    return {"sequences": seqs, "response_mask": response_mask}


# -- AOT audit surface (graftcheck-ir) ----------------------------------------


@register_entrypoint("decode_step", specs=("small", "xl"))
def build_decode_step(spec: str, mesh) -> EntryArtifacts:
    """The rollout decode loop as graftcheck-ir audits it: :func:`generate`
    over a ``TransformerLM`` cached decode — the same jitted callable
    ``MeshRLTrainer.generate`` builds — with replicated outputs and the
    sampling pipeline pinned by :data:`trlx_tpu.ops.sampling.AUDIT_GEN_KWARGS`.

    The ``xl`` spec is the 1.5B blueprint from the round-5 scale proof
    (GPT-2-XL dims, scanned layers): it exists to be *lowered*, deviceless,
    proving the audit scales past gpt2-small without hardware; CI compiles
    only ``small``.
    """
    from trlx_tpu.models.presets import PRESETS
    from trlx_tpu.models.transformer import TransformerLM
    from trlx_tpu.ops.sampling import AUDIT_GEN_KWARGS
    from trlx_tpu.parallel.mesh import BATCH_AXES

    from jax.sharding import NamedSharding, PartitionSpec

    dims = {
        "small": dict(hidden=64, layers=2, heads=4, vocab=256, B=8, P=16, N=8,
                      scan_layers=False),
        # GPT-2-XL shapes (~1.5B params): hidden 1600 x 48 layers, 25 heads
        "xl": dict(hidden=1600, layers=48, heads=25, vocab=50257, B=8, P=128,
                   N=16, scan_layers=True),
    }[spec]
    model_config = PRESETS["gpt2"].replace(
        vocab_size=dims["vocab"], hidden_size=dims["hidden"],
        num_layers=dims["layers"], num_heads=dims["heads"],
        intermediate_size=4 * dims["hidden"],
        max_position_embeddings=max(1024, dims["P"] + dims["N"]),
        param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
        scan_layers=dims["scan_layers"],
    )
    trunk = TransformerLM(model_config)

    params_shape = jax.eval_shape(
        lambda: trunk.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32), jnp.ones((1, 2), jnp.int32)
        )
    )["params"]
    from trlx_tpu.parallel.sharding import make_param_shardings

    abs_params = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        params_shape, make_param_shardings(params_shape, mesh),
    )

    B, P, N = dims["B"], dims["P"], dims["N"]
    bsh = NamedSharding(mesh, PartitionSpec(BATCH_AXES, None))
    abs_ids = jax.ShapeDtypeStruct((B, P), jnp.int32, sharding=bsh)
    abs_rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))

    def step_fn(params, ids, mask, positions, cache):
        logits, hidden, _, cache = trunk.apply({"params": params}, ids, mask, positions, cache)
        return logits, hidden, cache

    def decode_fn(params, ids, mask, rng):
        return generate(
            step_fn, params, lambda b, s: trunk.init_cache(b, s), ids, mask, rng,
            max_new_tokens=N, eos_token_id=0, pad_token_id=0, **AUDIT_GEN_KWARGS,
        )

    return EntryArtifacts(
        fn=decode_fn,
        args=(abs_params, abs_ids, abs_ids, abs_rng),
        donate_argnums=(),
        out_shardings=NamedSharding(mesh, PartitionSpec()),
        compute_dtype="bfloat16",
        meta=dict(batch=B, prompt=P, max_new_tokens=N,
                  hidden_size=dims["hidden"], num_layers=dims["layers"]),
    )
