"""Attention ops: Pallas TPU flash-attention forward + backward kernels + XLA reference.

The reference relies on external CUDA attention kernels (HF/NeMo, SURVEY.md §2.4.5);
this is the TPU-native equivalent. Forward is an online-softmax (FlashAttention-style)
Pallas kernel: grid = (batch, heads, q_blocks, kv_blocks) with the kv axis innermost —
TPU grids execute sequentially, so running max / denominator / accumulator live in
VMEM scratch across kv steps and the output tile is written once on the last step.
Causal blocks above the diagonal are skipped with ``@pl.when``.

Backward is the standard recompute-per-block scheme (two kernels, as in the in-tree
TPU flash attention): the forward saves only O and the per-row logsumexp; backward
recomputes P = exp(S - L) tile by tile, so training memory is O(T·block) rather than
the O(T·S) score matrix the old XLA-recompute fallback materialized. ``dkv`` runs
grid (B, Hkv, kv_blocks, q_blocks) accumulating dK/dV in VMEM across the inner q
steps; ``dq`` runs the forward's grid accumulating dQ across kv steps. The XLA
fallback is kept behind ``BACKWARD_IMPL`` and used for grad-parity tests.

Grouped-query attention is native: K/V arrive with their own head count ``Hkv`` and
the kernels map query head h -> kv head h // (H // Hkv) in the BlockSpec index maps,
so grouped K/V are never materialized at full head count (the old path ``jnp.repeat``-ed
them, multiplying HBM traffic by the group size).

Masking model matches :mod:`trlx_tpu.models.transformer`: slot-based causality plus a
[B, S] key-validity mask (left-padded prompts). Engaged on every multi-token forward:
the training loss, the logprob/value scoring passes, and generation *prefill* (which
attends over the just-computed prefix k/v while the cache write happens separately).
Only single-token decode steps stay on the XLA path. Arbitrary T/S are supported via
internal padding + block selection (see ``_flash_forward``).

Mosaic tiling note: small per-row tensors (kv mask, logsumexp, delta) are carried with
a trailing lane dim equal to the array's own last dim (8 sublane-replicated lanes),
which tiles legally where a bare [B, S]/(1, block) layout does not (observed as a
real-TPU lowering failure in round 2; interpret mode on CPU never checks).
"""

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# "pallas" (default) or "xla": which backward the flash custom_vjp traces.
# Pallas recomputes attention per block from the saved logsumexp — O(T·block)
# memory, mandatory at long context — but the recompute costs real throughput
# at small context: switching the backward from the XLA O(T·S) recompute to
# the Pallas kernels is what slid gpt2-small train MFU 0.43 -> 0.30 between
# bench rounds r02 and r05 (S=256, where the materialized score matrix is
# cheap). Pick per scale via set_flash_backward / TRLX_FLASH_BWD; tests also
# flip this to check grad parity between the two backwards.
BACKWARD_IMPL = os.environ.get("TRLX_FLASH_BWD", "pallas")


def set_flash_backward(impl: str) -> str:
    """Select the flash-attention backward ("pallas" | "xla") for subsequent
    traces; returns the previous value. The choice is captured at trace time,
    so set it before the train step is first jitted."""
    global BACKWARD_IMPL
    if impl not in ("pallas", "xla"):
        raise ValueError(f"flash backward must be 'pallas' or 'xla', got {impl!r}")
    prev, BACKWARD_IMPL = BACKWARD_IMPL, impl
    return prev

LANES = 8  # trailing lane width for per-row tensors (lse / delta / kv mask rows)


def _flash_kernel(
    kv_valid_ref,  # [1, 1, 8, block_k] int32 (sublane-replicated, per kv block)
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    o_ref,  # [1, 1, block_q, D]
    lse_ref,  # [1, 1, block_q, LANES] f32 or None (when with_lse)
    m_scratch,  # [block_q, 1] f32
    l_scratch,  # [block_q, 1] f32
    acc_scratch,  # [block_q, D] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    kv_steps: int,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    # skip fully-masked blocks above the causal diagonal
    run = jnp.logical_or(
        jnp.logical_not(causal), kj * block_k <= qi * block_q + (block_q - 1)
    )

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]

        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = kv_valid_ref[0, 0, 0][None, :] > 0
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scratch[...]  # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # fully-masked rows keep m == NEG_INF; exp(s - m) would be exp(0) = 1 there
        p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)  # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = alpha * l_scratch[...] + jnp.sum(p, axis=1, keepdims=True)
        acc_scratch[...] = acc_scratch[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scratch[...] = m_new
        l_scratch[...] = l_new

    @pl.when(kj == kv_steps - 1)
    def _finalize():
        l = l_scratch[...]
        # rows with no valid keys (fully masked) produce 0, not NaN
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, ...] = (acc_scratch[...] / safe_l).astype(o_ref.dtype)
        if lse_ref is not None:
            lse = jnp.where(l > 0.0, m_scratch[...] + jnp.log(safe_l), NEG_INF)
            lse_ref[0, 0, ...] = jnp.broadcast_to(lse, (block_q, LANES))


def _pick_block(n: int, max_block: int) -> int:
    """Largest multiple-of-8 block <= max_block dividing ceil8(n) (min padding)."""
    n8 = -(-n // 8) * 8
    return max(b for b in range(8, min(max_block, n8) + 1, 8) if n8 % b == 0)


def _kv_head_map(H: int, Hkv: int):
    """Query head -> kv head index map factor for grouped-query attention."""
    rep = H // Hkv
    return lambda h: h // rep


def _flash_forward(
    q: jnp.ndarray,  # [B, H, T, D]
    k: jnp.ndarray,  # [B, Hkv, S, D]
    v: jnp.ndarray,
    kv_valid: jnp.ndarray,  # [B, S] int32
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
    with_lse: bool = False,
):
    B, H, T, D = q.shape
    S = k.shape[2]
    # any T/S supported: pad to a sublane multiple and pick the largest block
    # (<= requested) that divides the padded length — e.g. T=144 (P16+R128) runs
    # at block 72 with no extra padding. Padded keys are masked via kv_valid;
    # padded query rows are sliced off. This lets the kernel cover prefill and
    # mixed P+R training shapes.
    block_q = _pick_block(T, block_q)
    block_k = _pick_block(S, block_k)
    pad_t = -T % block_q
    pad_s = -S % block_k
    if pad_t or pad_s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        kv_valid = jnp.pad(kv_valid, ((0, 0), (0, pad_s)))
    out, lse = _flash_padded(
        q, k, v, kv_valid, causal, scale, block_q, block_k, interpret, with_lse
    )
    if pad_t:
        out = out[:, :, :T, :]
        lse = lse[:, :, :T] if lse is not None else None
    return (out, lse) if with_lse else out


def _tile_kv_valid(kv_valid, B, kv_steps, block_k):
    """[B, S] -> [B, kv_steps, 8, block_k] sublane-replicated (tiles legally)."""
    return jnp.broadcast_to(
        kv_valid.astype(jnp.int32).reshape(B, kv_steps, 1, block_k),
        (B, kv_steps, 8, block_k),
    )


def _flash_padded(q, k, v, kv_valid, causal, scale, block_q, block_k, interpret, with_lse):
    B, H, T, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    assert T % block_q == 0 and S % block_k == 0, (T, S, block_q, block_k)
    assert H % Hkv == 0, (H, Hkv)
    kvh = _kv_head_map(H, Hkv)
    kv_steps = S // block_k
    grid = (B, H, T // block_q, kv_steps)

    kv_valid_tiled = _tile_kv_valid(kv_valid, B, kv_steps, block_k)

    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        kv_steps=kv_steps,
    )
    out_shape = [jax.ShapeDtypeStruct((B, H, T, D), q.dtype)]
    out_specs = [pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))]
    if with_lse:
        out_shape.append(jax.ShapeDtypeStruct((B, H, T, LANES), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, 1, block_q, LANES), lambda b, h, i, j: (b, h, i, 0))
        )
    else:
        kernel = functools.partial(_drop_last_ref, kernel)

    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, 8, block_k), lambda b, h, i, j: (b, j, 0, 0)),  # kv_valid
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, kvh(h), j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, kvh(h), j, 0)),
        ],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shape if with_lse else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(kv_valid_tiled, q, k, v)
    if with_lse:
        out, lse = res
        return out, lse[..., 0]  # [B, H, T]
    return res, None


def _drop_last_ref(kernel, *refs):
    """Adapt the shared kernel to the no-lse pallas_call signature: insert
    lse_ref=None between the single output ref and the scratch refs."""
    # refs = (kv_valid, q, k, v, o, m_s, l_s, acc_s)
    return kernel(*refs[:5], None, *refs[5:])


# ----------------------------------------------------------------- backward


def _flash_bwd_dkv_kernel(
    kv_valid_ref,  # [1, 1, 8, block_k]
    q_ref,  # [1, rep, block_q, D] — the kv head's whole query-head group
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    do_ref,  # [1, rep, block_q, D]
    lse_ref,  # [1, rep, block_q, LANES]
    delta_ref,  # [1, rep, block_q, LANES]
    dk_ref,  # [1, 1, block_k, D] out
    dv_ref,  # [1, 1, block_k, D] out
    dk_scratch,  # [block_k, D] f32
    dv_scratch,  # [block_k, D] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    q_steps: int,
    rep: int,
):
    kj = pl.program_id(2)
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_scratch[...] = jnp.zeros_like(dk_scratch)
        dv_scratch[...] = jnp.zeros_like(dv_scratch)

    run = jnp.logical_or(
        jnp.logical_not(causal), kj * block_k <= qi * block_q + (block_q - 1)
    )

    @pl.when(run)
    def _step():
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = kv_valid_ref[0, 0, 0][None, :] > 0
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)

        # dK/dV for a kv head sum over its whole query-head group; the group is
        # fetched as a block dim and the loop unrolls statically (rep is 1 for MHA)
        for r in range(rep):
            q = q_ref[0, r].astype(jnp.float32)  # [bq, D]
            do = do_ref[0, r].astype(jnp.float32)
            lse = lse_ref[0, r, :, :1]  # [bq, 1]
            delta = delta_ref[0, r, :, :1]

            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            ) * scale  # [bq, bk]
            # fully-masked rows have lse == NEG_INF; guard the exp to avoid inf*0
            lse_safe = jnp.where(lse > NEG_INF / 2, lse, 0.0)
            p = jnp.where(mask, jnp.exp(s - lse_safe), 0.0)  # [bq, bk]
            # dv += P^T dO
            dv_scratch[...] += jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )  # [bq, bk]
            ds = p * (dp - delta) * scale
            # dk += dS^T Q
            dk_scratch[...] += jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )

    @pl.when(qi == q_steps - 1)
    def _finalize():
        dk_ref[0, 0, ...] = dk_scratch[...].astype(dk_ref.dtype)
        dv_ref[0, 0, ...] = dv_scratch[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    kv_valid_ref,
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    dq_ref,  # [1, 1, block_q, D] out
    dq_scratch,  # [block_q, D] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    kv_steps: int,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        dq_scratch[...] = jnp.zeros_like(dq_scratch)

    run = jnp.logical_or(
        jnp.logical_not(causal), kj * block_k <= qi * block_q + (block_q - 1)
    )

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, :1]
        delta = delta_ref[0, 0, :, :1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = kv_valid_ref[0, 0, 0][None, :] > 0
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        lse_safe = jnp.where(lse > NEG_INF / 2, lse, 0.0)
        p = jnp.where(mask, jnp.exp(s - lse_safe), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * scale
        dq_scratch[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kj == kv_steps - 1)
    def _finalize():
        dq_ref[0, 0, ...] = dq_scratch[...].astype(dq_ref.dtype)


def _flash_backward(q, k, v, kv_valid, out, lse, g, causal, scale, block_q, block_k, interpret):
    """Pallas backward: recompute P per block from saved lse. Returns dq, dk, dv.

    Two kernels: ``dkv`` runs grid (B, Hkv, kv_blocks, q_blocks) — one program per
    *kv* head, its query-head group fetched as a block dimension so dK/dV sum over
    the group without output-block write conflicts; ``dq`` runs the forward's grid
    (B, H, q_blocks, kv_blocks) with dQ accumulated in VMEM across kv steps."""
    B, H, T, D = q.shape
    Hkv, S = k.shape[1], k.shape[2]
    rep = H // Hkv
    block_q = _pick_block(T, block_q)
    block_k = _pick_block(S, block_k)
    pad_t = -T % block_q
    pad_s = -S % block_k
    if pad_t or pad_s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
        g = jnp.pad(g, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
        out = jnp.pad(out, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
        # padded query rows: lse = NEG_INF marks them fully-masked (p == 0)
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_t)), constant_values=NEG_INF)
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        kv_valid = jnp.pad(kv_valid, ((0, 0), (0, pad_s)))
    Tp, Sp = q.shape[2], k.shape[2]
    q_steps, kv_steps = Tp // block_q, Sp // block_k
    kvh = _kv_head_map(H, Hkv)

    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [B,H,Tp]
    lse_l = jnp.broadcast_to(lse[..., None], (B, H, Tp, LANES))
    delta_l = jnp.broadcast_to(delta[..., None], (B, H, Tp, LANES))
    kv_valid_tiled = _tile_kv_valid(kv_valid, B, kv_steps, block_k)

    # block coordinate hk in a dim of block size `rep` addresses elements
    # [hk*rep, (hk+1)*rep) — exactly kv head hk's query-head group
    qo_spec = pl.BlockSpec((1, rep, block_q, D), lambda b, hk, kj, qi: (b, hk, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, D), lambda b, hk, kj, qi: (b, hk, kj, 0))
    row_spec = pl.BlockSpec((1, rep, block_q, LANES), lambda b, hk, kj, qi: (b, hk, qi, 0))
    mask_spec = pl.BlockSpec((1, 1, 8, block_k), lambda b, hk, kj, qi: (b, kj, 0, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            q_steps=q_steps, rep=rep,
        ),
        grid=(B, Hkv, kv_steps, q_steps),
        in_specs=[mask_spec, qo_spec, kv_spec, kv_spec, qo_spec, row_spec, row_spec],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((B, Hkv, Sp, D), jnp.float32),
            jax.ShapeDtypeStruct((B, Hkv, Sp, D), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(kv_valid_tiled, q, k, v, g, lse_l, delta_l)

    dq_q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, qi, kj: (b, h, qi, 0))
    dq_kv_spec = pl.BlockSpec((1, 1, block_k, D), lambda b, h, qi, kj: (b, kvh(h), kj, 0))
    dq_row_spec = pl.BlockSpec((1, 1, block_q, LANES), lambda b, h, qi, kj: (b, h, qi, 0))
    dq_mask_spec = pl.BlockSpec((1, 1, 8, block_k), lambda b, h, qi, kj: (b, kj, 0, 0))

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k, kv_steps=kv_steps,
        ),
        grid=(B, H, q_steps, kv_steps),
        in_specs=[dq_mask_spec, dq_q_spec, dq_kv_spec, dq_kv_spec, dq_q_spec, dq_row_spec, dq_row_spec],
        out_specs=dq_q_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Tp, D), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(kv_valid_tiled, q, k, v, g, lse_l, delta_l)

    if pad_t:
        dq = dq[:, :, :T, :]
    if pad_s:
        dk = dk[:, :, :S, :]
        dv = dv[:, :, :S, :]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def xla_attention(q, k, v, kv_valid, causal: bool, scale: float) -> jnp.ndarray:
    """Reference attention in plain XLA ([B,H,T,D] layout; grouped K/V repeated)."""
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    T, S = s.shape[-2], s.shape[-1]
    mask = kv_valid[:, None, None, :] > 0
    if causal:
        q_pos = jnp.arange(T)[:, None]
        k_pos = jnp.arange(S)[None, :]
        mask = jnp.logical_and(mask, (k_pos <= q_pos)[None, None])
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows -> 0
    p = jnp.where(jnp.any(mask, axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8)
)
def flash_attention(
    q, k, v, kv_valid, causal: bool = True, scale: Optional[float] = None,
    block_q: int = 128, block_k: int = 128, interpret: bool = False,
):
    """Flash attention, [B,H,T,D] layout; K/V may carry fewer (grouped) heads.
    Differentiable: backward runs Pallas dq/dkv kernels recomputing attention
    per block from the saved logsumexp (O(T·block) memory, matching the memory
    model of the reference's fused CUDA kernels — SURVEY.md §2.4.5)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash_forward(q, k, v, kv_valid, causal, scale, block_q, block_k, interpret)


def _fwd(q, k, v, kv_valid, causal, scale, block_q, block_k, interpret):
    scale_ = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, lse = _flash_forward(
        q, k, v, kv_valid, causal, scale_, block_q, block_k, interpret, with_lse=True
    )
    return out, (q, k, v, kv_valid, out, lse)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, kv_valid, out, lse = res
    scale_ = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])

    if BACKWARD_IMPL == "pallas":
        dq, dk, dv = _flash_backward(
            q, k, v, kv_valid, out, lse, g, causal, scale_, block_q, block_k, interpret
        )
        return dq, dk, dv, None

    def ref(q, k, v):
        return xla_attention(q, k, v, kv_valid, causal, scale_)

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)


def flash_attention_sharded(
    q, k, v, kv_valid, causal: bool, scale: Optional[float],
    block_q: int, block_k: int, interpret: bool,
    mesh, batch_axes, head_axis,
):
    """SPMD placement for the flash kernel: Mosaic kernels cannot be
    auto-partitioned by XLA's SPMD pass (it raises at compile time on any
    multi-device mesh), so shard the embarrassingly-parallel grid axes
    explicitly — batch over ``batch_axes``, heads over ``head_axis`` — and run
    the kernel per shard inside a ``shard_map``. No cross-shard terms exist:
    each (batch, head) pair's softmax is independent, and the grouped-KV head
    map stays consistent because H_local/Hkv_local equals the global ratio
    when both divide the axis. Differentiable: autodiff enters the shard_map
    and applies the kernel's custom VJP per shard."""
    from jax.sharding import PartitionSpec as P

    def local(q, k, v, kv_valid):
        return flash_attention(
            q, k, v, kv_valid, causal, scale, block_q, block_k, interpret
        )

    # The map must be manual over EVERY mesh axis the SPMD partitioner would
    # otherwise see — a Mosaic op under any remaining auto axis (e.g. `pipe`
    # during stacked-decode prefill) still raises cannot-be-auto-partitioned.
    # When nested inside an enclosing shard_map (the GPipe stage body is manual
    # over `pipe`), the tracing context's AbstractMesh must be named instead of
    # the concrete mesh, and its already-manual axes must be excluded.
    # jax.shard_map (not the experimental alias) carries the axis_names param.
    from jax.sharding import get_abstract_mesh

    amesh = get_abstract_mesh()
    already_manual = set()
    if amesh is not None and amesh.axis_names:
        already_manual = {
            n for n, t in zip(amesh.axis_names, amesh.axis_types) if "Manual" in str(t)
        }
        mesh = amesh
    axes = set(mesh.axis_names) - already_manual
    # Spare manual axes beyond batch/heads (e.g. `pipe` during stacked-decode
    # prefill) stay UNNAMED in the specs: each of their shards computes its
    # replica. Redundant compute, but folding them into the batch entry
    # instead miscompiled — XLA's partitioner emitted an invalid dynamic-slice
    # over the pipe-sharded stacked layer params ("slice dim size 4096 greater
    # than dynamic slice dimension: 2048", v5e compiler, scripts/scale_proof.py)
    # — and prefill under a pipe mesh is a once-per-generation cost.
    batch_entry = tuple(batch_axes) if isinstance(batch_axes, tuple) else (batch_axes,)
    batch_entry = tuple(a for a in batch_entry if a in axes)
    head_entry = head_axis if head_axis in axes else None
    spec = P(batch_entry or None, head_entry, None, None)
    vspec = P(batch_entry or None, None)
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec, vspec), out_specs=spec,
        check_vma=False, axis_names=axes,
    )(q, k, v, kv_valid)
