"""Attention ops: a Pallas TPU flash-attention kernel + XLA reference.

The reference relies on external CUDA attention kernels (HF/NeMo, SURVEY.md §2.4.5);
this is the TPU-native equivalent. Forward is an online-softmax (FlashAttention-style)
Pallas kernel: grid = (batch, heads, q_blocks, kv_blocks) with the kv axis innermost —
TPU grids execute sequentially, so running max / denominator / accumulator live in
VMEM scratch across kv steps and the output tile is written once on the last step.
Causal blocks above the diagonal are skipped with ``@pl.when``. The backward pass
recomputes attention in XLA (memory-efficient forward is what matters for the rollout
path; training can additionally remat).

Masking model matches :mod:`trlx_tpu.models.transformer`: slot-based causality plus a
[B, S] key-validity mask (left-padded prompts). Engaged on every multi-token forward:
the training loss, the logprob/value scoring passes, and generation *prefill* (which
attends over the just-computed prefix k/v while the cache write happens separately).
Only single-token decode steps stay on the XLA path. Arbitrary T/S are supported via
internal padding + block selection (see ``_flash_forward``).
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_kernel(
    kv_valid_ref,  # [1, 1, 8, block_k] int32 (sublane-replicated, per kv block)
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    o_ref,  # [1, 1, block_q, D]
    m_scratch,  # [block_q, 1] f32
    l_scratch,  # [block_q, 1] f32
    acc_scratch,  # [block_q, D] f32
    *,
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    kv_steps: int,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)

    @pl.when(kj == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    # skip fully-masked blocks above the causal diagonal
    run = jnp.logical_or(
        jnp.logical_not(causal), kj * block_k <= qi * block_q + (block_q - 1)
    )

    @pl.when(run)
    def _step():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]

        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = kv_valid_ref[0, 0, 0][None, :] > 0
        if causal:
            mask = jnp.logical_and(mask, k_pos <= q_pos)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_scratch[...]  # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # fully-masked rows keep m == NEG_INF; exp(s - m) would be exp(0) = 1 there
        p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)  # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = alpha * l_scratch[...] + jnp.sum(p, axis=1, keepdims=True)
        acc_scratch[...] = acc_scratch[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scratch[...] = m_new
        l_scratch[...] = l_new

    @pl.when(kj == kv_steps - 1)
    def _finalize():
        l = l_scratch[...]
        # rows with no valid keys (fully masked) produce 0, not NaN
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, ...] = (acc_scratch[...] / safe_l).astype(o_ref.dtype)


def _pick_block(n: int, max_block: int) -> int:
    """Largest multiple-of-8 block <= max_block dividing ceil8(n) (min padding)."""
    n8 = -(-n // 8) * 8
    return max(b for b in range(8, min(max_block, n8) + 1, 8) if n8 % b == 0)


def _flash_forward(
    q: jnp.ndarray,  # [B, H, T, D]
    k: jnp.ndarray,  # [B, H, S, D]
    v: jnp.ndarray,
    kv_valid: jnp.ndarray,  # [B, S] int32
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
) -> jnp.ndarray:
    B, H, T, D = q.shape
    S = k.shape[2]
    # any T/S supported: pad to a sublane multiple and pick the largest block
    # (<= requested) that divides the padded length — e.g. T=144 (P16+R128) runs
    # at block 72 with no extra padding. Padded keys are masked via kv_valid;
    # padded query rows are sliced off. This lets the kernel cover prefill and
    # mixed P+R training shapes.
    block_q = _pick_block(T, block_q)
    block_k = _pick_block(S, block_k)
    pad_t = -T % block_q
    pad_s = -S % block_k
    if pad_t or pad_s:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_t), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_s), (0, 0)))
        kv_valid = jnp.pad(kv_valid, ((0, 0), (0, pad_s)))
    out = _flash_padded(q, k, v, kv_valid, causal, scale, block_q, block_k, interpret)
    return out[:, :, :T, :] if pad_t else out


def _flash_padded(q, k, v, kv_valid, causal, scale, block_q, block_k, interpret):
    B, H, T, D = q.shape
    S = k.shape[2]
    assert T % block_q == 0 and S % block_k == 0, (T, S, block_q, block_k)
    kv_steps = S // block_k
    grid = (B, H, T // block_q, kv_steps)

    # Mosaic tiling rules: a block's last dim must be a multiple of 128 or equal
    # the array's dim; its second-to-last a multiple of 8 or equal. A [B, S] mask
    # blocked (1, block_k) satisfies neither when block_k < 128 (observed as a
    # real-TPU lowering failure in round 2's bench — interpret mode on CPU never
    # checks). Reshape to [B, kv_steps, 8, block_k] (sublane-replicated): the
    # block (1, 1, 8, block_k) then tiles legally and costs 8·S int32 per row.
    kv_valid_tiled = jnp.broadcast_to(
        kv_valid.astype(jnp.int32).reshape(B, kv_steps, 1, block_k),
        (B, kv_steps, 8, block_k),
    )

    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        kv_steps=kv_steps,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, 8, block_k), lambda b, h, i, j: (b, j, 0, 0)),  # kv_valid
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, T, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(kv_valid_tiled, q, k, v)


def xla_attention(q, k, v, kv_valid, causal: bool, scale: float) -> jnp.ndarray:
    """Reference attention in plain XLA ([B,H,T,D] layout)."""
    s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    T, S = s.shape[-2], s.shape[-1]
    mask = kv_valid[:, None, None, :] > 0
    if causal:
        q_pos = jnp.arange(T)[:, None]
        k_pos = jnp.arange(S)[None, :]
        mask = jnp.logical_and(mask, (k_pos <= q_pos)[None, None])
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows -> 0
    p = jnp.where(jnp.any(mask, axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8)
)
def flash_attention(
    q, k, v, kv_valid, causal: bool = True, scale: Optional[float] = None,
    block_q: int = 128, block_k: int = 128, interpret: bool = False,
):
    """Flash attention, [B,H,T,D] layout. Differentiable: backward recomputes
    attention in XLA (forward stays O(T) memory for the rollout path)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash_forward(q, k, v, kv_valid, causal, scale, block_q, block_k, interpret)


def _fwd(q, k, v, kv_valid, causal, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, kv_valid, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, kv_valid)


def _bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, kv_valid = res
    scale_ = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])

    def ref(q, k, v):
        return xla_attention(q, k, v, kv_valid, causal, scale_)

    _, vjp = jax.vjp(ref, q, k, v)
    dq, dk, dv = vjp(g)
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)
