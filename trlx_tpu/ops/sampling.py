"""Token sampling ops: temperature / top-k / top-p, fully jittable.

The reference delegates sampling to HF ``generate`` (CUDA) — SURVEY.md §2.4.8 calls
the KV-cache generation loop "the single most performance-critical piece to build".
These are its logit-space pieces; the loop lives in :mod:`trlx_tpu.ops.generation`.
"""


import jax
import jax.numpy as jnp

NEG_INF = -1e9


def apply_temperature(logits: jnp.ndarray, temperature: float) -> jnp.ndarray:
    return logits / jnp.maximum(temperature, 1e-6)


def exact_top_k(logits: jnp.ndarray, k: int, num_groups: int = 16):
    """Exact top-k via two-stage grouped selection: ``(values, indices)``,
    bit-identical to ``jax.lax.top_k`` (same values, same indices, same
    smallest-index tie-breaks).

    ``lax.top_k`` lowers to a full-vocab variadic sort on TPU — O(V log V)
    work per decode step for k tokens of output. Stage 1 splits the vocab
    into ``num_groups`` contiguous groups and selects each group's top-k
    (sorting runs over V/G elements); stage 2 selects the global top-k over
    the G*k survivors. Exactness: every true top-k element is in its own
    group's top-k. Tie-order: the candidate list is group-major with groups
    in index order and within-group ties already index-ascending, so the
    stage-2 positional tie-break reproduces the global smallest-index rule.
    (Bench: gpt2 decode with exact top-k 50 went 37.9k -> ~approx-path
    throughput once the full-vocab sort left the step.)
    """
    V = logits.shape[-1]
    if k >= V:  # graftcheck: noqa[JX004] — static shape/int, not traced
        return jax.lax.top_k(logits, k)
    # keep groups comfortably larger than k so stage 2 stays tiny; degenerate
    # vocabs fall back to the single-stage primitive
    G = min(num_groups, max(1, V // max(1, 2 * k)))
    if G <= 1:  # graftcheck: noqa[JX004] — static shape/int, not traced
        return jax.lax.top_k(logits, k)
    g = -(-V // G)  # ceil(V / G)
    pad = G * g - V
    if pad:  # graftcheck: noqa[JX004] — static shape/int, not traced
        # -inf pads sit at the highest indices of the last group, so any
        # genuine value (even a NEG_INF-masked one) outranks them on ties
        logits = jnp.pad(
            logits, [(0, 0)] * (logits.ndim - 1) + [(0, pad)],
            constant_values=-jnp.inf,
        )
    grouped = logits.reshape(*logits.shape[:-1], G, g)
    gv, gi = jax.lax.top_k(grouped, k)  # [..., G, k]
    gi = gi + (jnp.arange(G, dtype=gi.dtype) * g)[:, None]  # group -> vocab index
    cand_v = gv.reshape(*gv.shape[:-2], G * k)
    cand_i = gi.reshape(*gi.shape[:-2], G * k)
    vals, pos = jax.lax.top_k(cand_v, k)
    return vals, jnp.take_along_axis(cand_i, pos, axis=-1)


def apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask everything below the k-th largest logit. k<=0 disables."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    kth = exact_top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest set of tokens with cumulative prob >= p.

    Implemented sort-free-gather style: sort descending, find cutoff, map back.
    p>=1 disables.
    """
    # p is a static Python float (bound via partial before jit), not a tracer
    if p >= 1.0:  # graftcheck: noqa[JX004]
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens whose *previous* cumulative mass is < p (always keep the top-1)
    keep_sorted = jnp.concatenate(
        [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < p], axis=-1
    )
    # threshold logit = smallest kept logit
    cutoff = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits < cutoff, NEG_INF, logits)


def _nucleus_keep(sorted_vals: jnp.ndarray, p: float) -> jnp.ndarray:
    """Boolean keep-mask over descending-sorted logits: the smallest prefix
    whose cumulative softmax mass reaches ``p`` (top-1 always kept). The ONE
    definition of the nucleus boundary — sample_token and apply_top_k_top_p
    must share it or their distributions silently diverge."""
    probs = jax.nn.softmax(sorted_vals, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    return jnp.concatenate(
        [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < p], axis=-1
    )


def apply_top_k_top_p(logits: jnp.ndarray, k: int, p: float) -> jnp.ndarray:
    """Fused top-k -> top-p: the nucleus cutoff is computed on the k already-
    sorted top-k values instead of a full-vocab sort (``lax.top_k`` is O(V)
    selection; the sort shrinks from V to k elements — V/k less sort work per
    decode step, e.g. 50257 -> 50 for gpt2 sampling defaults).

    Equivalent to ``apply_top_p(apply_top_k(logits, k), p)`` up to float
    rounding at the cumulative-mass boundary: absent ties at the k-th value
    the two paths keep the same nucleus *mathematically*, but they normalize
    softmax over different element counts (k here vs V after masking), so a
    boundary token whose cumulative mass lands within float eps of ``p`` can
    flip between the two (observed at |cum - p| ~ 1e-6 with k=256, p=0.999).
    With ties at the k-th value this cutoff normalizes over k values instead
    of k+ties, so it can be at most one probability bin stricter — a
    measure-zero event for real-valued model logits."""
    vals = exact_top_k(logits, k)[0]  # [.., k], sorted descending
    kth = vals[..., -1:]
    kept = jnp.where(logits < kth, NEG_INF, logits)
    if p >= 1.0:
        return kept
    keep_sorted = _nucleus_keep(vals, p)
    cutoff = jnp.min(jnp.where(keep_sorted, vals, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(kept < cutoff, NEG_INF, kept)


def sample_token(
    rng: jax.Array,
    logits: jnp.ndarray,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 1.0,
    do_sample: bool = True,
    top_k_impl: str = "approx",
) -> jnp.ndarray:
    """Sample (or argmax) next tokens from [B, V] logits -> [B] int32.

    When ``0 < top_k < V`` the whole top-k/top-p/categorical pipeline runs in
    the k-candidate space: select (vals, indices), nucleus-mask the k sorted
    values, draw categorical over k, gather the token id. With exact selection
    this is distribution-identical to masking the full-V logits and sampling
    (softmax is invariant to the NEG_INF entries) but removes every full-vocab
    pass after the selection itself — on chip the old full-V path cost 4.4x
    decode throughput at B=256/k=50 (bench `gpt2_rollout_new_tok_s_topk50_topp95`
    11.5k vs 51.0k tok/s plain).

    ``top_k_impl``: "approx" (default) selects candidates with
    ``jax.lax.approx_max_k`` — the TPU-native binned selection (per-candidate
    recall 0.95, then an exact top-k over the candidate bins); a true-top-k
    tail member is occasionally replaced by a near-tied neighbor, the same
    kind of truncation noise top-k sampling itself introduces (rollout
    logprobs are computed from the full softmax either way, exactly as the
    reference's HF top-k sampling does). "exact" uses :func:`exact_top_k`,
    the two-stage grouped selection bit-identical to ``jax.lax.top_k``.
    """
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = apply_temperature(logits.astype(jnp.float32), temperature)
    if 0 < top_k < logits.shape[-1]:
        if top_k_impl == "approx":
            vals, idx = jax.lax.approx_max_k(
                logits, top_k, recall_target=0.95, aggregate_to_topk=True
            )
        else:
            vals, idx = exact_top_k(logits, top_k)
        if top_p < 1.0:
            vals = jnp.where(_nucleus_keep(vals, top_p), vals, NEG_INF)
        choice = jax.random.categorical(rng, vals, axis=-1)
        return jnp.take_along_axis(idx, choice[..., None], axis=-1)[..., 0].astype(jnp.int32)
    logits = apply_top_p(logits, top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def count_accepted_drafts(
    sampled: jnp.ndarray, proposed: jnp.ndarray
) -> jnp.ndarray:
    """Leading-match accept count for speculative verify.

    ``proposed`` ``[B, K+1]`` is what the verify pass scored: the pending
    token followed by K draft tokens. ``sampled`` ``[B, K+1]`` is the
    per-position model output (position j is the model's choice *after*
    ``proposed[:, :j+1]``). Draft j+1 is accepted iff it equals what the
    model would have emitted at position j AND every earlier draft was
    accepted — the count is the length of the leading run of
    ``proposed[:, 1:] == sampled[:, :K]``, in ``[0, K]``. Greedy decode then
    emits ``sampled[:, :accepted+1]``, which is by construction the exact
    token sequence non-speculative decode produces one step at a time.
    """
    K = proposed.shape[1] - 1
    if K == 0:
        return jnp.zeros((proposed.shape[0],), jnp.int32)
    match = (proposed[:, 1:] == sampled[:, :K]).astype(jnp.int32)
    return jnp.sum(jnp.cumprod(match, axis=1), axis=1)


#: The sampling configuration graftcheck-ir's decode audit locks down: the
#: full temperature -> top-k -> top-p -> categorical pipeline, with the exact
#: top-k implementation so the compiled HLO is identical across backends
#: (``approx_max_k`` lowers to a TPU-specific custom call that would fork the
#: deviceless-CPU budget from the TPU artifact). Changing these changes the
#: audited decode graph — regenerate graftcheck-ir-budget.json alongside.
AUDIT_GEN_KWARGS = dict(temperature=0.7, top_k=50, top_p=0.95, top_k_impl="exact")
