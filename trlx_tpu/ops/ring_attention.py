"""Ring attention: sequence-parallel causal attention over a mesh axis.

The reference has NO context parallelism — its only sequence-dim scaling is Megatron
SP activation sharding (SURVEY.md §2.3 row CP: "absent — gap to fill natively").
This implements blockwise ring attention (cf. Liu et al., Ring Attention; the
scaling-book collective recipe): Q/K/V are sharded along the sequence dimension
across a mesh axis; each step every device computes a flash-style online-softmax
block against its current K/V shard, then rotates K/V one hop around the ring with
``jax.lax.ppermute`` over ICI. Peak memory per chip is O(S_local), enabling sequences
far beyond a single chip's HBM.

Causal structure at shard granularity: after ``step`` rotations device ``i`` holds
the K/V shard originally on device ``(i - step) mod n``; it contributes fully when
source < i, diagonally (within-shard causal) when source == i, and is skipped when
source > i.
"""

import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, m, l, acc, scale, mask):
    """One online-softmax accumulation step.

    q [B,H,Tq,D]; k/v [B,H,Tk,D]; m/l [B,H,Tq,1]; acc [B,H,Tq,D];
    mask bool broadcastable to [B,H,Tq,Tk] or None (True = attend)."""
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "model",
    causal: bool = True,
    scale: Optional[float] = None,
    kv_valid: Optional[jnp.ndarray] = None,
    batch_axes: Optional[Any] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention. q/k/v: [B, H, S, D] with S sharded over
    ``axis_name`` (batch dim sharded per ``batch_axes``, head dim replicated).
    ``kv_valid`` [B, S] masks out padding keys (left-padded prompts); it rides
    the ring alongside K/V. Returns the attention output sharded like q.

    Each step computes ONE online-softmax block: the shard-granularity causal
    structure (full / diagonal / skip) is folded into the block's mask instead of
    computing masked and unmasked variants and selecting afterwards."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis_name]
    if kv_valid is None:
        kv_valid = jnp.ones((q.shape[0], k.shape[2]), jnp.int32)

    def local_fn(q_loc, k_loc, v_loc, valid_loc):
        B, H, T, D = q_loc.shape
        my = jax.lax.axis_index(axis_name)
        tri = jnp.tril(jnp.ones((T, T), dtype=bool))

        def body(step, carry):
            k_cur, v_cur, valid_cur, m, l, acc = carry
            src = (my - step) % n
            # shard-granularity causal structure folded into one mask:
            # src < my -> attend fully; src == my -> within-shard causal;
            # src > my -> contribute nothing
            mask = valid_cur[:, None, None, :] > 0  # [B,1,1,Tk]
            if causal:
                shard_mask = jnp.logical_or(src < my, jnp.logical_and(src == my, tri))
                mask = jnp.logical_and(mask, shard_mask[None, None])
            m, l, acc = _block_attn(q_loc, k_cur, v_cur, m, l, acc, scale, mask)
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_next = jax.lax.ppermute(k_cur, axis_name, perm)
            v_next = jax.lax.ppermute(v_cur, axis_name, perm)
            valid_next = jax.lax.ppermute(valid_cur, axis_name, perm)
            return (k_next, v_next, valid_next, m, l, acc)

        m0 = jnp.full((B, H, T, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, T, 1), jnp.float32)
        acc0 = jnp.zeros((B, H, T, D), jnp.float32)
        _, _, _, m, l, acc = jax.lax.fori_loop(
            0, n, body, (k_loc, v_loc, valid_loc, m0, l0, acc0)
        )
        safe_l = jnp.where(l == 0.0, 1.0, l)
        return (acc / safe_l).astype(q_loc.dtype)

    spec = P(batch_axes, None, axis_name, None)
    vspec = P(batch_axes, axis_name)
    return shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec, vspec), out_specs=spec,
        check_rep=False,
    )(q, k, v, kv_valid.astype(jnp.int32))
