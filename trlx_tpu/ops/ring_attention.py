"""Ring attention: sequence-parallel causal attention over a mesh axis.

The reference has NO context parallelism — its only sequence-dim scaling is Megatron
SP activation sharding (SURVEY.md §2.3 row CP: "absent — gap to fill natively").
This implements blockwise ring attention (cf. Liu et al., Ring Attention; the
scaling-book collective recipe): Q/K/V are sharded along the sequence dimension
across a mesh axis; each step every device computes a flash-style online-softmax
block against its current K/V shard, then rotates K/V one hop around the ring with
``jax.lax.ppermute`` over ICI. Peak memory per chip is O(S_local), enabling sequences
far beyond a single chip's HBM.

Causal structure at shard granularity: after ``step`` rotations device ``i`` holds
the K/V shard originally on device ``(i - step) mod n``; it contributes fully when
source < i, diagonally (within-shard causal) when source == i, and is skipped when
source > i.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, m, l, acc, scale, mask):
    """One online-softmax accumulation step.

    q [B,H,Tq,D]; k/v [B,H,Tk,D]; m/l [B,H,Tq,1]; acc [B,H,Tq,D];
    mask [Tq,Tk] bool or None (True = attend)."""
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "model",
    causal: bool = True,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention. q/k/v: [B, H, S, D] with S sharded over
    ``axis_name`` (batch/head dims replicated or sharded elsewhere). Returns the
    attention output with the same sharding as q."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = mesh.shape[axis_name]

    def local_fn(q_loc, k_loc, v_loc):
        B, H, T, D = q_loc.shape
        my = jax.lax.axis_index(axis_name)
        tri = jnp.tril(jnp.ones((T, T), dtype=bool))

        def body(step, carry):
            k_cur, v_cur, m, l, acc = carry
            src = (my - step) % n
            # contribution mask at shard granularity
            full = src < my
            diag = src == my
            m2, l2, acc2 = _block_attn(
                q_loc, k_cur, v_cur, m, l, acc, scale,
                mask=tri if causal else None,
            )
            mf, lf, accf = _block_attn(q_loc, k_cur, v_cur, m, l, acc, scale, mask=None)
            if causal:
                use_diag = diag
                use_full = full
                m_new = jnp.where(use_diag, m2, jnp.where(use_full, mf, m))
                l_new = jnp.where(use_diag, l2, jnp.where(use_full, lf, l))
                acc_new = jnp.where(use_diag, acc2, jnp.where(use_full, accf, acc))
            else:
                m_new, l_new, acc_new = mf, lf, accf
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_next = jax.lax.ppermute(k_cur, axis_name, perm)
            v_next = jax.lax.ppermute(v_cur, axis_name, perm)
            return (k_next, v_next, m_new, l_new, acc_new)

        m0 = jnp.full((B, H, T, 1), NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, T, 1), jnp.float32)
        acc0 = jnp.zeros((B, H, T, D), jnp.float32)
        _, _, m, l, acc = jax.lax.fori_loop(0, n, body, (k_loc, v_loc, m0, l0, acc0))
        safe_l = jnp.where(l == 0.0, 1.0, l)
        return (acc / safe_l).astype(q_loc.dtype)

    spec = P(None, None, axis_name, None)
    return shard_map(
        local_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_rep=False
    )(q, k, v)
