"""Ring attention: sequence-parallel causal attention over a mesh axis.

The reference has NO context parallelism — its only sequence-dim scaling is Megatron
SP activation sharding (SURVEY.md §2.3 row CP: "absent — gap to fill natively").
This implements blockwise ring attention (cf. Liu et al., Ring Attention; the
scaling-book collective recipe): Q/K/V are sharded along the sequence dimension
across a mesh axis; each step every device computes a flash-style online-softmax
block against its current K/V shard, then rotates K/V one hop around the ring with
``jax.lax.ppermute`` over ICI. Peak memory per chip is O(S_local), enabling sequences
far beyond a single chip's HBM.

Training memory is O(S_local) too: a ``jax.custom_vjp`` saves only the local Q/K/V
shards, output, and per-row logsumexp, then the backward *re-runs the ring* —
recomputing P = exp(S - L) per visiting shard while dK/dV accumulators ride the ring
back to their home device (n rotations = identity). Without this, autodiff through
the fori_loop of ppermutes saved every step's rotated K/V (O(S_full) residuals per
device), defeating the point of the ring.

Causal structure at shard granularity: after ``step`` rotations device ``i`` holds
the K/V shard originally on device ``(i - step) mod n``; it contributes fully when
source < i, diagonally (within-shard causal) when source == i, and is skipped when
source > i.
"""

import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from trlx_tpu.parallel.mesh import MODEL_AXIS

NEG_INF = -1e30


def _block_attn(q, k, v, m, l, acc, scale, mask):
    """One online-softmax accumulation step.

    q [B,H,Tq,D]; k/v [B,H,Tk,D]; m/l [B,H,Tq,1]; acc [B,H,Tq,D];
    mask bool broadcastable to [B,H,Tq,Tk] or None (True = attend)."""
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m, m_cur)
    p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def _shard_mask(causal, src, my, valid_cur, tri):
    """Visiting-shard mask: key validity x shard-granularity causal structure."""
    mask = valid_cur[:, None, None, :] > 0  # [B,1,1,Tk]
    # static python bool: the branch specializes the trace, it never sees an array
    if causal:  # graftcheck: noqa[JX004]
        sm = jnp.logical_or(src < my, jnp.logical_and(src == my, tri))
        mask = jnp.logical_and(mask, sm[None, None])
    return mask


def _fold_q(x, Hkv):
    """Fold grouped query heads into the row axis: [B, Hkv*rep, T, ...] ->
    [B, Hkv, rep*T, ...] (row r*T + t ↔ query head k*rep+r at position t).
    K/V then stay at their native Hkv heads through every einsum and ppermute —
    no repeat, so GQA models move 1/rep of the ICI bytes per rotation."""
    B, H, T = x.shape[:3]
    rep = H // Hkv
    return x.reshape((B, Hkv, rep * T) + x.shape[3:]), rep


def _unfold_q(x, rep):
    B, Hkv, RT = x.shape[:3]
    T = RT // rep
    return x.reshape((B, Hkv * rep, T) + x.shape[3:])


def _ring_fwd_local(q_loc, k_loc, v_loc, valid_loc, *, axis_name, n, causal, scale):
    """Forward ring on local shards; returns (out, lse) with lse = m + log(l)."""
    B, H, T, D = q_loc.shape
    Hkv = k_loc.shape[1]
    q_loc, rep = _fold_q(q_loc, Hkv)
    my = jax.lax.axis_index(axis_name)
    tri = jnp.tril(jnp.ones((T, T), dtype=bool))
    # rep is shape-derived (static at trace time): specialization, not data branching
    if rep > 1:  # graftcheck: noqa[JX004]
        tri = jnp.tile(tri, (rep, 1))  # folded row r*T+t keeps position t's row

    def body(step, carry):
        k_cur, v_cur, valid_cur, m, l, acc = carry
        src = (my - step) % n
        mask = _shard_mask(causal, src, my, valid_cur, tri)
        m, l, acc = _block_attn(q_loc, k_cur, v_cur, m, l, acc, scale, mask)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        valid_next = jax.lax.ppermute(valid_cur, axis_name, perm)
        return (k_next, v_next, valid_next, m, l, acc)

    rows = q_loc.shape[2]
    m0 = jnp.full((B, Hkv, rows, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, rows, 1), jnp.float32)
    acc0 = jnp.zeros((B, Hkv, rows, D), jnp.float32)
    _, _, _, m, l, acc = jax.lax.fori_loop(
        0, n, body, (k_loc, v_loc, valid_loc, m0, l0, acc0)
    )
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = _unfold_q((acc / safe_l), rep).astype(q_loc.dtype)
    lse = jnp.where(l > 0.0, m + jnp.log(safe_l), NEG_INF)[..., 0]
    return out, _unfold_q(lse, rep)  # [B,H,T]


def _ring_bwd_local(q_loc, k_loc, v_loc, valid_loc, out_loc, lse_loc, g_loc,
                    *, axis_name, n, causal, scale):
    """Backward ring: dQ accumulates locally; dK/dV accumulators travel with
    their K/V shard and arrive home after the full circle of n rotations."""
    B, H, T, D = q_loc.shape
    Hkv = k_loc.shape[1]
    q_loc, rep = _fold_q(q_loc, Hkv)
    my = jax.lax.axis_index(axis_name)
    tri = jnp.tril(jnp.ones((T, T), dtype=bool))
    if rep > 1:
        tri = jnp.tile(tri, (rep, 1))
    g32 = _fold_q(g_loc, Hkv)[0].astype(jnp.float32)
    out32 = _fold_q(out_loc, Hkv)[0].astype(jnp.float32)
    lse = _fold_q(lse_loc, Hkv)[0][..., None]  # [B,Hkv,rep*T,1]
    lse_safe = jnp.where(lse > NEG_INF / 2, lse, 0.0)
    delta = jnp.sum(g32 * out32, axis=-1, keepdims=True)

    def body(step, carry):
        k_cur, v_cur, valid_cur, dk_cur, dv_cur, dq = carry
        src = (my - step) % n
        mask = _shard_mask(causal, src, my, valid_cur, tri)
        s = jnp.einsum(
            "bhtd,bhsd->bhts", q_loc.astype(jnp.float32), k_cur.astype(jnp.float32)
        ) * scale
        p = jnp.where(mask, jnp.exp(s - lse_safe), 0.0)  # [B,H,T,Tk]
        dv_cur = dv_cur + jnp.einsum("bhts,bhtd->bhsd", p, g32)
        dp = jnp.einsum("bhtd,bhsd->bhts", g32, v_cur.astype(jnp.float32))
        ds = p * (dp - delta) * scale
        dq = dq + jnp.einsum("bhts,bhsd->bhtd", ds, k_cur.astype(jnp.float32))
        dk_cur = dk_cur + jnp.einsum("bhts,bhtd->bhsd", ds, q_loc.astype(jnp.float32))
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        valid_next = jax.lax.ppermute(valid_cur, axis_name, perm)
        dk_next = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_next = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (k_next, v_next, valid_next, dk_next, dv_next, dq)

    zeros_kv = jnp.zeros((B, Hkv, T, D), jnp.float32)
    zeros_q = jnp.zeros(q_loc.shape, jnp.float32)
    _, _, _, dk, dv, dq = jax.lax.fori_loop(
        0, n, body, (k_loc, v_loc, valid_loc, zeros_kv, zeros_kv, zeros_q)
    )
    return (
        _unfold_q(dq, rep).astype(q_loc.dtype),
        dk.astype(k_loc.dtype),
        dv.astype(v_loc.dtype),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _ring_core(q, k, v, kv_valid, mesh, axis_name, causal, scale, batch_axes):
    out, _ = _ring_fwd_sharded(q, k, v, kv_valid, mesh, axis_name, causal, scale, batch_axes)
    return out


def _specs(axis_name, batch_axes):
    spec = P(batch_axes, None, axis_name, None)
    vspec = P(batch_axes, axis_name)
    rowspec = P(batch_axes, None, axis_name)
    return spec, vspec, rowspec


def _ring_fwd_sharded(q, k, v, kv_valid, mesh, axis_name, causal, scale, batch_axes):
    n = mesh.shape[axis_name]
    spec, vspec, rowspec = _specs(axis_name, batch_axes)
    fn = functools.partial(
        _ring_fwd_local, axis_name=axis_name, n=n, causal=causal, scale=scale
    )
    return shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec, vspec),
        out_specs=(spec, rowspec), check_rep=False,
    )(q, k, v, kv_valid)


def _ring_core_fwd(q, k, v, kv_valid, mesh, axis_name, causal, scale, batch_axes):
    out, lse = _ring_fwd_sharded(q, k, v, kv_valid, mesh, axis_name, causal, scale, batch_axes)
    # O(S_local) residuals per device: local shards + output + logsumexp only
    return out, (q, k, v, kv_valid, out, lse)


def _ring_core_bwd(mesh, axis_name, causal, scale, batch_axes, res, g):
    q, k, v, kv_valid, out, lse = res
    n = mesh.shape[axis_name]
    spec, vspec, rowspec = _specs(axis_name, batch_axes)
    fn = functools.partial(
        _ring_bwd_local, axis_name=axis_name, n=n, causal=causal, scale=scale
    )
    dq, dk, dv = shard_map(
        fn, mesh=mesh,
        in_specs=(spec, spec, spec, vspec, spec, rowspec, spec),
        out_specs=(spec, spec, spec), check_rep=False,
    )(q, k, v, kv_valid, out, lse, g)
    return dq, dk, dv, None


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = MODEL_AXIS,
    causal: bool = True,
    scale: Optional[float] = None,
    kv_valid: Optional[jnp.ndarray] = None,
    batch_axes: Optional[Any] = None,
) -> jnp.ndarray:
    """Sequence-parallel attention. q/k/v: [B, H, S, D] with S sharded over
    ``axis_name`` (batch dim sharded per ``batch_axes``, head dim replicated).
    K/V may carry fewer (grouped) heads than q: they ride the ring at their
    native head count (1/rep of the ICI bytes per rotation for GQA models).
    ``kv_valid`` [B, S] masks out padding keys (left-padded prompts); it rides
    the ring alongside K/V. Returns the attention output sharded like q.

    Each step computes ONE online-softmax block: the shard-granularity causal
    structure (full / diagonal / skip) is folded into the block's mask instead of
    computing masked and unmasked variants and selecting afterwards.

    Differentiable with O(S_local) training memory (see module docstring)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if kv_valid is None:
        kv_valid = jnp.ones((q.shape[0], k.shape[2]), jnp.int32)
    return _ring_core(
        q, k, v, kv_valid.astype(jnp.int32), mesh, axis_name, causal, scale,
        batch_axes,
    )
