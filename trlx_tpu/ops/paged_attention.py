"""Paged-KV decode attention: gather K/V through a block table.

The serving engine (``trlx_tpu/serving``) stores the KV cache as a pool of
fixed-size token blocks instead of one contiguous ``[B, Hkv, S, D]`` buffer
per sequence. Each decode slot addresses its tokens through a per-sequence
block table, so

- finished sequences release their blocks immediately (continuous batching
  never pays for the longest straggler's padding),
- shared prompt prefixes map to the *same* physical blocks (ref-counted by
  the allocator), and
- the attention for one step reads exactly ``context_len`` tokens per slot,
  not the padded maximum.

Two implementations with one contract:

- :func:`paged_attention_xla` — gather + masked softmax in plain XLA. The
  reference path: runs everywhere (CPU tests, deviceless AOT audit, SPMD
  meshes where a Mosaic kernel cannot be auto-partitioned).
- :func:`paged_attention_pallas` — a fused Pallas kernel that walks the block
  table via scalar prefetch (the table is read in BlockSpec index maps, so
  each grid step DMAs only its own block) and dequantizes int8 blocks
  in-register: the per-row scales fold into the scores (k) and the softmax
  probabilities (v), leaving the HBM stream a pure int8 load — the same
  algebra the dense decode path uses (models/transformer.py), so the two
  paths agree numerically.

Layouts (per layer):

- ``k_pool`` / ``v_pool``: ``[num_blocks, block_size, Hkv, D]`` in the cache
  dtype, or int8 under quantization,
- ``k_scale`` / ``v_scale``: ``[num_blocks, block_size, Hkv]`` f32 per-row
  scales (quantized layout only; scheme: :func:`quantize_kv_rows`),
- ``block_tables``: ``[B, max_blocks]`` int32 physical block ids,
- ``context_lens``: ``[B]`` int32 — valid tokens per slot INCLUDING the token
  written this step (so it is always >= 1 for any slot that ran the step;
  idle slots recycle the reserved null block and their output is discarded
  by the scheduler, but it must still be finite).

Block 0 is reserved by the allocator as the null block: unused block-table
entries point at it, keeping every gather in range without masking tricks.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from trlx_tpu.analysis.ir.entrypoints import EntryArtifacts, register_entrypoint

NEG_INF = -1e30  # kernel-internal mask value (f32 exact, like ops/attention.py)


def _group_query_heads(q: jnp.ndarray, kv_heads: int) -> jnp.ndarray:
    """[B, H, D] -> [B, Hkv, rep, D] so query head h maps to kv head h // rep."""
    B, H, D = q.shape
    return q.reshape(B, kv_heads, H // kv_heads, D)


def paged_attention_xla(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Reference path: gather each slot's blocks, mask, softmax in f32.

    q ``[B, H, D]`` (one decode token per slot); returns ``[B, H, D]`` in
    ``q.dtype``. Scales (when given) fold into scores/probs exactly as the
    Pallas kernel and the dense int8 decode path do.
    """
    B, H, D = q.shape
    NB, BS, Hkv, _ = k_pool.shape
    MB = block_tables.shape[1]
    S = MB * BS
    if scale is None:
        scale = 1.0 / math.sqrt(D)

    # [B, MB, BS, Hkv, D] -> [B, S, Hkv, D]; tables always in range (null block 0)
    kh = jnp.take(k_pool, block_tables, axis=0).reshape(B, S, Hkv, D)
    vh = jnp.take(v_pool, block_tables, axis=0).reshape(B, S, Hkv, D)
    qg = _group_query_heads(q, Hkv)

    scores = jnp.einsum(
        "bkrd,bskd->bkrs", qg, kh, preferred_element_type=jnp.float32
    ) * scale
    if k_scale is not None:
        ks = jnp.take(k_scale, block_tables, axis=0).reshape(B, S, Hkv)
        scores = scores * ks.transpose(0, 2, 1)[:, :, None, :]
    valid = jnp.arange(S)[None, :] < context_lens[:, None]  # [B, S]
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if v_scale is not None:
        vs = jnp.take(v_scale, block_tables, axis=0).reshape(B, S, Hkv)
        probs = probs * vs.transpose(0, 2, 1)[:, :, None, :]
    out = jnp.einsum(
        "bkrs,bskd->bkrd", probs, vh.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(B, H, D).astype(q.dtype)


def paged_verify_attention_xla(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Speculative-verify / chunked-prefill widening of the reference path.

    q ``[B, Q, H, D]`` — Q tokens appended per slot in one step, token ``j``
    sitting at position ``context_lens + j`` (``context_lens`` here = tokens
    present BEFORE this step's append, unlike the decode entry which gets the
    post-write count). Query ``j`` attends causally: positions
    ``< context_lens + j + 1``. Returns ``[B, Q, H, D]``.

    Q folds into the grouped-head row axis so the contraction is the same
    ``bkrd,bskd->bkrs`` einsum as the single-token path — masked scores sit at
    :data:`NEG_INF`, whose softmax probability underflows to exact 0, so
    stale/garbage KV past a slot's frontier contributes exactly nothing and
    ``Q == 1`` with ``context_lens = lens`` reproduces the decode step's
    output bit-for-bit.
    """
    B, Q, H, D = q.shape
    NB, BS, Hkv, _ = k_pool.shape
    MB = block_tables.shape[1]
    S = MB * BS
    rep = H // Hkv
    if scale is None:
        scale = 1.0 / math.sqrt(D)

    kh = jnp.take(k_pool, block_tables, axis=0).reshape(B, S, Hkv, D)
    vh = jnp.take(v_pool, block_tables, axis=0).reshape(B, S, Hkv, D)
    # [B, Q, H, D] -> [B, Hkv, Q*rep, D]; row r <-> (q_idx = r // rep, rep = r % rep)
    qg = (
        q.reshape(B, Q, Hkv, rep, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, Hkv, Q * rep, D)
    )

    scores = jnp.einsum(
        "bkrd,bskd->bkrs", qg, kh, preferred_element_type=jnp.float32
    ) * scale
    if k_scale is not None:
        ks = jnp.take(k_scale, block_tables, axis=0).reshape(B, S, Hkv)
        scores = scores * ks.transpose(0, 2, 1)[:, :, None, :]
    q_idx = jnp.arange(Q * rep, dtype=jnp.int32) // rep  # [Q*rep]
    valid = (
        jnp.arange(S, dtype=jnp.int32)[None, None, :]
        < context_lens[:, None, None] + q_idx[None, :, None] + 1
    )  # [B, Q*rep, S]
    scores = jnp.where(valid[:, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if v_scale is not None:
        vs = jnp.take(v_scale, block_tables, axis=0).reshape(B, S, Hkv)
        probs = probs * vs.transpose(0, 2, 1)[:, :, None, :]
    out = jnp.einsum(
        "bkrs,bskd->bkrd", probs, vh.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    out = (
        out.reshape(B, Hkv, Q, rep, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, Q, H, D)
    )
    return out.astype(q.dtype)


def _paged_kernel(
    tables_ref,  # scalar prefetch: [B, MB] int32
    lens_ref,  # scalar prefetch: [B] int32
    q_ref,  # [1, 1, rep, D]
    k_ref,  # [1, BS, 1, D]
    v_ref,
    ks_ref,  # [1, BS, 1] f32 or None (bound via partial when quantized)
    vs_ref,
    o_ref,  # [1, 1, rep, D]
    m_scratch,  # [rep, 1] f32
    l_scratch,  # [rep, 1] f32
    acc_scratch,  # [rep, D] f32
    *,
    block_size: int,
    num_blocks_per_seq: int,
    scale: float,
):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    q = q_ref[0, 0].astype(jnp.float32)  # [rep, D]
    k = k_ref[0, :, 0, :].astype(jnp.float32)  # [BS, D]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [rep, BS]
    if ks_ref is not None:
        s = s * ks_ref[0, :, 0][None, :]

    # token index of each row in this block; valid rows only
    token_idx = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1
    )
    s = jnp.where(token_idx < lens_ref[b], s, NEG_INF)

    m_prev = m_scratch[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    # fully-masked blocks keep m == NEG_INF; exp(s - m) would be exp(0) there
    p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)  # [rep, BS]
    alpha = jnp.exp(m_prev - m_new)
    l_scratch[...] = alpha * l_scratch[...] + jnp.sum(p, axis=1, keepdims=True)
    if vs_ref is not None:
        p = p * vs_ref[0, :, 0][None, :]
    v = v_ref[0, :, 0, :].astype(jnp.float32)  # [BS, D]
    acc_scratch[...] = acc_scratch[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_scratch[...] = m_new

    @pl.when(j == num_blocks_per_seq - 1)
    def _finalize():
        l = l_scratch[...]
        safe_l = jnp.where(l == 0.0, 1.0, l)  # lens >= 1, but never NaN anyway
        o_ref[0, 0, ...] = (acc_scratch[...] / safe_l).astype(o_ref.dtype)


def _paged_verify_kernel(
    tables_ref,  # scalar prefetch: [B, MB] int32
    lens_ref,  # scalar prefetch: [B] int32 (tokens present BEFORE the append)
    q_ref,  # [1, 1, Q*rep, D]
    k_ref,  # [1, BS, 1, D]
    v_ref,
    ks_ref,  # [1, BS, 1] f32 or None (bound via partial when quantized)
    vs_ref,
    o_ref,  # [1, 1, Q*rep, D]
    m_scratch,  # [Q*rep, 1] f32
    l_scratch,  # [Q*rep, 1] f32
    acc_scratch,  # [Q*rep, D] f32
    *,
    block_size: int,
    num_blocks_per_seq: int,
    scale: float,
    rep: int,
):
    """Verify variant of :func:`_paged_kernel`: the Q query positions fold
    into the row axis (row r is query ``r // rep``, query-head ``r % rep``) so
    the per-block flash update is unchanged — only the mask limit becomes
    per-row: query j sees tokens ``< lens + j + 1``. Kept separate from the
    decode kernel so the ``spec_k == 0`` hot path stays byte-identical.
    """
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    q = q_ref[0, 0].astype(jnp.float32)  # [Q*rep, D]
    k = k_ref[0, :, 0, :].astype(jnp.float32)  # [BS, D]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [Q*rep, BS]
    if ks_ref is not None:
        s = s * ks_ref[0, :, 0][None, :]

    rows = q.shape[0]
    token_idx = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (rows, block_size), 1
    )
    q_idx = jax.lax.broadcasted_iota(jnp.int32, (rows, block_size), 0) // rep
    s = jnp.where(token_idx < lens_ref[b] + q_idx + 1, s, NEG_INF)

    m_prev = m_scratch[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.where(m_new > NEG_INF / 2, jnp.exp(s - m_new), 0.0)  # [Q*rep, BS]
    alpha = jnp.exp(m_prev - m_new)
    l_scratch[...] = alpha * l_scratch[...] + jnp.sum(p, axis=1, keepdims=True)
    if vs_ref is not None:
        p = p * vs_ref[0, :, 0][None, :]
    v = v_ref[0, :, 0, :].astype(jnp.float32)  # [BS, D]
    acc_scratch[...] = acc_scratch[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    m_scratch[...] = m_new

    @pl.when(j == num_blocks_per_seq - 1)
    def _finalize():
        l = l_scratch[...]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, ...] = (acc_scratch[...] / safe_l).astype(o_ref.dtype)


def _drop_scale_refs(kernel):
    """Adapter for the unquantized layout: same kernel, no scale operands."""

    @functools.wraps(kernel)
    def wrapped(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref, *scratch):
        return kernel(
            tables_ref, lens_ref, q_ref, k_ref, v_ref, None, None, o_ref, *scratch
        )

    return wrapped


def paged_attention_pallas(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused kernel: grid ``(B, Hkv, max_blocks)``, block table scalar-prefetched
    so each step's BlockSpec index map selects the physical block to DMA —
    the gather never materializes ``[B, S, Hkv, D]`` in HBM, and int8 blocks
    dequantize in-register via score/prob scale folding.
    """
    B, H, D = q.shape
    NB, BS, Hkv, _ = k_pool.shape
    MB = block_tables.shape[1]
    rep = H // Hkv
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    quant = k_scale is not None

    qg = _group_query_heads(q, Hkv)  # [B, Hkv, rep, D]
    kernel = functools.partial(
        _paged_kernel, block_size=BS, num_blocks_per_seq=MB, scale=scale
    )
    if not quant:
        kernel = _drop_scale_refs(kernel)

    # index maps receive (*grid, *scalar_prefetch_refs)
    q_spec = pl.BlockSpec((1, 1, rep, D), lambda b, h, j, t, n: (b, h, 0, 0))
    kv_spec = pl.BlockSpec((1, BS, 1, D), lambda b, h, j, t, n: (t[b, j], 0, h, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [qg, k_pool, v_pool]
    if quant:
        sc_spec = pl.BlockSpec((1, BS, 1), lambda b, h, j, t, n: (t[b, j], 0, h))
        in_specs += [sc_spec, sc_spec]
        inputs += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rep, D), lambda b, h, j, t, n: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, D), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32), *inputs)
    return out.reshape(B, H, D)


def paged_verify_attention_pallas(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused verify kernel: same ``(B, Hkv, max_blocks)`` grid and scalar-
    prefetched block walk as :func:`paged_attention_pallas`, with the Q query
    positions folded into the row axis of each grid cell (``[Q*rep, D]``
    tiles). ``context_lens`` = tokens present BEFORE the append.
    """
    B, Q, H, D = q.shape
    NB, BS, Hkv, _ = k_pool.shape
    MB = block_tables.shape[1]
    rep = H // Hkv
    rows = Q * rep
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    quant = k_scale is not None

    qg = (
        q.reshape(B, Q, Hkv, rep, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, Hkv, rows, D)
    )
    kernel = functools.partial(
        _paged_verify_kernel,
        block_size=BS, num_blocks_per_seq=MB, scale=scale, rep=rep,
    )
    if not quant:
        kernel = _drop_scale_refs(kernel)

    q_spec = pl.BlockSpec((1, 1, rows, D), lambda b, h, j, t, n: (b, h, 0, 0))
    kv_spec = pl.BlockSpec((1, BS, 1, D), lambda b, h, j, t, n: (t[b, j], 0, h, 0))
    in_specs = [q_spec, kv_spec, kv_spec]
    inputs = [qg, k_pool, v_pool]
    if quant:
        sc_spec = pl.BlockSpec((1, BS, 1), lambda b, h, j, t, n: (t[b, j], 0, h))
        in_specs += [sc_spec, sc_spec]
        inputs += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, MB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rows, D), lambda b, h, j, t, n: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rows, D), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32), *inputs)
    return (
        out.reshape(B, Hkv, Q, rep, D)
        .transpose(0, 2, 1, 3, 4)
        .reshape(B, Q, H, D)
    )


def paged_decode_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Dispatch: ``impl`` in {"auto", "pallas", "xla"}.

    "auto" picks the kernel on a single-device TPU backend and the XLA
    gather path everywhere else — a Mosaic kernel cannot be auto-partitioned
    by XLA SPMD, and on CPU interpret mode would only emulate it (the XLA
    path IS the CPU-native implementation; the kernel still runs under
    ``interpret=True`` in tests to prove parity).
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" and jax.device_count() == 1 else "xla"
    if impl == "pallas":
        return paged_attention_pallas(
            q, k_pool, v_pool, block_tables, context_lens,
            k_scale=k_scale, v_scale=v_scale, scale=scale,
            interpret=jax.default_backend() == "cpu",
        )
    if impl == "xla":
        return paged_attention_xla(
            q, k_pool, v_pool, block_tables, context_lens,
            k_scale=k_scale, v_scale=v_scale, scale=scale,
        )
    raise ValueError(f"unknown paged attention impl {impl!r}")


def paged_verify_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Multi-token dispatch, same ``impl`` policy as
    :func:`paged_decode_attention`. q is ``[B, Q, H, D]``; ``context_lens``
    counts tokens present BEFORE the Q-token append.
    """
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" and jax.device_count() == 1 else "xla"
    if impl == "pallas":
        return paged_verify_attention_pallas(
            q, k_pool, v_pool, block_tables, context_lens,
            k_scale=k_scale, v_scale=v_scale, scale=scale,
            interpret=jax.default_backend() == "cpu",
        )
    if impl == "xla":
        return paged_verify_attention_xla(
            q, k_pool, v_pool, block_tables, context_lens,
            k_scale=k_scale, v_scale=v_scale, scale=scale,
        )
    raise ValueError(f"unknown paged attention impl {impl!r}")


def write_paged_kv(
    cache: dict, k_new: jnp.ndarray, v_new: jnp.ndarray
) -> dict:
    """Write one token's K/V per slot into the block pool.

    ``cache`` is one layer's paged cache: pools plus the shared
    ``block_tables`` / ``context_lens`` (lens here = tokens already present,
    i.e. the write position of the incoming token). ``k_new``/``v_new`` are
    ``[B, Hkv, D]``. Quantizes when the layer carries scale pools (same
    per-row scheme as the contiguous cache: ``quantize_kv_rows``).

    Distinct live slots always write distinct physical slots (the allocator
    never lets a write frontier sit in a shared block); idle slots all write
    the reserved null block 0, whose contents are never read as valid.
    """
    from trlx_tpu.models.transformer import quantize_kv_rows

    k_pool = cache["k"]
    NB, BS, Hkv, D = k_pool.shape
    lens = cache["context_lens"]
    bt = cache["block_tables"]
    block = jnp.take_along_axis(bt, (lens // BS)[:, None], axis=1)[:, 0]
    slot = block * BS + lens % BS  # [B] flat row in the (NB*BS) pool

    def scatter(pool, rows):
        flat = pool.reshape(NB * BS, *pool.shape[2:])
        return flat.at[slot].set(rows.astype(pool.dtype)).reshape(pool.shape)

    out = dict(cache)
    if "k_scale" in cache:
        kq, ks = quantize_kv_rows(k_new)
        vq, vs = quantize_kv_rows(v_new)
        out["k"] = scatter(cache["k"], kq)
        out["v"] = scatter(cache["v"], vq)
        out["k_scale"] = scatter(cache["k_scale"], ks[..., 0])
        out["v_scale"] = scatter(cache["v_scale"], vs[..., 0])
    else:
        out["k"] = scatter(cache["k"], k_new)
        out["v"] = scatter(cache["v"], v_new)
    return out


def write_paged_kv_multi(
    cache: dict, k_new: jnp.ndarray, v_new: jnp.ndarray
) -> dict:
    """Write Q tokens' K/V per slot: ``k_new``/``v_new`` ``[B, Q, Hkv, D]``,
    token ``j`` landing at position ``context_lens + j`` through the slot's
    block table (lens = tokens already present, as in :func:`write_paged_kv`).

    Positions past the table's reach (``>= max_blocks * block_size``) are
    dropped outright and positions whose table entry is the padding 0 land in
    the reserved null block — the engine only ever *validates* positions it
    reserved real blocks for, and any position is rewritten before the
    attention mask can expose it, so overflow writes are harmless garbage.
    Quantization matches the single-token path row-for-row
    (:func:`quantize_kv_rows` per ``[Hkv, D]`` row), which is what keeps the
    speculative path bit-identical to non-speculative greedy decode.
    """
    from trlx_tpu.models.transformer import quantize_kv_rows

    k_pool = cache["k"]
    NB, BS, Hkv, D = k_pool.shape
    B, Q = k_new.shape[:2]
    lens = cache["context_lens"]
    bt = cache["block_tables"]
    MB = bt.shape[1]
    pos = lens[:, None] + jnp.arange(Q, dtype=lens.dtype)[None, :]  # [B, Q]
    pos_c = jnp.clip(pos, 0, MB * BS - 1)
    blk = jnp.take_along_axis(bt, pos_c // BS, axis=1)  # [B, Q]
    # out-of-table positions get an out-of-range flat index; mode="drop" below
    flat = jnp.where(pos < MB * BS, blk * BS + pos_c % BS, NB * BS).reshape(-1)

    def scatter(pool, rows):
        vals = rows.reshape(B * Q, *rows.shape[2:]).astype(pool.dtype)
        return (
            pool.reshape(NB * BS, *pool.shape[2:])
            .at[flat].set(vals, mode="drop")
            .reshape(pool.shape)
        )

    out = dict(cache)
    if "k_scale" in cache:
        kq, ks = quantize_kv_rows(k_new.reshape(B * Q, Hkv, D))
        vq, vs = quantize_kv_rows(v_new.reshape(B * Q, Hkv, D))
        out["k"] = scatter(cache["k"], kq.reshape(B, Q, Hkv, D))
        out["v"] = scatter(cache["v"], vq.reshape(B, Q, Hkv, D))
        out["k_scale"] = scatter(cache["k_scale"], ks[..., 0].reshape(B, Q, Hkv))
        out["v_scale"] = scatter(cache["v_scale"], vs[..., 0].reshape(B, Q, Hkv))
    else:
        out["k"] = scatter(cache["k"], k_new)
        out["v"] = scatter(cache["v"], v_new)
    return out


def paged_pool_layout(
    num_blocks: int, block_size: int, kv_heads: int, dim_per_head: int,
    dtype, quant: bool,
) -> dict:
    """Per-layer pool buffers as ``{key: (shape, dtype)}`` (mirror of the
    contiguous ``kv_cache_layout``)."""
    shape = (num_blocks, block_size, kv_heads, dim_per_head)
    if quant:
        return {
            "k": (shape, jnp.int8), "v": (shape, jnp.int8),
            "k_scale": (shape[:-1], jnp.float32),
            "v_scale": (shape[:-1], jnp.float32),
        }
    return {"k": (shape, dtype), "v": (shape, dtype)}


# -- AOT audit surface (graftcheck-ir) ----------------------------------------


@register_entrypoint("paged_decode_step", specs=("small",))
def build_paged_decode_step(spec: str, mesh) -> EntryArtifacts:
    """The serving engine's steady-state decode step as graftcheck-ir audits
    it: one token per slot through ``TransformerLM.paged_decode`` (paged-KV
    write + paged attention per layer) followed by the pinned sampling
    pipeline (:data:`trlx_tpu.ops.sampling.AUDIT_GEN_KWARGS`) — the jitted
    callable :class:`trlx_tpu.serving.engine.ServingEngine` runs every step.

    Audited with the XLA gather path (the deviceless CPU lowering cannot
    build a Mosaic artifact, and under the multi-device audit mesh the
    dispatch picks XLA anyway), int8-KV layout — the bandwidth-bound
    configuration the engine targets.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from trlx_tpu.models.presets import PRESETS
    from trlx_tpu.models.transformer import TransformerLM
    from trlx_tpu.ops.sampling import AUDIT_GEN_KWARGS, sample_token
    from trlx_tpu.parallel.mesh import BATCH_AXES
    from trlx_tpu.parallel.sharding import make_param_shardings

    dims = dict(hidden=64, layers=2, heads=4, vocab=256, B=8,
                num_blocks=24, block_size=8, max_blocks=4)
    model_config = PRESETS["gpt2"].replace(
        vocab_size=dims["vocab"], hidden_size=dims["hidden"],
        num_layers=dims["layers"], num_heads=dims["heads"],
        intermediate_size=4 * dims["hidden"], max_position_embeddings=1024,
        param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
        kv_cache_quant=True,
    )
    trunk = TransformerLM(model_config)

    params_shape = jax.eval_shape(
        lambda: trunk.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32), jnp.ones((1, 2), jnp.int32)
        )
    )["params"]
    abs_params = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        params_shape, make_param_shardings(params_shape, mesh),
    )

    B = dims["B"]
    NB, BS, MB = dims["num_blocks"], dims["block_size"], dims["max_blocks"]
    kvh, dph = model_config.kv_heads, model_config.dim_per_head
    repl = NamedSharding(mesh, PartitionSpec())
    bsh = NamedSharding(mesh, PartitionSpec(BATCH_AXES))
    layout = paged_pool_layout(NB, BS, kvh, dph, model_config.compute_dtype, True)
    abs_cache = {
        key: [jax.ShapeDtypeStruct(shp, dt, sharding=repl)
              for _ in range(dims["layers"])]
        for key, (shp, dt) in layout.items()
    }
    abs_cache["block_tables"] = jax.ShapeDtypeStruct((B, MB), jnp.int32, sharding=bsh)
    abs_cache["context_lens"] = jax.ShapeDtypeStruct((B,), jnp.int32, sharding=bsh)
    abs_tok = jax.ShapeDtypeStruct((B,), jnp.int32, sharding=bsh)
    abs_rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))

    def decode_fn(params, tok, cache, rng):
        logits, _, new_cache = trunk.apply(
            {"params": params}, tok[:, None], cache, method=trunk.paged_decode
        )
        next_tok = sample_token(rng, logits[:, -1, :], **AUDIT_GEN_KWARGS)
        return next_tok, new_cache

    # output cache shardings must equal the input's for the donated pool
    # buffers to alias (IR002); leaving them to inference breaks the aliasing
    cache_out_shardings = jax.tree.map(lambda _: repl, abs_cache)
    cache_out_shardings["block_tables"] = bsh
    cache_out_shardings["context_lens"] = bsh

    return EntryArtifacts(
        fn=decode_fn,
        args=(abs_params, abs_tok, abs_cache, abs_rng),
        donate_argnums=(2,),
        out_shardings=(bsh, cache_out_shardings),
        compute_dtype="bfloat16",
        # the paged-attention reference accumulates scores and probs@V in f32
        # (preferred_element_type, flash-kernel algebra): 2 dots/layer
        f32_allow=frozenset({"dot_general:4"}),
        meta=dict(batch=B, num_blocks=NB, block_size=BS,
                  hidden_size=dims["hidden"], num_layers=dims["layers"]),
    )


@register_entrypoint("spec_verify_step", specs=("small", "xl"))
def build_spec_verify_step(spec: str, mesh) -> EntryArtifacts:
    """The speculative-verify round as graftcheck-ir audits it: ``K + 1``
    tokens per slot (pending token + K n-gram drafts) through
    ``TransformerLM.paged_verify`` — multi-position paged-KV write + the
    widened verify attention — then per-position sampling and the on-device
    accept count, exactly the jitted ``_verify_step`` the serving engine runs
    when ``serving.spec_k > 0``.

    ``small`` mirrors ``paged_decode_step``'s dims (int8-KV, per-layer pool
    lists) and is what CI compiles and gates against the budget. ``xl`` is
    the GPT-2-XL blueprint — scanned layers over *stacked* ``[L, ...]``
    pools — and exists to be lowered deviceless so paged/speculative decode
    evidence reaches past gpt2-small (ROADMAP big-model item).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from trlx_tpu.models.presets import PRESETS
    from trlx_tpu.models.transformer import TransformerLM
    from trlx_tpu.ops.sampling import (
        AUDIT_GEN_KWARGS, count_accepted_drafts, sample_token,
    )
    from trlx_tpu.parallel.mesh import BATCH_AXES
    from trlx_tpu.parallel.sharding import make_param_shardings

    dims = {
        "small": dict(hidden=64, layers=2, heads=4, vocab=256, B=8,
                      num_blocks=24, block_size=8, max_blocks=4, spec_k=4,
                      scan_layers=False),
        # GPT-2-XL shapes (~1.5B params), scanned layers + stacked pools
        "xl": dict(hidden=1600, layers=48, heads=25, vocab=50257, B=8,
                   num_blocks=64, block_size=16, max_blocks=16, spec_k=4,
                   scan_layers=True),
    }[spec]
    model_config = PRESETS["gpt2"].replace(
        vocab_size=dims["vocab"], hidden_size=dims["hidden"],
        num_layers=dims["layers"], num_heads=dims["heads"],
        intermediate_size=4 * dims["hidden"], max_position_embeddings=1024,
        param_dtype=jnp.float32, compute_dtype=jnp.bfloat16,
        kv_cache_quant=True, scan_layers=dims["scan_layers"],
    )
    trunk = TransformerLM(model_config)

    params_shape = jax.eval_shape(
        lambda: trunk.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2), jnp.int32), jnp.ones((1, 2), jnp.int32)
        )
    )["params"]
    abs_params = jax.tree.map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        params_shape, make_param_shardings(params_shape, mesh),
    )

    B, K = dims["B"], dims["spec_k"]
    NB, BS, MB = dims["num_blocks"], dims["block_size"], dims["max_blocks"]
    kvh, dph = model_config.kv_heads, model_config.dim_per_head
    repl = NamedSharding(mesh, PartitionSpec())
    bsh = NamedSharding(mesh, PartitionSpec(BATCH_AXES))
    bsh2 = NamedSharding(mesh, PartitionSpec(BATCH_AXES, None))
    layout = paged_pool_layout(NB, BS, kvh, dph, model_config.compute_dtype, True)
    if dims["scan_layers"]:
        abs_cache = {
            key: jax.ShapeDtypeStruct((dims["layers"],) + shp, dt, sharding=repl)
            for key, (shp, dt) in layout.items()
        }
    else:
        abs_cache = {
            key: [jax.ShapeDtypeStruct(shp, dt, sharding=repl)
                  for _ in range(dims["layers"])]
            for key, (shp, dt) in layout.items()
        }
    abs_cache["block_tables"] = jax.ShapeDtypeStruct((B, MB), jnp.int32, sharding=bsh2)
    abs_cache["context_lens"] = jax.ShapeDtypeStruct((B,), jnp.int32, sharding=bsh)
    abs_tok = jax.ShapeDtypeStruct((B, K + 1), jnp.int32, sharding=bsh2)
    abs_rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))

    def verify_fn(params, tok, cache, rng):
        lens0 = cache["context_lens"]
        logits, _, new_cache = trunk.apply(
            {"params": params}, tok, cache, method=trunk.paged_verify
        )
        y = sample_token(rng, logits, **AUDIT_GEN_KWARGS)  # [B, K+1]
        accepted = count_accepted_drafts(y, tok)
        new_cache["context_lens"] = lens0 + accepted + 1
        return y, accepted, new_cache

    cache_out_shardings = jax.tree.map(lambda _: repl, abs_cache)
    cache_out_shardings["block_tables"] = bsh2
    cache_out_shardings["context_lens"] = bsh

    return EntryArtifacts(
        fn=verify_fn,
        args=(abs_params, abs_tok, abs_cache, abs_rng),
        donate_argnums=(2,),
        out_shardings=(bsh2, bsh, cache_out_shardings),
        compute_dtype="bfloat16",
        # verify attention accumulates scores and probs@V in f32 like the
        # decode step: 2 dots/layer
        f32_allow=frozenset({"dot_general:4"}),
        meta=dict(batch=B, spec_k=K, num_blocks=NB, block_size=BS,
                  hidden_size=dims["hidden"], num_layers=dims["layers"]),
    )
