from trlx_tpu.ops.generation import generate, left_pad_batch, pad_to_bucket
from trlx_tpu.ops.sampling import sample_token
