"""Branch benchmark-comparison harness (capability parity with
`/root/reference/trlx/reference.py:1-103` + `scripts/benchmark.sh`).

The reference clones two git revisions, runs the benchmark suite on each, and builds
a W&B report keyed by repo tree-hash. Here: run the deterministic benchmark workloads
on the current checkout, record metrics keyed by ``git rev-parse HEAD^{tree}``, and
compare against a previously recorded baseline file.

Usage:
    python -m trlx_tpu.reference run  --output runs/bench_<hash>.json
    python -m trlx_tpu.reference diff runs/bench_a.json runs/bench_b.json
"""

import argparse
import json
import subprocess
import sys
import time


def tree_hash() -> str:
    try:
        return subprocess.check_output(["git", "rev-parse", "HEAD^{tree}"], text=True).strip()
    except Exception:
        return "unknown"


def run_suite(output: str, rev: str = None):
    """Run bench.py (the randomwalks PPO workload) and store its metric.

    With ``rev``, the suite runs against that git revision in a temporary
    worktree — the local counterpart of the reference's clone-two-branches
    benchmark (`trlx/reference.py:34-49`, `scripts/benchmark.sh`)."""
    import os
    import shutil
    import tempfile

    cwd = os.getcwd()
    worktree = None
    try:
        if rev:
            safe = rev[:12].replace("/", "-")
            worktree = tempfile.mkdtemp(prefix=f"trlx_bench_{safe}_")
            added = subprocess.run(
                ["git", "worktree", "add", "--detach", worktree, rev],
                capture_output=True, text=True,
            )
            if added.returncode != 0:
                raise RuntimeError(f"git worktree add {rev!r} failed: {added.stderr.strip()}")
            cwd = worktree
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, "bench.py"], capture_output=True, text=True, cwd=cwd
        )
        metrics = {}
        for line in reversed(proc.stdout.splitlines()):
            try:
                metrics = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
        try:
            th = subprocess.check_output(
                ["git", "rev-parse", f"{rev}^{{tree}}" if rev else "HEAD^{tree}"], text=True
            ).strip()
        except Exception:
            th = "unknown"
        record = {
            "rev": rev or "HEAD",
            "tree_hash": th,
            "time": time.time(),
            "seconds": round(time.time() - t0, 1),
            "metrics": metrics,
            "returncode": proc.returncode,
        }
    finally:
        if worktree:
            subprocess.run(["git", "worktree", "remove", "--force", worktree],
                           capture_output=True)
            shutil.rmtree(worktree, ignore_errors=True)
    with open(output, "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    return record


def diff(a_path: str, b_path: str):
    a = json.load(open(a_path))
    b = json.load(open(b_path))
    ma, mb = a.get("metrics", {}), b.get("metrics", {})
    if "value" in ma and "value" in mb:
        ratio = mb["value"] / ma["value"] if ma["value"] else float("nan")
        print(
            f"{ma.get('metric')}: {ma['value']} ({a['tree_hash'][:8]}) -> "
            f"{mb['value']} ({b['tree_hash'][:8]})  x{ratio:.3f}"
        )
    else:
        print("incomparable records", ma, mb)


def main():
    parser = argparse.ArgumentParser()
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_run = sub.add_parser("run")
    p_run.add_argument("--output", default=None)
    p_run.add_argument("--rev", default=None, help="git revision to benchmark in a temp worktree")
    p_diff = sub.add_parser("diff")
    p_diff.add_argument("a")
    p_diff.add_argument("b")
    p_cmp = sub.add_parser("compare", help="benchmark HEAD and REV, then diff")
    p_cmp.add_argument("rev")
    args = parser.parse_args()
    if args.cmd == "run":
        out = args.output or f"bench_{tree_hash()[:12]}.json"
        run_suite(out, rev=args.rev)
    elif args.cmd == "compare":
        run_suite("bench_rev.json", rev=args.rev)
        run_suite("bench_head.json")
        diff("bench_rev.json", "bench_head.json")
    else:
        diff(args.a, args.b)


if __name__ == "__main__":
    main()
