"""Bounded online experience buffer: labeled completion groups with version tags.

The collector (``online/collector.py``) produces :class:`LabeledGroup`\\ s —
one prompt, G scored completions, stamped with the serving policy version
that generated them — and the GRPO trainer drains them on each experience
refill. The buffer sits between two clocks (fleet traffic arrives at
serving rate, the learner consumes at training rate), so it is *bounded*:
past ``capacity`` the oldest group is evicted, because in an online loop
old experience is the cheapest thing to lose. Staleness is enforced at
drain time through the same :class:`~trlx_tpu.rollout.staleness.\
StalenessAccountant` the async PPO path uses — a ``LabeledGroup`` carries
``policy_version`` exactly like a ``PPORLElement`` does, so the admission
cap and its gauges need no new machinery.

Gauges: ``online/buffer_depth``, ``online/buffer_evicted``,
``online/dropped_stale`` (docs/online.md).
"""

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from trlx_tpu.rollout.staleness import StalenessAccountant
from trlx_tpu.utils.metrics import gauges


@dataclass
class LabeledGroup:
    """One scored completion group: GRPO's unit of experience.

    ``completions`` are token-id lists (ragged — padding happens at scoring
    time in the trainer); ``scores`` aligns with them. ``policy_version`` is
    the serving version that generated the group (staleness admission keys
    on it); ``uids`` keeps the originating request uids for exactly-once
    audits."""

    prompt: List[int]
    completions: List[List[int]]
    scores: np.ndarray
    policy_version: int = 0
    uids: Tuple[int, ...] = ()
    meta: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self):
        self.scores = np.asarray(self.scores, dtype=np.float32)
        if len(self.completions) != self.scores.size:
            raise ValueError(
                f"scores ({self.scores.size}) must align with completions "
                f"({len(self.completions)})"
            )

    @property
    def group_size(self) -> int:
        return len(self.completions)


class OnlineExperienceBuffer:
    """Thread-safe bounded FIFO of :class:`LabeledGroup`\\ s.

    ``put`` runs wherever the collector runs (possibly a serving thread);
    ``drain`` runs on the learner thread — one lock covers the deque, held
    only for the queue ops themselves.
    """

    def __init__(self, capacity: int = 256, max_staleness: Optional[int] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._groups: deque = deque()
        self._lock = threading.Lock()
        self._evicted = 0
        self.accountant = (
            StalenessAccountant(max_staleness) if max_staleness is not None else None
        )

    def __len__(self) -> int:
        with self._lock:
            return len(self._groups)

    def put(self, group: LabeledGroup) -> None:
        with self._lock:
            self._groups.append(group)
            while len(self._groups) > self.capacity:
                self._groups.popleft()
                self._evicted += 1
            depth, evicted = len(self._groups), self._evicted
        gauges.set("online/buffer_depth", float(depth))
        gauges.set("online/buffer_evicted", float(evicted))

    def drain(
        self, max_groups: int, learner_version: int = 0
    ) -> List[LabeledGroup]:
        """Pop up to ``max_groups`` oldest groups, drop the ones staler than
        the admission cap (when a cap is configured), return the admitted
        rest. Dropped groups are gone — re-admitting ever-staler experience
        later would only get worse."""
        popped: List[LabeledGroup] = []
        with self._lock:
            while self._groups and len(popped) < max_groups:
                popped.append(self._groups.popleft())
            depth = len(self._groups)
        gauges.set("online/buffer_depth", float(depth))
        if self.accountant is None:
            return popped
        fresh, _ = self.accountant.admit(popped, learner_version)
        gauges.set(
            "online/dropped_stale", float(self.accountant.stats()["dropped_stale"])
        )
        return fresh

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = {"depth": float(len(self._groups)), "evicted": float(self._evicted)}
        if self.accountant is not None:
            out.update(
                {k: float(v) for k, v in self.accountant.stats().items()}
            )
        return out
