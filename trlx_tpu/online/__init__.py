"""Online learning subsystem: harvest labeled experience from live traffic.

The pieces of the closed loop (docs/online.md):

- :class:`~trlx_tpu.online.buffer.OnlineExperienceBuffer` /
  :class:`~trlx_tpu.online.buffer.LabeledGroup` — bounded, version-tagged
  storage of scored completion groups;
- :class:`~trlx_tpu.online.collector.PreferenceCollector` — exactly-once
  harvest of completion groups from fleet/serving terminal requests, scored
  by reward_fn, pairwise preference judging, or environment returns;
- :class:`~trlx_tpu.online.environment.Environment` — the multi-turn
  observe → generate → act → reward interface, with
  :class:`~trlx_tpu.online.environment.SyntheticEnvironment` as the seeded
  test world.

The consumer is ``GRPOTrainer`` (``trainer/grpo_trainer.py``): fleet-served
groups are exactly the group-relative advantage's input shape.
"""

from trlx_tpu.online.buffer import LabeledGroup, OnlineExperienceBuffer
from trlx_tpu.online.collector import PreferenceCollector
from trlx_tpu.online.environment import (
    Environment,
    SyntheticEnvironment,
    environment_reward_fn,
    run_environment_rollout,
)

__all__ = [
    "Environment",
    "LabeledGroup",
    "OnlineExperienceBuffer",
    "PreferenceCollector",
    "SyntheticEnvironment",
    "environment_reward_fn",
    "run_environment_rollout",
]
