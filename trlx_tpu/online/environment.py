"""Environment interface for multi-turn / tool-use rollouts.

Plain reward_fn training scores a finished string; an *environment* is an
interaction loop — the policy observes, generates an action, the world
responds, and reward accrues per turn (observe → generate → act → reward).
The interface is deliberately token-level and tiny:

- :meth:`Environment.reset` returns the initial observation as token ids
  (the prompt the policy generates against);
- :meth:`Environment.step` consumes the policy's action tokens and returns
  the next observation, a scalar reward, and a done flag;
- :meth:`Environment.evaluate` is the optional *stateless* shortcut — a
  per-(prompt, action) score for environments whose reward needs no
  interaction state. It is what lets an environment stand in for a
  reward_fn in single-turn training (``trlx.train(environment=...)``) and
  what the online collector uses to score fleet-served completions.

:func:`run_environment_rollout` is the generic interaction loop;
:class:`SyntheticEnvironment` is the seeded, fully deterministic test
world (reward = fraction of action tokens equal to a target token) used by
tests, the example script, and the ``online_grpo`` bench leg.
"""

import abc
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

GenerateFn = Callable[[List[int]], List[int]]


class Environment(abc.ABC):
    """One episodic, token-level environment (see module docstring)."""

    @abc.abstractmethod
    def reset(self, seed: Optional[int] = None) -> List[int]:
        """Begin an episode; returns the initial observation token ids."""

    @abc.abstractmethod
    def step(self, action: Sequence[int]) -> Tuple[List[int], float, bool]:
        """Consume the policy's action tokens; returns
        ``(next_observation_tokens, reward, done)``."""

    def evaluate(self, prompt: Sequence[int], action: Sequence[int]) -> float:
        """Stateless per-(prompt, action) score, when the environment's
        reward does not depend on interaction state. Environments that only
        make sense as a loop leave this unimplemented and train through the
        collector's environment path instead."""
        raise NotImplementedError(
            f"{type(self).__name__} has no stateless evaluate(); "
            f"use run_environment_rollout for multi-turn reward"
        )


def run_environment_rollout(
    env: Environment,
    generate_fn: GenerateFn,
    max_turns: int = 4,
    seed: Optional[int] = None,
) -> Tuple[List[int], List[int], float]:
    """The observe → generate → act → reward loop.

    ``generate_fn`` maps the running transcript (all tokens so far) to the
    next action's tokens. Returns ``(initial_prompt, action_trace,
    episode_return)`` — the initial observation, every action token in
    order, and the summed reward: exactly the (prompt, completion, score)
    triple the online buffer stores.
    """
    obs = list(env.reset(seed=seed))
    prompt = list(obs)
    transcript = list(obs)
    actions: List[int] = []
    episode_return = 0.0
    for _ in range(max_turns):
        action = list(generate_fn(transcript))
        obs, reward, done = env.step(action)
        episode_return += float(reward)
        actions.extend(action)
        transcript.extend(action)
        transcript.extend(obs)
        if done:
            break
    return prompt, actions, episode_return


class SyntheticEnvironment(Environment):
    """Seeded deterministic test world over a small token alphabet.

    Each episode draws a random prompt of ``prompt_len`` tokens from the
    seeded stream; the reward of an action is the fraction of its tokens
    equal to ``target_token`` — stateless, so :meth:`evaluate` is exact and
    a policy improves by emitting the target more often (the measurable
    learning signal the e2e soak asserts on). Episodes run ``max_turns``
    turns; ``done`` after the last.
    """

    def __init__(
        self,
        vocab_size: int = 16,
        prompt_len: int = 4,
        target_token: int = 1,
        max_turns: int = 1,
        seed: int = 0,
    ):
        if not 0 <= target_token < vocab_size:
            raise ValueError(
                f"target_token {target_token} outside vocab [0, {vocab_size})"
            )
        self.vocab_size = int(vocab_size)
        self.prompt_len = int(prompt_len)
        self.target_token = int(target_token)
        self.max_turns = int(max_turns)
        self._base_seed = int(seed)
        self._episodes = 0
        self._rng = np.random.default_rng(self._base_seed)
        self._turn = 0

    def reset(self, seed: Optional[int] = None) -> List[int]:
        if seed is None:
            # deterministic stream: episode i always draws the same prompt
            seed = self._base_seed + self._episodes
        self._episodes += 1
        self._rng = np.random.default_rng(int(seed))
        self._turn = 0
        return self._rng.integers(0, self.vocab_size, size=self.prompt_len).tolist()

    def step(self, action: Sequence[int]) -> Tuple[List[int], float, bool]:
        self._turn += 1
        reward = self._action_reward(action)
        done = self._turn >= self.max_turns
        obs = (
            []
            if done
            else self._rng.integers(0, self.vocab_size, size=self.prompt_len).tolist()
        )
        return obs, reward, done

    def evaluate(self, prompt: Sequence[int], action: Sequence[int]) -> float:
        return self._action_reward(action)

    def _action_reward(self, action: Sequence[int]) -> float:
        action = list(action)
        if not action:
            return 0.0
        hits = sum(1 for t in action if int(t) == self.target_token)
        return hits / len(action)


def environment_reward_fn(env: Environment):
    """Adapt a stateless-scorable environment into a trlx reward_fn.

    The returned callable has the trainer's reward signature
    ``fn(samples, prompts, outputs, tokenizer=..., **meta)`` and scores each
    (prompt, output) pair through :meth:`Environment.evaluate` after
    re-encoding the decoded strings. Exact for single-turn environments;
    multi-turn reward needs the interaction loop (the collector's
    :meth:`~trlx_tpu.online.collector.PreferenceCollector.collect_environment`).
    """

    def reward_fn(samples, prompts, outputs, tokenizer=None, **kwargs):
        if tokenizer is None:
            raise ValueError("environment_reward_fn needs the tokenizer kwarg")
        scores = []
        for prompt, output in zip(prompts, outputs):
            p_ids = tokenizer.encode(prompt)
            a_ids = tokenizer.encode(output)
            scores.append(float(env.evaluate(p_ids, a_ids)))
        return scores

    return reward_fn
