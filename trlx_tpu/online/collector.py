"""PreferenceCollector: harvest labeled completion groups from live traffic.

The fleet serves requests anyway; the collector turns that exhaust into
GRPO training data. It observes terminal :class:`~trlx_tpu.serving.\
scheduler.Request`\\ s (the router's swept, exactly-once-per-uid stream),
filters to *learn-eligible* traffic (the router stamps
``req.learn_eligible``; unstamped requests fall back to "finished
successfully"), groups completions by prompt, and — when a group reaches
``group_size`` — scores it and feeds the bounded
:class:`~trlx_tpu.online.buffer.OnlineExperienceBuffer`, stamped with the
serving policy version for staleness admission downstream.

Three label sources (``train.online.label_type``):

- **reward**: ``reward_fn(prompt_tokens, completions) -> scores`` — direct
  scalar scoring (a scripted reward, a reward model);
- **preference**: ``preference_fn(prompt, completion_a, completion_b) ->
  p(a beats b)`` — round-robin pairwise comparisons reduced to per-
  completion mean win rates (the GRPO group baseline only needs relative
  order, so win rate is a sufficient score);
- **environment**: episode returns from
  :meth:`collect_environment`'s interaction loops.

**Exactly-once.** Each uid is harvested at most once (a ``_seen`` set,
mirroring the router's delivered-set), and each harvest journals a
``store`` flight event against the uid — the FlightRecorder's terminal
accounting extends through the learning loop. The seeded CI regression
``TRLX_ONLINE_SEED_REGRESSION=double_harvest`` disables the dedup so the
exactly-once test MUST fail under it (scripts/ci.sh proves the gate bites).

Gauges: ``online/labels_harvested``, ``online/groups_ready``,
``online/pending_completions``, ``online/duplicates_dropped``
(docs/online.md).
"""

import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from trlx_tpu.obs.flight import flight
from trlx_tpu.online.buffer import LabeledGroup, OnlineExperienceBuffer
from trlx_tpu.online.environment import Environment, run_environment_rollout
from trlx_tpu.serving.scheduler import (
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_STOP,
    Request,
)
from trlx_tpu.utils.metrics import gauges

#: finish reasons eligible for harvest when the router did not stamp
#: ``learn_eligible`` (matches the fleet ledger's success set)
_HARVESTABLE = (FINISH_EOS, FINISH_STOP, FINISH_LENGTH)

_SEED_ENV = "TRLX_ONLINE_SEED_REGRESSION"
_SEED_MODES = ("double_harvest",)


def _seed_regression() -> Optional[str]:
    mode = os.environ.get(_SEED_ENV)
    if mode and mode not in _SEED_MODES:
        raise ValueError(
            f"{_SEED_ENV}={mode!r} is not a known seeded regression "
            f"(expected one of {_SEED_MODES})"
        )
    return mode or None


class PreferenceCollector:
    """Group completions from terminal requests into labeled experience.

    :param buffer: destination for full scored groups.
    :param group_size: completions per group (must match the GRPO method's).
    :param reward_fn: ``fn(prompt_tokens, completions) -> [G] scores``.
    :param preference_fn: ``fn(prompt, a, b) -> p(a beats b)`` pairwise
        judge; exactly one of reward_fn / preference_fn must be given for
        request harvesting (environment episodes carry their own returns).

    Thread-safety: ``observe`` may run on the fleet's driving thread while
    the learner reads gauges — one lock covers the pending tables.
    """

    def __init__(
        self,
        buffer: OnlineExperienceBuffer,
        group_size: int = 4,
        reward_fn: Optional[Callable[..., Sequence[float]]] = None,
        preference_fn: Optional[Callable[..., float]] = None,
    ):
        if group_size < 2:
            raise ValueError(f"group_size must be >= 2, got {group_size}")
        self.buffer = buffer
        self.group_size = int(group_size)
        self.reward_fn = reward_fn
        self.preference_fn = preference_fn
        self._seed_regression = _seed_regression()
        self._lock = threading.Lock()
        self._seen: set = set()
        # prompt key -> list of (uid, completion tokens)
        self._pending: Dict[Tuple[int, ...], List[Tuple[int, List[int]]]] = {}
        self._pending_version: Dict[Tuple[int, ...], int] = {}
        self._harvested = 0
        self._duplicates = 0
        self._groups_ready = 0

    # ------------------------------------------------------------ harvesting

    def _eligible(self, req: Request) -> bool:
        stamped = getattr(req, "learn_eligible", None)
        if stamped is not None:
            return bool(stamped)
        return req.finish_reason in _HARVESTABLE and bool(req.generated)

    def observe(self, req: Request, policy_version: int = 0) -> bool:
        """Consider one terminal request for harvest; returns True when its
        completion was banked (exactly once per uid)."""
        if not self._eligible(req):
            return False
        ready = False
        members: List[Tuple[int, List[int]]] = []
        version = 0
        with self._lock:
            if req.uid in self._seen and self._seed_regression != "double_harvest":
                self._duplicates += 1
                duplicates = self._duplicates
            else:
                duplicates = None
                self._seen.add(req.uid)
                key = tuple(map(int, req.prompt))
                self._pending.setdefault(key, []).append(
                    (req.uid, list(map(int, req.generated)))
                )
                # a group is scored against the *newest* version that fed
                # it — staleness admission must not under-count the lag
                self._pending_version[key] = max(
                    self._pending_version.get(key, 0), int(policy_version)
                )
                self._harvested += 1
                harvested = self._harvested
                ready = len(self._pending[key]) >= self.group_size
                if ready:
                    members = self._pending.pop(key)[: self.group_size]
                    version = self._pending_version.pop(key)
                pending_total = sum(len(v) for v in self._pending.values())
        # gauge/flight exports outside the collector lock (flat lock order)
        if duplicates is not None:
            gauges.set("online/duplicates_dropped", float(duplicates))
            return False
        flight.record(req.uid, "store")
        gauges.set("online/labels_harvested", float(harvested))
        gauges.set("online/pending_completions", float(pending_total))
        if ready:
            self._bank_group(list(key), members, version)
        return True

    def harvest(self, source: Any, policy_version: Optional[int] = None) -> int:
        """Sweep a router/engine's finished requests through :meth:`observe`.

        ``source`` is anything with ``.scheduler.pop_finished()`` (a
        :class:`~trlx_tpu.fleet.router.FleetRouter`, a ``ServingEngine``) or
        a plain ``{uid: Request}`` dict. The policy version defaults to the
        source's ``serving_version``. Returns the number harvested."""
        if policy_version is None:
            policy_version = int(getattr(source, "serving_version", 0) or 0)
        if isinstance(source, dict):
            finished = source
        else:
            finished = source.scheduler.pop_finished()
        n = 0
        for req in finished.values():
            if self.observe(req, policy_version=policy_version):
                n += 1
        return n

    # --------------------------------------------------------------- scoring

    def _bank_group(
        self,
        prompt: List[int],
        members: List[Tuple[int, List[int]]],
        policy_version: int,
    ) -> None:
        uids = tuple(uid for uid, _ in members)
        completions = [toks for _, toks in members]
        scores = self._score_group(prompt, completions)
        self.buffer.put(
            LabeledGroup(
                prompt=prompt,
                completions=completions,
                scores=scores,
                policy_version=policy_version,
                uids=uids,
            )
        )
        with self._lock:
            self._groups_ready += 1
            ready = self._groups_ready
        gauges.set("online/groups_ready", float(ready))

    def _score_group(
        self, prompt: List[int], completions: List[List[int]]
    ) -> np.ndarray:
        if self.reward_fn is not None:
            return np.asarray(
                self.reward_fn(prompt, completions), dtype=np.float32
            )
        if self.preference_fn is not None:
            return self._pairwise_win_rates(prompt, completions)
        raise ValueError(
            "PreferenceCollector needs a reward_fn or a preference_fn to "
            "score harvested groups"
        )

    def _pairwise_win_rates(
        self, prompt: List[int], completions: List[List[int]]
    ) -> np.ndarray:
        """Round-robin pairwise judging reduced to mean win rates. The judge
        returns p(a beats b); each ordered pair is judged once and credited
        symmetrically, so G completions cost G*(G-1)/2 judge calls."""
        g = len(completions)
        wins = np.zeros(g, dtype=np.float32)
        for i in range(g):
            for j in range(i + 1, g):
                p = float(self.preference_fn(prompt, completions[i], completions[j]))
                wins[i] += p
                wins[j] += 1.0 - p
        return wins / max(1, g - 1)

    # ----------------------------------------------------------- environment

    def collect_environment(
        self,
        env: Environment,
        generate_fn: Callable[[List[int]], List[int]],
        episodes: int,
        max_turns: int = 4,
        seed: int = 0,
        policy_version: int = 0,
    ) -> int:
        """Collect ``episodes`` groups of environment rollouts.

        Each group re-seeds the environment so its ``group_size`` members
        share one initial observation (the group baseline needs a shared
        prompt); ``generate_fn`` supplies the diversity. Scores are episode
        returns — no reward_fn / preference_fn needed. Returns groups
        banked."""
        banked = 0
        for g in range(int(episodes)):
            group_seed = int(seed) + g
            prompt: Optional[List[int]] = None
            completions: List[List[int]] = []
            returns: List[float] = []
            for _ in range(self.group_size):
                p, actions, ep_return = run_environment_rollout(
                    env, generate_fn, max_turns=max_turns, seed=group_seed
                )
                if prompt is None:
                    prompt = p
                completions.append(actions)
                returns.append(ep_return)
            self.buffer.put(
                LabeledGroup(
                    prompt=prompt or [],
                    completions=completions,
                    scores=np.asarray(returns, dtype=np.float32),
                    policy_version=int(policy_version),
                )
            )
            banked += 1
            with self._lock:
                self._harvested += self.group_size
                self._groups_ready += 1
                harvested, ready = self._harvested, self._groups_ready
            gauges.set("online/labels_harvested", float(harvested))
            gauges.set("online/groups_ready", float(ready))
        return banked

    # ---------------------------------------------------------------- stats

    def flush(self) -> int:
        """Drop partial groups (end of a run / before a policy swap whose
        staleness would mix versions inside one group). Returns completions
        discarded."""
        with self._lock:
            dropped = sum(len(v) for v in self._pending.values())
            self._pending.clear()
            self._pending_version.clear()
        gauges.set("online/pending_completions", 0.0)
        return dropped

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "labels_harvested": float(self._harvested),
                "groups_ready": float(self._groups_ready),
                "pending_completions": float(
                    sum(len(v) for v in self._pending.values())
                ),
                "duplicates_dropped": float(self._duplicates),
            }
