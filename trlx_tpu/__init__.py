"""trlx_tpu — a TPU-native RLHF fine-tuning framework (capabilities of CarperAI/trlX,
built on JAX/XLA/pjit/Pallas). Public API mirrors the reference:
``trlx_tpu.train(...)`` (cf. `/root/reference/trlx/__init__.py`)."""

__version__ = "0.1.0"

from trlx_tpu.data.configs import TRLConfig


def train(*args, **kwargs):
    """Dispatch online (PPO), offline (ILQL) or supervised (SFT/RFT) training.

    Lazy wrapper around :func:`trlx_tpu.trlx.train` so that importing the package
    stays light (no model/trainer imports until training starts)."""
    from trlx_tpu.trlx import train as _train

    return _train(*args, **kwargs)


__all__ = ["train", "TRLConfig", "__version__"]
