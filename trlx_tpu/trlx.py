"""Public ``train()`` entry (parity: `/root/reference/trlx/trlx.py:15-143`): one
function dispatching every training mode, building the trainer and pipelines and
running ``learn()``.

Dispatch table (first matching row wins; ``config`` overrides the inferred
default when given explicitly):

==========================  =========================  ======================
 given                       mode                       default config
==========================  =========================  ======================
 ``reward_fn``               online RL (PPO/GRPO/RFT)   ``default_ppo_config``
 ``environment``             environment RL (GRPO)      ``default_grpo_config``
 ``samples`` + ``rewards``   offline RL (ILQL)          ``default_ilql_config``
 ``samples``                 supervised (SFT)           ``default_sft_config``
==========================  =========================  ======================

``environment`` is an :class:`~trlx_tpu.online.environment.Environment`
whose reward is an interaction loop (observe → generate → act → reward); a
stateless-scorable environment is adapted into a reward_fn here and flows
through the prompt-pipeline path. Fleet-harvested online training
(``train.online``; docs/online.md) also enters through the reward_fn row —
the collector feeds the trainer's experience buffer while ``learn()`` runs.
"""

from typing import Callable, Dict, Iterable, List, Optional, Tuple, Union

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.data.default_configs import (
    default_grpo_config,
    default_ilql_config,
    default_ppo_config,
    default_sft_config,
)
from trlx_tpu.utils import logging, set_seed
from trlx_tpu.utils.loading import get_pipeline, get_trainer

logger = logging.get_logger(__name__)


def train(
    model_path: Optional[str] = None,
    reward_fn: Optional[Callable] = None,
    dataset: Optional[Iterable[Tuple[str, float]]] = None,
    samples: Optional[List[str]] = None,
    rewards: Optional[List[float]] = None,
    prompts: Optional[List[Union[str, Dict]]] = None,
    eval_prompts: Optional[List[Union[str, Dict]]] = None,
    metric_fn: Optional[Callable] = None,
    config: Optional[TRLConfig] = None,
    stop_sequences: Optional[List[str]] = None,
    environment=None,
):
    """Dispatch & fit (see the module docstring's dispatch table). The
    reference surface is identical (model_path, reward_fn, samples, rewards,
    prompts, eval_prompts, metric_fn, config, stop_sequences) plus
    ``environment``: an :class:`~trlx_tpu.online.environment.Environment`
    scored through its stateless ``evaluate`` and trained with GRPO by
    default."""
    if reward_fn is not None and environment is not None:
        raise ValueError(
            "`reward_fn` and `environment` are mutually exclusive: an "
            "environment IS the reward source"
        )
    if config is None:
        logger.warning(
            "Passing the `config` argument implicitly is depreciated, use or adapt one of the default configs instead"
        )
        if reward_fn:
            config = default_ppo_config()
        elif environment is not None:
            config = default_grpo_config()
        elif rewards:
            config = default_ilql_config()
        else:
            config = default_sft_config()
    if environment is not None:
        # adapt the environment into the reward_fn row of the dispatch table
        from trlx_tpu.online.environment import environment_reward_fn

        reward_fn = environment_reward_fn(environment)
    if model_path:
        config.model.model_path = model_path

    # multi-process init must precede any backend-initializing jax call
    # (set_seed queries jax.process_index)
    from trlx_tpu.parallel.mesh import initialize_distributed

    initialize_distributed()
    set_seed(config.train.seed)

    if dataset is not None:
        logger.warning("the `dataset` argument is being depreciated, split it into `samples` and `rewards` instead")
        samples, rewards = dataset

    trainer_cls = get_trainer(config.train.trainer)
    trainer = trainer_cls(
        config=config,
        reward_fn=reward_fn,
        metric_fn=metric_fn,
        stop_sequences=stop_sequences,
        **config.train.trainer_kwargs,
    )

    batch_size = config.train.batch_size
    max_prompt_length = config.train.seq_length - config.method.gen_kwargs.get("max_new_tokens", 0)

    # online RL (PPO / GRPO / RFT): prompts + reward_fn (an environment was
    # adapted into reward_fn above)
    if reward_fn:
        prompts = prompts or [trainer.tokenizer.bos_token] * batch_size
        if eval_prompts is None:
            eval_prompts = prompts[:batch_size]
        pipeline = get_pipeline(config.train.pipeline)(
            prompts, max_prompt_length, trainer.tokenizer
        )
        trainer.add_prompt_pipeline(pipeline)

    # offline RL (ILQL): samples + rewards
    elif samples is not None and rewards is not None:
        if len(samples) != len(rewards):
            raise ValueError(f"Number of samples {len(samples)} should match the number of rewards {len(rewards)}")
        if eval_prompts is None:
            eval_prompts = [trainer.tokenizer.bos_token] * batch_size
        trainer.make_experience(samples, rewards, config.train.seq_length)

    # supervised fine-tuning (SFT): samples only
    elif samples is not None:
        if eval_prompts is None:
            eval_prompts = [trainer.tokenizer.bos_token] * batch_size
        trainer.make_experience(samples, config.train.seq_length)

    else:
        raise ValueError(
            "One of `samples` (SFT / +`rewards` for ILQL), `reward_fn` "
            "(PPO/GRPO/RFT) or `environment` (GRPO over interaction "
            "rollouts) should be given for training"
        )

    eval_pipeline = get_pipeline(config.train.pipeline)(
        eval_prompts, max_prompt_length, trainer.tokenizer
    )
    trainer.add_eval_pipeline(eval_pipeline)

    trainer.learn()
    return trainer
