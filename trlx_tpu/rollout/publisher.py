"""Versioned parameter snapshots for the async rollout producer.

The learner's jitted train step *donates* its param buffers (real buffer reuse
on TPU — see ``MeshRLTrainer.make_grad_accum_step``), so the producer must
never hold a reference into the live train state: the buffers it would read
get invalidated by the very next optimizer step. The publisher therefore takes
a **donate-free device copy** at publish time (the ``device_copy`` pattern the
PPO trainer already uses for its frozen KL reference) and pairs it with a
monotonic policy version: the producer generates with version *v* while the
learner optimizes toward *v+1*, and every experience element is tagged with
the version it was sampled from so staleness is observable downstream.

Under ``train.islands`` the one-shot snapshot here is replaced by the
drop-in :class:`~trlx_tpu.rollout.broadcast.ChunkedParameterPublisher`
(same ``publish``/``latest``/``version`` surface), which streams the tree
layer-by-layer under the generation island's round gate and commits each
version atomically — docs/parallelism.md "Islands".
"""

import threading
from typing import Any, Callable, Optional, Tuple

import jax


def _default_copy(tree):
    """Deep copy of an array pytree (host numpy or committed jax.Arrays)."""
    return jax.tree.map(lambda x: x.copy(), tree)


class ParameterPublisher:
    """Single-writer (learner) / single-reader (producer) snapshot mailbox.

    ``publish`` replaces the snapshot and bumps the version; ``latest`` hands
    back the newest ``(version, params)``. Versions are monotonic from 0.
    """

    def __init__(self, copy_fn: Optional[Callable[[Any], Any]] = None):
        self._copy = copy_fn or _default_copy
        self._lock = threading.Lock()
        self._version = -1
        self._snapshot: Any = None

    def publish(self, params: Any) -> int:
        """Snapshot ``params`` (copy happens outside the lock — it may involve
        device work) and return the new, strictly-increasing version."""
        snapshot = self._copy(params)
        with self._lock:
            self._version += 1
            self._snapshot = snapshot
            return self._version

    def latest(self) -> Tuple[int, Any]:
        """Newest ``(version, params)``; raises if nothing was published yet."""
        with self._lock:
            if self._version < 0:
                raise RuntimeError("ParameterPublisher.latest() before first publish()")
            return self._version, self._snapshot

    @property
    def version(self) -> int:
        with self._lock:
            return self._version
