"""Deterministic re-ordering of out-of-order rollout completions.

The continuous-batching engine finishes sequences in whatever order decode
lengths dictate, and the stream-overlap reward pool finishes scoring in
whatever order the workers race to.  The replay store, however, must see
elements in submission order so that overlap-on runs append identical
contents in identical order to the serial path (and so repeated runs are
byte-stable regardless of thread timing).

:class:`ReorderBuffer` is the TCP-reassembly-style seam: producers ``add``
items under their original submission index, possibly out of order, and the
consumer drains the contiguous ready prefix with ``pop_ready``.  A ``None``
item is a tombstone — it advances the cursor without emitting anything, so a
quarantine-dropped element can never stall the sequences behind it.
"""

import threading
from typing import Any, Dict, List, Optional

__all__ = ["ReorderBuffer"]


class ReorderBuffer:
    """Reassemble indexed completions into submission order."""

    def __init__(self, start: int = 0) -> None:
        self._lock = threading.Lock()
        self._next = start
        self._slots: Dict[int, Optional[Any]] = {}

    def add(self, index: int, item: Optional[Any]) -> None:
        """Record ``item`` for submission ``index``; ``None`` is a tombstone."""
        with self._lock:
            if index < self._next or index in self._slots:
                raise ValueError(f"duplicate completion for index {index}")
            self._slots[index] = item

    def pop_ready(self) -> List[Any]:
        """Drain the contiguous prefix, skipping tombstones."""
        out: List[Any] = []
        with self._lock:
            while self._next in self._slots:
                item = self._slots.pop(self._next)
                self._next += 1
                if item is not None:
                    out.append(item)
        return out

    @property
    def pending(self) -> int:
        """Completions received but blocked behind a missing earlier index."""
        with self._lock:
            return len(self._slots)

    @property
    def next_index(self) -> int:
        with self._lock:
            return self._next
