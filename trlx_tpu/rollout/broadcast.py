"""Chunked, decode-overlapped parameter broadcast for disaggregated islands.

The monolithic :class:`~trlx_tpu.rollout.publisher.ParameterPublisher` copies
the whole parameter tree in one shot, which on a real generation island means
one long bus transfer the decode loop must hide all at once. This module
ships the LlamaRL-style alternative: the publisher streams the tree
**layer-by-layer** into pinned per-layer staging buffers while decode rounds
keep running, stamps every broadcast with a version-numbered
:class:`BroadcastManifest`, and only when the last chunk has landed commits
the assembled tree in one atomic swap. Consumers (the serving engine's
round-boundary poll — :meth:`trlx_tpu.serving.engine.ServingEngine.step`)
can therefore never observe a torn version: ``latest``/``poll_update`` hand
out committed snapshots only, and a publisher that dies mid-broadcast leaves
the previous version in place (its burned version number is visible in the
``rollout/broadcast/aborted`` gauge, nothing else).

Round-boundary synchronization happens through an optional ``round_gate``
lock shared with the generation island: the publisher takes it only for the
brief per-chunk staging install, so a decode round and a chunk install never
interleave but the broadcast as a whole stays hidden under decode. The
seeded CI regression ``TRLX_ISLAND_SEED_REGRESSION=blocking_broadcast``
inverts exactly that property — the publisher holds the gate for the entire
broadcast — which must make the idle-bubble proof test fail (scripts/ci.sh
proves the gate bites).
"""

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple

import jax

from trlx_tpu.resilience.chaos import chaos
from trlx_tpu.rollout.publisher import _default_copy
from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)

#: every broadcast gauge lives under this prefix; cleared prefix-aware on
#: island shutdown (GaugeRegistry.clear)
BROADCAST_GAUGE_PREFIX = "rollout/broadcast/"


def _tree_nbytes(tree: Any) -> int:
    return sum(int(getattr(x, "nbytes", 0) or 0) for x in jax.tree.leaves(tree))


def layer_chunks(tree: Any, chunk_layers: int = 1) -> List[Tuple[str, Any]]:
    """Split a parameter pytree into named broadcast chunks.

    A mapping splits by top-level key (for a transformer params dict that is
    per-layer: ``wte``, ``h_0`` … ``h_N``, ``ln_f``), grouping
    ``chunk_layers`` consecutive keys per chunk; anything else is a single
    ``"all"`` chunk. Key order follows the tree's own (insertion) order, so
    the chunking is deterministic for a fixed tree and reassembly by key is
    exact regardless of chunk grouping.
    """
    if not isinstance(tree, dict) or not tree:
        return [("all", tree)]
    keys = list(tree)
    k = max(1, int(chunk_layers))
    out: List[Tuple[str, Any]] = []
    for i in range(0, len(keys), k):
        group = keys[i:i + k]
        name = group[0] if len(group) == 1 else f"{group[0]}..{group[-1]}"
        out.append((name, {key: tree[key] for key in group}))
    return out


@dataclass(frozen=True)
class BroadcastManifest:
    """Version-stamped description of one chunked broadcast: what was shipped
    and how big each chunk was. Committed alongside the assembled snapshot so
    a consumer can attribute the version it swapped to."""

    version: int
    chunk_names: Tuple[str, ...]
    chunk_bytes: Tuple[int, ...]

    @property
    def total_bytes(self) -> int:
        return sum(self.chunk_bytes)

    @property
    def num_chunks(self) -> int:
        return len(self.chunk_names)


class ChunkedParameterPublisher:
    """Drop-in for :class:`~trlx_tpu.rollout.publisher.ParameterPublisher`
    (same ``publish``/``latest``/``version`` surface) that broadcasts
    layer-by-layer with an atomic commit (module docstring).

    Single-writer (the learner thread calls ``publish``), many-reader
    (``latest``/``poll_update`` from the producer and engine threads). The
    torn-version invariant is structural: the staging dict is private to the
    in-flight ``publish`` call, and the committed ``(version, snapshot,
    manifest)`` triple only ever changes under ``_lock`` after the last chunk
    landed.
    """

    def __init__(
        self,
        copy_fn: Optional[Callable[[Any], Any]] = None,
        chunk_layers: int = 1,
        chunk_pause_s: float = 0.0,
        round_gate: Optional[threading.Lock] = None,
    ):
        self._copy = copy_fn or _default_copy
        self.chunk_layers = max(1, int(chunk_layers))
        self.chunk_pause_s = float(chunk_pause_s)
        self._gate = round_gate
        seed_reg = os.environ.get("TRLX_ISLAND_SEED_REGRESSION", "")
        if seed_reg not in ("", "blocking_broadcast"):
            raise ValueError(
                f"TRLX_ISLAND_SEED_REGRESSION={seed_reg!r}: only "
                f"'blocking_broadcast' is defined"
            )
        self._blocking = seed_reg == "blocking_broadcast"
        if self._blocking:
            logger.warning(
                "TRLX_ISLAND_SEED_REGRESSION=blocking_broadcast: the publisher "
                "will hold the round gate for entire broadcasts (CI gate mode)"
            )
        self._lock = threading.Lock()
        self._version = -1
        self._snapshot: Any = None
        self._manifest: Optional[BroadcastManifest] = None
        self._next_version = 0
        self._chunks_sent = 0
        self._bytes_sent = 0
        self._aborted = 0
        self._last_bytes_s = 0.0
        self._last_broadcast_s = 0.0
        # island observability hook: object with note_broadcast_chunk(t0, t1)
        self._observer: Any = None

    # --------------------------------------------------------------- wiring

    def attach_observer(self, observer: Any) -> None:
        """Register the generation island (or any object with a
        ``note_broadcast_chunk(t0, t1)`` method) to receive per-chunk busy
        intervals for the broadcast-hidden-under-decode ledger.

        Wiring-time only: called once while the island is assembled, before
        the learner thread ever publishes — no publish can be in flight."""
        self._observer = observer  # graftcheck: noqa[CC001]

    # -------------------------------------------------------------- publish

    def publish(self, params: Any) -> int:
        """Broadcast ``params`` chunk-by-chunk and atomically commit the new
        version; returns it. On any failure mid-broadcast the previous
        committed version stays visible and the in-flight version number is
        burned (monotonicity is preserved; the abort is counted)."""
        with self._lock:
            version = self._next_version
            self._next_version += 1
        named = layer_chunks(params, self.chunk_layers)
        staged = {}
        chunk_bytes: List[int] = []
        gate = self._gate
        held = False
        t_start = time.monotonic()
        try:
            if self._blocking and gate is not None:
                # seeded regression: the whole broadcast squats on the round
                # gate, serializing decode behind it — the exact failure the
                # idle-bubble proof must catch
                gate.acquire()
                held = True
            for i, (name, subtree) in enumerate(named):
                chaos.fail_if_armed(
                    "broadcast-chunk", f"chunk {name!r} of version {version}"
                )
                t0 = time.monotonic()
                copied = self._copy(subtree)
                if gate is not None and not held:
                    # per-chunk install at a round boundary: a decode round
                    # and a staging install never interleave, but the gate is
                    # released between chunks so rounds keep flowing
                    with gate:
                        staged[name] = copied
                else:
                    staged[name] = copied
                t1 = time.monotonic()
                chunk_bytes.append(_tree_nbytes(copied))
                if self._observer is not None:
                    self._observer.note_broadcast_chunk(t0, t1)
                if self.chunk_pause_s > 0 and i + 1 < len(named):
                    time.sleep(self.chunk_pause_s)
        except BaseException:
            with self._lock:
                self._aborted += 1
                aborted = self._aborted
            gauges.set(BROADCAST_GAUGE_PREFIX + "aborted", float(aborted))
            raise
        finally:
            if held:
                gate.release()
        manifest = BroadcastManifest(version, tuple(n for n, _ in named), tuple(chunk_bytes))
        if isinstance(params, dict) and params:
            assembled: Any = {}
            for name, _ in named:
                assembled.update(staged[name])
        else:
            assembled = staged["all"]
        wall = max(time.monotonic() - t_start, 1e-9)
        with self._lock:
            # the atomic swap: version, snapshot and manifest move together,
            # and only after every chunk landed
            self._version = version
            self._snapshot = assembled
            self._manifest = manifest
            self._chunks_sent += manifest.num_chunks
            self._bytes_sent += manifest.total_bytes
            self._last_broadcast_s = wall
            self._last_bytes_s = manifest.total_bytes / wall
        self._export_gauges()
        return version

    # --------------------------------------------------------------- readers

    def latest(self) -> Tuple[int, Any]:
        """Newest committed ``(version, params)``; raises before the first
        commit (mirrors ParameterPublisher)."""
        with self._lock:
            if self._version < 0:
                raise RuntimeError(
                    "ChunkedParameterPublisher.latest() before first commit"
                )
            return self._version, self._snapshot

    def poll_update(self, last_seen: int) -> Optional[Tuple[int, Any]]:
        """Newest committed ``(version, params)`` if newer than ``last_seen``,
        else None. Also records the observed version lag (how many commits
        behind the poller was) in the ``rollout/broadcast/version_lag``
        gauge."""
        with self._lock:
            if self._version < 0 or self._version <= last_seen:
                return None
            lag = self._version - max(int(last_seen), -1)
            out = (self._version, self._snapshot)
        gauges.set(BROADCAST_GAUGE_PREFIX + "version_lag", float(lag))
        return out

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def manifest(self) -> Optional[BroadcastManifest]:
        """Manifest of the committed version (None before the first)."""
        with self._lock:
            return self._manifest

    # ------------------------------------------------------------------ obs

    def stats(self) -> dict:
        with self._lock:
            return {
                "version": self._version,
                "chunks_sent": self._chunks_sent,
                "bytes_sent": self._bytes_sent,
                "aborted": self._aborted,
                "last_broadcast_s": self._last_broadcast_s,
                "last_bytes_s": self._last_bytes_s,
            }

    def _export_gauges(self) -> None:
        s = self.stats()
        gauges.set(BROADCAST_GAUGE_PREFIX + "version", float(s["version"]))
        gauges.set(BROADCAST_GAUGE_PREFIX + "chunks_sent", float(s["chunks_sent"]))
        gauges.set(BROADCAST_GAUGE_PREFIX + "bytes_s", s["last_bytes_s"])
        gauges.set(BROADCAST_GAUGE_PREFIX + "broadcast_s", s["last_broadcast_s"])
        gauges.set(BROADCAST_GAUGE_PREFIX + "aborted", float(s["aborted"]))

    def close(self) -> None:
        """Retire this publisher's observability surface (prefix-aware clear,
        same contract as ServingEngine.close)."""
        gauges.clear(prefix=BROADCAST_GAUGE_PREFIX)
