"""The async rollout producer loop.

One daemon thread runs ``produce_fn(params, version) -> [PPORLElement]`` — the
trainer's existing jitted generate → reward → score chunk pipeline,
parameterized by a published parameter snapshot — in a loop:

    snapshot = publisher.latest()        # freshest policy the learner published
    elements = produce_fn(*snapshot)     # device decode + scoring, host reward
    tag each element with the snapshot's policy version
    queue.put(elements)                  # blocks on backpressure / watermarks

The learner, on the main thread, calls :meth:`AsyncRolloutEngine.collect` to
pop experience, runs staleness admission, and keeps training while the
producer refills the queue — that concurrent window is the recovered idle
time. JAX dispatch is thread-safe; on a single controller the two threads
interleave device work, and on disaggregated topologies the same seam lets
the producer target separate inference chips.

Coordination rules (enforced here, relied on by the trainer):

- ``paused()`` grabs the same lock the producer holds across one produce
  iteration — the trainer wraps ``evaluate()`` in it because eval shares the
  tokenizer/RNG/generation caches with the producer.
- A producer crash closes the queue and re-raises from ``collect``/``stop``
  so a dead producer can never silently starve the learner. Under a
  :class:`~trlx_tpu.rollout.supervisor.ProducerSupervisor` the engine is
  built with ``close_queue_on_death=False``: the crash still re-raises from
  ``collect``, but the shared queue stays open so a *replacement* engine can
  keep feeding it (the supervisor catches the raise and restarts).
- ``stop()`` closes the queue (waking a blocked ``put``), joins the thread,
  and reports drain statistics; no dangling threads after ``learn()``.
  Elements abandoned mid-``put`` during shutdown are counted as
  ``dropped_shutdown`` so the drain ledger balances:
  ``produced == consumed + dropped_stale + leftover + dropped_shutdown``.
"""

import contextlib
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

from trlx_tpu.obs import span, watchdog
from trlx_tpu.resilience.chaos import chaos
from trlx_tpu.rollout.publisher import ParameterPublisher
from trlx_tpu.rollout.queue import ExperienceQueue, QueueClosed
from trlx_tpu.rollout.staleness import StalenessAccountant
from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)

#: Watchdog heartbeat name for the producer thread (docs/observability.md).
PRODUCER_HEARTBEAT = "rollout-producer"


def length_bucketed(batches: Iterator[Dict[str, list]], lookahead: int) -> Iterator[Dict[str, list]]:
    """Reorder a prompt-batch stream so each batch holds similar-length prompts.

    The one-shot generate path pads every batch to the length bucket of its
    longest prompt, so one straggler makes the whole batch pay its prefill and
    per-token attention cost. This wrapper pulls a window of ``lookahead``
    batches, stable-sorts the window's prompts by token length, and re-chunks
    them into batches of the original sizes — tight buckets without changing
    the set of prompts drawn (the serving engine's admission rounds do the
    same sort slot-by-slot; this is the cheap precursor for the generate path).

    Deterministic and replay-safe: the reorder is a pure function of the
    incoming window, and k batches in -> k batches out, so the auto-resume
    fast-forward (which counts batches drawn) lands on the same stream
    position. ``lookahead <= 1`` yields the stream unchanged.
    """
    if lookahead <= 1:
        yield from batches
        return
    batches = iter(batches)
    while True:
        window = []
        try:
            for _ in range(lookahead):
                window.append(next(batches))
        except StopIteration:
            pass
        if not window:
            return
        sizes = [len(b["input_ids"]) for b in window]
        keys = list(window[0].keys())
        flat = {k: [v for b in window for v in b[k]] for k in keys}
        order = sorted(range(len(flat["input_ids"])), key=lambda i: len(flat["input_ids"][i]))
        start = 0
        for size in sizes:
            idx = order[start:start + size]
            start += size
            yield {k: [flat[k][i] for i in idx] for k in keys}
        if len(window) < lookahead:  # underlying stream ended mid-window
            return


class AsyncRolloutEngine:
    """Continuously-running experience producer decoupled from the learner."""

    def __init__(
        self,
        produce_fn: Callable[[Any, int], List[Any]],
        publisher: ParameterPublisher,
        queue: ExperienceQueue,
        accountant: StalenessAccountant,
        name: str = "rollout-producer",
        close_queue_on_death: bool = True,
    ):
        self._produce = produce_fn
        self.publisher = publisher
        self.queue = queue
        self.accountant = accountant
        self._name = name
        # True (default, unsupervised): a producer crash closes the queue so
        # the learner unblocks and the error re-raises. False (supervised):
        # the queue is shared with successor engines and must outlive us.
        self._close_queue_on_death = close_queue_on_death
        self._abandoned = False
        self._stop_evt = threading.Event()
        # held by the producer across one produce iteration; evaluate() takes
        # it to pause production while it shares tokenizer/RNG/generate caches
        self._pause_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # guards the producer-written counters below; deliberately separate
        # from _pause_lock so stats readers on the learner thread never wait
        # out a full produce iteration
        self._stats_lock = threading.Lock()
        self._busy_time = 0.0
        self._wall_start: Optional[float] = None
        self._produced = 0
        self._dropped_shutdown = 0

    # ---------------------------------------------------------------- lifecycle

    def start(self):
        # the handle/counters are guarded: running/summary()/overlap_fraction()
        # are read from the learner thread while this engine starts elsewhere
        with self._stats_lock:
            if self._thread is not None:
                raise RuntimeError("engine already started")
            self._wall_start = time.monotonic()
            thread = threading.Thread(target=self._loop, name=self._name, daemon=True)
            self._thread = thread
        # register the heartbeat before the first produce: a producer wedged on
        # its very first iteration must still be detectable
        watchdog.beat(PRODUCER_HEARTBEAT)
        thread.start()

    def _loop(self):
        try:
            while not self._stop_evt.is_set():
                # chaos site "producer-wedge": simulate a hang (stuck reward
                # RPC, wedged decode) — no heartbeats, no exception, no
                # progress, until abandoned or shut down. Deliberately outside
                # the pause lock so a wedged producer cannot deadlock
                # evaluate(); the watchdog escalation / supervisor wedge
                # timeout is what recovers from this.
                if chaos.should_fail("producer-wedge"):
                    logger.warning(
                        "chaos: rollout producer wedged at site 'producer-wedge' "
                        "(silent, no heartbeats) — waiting for abandon/stop"
                    )
                    self._stop_evt.wait()
                    break
                with self._pause_lock:
                    if self._stop_evt.is_set():
                        break
                    # resilience fault site: lets tests kill the producer and
                    # prove the close-on-death / re-raise-from-collect contract
                    chaos.fail_if_armed("rollout-producer")
                    version, params = self.publisher.latest()
                    t0 = time.monotonic()
                    elements = self._produce(params, version)
                    with self._stats_lock:
                        self._busy_time += time.monotonic() - t0
                        self._produced += len(elements)
                tagged = [e.replace(policy_version=version) for e in elements]
                # outside the pause lock: backpressure must not block evaluate().
                # Bounded puts with heartbeats between retries: a *gated* queue
                # (learner mid-epoch, backpressure working as designed) must not
                # read as a producer stall to the watchdog
                delivered = False
                try:
                    with span("queue_put"):
                        while not self._stop_evt.is_set():
                            if self.queue.put(tagged, timeout=5.0):
                                delivered = True
                                break
                            watchdog.beat(PRODUCER_HEARTBEAT)
                except QueueClosed:
                    pass
                if not delivered:
                    # shutdown raced the put: the batch is lost by design, but
                    # it must show up in the drain ledger, not vanish from it
                    with self._stats_lock:
                        self._dropped_shutdown += len(tagged)
                    break
                watchdog.beat(PRODUCER_HEARTBEAT)
                self._export_gauges()
        except QueueClosed:
            pass
        except BaseException as e:  # noqa: B036 — re-raised from collect/stop
            with self._stats_lock:
                self._error = e
            logger.error(f"async rollout producer died: {type(e).__name__}: {e}")
        finally:
            # a dead producer must never leave the learner blocked in get() —
            # except under supervision, where the queue is shared with the
            # replacement engine and collect() detects death by polling
            with self._stats_lock:
                close_queue = self._close_queue_on_death and not self._abandoned
            if close_queue:
                self.queue.close()

    def stop(self, timeout: Optional[float] = 30.0) -> dict:
        """Close the queue, join the producer, return drain statistics."""
        self._stop_evt.set()
        self.queue.close()
        try:
            with self._stats_lock:
                thread = self._thread
            if thread is not None:
                # join OUTSIDE the lock: the producer's finally-clause and the
                # stats/gauge readers must stay live while we wait it out
                thread.join(timeout)
                if thread.is_alive():
                    raise RuntimeError(
                        f"rollout producer failed to stop within {timeout}s"
                    )
                with self._stats_lock:
                    if self._thread is thread:  # re-check under the lock
                        self._thread = None
            with self._stats_lock:
                error = self._error
            if error is not None:
                raise RuntimeError("async rollout producer died") from error
            stats = self.summary()
            stats["leftover"] = self.queue.qsize()
            return stats
        finally:
            # a finished producer must neither page the watchdog nor keep its
            # last gauge values being exported as if still live
            watchdog.unregister(PRODUCER_HEARTBEAT)
            gauges.clear(prefix="rollout/")

    def abandon(self):
        """Give up on this engine without draining it (supervisor restart path).

        Sets the stop event (a wedged-by-chaos or healthy producer exits at
        the next check) but does NOT close the shared queue and does NOT join:
        a genuinely wedged thread cannot be joined, and as a daemon it is
        harmless once abandoned. Its finally-clause is told not to close the
        queue either, so the successor engine keeps feeding the same queue."""
        with self._stats_lock:
            self._abandoned = True
        self._stop_evt.set()

    @property
    def running(self) -> bool:
        with self._stats_lock:
            thread = self._thread
        return thread is not None and thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> bool:
        """Best-effort join of the producer thread without ``stop()`` semantics
        (the supervisor reaps abandoned generations this way). Returns whether
        the thread is still alive afterwards."""
        with self._stats_lock:
            thread = self._thread
        if thread is None:
            return False
        thread.join(timeout)
        return thread.is_alive()

    @contextlib.contextmanager
    def paused(self):
        """Hold production across a critical section (e.g. ``evaluate()``)."""
        with self._pause_lock:
            yield

    # ----------------------------------------------------------------- learner

    def collect(self, n: int, learner_version: int, timeout: Optional[float] = None) -> List[Any]:
        """Pop ``n`` staleness-admitted elements for the learner; dropped-stale
        elements are replaced by further pops. Raises if the producer died or
        the queue closed before ``n`` elements could be collected."""
        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[Any] = []
        while len(out) < n:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise TimeoutError(
                    f"collected {len(out)}/{n} rollouts within {timeout}s "
                    f"(queue depth {self.queue.qsize()})"
                )
            got = self.queue.get(n - len(out), timeout=1.0 if remaining is None else min(1.0, remaining))
            if not got:
                with self._stats_lock:
                    error = self._error
                if error is not None:
                    raise RuntimeError("async rollout producer died") from error
                if self.queue.closed and self.queue.qsize() == 0:
                    raise RuntimeError(
                        f"experience queue closed after {len(out)}/{n} rollouts"
                    )
                # liveness, not just error state: a producer killed without
                # running its except-clause (or never started) leaves _error
                # unset and the queue open — with timeout=None this loop would
                # otherwise poll an empty queue forever
                if not self.running and not self._stop_evt.is_set() and self.queue.qsize() == 0:
                    raise RuntimeError(
                        f"async rollout producer is not running (no error recorded); "
                        f"collected {len(out)}/{n} rollouts from an empty open queue"
                    )
                continue
            fresh, dropped = self.accountant.admit(got, learner_version)
            if dropped:
                logger.info(
                    f"dropped {dropped} rollouts staler than "
                    f"{self.accountant.max_staleness} (learner v{learner_version})"
                )
            out.extend(fresh)
        self._export_gauges()
        return out

    # ------------------------------------------------------------------ metrics

    def overlap_fraction(self) -> float:
        """Fraction of engine wall-time the producer spent generating — the
        recovered generator utilization (1.0 = fully hidden behind learning)."""
        with self._stats_lock:
            wall_start = self._wall_start
            busy = self._busy_time
        if wall_start is None:
            return 0.0
        wall = max(time.monotonic() - wall_start, 1e-9)
        return min(1.0, busy / wall)

    def summary(self) -> dict:
        q = self.queue.stats()
        s = self.accountant.stats()
        with self._stats_lock:
            produced = self._produced
            dropped_shutdown = self._dropped_shutdown
        return {
            "produced": produced,
            # admitted-to-the-learner count, NOT raw queue pops: with
            # ``leftover`` stamped by stop(), the drain ledger balances as
            # produced == consumed + dropped_stale + leftover + dropped_shutdown
            "consumed": s["admitted"],
            "dropped_stale": s["dropped_stale"],
            "dropped_shutdown": dropped_shutdown,
            "peak_queue_depth": q["peak_depth"],
            "overlap_fraction": self.overlap_fraction(),
            "staleness_mean": s["staleness_mean"],
            "staleness_max": s["staleness_max"],
        }

    def _export_gauges(self):
        q = self.queue.stats()
        s = self.accountant.stats()
        gauges.set("rollout/queue_depth", float(q["depth"]))
        gauges.set("rollout/queue_peak_depth", float(q["peak_depth"]))
        gauges.set("rollout/queue_gated", q["gated"])
        with self._stats_lock:
            produced = self._produced
            dropped_shutdown = self._dropped_shutdown
        gauges.set("rollout/produced", float(produced))
        gauges.set("rollout/dropped_shutdown", float(dropped_shutdown))
        gauges.set("rollout/dropped_stale", float(s["dropped_stale"]))
        gauges.set("rollout/staleness_mean", float(s["staleness_last_mean"]))
        gauges.set("rollout/staleness_max", float(s["staleness_max"]))
        gauges.set("rollout/overlap_fraction", self.overlap_fraction())
