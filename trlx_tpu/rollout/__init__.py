"""Async rollout subsystem: disaggregated experience generation / PPO learning.

The PPO hot loop is two phases — ``make_experience`` (decode-bound) and the
optimizer epochs (FLOP-bound) — and running them strictly alternately idles the
generator for the whole learn phase. This package turns experience generation
into a continuously-running *producer* decoupled from the learner through a
bounded queue, with explicit off-policy staleness control (the OPPO /
LlamaRL-style pipelined-rollout design; see docs/rollout.md):

- :mod:`trlx_tpu.rollout.queue` — bounded thread-safe experience queue with
  backpressure, high/low watermark hysteresis, and drain-on-shutdown.
- :mod:`trlx_tpu.rollout.publisher` — versioned parameter snapshots (monotonic
  policy version; donate-free device copies) so the producer samples with
  version *v* while the learner optimizes toward *v+1*.
- :mod:`trlx_tpu.rollout.broadcast` — chunked, decode-overlapped weight
  broadcast for the island split: layer-by-layer staging under a round gate,
  version-stamped manifests, atomic commit (``train.islands``;
  docs/parallelism.md "Islands").
- :mod:`trlx_tpu.rollout.staleness` — staleness accounting, the
  ``max_staleness`` admission cap, and the clipped per-token importance-weight
  correction applied inside the PPO loss.
- :mod:`trlx_tpu.rollout.engine` — the producer loop wrapping the trainer's
  jitted generate/score pipeline, tagging every element with the policy
  version it was sampled from.
- :mod:`trlx_tpu.rollout.supervisor` — self-healing wrapper that restarts a
  crashed or watchdog-wedged producer with exponential backoff and a bounded
  restart budget (``TrainConfig.self_healing``; docs/resilience.md).

Enabled via ``TrainConfig.async_rollouts``; the synchronous path stays the
default and ``max_staleness=0`` falls back to it exactly.
"""

from trlx_tpu.rollout.broadcast import (
    BroadcastManifest,
    ChunkedParameterPublisher,
    layer_chunks,
)
from trlx_tpu.rollout.engine import AsyncRolloutEngine
from trlx_tpu.rollout.publisher import ParameterPublisher
from trlx_tpu.rollout.queue import ExperienceQueue, QueueClosed
from trlx_tpu.rollout.reorder import ReorderBuffer
from trlx_tpu.rollout.staleness import StalenessAccountant, staleness_importance_weights
from trlx_tpu.rollout.supervisor import ProducerRestartBudgetExceeded, ProducerSupervisor

__all__ = [
    "AsyncRolloutEngine",
    "BroadcastManifest",
    "ChunkedParameterPublisher",
    "layer_chunks",
    "ExperienceQueue",
    "ParameterPublisher",
    "ProducerRestartBudgetExceeded",
    "ProducerSupervisor",
    "QueueClosed",
    "ReorderBuffer",
    "StalenessAccountant",
    "staleness_importance_weights",
]
