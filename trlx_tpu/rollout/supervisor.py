"""Producer supervision: restart a crashed or wedged rollout producer.

The base :class:`~trlx_tpu.rollout.engine.AsyncRolloutEngine` contract is
deliberately fatal — a producer crash closes the queue and re-raises from
``collect``, because *silently* losing the experience stream is worse than
dying. But a production run should not die for a transient fault (a reward
endpoint hiccup, one poisoned generation batch, a wedged RPC): supervision
turns "re-raise fatally" into "restart with backoff, bounded by a budget".

:class:`ProducerSupervisor` is a drop-in replacement for the engine from the
trainer's point of view (``publisher`` / ``start`` / ``collect`` /
``paused`` / ``running`` / ``stop``), built on three mechanisms:

- **Engine generations.** The supervisor owns an ``engine_factory`` that
  builds a fresh :class:`AsyncRolloutEngine` sharing the *same* queue,
  publisher, and staleness accountant, constructed with
  ``close_queue_on_death=False`` so a dead generation never closes the queue
  its successor must feed. Restart = ``abandon()`` the old generation (set
  its stop event, never join a wedged thread), sleep an exponential backoff
  (``restart_backoff_base_s`` doubling up to ``restart_backoff_max_s``),
  build + start the next one. The new producer's first iteration reads
  ``publisher.latest()`` — that *is* the resync: it samples with the
  freshest published policy, not the snapshot the dead producer held. The
  same contract covers the chunked island publisher
  (:class:`~trlx_tpu.rollout.broadcast.ChunkedParameterPublisher`):
  ``latest()`` only ever returns *committed* broadcasts, so a restart can
  resync mid-broadcast without observing a torn version.
- **Crash detection at the collect seam.** All recovery runs on the learner
  thread inside :meth:`collect`: the engine's own liveness checks (error
  recorded, thread dead without error) raise ``RuntimeError``, the
  supervisor catches it and restarts. No third supervision thread exists —
  the learner is the only party that *needs* experience, so it is the right
  place to pay for recovery.
- **Wedge detection.** A wedged producer raises nothing. Two independent
  detectors cover it: the obs watchdog's per-heartbeat escalation hook
  (:meth:`StallWatchdog.escalate` on ``"rollout-producer"``) sets a flag
  from the watchdog thread, and a supervisor-side fallback restarts when
  ``collect`` has waited ``wedge_timeout_s`` with a live-but-silent producer
  (covers runs with the watchdog disabled).

The restart budget fails closed: exceeding ``max_producer_restarts`` writes
a diagnostics bundle (gauges, restart history, thread stacks — the wedged
thread's stack is the payload) and raises
:class:`ProducerRestartBudgetExceeded` with the bundle path in the message.
Every restart increments the ``resilience/restarts`` gauge.
"""

import time
import threading
from typing import Any, Callable, Dict, List, Optional

from trlx_tpu.obs import watchdog
from trlx_tpu.rollout.engine import PRODUCER_HEARTBEAT, AsyncRolloutEngine
from trlx_tpu.utils import logging
from trlx_tpu.utils.metrics import gauges

logger = logging.get_logger(__name__)


class ProducerRestartBudgetExceeded(RuntimeError):
    """Restart budget exhausted; the message carries the diagnostics bundle path."""


class ProducerSupervisor:
    """Self-healing wrapper around generations of rollout engines (module docs).

    Single-consumer by design: ``collect``/``stop`` run on the learner
    thread; the only cross-thread touch is the watchdog escalation setting
    ``_wedge_evt``.
    """

    def __init__(
        self,
        engine_factory: Callable[[], AsyncRolloutEngine],
        max_restarts: int = 5,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        wedge_timeout_s: Optional[float] = 600.0,
        diagnostics_dir: str = "diagnostics",
        heartbeat: str = PRODUCER_HEARTBEAT,
    ):
        self._factory = engine_factory
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.wedge_timeout_s = None if wedge_timeout_s is None else float(wedge_timeout_s)
        self.diagnostics_dir = diagnostics_dir
        self._heartbeat = heartbeat
        self._engine: Optional[AsyncRolloutEngine] = None
        self._abandoned: List[AsyncRolloutEngine] = []
        self._wedge_evt = threading.Event()
        self.restarts = 0
        self.restart_history: List[Dict[str, Any]] = []
        # drain stats of abandoned generations, folded into stop()'s summary
        self._dead_produced = 0
        self._dead_dropped_shutdown = 0

    # ---------------------------------------------------------------- lifecycle

    def start(self):
        if self._engine is not None:
            raise RuntimeError("supervisor already started")
        self._engine = self._factory()
        self._engine.start()
        # watchdog escalation: a stale producer heartbeat becomes a restart
        # request, not just a stack dump. The callback must return fast (it
        # runs on the watchdog thread): set the flag, let collect() act on it.
        watchdog.escalate(self._heartbeat, self._on_stall)

    def _on_stall(self, name: str, age: float):
        logger.warning(
            f"watchdog escalation: heartbeat {name!r} stale for {age:.1f}s — "
            f"flagging producer as wedged for supervised restart"
        )
        self._wedge_evt.set()

    def stop(self, timeout: Optional[float] = 30.0) -> dict:
        """Stop the current generation and return aggregated drain stats."""
        watchdog.escalate(self._heartbeat, None)
        engine = self._engine
        self._engine = None
        if engine is None:
            return {"producer_restarts": self.restarts}
        try:
            stats = engine.stop(timeout)
        except RuntimeError as e:
            # a generation that died right before shutdown is not a *shutdown*
            # failure — report the drain honestly instead of re-raising
            logger.warning(f"supervised producer was dead at stop(): {e}")
            stats = engine.summary()
            stats["leftover"] = engine.queue.qsize()
            engine.queue.close()
        for old in self._abandoned:
            old.join(timeout=1.0)  # best effort; wedged daemons linger
        stats["produced"] += self._dead_produced
        stats["dropped_shutdown"] += self._dead_dropped_shutdown
        stats["producer_restarts"] = self.restarts
        return stats

    @property
    def publisher(self):
        return self._require_engine().publisher

    @property
    def running(self) -> bool:
        return self._engine is not None and self._engine.running

    def paused(self):
        return self._require_engine().paused()

    def summary(self) -> dict:
        stats = self._require_engine().summary()
        stats["produced"] += self._dead_produced
        stats["dropped_shutdown"] += self._dead_dropped_shutdown
        stats["producer_restarts"] = self.restarts
        return stats

    def _require_engine(self) -> AsyncRolloutEngine:
        if self._engine is None:
            raise RuntimeError("supervisor not started")
        return self._engine

    # ------------------------------------------------------------------ restart

    def _restart(self, reason: str, cause: Optional[BaseException] = None):
        self.restarts += 1
        gauges.set("resilience/restarts", float(self.restarts))
        if self.restarts > self.max_restarts:
            from trlx_tpu.resilience.health import write_diagnostics_bundle

            bundle = write_diagnostics_bundle(
                self.diagnostics_dir,
                kind="producer-restart-budget",
                extra={
                    "restart_history": self.restart_history,
                    "last_reason": reason,
                    "max_restarts": self.max_restarts,
                },
            )
            raise ProducerRestartBudgetExceeded(
                f"rollout producer restart budget exhausted "
                f"({self.max_restarts} restarts); last failure: {reason}; "
                f"diagnostics bundle: {bundle}"
            ) from cause
        backoff = min(self.backoff_base_s * (2 ** (self.restarts - 1)), self.backoff_max_s)
        self.restart_history.append({"time": time.time(), "reason": reason, "backoff_s": backoff})
        logger.warning(
            f"restarting rollout producer ({self.restarts}/{self.max_restarts}, "
            f"backoff {backoff:.2f}s) after: {reason}"
        )
        old = self._require_engine()
        old.abandon()
        dead_stats = old.summary()
        self._dead_produced += dead_stats["produced"]
        self._dead_dropped_shutdown += dead_stats["dropped_shutdown"]
        self._abandoned.append(old)
        self._wedge_evt.clear()
        time.sleep(backoff)
        # the successor's first produce reads publisher.latest(): the restart
        # resyncs to the freshest policy instead of replaying a stale snapshot
        self._engine = self._factory()
        self._engine.start()

    # ------------------------------------------------------------------ learner

    def collect(self, n: int, learner_version: int, timeout: Optional[float] = None) -> List[Any]:
        """Pop ``n`` admitted elements, restarting the producer as needed.

        The caller's ``timeout`` bounds the *whole* collect including
        restarts and backoff; ``TimeoutError`` is not a producer failure and
        consumes no restart budget.
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[Any] = []
        last_progress = time.monotonic()
        while len(out) < n:
            if self._wedge_evt.is_set():
                self._restart("watchdog escalation: stale producer heartbeat")
                last_progress = time.monotonic()
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise TimeoutError(
                    f"collected {len(out)}/{n} rollouts within {timeout}s "
                    f"(after {self.restarts} producer restarts)"
                )
            slice_s = 1.0 if self.wedge_timeout_s is None else max(0.05, self.wedge_timeout_s / 4)
            if remaining is not None:
                slice_s = min(slice_s, remaining)
            engine = self._require_engine()
            try:
                # one element per call: engine.collect discards its partial
                # batch when its timeout fires, so short supervision slices
                # must never ask for more than they can lose
                got = engine.collect(1, learner_version, timeout=slice_s)
            except TimeoutError:
                waited = time.monotonic() - last_progress
                if self.wedge_timeout_s is not None and waited > self.wedge_timeout_s:
                    self._restart(
                        f"wedge timeout: producer alive but silent for {waited:.1f}s "
                        f"while the learner waited (wedge_timeout_s={self.wedge_timeout_s})"
                    )
                    last_progress = time.monotonic()
                continue
            except RuntimeError as e:
                if engine.queue.closed:
                    raise  # external shutdown, not a producer fault
                self._restart(f"producer died: {e}", cause=e)
                last_progress = time.monotonic()
                continue
            out.extend(got)
            last_progress = time.monotonic()
            # delivery disproves a pending wedge escalation: the watchdog may
            # have flagged the producer just as it recovered, and acting on
            # that stale flag would abandon a healthy generation and burn a
            # restart. Queue delivery IS the producer's liveness proof.
            self._wedge_evt.clear()
        return out
