"""Staleness accounting and importance-weight correction for async PPO.

Staleness of an experience element is ``learner_version - policy_version``:
how many parameter publishes happened between sampling it and training on it.
Two mechanisms keep async PPO honest (the OPPO / LlamaRL recipe):

- **Admission cap** (:class:`StalenessAccountant`): elements staler than
  ``max_staleness`` are dropped at consumption time rather than trained on.
  ``max_staleness=0`` is reserved as "fully on-policy" — the trainer falls
  back to the synchronous path entirely instead of running the producer.
- **Clipped importance weights** (:func:`staleness_importance_weights`): for
  admitted-but-stale samples, the PPO policy-gradient term is reweighted by a
  per-token clipped IS ratio of the current policy against the behavior
  policy whose logprobs are already stored in ``PPORLBatch.logprobs``. At
  staleness 0 the weight is *exactly* 1.0 (a ``where`` on the staleness, not
  an algebraic identity), so the corrected loss is bitwise-identical to the
  vanilla loss on on-policy data.
"""

import threading
from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp


def staleness_importance_weights(
    log_ratio: jnp.ndarray, staleness: jnp.ndarray, clip_ratio: float
) -> jnp.ndarray:
    """Per-token clipped IS weights, exactly 1.0 where staleness == 0.

    :param log_ratio: [B, T] masked log(pi_current / pi_behavior) over response
        tokens (the PPO loss already computes this from the stored behavior
        logprobs).
    :param staleness: [B] (or [B, T]) integer policy-version lag per sample.
    :param clip_ratio: weights are clipped to ``[1/clip_ratio, clip_ratio]``.
    """
    if clip_ratio < 1.0:
        raise ValueError(f"clip_ratio must be >= 1.0, got {clip_ratio}")
    w = jnp.clip(jnp.exp(log_ratio), 1.0 / clip_ratio, clip_ratio)
    # a fixed reweighting of the surrogate, not a new gradient path
    w = jax.lax.stop_gradient(w)
    stale = staleness > 0
    if stale.ndim == log_ratio.ndim - 1:
        stale = stale[:, None]
    return jnp.where(stale, w, jnp.ones_like(w))


class StalenessAccountant:
    """Admission control + running staleness statistics (thread-safe).

    ``admit`` filters a freshly-popped batch of elements against the cap and
    records the observed staleness distribution; ``stats`` exposes the gauges
    the trainer exports through the trackers.
    """

    def __init__(self, max_staleness: int):
        if max_staleness < 0:
            raise ValueError(f"max_staleness must be >= 0, got {max_staleness}")
        self.max_staleness = int(max_staleness)
        self._lock = threading.Lock()
        self._admitted = 0
        self._dropped = 0
        self._staleness_sum = 0
        self._staleness_max = 0
        self._last_mean = 0.0
        self._last_max = 0

    @staticmethod
    def element_staleness(element: Any, learner_version: int) -> int:
        version = int(getattr(element, "policy_version", 0) or 0)
        return max(0, int(learner_version) - version)

    def admit(
        self, elements: Sequence[Any], learner_version: int
    ) -> Tuple[List[Any], int]:
        """Split ``elements`` into (admitted, n_dropped) under the cap."""
        fresh: List[Any] = []
        staleness_values: List[int] = []
        dropped = 0
        for e in elements:
            s = self.element_staleness(e, learner_version)
            if s > self.max_staleness:
                dropped += 1
                continue
            fresh.append(e)
            staleness_values.append(s)
        with self._lock:
            self._dropped += dropped
            self._admitted += len(fresh)
            if staleness_values:
                self._staleness_sum += sum(staleness_values)
                self._last_max = max(staleness_values)
                self._staleness_max = max(self._staleness_max, self._last_max)
                self._last_mean = sum(staleness_values) / len(staleness_values)
        return fresh, dropped

    def stats(self) -> dict:
        with self._lock:
            return {
                "admitted": self._admitted,
                "dropped_stale": self._dropped,
                "staleness_mean": (
                    self._staleness_sum / self._admitted if self._admitted else 0.0
                ),
                "staleness_last_mean": self._last_mean,
                "staleness_last_max": self._last_max,
                "staleness_max": self._staleness_max,
            }
