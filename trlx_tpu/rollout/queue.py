"""Bounded thread-safe experience queue with backpressure and watermarks.

One producer (the rollout engine thread) pushes lists of experience elements;
one consumer (the learner, on the main thread) pops fixed counts. Three
properties matter for the async rollout design and are each load-bearing:

- **Hard bound**: the queue never holds more than ``capacity`` elements, so a
  fast producer cannot run unboundedly ahead of the learner (which would both
  waste generation and blow up staleness).
- **Watermark hysteresis**: once depth reaches ``high_watermark`` the producer
  is gated until the learner drains it back to ``low_watermark``. Without the
  hysteresis the producer wakes for every popped element and generates
  one-chunk dribbles right at the bound; with it, production happens in runs
  that keep the generator's batches full.
- **Drain-on-shutdown**: ``close()`` wakes every waiter; pending ``put`` calls
  raise :class:`QueueClosed`, while ``get`` returns whatever is left (then
  empty lists), so the learner can consume the tail before teardown.
"""

import threading
import time
from collections import deque
from typing import Any, Iterable, List, Optional


class QueueClosed(RuntimeError):
    """Raised by :meth:`ExperienceQueue.put` after :meth:`ExperienceQueue.close`."""


class ExperienceQueue:
    """Bounded FIFO of experience elements shared between one producer thread
    and one consumer thread (see module docstring for semantics)."""

    def __init__(
        self,
        capacity: int,
        high_watermark: Optional[int] = None,
        low_watermark: Optional[int] = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.high_watermark = self.capacity if high_watermark is None else int(high_watermark)
        self.low_watermark = (
            self.high_watermark // 2 if low_watermark is None else int(low_watermark)
        )
        if not 0 <= self.low_watermark <= self.high_watermark <= self.capacity:
            raise ValueError(
                f"need 0 <= low_watermark <= high_watermark <= capacity, got "
                f"low={self.low_watermark} high={self.high_watermark} cap={self.capacity}"
            )
        self._items: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._gated = False
        self._peak_depth = 0
        self._total_put = 0
        self._total_got = 0

    # ---------------------------------------------------------------- producer

    def put(self, items: Iterable[Any], timeout: Optional[float] = None) -> bool:
        """Append ``items`` atomically. Blocks while the queue is gated (above
        the high watermark and not yet drained to the low watermark) or while
        the batch would exceed ``capacity``. Returns False on timeout; raises
        :class:`QueueClosed` if the queue is (or becomes) closed."""
        items = list(items)
        if len(items) > self.capacity:
            raise ValueError(
                f"batch of {len(items)} exceeds queue capacity {self.capacity}"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._closed:
                    raise QueueClosed("experience queue is closed")
                if not self._gated and len(self._items) + len(items) <= self.capacity:
                    break
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            self._items.extend(items)
            self._total_put += len(items)
            self._peak_depth = max(self._peak_depth, len(self._items))
            if len(self._items) >= self.high_watermark:
                self._gated = True
            self._cond.notify_all()
            return True

    # ---------------------------------------------------------------- consumer

    def get(self, n: int, timeout: Optional[float] = None) -> List[Any]:
        """Pop up to ``n`` elements (FIFO), blocking until at least one is
        available. Never blocks on *fullness* of the request — the consumer
        must accept partial batches, or a high watermark below the consumer's
        demand would deadlock a gated producer against a waiting consumer.
        After :meth:`close`, returns whatever remains (eventually ``[]``).
        On timeout returns ``[]`` without consuming."""
        if n < 1:
            return []
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._items and not self._closed:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return []
                self._cond.wait(remaining)
            k = min(n, len(self._items))
            out = [self._items.popleft() for _ in range(k)]
            self._total_got += k
            if self._gated and len(self._items) <= self.low_watermark:
                self._gated = False
            self._cond.notify_all()
            return out

    # ---------------------------------------------------------------- lifecycle

    def close(self):
        """Stop accepting puts and wake every waiter (idempotent)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # ------------------------------------------------------------------- state

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    @property
    def gated(self) -> bool:
        with self._cond:
            return self._gated

    def qsize(self) -> int:
        with self._cond:
            return len(self._items)

    def stats(self) -> dict:
        """Counters for the rollout gauges (peak depth proves the bound held)."""
        with self._cond:
            return {
                "depth": len(self._items),
                "peak_depth": self._peak_depth,
                "capacity": self.capacity,
                "total_put": self._total_put,
                "total_got": self._total_got,
                "gated": float(self._gated),
            }
