"""Experiment trackers: wandb / tensorboard / jsonl (offline default).

Parity: the reference logs through ``accelerator.init_trackers``/``accelerator.log``
(wandb or tensorboard, `accelerate_base_trainer.py:79-136,644`). Here trackers are a
tiny strategy class; ``jsonl`` keeps full observability in zero-egress environments.
Only process 0 logs (parity: rank-0 tracker init).
"""

import json
import os
import time
from typing import Any, Dict, Optional

import jax

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)


class BaseTracker:
    def log(self, stats: Dict[str, Any], step: int):
        pass

    def log_table(self, name: str, columns, rows, step: int):
        pass

    def finish(self):
        pass


class JsonlTracker(BaseTracker):
    def __init__(self, logging_dir: str, run_name: str, config: Optional[dict] = None):
        os.makedirs(logging_dir, exist_ok=True)
        self.path = os.path.join(logging_dir, f"{run_name}.jsonl")
        self._f = open(self.path, "a")
        if config is not None:
            self._f.write(json.dumps({"_config": config, "_time": time.time()}) + "\n")

    def log(self, stats, step):
        rec = {"step": step, "_time": time.time()}
        for k, v in stats.items():
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                continue
        self._f.write(json.dumps(rec) + "\n")
        # flush per record: an async-rollout run killed mid-flight (or a
        # preempted TPU VM) must not lose the tail of its stats
        self._f.flush()

    def log_table(self, name, columns, rows, step):
        self._f.write(
            json.dumps({"step": step, "_table": name, "columns": columns, "rows": rows[:32]})
            + "\n"
        )
        self._f.flush()

    def finish(self):
        if self._f.closed:
            return
        try:
            self._f.flush()
            os.fsync(self._f.fileno())  # durable through an OS-level crash too
        except OSError:
            pass
        self._f.close()


def rows_to_markdown(columns, rows, max_rows: int = 32) -> str:
    """Render a sample table as a GitHub-style markdown table (pipes escaped
    so generated text can't break the layout)."""

    def cell(v):
        return str(v).replace("|", "\\|").replace("\n", " ")

    lines = [
        "| " + " | ".join(cell(c) for c in columns) + " |",
        "|" + " --- |" * len(columns),
    ]
    for row in rows[:max_rows]:
        lines.append("| " + " | ".join(cell(v) for v in row) + " |")
    if len(rows) > max_rows:
        lines.append(f"\n_… {len(rows) - max_rows} more rows truncated_")
    return "\n".join(lines)


class TensorboardTracker(BaseTracker):
    def __init__(self, logging_dir: str, run_name: str, config=None):
        from torch.utils.tensorboard import SummaryWriter

        self.writer = SummaryWriter(os.path.join(logging_dir, run_name))

    def log(self, stats, step):
        for k, v in stats.items():
            try:
                self.writer.add_scalar(k, float(v), step)
            except (TypeError, ValueError):
                continue

    def log_table(self, name, columns, rows, step):
        # tensorboard has no table primitive: render the eval sample table as
        # markdown through add_text (the TEXT tab renders it) instead of
        # silently dropping it
        try:
            self.writer.add_text(name, rows_to_markdown(columns, rows), step)
        except Exception as e:
            logger.warning(f"tensorboard log_table failed ({e}); table dropped")

    def finish(self):
        # flush BEFORE close: close() alone can discard events still buffered
        # in the writer's queue at the end of a run
        try:
            self.writer.flush()
        finally:
            self.writer.close()


class WandbTracker(BaseTracker):
    """wandb backend. ``log``/``log_table`` swallow backend exceptions — a
    network hiccup mid-run must not kill training (the same contract
    :func:`make_tracker` applies to tracker construction)."""

    def __init__(self, project, entity, group, name, tags, config):
        import wandb

        self.run = wandb.init(
            project=project, entity=entity, group=group, name=name, tags=tags,
            config=config, reinit=True,
        )
        self.wandb = wandb

    def log(self, stats, step):
        try:
            self.run.log(dict(stats), step=step)
        except Exception as e:
            logger.warning(f"wandb log failed at step {step} ({e}); stats dropped")

    def log_table(self, name, columns, rows, step):
        try:
            table = self.wandb.Table(columns=columns, rows=rows)
            self.run.log({name: table}, step=step)
        except Exception as e:
            logger.warning(f"wandb log_table failed at step {step} ({e}); table dropped")

    def finish(self):
        try:
            self.run.finish()
        except Exception as e:
            logger.warning(f"wandb finish failed ({e})")


def make_tracker(train_config, full_config: dict) -> BaseTracker:
    """Build the configured tracker on process 0; BaseTracker (no-op) elsewhere."""
    if jax.process_index() != 0 or train_config.tracker is None:
        return BaseTracker()
    run_name = train_config.run_name or f"run-{int(time.time())}"
    logging_dir = train_config.logging_dir or os.path.join(
        train_config.checkpoint_dir, "logs"
    )
    kind = train_config.tracker
    try:
        if kind == "wandb":
            return WandbTracker(
                train_config.project_name, train_config.entity_name,
                train_config.group_name, run_name, list(train_config.tags), full_config,
            )
        if kind == "tensorboard":
            return TensorboardTracker(logging_dir, run_name, full_config)
        if kind == "jsonl":
            return JsonlTracker(logging_dir, run_name, full_config)
    except Exception as e:  # tracker backends are optional; never kill training
        logger.warning(f"Tracker {kind!r} unavailable ({e}); falling back to jsonl")
        return JsonlTracker(logging_dir, run_name, full_config)
    raise ValueError(f"Unknown tracker {kind!r}")
