"""Shared name→class registry factory used by the method / pipeline / trainer
registries (the reference repeats this decorator three times; here it is one)."""

from typing import Dict


def make_registry(store: Dict[str, type]):
    """Return a ``register`` decorator writing (lowercased name → class) into
    ``store``. Accepts ``@register``, ``@register("name")``, or ``register(cls)``."""

    def register(name_or_cls=None):
        def _register(cls, name=None):
            store[(name or cls.__name__).lower()] = cls
            return cls

        if isinstance(name_or_cls, str):
            return lambda cls: _register(cls, name_or_cls)
        if name_or_cls is None:
            return _register
        return _register(name_or_cls)

    return register
