"""Metrics: text-overlap scores (ROUGE) and process-local runtime gauges.

Runtime gauges (:class:`GaugeRegistry` / the module-level :data:`gauges`) are
thread-safe named floats that background subsystems — currently the async
rollout engine (queue depth, staleness, overlap fraction) and the obs layer
(stall counts, step-time histograms) — set from worker threads; the trainer
merges ``gauges.snapshot()`` into its per-step stats so every tracker backend
(wandb / tensorboard / jsonl) exports them without knowing about the producers.

Besides plain ``set``/``inc`` gauges, the registry keeps **streaming
histograms**: ``observe(name, value)`` appends to a bounded per-name window
and ``hist_stats(name)`` reduces it to p50/p95/max/mean/count — how step-time
tail latency (``time/step_p95``) reaches the trackers without storing an
unbounded series. ``clear(prefix=...)`` drops a subsystem's gauges when it
shuts down (the rollout engine clears ``rollout/*`` so a finished producer's
stale gauges stop being exported in later steps).

Text-overlap metrics (ROUGE) — from-scratch, zero-dependency.

The reference's summarize_rlhf example publishes its only quality numbers as a
ROUGE table computed with ``evaluate.load("rouge")``
(`/root/reference/examples/summarize_rlhf/trlx_inference_gptj.py:70-135`,
README table: SFT 0.240 / PPO 0.223 avg ROUGE). That package wraps
``rouge_score`` (Google); neither is baked into this image, so this module
reimplements the same scores: ROUGE-N F-measure on n-gram multiset overlap and
ROUGE-L F-measure on the longest common subsequence, with rouge_score's
default tokenization (lowercase, runs of [a-z0-9]) and no stemming
(evaluate's default ``use_stemmer=False``).
"""

import math
import re
import threading
from collections import Counter, deque
from typing import Dict, List, Sequence


def nearest_rank(sorted_values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample: the smallest
    member such that at least ``q`` of the sample is <= it, i.e. index
    ``ceil(q*n) - 1``. The previous ``int(q*n)`` indexing selected one rank
    too high for most n (n=2 p50 returned the LARGER value); every percentile
    in the repo now routes through this one definition."""
    n = len(sorted_values)
    if n == 0:
        return 0.0
    return sorted_values[min(n - 1, max(0, math.ceil(q * n) - 1))]


class GaugeRegistry:
    """Thread-safe named float gauges + streaming histograms (see module
    docstring)."""

    def __init__(self, hist_window: int = 512):
        self._lock = threading.Lock()
        self._values: Dict[str, float] = {}
        self._hists: Dict[str, deque] = {}
        self._hist_counts: Dict[str, int] = {}
        self.hist_window = int(hist_window)

    def set(self, name: str, value: float):
        with self._lock:
            self._values[name] = float(value)

    def inc(self, name: str, delta: float = 1.0):
        with self._lock:
            self._values[name] = self._values.get(name, 0.0) + float(delta)

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._values.get(name, default)

    def observe(self, name: str, value: float):
        """Append ``value`` to the bounded streaming histogram ``name``."""
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = deque(maxlen=self.hist_window)
            hist.append(float(value))
            self._hist_counts[name] = self._hist_counts.get(name, 0) + 1

    def hist_stats(self, name: str) -> Dict[str, float]:
        """p50/p95/max/mean/count over the histogram's current window (count is
        lifetime observations, not window size). Empty dict if never observed."""
        with self._lock:
            hist = self._hists.get(name)
            if not hist:
                return {}
            values = sorted(hist)
            count = self._hist_counts[name]
        n = len(values)
        # nearest-rank percentiles: exact window members, no interpolation
        p50 = nearest_rank(values, 0.50)
        p95 = nearest_rank(values, 0.95)
        return {
            "p50": p50,
            "p95": p95,
            "max": values[-1],
            "mean": sum(values) / n,
            "count": float(count),
        }

    def hist_snapshot(self, prefix: str = "") -> Dict[str, float]:
        """Flattened ``{name_p50: v, name_p95: v, name_max: v}`` for every
        histogram under ``prefix`` — merged into per-step tracker stats."""
        with self._lock:
            names = [k for k in self._hists if k.startswith(prefix)]
        out: Dict[str, float] = {}
        for name in names:
            stats = self.hist_stats(name)
            for key in ("p50", "p95", "max"):
                if key in stats:
                    out[f"{name}_{key}"] = stats[key]
        return out

    def snapshot(self, prefix: str = "") -> Dict[str, float]:
        """Copy of the current gauges (optionally filtered by name prefix)."""
        with self._lock:
            return {k: v for k, v in self._values.items() if k.startswith(prefix)}

    def clear(self, prefix: str = ""):
        """Drop gauges and histograms under ``prefix`` ("" clears everything) —
        called by subsystems on shutdown so their last values don't keep being
        exported as if still live."""
        with self._lock:
            if not prefix:
                self._values.clear()
                self._hists.clear()
                self._hist_counts.clear()
                return
            for store in (self._values, self._hists, self._hist_counts):
                for k in [k for k in store if k.startswith(prefix)]:
                    del store[k]


#: Process-global registry; subsystems set, the trainer step exports.
gauges = GaugeRegistry()


_TOKEN_RE = re.compile(r"[a-z0-9]+")


def _tokenize(text: str) -> List[str]:
    return _TOKEN_RE.findall(text.lower())


def _f_measure(p: float, r: float) -> float:
    return 0.0 if p + r == 0 else 2 * p * r / (p + r)


def _rouge_n(pred: List[str], ref: List[str], n: int) -> float:
    pred_ngrams = Counter(tuple(pred[i:i + n]) for i in range(len(pred) - n + 1))
    ref_ngrams = Counter(tuple(ref[i:i + n]) for i in range(len(ref) - n + 1))
    if not pred_ngrams or not ref_ngrams:
        return 0.0
    overlap = sum((pred_ngrams & ref_ngrams).values())
    return _f_measure(
        overlap / max(1, sum(pred_ngrams.values())),
        overlap / max(1, sum(ref_ngrams.values())),
    )


def _lcs_len(a: List[str], b: List[str]) -> int:
    if not a or not b:
        return 0
    # one-row DP; O(len(a)*len(b)) time, O(len(b)) space — summaries are short
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y else max(prev[j], cur[-1]))
        prev = cur
    return prev[-1]


def _rouge_l(pred: List[str], ref: List[str]) -> float:
    if not pred or not ref:
        return 0.0
    lcs = _lcs_len(pred, ref)
    return _f_measure(lcs / len(pred), lcs / len(ref))


def rouge(pred: str, ref: str) -> Dict[str, float]:
    """ROUGE-1/2/L F-measures for one (prediction, reference) pair."""
    p, r = _tokenize(pred), _tokenize(ref)
    return {"rouge1": _rouge_n(p, r, 1), "rouge2": _rouge_n(p, r, 2), "rougeL": _rouge_l(p, r)}


def rouge_scores(
    predictions: Sequence[str], references: Sequence[str]
) -> Dict[str, float]:
    """Corpus ROUGE: per-pair F-measures averaged (what ``evaluate``'s rouge
    returns), plus ``rouge_avg`` — the mean over 1/2/L that the reference's
    README table reports as "Average"."""
    assert len(predictions) == len(references), (len(predictions), len(references))
    if not predictions:
        return {"rouge1": 0.0, "rouge2": 0.0, "rougeL": 0.0, "rouge_avg": 0.0}
    totals = Counter()
    for pred, ref in zip(predictions, references):
        totals.update(rouge(pred, ref))
    n = len(predictions)
    out = {k: totals[k] / n for k in ("rouge1", "rouge2", "rougeL")}
    out["rouge_avg"] = (out["rouge1"] + out["rouge2"] + out["rougeL"]) / 3
    return out


def rouge_per_sample(
    predictions: Sequence[str], references: Sequence[str]
) -> Dict[str, List[float]]:
    """Per-sample ROUGE lists, shaped for a trainer ``metric_fn`` (each metric
    becomes a table column + a mean stat)."""
    rows = [rouge(p, r) for p, r in zip(predictions, references)]
    out: Dict[str, List[float]] = {k: [row[k] for row in rows] for k in ("rouge1", "rouge2", "rougeL")}
    out["rouge_avg"] = [
        (a + b + c) / 3 for a, b, c in zip(out["rouge1"], out["rouge2"], out["rougeL"])
    ]
    return out
