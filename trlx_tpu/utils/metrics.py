"""Metrics: text-overlap scores (ROUGE) and process-local runtime gauges.

Runtime gauges (:class:`GaugeRegistry` / the module-level :data:`gauges`) are
thread-safe named floats that background subsystems — currently the async
rollout engine (queue depth, staleness, overlap fraction) — set from worker
threads; the trainer merges ``gauges.snapshot()`` into its per-step stats so
every tracker backend (wandb / tensorboard / jsonl) exports them without
knowing about the producers.

Text-overlap metrics (ROUGE) — from-scratch, zero-dependency.

The reference's summarize_rlhf example publishes its only quality numbers as a
ROUGE table computed with ``evaluate.load("rouge")``
(`/root/reference/examples/summarize_rlhf/trlx_inference_gptj.py:70-135`,
README table: SFT 0.240 / PPO 0.223 avg ROUGE). That package wraps
``rouge_score`` (Google); neither is baked into this image, so this module
reimplements the same scores: ROUGE-N F-measure on n-gram multiset overlap and
ROUGE-L F-measure on the longest common subsequence, with rouge_score's
default tokenization (lowercase, runs of [a-z0-9]) and no stemming
(evaluate's default ``use_stemmer=False``).
"""

import re
import threading
from collections import Counter
from typing import Dict, List, Sequence


class GaugeRegistry:
    """Thread-safe named float gauges (see module docstring)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values: Dict[str, float] = {}

    def set(self, name: str, value: float):
        with self._lock:
            self._values[name] = float(value)

    def inc(self, name: str, delta: float = 1.0):
        with self._lock:
            self._values[name] = self._values.get(name, 0.0) + float(delta)

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._values.get(name, default)

    def snapshot(self, prefix: str = "") -> Dict[str, float]:
        """Copy of the current gauges (optionally filtered by name prefix)."""
        with self._lock:
            return {k: v for k, v in self._values.items() if k.startswith(prefix)}

    def clear(self):
        with self._lock:
            self._values.clear()


#: Process-global registry; subsystems set, the trainer step exports.
gauges = GaugeRegistry()


_TOKEN_RE = re.compile(r"[a-z0-9]+")


def _tokenize(text: str) -> List[str]:
    return _TOKEN_RE.findall(text.lower())


def _f_measure(p: float, r: float) -> float:
    return 0.0 if p + r == 0 else 2 * p * r / (p + r)


def _rouge_n(pred: List[str], ref: List[str], n: int) -> float:
    pred_ngrams = Counter(tuple(pred[i:i + n]) for i in range(len(pred) - n + 1))
    ref_ngrams = Counter(tuple(ref[i:i + n]) for i in range(len(ref) - n + 1))
    if not pred_ngrams or not ref_ngrams:
        return 0.0
    overlap = sum((pred_ngrams & ref_ngrams).values())
    return _f_measure(
        overlap / max(1, sum(pred_ngrams.values())),
        overlap / max(1, sum(ref_ngrams.values())),
    )


def _lcs_len(a: List[str], b: List[str]) -> int:
    if not a or not b:
        return 0
    # one-row DP; O(len(a)*len(b)) time, O(len(b)) space — summaries are short
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0]
        for j, y in enumerate(b, 1):
            cur.append(prev[j - 1] + 1 if x == y else max(prev[j], cur[-1]))
        prev = cur
    return prev[-1]


def _rouge_l(pred: List[str], ref: List[str]) -> float:
    if not pred or not ref:
        return 0.0
    lcs = _lcs_len(pred, ref)
    return _f_measure(lcs / len(pred), lcs / len(ref))


def rouge(pred: str, ref: str) -> Dict[str, float]:
    """ROUGE-1/2/L F-measures for one (prediction, reference) pair."""
    p, r = _tokenize(pred), _tokenize(ref)
    return {"rouge1": _rouge_n(p, r, 1), "rouge2": _rouge_n(p, r, 2), "rougeL": _rouge_l(p, r)}


def rouge_scores(
    predictions: Sequence[str], references: Sequence[str]
) -> Dict[str, float]:
    """Corpus ROUGE: per-pair F-measures averaged (what ``evaluate``'s rouge
    returns), plus ``rouge_avg`` — the mean over 1/2/L that the reference's
    README table reports as "Average"."""
    assert len(predictions) == len(references), (len(predictions), len(references))
    if not predictions:
        return {"rouge1": 0.0, "rouge2": 0.0, "rougeL": 0.0, "rouge_avg": 0.0}
    totals = Counter()
    for pred, ref in zip(predictions, references):
        totals.update(rouge(pred, ref))
    n = len(predictions)
    out = {k: totals[k] / n for k in ("rouge1", "rouge2", "rougeL")}
    out["rouge_avg"] = (out["rouge1"] + out["rouge2"] + out["rougeL"]) / 3
    return out


def rouge_per_sample(
    predictions: Sequence[str], references: Sequence[str]
) -> Dict[str, List[float]]:
    """Per-sample ROUGE lists, shaped for a trainer ``metric_fn`` (each metric
    becomes a table column + a mean stat)."""
    rows = [rouge(p, r) for p, r in zip(predictions, references)]
    out: Dict[str, List[float]] = {k: [row[k] for row in rows] for k in ("rouge1", "rouge2", "rougeL")}
    out["rouge_avg"] = [
        (a + b + c) / 3 for a, b, c in zip(out["rouge1"], out["rouge2"], out["rougeL"])
    ]
    return out
