"""General utilities: seeding, timers, pytree helpers, optimizer/scheduler registries.

Capability parity with `/root/reference/trlx/utils/__init__.py` (seeding :44-52,
optimizer/scheduler registries :83-146, Clock :149-187, tree_map/to_device :190-208,
infinite_dataloader :240), re-expressed for JAX: optimizers/schedules resolve to optax,
device placement is handled by shardings so ``to_device`` has no analogue, and RNG is
explicit (`jax.random.PRNGKey`) with a numpy fallback for host-side shuffling.
"""

import math
import random
import subprocess
import time
from enum import Enum
from numbers import Number
from typing import Any, Dict, Iterable, Iterator, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax


def set_seed(seed: int) -> np.random.Generator:
    """Seed python/numpy RNGs and return a numpy Generator for host-side sampling.

    Deliberately NO per-process offset, unlike the reference's ``seed + rank``
    (utils/__init__.py:44-52): under single-controller SPMD every process must
    run the identical program on identical data — per-host divergence (in data
    order, sampled tokens, anything feeding a jit input) is undefined behavior.
    Per-sample generation diversity comes from the batched device RNG, not from
    rank offsets. JAX device RNG is explicit — trainers derive
    ``jax.random.PRNGKey(seed)`` themselves.
    """
    seed = int(seed)
    random.seed(seed)
    np.random.seed(seed % (2**32))
    return np.random.default_rng(seed)


def significant(x: Any, ndigits: int = 3) -> Any:
    """Round a number to ``ndigits`` significant figures (for stat logging)."""
    if isinstance(x, (jnp.ndarray, np.ndarray)):
        x = float(x)
    if not isinstance(x, Number) or x == 0 or not math.isfinite(x):
        return x
    return round(x, ndigits - int(math.floor(math.log10(abs(x)))) - 1)


class Clock:
    """Wall-clock timer tracking time/samples deltas between ``tick`` calls
    (parity: reference ``Clock``, utils/__init__.py:149-187)."""

    def __init__(self):
        self.start = time.time()
        self.total_time = 0.0
        self.total_samples = 0

    def tick(self, samples: int = 0) -> float:
        """Returns time (s) since last tick; accumulates samples for throughput."""
        end = time.time()
        delta = end - self.start
        self.start = end
        if samples != 0:
            self.total_time += delta
            self.total_samples += samples
        return delta

    def get_stat(self, n_samp: int = 1000, reset: bool = False) -> float:
        """Seconds per ``n_samp`` samples over the accumulated window."""
        stat = self.total_time * n_samp / max(self.total_samples, 1)
        if reset:
            self.total_time = 0.0
            self.total_samples = 0
        return stat


def tree_map_number(fn, tree: Any) -> Any:
    """Apply ``fn`` to every leaf of a nested dict/list structure (host-side)."""
    if isinstance(tree, dict):
        return {k: tree_map_number(fn, v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return type(tree)(tree_map_number(fn, v) for v in tree)
    return fn(tree)


def filter_non_scalars(xs: Dict) -> Dict:
    """Keep only numeric leaves of a flat stats dict (for tracker logging)."""
    ys = {}
    for k, v in xs.items():
        try:
            ys[k] = float(v)
        except (TypeError, ValueError):
            continue
    return ys


def get_git_tag() -> Tuple[str, str]:
    """(commit hash, branch) of the current repo, or placeholders outside git."""
    try:
        output = subprocess.check_output("git log --format='%h/%as' -n1".split())
        branch = subprocess.check_output("git rev-parse --abbrev-ref HEAD".split())
        return output.decode()[1:-2], branch.decode()[:-1]
    except subprocess.CalledProcessError:
        return "unknown", "unknown"


def infinite_loader(loader: Iterable) -> Iterator:
    """Cycle a (re-iterable) dataloader forever (parity: ``infinite_dataloader``)."""
    while True:
        yield from loader


# ----------------------------- optimizers ------------------------------------


class OptimizerName(str, Enum):
    """Supported optimizer names. The 8-bit variants use true int8
    blockwise-quantized moment states (:mod:`trlx_tpu.ops.quantized_adam`),
    the TPU-native counterpart of the reference's bitsandbytes optimizers
    (utils/__init__.py:104-123)."""

    ADAM = "adam"
    ADAMW = "adamw"
    ADAM_8BIT = "adam_8bit_bnb"
    ADAMW_8BIT = "adamw_8bit_bnb"
    SGD = "sgd"
    LION = "lion"
    ADAFACTOR = "adafactor"
    RMSPROP = "rmsprop"


def get_optimizer_class(name) -> Any:
    """Resolve an optimizer registry name to an optax constructor.

    Constructors accept ``learning_rate`` plus the usual kwargs (``betas`` is
    translated to optax's ``b1``/``b2``).
    """
    name = OptimizerName(name.lower() if isinstance(name, str) else name)

    def _adamlike(ctor):
        def make(learning_rate, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, **kw):
            return ctor(
                learning_rate=learning_rate,
                b1=betas[0],
                b2=betas[1],
                eps=eps,
                weight_decay=weight_decay,
                **kw,
            )

        return make

    if name == OptimizerName.ADAMW:
        return _adamlike(optax.adamw)
    if name == OptimizerName.ADAMW_8BIT:
        from trlx_tpu.ops.quantized_adam import adamw_8bit

        return _adamlike(adamw_8bit)
    if name == OptimizerName.ADAM_8BIT:
        from trlx_tpu.ops.quantized_adam import adam_8bit

        def make_adam8(learning_rate, betas=(0.9, 0.999), eps=1e-8, **kw):
            kw.pop("weight_decay", None)
            return adam_8bit(learning_rate, b1=betas[0], b2=betas[1], eps=eps, **kw)

        return make_adam8
    if name == OptimizerName.ADAM:

        def make_adam(learning_rate, betas=(0.9, 0.999), eps=1e-8, **kw):
            kw.pop("weight_decay", None)
            return optax.adam(learning_rate, b1=betas[0], b2=betas[1], eps=eps, **kw)

        return make_adam
    if name == OptimizerName.SGD:

        def make_sgd(learning_rate, momentum=0.0, weight_decay=0.0, **kw):
            tx = optax.sgd(learning_rate, momentum=momentum or None, **kw)
            if weight_decay:
                tx = optax.chain(optax.add_decayed_weights(weight_decay), tx)
            return tx

        return make_sgd
    if name == OptimizerName.LION:

        def make_lion(learning_rate, betas=(0.9, 0.99), weight_decay=0.0, **kw):
            return optax.lion(learning_rate, b1=betas[0], b2=betas[1], weight_decay=weight_decay, **kw)

        return make_lion
    if name == OptimizerName.ADAFACTOR:
        return lambda learning_rate, **kw: optax.adafactor(learning_rate, **kw)
    if name == OptimizerName.RMSPROP:
        return lambda learning_rate, **kw: optax.rmsprop(learning_rate, **kw)
    raise ValueError(f"Unknown optimizer {name}")


# ----------------------------- schedulers ------------------------------------


class SchedulerName(str, Enum):
    COSINE_ANNEALING = "cosine_annealing"
    LINEAR = "linear"
    CONSTANT = "constant"
    COSINE_WARMUP = "cosine_warmup"


def get_scheduler_class(name) -> Any:
    """Resolve a scheduler registry name to an optax schedule constructor.

    Returned constructors take the same hyperparameters as the reference's torch
    schedulers (``T_max``/``eta_min`` for cosine) and produce ``optax.Schedule``s.
    """
    name = SchedulerName(name.lower() if isinstance(name, str) else name)
    if name == SchedulerName.COSINE_ANNEALING:

        def make_cosine(learning_rate, T_max, eta_min=0.0, **_):
            return optax.cosine_decay_schedule(
                init_value=learning_rate,
                decay_steps=max(int(T_max), 1),
                alpha=eta_min / learning_rate if learning_rate else 0.0,
            )

        return make_cosine
    if name == SchedulerName.LINEAR:

        def make_linear(learning_rate, total_steps, end_value=0.0, **_):
            return optax.linear_schedule(learning_rate, end_value, max(int(total_steps), 1))

        return make_linear
    if name == SchedulerName.CONSTANT:
        return lambda learning_rate, **_: optax.constant_schedule(learning_rate)
    if name == SchedulerName.COSINE_WARMUP:

        def make_warmup(learning_rate, warmup_steps, total_steps, eta_min=0.0, **_):
            return optax.warmup_cosine_decay_schedule(
                init_value=0.0,
                peak_value=learning_rate,
                warmup_steps=int(warmup_steps),
                decay_steps=max(int(total_steps), 1),
                end_value=eta_min,
            )

        return make_warmup
    raise ValueError(f"Unknown scheduler {name}")
