"""Name→class resolution for trainers and pipelines (parity:
`/root/reference/trlx/utils/loading.py:14-50`). Importing this module pulls in the
built-in trainers/pipelines so their registry decorators run."""


def get_trainer(name: str) -> type:
    import trlx_tpu.trainer  # noqa: F401 — populate registry

    from trlx_tpu.trainer import _TRAINERS

    key = name.lower()
    if key in _TRAINERS:
        return _TRAINERS[key]
    raise ValueError(f"Unknown trainer {name!r}. Registered: {sorted(_TRAINERS)}")


def get_pipeline(name: str) -> type:
    import trlx_tpu.pipeline  # noqa: F401 — populate registry

    from trlx_tpu.pipeline import _DATAPIPELINES

    key = name.lower()
    if key in _DATAPIPELINES:
        return _DATAPIPELINES[key]
    raise ValueError(f"Unknown pipeline {name!r}. Registered: {sorted(_DATAPIPELINES)}")
