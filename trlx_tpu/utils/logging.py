"""Library-wide logging subsystem.

Capability parity with the reference (`/root/reference/trlx/utils/logging.py:47-341`):
HF-transformers-style per-library verbosity controlled by the ``TRLX_VERBOSITY`` env var,
a multi-process adapter that can restrict records to specific process indices and prefixes
``[RANK n]``, and a switchable tqdm. Process identity comes from ``jax.process_index()``
instead of torch.distributed ranks.
"""

import logging
import os
import sys
import threading
from typing import Optional

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}

_default_log_level = logging.INFO
_lock = threading.Lock()
_default_handler: Optional[logging.Handler] = None

_LIBRARY_NAME = "trlx_tpu"


def _get_default_logging_level() -> int:
    env_level = os.environ.get("TRLX_VERBOSITY", None)
    if env_level:
        if env_level.lower() in log_levels:
            return log_levels[env_level.lower()]
        logging.getLogger().warning(
            f"Unknown TRLX_VERBOSITY={env_level}, must be one of {list(log_levels)}"
        )
    return _default_log_level


def _get_library_root_logger() -> logging.Logger:
    return logging.getLogger(_LIBRARY_NAME)


def _configure_library_root_logger() -> None:
    global _default_handler
    with _lock:
        if _default_handler:
            return
        _default_handler = logging.StreamHandler(sys.stdout)
        _default_handler.flush = sys.stdout.flush
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s", "%H:%M:%S"
        )
        _default_handler.setFormatter(formatter)
        root = _get_library_root_logger()
        root.addHandler(_default_handler)
        root.setLevel(_get_default_logging_level())
        root.propagate = False


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", 0))


class MultiProcessAdapter(logging.LoggerAdapter):
    """Logger adapter filtering records by process index.

    ``logger.info(msg, ranks=[0])`` only emits on process 0 (the default);
    ``ranks=[-1]`` emits on every process with a ``[RANK n]`` prefix.
    """

    @staticmethod
    def _should_log(ranks) -> bool:
        idx = _process_index()
        return idx in ranks or -1 in ranks

    def log(self, level, msg, *args, **kwargs):
        ranks = kwargs.pop("ranks", [0])
        idx = _process_index()
        if self.isEnabledFor(level) and self._should_log(ranks):
            if idx != 0 or -1 in ranks:
                msg = f"[RANK {idx}] {msg}"
            self.logger.log(level, msg, *args, **kwargs)

    def process(self, msg, kwargs):
        return msg, kwargs


def get_logger(name: Optional[str] = None) -> MultiProcessAdapter:
    """Return a ``MultiProcessAdapter`` for ``name`` (defaults to the library root)."""
    _configure_library_root_logger()
    if name is None:
        name = _LIBRARY_NAME
    return MultiProcessAdapter(logging.getLogger(name), {})


def get_verbosity() -> int:
    _configure_library_root_logger()
    return _get_library_root_logger().getEffectiveLevel()


def set_verbosity(verbosity: int) -> None:
    _configure_library_root_logger()
    _get_library_root_logger().setLevel(verbosity)


def set_verbosity_debug():
    set_verbosity(logging.DEBUG)


def set_verbosity_info():
    set_verbosity(logging.INFO)


def set_verbosity_warning():
    set_verbosity(logging.WARNING)


def set_verbosity_error():
    set_verbosity(logging.ERROR)


def disable_default_handler() -> None:
    _configure_library_root_logger()
    assert _default_handler is not None
    _get_library_root_logger().removeHandler(_default_handler)


def enable_default_handler() -> None:
    _configure_library_root_logger()
    assert _default_handler is not None
    _get_library_root_logger().addHandler(_default_handler)


_tqdm_active = True


class _EmptyTqdm:
    def __init__(self, *args, **kwargs):
        self._iterator = args[0] if args else None

    def __iter__(self):
        return iter(self._iterator)

    def __getattr__(self, _):
        def empty_fn(*args, **kwargs):
            return

        return empty_fn

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return


class _TqdmCls:
    def __call__(self, *args, **kwargs):
        if _tqdm_active and _process_index() == 0:
            try:
                from tqdm import tqdm as real_tqdm

                return real_tqdm(*args, **kwargs)
            except ImportError:
                pass
        return _EmptyTqdm(*args, **kwargs)


tqdm = _TqdmCls()


def enable_progress_bar():
    global _tqdm_active
    _tqdm_active = True


def disable_progress_bar():
    global _tqdm_active
    _tqdm_active = False
