"""Model/numeric helpers: logprobs, whitening, distributed statistics, dict flattening.

Capability parity with `/root/reference/trlx/utils/modeling.py` (logprobs_of_labels :213,
whiten/get_global_statistics :169-207, RunningMoments :264-307, flatten_dict :220). Under
single-program SPMD (jit over a Mesh with global-view arrays) batch statistics computed with
plain ``jnp.mean``/``var`` are already *global* — XLA inserts the collectives — so the
reference's ``torch.distributed.all_reduce`` plumbing disappears. Explicit named-axis
variants are provided for use inside ``shard_map`` regions.
"""

from typing import Any, Dict, MutableMapping, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


def make_head_init(scale: float = 0.02):
    """Initializer for value/Q heads (normal, like HF head init)."""
    return jax.nn.initializers.normal(stddev=scale)


def logprobs_of_labels(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Log-probabilities of ``labels`` under ``logits``: log softmax + gather.

    Shapes: logits [..., T, V], labels [..., T] -> [..., T].
    Parity: reference utils/modeling.py:213-218 (which shifts externally; callers here
    pass already-aligned slices). Logits arrive in the model's compute dtype (bf16 on
    TPU); the logsumexp inside log_softmax must not accumulate a 32k-vocab sum in a
    7-bit mantissa, so upcast first — KL penalties are differences of these logprobs
    and bf16 rounding there directly biases the reward.
    """
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logprobs, labels[..., None], axis=-1)[..., 0]


def masked_mean(x: jnp.ndarray, mask: jnp.ndarray, axis=None) -> jnp.ndarray:
    """Mean of ``x`` over positions where ``mask`` is 1."""
    mask = mask.astype(x.dtype)
    return (x * mask).sum(axis=axis) / jnp.maximum(mask.sum(axis=axis), 1e-8)


def masked_var(x: jnp.ndarray, mask: jnp.ndarray, mean: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    if mean is None:
        mean = masked_mean(x, mask)
    return masked_mean((x - mean) ** 2, mask)


def whiten(xs: jnp.ndarray, shift_mean: bool = True, mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Whiten values to zero mean / unit variance over the *global* batch.

    Under jit-over-Mesh the reductions are global across all devices, matching the
    reference's distributed whitening (utils/modeling.py:169-185) without explicit
    collectives.
    """
    if mask is not None:
        mean = masked_mean(xs, mask)
        var = masked_var(xs, mask, mean)
    else:
        mean, var = jnp.mean(xs), jnp.var(xs)
    whitened = (xs - mean) * jax.lax.rsqrt(var + 1e-8)
    if not shift_mean:
        whitened = whitened + mean
    return whitened


def get_global_statistics(
    xs: jnp.ndarray, axis_name: Optional[str] = None
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(mean, var, count) of ``xs``. With ``axis_name`` set, reduces across that named
    mesh axis too (for use inside ``shard_map``); otherwise relies on global-view SPMD."""
    if axis_name is None:
        count = jnp.array(xs.size, dtype=jnp.float32)
        mean = jnp.mean(xs)
        var = jnp.var(xs)
        return mean, var, count
    # accumulate in f32 regardless of xs.dtype: a bf16 sum over a shard is
    # already wrong before the psum ever sees it (JX007 discipline)
    s = jax.lax.psum(jnp.array([xs.sum(dtype=jnp.float32), xs.size], dtype=jnp.float32), axis_name)
    global_sum, count = s[0], s[1]
    mean = global_sum / count
    sum_var = jax.lax.psum(((xs - mean) ** 2).sum(dtype=jnp.float32), axis_name)
    return mean, sum_var / count, count


class RunningMoments:
    """Streaming mean/std of reward batches with Welford-style merging.

    Parity: reference ``RunningMoments`` (utils/modeling.py:264-307). Operates on
    *global* (already gathered) arrays on the host; under a multi-controller setup
    callers gather per-host scores first (see trainer.gather_scores).
    """

    def __init__(self):
        self.mean = 0.0
        self.std = 1.0
        self.var = 1.0
        self.count = 1e-24

    def update(self, xs: np.ndarray) -> Tuple[float, float]:
        """Update from a batch; returns (batch mean, batch std)."""
        xs = np.asarray(jax.device_get(xs), dtype=np.float64).reshape(-1)
        xs_count = xs.size
        xs_mean = float(xs.mean())
        xs_var = float(xs.var())

        delta = xs_mean - self.mean
        tot_count = self.count + xs_count
        new_sum = xs_var * xs_count
        old_sum = self.var * self.count + delta**2 * self.count * xs_count / tot_count
        tot_sum = old_sum + new_sum

        self.mean += delta * xs_count / tot_count
        self.var = tot_sum / tot_count
        self.std = float(np.sqrt(self.var * tot_count / max(tot_count - 1, 1)))
        self.count = tot_count
        return xs_mean, float(np.sqrt(xs_var * xs_count / max(xs_count - 1, 1)))


def flatten_dict(d: MutableMapping, parent_key: str = "", sep: str = "/") -> Dict[str, Any]:
    """Flatten a nested dict with ``/``-joined keys (parity: utils/modeling.py:220-230)."""
    items = []
    for k, v in d.items():
        new_key = parent_key + sep + str(k) if parent_key else str(k)
        if isinstance(v, MutableMapping):
            items.extend(flatten_dict(v, new_key, sep).items())
        else:
            items.append((new_key, v))
    return dict(items)


def gather_dict(obj: Dict, grad_state=None) -> Dict:
    """Gather a metadata dict of lists from every process (parity:
    utils/modeling.py:238-259). Single-process: identity. Multi-host: uses
    ``jax.experimental.multihost_utils`` process allgather on pickled objects."""
    if jax.process_count() == 1:
        return obj
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(obj, tiled=False)
    # process_allgather returns stacked arrays per leaf; convert back to lists
    out = {}
    for k, v in gathered.items():
        out[k] = list(np.concatenate([np.atleast_1d(x) for x in v]))
    return out


def param_path_leaves(params) -> Dict[str, Any]:
    """Flatten a nested param dict to {"a/b/c": leaf} for path-predicate surgery."""
    flat = flatten_dict(params)
    return flat
