"""Persistent XLA compilation cache setup.

One place owns the jax cache knobs because the enablement check is latched:
``jax._src.compilation_cache.is_cache_used`` memoizes its answer at the FIRST
compile of the process, so configuring the cache after anything has compiled
(even a ``jax.random.PRNGKey``) silently disables it for the whole process.
Callers therefore invoke :func:`configure_compilation_cache` as the first
jax-touching act: ``MeshRLTrainer.__init__`` before it derives its RNG key,
and ``python -m trlx_tpu.analysis.ir`` before lowering.

Resolution order for the cache dir: explicit argument, then
``train.compilation_cache_dir``, then ``mesh.compilation_cache_dir`` (the
pre-existing knob), then ``$TRLX_COMPILE_CACHE``. Unset everywhere = cache
off (jax default).

On the CPU backend the cache is configured only for callers that never
*execute* what they deserialize (``compile_only=True``, e.g. the graftcheck-ir
AOT gate): with jaxlib 0.4.36, re-loading the PPO grad-accum train step from
the disk cache and running it corrupts the heap (glibc abort at the next
step; numerics up to that point are correct, which points at a temp-buffer
sizing bug in XLA:CPU executable deserialization — other cached executables,
including the decode step, round-trip fine). TPU/GPU backends are unaffected
and always honor the configured dir. ``TRLX_COMPILE_CACHE_FORCE=1`` overrides
the CPU guard for debugging.
"""

import os
from typing import Optional

from trlx_tpu.utils import logging

logger = logging.get_logger(__name__)

ENV_VAR = "TRLX_COMPILE_CACHE"
FORCE_ENV_VAR = "TRLX_COMPILE_CACHE_FORCE"


def resolve_cache_dir(config=None, cache_dir: Optional[str] = None) -> Optional[str]:
    """The effective cache dir for a TRLConfig (or None)."""
    if cache_dir:
        return cache_dir
    if config is not None:
        train_dir = getattr(getattr(config, "train", None), "compilation_cache_dir", None)
        if train_dir:
            return train_dir
        mesh_dir = getattr(getattr(config, "mesh", None), "compilation_cache_dir", None)
        if mesh_dir:
            return mesh_dir
    return os.environ.get(ENV_VAR) or None


def configure_compilation_cache(
    cache_dir: Optional[str] = None,
    config=None,
    min_compile_time_secs: float = 0.5,
    compile_only: bool = False,
) -> Optional[str]:
    """Point jax at an on-disk compile cache; returns the dir, or None when
    no dir is configured anywhere (or the CPU guard declined — see the module
    docstring). ``min_compile_time_secs`` trades cache-dir churn for coverage
    — 0.5s keeps real model steps while skipping the trivial host-side jits;
    tests pass 0.0 to cache everything. ``compile_only=True`` asserts the
    caller never executes deserialized executables, which sidesteps the
    XLA:CPU deserialization bug and so lifts the CPU guard."""
    cache_dir = resolve_cache_dir(config, cache_dir)
    if not cache_dir:
        return None

    import jax

    if (
        not compile_only
        and os.environ.get(FORCE_ENV_VAR) != "1"
        and jax.default_backend() == "cpu"
    ):
        logger.warning(
            f"ignoring compilation cache dir {cache_dir}: executing "
            "cache-deserialized donated executables corrupts the heap on the "
            "CPU backend (jaxlib 0.4.36, see trlx_tpu/utils/"
            f"compilation_cache.py); set {FORCE_ENV_VAR}=1 to force"
        )
        return None

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_time_secs)
    )
    try:
        # cache regardless of artifact size (the default skips small modules)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except AttributeError:
        pass  # knob absent on older jax; size-based skipping just applies
    logger.info(f"persistent compilation cache at {cache_dir}")
    return cache_dir
