"""End-to-end micro-training tests (strategy mirrors reference tests/test_trainers.py:
real trainers on tiny models, a handful of steps, checkpoint layout assertions)."""

import os
import sys

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import trlx_tpu
from trlx_tpu.data.configs import (
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    SchedulerConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.methods.ilql import ILQLConfig
from trlx_tpu.methods.ppo import PPOConfig
from trlx_tpu.methods.sft import SFTConfig

ALPHABET = "abcdefgh "

TINY_MODEL = dict(
    vocab_size=len(ALPHABET) + 3, hidden_size=32, num_layers=2, num_heads=2,
    intermediate_size=64, max_position_embeddings=64,
)


def base_kwargs(tmp_path, trainer, total_steps=3, batch_size=4, seq_length=16):
    return dict(
        train=TrainConfig(
            seq_length=seq_length, epochs=2, total_steps=total_steps,
            batch_size=batch_size, minibatch_size=batch_size // 2,
            checkpoint_interval=2, eval_interval=2,
            checkpoint_dir=str(tmp_path / "ckpts"),
            pipeline="PromptPipeline", trainer=trainer, tracker="jsonl", seed=2,
        ),
        model=ModelConfig(model_path="gpt2", num_layers_unfrozen=-1, model_overrides=dict(TINY_MODEL)),
        tokenizer=TokenizerConfig(tokenizer_path=f"char://{ALPHABET}"),
        optimizer=OptimizerConfig(name="adamw", kwargs=dict(lr=1e-3)),
        scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=100, eta_min=1e-3)),
        mesh=MeshConfig(data=2, fsdp=2, model=2, compute_dtype="float32"),
    )


def dog_reward(samples, **kwargs):
    """Count 'a's (reference uses dog-counting; same idea)."""
    return [float(s.count("a")) for s in samples]


@pytest.mark.slow
def test_ppo_end_to_end(tmp_path):
    config = TRLConfig(
        method=PPOConfig(
            num_rollouts=8, chunk_size=4, ppo_epochs=2, init_kl_coef=0.01,
            target=None, gen_kwargs=dict(max_new_tokens=6, do_sample=True, top_k=0, top_p=1.0),
        ),
        **base_kwargs(tmp_path, "PPOTrainer"),
    )
    trainer = trlx_tpu.train(
        reward_fn=dog_reward,
        prompts=["ab", "cd ef", "gh", "a b c"] * 2,
        eval_prompts=["ab", "cd"],
        config=config,
    )
    assert trainer.iter_count >= 3
    ckpts = os.listdir(config.train.checkpoint_dir)
    assert any(c.startswith("checkpoint_") for c in ckpts)
    assert "best_checkpoint" in ckpts or True  # best requires eval reward improvement
    # checkpoint roundtrip restores step count
    ckpt = sorted(c for c in ckpts if c.startswith("checkpoint_"))[0]
    trainer.load(os.path.join(config.train.checkpoint_dir, ckpt))
    assert trainer.iter_count > 0


@pytest.mark.slow
def test_evaluate_mixed_prompt_buckets(tmp_path):
    """Eval batches that bucket to different prompt lengths must each be decoded
    with their own pad offset (regression: round-1 used the LAST batch's pad_len
    for every batch, corrupting outputs of earlier batches)."""
    from trlx_tpu.pipeline.offline_pipeline import PromptPipeline
    from trlx_tpu.utils.loading import get_trainer

    captured = {}

    def capture_reward(samples, prompts, outputs, **kw):
        captured["prompts"] = list(prompts)
        captured["outputs"] = list(outputs)
        return [0.0] * len(samples)

    config = TRLConfig(
        method=SFTConfig(gen_kwargs=dict(max_new_tokens=4, do_sample=False)),
        **base_kwargs(tmp_path, "SFTTrainer", batch_size=2),
    )
    trainer = get_trainer("SFTTrainer")(config=config, reward_fn=capture_reward)
    short = ["ab", "cd"]       # bucket to prompt pad 8
    long = ["abcdefgh ab", "cdefgh abc"]  # bucket to prompt pad 16
    trainer.add_eval_pipeline(PromptPipeline(short + long, 32, trainer.tokenizer))
    trainer.evaluate()
    assert captured["prompts"] == short + long
    mixed_outputs = captured["outputs"]

    # greedy decoding: the short batch's outputs must be identical when the
    # differently-bucketed long batch is absent
    trainer.add_eval_pipeline(PromptPipeline(short, 32, trainer.tokenizer))
    trainer.evaluate()
    assert captured["outputs"] == mixed_outputs[:2]


@pytest.mark.slow
@pytest.mark.parametrize(
    "peft_config",
    [
        {"peft_type": "LORA", "r": 4},
        {"peft_type": "PREFIX_TUNING", "num_virtual_tokens": 4},
        {"peft_type": "PROMPT_TUNING", "num_virtual_tokens": 4},
    ],
)
def test_ppo_peft_end_to_end(tmp_path, peft_config):
    """PPO with each native peft type: adapters+heads train, the KL reference is
    the same params with adapters structurally disabled, and the hf_model export
    carries an adapter-only artifact (parity: reference tests/test_peft.py +
    test_trainers.py LoRA case)."""
    kwargs = base_kwargs(tmp_path, "PPOTrainer")
    kwargs["model"] = ModelConfig(
        model_path="gpt2", num_layers_unfrozen=-1,
        model_overrides=dict(TINY_MODEL), peft_config=peft_config,
    )
    config = TRLConfig(
        method=PPOConfig(
            num_rollouts=8, chunk_size=4, ppo_epochs=1, init_kl_coef=0.01,
            target=None, gen_kwargs=dict(max_new_tokens=6, do_sample=True, top_k=0, top_p=1.0),
        ),
        **kwargs,
    )
    trainer = trlx_tpu.train(
        reward_fn=dog_reward, prompts=["ab", "cd ef", "gh", "a b c"] * 2,
        eval_prompts=["ab"], config=config,
    )
    assert trainer.iter_count >= 3
    hf_dir = os.path.join(config.train.checkpoint_dir, "hf_model")
    assert os.path.exists(os.path.join(hf_dir, "adapters.msgpack"))


@pytest.mark.slow
@pytest.mark.parametrize("family", ["bloom", "gpt_bigcode"])
def test_ppo_new_families_end_to_end(tmp_path, family):
    """Full PPO (incl. hydra frozen branch) on the ALiBi and MQA families."""
    kwargs = base_kwargs(tmp_path, "PPOTrainer")
    overrides = dict(TINY_MODEL)
    overrides.pop("intermediate_size", None)
    # the gpt_bigcode preset already carries num_kv_heads=1 (MQA)
    kwargs["model"] = ModelConfig(
        model_path=family, num_layers_unfrozen=1, model_overrides=overrides
    )
    config = TRLConfig(
        method=PPOConfig(
            num_rollouts=8, chunk_size=4, ppo_epochs=1, init_kl_coef=0.01,
            target=None, gen_kwargs=dict(max_new_tokens=6, do_sample=True, top_k=0, top_p=1.0),
        ),
        **kwargs,
    )
    trainer = trlx_tpu.train(
        reward_fn=dog_reward, prompts=["ab", "cd ef", "gh", "a b c"] * 2,
        eval_prompts=["ab"], config=config,
    )
    assert trainer.iter_count >= 3


def test_reward_on_process_zero_auto_default():
    """None (the default) resolves by process count: off single-process, on
    multi-process (VERDICT r3 item 6); an explicit bool always wins."""
    from trlx_tpu.data.default_configs import default_ppo_config
    from trlx_tpu.trainer.mesh_trainer import MeshRLTrainer

    t = object.__new__(MeshRLTrainer)  # property only reads config + process count
    t.config = default_ppo_config()
    assert t.config.train.reward_on_process_zero is None
    assert t.reward_on_process_zero is False  # tests run single-process
    t.config.train.reward_on_process_zero = True
    assert t.reward_on_process_zero is True
    t.config.train.reward_on_process_zero = False
    assert t.reward_on_process_zero is False


@pytest.mark.slow
def test_ppo_overlap_reward_scoring(tmp_path):
    """Double-buffered rollouts: reward_fn for chunk i runs on a worker thread
    while chunk i+1 generates; results must be complete and ordered."""
    calls = []

    def slow_reward(samples, **kw):
        calls.append(len(samples))
        import time

        time.sleep(0.05)
        return [float(s.count("a")) for s in samples]

    config = TRLConfig(
        method=PPOConfig(
            num_rollouts=8, chunk_size=4, ppo_epochs=1, init_kl_coef=0.01,
            target=None, overlap_reward_scoring=True,
            gen_kwargs=dict(max_new_tokens=6, do_sample=True, top_k=0, top_p=1.0),
        ),
        **base_kwargs(tmp_path, "PPOTrainer"),
    )
    trainer = trlx_tpu.train(
        reward_fn=slow_reward, prompts=["ab", "cd ef", "gh", "a b c"] * 2,
        eval_prompts=["ab"], config=config,
    )
    assert trainer.iter_count >= 3
    assert len(trainer.store) >= 8  # full experience despite async scoring


@pytest.mark.slow
def test_ppo_offload_ref(tmp_path):
    """ModelConfig.offload_ref: the full frozen reference lives in host memory,
    streams in for scoring, and is released before the update phase — training
    must run green and the device view must equal the held host copy."""
    import jax

    config = TRLConfig(
        method=PPOConfig(
            num_rollouts=8, chunk_size=4, ppo_epochs=1, init_kl_coef=0.01,
            target=None, gen_kwargs=dict(max_new_tokens=4, do_sample=True, top_k=0, top_p=1.0),
        ),
        **base_kwargs(tmp_path, "PPOTrainer"),
    )
    config.model.offload_ref = True
    assert config.model.num_layers_unfrozen == -1  # offload needs the full-copy ref
    trainer = trlx_tpu.train(
        reward_fn=dog_reward, prompts=["ab", "cd ef", "gh", "a b c"] * 2,
        eval_prompts=["ab"], config=config,
    )
    assert trainer.iter_count >= 3
    assert trainer.ref_params is None and trainer._ref_host is not None
    assert trainer._ref_dev is None  # released after the last make_experience
    dev = trainer._ref_scoring_params()
    host_leaves = jax.tree.leaves(jax.tree.map(np.asarray, trainer._ref_host))
    dev_leaves = jax.tree.leaves(jax.tree.map(np.asarray, dev))
    for h, d in zip(host_leaves, dev_leaves):
        np.testing.assert_array_equal(h, d)
    trainer._release_ref()
    assert trainer._ref_dev is None


@pytest.mark.slow
def test_decode_stop_sequences(tmp_path):
    """Token-level stop trimming: outputs are cut at the first stop sequence with
    the reference's rstrip semantics, and output ids match the decoded string
    without re-tokenization (parity: accelerate_base_trainer.py:203-255)."""
    from trlx_tpu.utils.loading import get_trainer

    config = TRLConfig(
        method=SFTConfig(gen_kwargs=dict(max_new_tokens=4)),
        **base_kwargs(tmp_path, "SFTTrainer"),
    )
    trainer = get_trainer("SFTTrainer")(config=config, stop_sequences=["gh"])
    tok = trainer.tokenizer
    P = 4
    prompts = [np.asarray(tok("ab").input_ids, np.int32)] * 2
    resps = [tok("cd efgh ab").input_ids, tok("cd  gh ef").input_ids]
    R = max(len(r) for r in resps)
    samples = np.full((2, P + R), tok.pad_token_id, np.int32)
    rmask = np.zeros((2, R), np.int32)
    for i, (pr, r) in enumerate(zip(prompts, resps)):
        samples[i, P - len(pr) : P] = pr
        samples[i, P : P + len(r)] = r
        rmask[i, : len(r)] = 1
    _, _, outputs, out_ids = trainer.decode(prompts, samples, P, response_masks=rmask)
    assert outputs[0] == "cd ef"
    assert outputs[1] == "cd"  # whitespace before the stop is rstripped
    assert tok.decode(out_ids[0]) == "cd ef"
    assert tok.decode(out_ids[1]) == "cd"


@pytest.mark.slow
def test_ilql_end_to_end(tmp_path):
    config = TRLConfig(
        method=ILQLConfig(
            steps_for_target_q_sync=2, two_qs=True,
            gen_kwargs=dict(max_new_tokens=4, top_k=4, beta=1.0, temperature=1.0),
        ),
        **base_kwargs(tmp_path, "ILQLTrainer"),
    )
    samples = [["ab", "cd"], ["ef", "gh"], ["a", "bc"], ["de", "fg"]] * 2
    rewards = [1.0, 0.5, -0.5, 0.25] * 2
    trainer = trlx_tpu.train(
        samples=samples, rewards=rewards, eval_prompts=["ab", "ef"], config=config
    )
    assert trainer.iter_count >= 3


@pytest.mark.slow
def test_sft_end_to_end(tmp_path):
    config = TRLConfig(
        method=SFTConfig(gen_kwargs=dict(max_new_tokens=4)),
        **base_kwargs(tmp_path, "SFTTrainer"),
    )
    samples = [["ab", "cd"], ["ef", "gh"], ["a", "bc"], ["de", "fg"]] * 2
    trainer = trlx_tpu.train(samples=samples, eval_prompts=["ab"], config=config)
    assert trainer.iter_count >= 3


@pytest.mark.slow
def test_rft_end_to_end(tmp_path):
    from trlx_tpu.methods.rft import RFTConfig

    kwargs = base_kwargs(tmp_path, "RFTTrainer")
    config = TRLConfig(
        method=RFTConfig(
            n_generations_per_prompt=2, n_improve_steps=2,
            start_percentile=0.25, end_percentile=0.75,
            gen_kwargs=dict(max_new_tokens=4, do_sample=True),
        ),
        **kwargs,
    )
    trainer = trlx_tpu.train(
        reward_fn=dog_reward, prompts=["ab", "cd", "a", "b"], eval_prompts=["ab"],
        config=config,
    )
    assert trainer.iter_count >= 1


@pytest.mark.slow
@pytest.mark.parametrize("n_unfrozen", [-1, 1])
def test_ppo_seq2seq_end_to_end(tmp_path, n_unfrozen):
    """T5 PPO path (parity: reference seq2seq PPO, ppo_sentiments_t5);
    n_unfrozen=1 exercises the decoder-top hydra reference branch."""
    kwargs = base_kwargs(tmp_path, "PPOTrainer")
    kwargs["model"] = ModelConfig(
        model_path="t5", model_arch_type="seq2seq", num_layers_unfrozen=n_unfrozen,
        model_overrides=dict(
            vocab_size=len(ALPHABET) + 3, d_model=32, d_kv=8, d_ff=64,
            num_layers=2, num_decoder_layers=2, num_heads=4,
            relative_attention_num_buckets=8, decoder_start_token_id=1,
        ),
    )
    config = TRLConfig(
        method=PPOConfig(
            num_rollouts=8, chunk_size=4, ppo_epochs=2, init_kl_coef=0.01,
            target=None, gen_kwargs=dict(max_new_tokens=6, do_sample=True, top_k=0, top_p=1.0),
        ),
        **kwargs,
    )
    trainer = trlx_tpu.train(
        reward_fn=dog_reward,
        prompts=["ab", "cd ef", "gh", "a b c"] * 2,
        eval_prompts=["ab", "cd"],
        config=config,
    )
    assert trainer.iter_count >= 3


@pytest.mark.slow
def test_ilql_seq2seq_end_to_end(tmp_path):
    """T5 ILQL path (parity: reference seq2seq ILQL, ilql_sentiments_t5)."""
    kwargs = base_kwargs(tmp_path, "ILQLTrainer")
    kwargs["model"] = ModelConfig(
        model_path="t5", model_arch_type="seq2seq", num_layers_unfrozen=-1,
        model_overrides=dict(
            vocab_size=len(ALPHABET) + 3, d_model=32, d_kv=8, d_ff=64,
            num_layers=2, num_decoder_layers=2, num_heads=4,
            relative_attention_num_buckets=8, decoder_start_token_id=1,
        ),
    )
    config = TRLConfig(
        method=ILQLConfig(
            steps_for_target_q_sync=2, two_qs=True,
            gen_kwargs=dict(max_new_tokens=4, top_k=4, beta=1.0, temperature=1.0),
        ),
        **kwargs,
    )
    samples = [["ab", "cd"], ["ef", "gh"], ["a", "bc"], ["de", "fg"]] * 2
    rewards = [1.0, 0.5, -0.5, 0.25] * 2
    trainer = trlx_tpu.train(
        samples=samples, rewards=rewards, eval_prompts=["ab", "ef"], config=config
    )
    assert trainer.iter_count >= 3


@pytest.mark.slow
def test_ppo_seq2seq_peft_end_to_end(tmp_path):
    """T5 + LoRA PPO (VERDICT r2 missing #4: reference peft support is
    architecture-agnostic, modeling_base.py:162-240): adapters train, the trunk
    stays frozen, and the KL reference reuses the live params with adapters
    structurally disabled (zero extra copies)."""
    kwargs = base_kwargs(tmp_path, "PPOTrainer")
    kwargs["model"] = ModelConfig(
        model_path="t5", model_arch_type="seq2seq", num_layers_unfrozen=-1,
        peft_config={"peft_type": "LORA", "r": 4, "lora_alpha": 16},
        model_overrides=dict(
            vocab_size=len(ALPHABET) + 3, d_model=32, d_kv=8, d_ff=64,
            num_layers=2, num_decoder_layers=2, num_heads=4,
            relative_attention_num_buckets=8, decoder_start_token_id=1,
        ),
    )
    config = TRLConfig(
        method=PPOConfig(
            num_rollouts=8, chunk_size=4, ppo_epochs=2, init_kl_coef=0.01,
            target=None, gen_kwargs=dict(max_new_tokens=6, do_sample=True, top_k=0, top_p=1.0),
        ),
        **kwargs,
    )
    trainer = trlx_tpu.train(
        reward_fn=dog_reward,
        prompts=["ab", "cd ef", "gh", "a b c"] * 2,
        eval_prompts=["ab", "cd"],
        config=config,
    )
    assert trainer.iter_count >= 3
    # adapters train, everything else in the t5 trunk is frozen
    import jax

    params = jax.device_get(trainer.params)
    labels = trainer._trainable_labels(params)

    def check(tree, ltree, path=""):
        for k, v in tree.items():
            if isinstance(v, dict):
                check(v, ltree[k], path + "/" + k)
            elif "lora_" in k:
                assert ltree[k] == "train", path + "/" + k
            elif "t5" in path:
                assert ltree[k] == "freeze", path + "/" + k

    check(params, labels)


@pytest.mark.slow
def test_summarize_rlhf_three_stage_chain(tmp_path):
    """The reference's flagship recipe shape (examples/summarize_rlhf/): SFT ->
    pairwise reward-model training -> PPO from the SFT checkpoint against the
    learned reward, with checkpoint handoff at each boundary."""
    from examples.summarize_rlhf.trlx_gptj_text_summarization import main

    trainer = main(
        hparams={"train.total_steps": 4, "train.eval_interval": 2,
                 "method.num_rollouts": 8, "method.chunk_size": 8,
                 "train.batch_size": 8, "train.minibatch_size": 8},
        base_dir=str(tmp_path), sft_steps=4, rm_steps=4,
    )
    # stage boundaries actually produced artifacts
    assert os.path.isdir(tmp_path / "sft_model")  # SFT export consumed by PPO
    assert trainer.iter_count >= 4  # PPO ran from the SFT checkpoint
    logs = list((tmp_path / "ppo" / "logs").glob("*.jsonl"))
    assert logs, f"no jsonl tracker output under {tmp_path}/ppo/logs"


@pytest.mark.slow
def test_ppo_rollout_param_dtype(tmp_path):
    """train.rollout_param_dtype: generation uses a cached bf16 copy of the
    params (decode streams every weight per token; f32 masters double rollout
    HBM traffic), invalidated after each optimizer step; masters stay f32."""
    import jax.numpy as jnp

    config = TRLConfig(
        method=PPOConfig(
            num_rollouts=8, chunk_size=4, ppo_epochs=1, init_kl_coef=0.01,
            target=None, gen_kwargs=dict(max_new_tokens=6, do_sample=True, top_k=0, top_p=1.0),
        ),
        **base_kwargs(tmp_path, "PPOTrainer"),
    )
    config.train.rollout_param_dtype = "bfloat16"
    trainer = trlx_tpu.train(
        reward_fn=dog_reward,
        prompts=["ab", "cd ef", "gh", "a b c"] * 2,
        eval_prompts=["ab", "cd"],
        config=config,
    )
    assert trainer.iter_count >= 3
    # masters stay full precision; the rollout copy is bf16 and freshly cast
    import jax

    master_dtypes = {x.dtype for x in jax.tree.leaves(trainer.params) if jnp.issubdtype(x.dtype, jnp.floating)}
    assert jnp.bfloat16 not in master_dtypes
    gp = trainer.generation_params()
    gen_dtypes = {x.dtype for x in jax.tree.leaves(gp) if jnp.issubdtype(x.dtype, jnp.floating)}
    assert gen_dtypes == {jnp.dtype(jnp.bfloat16)}
    trainer._rollout_params = None  # invalidation path: re-cast produces a fresh tree
    assert trainer.generation_params() is not gp


@pytest.mark.slow
def test_ilql_beta_sweep_end_to_end(tmp_path):
    """List-valued ILQL beta (reference ilql_hh gen_kwargs beta=[1, 4]): eval
    sweeps the advantage-shaping strength, each value compiled with its own
    logits processor; rollout/default beta is the first entry."""
    config = TRLConfig(
        method=ILQLConfig(
            steps_for_target_q_sync=2, two_qs=True,
            gen_kwargs=dict(max_new_tokens=4, top_k=4, beta=[1.0, 4.0], temperature=1.0),
        ),
        **base_kwargs(tmp_path, "ILQLTrainer"),
    )
    samples = [["ab", "cd"], ["ef", "gh"], ["a", "bc"], ["de", "fg"]] * 2
    rewards = [1.0, 0.5, -0.5, 0.25] * 2
    trainer = trlx_tpu.train(
        samples=samples, rewards=rewards, eval_prompts=["ab", "ef"], config=config
    )
    assert trainer.iter_count >= 3
    assert trainer.ilql_beta == 1.0
    # one compiled generate per swept beta value
    betas = {dict(k[-1]).get("beta") for k in trainer._compiled_generate}
    assert betas == {1.0, 4.0}


@pytest.mark.slow
def test_ppo_resume_and_continue_training(tmp_path):
    """Resume from a checkpoint and KEEP TRAINING on a multi-device mesh
    (regression: orbax restore handed back single-device scalar leaves — a
    resumed adam `count` on device 0 vs params spanning the mesh — and the
    first post-resume train_step died with 'incompatible devices')."""
    def cfg(total_steps, resume=None):
        kwargs = base_kwargs(tmp_path, "PPOTrainer", total_steps=total_steps)
        kwargs["train"].resume_from_checkpoint = resume
        return TRLConfig(
            method=PPOConfig(
                num_rollouts=8, chunk_size=4, ppo_epochs=1, init_kl_coef=0.01,
                target=None, gen_kwargs=dict(max_new_tokens=6, do_sample=True, top_k=0, top_p=1.0),
            ),
            **kwargs,
        )

    prompts = ["ab", "cd ef", "gh", "a b c"] * 2
    trlx_tpu.train(reward_fn=dog_reward, prompts=prompts, config=cfg(3))
    ckpt = str(tmp_path / "ckpts" / "checkpoint_2")
    assert os.path.isdir(ckpt)

    trainer2 = trlx_tpu.train(
        reward_fn=dog_reward, prompts=prompts, config=cfg(5, resume=ckpt)
    )
    assert trainer2.iter_count >= 5  # trained PAST the restored step


@pytest.mark.slow
def test_sft_seq2seq_end_to_end(tmp_path):
    """Seq2seq SFT: teacher-forced decoder CE on (prompt, output) pairs with
    eval generation and HF export — the supervised warm-start stage the T5 PPO
    recipe needs (the reference's SFT trainer is causal-only)."""
    kwargs = base_kwargs(tmp_path, "SFTTrainer")
    kwargs["model"] = ModelConfig(
        model_path="t5", model_arch_type="seq2seq", num_layers_unfrozen=-1,
        model_overrides=dict(
            vocab_size=len(ALPHABET) + 3, d_model=32, d_kv=8, d_ff=64,
            num_layers=2, num_decoder_layers=2, num_heads=4,
            relative_attention_num_buckets=8, decoder_start_token_id=1,
        ),
    )
    config = TRLConfig(
        method=SFTConfig(gen_kwargs=dict(max_new_tokens=4, top_k=1)),
        **kwargs,
    )
    trainer = trlx_tpu.train(
        samples=[["ab", "cd"], ["ef", "gh"], ["a b", "c d"], ["gh", "ab"]] * 2,
        eval_prompts=["ab", "ef"],
        config=config,
    )
    assert trainer.iter_count >= 3
    out = str(tmp_path / "sft_t5")
    trainer.save_pretrained(out)
    assert os.path.exists(os.path.join(out, "config.json"))
    # export round-trips through the seq2seq loader
    from trlx_tpu.models.hf_loading import load_pretrained_seq2seq

    config2, params2 = load_pretrained_seq2seq(out, overrides={})
    assert params2 is not None
