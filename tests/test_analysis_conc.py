"""Concurrency analyzer (trlx_tpu/analysis/conc): CC001-CC005 positive and
negative fixtures, thread-entry-point modeling (Thread targets, escalation
callbacks, spawned closures), noqa/baseline round-trips, the seeded-regression
gate self-test, --jobs parity, and the repo-level CC-clean contract.

Fixtures run through the public ``run()`` entry with ``select=["CC"]`` (the
family prefix) so the whole pipeline — parse, call graph, conc model, rule
replay, noqa — is exercised, isolated from the JX/TH rules the same snippets
would also trip.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from trlx_tpu.analysis import RULES, run
from trlx_tpu.analysis.cli import main as cli_main
from trlx_tpu.analysis.conc import seeds
from trlx_tpu.analysis.core import resolve_select
from trlx_tpu.analysis import core as core_mod

pytestmark = pytest.mark.analysis_conc

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def check_snippet(tmp_path, source, name="snippet.py", select=("CC",)):
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    return run([str(f)], select=list(select) if select else None)


def rule_ids(findings):
    return [f.rule for f in findings]


# ----------------------------------------------------------------- registry


def test_cc_rules_registered():
    assert {"CC001", "CC002", "CC003", "CC004", "CC005"} <= set(RULES)
    for rid in ("CC001", "CC002", "CC003", "CC004", "CC005"):
        assert RULES[rid].summary


def test_select_family_prefix():
    assert [r.id for r in resolve_select(["CC"])] == [
        "CC001", "CC002", "CC003", "CC004", "CC005",
    ]
    with pytest.raises(ValueError):
        resolve_select(["CC9"])


# ------------------------------------------------------------------- CC001


CC001_POSITIVE = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.items = []
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

        def _loop(self):
            self.items.append(1)

        def drain(self):
            with self._lock:
                return list(self.items)
    """


def test_cc001_unguarded_shared_attr_positive(tmp_path):
    findings = check_snippet(tmp_path, CC001_POSITIVE)
    assert rule_ids(findings) == ["CC001"]
    assert "items" in findings[0].message
    assert "_loop" in findings[0].message  # anchored at the unguarded side


def test_cc001_both_sides_locked_is_clean(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self.items.append(1)

            def drain(self):
                with self._lock:
                    return list(self.items)
        """,
    )
    assert findings == []


def test_cc001_entry_lockset_propagates_through_private_helper(tmp_path):
    # _snapshot is only ever called with the lock held: the interprocedural
    # entry lockset proves self.items guarded, where TH001's lexical view
    # could not
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                with self._lock:
                    self.items.append(1)

            def drain(self):
                with self._lock:
                    return self._snapshot()

            def _snapshot(self):
                return list(self.items)
        """,
    )
    assert findings == []


def test_cc001_init_writes_do_not_count_as_shared(tmp_path):
    # construction happens-before publication: __init__-only writes plus one
    # reader role must stay clean
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Holder:
            def __init__(self, limit):
                self._lock = threading.Lock()
                self.limit = limit

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                return self.limit

            def read(self):
                return self.limit
        """,
    )
    assert findings == []


def test_cc001_escalation_callback_is_a_thread_root(tmp_path):
    # watchdog-style `x.escalate(name, self._cb)` registration: _cb runs on
    # the watchdog thread, so its unguarded write races the locked reader
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Supervisor:
            def __init__(self, dog):
                self._lock = threading.Lock()
                self.flag = 0
                dog.escalate("producer", self._on_stall)

            def _on_stall(self, name, age):
                self.flag = 1

            def read(self):
                with self._lock:
                    return self.flag
        """,
    )
    assert rule_ids(findings) == ["CC001"]
    assert "flag" in findings[0].message


def test_cc001_spawned_closure_is_a_thread_root(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.done = 0

            def start(self):
                def work():
                    self.done = 1
                threading.Thread(target=work, daemon=True).start()

            def poll(self):
                with self._lock:
                    return self.done
        """,
    )
    assert rule_ids(findings) == ["CC001"]
    assert "done" in findings[0].message


# ------------------------------------------------------------------- CC002


def test_cc002_lock_order_cycle_positive(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.x = 0

            def fwd(self):
                with self._a:
                    with self._b:
                        self.x += 1

            def rev(self):
                with self._b:
                    with self._a:
                        self.x += 1
        """,
    )
    assert "CC002" in rule_ids(findings)


def test_cc002_consistent_order_is_clean(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.x = 0

            def fwd(self):
                with self._a:
                    with self._b:
                        self.x += 1

            def rev(self):
                with self._a:
                    with self._b:
                        self.x -= 1
        """,
    )
    assert "CC002" not in rule_ids(findings)


def test_cc002_cycle_through_callee_summary(tmp_path):
    # fwd holds _a and calls a helper that takes _b; rev orders b-then-a:
    # the edge comes from the call-graph acquired-lock summary, not lexical
    # nesting
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.x = 0

            def fwd(self):
                with self._a:
                    self._bump()

            def _bump(self):
                with self._b:
                    self.x += 1

            def rev(self):
                with self._b:
                    with self._a:
                        self.x -= 1
        """,
    )
    assert "CC002" in rule_ids(findings)


# ------------------------------------------------------------------- CC003


def test_cc003_wait_outside_predicate_loop_positive(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Box:
            def __init__(self):
                self._cv = threading.Condition()
                self.items = []

            def get(self):
                with self._cv:
                    if not self.items:
                        self._cv.wait()
                    return self.items.pop()

            def put(self, x):
                with self._cv:
                    self.items.append(x)
                    self._cv.notify()
        """,
    )
    assert rule_ids(findings) == ["CC003"]
    assert "wait" in findings[0].message


def test_cc003_notify_without_lock_positive(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Box:
            def __init__(self):
                self._cv = threading.Condition()
                self.items = []

            def get(self):
                with self._cv:
                    while not self.items:
                        self._cv.wait()
                    return self.items.pop()

            def put(self, x):
                with self._cv:
                    self.items.append(x)
                self._cv.notify()
        """,
    )
    assert rule_ids(findings) == ["CC003"]
    assert "notify" in findings[0].message


def test_cc003_discarded_timed_wait_positive(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Box:
            def __init__(self):
                self._cv = threading.Condition()
                self.n = 0

            def poke(self):
                with self._cv:
                    self._cv.wait(1.0)
                    return self.n

            def put(self):
                with self._cv:
                    self.n += 1
                    self._cv.notify()
        """,
    )
    assert rule_ids(findings) == ["CC003"]
    assert "timeout" in findings[0].message


def test_cc003_textbook_protocol_is_clean(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Box:
            def __init__(self):
                self._cv = threading.Condition()
                self.items = []

            def get(self):
                with self._cv:
                    while not self.items:
                        self._cv.wait()
                    return self.items.pop()

            def get_bounded(self):
                with self._cv:
                    while not self.items:
                        if not self._cv.wait(1.0):
                            return None
                    return self.items.pop()

            def put(self, x):
                with self._cv:
                    self.items.append(x)
                    self._cv.notify()
        """,
    )
    assert findings == []


# ------------------------------------------------------------------- CC004


def test_cc004_check_then_act_positive(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def bump(self, n):
                with self._lock:
                    cur = self.total
                grown = cur + n
                with self._lock:
                    self.total = grown
        """,
    )
    assert rule_ids(findings) == ["CC004"]
    assert "total" in findings[0].message


def test_cc004_reread_merge_is_clean(tmp_path):
    # the scheduler's kept+pending idiom: the second section re-reads before
    # writing, so nothing observed in the first section is trusted stale
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Queue:
            def __init__(self):
                self._lock = threading.Lock()
                self.pending = []

            def requeue(self, kept):
                with self._lock:
                    current = list(self.pending)
                kept = [k for k in kept if k not in current]
                with self._lock:
                    self.pending = kept + self.pending
        """,
    )
    assert findings == []


def test_cc004_single_section_is_clean(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def bump(self, n):
                with self._lock:
                    self.total += n
        """,
    )
    assert findings == []


# ------------------------------------------------------------------- CC005


def test_cc005_file_io_under_lock_positive(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Sink:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def write(self, rec):
                with self._lock:
                    self.n += 1
                    with open("log.txt", "a") as f:
                        f.write(rec)
        """,
    )
    assert rule_ids(findings) == ["CC005"]
    assert "open" in findings[0].message


def test_cc005_queue_put_under_lock_positive(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import queue
        import threading

        class Producer:
            def __init__(self):
                self._lock = threading.Lock()
                self.q = queue.Queue()
                self.n = 0

            def send(self, x):
                with self._lock:
                    self.n += 1
                    self.q.put(x)
        """,
    )
    assert rule_ids(findings) == ["CC005"]


def test_cc005_blocking_callee_summary_positive(tmp_path):
    # client.py shape: the blocking op is inside another class's method; the
    # call-site report needs the cross-class may-block summary
    findings = check_snippet(
        tmp_path,
        """
        import threading
        import jax

        class Engine:
            def run(self):
                return jax.device_get(1)

        class Client:
            def __init__(self, engine: Engine):
                self._lock = threading.Lock()
                self.engine = engine

            def step(self):
                with self._lock:
                    return self.engine.run()
        """,
    )
    assert rule_ids(findings) == ["CC005"]
    assert "Engine.run" in findings[0].message


def test_cc005_blocking_outside_lock_is_clean(tmp_path):
    findings = check_snippet(
        tmp_path,
        """
        import threading

        class Sink:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def write(self, rec):
                with self._lock:
                    self.n += 1
                with open("log.txt", "a") as f:
                    f.write(rec)
        """,
    )
    assert findings == []


# ------------------------------------------------- suppression round-trips


def test_cc_noqa_suppresses_at_the_anchor_line(tmp_path):
    src = CC001_POSITIVE.replace(
        "self.items.append(1)",
        "self.items.append(1)  # graftcheck: noqa[CC001]",
    )
    assert check_snippet(tmp_path, src) == []


def test_cc_baseline_round_trip(tmp_path, monkeypatch):
    f = tmp_path / "racy.py"
    f.write_text(textwrap.dedent(CC001_POSITIVE))
    bl = tmp_path / "baseline.txt"
    monkeypatch.delenv(seeds.ENV_VAR, raising=False)
    assert cli_main([str(f), "--select", "CC", "--baseline", str(bl), "--write-baseline"]) == 0
    assert cli_main([str(f), "--select", "CC", "--baseline", str(bl)]) == 0
    # the entry keys on the code text: fixing the line makes it stale, and a
    # genuinely new finding still fails
    assert cli_main([str(f), "--select", "CC", "--baseline", str(bl), "--no-baseline"]) == 1


def test_stale_baseline_for_unselected_rule_not_reported(tmp_path, capsys):
    f = tmp_path / "clean.py"
    f.write_text("x = 1\n")
    bl = tmp_path / "baseline.txt"
    bl.write_text("other.py:TH001:self.x = 1  # grandfathered\n")
    assert cli_main([str(f), "--select", "CC", "--baseline", str(bl)]) == 0
    assert "stale baseline entry" not in capsys.readouterr().out


def test_stale_baseline_for_unscanned_file_not_reported(tmp_path, capsys):
    # precommit passes only changed files: entries for files outside that
    # list never had the chance to be re-found and must not read as stale
    f = tmp_path / "clean.py"
    f.write_text("x = 1\n")
    bl = tmp_path / "baseline.txt"
    bl.write_text("elsewhere/racy.py:CC005:self.q.put(x)  # grandfathered\n")
    assert cli_main([str(f), "--baseline", str(bl)]) == 0
    assert "stale baseline entry" not in capsys.readouterr().out


# ------------------------------------------------------- seeded regression


def test_seed_scheduler_race_fires_cc001(tmp_path, monkeypatch):
    monkeypatch.setenv(seeds.ENV_VAR, "scheduler_race")
    findings = run(
        [os.path.join(REPO_ROOT, "trlx_tpu", "serving", "scheduler.py")],
        select=["CC"],
    )
    hits = [f for f in findings if f.rule == "CC001" and "finished" in f.message]
    assert hits, rule_ids(findings)


def test_seed_is_in_memory_only(tmp_path, monkeypatch):
    # same file, seed unset: clean — the seed never touches the tree on disk
    monkeypatch.delenv(seeds.ENV_VAR, raising=False)
    findings = run(
        [os.path.join(REPO_ROOT, "trlx_tpu", "serving", "scheduler.py")],
        select=["CC"],
    )
    assert [f for f in findings if f.rule == "CC001"] == []


def test_unknown_seed_is_exit_2(tmp_path, monkeypatch):
    f = tmp_path / "empty.py"
    f.write_text("x = 1\n")
    monkeypatch.setenv(seeds.ENV_VAR, "not_a_seed")
    assert cli_main([str(f), "--select", "CC", "--no-baseline"]) == 2


# ----------------------------------------------------------------- --jobs


def test_jobs_pool_parity(tmp_path, monkeypatch):
    # force the fork-pool path even on 1-core CI hosts (run() clamps jobs to
    # cpu_count); findings must match the serial path exactly
    for i in range(4):
        (tmp_path / f"mod{i}.py").write_text(
            textwrap.dedent(CC001_POSITIVE).replace("Worker", f"Worker{i}")
        )
    serial = run([str(tmp_path)], select=["CC"], jobs=1)
    monkeypatch.setattr(core_mod.os, "cpu_count", lambda: 4)
    pooled = run([str(tmp_path)], select=["CC"], jobs=4)
    key = lambda f: (f.path, f.lineno, f.rule, f.message)  # noqa: E731
    assert sorted(map(key, serial)) == sorted(map(key, pooled))
    assert len(serial) == 4


# ----------------------------------------------------- repo-level contract


@pytest.mark.slow
def test_repo_tree_is_cc_clean():
    """Acceptance criteria: the merged tree passes the CC gate..."""
    env = {k: v for k, v in os.environ.items() if k != seeds.ENV_VAR}
    proc = subprocess.run(
        [sys.executable, "-m", "trlx_tpu.analysis", "trlx_tpu", "tests",
         "examples", "scripts", "bench.py", "__graft_entry__.py", "--select", "CC"],
        cwd=REPO_ROOT, capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_repo_tree_seeded_race_fails_the_gate():
    """...and the seeded PR-8 race makes the same command exit 1."""
    env = dict(os.environ, **{seeds.ENV_VAR: "scheduler_race"})
    proc = subprocess.run(
        [sys.executable, "-m", "trlx_tpu.analysis", "trlx_tpu", "tests",
         "examples", "scripts", "bench.py", "__graft_entry__.py", "--select", "CC"],
        cwd=REPO_ROOT, capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "CC001" in proc.stdout and "finished" in proc.stdout
