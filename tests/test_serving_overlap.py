"""Stream-overlapped PPO tests (``train.serving.stream_overlap``;
docs/serving.md "Stream-overlapped PPO"): reorder-buffer determinism, overlap
interval accounting, the bounded score-fn bucket ladder, overlap-off bitwise
parity with the serial serving path, overlap-on rollout-content parity under
shuffled reward completion, exactly-once scoring through chaos (engine crash +
wedged reward producer), ref-offload pinning across the streaming window,
staged learner-batch consumption, and the live overlap-fraction / span-nesting
proof the CI serialize gate runs against."""

import threading
import time

import numpy as np
import pytest

import jax

pytestmark = pytest.mark.serving_overlap


# ------------------------------------------------------------ reorder buffer


def test_reorder_buffer_orders_out_of_order_completion():
    from trlx_tpu.rollout import ReorderBuffer

    rb = ReorderBuffer()
    rb.add(2, "c")
    rb.add(0, "a")
    assert rb.pop_ready() == ["a"]  # index 1 still missing
    assert rb.pending == 1 and rb.next_index == 1
    rb.add(1, "b")
    assert rb.pop_ready() == ["b", "c"]
    assert rb.pending == 0 and rb.next_index == 3


def test_reorder_buffer_rejects_duplicates_and_replays():
    from trlx_tpu.rollout import ReorderBuffer

    rb = ReorderBuffer()
    rb.add(0, "a")
    with pytest.raises(ValueError):
        rb.add(0, "dup")
    rb.pop_ready()
    with pytest.raises(ValueError):
        rb.add(0, "behind-cursor")


def test_reorder_buffer_tombstones_never_stall_the_cursor():
    from trlx_tpu.rollout import ReorderBuffer

    rb = ReorderBuffer()
    rb.add(1, None)  # quarantine-dropped element
    rb.add(0, "a")
    rb.add(2, "c")
    # the tombstone is skipped, not emitted, and the cursor crosses it
    assert rb.pop_ready() == ["a", "c"]
    assert rb.next_index == 3


# ---------------------------------------------------------- overlap window


def test_overlap_window_interval_accounting():
    from trlx_tpu.obs.overlap import OverlapWindow

    w = OverlapWindow()
    w.note_decode(0.0, 1.0)
    w.note_decode(1.0001, 2.0)  # sub-epsilon gap: merged into [0, 2]
    w.note_decode(3.0, 4.0)
    w.note_work(0.5, 1.5)  # 1.0 s inside [0, 2]
    w.note_work(2.2, 2.8)  # fully in the decode gap
    w.note_work(3.5, 5.0)  # 0.5 s inside [3, 4]
    assert w.decode_busy_s == pytest.approx(3.0, abs=1e-6)
    assert w.overlapped_s == pytest.approx(1.5, abs=1e-6)
    assert w.fraction == pytest.approx(0.5, abs=1e-6)


def test_overlap_window_empty_is_zero():
    from trlx_tpu.obs.overlap import OverlapWindow

    w = OverlapWindow()
    assert w.decode_busy_s == 0.0 and w.overlapped_s == 0.0 and w.fraction == 0.0


# ------------------------------------------------- bounded score-fn buckets


def test_overlap_r_bucket_ladder_is_bounded():
    from types import SimpleNamespace

    from trlx_tpu.trainer.ppo_trainer import _STREAM_MAX_R_BUCKETS, PPOTrainer

    for max_new in (1, 4, 7, 12, 64, 100, 1000):
        ladder = PPOTrainer._overlap_r_buckets(
            SimpleNamespace(_serving_max_new=max_new)
        )
        assert len(ladder) <= _STREAM_MAX_R_BUCKETS
        assert ladder == sorted(set(ladder))
        # the full shape is always present: decode may re-append eos
        assert ladder[-1] >= max_new + 1


def test_check_stream_bucket_family_asserts_on_overflow():
    from trlx_tpu.trainer.ppo_trainer import check_stream_bucket_family

    families = {}
    for r in (8, 16, 32, 64):
        check_stream_bucket_family(families, 4, 8, r)
    assert families[(4, 8)] == {8, 16, 32, 64}
    with pytest.raises(AssertionError, match="bucket family"):
        check_stream_bucket_family(families, 4, 8, 128)
    # other (B, P) families are independent
    check_stream_bucket_family(families, 2, 8, 128)


def test_stream_overlap_config_defaults_off():
    from trlx_tpu.data.configs import ServingConfig, TrainConfig

    s = ServingConfig()
    assert s.stream_overlap is False
    assert s.overlap_reward_workers == 2
    assert s.overlap_microbucket == 0
    assert s.overlap_learn_stage is True
    cfg = TrainConfig.from_dict(dict(
        total_steps=1, batch_size=1, checkpoint_dir="/tmp/x",
        serving=dict(enabled=True, stream_overlap=True, overlap_microbucket=2),
    ))
    assert cfg.serving.stream_overlap is True
    assert cfg.serving.overlap_microbucket == 2


# ----------------------------------------------------------- tiny PPO rig


def _tiny_ppo_config(tmp_path, serving=None, self_healing=None,
                     serving_resilience=None, model_kw=None, **method_kw):
    from trlx_tpu.data.configs import (
        MeshConfig, ModelConfig, OptimizerConfig, SchedulerConfig,
        SelfHealingConfig, ServingConfig, ServingResilienceConfig,
        TokenizerConfig, TrainConfig, TRLConfig,
    )
    from trlx_tpu.methods.ppo import PPOConfig

    alphabet = "abcdefgh "
    mkw = dict(
        num_rollouts=4, chunk_size=2, ppo_epochs=1, init_kl_coef=0.01,
        target=None, gen_kwargs=dict(max_new_tokens=4, do_sample=False),
    )
    mkw.update(method_kw)
    return TRLConfig(
        method=PPOConfig(**mkw),
        train=TrainConfig(
            seq_length=32, epochs=1, total_steps=1, batch_size=4, minibatch_size=2,
            checkpoint_interval=100, eval_interval=100,
            checkpoint_dir=str(tmp_path / "ckpts"), pipeline="PromptPipeline",
            trainer="PPOTrainer", tracker=None, seed=2,
            serving=serving or ServingConfig(),
            self_healing=self_healing or SelfHealingConfig(),
            serving_resilience=serving_resilience or ServingResilienceConfig(),
        ),
        model=ModelConfig(
            model_path="gpt2", num_layers_unfrozen=-1,
            model_overrides=dict(
                vocab_size=len(alphabet) + 3, hidden_size=32, num_layers=2,
                num_heads=2, intermediate_size=64, max_position_embeddings=64,
            ),
            **(model_kw or {}),
        ),
        tokenizer=TokenizerConfig(tokenizer_path=f"char://{alphabet}"),
        optimizer=OptimizerConfig(name="adamw", kwargs=dict(lr=1e-3)),
        scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=100, eta_min=1e-3)),
        mesh=MeshConfig(data=1, fsdp=1, model=1, compute_dtype="float32"),
    )


@pytest.fixture
def single_device_mesh(monkeypatch):
    """Serving requires a single-device mesh; conftest exposes 8 virtual CPU
    devices, so pin trainer meshes to the first."""
    from trlx_tpu.parallel import mesh as mesh_lib

    real = mesh_lib.make_mesh
    monkeypatch.setattr(
        mesh_lib, "mesh_from_config",
        lambda cfg, devices=None: real(
            data=1, fsdp=1, model=1, devices=jax.devices()[:1]
        ),
    )


def _build_ppo(config, reward_fn=None, prompts=None):
    from trlx_tpu.pipeline.offline_pipeline import PromptPipeline
    from trlx_tpu.utils.loading import get_trainer

    if reward_fn is None:
        def reward_fn(samples, **kw):
            return [float(s.count("a")) for s in samples]

    trainer = get_trainer("PPOTrainer")(config=config, reward_fn=reward_fn)
    prompts = prompts or ["ab", "cd ef", "gh", "a b c"]
    trainer.add_prompt_pipeline(PromptPipeline(prompts, 12, trainer.tokenizer))
    return trainer


def _store_dump(trainer):
    return [
        (np.asarray(e.query_tensor).tolist(), np.asarray(e.response_tensor).tolist())
        for e in trainer.store.history
    ]


def _serving(**kw):
    from trlx_tpu.data.configs import ServingConfig

    base = dict(enabled=True, num_slots=3, block_size=4)
    base.update(kw)
    return ServingConfig(**base)


# ------------------------------------------------------- parity (off / on)


@pytest.mark.slow
def test_stream_overlap_off_bitwise_parity(tmp_path, single_device_mesh):
    """``stream_overlap`` off keeps the serving experience path byte-identical
    to the serial one — and never opens an overlap window."""
    t_serial = _build_ppo(_tiny_ppo_config(tmp_path / "serial", serving=_serving()))
    t_serial._resolve_serving()
    t_serial.make_experience(4, 0)
    ref = _store_dump(t_serial)
    assert t_serial._serving_engine.summary()["overlap_windows"] == 0.0

    t_off = _build_ppo(_tiny_ppo_config(
        tmp_path / "off", serving=_serving(stream_overlap=False)
    ))
    t_off._resolve_serving()
    t_off.make_experience(4, 0)
    assert _store_dump(t_off) == ref
    assert t_off._serving_engine.summary()["overlap_windows"] == 0.0
    # identical PPO-side stats: same rewards, same KL accounting
    h_ref = t_serial.store.history
    h_off = t_off.store.history
    for a, b in zip(h_ref, h_off):
        assert np.array_equal(np.asarray(a.rewards), np.asarray(b.rewards))
        assert np.array_equal(np.asarray(a.logprobs), np.asarray(b.logprobs))
        assert np.array_equal(np.asarray(a.values), np.asarray(b.values))


@pytest.mark.slow
def test_stream_overlap_on_contents_match_serial(tmp_path, single_device_mesh):
    """With overlap on, greedy rollout contents and store order are identical
    to the serial serving path — reward completion timing must not leak into
    bucket composition or store order. Two streamed runs with different
    (seeded) reward delays produce byte-identical stores."""
    import random

    t_ref = _build_ppo(_tiny_ppo_config(tmp_path / "ref", serving=_serving()))
    t_ref._resolve_serving()
    t_ref.make_experience(4, 0)
    ref = _store_dump(t_ref)

    def delayed_reward(seed):
        rng = random.Random(seed)

        def reward_fn(samples, **kw):
            time.sleep(rng.random() * 0.02)  # shuffle worker completion order
            return [float(s.count("a")) for s in samples]

        return reward_fn

    dumps = []
    for run, seed in enumerate((7, 1234)):
        t = _build_ppo(
            _tiny_ppo_config(
                tmp_path / f"stream{run}", serving=_serving(stream_overlap=True)
            ),
            reward_fn=delayed_reward(seed),
        )
        t._resolve_serving()
        t.make_experience(4, 0)
        assert t._serving_engine.summary()["overlap_windows"] == 1.0
        assert t._serving_engine.allocator.blocks_in_use == 0
        dumps.append(_store_dump(t))
    assert dumps[0] == ref
    assert dumps[1] == ref  # deterministic under shuffled completion


# --------------------------------------------------------------- chaos soak


@pytest.mark.slow
def test_stream_overlap_exactly_once_under_chaos(tmp_path, single_device_mesh):
    """Chaos soak: a serving-decode crash (supervised restart, replay) plus a
    wedged reward producer must not double-score or drop any sequence — the
    reward_fn fires exactly once per rollout and the store stays whole and
    identical to the serial path."""
    from trlx_tpu.data.configs import ServingResilienceConfig
    from trlx_tpu.resilience.chaos import chaos

    t_ref = _build_ppo(_tiny_ppo_config(tmp_path / "ref", serving=_serving()))
    t_ref._resolve_serving()
    t_ref.make_experience(4, 0)
    ref = _store_dump(t_ref)

    calls = []
    lock = threading.Lock()

    def counting_reward(samples, **kw):
        with lock:
            calls.extend(samples)
        return [float(s.count("a")) for s in samples]

    t = _build_ppo(
        _tiny_ppo_config(
            tmp_path / "chaos",
            serving=_serving(stream_overlap=True),
            serving_resilience=ServingResilienceConfig(enabled=True, max_restarts=8),
        ),
        reward_fn=counting_reward,
    )
    t._resolve_serving()
    chaos.configure("serving-decode:1,producer-wedge:1")
    try:
        t.make_experience(4, 0)
    finally:
        chaos.configure(None)
    assert _store_dump(t) == ref  # replayed greedy decode, nothing lost
    assert len(calls) == 4  # exactly once per sequence, despite the restart
    assert len(set(calls)) == len(calls)
    assert t._serving_engine.restarts >= 1


# ----------------------------------------------------------- ref offload


@pytest.mark.slow
def test_stream_overlap_ref_offload_pinned_window(tmp_path, single_device_mesh):
    """S2: with ``model.offload_ref``, the device ref copy is materialized
    once, pinned across the whole streaming window, and released at stream
    drain — and the offloaded-ref streamed store matches the resident-ref
    streamed store bitwise."""
    t_res = _build_ppo(_tiny_ppo_config(
        tmp_path / "resident", serving=_serving(stream_overlap=True)
    ))
    t_res._resolve_serving()
    t_res.make_experience(4, 0)
    ref = _store_dump(t_res)

    t_off = _build_ppo(_tiny_ppo_config(
        tmp_path / "offload", serving=_serving(stream_overlap=True),
        model_kw=dict(offload_ref=True),
    ))
    t_off._resolve_serving()
    assert t_off._ref_host is not None  # offload actually engaged

    uploads = []
    orig = type(t_off)._ref_scoring_params

    def counting_ref(self):
        fresh = self._ref_dev is None
        out = orig(self)
        if fresh and self._ref_dev is not None:
            uploads.append(1)
        return out

    type(t_off)._ref_scoring_params = counting_ref
    try:
        t_off.make_experience(4, 0)
    finally:
        type(t_off)._ref_scoring_params = orig
    assert _store_dump(t_off) == ref
    # pinned: exactly one host->device upload for the whole window...
    assert len(uploads) == 1
    # ...and released at stream drain (make_experience tail)
    assert t_off._ref_dev is None
    assert not t_off._ref_pinned


# ----------------------------------------------------------- learn staging


@pytest.mark.slow
def test_stream_overlap_staged_learn_batches_consumed(tmp_path, single_device_mesh):
    """First-epoch learner microbatches staged during the streaming window are
    consumed by ``train_step`` (content-matched against the loader's batch)
    instead of a fresh host->device transfer."""
    t = _build_ppo(_tiny_ppo_config(
        tmp_path, serving=_serving(stream_overlap=True)
    ))
    t._resolve_serving()
    t.make_experience(4, 0)
    # num_rollouts=4, batch_size=4 -> exactly one staged learner batch
    assert len(t._staged_learn) == 1
    t.prepare_learning()
    for batch in t.create_train_dataloader():
        stats = t.train_step(batch)
        break
    assert len(t._staged_learn) == 0  # consumed, not discarded
    assert np.isfinite(stats["losses/total_loss"])


def test_staged_learn_mismatch_falls_back(tmp_path):
    """The staging seam never trusts itself: a content mismatch at consume
    time clears the stage and falls back to a fresh transfer (returns None)."""
    from trlx_tpu.trainer.mesh_trainer import MeshRLTrainer

    class Seam:
        _clear_staged_learn = MeshRLTrainer._clear_staged_learn
        _stage_learn_batch = MeshRLTrainer._stage_learn_batch
        _host_batches_equal = staticmethod(MeshRLTrainer._host_batches_equal)
        _pop_staged_learn = MeshRLTrainer._pop_staged_learn

    s = Seam()
    host = {"x": np.arange(4), "y": np.ones((2, 2))}
    s._stage_learn_batch(host, "DEVICE")
    # exact content match pops the staged device batch
    match = {"x": np.arange(4), "y": np.ones((2, 2))}
    assert s._pop_staged_learn(match) == "DEVICE"
    assert s._staged_learn == []
    # mismatch clears everything and returns None
    s._stage_learn_batch(host, "DEVICE")
    assert s._pop_staged_learn({"x": np.arange(4), "y": np.zeros((2, 2))}) is None
    assert s._staged_learn == []
    # different tree structure is a mismatch, not a crash
    s._stage_learn_batch(host, "DEVICE")
    assert s._pop_staged_learn({"x": np.arange(4)}) is None


# ------------------------------------------- live overlap + span nesting


def _overlap_rig(tmp_path, reward_sleep_s=0.03, **serving_kw):
    """A rig sized so reward/score work genuinely lands inside the decode
    window: 2 decode slots over 8 prompts stagger completions into waves, and
    each wave's rewards overlap the next wave's decode."""
    serving = _serving(
        stream_overlap=True, num_slots=2, overlap_microbucket=2,
        overlap_reward_workers=2, **serving_kw,
    )
    config = _tiny_ppo_config(
        tmp_path, serving=serving,
        num_rollouts=8, chunk_size=8,
        gen_kwargs=dict(max_new_tokens=12, do_sample=False),
    )

    def reward_fn(samples, **kw):
        time.sleep(reward_sleep_s * len(samples))
        return [float(s.count("a")) for s in samples]

    prompts = ["ab", "cd ef", "gh", "a b c", "ba", "fe dc", "hg", "c b a"]
    t = _build_ppo(config, reward_fn=reward_fn, prompts=prompts)
    t._resolve_serving()
    return t


def _summary_overlap_delta(before, after):
    decode = after["overlap_decode_s"] - before["overlap_decode_s"]
    overlapped = after["overlap_overlapped_s"] - before["overlap_overlapped_s"]
    return overlapped / max(1e-9, decode)


@pytest.mark.slow
def test_stream_overlap_fraction_and_span_nesting(tmp_path, single_device_mesh):
    """The acceptance proof: after a compile warmup, a streamed rollout on CPU
    overlaps >= 0.5 of its decode-busy time with reward/score/stage work, and
    the span trace shows score spans nested inside the decode span with reward
    spans time-contained in the decode window.

    The CI serialize gate re-runs this test with
    ``TRLX_OVERLAP_SEED_REGRESSION=serialize`` and requires it to FAIL — a
    pipeline that quietly serializes must not report overlap."""
    from trlx_tpu.obs.spans import tracer

    t = _overlap_rig(tmp_path)
    t.make_experience(8, 0)  # warmup: decode/score/prefill compiles
    before = t._serving_engine.summary()
    tracer.reset()
    tracer.configure(enabled=True, trace_path=str(tmp_path / "trace.json"))
    try:
        t.make_experience(8, 1)
    finally:
        events = tracer.snapshot_events()
        tracer.configure(enabled=False, trace_path=None)
        tracer.reset()
    after = t._serving_engine.summary()
    frac = _summary_overlap_delta(before, after)
    assert frac >= 0.5, f"overlap fraction {frac:.3f} < 0.5 (decode not overlapped)"
    assert after["overlap_fraction"] > 0.0

    # span-nesting proof: scoring dispatch runs inside the decode span on the
    # driving thread, and at least one worker-thread reward span is fully
    # contained in a decode span's time window
    names = {e["name"] for e in events}
    assert "decode.score" in names, sorted(names)
    decode_windows = [
        (e["ts"], e["ts"] + e["dur"]) for e in events if e["name"] == "decode"
    ]
    rewards = [(e["ts"], e["ts"] + e["dur"]) for e in events if e["name"] == "reward"]
    assert decode_windows and rewards
    assert any(
        d0 <= r0 and r1 <= d1
        for (r0, r1) in rewards
        for (d0, d1) in decode_windows
    ), "no reward span nested inside the decode window"


@pytest.mark.slow
def test_stream_overlap_serialize_env_collapses_fraction(tmp_path, monkeypatch,
                                                         single_device_mesh):
    """``TRLX_OVERLAP_SEED_REGRESSION=serialize`` forces serial in-memory
    consumption: every reward blocks the decode loop, so the measured overlap
    fraction collapses — the seeded regression the CI gate exists to catch."""
    t = _overlap_rig(tmp_path)
    t.make_experience(8, 0)  # warmup (normal mode, compiles everything)
    monkeypatch.setenv("TRLX_OVERLAP_SEED_REGRESSION", "serialize")
    before = t._serving_engine.summary()
    t.make_experience(8, 1)
    after = t._serving_engine.summary()
    frac = _summary_overlap_delta(before, after)
    assert frac < 0.5, f"serialized run still reports overlap {frac:.3f}"


# --------------------------------------------------- S1: bounded jit cache


@pytest.mark.slow
def test_stream_score_fn_cache_stays_bounded(tmp_path, single_device_mesh):
    """S1: every streamed scoring shape comes off the quantized R ladder, so
    the jit cache holds <= 4 R shapes per (B, P) family no matter how ragged
    the finished lengths are."""
    from trlx_tpu.trainer.ppo_trainer import _STREAM_MAX_R_BUCKETS

    t = _overlap_rig(tmp_path, reward_sleep_s=0.0)
    t.make_experience(8, 0)
    assert t._score_fn_families  # the streamed path registered its shapes
    for (B, P), rs in t._score_fn_families.items():
        assert len(rs) <= _STREAM_MAX_R_BUCKETS
        assert rs <= set(t._overlap_r_buckets())
