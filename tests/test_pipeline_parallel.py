"""Pipeline parallelism: stacked-layer layout + GPipe schedule over the pipe axis.

The reference's PP is Apex's fwd/bwd microbatch engine driven from NeMo
(`modeling_nemo_ppo.py:713-731`); here it's a shard_map GPipe schedule over
``ppermute`` (trlx_tpu/parallel/pipeline.py). These tests check the stacked
param layout is exactly equivalent to the listed layout, and that the pipelined
forward/backward matches the plain model to float32 tolerance on real
multi-device meshes (which the reference's test suite cannot do at all —
SURVEY.md §4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trlx_tpu.models.presets import PRESETS
from trlx_tpu.models.transformer import TransformerLM
from trlx_tpu.parallel.mesh import make_mesh, put_batch
from trlx_tpu.parallel.pipeline import (
    pick_microbatches,
    stack_layer_params,
    unstack_layer_params,
)
from trlx_tpu.parallel.sharding import make_param_shardings

CFG = PRESETS["gpt2"].replace(
    vocab_size=128, hidden_size=64, num_layers=4, num_heads=4,
    intermediate_size=256, max_position_embeddings=64, compute_dtype=jnp.float32,
)
CFG_PP = CFG.replace(pipeline_stages=4, pipeline_microbatches=4)
B, T = 8, 16


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(1, CFG.vocab_size, (B, T)), jnp.int32)
    mask = np.ones((B, T), np.int32)
    mask[:, :3] = 0  # left padding
    mask = jnp.asarray(mask)
    m_list = TransformerLM(CFG)
    p_list = m_list.init(jax.random.PRNGKey(0), ids[:1], mask[:1])["params"]
    logits_ref, hidden_ref, _, _ = m_list.apply({"params": p_list}, ids, mask)
    p_stack = stack_layer_params(jax.device_get(p_list), CFG.num_layers)
    return ids, mask, m_list, p_list, logits_ref, hidden_ref, p_stack


def test_stacked_layout_matches_listed(setup):
    ids, mask, _, p_list, logits_ref, _, p_stack = setup
    m_pp = TransformerLM(CFG_PP)
    logits, _, _, _ = m_pp.apply({"params": p_stack}, ids, mask)
    assert float(jnp.max(jnp.abs(logits - logits_ref))) < 1e-5

    p_round = unstack_layer_params(p_stack, CFG.num_layers)
    ok = jax.tree.map(
        lambda a, b: np.allclose(np.asarray(a), np.asarray(b)), p_list, p_round
    )
    assert all(jax.tree.leaves(ok))


def test_pipelined_forward_matches(setup):
    ids, mask, _, _, logits_ref, _, p_stack = setup
    m_pp = TransformerLM(CFG_PP)
    mesh = make_mesh(data=2, fsdp=1, model=1, pipe=4)
    shardings = make_param_shardings({"transformer": p_stack}, mesh)["transformer"]
    p_dev = jax.tree.map(jax.device_put, p_stack, shardings)
    batch = put_batch(mesh, {"ids": np.asarray(ids), "mask": np.asarray(mask)})
    with mesh:
        logits = jax.jit(lambda p, i, m: m_pp.apply({"params": p}, i, m)[0])(
            p_dev, batch["ids"], batch["mask"]
        )
    assert float(jnp.max(jnp.abs(logits - logits_ref))) < 1e-4


def test_pipelined_composes_with_tp(setup):
    """pipe=2 × model=2 × data=2: PP composes with tensor parallelism (the
    reference's TPxPPxDP grid, nemo_ppo_trainer.py:344-346)."""
    ids, mask, _, _, logits_ref, _, p_stack = setup
    m_pp = TransformerLM(CFG.replace(pipeline_stages=2, pipeline_microbatches=2))
    mesh = make_mesh(data=2, fsdp=1, model=2, pipe=2)
    shardings = make_param_shardings({"transformer": p_stack}, mesh)["transformer"]
    p_dev = jax.tree.map(jax.device_put, p_stack, shardings)
    batch = put_batch(mesh, {"ids": np.asarray(ids), "mask": np.asarray(mask)})
    with mesh:
        logits = jax.jit(lambda p, i, m: m_pp.apply({"params": p}, i, m)[0])(
            p_dev, batch["ids"], batch["mask"]
        )
    assert float(jnp.max(jnp.abs(logits - logits_ref))) < 1e-4


def test_pipelined_grad_matches(setup):
    ids, mask, m_list, p_list, _, _, p_stack = setup
    m_pp = TransformerLM(CFG_PP)
    mesh = make_mesh(data=2, fsdp=1, model=1, pipe=4)
    shardings = make_param_shardings({"transformer": p_stack}, mesh)["transformer"]
    p_dev = jax.tree.map(jax.device_put, p_stack, shardings)

    def loss_list(p):
        lg, _, _, _ = m_list.apply({"params": p}, ids, mask)
        return jnp.mean((lg * mask[..., None]) ** 2)

    def loss_pp(p):
        lg, _, _, _ = m_pp.apply({"params": p}, ids, mask)
        return jnp.mean((lg * mask[..., None]) ** 2)

    g_ref = stack_layer_params(jax.device_get(jax.grad(loss_list)(p_list)), CFG.num_layers)
    with mesh:
        g_pp = jax.device_get(jax.jit(jax.grad(loss_pp))(p_dev))
    errs = jax.tree.map(
        lambda a, b: float(np.max(np.abs(np.asarray(a) - np.asarray(b)))), g_ref, g_pp
    )
    assert max(jax.tree.leaves(errs)) < 1e-4


def test_stacked_cached_decode_matches(setup):
    """Generation path: stacked models run a sequential layer scan over the cache
    (prefill + decode steps) and must match the listed model exactly."""
    ids, mask, m_list, p_list, _, _, p_stack = setup
    m_pp = TransformerLM(CFG_PP)
    S = T + 2
    cache_l = m_list.init_cache(B, S)
    cache_s = m_pp.init_cache(B, S)

    def mask_at(extra):  # [B, S] validity over cache slots, `extra` decoded tokens
        m = np.concatenate(
            [np.asarray(mask), np.zeros((B, 2), np.asarray(mask).dtype)], axis=1
        )
        m[:, T : T + extra] = 1
        return jnp.asarray(m)

    lg_l, _, _, cache_l = m_list.apply({"params": p_list}, ids, mask_at(0), cache=cache_l)
    lg_s, _, _, cache_s = m_pp.apply({"params": p_stack}, ids, mask_at(0), cache=cache_s)
    np.testing.assert_allclose(np.asarray(lg_l), np.asarray(lg_s), atol=1e-5)

    tok = jnp.full((B, 1), 7, jnp.int32)
    for i in range(2):
        lg_l, _, _, cache_l = m_list.apply(
            {"params": p_list}, tok, mask_at(i + 1), cache=cache_l
        )
        lg_s, _, _, cache_s = m_pp.apply(
            {"params": p_stack}, tok, mask_at(i + 1), cache=cache_s
        )
        np.testing.assert_allclose(np.asarray(lg_l), np.asarray(lg_s), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(cache_l["k"]), np.asarray(cache_s["k"]), atol=1e-5
    )


def test_pipelined_cached_decode_matches_on_mesh(setup):
    """Cached decode under ``pipeline_stages > 1`` on a REAL multi-device mesh
    (data=2 x pipe=2 x model=2): the sequential layer scan streams every
    stage's param shards (transformer.py `_apply_stacked` cache branch), and
    its prefill + per-token logits must match the single-stage listed model.
    VERDICT r4 weak-item 4: this path was trusted single-device, untested
    multi-device."""
    ids, mask, m_list, p_list, _, _, p_stack = setup
    m_pp = TransformerLM(CFG.replace(pipeline_stages=2, pipeline_microbatches=2))
    mesh = make_mesh(data=2, fsdp=1, model=2, pipe=2)
    shardings = make_param_shardings({"transformer": p_stack}, mesh)["transformer"]
    p_dev = jax.tree.map(jax.device_put, p_stack, shardings)
    S = T + 2

    def mask_at(extra):
        m = np.concatenate(
            [np.asarray(mask), np.zeros((B, 2), np.asarray(mask).dtype)], axis=1
        )
        m[:, T : T + extra] = 1
        return m

    @jax.jit
    def prefill(p, i, m):
        cache = m_pp.init_cache(B, S)
        cache = {**cache, "index": 0}
        lg, _, _, cache = m_pp.apply({"params": p}, i, m, cache=cache)
        return lg, cache

    @jax.jit
    def decode(p, tok, m, cache):
        lg, _, _, cache = m_pp.apply({"params": p}, tok, m, cache=cache)
        return lg, cache

    # reference: the listed single-stage model, no mesh
    cache_l = m_list.init_cache(B, S)
    lg_ref, _, _, cache_l = m_list.apply(
        {"params": p_list}, ids, jnp.asarray(mask_at(0)), cache=cache_l
    )

    batch = put_batch(mesh, {"ids": np.asarray(ids), "mask": mask_at(0)})
    with mesh:
        lg, cache = prefill(p_dev, batch["ids"], batch["mask"])
    np.testing.assert_allclose(np.asarray(lg), np.asarray(lg_ref), atol=1e-4)

    tok = jnp.full((B, 1), 7, jnp.int32)
    for i in range(2):
        lg_ref, _, _, cache_l = m_list.apply(
            {"params": p_list}, tok, jnp.asarray(mask_at(i + 1)), cache=cache_l
        )
        dbatch = put_batch(mesh, {"tok": np.asarray(tok), "mask": mask_at(i + 1)})
        with mesh:
            lg, cache = decode(p_dev, dbatch["tok"], dbatch["mask"], cache)
        np.testing.assert_allclose(np.asarray(lg), np.asarray(lg_ref), atol=1e-4)


def test_pipelined_bf16_forward_compiles(setup):
    """bf16 regression: XLA-CPU's AllReducePromotion pass crashed on the GPipe
    output psum in bf16 ('Invalid binary instruction opcode copy'); the psum now
    runs in f32."""
    ids, mask, _, _, _, _, p_stack = setup
    m_pp = TransformerLM(
        CFG.replace(pipeline_stages=2, pipeline_microbatches=2, compute_dtype=jnp.bfloat16)
    )
    mesh = make_mesh(data=2, fsdp=1, model=2, pipe=2)
    shardings = make_param_shardings({"transformer": p_stack}, mesh)["transformer"]
    p_dev = jax.tree.map(jax.device_put, p_stack, shardings)
    with mesh:
        logits = jax.jit(lambda p, i, m: m_pp.apply({"params": p}, i, m)[0])(
            p_dev, ids, mask
        )
    assert logits.dtype == jnp.bfloat16 or logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits.astype(jnp.float32))))


def test_scan_layers_matches_listed(setup):
    """scan_layers=True uses the stacked layout + lax.scan WITHOUT pipelining
    (O(1)-in-depth compile); numerics must match the listed model, including
    cached decode."""
    ids, mask, m_list, p_list, logits_ref, _, p_stack = setup
    m_scan = TransformerLM(CFG.replace(scan_layers=True))
    logits, _, _, _ = m_scan.apply({"params": p_stack}, ids, mask)
    assert float(jnp.max(jnp.abs(logits - logits_ref))) < 1e-5

    S = T + 1
    cache_l = m_list.init_cache(B, S)
    cache_s = m_scan.init_cache(B, S)
    full = jnp.pad(mask, ((0, 0), (0, 1)))
    lg_l, _, _, _ = m_list.apply({"params": p_list}, ids, full, cache=cache_l)
    lg_s, _, _, _ = m_scan.apply({"params": p_stack}, ids, full, cache=cache_s)
    np.testing.assert_allclose(np.asarray(lg_l), np.asarray(lg_s), atol=1e-5)


@pytest.mark.slow
def test_sft_trains_with_scan_layers(tmp_path):
    """End-to-end SFT with scan_layers through model_overrides (no pipe axis)."""
    import trlx_tpu
    from trlx_tpu.methods.sft import SFTConfig

    config = _trl_config(tmp_path, "SFTTrainer", SFTConfig(gen_kwargs=dict(max_new_tokens=4)))
    config.mesh.pipe = 1
    config.mesh.model = 2
    config.mesh.fsdp = 2
    config.model.model_overrides["scan_layers"] = True
    trainer = trlx_tpu.train(
        samples=["ab ab abab", "cd cdcd", "efgh ef", "a b a b"] * 2,
        eval_prompts=["ab", "cd"],
        config=config,
    )
    assert trainer.iter_count >= 3
    assert "layers_scan" in trainer.params["transformer"]


def test_pick_microbatches():
    assert pick_microbatches(8, 4) == 4
    assert pick_microbatches(6, 4) == 3
    assert pick_microbatches(7, 4) == 1
    assert pick_microbatches(2, 16) == 2


ALPHABET = "abcdefgh "


def _trl_config(tmp_path, trainer, method):
    from trlx_tpu.data.configs import (
        MeshConfig,
        ModelConfig,
        OptimizerConfig,
        SchedulerConfig,
        TokenizerConfig,
        TrainConfig,
        TRLConfig,
    )

    return TRLConfig(
        method=method,
        train=TrainConfig(
            seq_length=16, epochs=2, total_steps=3, batch_size=4, minibatch_size=2,
            checkpoint_interval=100, eval_interval=2,
            checkpoint_dir=str(tmp_path / "ckpts"),
            pipeline="PromptPipeline", trainer=trainer, tracker=None, seed=2,
        ),
        model=ModelConfig(
            model_path="gpt2", num_layers_unfrozen=-1,
            model_overrides=dict(
                vocab_size=len(ALPHABET) + 3, hidden_size=32, num_layers=2,
                num_heads=2, intermediate_size=64, max_position_embeddings=64,
            ),
        ),
        tokenizer=TokenizerConfig(tokenizer_path=f"char://{ALPHABET}"),
        optimizer=OptimizerConfig(name="adamw", kwargs=dict(lr=1e-3)),
        scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=100, eta_min=1e-3)),
        mesh=MeshConfig(
            data=2, fsdp=1, pipe=2, model=2, compute_dtype="float32",
            pipeline_microbatches=2,
        ),
    )


@pytest.mark.slow
def test_sft_trains_on_pipe_mesh(tmp_path):
    """End-to-end SFT on a data×pipe×model mesh (TPxPPxDP grid parity:
    nemo_sft_trainer + megatron_trainer, nemo_ilql_trainer.py:31-82)."""
    import trlx_tpu
    from trlx_tpu.methods.sft import SFTConfig

    config = _trl_config(tmp_path, "SFTTrainer", SFTConfig(gen_kwargs=dict(max_new_tokens=4)))
    trainer = trlx_tpu.train(
        samples=["ab ab abab", "cd cdcd", "efgh ef", "a b a b"] * 2,
        eval_prompts=["ab", "cd"],
        config=config,
    )
    assert trainer.iter_count >= 3
    assert trainer.model_config.pipeline_stages == 2
    assert "layers_scan" in trainer.params["transformer"]


@pytest.mark.slow
def test_ppo_trains_on_pipe_mesh(tmp_path):
    """End-to-end PPO (rollout generation through the stacked decode path + a
    pipelined train step with the full-copy reference model)."""
    import trlx_tpu
    from trlx_tpu.methods.ppo import PPOConfig

    config = _trl_config(
        tmp_path, "PPOTrainer",
        PPOConfig(
            num_rollouts=8, chunk_size=4, ppo_epochs=1, init_kl_coef=0.01, target=None,
            gen_kwargs=dict(max_new_tokens=6, do_sample=True, top_k=0, top_p=1.0),
        ),
    )
    trainer = trlx_tpu.train(
        reward_fn=lambda samples, **kw: [float(s.count("a")) for s in samples],
        prompts=["ab", "cd ef", "gh", "a b c"] * 2,
        eval_prompts=["ab", "cd"],
        config=config,
    )
    assert trainer.iter_count >= 3
    assert trainer.ref_params is not None  # full-copy reference under PP


def test_pipe_rejects_partial_freeze(tmp_path):
    from trlx_tpu.methods.sft import SFTConfig
    from trlx_tpu.utils.loading import get_trainer

    config = _trl_config(tmp_path, "SFTTrainer", SFTConfig())
    config.model.num_layers_unfrozen = 1
    with pytest.raises(ValueError, match="num_layers_unfrozen"):
        get_trainer("SFTTrainer")(config=config)


def test_config_validation():
    with pytest.raises(ValueError):
        TransformerLM(CFG.replace(pipeline_stages=3)).init(
            jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
        )
    with pytest.raises(ValueError):
        TransformerLM(CFG.replace(pipeline_stages=2, attention_impl="ring")).init(
            jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32)
        )
