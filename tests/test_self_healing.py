"""Self-healing loop tests (trlx_tpu/rollout/supervisor, trlx_tpu/resilience/
health+quarantine; docs/resilience.md "Self-healing").

Units cover the supervisor restart/budget machinery against fake producers,
the health guard's skip -> rollback -> halt ladder, the experience quarantine
screen, the new chaos sites, and the watchdog escalation hook (satellite S4).
The end-to-end block proves the acceptance criteria on tiny trainer runs over
the 8-device virtual CPU mesh: off-by-default parity (bitwise), the combined
chaos soak with every recovery visible in gauges/summary, rollback-to-last-
committed-checkpoint, and the fail-closed halt with a diagnostics bundle."""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

import trlx_tpu
from trlx_tpu.data.configs import (
    AsyncRolloutConfig,
    MeshConfig,
    ModelConfig,
    ObservabilityConfig,
    OptimizerConfig,
    SchedulerConfig,
    SelfHealingConfig,
    TokenizerConfig,
    TrainConfig,
    TRLConfig,
)
from trlx_tpu.data.ppo_types import PPORLElement
from trlx_tpu.methods.ppo import PPOConfig
from trlx_tpu.methods.sft import SFTConfig
from trlx_tpu.obs import watchdog
from trlx_tpu.obs.watchdog import StallWatchdog
from trlx_tpu.resilience.chaos import ChaosMonkey, chaos
from trlx_tpu.resilience.health import (
    TrainingHealthError,
    TrainingHealthGuard,
    chaos_poison_batch,
    write_diagnostics_bundle,
)
from trlx_tpu.resilience.quarantine import (
    ExperienceQuarantine,
    chaos_corrupt_elements,
    validate_element,
)
from trlx_tpu.rollout import (
    AsyncRolloutEngine,
    ExperienceQueue,
    ParameterPublisher,
    ProducerSupervisor,
    StalenessAccountant,
)
from trlx_tpu.rollout.supervisor import ProducerRestartBudgetExceeded
from trlx_tpu.utils.metrics import gauges

pytestmark = pytest.mark.self_healing

ALPHABET = "abcdefgh "

TINY_MODEL = dict(
    vocab_size=len(ALPHABET) + 3, hidden_size=32, num_layers=2, num_heads=2,
    intermediate_size=64, max_position_embeddings=64,
)


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    """Chaos disarmed, self-healing gauges cleared, no global watchdog — before
    AND after every test (all three are process-global)."""
    monkeypatch.delenv("TRLX_CHAOS", raising=False)
    chaos.configure(None)
    gauges.clear("resilience/")
    watchdog.install(None)
    yield
    chaos.configure(None)
    gauges.clear("resilience/")
    watchdog.install(None)


def make_element(i: int, version: int = 0) -> PPORLElement:
    return PPORLElement(
        query_tensor=np.array([i, i + 1], np.int32),
        response_tensor=np.array([i + 2], np.int32),
        logprobs=np.array([-0.5], np.float32),
        values=np.array([0.1], np.float32),
        rewards=np.array([1.0], np.float32),
        policy_version=version,
    )


# ------------------------------------------------------------------ config


def test_self_healing_config_defaults_and_roundtrip():
    from trlx_tpu.data.default_configs import default_ppo_config

    config = default_ppo_config()
    sh = config.train.self_healing
    assert sh.enabled is False  # off by default: parity with the seed behavior
    assert sh.max_producer_restarts == 5
    assert sh.rollback_after == 3 and sh.max_rollbacks == 2
    d = config.to_dict()
    assert d["train"]["self_healing"]["enabled"] is False
    assert TRLConfig.from_dict(d).to_dict() == d

    new = TRLConfig.update(
        d,
        {
            "train.self_healing.enabled": True,
            "train.self_healing.rollback_after": 5,
            "train.self_healing.wedge_timeout_s": None,
        },
    )
    assert new.train.self_healing.enabled is True
    assert new.train.self_healing.rollback_after == 5
    assert new.train.self_healing.wedge_timeout_s is None
    with pytest.raises(ValueError):
        TRLConfig.update(d, {"train.self_healing.bogus_knob": 1})


# ------------------------------------------------------------------- chaos


def test_new_chaos_sites_parse_and_budget():
    monkey = ChaosMonkey("producer-wedge:2,nan-loss:1,bad-element:3")
    assert monkey.armed
    assert monkey.should_fail("producer-wedge")
    assert monkey.should_fail("producer-wedge")
    assert not monkey.should_fail("producer-wedge")  # budget of 2, exactly
    assert monkey.should_fail("nan-loss")
    assert not monkey.should_fail("nan-loss")
    assert monkey.stats() == {"producer-wedge": 2, "nan-loss": 1}
    with pytest.raises(ValueError, match="unknown site"):
        monkey.configure("producer-hedge:1")


def test_chaos_poison_batch_nans_floats_only():
    batch = {
        "ids": np.arange(4, dtype=np.int32),
        "logprobs": np.ones(4, np.float32),
    }
    assert chaos_poison_batch(batch) is batch  # unarmed: free passthrough
    chaos.configure("nan-loss:1")
    out = chaos_poison_batch(batch)
    assert np.all(np.isnan(out["logprobs"]))
    assert np.array_equal(out["ids"], batch["ids"])  # ints untouched
    assert np.all(np.isfinite(batch["logprobs"]))  # original not mutated
    assert chaos_poison_batch(batch) is batch  # budget consumed


def test_chaos_corrupt_elements_first_only():
    elements = [make_element(i) for i in range(3)]
    assert chaos_corrupt_elements(elements) is elements  # unarmed
    chaos.configure("bad-element:1")
    assert chaos_corrupt_elements([]) == []  # empty list never burns budget
    out = chaos_corrupt_elements(elements)
    assert np.all(np.isnan(np.asarray(out[0].logprobs)))
    assert out[1] is elements[1] and out[2] is elements[2]
    assert chaos_corrupt_elements(elements) is elements  # budget consumed


# -------------------------------------------------------------- quarantine


def test_validate_element_reasons():
    assert validate_element(make_element(0)) is None
    empty = make_element(0).replace(response_tensor=np.array([], np.int32))
    assert validate_element(empty) == "empty response"
    for field in ("logprobs", "values", "rewards"):
        bad = make_element(0).replace(**{field: np.array([np.nan], np.float32)})
        assert validate_element(bad) == f"non-finite {field}"
    inf_bad = make_element(0).replace(rewards=np.array([np.inf], np.float32))
    assert validate_element(inf_bad) == "non-finite rewards"


def test_quarantine_filter_writes_sidecar_and_gauge(tmp_path):
    quar = ExperienceQuarantine(str(tmp_path / "quar"))
    good = [make_element(i) for i in range(3)]
    bad = make_element(9).replace(logprobs=np.array([np.nan], np.float32))
    clean = quar.filter(good + [bad], context="iter=7")
    assert clean == good
    assert quar.count == 1
    assert gauges.get("resilience/quarantined") == 1.0
    with open(quar.path) as f:
        records = [json.loads(line) for line in f]
    assert len(records) == 1
    assert records[0]["reason"] == "non-finite logprobs"
    assert records[0]["context"] == "iter=7"
    assert records[0]["response_tokens"] == [11]
    # appends accumulate across calls
    assert quar.filter([bad], context="iter=8") == []
    assert quar.count == 2
    with open(quar.path) as f:
        assert len(f.readlines()) == 2
    assert gauges.get("resilience/quarantined") == 2.0


# ------------------------------------------------------------- health guard


def _guard(tmp_path, **overrides) -> TrainingHealthGuard:
    config = SelfHealingConfig(enabled=True, **overrides)
    return TrainingHealthGuard(config, diagnostics_dir=str(tmp_path / "diag"))


def _healthy(grad_norm=1.0, kl=0.1):
    return {
        "loss": 0.5,
        "health/update_applied": 1.0,
        "health/grad_norm": grad_norm,
        "policy/sqrt_kl": kl,
    }


_SKIPPED = {
    "loss": float("nan"),
    "health/update_applied": 0.0,
    "health/grad_norm": float("nan"),
}


def test_guard_caps_warm_up_then_track_median(tmp_path):
    guard = _guard(tmp_path, min_window=3, anomaly_window=8,
                   grad_norm_spike_factor=10.0)
    assert guard.grad_norm_cap() == float("inf")
    for step, gn in enumerate([1.0, 1.2, 1.1]):
        assert guard.observe(_healthy(grad_norm=gn), step) == "ok"
    assert guard.grad_norm_cap() == pytest.approx(11.0)  # 10 x median(1.0,1.1,1.2)


def test_guard_kl_spike_is_anomalous_without_skip(tmp_path):
    guard = _guard(tmp_path, min_window=2, kl_spike_factor=2.0)
    for step in range(3):
        assert guard.observe(_healthy(kl=0.1), step) == "ok"
    assert guard.observe(_healthy(kl=10.0), 3) == "anomaly"
    assert guard.skipped_updates == 0  # the update WAS applied; host-level only
    assert guard.anomalies[-1]["reasons"][0].startswith("KL spike")
    # the spike must not have fed (and inflated) the baseline window
    assert guard._kl_cap() == pytest.approx(0.2)


def test_guard_zero_baseline_keeps_caps_disarmed(tmp_path):
    # A warm-started policy sits at its KL reference: the window fills with
    # sqrt_kl ~ 0 and a ratio cap armed off that median (10 x 0 = 0) would
    # flag every healthy step once the policy starts moving. A ~zero median
    # must read as "no usable baseline", not as a zero threshold.
    guard = _guard(tmp_path, min_window=2, kl_spike_factor=10.0,
                   grad_norm_spike_factor=10.0)
    for step in range(4):
        assert guard.observe(_healthy(grad_norm=0.0, kl=0.0), step) == "ok"
    assert guard._kl_cap() == float("inf")
    assert guard.grad_norm_cap() == float("inf")
    # the first real policy movement is healthy, and it seeds the baseline
    assert guard.observe(_healthy(grad_norm=0.5, kl=0.3), 4) == "ok"
    assert guard.anomalies == []


def test_guard_ladder_skip_rollback_halt(tmp_path):
    guard = _guard(tmp_path, min_window=2, rollback_after=2, max_rollbacks=1)
    for step in range(2):
        assert guard.observe(_healthy(), step) == "ok"

    assert guard.observe(_SKIPPED, 2) == "anomaly"
    assert guard.skipped_updates == 1
    assert gauges.get("resilience/skipped_updates") == 1.0
    assert guard.observe(_healthy(), 3) == "ok"  # healthy resets the streak
    assert guard.consecutive_anomalies == 0

    assert guard.observe(_SKIPPED, 4) == "anomaly"
    assert guard.observe(_SKIPPED, 5) == "rollback"
    assert guard.rollback_budget_left()
    guard.on_rollback(5, restored=True)
    assert guard.rollbacks == 1 and guard.consecutive_anomalies == 0
    assert gauges.get("resilience/rollbacks") == 1.0
    assert not guard.rollback_budget_left()

    with pytest.raises(TrainingHealthError, match="diagnostics bundle") as ei:
        guard.halt(6, "rollback budget exhausted")
    bundle = str(ei.value).rsplit("diagnostics bundle: ", 1)[1]
    assert os.path.isfile(os.path.join(bundle, "stacks.txt"))
    with open(os.path.join(bundle, "bundle.json")) as f:
        payload = json.load(f)
    assert payload["kind"] == "health-halt"
    assert payload["halt_step"] == 6 and payload["rollbacks"] == 1
    assert len(payload["anomalies"]) == 3

    report = guard.report()
    assert report["skipped_updates"] == 3
    assert report["rollbacks"] == 1
    assert report["anomalies"] == 3


def test_diagnostics_bundle_contents(tmp_path):
    chaos.configure("nan-loss:1")
    chaos.should_fail("nan-loss")
    gauges.set("resilience/skipped_updates", 7.0)
    bundle = write_diagnostics_bundle(
        str(tmp_path), kind="unit", anomalies=[{"step": 3}], extra={"note": "x"}
    )
    with open(os.path.join(bundle, "bundle.json")) as f:
        payload = json.load(f)
    assert payload["kind"] == "unit"
    assert payload["anomalies"] == [{"step": 3}]
    assert payload["note"] == "x"
    assert payload["chaos_injected"] == {"nan-loss": 1}
    assert payload["gauges"]["resilience/skipped_updates"] == 7.0
    with open(os.path.join(bundle, "stacks.txt")) as f:
        assert "MainThread" in f.read()


# ---------------------------------------------------- engine (S1/S2 + wedge)


def _build_engine(produce_fn, capacity=16, close_queue_on_death=True):
    pub = ParameterPublisher(copy_fn=dict)
    pub.publish({"step": 0})
    queue = ExperienceQueue(capacity)
    engine = AsyncRolloutEngine(
        produce_fn, pub, queue, StalenessAccountant(max_staleness=8),
        close_queue_on_death=close_queue_on_death,
    )
    return engine, queue


def test_collect_raises_on_dead_or_unstarted_producer():
    """S1: collect on an engine whose thread is gone (never started, or killed
    without running its except clause) must raise, not poll forever."""
    engine, _ = _build_engine(lambda params, version: [make_element(0)])
    with pytest.raises(RuntimeError, match="not running"):
        engine.collect(1, learner_version=0, timeout=5.0)
    # a thread that died leaving no error behind (e.g. killed mid-flight)
    dead = threading.Thread(target=lambda: None)
    dead.start()
    dead.join()
    engine._thread = dead
    with pytest.raises(RuntimeError, match="no error recorded"):
        engine.collect(1, learner_version=0, timeout=5.0)


def test_engine_shutdown_put_drop_balances_ledger():
    """S2: elements abandoned mid-put during shutdown land in dropped_shutdown
    so produced == consumed + dropped_stale + leftover + dropped_shutdown."""
    counter = {"n": 0}

    def produce(params, version):
        counter["n"] += 1
        return [make_element(counter["n"])]

    engine, _ = _build_engine(produce, capacity=1)
    engine.start()
    deadline = time.monotonic() + 10.0
    while engine.summary()["produced"] < 2:
        assert time.monotonic() < deadline, "producer never reached a blocked put"
        time.sleep(0.01)
    stats = engine.stop(timeout=10.0)
    assert stats["dropped_shutdown"] >= 1
    assert stats["produced"] == (
        stats["consumed"] + stats["dropped_stale"]
        + stats["leftover"] + stats["dropped_shutdown"]
    )


def test_producer_wedge_site_parks_silently_until_abandoned():
    chaos.configure("producer-wedge:1")
    engine, queue = _build_engine(
        lambda params, version: [make_element(0)], close_queue_on_death=False
    )
    engine.start()
    with pytest.raises(TimeoutError):
        engine.collect(1, learner_version=0, timeout=0.3)
    assert engine.running  # alive, silent — the failure mode no exception models
    engine.abandon()
    engine._thread.join(5.0)
    assert not engine.running
    assert not queue.closed  # the shared queue stays open for a successor


# -------------------------------------------------------------- supervisor


def _make_supervised(produce_for, tmp_path, **kwargs):
    """Supervisor over engine generations sharing one queue/publisher/accountant;
    ``produce_for(generation)`` returns the produce_fn for each generation."""
    pub = ParameterPublisher(copy_fn=dict)
    pub.publish({"step": 0})
    queue = ExperienceQueue(16)
    accountant = StalenessAccountant(max_staleness=8)
    generation = {"n": 0}

    def factory():
        fn = produce_for(generation["n"])
        generation["n"] += 1
        return AsyncRolloutEngine(
            fn, pub, queue, accountant, close_queue_on_death=False
        )

    kwargs.setdefault("backoff_base_s", 0.01)
    kwargs.setdefault("backoff_max_s", 0.05)
    kwargs.setdefault("diagnostics_dir", str(tmp_path / "diag"))
    return ProducerSupervisor(factory, **kwargs), queue


def test_supervisor_restarts_crashed_producer(tmp_path):
    def produce_for(generation):
        if generation == 0:
            def crash(params, version):
                raise RuntimeError("synthetic producer crash")
            return crash
        return lambda params, version: [make_element(generation)]

    sup, queue = _make_supervised(produce_for, tmp_path, wedge_timeout_s=None)
    sup.start()
    try:
        got = sup.collect(2, learner_version=0, timeout=30.0)
        assert len(got) == 2
        assert sup.restarts == 1
        assert gauges.get("resilience/restarts") == 1.0
        assert "producer died" in sup.restart_history[0]["reason"]
    finally:
        stats = sup.stop(timeout=10.0)
    assert stats["producer_restarts"] == 1
    assert queue.closed  # stop() still closes the shared queue at the end


def test_supervisor_restart_budget_fails_closed(tmp_path):
    def produce_for(generation):
        def crash(params, version):
            raise RuntimeError("permanent failure")
        return crash

    sup, _ = _make_supervised(
        produce_for, tmp_path, max_restarts=2, wedge_timeout_s=None
    )
    sup.start()
    try:
        with pytest.raises(ProducerRestartBudgetExceeded, match="diagnostics bundle") as ei:
            sup.collect(1, learner_version=0, timeout=30.0)
    finally:
        sup.stop(timeout=10.0)
    assert sup.restarts == 3  # 2 within budget + the one that tripped it
    bundle = str(ei.value).rsplit("diagnostics bundle: ", 1)[1]
    with open(os.path.join(bundle, "bundle.json")) as f:
        payload = json.load(f)
    assert payload["kind"] == "producer-restart-budget"
    assert payload["max_restarts"] == 2
    assert len(payload["restart_history"]) == 2
    assert os.path.isfile(os.path.join(bundle, "stacks.txt"))


def test_supervisor_wedge_timeout_fallback(tmp_path):
    """A live-but-silent producer is restarted by the collect-side fallback
    even with no watchdog installed."""
    release = threading.Event()

    def produce_for(generation):
        if generation == 0:
            def wedged(params, version):
                release.wait(30.0)
                return []
            return wedged
        return lambda params, version: [make_element(generation)]

    sup, _ = _make_supervised(produce_for, tmp_path, wedge_timeout_s=0.3)
    sup.start()
    try:
        got = sup.collect(2, learner_version=0, timeout=30.0)
        assert len(got) == 2
        assert sup.restarts == 1
        assert "wedge timeout" in sup.restart_history[0]["reason"]
    finally:
        release.set()
        sup.stop(timeout=10.0)


def test_watchdog_escalation_fires_once_per_episode():
    """S4: the escalation hook — per-heartbeat callback, once per stall
    episode, re-armed by a beat, never lethal to the watchdog, unregistrable."""
    calls = []
    wd = StallWatchdog(timeout_s=0.05, poll_s=100.0)  # poll manually, no thread
    wd.escalate("prod", lambda name, age: calls.append((name, age)))

    wd.beat("prod")
    late = time.monotonic() + 1.0
    wd.check(now=late)
    assert len(calls) == 1
    assert calls[0][0] == "prod" and calls[0][1] > 0.05
    wd.check(now=late)  # same episode: no second fire
    assert len(calls) == 1
    wd.beat("prod")  # progress re-arms the episode
    wd.check(now=time.monotonic() + 1.0)
    assert len(calls) == 2

    # a raising escalation must not kill the check (or the watchdog thread)
    wd.escalate("prod", lambda name, age: 1 / 0)
    wd.beat("prod")
    wd.check(now=time.monotonic() + 1.0)

    wd.escalate("prod", None)  # unregister
    wd.beat("prod")
    wd.check(now=time.monotonic() + 1.0)
    assert len(calls) == 2


def test_supervisor_restarts_on_watchdog_escalation(tmp_path):
    """S4 end-to-end at the unit level: stale producer heartbeat -> watchdog
    escalation -> supervisor restart, with the wedge fallback disabled so the
    escalation path alone must do the job."""
    chaos.configure("producer-wedge:1")  # generation 0 parks, beats nothing
    impl = StallWatchdog(timeout_s=0.2, poll_s=0.05)
    watchdog.install(impl)
    impl.start()

    def produce_for(generation):
        return lambda params, version: [make_element(generation)]

    sup, _ = _make_supervised(produce_for, tmp_path, wedge_timeout_s=None)
    sup.start()
    try:
        got = sup.collect(2, learner_version=0, timeout=30.0)
        assert len(got) == 2
        assert sup.restarts == 1
        assert "watchdog escalation" in sup.restart_history[0]["reason"]
    finally:
        sup.stop(timeout=10.0)
        watchdog.install(None)


# ------------------------------------------------------------------- e2e


def _sft_config(tmp_path, total_steps=2, **train_overrides):
    train = dict(
        seq_length=16, epochs=4, total_steps=total_steps, batch_size=4,
        minibatch_size=2, checkpoint_interval=2, eval_interval=100,
        checkpoint_dir=str(tmp_path / "ckpts"),
        pipeline="PromptPipeline", trainer="SFTTrainer", tracker=None, seed=2,
    )
    train.update(train_overrides)
    return TRLConfig(
        method=SFTConfig(gen_kwargs=dict(max_new_tokens=4)),
        train=TrainConfig(**train),
        model=ModelConfig(model_path="gpt2", num_layers_unfrozen=-1,
                          model_overrides=dict(TINY_MODEL)),
        tokenizer=TokenizerConfig(tokenizer_path=f"char://{ALPHABET}"),
        optimizer=OptimizerConfig(name="adamw", kwargs=dict(lr=1e-3)),
        scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=100, eta_min=1e-3)),
        mesh=MeshConfig(data=2, fsdp=2, model=2, compute_dtype="float32"),
    )


def _ppo_config(tmp_path, total_steps=4, self_healing=None, observability=None,
                async_rollouts=None, **train_overrides):
    train = dict(
        seq_length=16, epochs=30, total_steps=total_steps, batch_size=4,
        minibatch_size=2, checkpoint_interval=100, eval_interval=100,
        checkpoint_dir=str(tmp_path / "ckpts"),
        pipeline="PromptPipeline", trainer="PPOTrainer", tracker=None, seed=2,
    )
    train.update(train_overrides)
    cfg = TRLConfig(
        method=PPOConfig(
            num_rollouts=4, chunk_size=4, ppo_epochs=1, init_kl_coef=0.01,
            target=None,
            gen_kwargs=dict(max_new_tokens=4, do_sample=True, top_k=0, top_p=1.0),
        ),
        train=TrainConfig(**train),
        model=ModelConfig(model_path="gpt2", num_layers_unfrozen=-1,
                          model_overrides=dict(TINY_MODEL)),
        tokenizer=TokenizerConfig(tokenizer_path=f"char://{ALPHABET}"),
        optimizer=OptimizerConfig(name="adamw", kwargs=dict(lr=1e-3)),
        scheduler=SchedulerConfig(name="cosine_annealing", kwargs=dict(T_max=100, eta_min=1e-3)),
        mesh=MeshConfig(data=2, fsdp=2, model=2, compute_dtype="float32"),
    )
    if self_healing is not None:
        cfg.train.self_healing = self_healing
    if observability is not None:
        cfg.train.observability = observability
    if async_rollouts is not None:
        cfg.train.async_rollouts = async_rollouts
    return cfg


PROMPTS = ["ab", "cd", "ef", "gh"] * 2

SFT_SAMPLES = [["ab", "cd"], ["ef", "gh"], ["a b", "c d"], ["e f", "g h"]]


def _reward(samples, **kwargs):
    return [float(s.count("a")) for s in samples]


def test_disabled_self_healing_is_bitwise_inert(tmp_path):
    """Acceptance: with self_healing present but disabled (even with every
    other knob changed), final params and checkpoint state are byte-identical
    to a run that never mentions the subsystem."""
    import jax

    config_a = _sft_config(tmp_path / "a")
    trainer_a = trlx_tpu.train(samples=SFT_SAMPLES, eval_prompts=["ab"], config=config_a)

    config_b = _sft_config(tmp_path / "b")
    config_b.train.self_healing = SelfHealingConfig(
        enabled=False, max_producer_restarts=1, rollback_after=1,
        max_rollbacks=0, min_window=1, grad_norm_spike_factor=1.0,
        kl_spike_factor=1.0, wedge_timeout_s=0.1,
    )
    trainer_b = trlx_tpu.train(samples=SFT_SAMPLES, eval_prompts=["ab"], config=config_b)

    assert trainer_b.health is None
    assert trainer_b.self_healing_summary is None
    assert gauges.snapshot("resilience/") == {}  # the layer never even woke up

    leaves_a = jax.tree.leaves(jax.device_get(trainer_a.params))
    leaves_b = jax.tree.leaves(jax.device_get(trainer_b.params))
    assert len(leaves_a) == len(leaves_b)
    for a, b in zip(leaves_a, leaves_b):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    with open(os.path.join(config_a.train.checkpoint_dir, "checkpoint_2", "state.json"), "rb") as f:
        state_a = f.read()
    with open(os.path.join(config_b.train.checkpoint_dir, "checkpoint_2", "state.json"), "rb") as f:
        state_b = f.read()
    assert state_a == state_b


def test_chaos_soak_end_to_end(tmp_path, monkeypatch):
    """The acceptance soak: producer crashes (rollout-producer + reward faults),
    poisoned train batches (nan-loss), corrupted scored elements (bad-element)
    — all in one tiny async run that must complete, with every recovery
    visible in the gauges and the trainer's self_healing_summary."""
    monkeypatch.setenv("TRLX_CHAOS", "rollout-producer:2,nan-loss:2,bad-element:3,reward:1")
    config = _ppo_config(
        tmp_path,
        total_steps=4,
        self_healing=SelfHealingConfig(
            enabled=True, max_producer_restarts=8,
            restart_backoff_base_s=0.01, restart_backoff_max_s=0.05,
            wedge_timeout_s=None,
        ),
        async_rollouts=AsyncRolloutConfig(
            enabled=True, max_staleness=4, queue_capacity=32
        ),
    )
    trainer = trlx_tpu.train(
        reward_fn=_reward, prompts=PROMPTS, eval_prompts=["ab"], config=config
    )
    assert trainer.iter_count == 4  # the run survived everything and finished

    summary = trainer.self_healing_summary
    # 2 rollout-producer faults + 1 reward fault, each killing one generation
    assert summary["producer_restarts"] == 3
    assert summary["skipped_updates"] == 2  # both nan-loss batches skipped
    assert summary["anomalies"] == 2
    assert summary["rollbacks"] == 0  # 2 consecutive < rollback_after=3
    assert summary["quarantined"] == 3  # one element per bad-element chunk
    assert gauges.get("resilience/restarts") == 3.0
    assert gauges.get("resilience/quarantined") == 3.0

    quarantine_path = os.path.join(
        config.train.checkpoint_dir, "quarantine", "quarantine.jsonl"
    )
    assert os.path.isfile(quarantine_path)
    with open(quarantine_path) as f:
        records = [json.loads(line) for line in f]
    assert len(records) == 3
    assert all(r["reason"] == "non-finite logprobs" for r in records)
    # every armed budget was actually spent — the soak tested what it claims
    assert chaos.stats() == {
        "rollout-producer": 2, "nan-loss": 2, "bad-element": 3, "reward": 1,
    }


def test_wedge_escalation_end_to_end(tmp_path, monkeypatch):
    """A watchdog-detected wedge (no exception anywhere) is healed by the
    supervisor inside a real training run: obs watchdog -> escalation hook ->
    restart -> run completes."""
    monkeypatch.setenv("TRLX_CHAOS", "producer-wedge:1")
    config = _ppo_config(
        tmp_path,
        total_steps=2,
        self_healing=SelfHealingConfig(
            # a 2s watchdog also pages on legitimate pauses (evals holding the
            # producer's pause lock, first-step compiles) — harmless extra
            # restarts by design, so give the budget headroom
            enabled=True, max_producer_restarts=8,
            restart_backoff_base_s=0.01, restart_backoff_max_s=0.05,
            wedge_timeout_s=None,  # escalation path alone must recover
        ),
        observability=ObservabilityConfig(enabled=True, watchdog_timeout_s=2.0),
        async_rollouts=AsyncRolloutConfig(enabled=True, max_staleness=4),
    )
    # the library root logger doesn't propagate (no caplog): attach a handler
    import logging as _logging

    records = []

    class _Capture(_logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    lib_logger = _logging.getLogger("trlx_tpu")
    handler = _Capture(level=_logging.WARNING)
    lib_logger.addHandler(handler)
    try:
        trainer = trlx_tpu.train(
            reward_fn=_reward, prompts=PROMPTS, eval_prompts=["ab"], config=config
        )
    finally:
        lib_logger.removeHandler(handler)
    assert trainer.iter_count == 2
    assert trainer.self_healing_summary["producer_restarts"] >= 1
    # the wedge was healed through the escalation hook, not the collect fallback
    assert any("watchdog escalation" in m for m in records)
    assert any("chaos: rollout producer wedged" in m for m in records)


def test_rollback_restores_last_committed_checkpoint(tmp_path, monkeypatch):
    """Consecutive anomalies past rollback_after restore the last committed
    checkpoint through the exact-resume machinery and the run still reaches
    total_steps once the fault clears."""
    monkeypatch.setenv("TRLX_CHAOS", "nan-loss:4")
    config = _ppo_config(
        tmp_path,
        total_steps=4,
        checkpoint_interval=1,
        self_healing=SelfHealingConfig(
            enabled=True, rollback_after=2, max_rollbacks=3
        ),
    )
    trainer = trlx_tpu.train(
        reward_fn=_reward, prompts=PROMPTS, eval_prompts=["ab"], config=config
    )
    assert trainer.iter_count == 4
    summary = trainer.self_healing_summary
    # steps 1,2 poisoned -> rollback #1 to ckpt_1; retried step 2 and step 3
    # poisoned -> rollback #2 to ckpt_2; budget of 4 spent, run finishes clean
    assert summary["rollbacks"] == 2
    assert summary["skipped_updates"] == 4
    assert summary["anomalies"] == 4


def test_halt_fails_closed_with_diagnostics_bundle(tmp_path, monkeypatch):
    """An exhausted rollback budget halts the run with TrainingHealthError
    carrying a diagnostics-bundle path — never an infinite recovery loop.
    With no committed checkpoint to restore, the budget is still consumed."""
    monkeypatch.setenv("TRLX_CHAOS", "nan-loss:12")
    config = _ppo_config(
        tmp_path,
        total_steps=4,  # checkpoint_interval=100: nothing ever committed
        self_healing=SelfHealingConfig(
            enabled=True, rollback_after=1, max_rollbacks=1
        ),
    )
    with pytest.raises(TrainingHealthError, match="diagnostics bundle") as ei:
        trlx_tpu.train(
            reward_fn=_reward, prompts=PROMPTS, eval_prompts=["ab"], config=config
        )
    bundle = str(ei.value).rsplit("diagnostics bundle: ", 1)[1]
    assert os.path.isdir(bundle)
    assert bundle.startswith(os.path.join(config.train.checkpoint_dir, "diagnostics"))
    with open(os.path.join(bundle, "bundle.json")) as f:
        payload = json.load(f)
    assert payload["kind"] == "health-halt"
    assert payload["anomalies"]  # the history that led here is in the bundle
    assert payload["rollbacks"] == 1
    assert payload["chaos_injected"]["nan-loss"] >= 2
    assert os.path.isfile(os.path.join(bundle, "stacks.txt"))
