"""Serving fleet tests (docs/serving.md "Fleet serving"): uid-block seating
and per-replica gauge namespacing, prefix-affinity routing (warm prefix beats
least-loaded; tenant stickiness survives a load gap), autoscaler hysteresis
(oscillating load never flaps; exactly one action per sustained breach, then
a cooldown), replica-kill re-route with exactly-once terminal accounting,
replica-tagged typed client errors, the fleet chaos soak (N=3 replicas,
4 tenants / 2 SLO classes, >=1 kill + >=1 autoscale drain mid-run, per-class
p99 ordering fleet-wide, zero quota violations, affinity beats random), and
the N=1 parity contract: a fleet of one replica is uid- and token-identical
to the bare engine."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from trlx_tpu.fleet import (
    DRAINING,
    UID_STRIDE,
    FleetAutoscaler,
    FleetRouter,
    FleetScenarioReport,
    run_fleet_scenario,
)
from trlx_tpu.models.presets import PRESETS
from trlx_tpu.models.transformer import TransformerLM
from trlx_tpu.resilience.chaos import chaos
from trlx_tpu.serving import (
    EngineDrainingError,
    GenerationClient,
    RequestShedError,
    ServingEngine,
    ServingResiliencePolicy,
    ServingRestartBudgetExceeded,
    TenantRegistry,
    TenantTraffic,
)
from trlx_tpu.serving.scheduler import (
    FINISH_CANCELLED,
    FINISH_DEADLINE,
    FINISH_EOS,
    FINISH_LENGTH,
    FINISH_SHED,
    FINISH_STOP,
)
from trlx_tpu.utils.metrics import gauges

pytestmark = [pytest.mark.serving, pytest.mark.serving_fleet]

TINY = dict(
    vocab_size=37, hidden_size=16, num_layers=2, num_heads=2,
    max_position_embeddings=64, compute_dtype=jnp.float32,
)

TERMINAL_REASONS = {
    FINISH_EOS, FINISH_STOP, FINISH_LENGTH, FINISH_CANCELLED,
    FINISH_DEADLINE, FINISH_SHED,
}


@pytest.fixture(autouse=True)
def _disarm_chaos():
    yield
    chaos.configure(None)


@pytest.fixture(scope="module")
def tiny_engine_parts():
    config = PRESETS["gpt2"].replace(**TINY)
    model = TransformerLM(config)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), jnp.ones((1, 4), jnp.int32)
    )["params"]
    return model, params, config


def _make_engine(parts, *, num_slots=3, num_blocks=0, policy=None, max_seq_len=32,
                 seed=0, prefix_caching=False, tenants=None, replica_id=None):
    model, params, _ = parts
    return ServingEngine(
        model, params, num_slots=num_slots, max_seq_len=max_seq_len, block_size=4,
        num_blocks=num_blocks, eos_token_id=None, pad_token_id=0,
        gen_kwargs=dict(do_sample=False), seed=seed, policy=policy,
        prefix_caching=prefix_caching, tenants=tenants, replica_id=replica_id,
    )


def _make_fleet(parts, num_replicas, tmp_path, *, factory=None, **kw):
    """FleetRouter with test-friendly supervisor knobs (no watchdog thread,
    fast backoff, diagnostics into tmp)."""
    if factory is None:
        def factory(seat):
            return _make_engine(parts)
    kw.setdefault("wedge_timeout_s", None)
    kw.setdefault("backoff_base_s", 0.01)
    kw.setdefault("diagnostics_dir", str(tmp_path))
    return FleetRouter(factory, num_replicas, **kw)


def _assert_greedy_equivalent(parts, prompt, gen_a, gen_b, tol=1e-3):
    """Token-for-token greedy parity modulo genuine argmax float ties (same
    contract as the resilience parity tests: a real routing/replay bug decodes
    from the wrong context and diverges with a large logit gap)."""
    model, params, _ = parts
    assert len(gen_a) == len(gen_b)
    for i, (ta, tb) in enumerate(zip(gen_a, gen_b)):
        if ta == tb:
            continue
        ctx = list(prompt) + list(gen_a[:i])
        ids = jnp.asarray([ctx], jnp.int32)
        mask = jnp.ones_like(ids)
        positions = jnp.arange(len(ctx), dtype=jnp.int32)[None]
        cache = {**model.init_cache(1, len(ctx)), "index": 0}
        logits, _, _, _ = model.apply({"params": params}, ids, mask, positions, cache)
        last = np.asarray(logits[0, -1], np.float64)
        gap = abs(last[ta] - last[tb])
        assert gap < tol, (
            f"greedy runs diverged at token {i} ({ta} vs {tb}) with logit gap "
            f"{gap:.3e} — not a float tie: the runs decoded different contexts"
        )
        return


# ------------------------------------------------------- seating/namespacing


def test_uid_blocks_and_gauge_namespaces_per_seat(tiny_engine_parts, tmp_path):
    """Each seat's scheduler counts uids from seat * UID_STRIDE and exports
    gauges under serving/replica/<seat>/; close() clears every namespace."""
    router = _make_fleet(tiny_engine_parts, 2, tmp_path)
    try:
        seats = [h.seat for h in router._active_handles()]
        assert seats == [0, 1]
        for h in router._active_handles():
            eng = h.supervisor.engine
            assert eng.gauge_prefix == f"serving/replica/{h.seat}/"
            assert eng.replica_id == h.seat
            assert eng.scheduler.uid_hwm == h.seat * UID_STRIDE
        u0 = router.submit([1, 2, 3], 3)           # seat 0 (tie-break)
        u1 = router.submit([4, 5, 6], 3)           # seat 1 (least loaded)
        assert 0 <= u0 < UID_STRIDE <= u1 < 2 * UID_STRIDE
        assert router.replica_of(u0) == 0 and router.replica_of(u1) == 1
        done = router.run([u0, u1])
        assert set(done) == {u0, u1}
        router.export_gauges()
        assert gauges.snapshot(prefix="serving/replica/0/")
        assert gauges.snapshot(prefix="serving/replica/1/")
        fleet = gauges.snapshot(prefix="fleet/")
        assert fleet["fleet/replicas"] == 2.0
        assert fleet["fleet/routed"] == 2.0
        assert fleet["fleet/finished"] == 2.0
    finally:
        router.close()
    assert gauges.snapshot(prefix="serving/") == {}
    assert gauges.snapshot(prefix="fleet/") == {}


def test_bare_engine_keeps_default_gauge_prefix(tiny_engine_parts):
    """Outside a fleet nothing moves: the engine's gauges stay at serving/*."""
    eng = _make_engine(tiny_engine_parts)
    assert eng.gauge_prefix == "serving/" and eng.replica_id is None
    uid = eng.submit([1, 2], 3)
    eng.run([uid])
    eng.export_gauges()
    snap = gauges.snapshot(prefix="serving/")
    assert snap and not any(k.startswith("serving/replica/") for k in snap)
    assert "serving/live_slots" in snap
    eng.close()
    assert gauges.snapshot(prefix="serving/") == {}


# ----------------------------------------------------------------- affinity


def test_fleet_affinity_warm_prefix_beats_least_loaded(tiny_engine_parts, tmp_path):
    """A replica holding the prompt's warm prefix blocks wins the route even
    against a strictly less-loaded replica. (This is the deterministic half
    of the ci.sh seeded gate: under TRLX_FLEET_SEED_REGRESSION=blind_router
    the router degenerates to least-loaded and this test must FAIL.)"""
    def factory(seat):
        return _make_engine(tiny_engine_parts, num_slots=2, prefix_caching=True)

    router = _make_fleet(tiny_engine_parts, 2, tmp_path, factory=factory)
    try:
        warm_prompt = [1, 2, 3, 4, 5, 6, 7, 8]  # 2 full blocks at block_size 4
        u0 = router.submit(warm_prompt, 3, tenant_id="a")
        assert router.replica_of(u0) == 0
        router.run([u0])
        seat0 = router._active_handles()[0].supervisor.engine
        assert seat0.allocator.cached_prefix_blocks(warm_prompt) >= 2
        # distinct tenant + cold prompt: lands on seat 0 by tie-break and
        # loads it (1 pending / 2 slots)
        filler = router.submit([9, 10], 3, tenant_id="b")
        assert router.replica_of(filler) == 0
        # third tenant re-asks the warm prompt: seat 1 is strictly less
        # loaded, but seat 0's 2 warm blocks outweigh the load gap
        probe = router.submit(warm_prompt, 3, tenant_id="c")
        assert router.replica_of(probe) == 0, (
            "warm-prefix affinity lost to least-loaded routing"
        )
        router.run([filler, probe])
        s = router.ledger.summary()
        assert s["fleet_affinity_hit_rate"] == pytest.approx(1 / 3)
    finally:
        router.close()


def test_fleet_affinity_tenant_stickiness(tiny_engine_parts, tmp_path):
    """With no warm prefix anywhere, a tenant's recent traffic pulls its next
    request onto the same replica even across a load gap; an unseen tenant
    still falls back to least-loaded."""
    def factory(seat):
        return _make_engine(tiny_engine_parts, num_slots=2)

    router = _make_fleet(
        tiny_engine_parts, 2, tmp_path, factory=factory,
        tenant_weight=2.0, load_weight=0.5,
    )
    try:
        u0 = router.submit([1, 2, 3], 3, tenant_id="t")
        assert router.replica_of(u0) == 0
        # seat 0 now carries load; stickiness (2.0) still beats the load
        # penalty (0.5 * 0.5) for the same tenant...
        u1 = router.submit([7, 8, 9], 3, tenant_id="t")
        assert router.replica_of(u1) == 0
        # ...while a tenant with no history routes by load alone
        u2 = router.submit([4, 5, 6], 3, tenant_id="u")
        assert router.replica_of(u2) == 1
        router.run([u0, u1, u2])
        assert router.ledger.summary()["fleet_sticky_hit_rate"] == pytest.approx(1 / 3)
    finally:
        router.close()


def test_fleet_affinity_hit_rate_beats_random(tiny_engine_parts, tmp_path):
    """Shared-prefix traffic through the scenario harness: the router's
    warm-prefix hit rate must beat what uniform-random replica choice would
    have scored. (The statistical half of the ci.sh blind_router gate.)"""
    model, params, _ = tiny_engine_parts
    reg = TenantRegistry()
    reg.register("alpha", slo_class=0)
    reg.register("beta", slo_class=0)

    def factory(seat):
        return ServingEngine(
            model, params, num_slots=3, max_seq_len=32, block_size=4,
            eos_token_id=None, pad_token_id=0, gen_kwargs=dict(do_sample=False),
            seed=seat, prefix_caching=True, tenants=reg,
        )

    traffic = [
        TenantTraffic("alpha", num_requests=8, arrivals_per_round=0.5,
                      prompt_len=(2, 4), max_new=(3, 5), vocab=37,
                      shared_prefix=8),
        TenantTraffic("beta", num_requests=8, arrivals_per_round=0.5,
                      prompt_len=(2, 4), max_new=(3, 5), vocab=37,
                      shared_prefix=8),
    ]
    report = run_fleet_scenario(
        factory, reg, traffic, num_replicas=3, autoscale=False,
        dt_s=0.05, max_rounds=300, diagnostics_dir=str(tmp_path),
    )
    assert report.replica_kills == 0 and report.restarts == 0
    assert report.affinity_hit_rate > report.random_hit_rate, (
        f"affinity routing ({report.affinity_hit_rate:.3f}) did not beat the "
        f"uniform-random baseline ({report.random_hit_rate:.3f})"
    )
    # each tenant's 8-token shared prefix pins it to one replica after its
    # first completion: the bulk of routes must be warm
    assert report.affinity_hit_rate > 0.5


def test_fleet_seed_regression_env_validated(monkeypatch, tiny_engine_parts, tmp_path):
    monkeypatch.setenv("TRLX_FLEET_SEED_REGRESSION", "bogus")
    with pytest.raises(ValueError, match="TRLX_FLEET_SEED_REGRESSION"):
        _make_fleet(tiny_engine_parts, 1, tmp_path)


# --------------------------------------------------------------- autoscaler


def test_autoscaler_hysteresis_no_flap(tiny_engine_parts, tmp_path):
    """Oscillating load (2 hot rounds, then idle) never scales; a sustained
    breach scales exactly once, then the cooldown blocks immediate reversal;
    sustained idleness drains the newest replica back down."""
    def factory(seat):
        return _make_engine(tiny_engine_parts, num_slots=2)

    router = _make_fleet(tiny_engine_parts, 1, tmp_path, factory=factory)
    scaler = FleetAutoscaler(
        router, min_replicas=1, max_replicas=2,
        scale_up_pending_per_slot=1.0, scale_down_occupancy=0.5,
        breach_rounds=3, cooldown_rounds=4,
    )

    def observe():
        router.export_gauges()
        scaler.observe()

    try:
        for _ in range(3):  # oscillate: 2 hot observes, then drain to idle
            uids = [router.submit([i + 1, i + 2], 2) for i in range(6)]
            observe()
            observe()
            router.run(uids)  # pending -> 0 before the third breach
            observe()
        assert scaler.events == [] and router.num_replicas == 1

        # sustained breach: exactly one scale-up at breach_rounds
        uids = [router.submit([i + 1, i + 2], 2) for i in range(6)]
        observe()
        observe()
        assert router.num_replicas == 1
        observe()
        assert [e[1] for e in scaler.events] == ["up"]
        assert router.num_replicas == 2
        # cooldown: still-breaching observes take no further action
        observe()
        observe()
        assert [e[1] for e in scaler.events] == ["up"]
        router.run(uids)

        # drain the cooldown, then sustained idleness drains one replica
        for _ in range(8):
            observe()
        assert [e[1] for e in scaler.events] == ["up", "drain"]
        draining = [h for h in router._live_handles() if h.state == DRAINING]
        assert [h.seat for h in draining] == [1]  # newest seat drains first
        router.step()  # idle drain retires immediately
        assert router.num_replicas == 1
        assert [h.seat for h in router._active_handles()] == [0]
    finally:
        router.close()


def test_autoscaler_validates_bounds(tiny_engine_parts, tmp_path):
    router = _make_fleet(tiny_engine_parts, 1, tmp_path)
    try:
        with pytest.raises(ValueError, match="min_replicas"):
            FleetAutoscaler(router, min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="breach_rounds"):
            FleetAutoscaler(router, breach_rounds=0)
    finally:
        router.close()


# ------------------------------------------------------------ kill/re-route


def test_replica_kill_reroutes_and_finishes_exactly_once(tiny_engine_parts, tmp_path):
    """Chaos kills the busiest replica mid-flight: its live + pending
    requests are adopted by the survivor, keep their uids, and every uid
    reaches exactly one terminal state."""
    def factory(seat):
        return _make_engine(tiny_engine_parts, num_slots=2)

    router = _make_fleet(tiny_engine_parts, 2, tmp_path, factory=factory)
    try:
        uids = [router.submit([i + 1, i + 2, i + 3], 4) for i in range(6)]
        assert {router.replica_of(u) for u in uids} == {0, 1}  # both seats used
        router.step()  # decode at least one token so replay carries state
        chaos.configure("fleet-replica-kill:1")
        done = router.run(uids)
        assert set(done) == set(uids)
        assert all(done[u].finish_reason == FINISH_LENGTH for u in uids)
        s = router.ledger.summary()
        assert s["fleet_replica_kills"] == 1 and s["fleet_reroutes"] >= 1
        survivor = router._active_handles()
        assert len(survivor) == 1
        # ownership followed the requests onto the survivor
        assert all(router.replica_of(u) == survivor[0].seat for u in uids)
        assert chaos.stats().get("fleet-replica-kill") == 1
    finally:
        router.close()


def test_fleet_fails_closed_with_no_active_replica(tiny_engine_parts, tmp_path):
    router = _make_fleet(tiny_engine_parts, 1, tmp_path)
    router.close()
    with pytest.raises(ServingRestartBudgetExceeded, match="no active replica"):
        router.submit([1, 2], 2)


# ----------------------------------------------------- replica-tagged errors


def test_typed_errors_carry_replica_id(tiny_engine_parts, tmp_path):
    """Engine-raised and client-raised typed errors both say WHICH replica
    failed the request — fleet callers distinguish engine-fatal from
    request-fatal without string parsing."""
    eng = _make_engine(tiny_engine_parts, replica_id=7)
    eng.begin_drain()
    with pytest.raises(EngineDrainingError) as ei:
        eng.submit([1, 2], 2)
    assert ei.value.replica_id == 7
    eng.close()

    def factory(seat):
        return _make_engine(
            tiny_engine_parts, num_slots=2, policy=ServingResiliencePolicy()
        )

    router = _make_fleet(tiny_engine_parts, 2, tmp_path, factory=factory)
    try:
        client = GenerationClient(router)
        uid = client.submit([1, 2, 3], 4)
        seat = router.replica_of(uid)
        router.begin_drain(shed_pending=True)
        with pytest.raises(RequestShedError) as se:
            list(client.stream(uid))
        assert se.value.replica_id == seat
        assert se.value.tenant_id is not None
    finally:
        router.close()


# --------------------------------------------------------------- N=1 parity


def test_fleet_of_one_matches_bare_engine(tiny_engine_parts, tmp_path):
    """A one-replica fleet is the bare engine: same uid sequence (seat 0
    counts from 0), same greedy tokens, same finish reasons."""
    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 37, size=n).tolist() for n in (4, 6, 5, 8)]
    bare = _make_engine(tiny_engine_parts, num_slots=3)
    uids_b = [bare.submit(p, 6) for p in prompts]
    done_b = bare.run(uids_b)
    bare.close()  # the soak asserts a clean serving/* namespace at the end

    router = _make_fleet(
        tiny_engine_parts, 1, tmp_path,
        factory=lambda seat: _make_engine(tiny_engine_parts, num_slots=3),
    )
    try:
        uids_f = [router.submit(p, 6) for p in prompts]
        assert uids_f == uids_b  # identical uid sequence, not just disjoint
        done_f = router.run(uids_f)
    finally:
        router.close()
    for prompt, ub, uf in zip(prompts, uids_b, uids_f):
        assert done_b[ub].finish_reason == done_f[uf].finish_reason
        _assert_greedy_equivalent(
            tiny_engine_parts, prompt, done_b[ub].generated, done_f[uf].generated
        )


# --------------------------------------------------------------- chaos soak


def _soak_registry():
    reg = TenantRegistry(class_ttl_s={0: 8.0, 1: 16.0})
    reg.register("free1", slo_class=0, kv_block_quota=6)
    reg.register("free2", slo_class=0, kv_block_quota=6)
    reg.register("pro1", slo_class=1)
    reg.register("pro2", slo_class=1)
    return reg


def _soak_traffic():
    return [
        TenantTraffic("free1", num_requests=12, arrivals_per_round=2.0,
                      prompt_len=(4, 10), max_new=(4, 8), vocab=37),
        TenantTraffic("free2", num_requests=12, arrivals_per_round=2.0,
                      prompt_len=(4, 10), max_new=(4, 8), vocab=37),
        TenantTraffic("pro1", num_requests=6, arrivals_per_round=0.5,
                      prompt_len=(4, 10), max_new=(4, 8), vocab=37,
                      shared_prefix=4),
        TenantTraffic("pro2", num_requests=6, arrivals_per_round=0.5,
                      prompt_len=(6, 12), max_new=(4, 8), vocab=37,
                      shared_prefix=4),
    ]


def test_fleet_chaos_soak_exactly_once_and_slo(tiny_engine_parts, tmp_path):
    """The acceptance soak: 3 replicas, 4 tenants / 2 SLO classes, a hard
    replica kill AND an in-replica crash restart AND chaos mis-routes, with
    the autoscaler live so the idle tail triggers a graceful drain mid-run.
    Every uid reaches exactly one terminal state, per-class p99 ordering
    holds fleet-wide, zero quota violations, and affinity beats random."""
    model, params, _ = tiny_engine_parts
    reg = _soak_registry()
    policy = ServingResiliencePolicy(
        max_pending=16, high_watermark=1.0, low_watermark=0.5, preemption=True,
    )

    def factory(seat):
        return ServingEngine(
            model, params, num_slots=3, max_seq_len=32, block_size=4,
            num_blocks=20, eos_token_id=None, pad_token_id=0,
            gen_kwargs=dict(do_sample=False), seed=seat, policy=policy,
            prefix_caching=True, tenants=reg,
        )

    report = run_fleet_scenario(
        factory, reg, _soak_traffic(), num_replicas=3,
        chaos_spec="fleet-replica-kill:1,fleet-route:2,serving-decode:1",
        dt_s=0.05, max_rounds=400, seed=0, wedge_timeout_s=0.25,
        diagnostics_dir=str(tmp_path),
        autoscale=True, min_replicas=1, max_replicas=4,
        scale_down_occupancy=0.3, breach_rounds=3, cooldown_rounds=4,
        idle_tail_rounds=30,
    )
    assert isinstance(report, FleetScenarioReport)
    # the harness already asserted exactly-once accounting; re-check the
    # externally visible facts
    assert report.submitted == 36 and report.rejected == 0
    assert len(report.terminal) == 36
    assert set(report.terminal.values()) <= TERMINAL_REASONS
    assert report.replica_kills >= 1, "chaos never killed a replica"
    assert report.reroutes >= 1, "the kill re-routed nothing"
    assert report.restarts >= 1, "chaos never forced a supervised restart"
    assert "drain" in [a for _, a in report.autoscale_events], (
        f"the idle tail never triggered an autoscale drain: "
        f"{report.autoscale_events}"
    )
    assert report.quota_violations == 0
    assert report.p99_ordering_ok(), (
        f"higher SLO class saw worse p99 fleet-wide: {report.p99_by_class}"
    )
    assert report.affinity_hit_rate > report.random_hit_rate
    assert report.replicas_peak >= 3 and report.replicas_final < 3
    assert 0.0 < report.fairness_jain <= 1.0
    # fleet gauges snapshotted before close agree with the ledger
    assert report.gauges["fleet/replica_kills"] == float(report.replica_kills)
    assert report.gauges["fleet/reroutes"] == float(report.reroutes)
    assert report.gauges["fleet/autoscale/drain"] >= 1.0
    assert report.gauges["fleet/finished"] == 36.0
    # everything was cleared by router.close() at the end
    assert gauges.snapshot(prefix="serving/") == {}
    assert gauges.snapshot(prefix="fleet/") == {}


def test_fleet_scenario_without_chaos_is_clean(tiny_engine_parts, tmp_path):
    """No chaos, light traffic, autoscale off: nothing kills, restarts or
    sheds; everyone finishes; the fleet ends at its starting size."""
    model, params, _ = tiny_engine_parts
    reg = TenantRegistry()
    reg.register("a", slo_class=0)
    reg.register("b", slo_class=1)

    def factory(seat):
        return ServingEngine(
            model, params, num_slots=3, max_seq_len=32, block_size=4,
            eos_token_id=None, pad_token_id=0, gen_kwargs=dict(do_sample=False),
            seed=seat, prefix_caching=False, tenants=reg,
        )

    traffic = [
        TenantTraffic("a", num_requests=5, arrivals_per_round=1.0,
                      prompt_len=(4, 8), max_new=(4, 6), vocab=37),
        TenantTraffic("b", num_requests=5, arrivals_per_round=1.0,
                      prompt_len=(4, 8), max_new=(4, 6), vocab=37),
    ]
    report = run_fleet_scenario(
        factory, reg, traffic, num_replicas=2, autoscale=False,
        dt_s=0.05, max_rounds=200, diagnostics_dir=str(tmp_path),
    )
    assert report.restarts == 0 and report.replica_kills == 0
    assert report.quota_violations == 0
    assert sorted(report.terminal.values()) == [FINISH_LENGTH] * 10
    assert report.replicas_final == 2 and report.autoscale_events == []
    assert report.fairness_jain > 0.9


# ------------------------------------------------------------------- config


def test_train_config_parses_serving_fleet_block():
    from trlx_tpu.data.configs import ServingFleetConfig, TrainConfig

    cfg = TrainConfig.from_dict(dict(
        total_steps=1, batch_size=1, checkpoint_dir="/tmp/x",
        serving_fleet=dict(
            enabled=True, num_replicas=3, prefix_weight=2.0, autoscale=True,
            min_replicas=2, max_replicas=5, breach_rounds=4,
        ),
    ))
    svf = cfg.serving_fleet
    assert isinstance(svf, ServingFleetConfig)
    assert svf.enabled and svf.num_replicas == 3 and svf.prefix_weight == 2.0
    assert svf.autoscale and svf.min_replicas == 2 and svf.max_replicas == 5
    # default stays off: the fleet is opt-in
    assert TrainConfig.from_dict(dict(
        total_steps=1, batch_size=1, checkpoint_dir="/tmp/x",
    )).serving_fleet.enabled is False
    with pytest.raises(ValueError, match="num_replicas"):
        ServingFleetConfig(num_replicas=0)
    with pytest.raises(ValueError, match="min_replicas"):
        ServingFleetConfig(min_replicas=4, max_replicas=2)
